"""m3lint self-tests: each checker fires on a known-bad synthetic snippet
and stays quiet on the fixed codebase, suppressions require rationales,
and the tools/check_lint.py gate passes on the current tree (this test IS
the tier-1 wiring of the lint gate)."""

import json
import subprocess
import sys
import textwrap

from tools.m3lint import REPO_ROOT, lint_paths, lint_source


def codes(findings):
    return {f.code for f in findings}


def lint(src, rel="synthetic/mod.py", extra=None):
    return lint_source(textwrap.dedent(src), rel=rel, extra=extra)


# --- M3L001 device-op-under-lock ---


def test_device_op_under_lock_fires():
    findings = lint(
        """
        import jax, threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def admit(self, x):
                with self._lock:
                    staged = jax.device_put(x)
                    staged.block_until_ready()
                return staged
        """
    )
    assert codes(findings) == {"M3L001"} and len(findings) == 2


def test_send_frame_under_lock_fires():
    # socket-blocking boundary (PR 6 satellite): a frame send inside a
    # lock turns one slow peer into a process-wide pile-up
    findings = lint(
        """
        import threading
        from m3_tpu.net import wire

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def flush(self, sock, batch):
                with self._lock:
                    wire.send_frame(sock, {"entries": batch})
        """
    )
    assert codes(findings) == {"M3L001"} and len(findings) == 1
    assert "send" in findings[0].message


def test_send_frame_outside_lock_quiet():
    findings = lint(
        """
        import threading
        from m3_tpu.net import wire

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def flush(self, sock):
                with self._lock:
                    batch, self._buf = self._buf, []  # snapshot under lock
                wire.send_frame(sock, {"entries": batch})  # send lock-free
        """
    )
    assert findings == []


def test_device_op_outside_lock_quiet():
    findings = lint(
        """
        import jax, threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def admit(self, x):
                staged = jax.device_put(x)
                with self._lock:
                    self.table = staged  # bookkeeping only under the lock
                return staged
        """
    )
    assert findings == []


def test_nested_def_under_lock_not_flagged():
    # a function DEFINED under a lock does not RUN there
    findings = lint(
        """
        import jax, threading

        _lock = threading.Lock()

        def make():
            with _lock:
                def later(x):
                    return jax.device_put(x)
            return later
        """
    )
    assert findings == []


# --- M3L002 jit-mutable-capture ---


def test_jit_mutable_global_capture_fires():
    findings = lint(
        """
        import jax

        _SCALE = 1.0

        def set_scale(v):
            global _SCALE
            _SCALE = v

        @jax.jit
        def apply(x):
            return x * _SCALE
        """
    )
    assert codes(findings) == {"M3L002"}


def test_jit_self_capture_fires():
    findings = lint(
        """
        import functools, jax

        class K:
            @functools.partial(jax.jit, static_argnames=())
            def run(self, x):
                return x + self.offset
        """
    )
    assert "M3L002" in codes(findings)


def test_jit_constant_global_quiet():
    findings = lint(
        """
        import jax

        _TABLE = (1, 2, 3)  # assigned once: a real constant

        @jax.jit
        def apply(x):
            return x * _TABLE[0]
        """
    )
    assert findings == []


# --- M3L003 wire-registry-consistency ---

_FAKE_WIRE = """
IDEMPOTENT_OPS = frozenset({"fetch", "write_thing", "ghost_op"})
UNTRACED_OPS = frozenset({"health", "phantom"})
RETRYABLE_ETYPES = frozenset({"NopeError"})
"""

_FAKE_SERVICE = """
class Service:
    def handle(self, req):
        op = req.get("op")
        if op == "health":
            return True
        fn = getattr(self, f"op_{op}", None)
        return fn(req)

    def op_fetch(self, req):
        return 1

    def op_write_thing(self, req):
        return 1

    def op_mystery(self, req):
        return 1


def probe(client):
    return client._call("nonexistent_op")
"""


def test_wire_registry_consistency_fires_on_all_shapes():
    findings = lint(
        _FAKE_SERVICE,
        rel="pkg/services/svc.py",
        extra={"pkg/net/wire.py": _FAKE_WIRE},
    )
    msgs = "\n".join(f.message for f in findings)
    assert codes(findings) == {"M3L003"}
    assert "'ghost_op' is not dispatched" in msgs  # stale registry entry
    assert "mutating op 'write_thing'" in msgs  # write registered idempotent
    assert "'phantom' is not dispatched" in msgs  # stale UNTRACED entry
    assert "'NopeError'" in msgs  # undefined exception class
    assert "'mystery' is unclassified" in msgs  # op with no classification
    assert "'nonexistent_op'" in msgs  # client typo


def test_wire_registry_consistency_quiet_when_in_sync():
    findings = lint(
        """
        class Service:
            def handle(self, req):
                op = req.get("op")
                fn = getattr(self, f"op_{op}", None)
                return fn(req)

            def op_fetch(self, req):
                return 1

            def op_write_thing(self, req):
                return 1


        class NopeError(RuntimeError):
            pass
        """,
        rel="pkg/services/svc.py",
        extra={
            "pkg/net/wire.py": """
IDEMPOTENT_OPS = frozenset({"fetch"})
UNTRACED_OPS = frozenset({"fetch"})
RETRYABLE_ETYPES = frozenset({"NopeError"})
"""
        },
    )
    assert findings == []


# --- M3L004 deadline-clock-discipline ---


def test_wall_clock_deadline_fires():
    findings = lint(
        """
        import time

        def wait_for(pred, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return True
            return False
        """
    )
    assert codes(findings) == {"M3L004"} and len(findings) == 2


def test_monotonic_deadline_and_timestamps_quiet():
    findings = lint(
        """
        import time

        def wait_for(pred, timeout):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return True
            return False

        def stamp():
            return time.time()  # a wall-clock TIMESTAMP is fine
        """
    )
    assert findings == []


def test_wall_clock_suppression_needs_rationale():
    src = """
    import time

    def deadline_frame(timeout):
        # m3lint: disable=M3L004
    """ + "    return time.time() + timeout\n"
    findings = lint(src)
    # the suppression eats the M3L004 but yields M3L000 (no rationale)
    assert codes(findings) == {"M3L000"}

    src_ok = """
    import time

    def deadline_frame(timeout):
        # m3lint: disable=M3L004 -- wire deadline is wall-clock by protocol
    """ + "    return time.time() + timeout\n"
    assert lint(src_ok) == []


def test_stale_suppression_is_reported():
    # the flagged code was fixed but the comment stayed behind: flag it,
    # or it would silently mask the next real finding at the same spot
    findings = lint(
        """
        import time

        def deadline_frame(timeout):
            # m3lint: disable=M3L004 -- wire deadline is wall-clock by protocol
            return time.monotonic() + timeout
        """
    )
    assert codes(findings) == {"M3L000"}
    assert "unused suppression" in findings[0].message


# --- M3L005 metric-name-discipline ---


def test_dynamic_metric_name_fires():
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def track(op):
            METRICS.counter(f"requests_{op}_total").inc()
        """
    )
    assert codes(findings) == {"M3L005"}


def test_double_prefix_and_bad_label_key_fire():
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        METRICS.counter("m3tpu_requests_total")
        METRICS.gauge("depth", labels={"series_id": "abc"})
        """
    )
    assert codes(findings) == {"M3L005"} and len(findings) == 2


def test_migration_label_key_outside_allowlist_fires():
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        METRICS.counter(
            "migration_streamed_bytes_total",
            "bytes pulled during handoff",
            labels={"source_node": "node-a"},
        ).inc(4096)
        """
    )
    assert codes(findings) == {"M3L005"}


def test_migration_peer_label_key_quiet():
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        METRICS.counter(
            "migration_streamed_bytes_total",
            "bytes pulled during handoff",
            labels={"peer": "node-a"},
        ).inc(4096)
        """
    )
    assert findings == []


def test_colon_recorded_name_fires_outside_ruler():
    src = """
    from pkg.instrument import DEFAULT as METRICS

    METRICS.counter("job:rpc_errors:rate5m")
    """
    findings = lint(src)
    assert codes(findings) == {"M3L005"}
    assert "ruler writer context" in findings[0].message


def test_colon_recorded_name_quiet_inside_ruler():
    src = """
    from pkg.instrument import DEFAULT as METRICS

    METRICS.counter("job:rpc_errors:rate5m")
    """
    assert lint(src, rel="m3_tpu/ruler/synthetic.py") == []


def test_clean_metric_quiet():
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        METRICS.counter("requests_total", "help", labels={"op": "fetch"})
        """
    )
    assert findings == []


def test_tenant_and_scope_label_keys_quiet():
    # per-tenant attribution labels: "tenant" (ledger-capped values) and
    # "scope" (the fixed enforcer-chain links) are allowlisted
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def charge(tenant, scope):
            METRICS.counter("tenant_shed_total", labels={"tenant": tenant})
            METRICS.counter(
                "query_limit_exceeded_total", labels={"scope": scope}
            )
        """
    )
    assert findings == []


def test_slo_objective_and_window_label_keys_quiet():
    # SLO attribution labels: "objective" values are the operator's
    # --slo-config names (spec.py rejects duplicates and non-slugs) and
    # "window" values are the spec's fixed window tokens — allowlisted
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def burn(objective):
            METRICS.gauge(
                "slo_budget_remaining_ratio",
                labels={"objective": objective},
            )
            METRICS.gauge(
                "slo_burn_rate",
                labels={"objective": objective, "window": "5m/1h"},
            )
        """
    )
    assert findings == []


def test_slo_alertname_label_key_fires():
    # alertname is derived per-rule and belongs in the alert payload,
    # not a metric label — it stays outside the allowlist
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def fired(alertname):
            METRICS.counter(
                "slo_violations_total", labels={"alertname": alertname}
            )
        """
    )
    assert codes(findings) == {"M3L005"}


def test_shard_label_key_quiet():
    # per-shard heat attribution (resident/heat.py): "shard" values are
    # configured shard ids, hard-capped by ShardHeat — allowlisted
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def charge(shard):
            METRICS.counter(
                "resident_shard_hits_total", labels={"shard": shard}
            )
        """
    )
    assert findings == []


def test_frame_label_key_fires():
    # frame/stack discipline (m3_tpu/profiling/): profile stacks are
    # unbounded runtime strings — they belong in the folded-stack table,
    # NEVER in metric labels, so "frame" stays off the allowlist
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def record(frame):
            METRICS.counter("profile_hits_total", labels={"frame": frame})
        """
    )
    assert codes(findings) == {"M3L005"}


def test_ingest_spill_reason_label_quiet():
    # the device-ingest family (ingest/buffer.py): spill causes are the
    # hand-enumerated window/lanes/slots vocabulary under the allowlisted
    # "reason" key; the unlabeled counters are the sync/seal/admission
    # totals the check_ingest gate scrapes
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def spill(reason):
            METRICS.counter(
                "ingest_spilled_total", "rows the planes could not take",
                labels={"reason": reason},
            )
            METRICS.counter("ingest_device_syncs_total", "plane scatters")
            METRICS.counter("ingest_device_admissions_total", "born resident")
        """
    )
    assert findings == []


def test_ingest_per_series_label_key_fires():
    # series ids are unbounded user data — a per-sid ingest counter would
    # be one exposition series per written series; lanes are addressed by
    # the bounded "shard" key or not at all
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def spill(sid):
            METRICS.counter(
                "ingest_lane_overflow_total", "per-series lane overflow",
                labels={"sid": sid},
            )
        """
    )
    assert codes(findings) == {"M3L005"}


def test_encode_kernel_prefixed_name_fires():
    # the encode family keeps the registry-prefix rule: minting
    # "m3tpu_encode_*" literals would expose m3tpu_m3tpu_encode_*
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        METRICS.counter("m3tpu_encode_lanes_total", "device-encoded lanes")
        """
    )
    assert codes(findings) == {"M3L005"}
    assert "m3tpu_" in findings[0].message


def test_uncapped_tenant_like_label_key_fires():
    # near-miss keys stay banned: an uncapped identity key ("tenant_id",
    # "user") would be unbounded exposition cardinality
    findings = lint(
        """
        from pkg.instrument import DEFAULT as METRICS

        def charge(tid):
            METRICS.counter("tenant_shed_total", labels={"tenant_id": tid})
        """
    )
    assert codes(findings) == {"M3L005"}


# --- M3L006 thread-daemon-discipline ---


def test_non_daemon_thread_in_rpc_plane_fires():
    src = """
    import threading

    def fan_out(fn):
        t = threading.Thread(target=fn)
        t.start()
    """
    assert codes(lint(src, rel="m3_tpu/net/fanout.py")) == {"M3L006"}
    # same code outside the scoped dirs is not flagged
    assert lint(src, rel="m3_tpu/ops/fanout.py") == []


def test_daemon_thread_quiet():
    findings = lint(
        """
        import threading

        def fan_out(fn):
            threading.Thread(target=fn, daemon=True).start()
        """,
        rel="m3_tpu/net/fanout.py",
    )
    assert findings == []


# --- M3L007 swallowed-exception ---


def test_bare_except_and_silent_swallow_fire():
    findings = lint(
        """
        def poll(fn):
            try:
                fn()
            except:
                return None

        def probe(fn):
            try:
                fn()
            except Exception:
                pass
        """
    )
    assert codes(findings) == {"M3L007"} and len(findings) == 2


def test_counted_or_narrow_swallow_quiet():
    findings = lint(
        """
        def probe(fn, errors):
            try:
                fn()
            except Exception:
                errors.inc()

        def close(sock):
            try:
                sock.close()
            except OSError:
                pass  # narrow except: a deliberate, reviewable contract
        """
    )
    assert findings == []


# --- M3L008 durable-write-discipline ---


def test_bare_open_and_post_checkpoint_write_fire():
    src = """
    import os

    def persist(base, payload, DISK):
        with open(os.path.join(base, "info.db"), "wb") as f:
            f.write(payload)

    def commit(base, digest_payload, data, DISK):
        DISK.write_durable(os.path.join(base, "checkpoint.db"),
                           digest_payload)
        DISK.write_durable(os.path.join(base, "data.db"), data)
    """
    findings = lint(src, rel="m3_tpu/storage/newstore.py")
    assert codes(findings) == {"M3L008"} and len(findings) == 2
    # same code outside storage/ (and in the seam itself) is not flagged
    assert lint(src, rel="m3_tpu/ops/newstore.py") == []
    assert lint(src, rel="m3_tpu/storage/faults.py") == []


def test_seamed_checkpoint_last_quiet():
    findings = lint(
        """
        import os

        def commit(base, files, digest_payload, DISK):
            for suffix, payload in files.items():
                DISK.write_durable(os.path.join(base, suffix + ".db"),
                                   payload)
            DISK.write_durable(os.path.join(base, "checkpoint.db"),
                               digest_payload)

        def read(path):
            with open(path, "rb") as f:
                return f.read()
        """,
        rel="m3_tpu/storage/newstore.py",
    )
    assert findings == []


# --- the fixed codebase stays quiet + the gate runs inside tier-1 ---


def test_current_tree_is_clean():
    res = lint_paths(["m3_tpu", "tools"], repo_root=REPO_ROOT)
    assert res.errors == []
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    # every suppression that made the tree clean carries a rationale
    assert all(why for _, why in res.suppressed)
    assert all(why for _, why in res.baselined)


def test_check_lint_gate_passes():
    from tools import check_lint

    assert check_lint.main([]) == 0


def test_cli_json_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "tools.m3lint", "m3_tpu", "tools",
         "--format", "json"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] and payload["findings"] == []
    assert payload["files_scanned"] > 100


# --- pass-1 project model (call graph, locks, jit surfaces) ---


def build_model(files):
    from tools.m3lint import FileContext
    from tools.m3lint.model import ProjectModel

    return ProjectModel(
        [
            FileContext(rel, textwrap.dedent(src))
            for rel, src in files.items()
        ]
    )


def test_model_wire_edge_resolution():
    model = build_model({
        "m3_tpu/net/client.py": """
            class RpcClient:
                def _call(self, op, **kw):
                    pass

                def sync(self):
                    return self._call("sync")
            """,
        "m3_tpu/services/node.py": """
            class NodeService:
                def handle(self, req):
                    pass

                def op_sync(self, req):
                    return 1
            """,
    })
    fi = model.functions["m3_tpu/net/client.py::RpcClient.sync"]
    call = next(c for c in fi.calls if c.wire_op == "sync")
    targets = model.resolve(fi, call)
    assert [t.qualname for t in targets] == [
        "m3_tpu/services/node.py::NodeService.op_sync"
    ]


def test_model_method_resolution_through_bases():
    model = build_model({
        "m3_tpu/a.py": """
            class Base:
                def helper(self):
                    pass

            class Child(Base):
                def go(self):
                    self.helper()
            """,
    })
    fi = model.functions["m3_tpu/a.py::Child.go"]
    call = next(c for c in fi.calls if c.name == "helper")
    assert [t.display for t in model.resolve(fi, call)] == ["Base.helper"]


def test_model_generic_method_names_never_resolve_by_uniqueness():
    # `f.write(...)` must not resolve to the one class defining write()
    model = build_model({
        "m3_tpu/a.py": """
            class Sink:
                def write(self, b):
                    pass

            def save(f):
                f.write(b"x")
            """,
    })
    fi = model.functions["m3_tpu/a.py::save"]
    call = next(c for c in fi.calls if c.name == "write")
    assert model.resolve(fi, call) == []


def test_model_lock_summaries():
    model = build_model({
        "m3_tpu/p.py": """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()

                def admit(self, other):
                    with self._lock:
                        other.enter()
            """,
    })
    fi = model.functions["m3_tpu/p.py::Pool.admit"]
    assert [a.lock for a in fi.acquires] == ["Pool._lock"]
    assert model.lock_kinds["Pool._lock"] == "Lock"
    call = next(c for c in fi.calls if c.name == "enter")
    # the call site knows which locks are held around it
    assert [lock for lock, _line in call.locks_held] == ["Pool._lock"]


def test_model_jit_surfaces():
    model = build_model({
        "m3_tpu/k.py": """
            import functools

            import jax

            _MEMO = None

            @functools.partial(
                jax.jit, static_argnums=(1,), donate_argnums=(0,)
            )
            def fused(buf, n):
                return buf

            def get():
                global _MEMO
                if _MEMO is None:
                    _MEMO = jax.jit(lambda x: x)
                return _MEMO

            def factory():
                return jax.jit(lambda x: x)
            """,
    })
    dec = next(s for s in model.jit_surfaces if s.kind == "decorated")
    assert dec.name == "fused"
    assert dec.static_argnums == (1,)
    assert dec.donate_argnums == (0,)
    memo = next(
        s for s in model.jit_surfaces if s.kind == "call" and s.memoized
    )
    assert memo.in_function == "get"
    ret = next(
        s for s in model.jit_surfaces if s.kind == "call" and s.returned
    )
    assert ret.in_function == "factory"


# --- M3L009 static-lock-order ---


def test_static_lock_order_fires_on_ab_ba():
    # the exact AB/BA shape tests/test_lockcheck.py witnesses at runtime,
    # found here without executing anything
    findings = lint(
        """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()

        def ab():
            with a_lock:
                with b_lock:
                    pass

        def ba():
            with b_lock:
                with a_lock:
                    pass
        """
    )
    assert codes(findings) == {"M3L009"} and len(findings) == 1
    msg = findings[0].message
    # BOTH witness chains are in the finding
    assert "ab (" in msg and "ba (" in msg
    assert "deadlock" in msg


def test_static_lock_order_quiet_on_consistent_order():
    findings = lint(
        """
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()

        def one():
            with a_lock:
                with b_lock:
                    pass

        def two():
            with a_lock:
                with b_lock:
                    pass
        """
    )
    assert findings == []


def test_static_lock_order_fires_across_call_chain():
    # the inversion only exists through resolved call edges: A.outer
    # holds A._lock and calls into B.enter (taking B._lock) while
    # B.reverse holds B._lock and calls back into A.outer
    findings = lint(
        """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self, other):
                with self._lock:
                    other.enter()

        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def enter(self):
                with self._lock:
                    pass

            def reverse(self, a):
                with self._lock:
                    a.outer(self)
        """
    )
    assert "M3L009" in codes(findings)
    assert any("A._lock" in f.message and "B._lock" in f.message
               for f in findings if f.code == "M3L009")


# --- M3L010 host-sync-on-hot-path ---


HOT_SYNC_SRC = """
    import numpy as np

    def resident_scan_totals(aggs):
        return _finish(aggs)

    def _finish(aggs):
        return np.asarray(aggs)
    """


def test_host_sync_fires_with_reachability_chain():
    findings = lint(HOT_SYNC_SRC, rel="m3_tpu/resident/scan.py")
    assert codes(findings) == {"M3L010"} and len(findings) == 1
    msg = findings[0].message
    assert "np.asarray" in msg
    # the finding names the chain from the hot entry to the sync site
    assert "resident_scan_totals" in msg and "_finish" in msg


def test_host_sync_quiet_off_hot_path():
    # byte-identical code outside the hot-entry registry is fine
    assert lint(HOT_SYNC_SRC, rel="m3_tpu/utils/export.py") == []


def test_host_sync_quiet_on_host_literal_asarray():
    findings = lint(
        """
        import numpy as np

        def resident_scan_totals(ranges):
            los = np.asarray([lo for lo, _ in ranges] or [0], np.int32)
            return los
        """,
        rel="m3_tpu/resident/scan.py",
    )
    assert findings == []


def test_host_sync_does_not_cross_wire_boundary():
    # `_call("x")` edges are NOT followed: work past the RPC dispatch
    # runs in the serving process, not on this caller's hot path
    findings = lint(
        """
        def resident_scan_totals(client):
            return client._call("scan_sync")
        """,
        rel="m3_tpu/resident/scan.py",
        extra={
            "m3_tpu/services/node.py": textwrap.dedent(
                """
                import jax

                class NodeService:
                    def handle(self, req):
                        pass

                    def op_scan_sync(self, req):
                        jax.block_until_ready(req)
                """
            ),
        },
    )
    assert "M3L010" not in codes(findings)


# --- M3L011 jit-recompile-hazard ---


def test_jit_in_request_body_fires():
    findings = lint(
        """
        import jax

        def handle(x):
            fn = jax.jit(lambda v: v + 1)
            return fn(x)
        """
    )
    assert codes(findings) == {"M3L011"} and len(findings) == 1
    assert "hoist" in findings[0].message


def test_jit_global_memo_quiet():
    findings = lint(
        """
        import jax

        _J = None

        def handle(x):
            global _J
            if _J is None:
                _J = jax.jit(lambda v: v + 1)
            return _J(x)
        """
    )
    assert findings == []


def test_jit_compile_factory_return_quiet():
    # `return jax.jit(...)` is a factory: the CALLER owns memoization
    # (kernels._get_jit build()s, parallel.scan make_sharded_*)
    findings = lint(
        """
        import jax

        def make_step(n):
            def step(x):
                return x * n
            return jax.jit(step)
        """
    )
    assert findings == []


def test_jit_traced_branch_fires_and_static_quiet():
    fired = lint(
        """
        import jax

        @jax.jit
        def f(x, n):
            if n > 0:
                return x
            return x * 2
        """
    )
    assert codes(fired) == {"M3L011"} and len(fired) == 1
    assert "traced parameter `n`" in fired[0].message

    quiet = lint(
        """
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            if n > 0:
                return x
            return x * 2
        """
    )
    assert quiet == []


def test_jit_shape_guards_quiet():
    # x.ndim / len() / `is None` are static at trace time — not value
    # branches
    findings = lint(
        """
        import jax

        @jax.jit
        def f(x, mask=None):
            if mask is None:
                return x
            if x.ndim > 1:
                return x + mask
            return x
        """
    )
    assert findings == []


def test_jit_mutated_closure_read_fires():
    findings = lint(
        """
        import jax

        SCALE = 2.0

        @jax.jit
        def apply(x):
            return x * SCALE
        """,
        rel="m3_tpu/ops/knob.py",
        extra={
            "m3_tpu/query/tune.py": textwrap.dedent(
                """
                import m3_tpu.ops.knob as knob

                def tune():
                    knob.SCALE = 3.0
                """
            ),
        },
    )
    assert "M3L011" in codes(findings)
    hit = next(f for f in findings if f.code == "M3L011")
    assert "SCALE" in hit.message and "old value" in hit.message


# --- M3L012 donation-after-use ---


DONATE_SRC = """
    import jax

    _STEP = jax.jit(lambda b, y: b + y, donate_argnums=(0,))

    def step(buf, y):
        out = _STEP(buf, y)
        total = buf.sum()
        return out, total
    """


def test_donation_after_use_fires():
    findings = lint(DONATE_SRC)
    assert codes(findings) == {"M3L012"} and len(findings) == 1
    assert "`buf` was donated" in findings[0].message


def test_donation_rebind_quiet():
    findings = lint(
        """
        import jax

        _STEP = jax.jit(lambda b, y: b + y, donate_argnums=(0,))

        def step(buf, y):
            buf = _STEP(buf, y)
            return buf.sum()
        """
    )
    assert findings == []


def test_donation_at_return_quiet():
    # dispatch inside `return` hands the buffer off; the other return is
    # a disjoint control path, not a use-after-donation (the
    # resident/pool.py _scatter donate/non-donate branch shape)
    findings = lint(
        """
        import jax

        _STEP = jax.jit(lambda b, y: b + y, donate_argnums=(0,))

        def step(buf, y, donate):
            if donate:
                return _STEP(buf, y)
            return _STEP(buf, y)
        """
    )
    assert findings == []


# --- differential mode + SARIF ---


def test_changed_lines_and_differential_filter(tmp_path):
    import subprocess as sp

    from tools.m3lint import Finding, Result, changed_lines, filter_to_changed

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        sp.run(["git", *args], cwd=repo, check=True, capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@example.invalid")
    git("config", "user.name", "t")
    pkg = repo / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text("a = 1\nb = 2\nc = 3\n")
    git("add", "-A")
    git("commit", "-q", "-m", "base")
    (pkg / "m.py").write_text("a = 1\nb = 99\nc = 3\nd = 4\n")

    changed = changed_lines("HEAD", repo_root=str(repo))
    assert changed == {"pkg/m.py": {2, 4}}

    res = Result(findings=[
        Finding("M3L010", "pkg/m.py", 2, "on a changed line"),
        Finding("M3L010", "pkg/m.py", 3, "on an unchanged line"),
        Finding("M3L010", "pkg/other.py", 2, "in an untouched file"),
    ])
    out = filter_to_changed(res, changed)
    assert [(f.path, f.line) for f in out.findings] == [("pkg/m.py", 2)]
    # parse errors always survive differential mode
    res2 = Result(errors=["pkg/bad.py: boom"])
    assert filter_to_changed(res2, changed).errors == ["pkg/bad.py: boom"]


def test_sarif_matches_golden():
    import os

    from tools.m3lint import Finding, Result, sarif_from_result

    res = Result(
        findings=[
            Finding(
                "M3L010",
                "m3_tpu/resident/scan.py",
                42,
                "np.asarray() (device->host copy) reachable from hot entry",
                "host-sync-on-hot-path",
            )
        ],
        files_scanned=1,
    )
    doc = sarif_from_result(res)
    golden_path = os.path.join(
        os.path.dirname(__file__), "data", "m3lint_golden.sarif"
    )
    with open(golden_path, encoding="utf-8") as f:
        golden = json.load(f)
    assert doc == golden, (
        "SARIF output drifted from tests/data/m3lint_golden.sarif — if "
        "the change is deliberate (new checker, schema fix), regenerate "
        "the golden with json.dump(sarif_from_result(...))"
    )


def test_cli_sarif_and_changed_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "tools.m3lint", "m3_tpu", "tools",
         "--format", "sarif", "--changed", "HEAD"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"M3L009", "M3L010", "M3L011", "M3L012"} <= rule_ids
    assert run["results"] == []
