"""Networked control-plane KV: remote store semantics + watches + the
control-plane stack (placement service, services discovery, election)
running over a live KV server (cluster/kv/etcd/store.go:54 role)."""

import threading
import time

import pytest

from m3_tpu.cluster.kv import KVStore
from m3_tpu.cluster.kv_service import KVServer, RemoteKVStore
from m3_tpu.cluster.placement import PlacementService, build_initial_placement
from m3_tpu.cluster.services import LeaderElection, ServiceInstance, Services


@pytest.fixture()
def remote_kv():
    srv = KVServer()
    srv.start()
    kv = RemoteKVStore(srv.host, srv.port)
    yield kv
    kv.close()
    srv.stop()


def test_remote_kv_store_semantics(remote_kv):
    kv = remote_kv
    assert kv.get("missing") is None
    assert kv.set("k", {"a": [1, 2]}) == 1
    assert kv.get("k").value == {"a": [1, 2]}
    assert kv.check_and_set("k", 1, "v2") == 2
    with pytest.raises(ValueError):
        kv.check_and_set("k", 1, "stale")
    with pytest.raises(KeyError):
        kv.set_if_not_exists("k", "nope")
    assert kv.set_if_not_exists("fresh", 7) == 1
    kv.set("pre/a", 1)
    kv.set("pre/b", 2)
    assert kv.keys("pre/") == ["pre/a", "pre/b"]
    kv.delete("pre/a")
    assert kv.keys("pre/") == ["pre/b"]


def test_remote_kv_watch_delivers_every_observed_version(remote_kv):
    kv = remote_kv
    kv.set("w", "v1")
    seen = []
    done = threading.Event()

    def on_change(vv):
        seen.append((vv.version, vv.value))
        if len(seen) >= 2:
            done.set()

    unsub = kv.watch("w", on_change)
    deadline = time.time() + 5
    while not seen and time.time() < deadline:
        time.sleep(0.02)
    assert seen == [(1, "v1")]  # immediate fire with current value
    kv.set("w", "v2")
    assert done.wait(5)
    assert seen[-1] == (2, "v2")
    unsub()
    kv.set("w", "v3")
    time.sleep(0.3)
    assert seen[-1] == (2, "v2")  # unsubscribed: no more deliveries


def test_delete_then_recreate_resumes_versioning(remote_kv):
    """A re-created key must continue past its tombstone version so
    version-gated long-poll watchers never miss the rebirth."""
    kv = remote_kv
    kv.set("r", "v1")
    kv.set("r", "v2")  # version 2
    kv.delete("r")
    assert kv.set("r", "v3") == 3  # resumes, not back to 1
    seen = []
    unsub = kv.watch("r", lambda vv: seen.append(vv.version))
    deadline = time.time() + 5
    while not seen and time.time() < deadline:
        time.sleep(0.02)
    assert seen == [3]
    kv.delete("r")
    kv.set("r", "v4")
    deadline = time.time() + 5
    while len(seen) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert seen == [3, 4]  # watcher saw the re-creation
    unsub()


def test_placement_service_over_remote_kv(remote_kv):
    svc = PlacementService(remote_kv)
    p = build_initial_placement(["a", "b", "c"], 8, 3)
    svc.set(p)
    got, version = svc.get_versioned()
    assert set(got.instances) == {"a", "b", "c"}
    assert version == 1
    got.instances["a"].endpoint = "127.0.0.1:9999"
    svc.check_and_set(got, 1)
    assert svc.get().instances["a"].endpoint == "127.0.0.1:9999"

    events = []
    unsub = svc.watch(lambda pl: events.append(set(pl.instances)))
    deadline = time.time() + 5
    while not events and time.time() < deadline:
        time.sleep(0.02)
    assert events and events[0] == {"a", "b", "c"}
    unsub()


def test_services_discovery_and_election_over_remote_kv(remote_kv):
    # two "processes": two independent Services clients on one KV server
    s1 = Services(remote_kv, heartbeat_timeout=0.5)
    s2 = Services(remote_kv, heartbeat_timeout=0.5)
    s1.advertise("m3db", ServiceInstance("n0", "127.0.0.1:1"))
    s2.advertise("m3db", ServiceInstance("n1", "127.0.0.1:2"))
    # each sees the other through the KV
    assert [i.id for i in s1.instances("m3db")] == ["n0", "n1"]
    assert [i.endpoint for i in s2.instances("m3db")] == ["127.0.0.1:1", "127.0.0.1:2"]
    # liveness decays without heartbeats
    s1._backdate("m3db", "n0", 1.0)
    assert [i.id for i in s2.instances("m3db")] == ["n1"]
    assert [i.id for i in s2.instances("m3db", live_only=False)] == ["n0", "n1"]
    # heartbeat revives
    s1.heartbeat("m3db", "n0")
    assert [i.id for i in s2.instances("m3db")] == ["n0", "n1"]

    e1 = LeaderElection(remote_kv, "shardset-0")
    e2 = LeaderElection(remote_kv, "shardset-0")
    assert e1.campaign("n0") is True
    assert e2.campaign("n1") is False
    assert e2.leader() == "n0"
    e1.resign("n0")
    assert e2.campaign("n1") is True
    assert e1.leader() == "n1"
