"""Continuous profiling (m3_tpu/profiling/): the host-tier stack sampler
(determinism, bounded tables, retention, folded golden), the device tier
(HLO cost capture — CPU-backend tolerant — and the device-memory split),
the fleet merge (dead peers counted, per-instance tags), the per-shard
heat satellite, and the selfmon round-trip of m3tpu_profile_*."""

import numpy as np
import pytest

from m3_tpu import profiling
from m3_tpu.profiling import (
    StackSampler,
    collect_device_memory,
    collect_fleet_profile,
    folded_text,
    merge_profiles,
    process_profile,
)
from m3_tpu.profiling.sampler import OVERFLOW_STACK, TRUNCATED_FRAME, fold_frames
from m3_tpu.utils.instrument import KernelProfiler, Registry

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS


# --- fake frames: fold_frames only touches f_code/f_back ---


class _Code:
    def __init__(self, filename, name):
        self.co_filename = filename
        self.co_name = name


class _Frame:
    def __init__(self, name, filename="proj/pkg/mod.py", back=None):
        self.f_code = _Code(filename, name)
        self.f_back = back


def _chain(*names):
    """Build a leaf frame whose f_back chain is names root->leaf."""
    frame = None
    for name in names:
        frame = _Frame(name, back=frame)
    return frame


def _counter_value(reg, name, labels=None):
    fam = reg.collect().get(name)
    if not fam:
        return 0.0
    want = labels or {}
    return sum(
        c["value"]
        for c in fam["children"]
        if all(c["labels"].get(k) == v for k, v in want.items())
    )


# --- host tier: sampler ---


def test_fold_frames_root_first_and_truncation():
    stack, truncated = fold_frames(_chain("root", "mid", "leaf"), max_depth=8)
    assert stack == "proj/pkg/mod.py:root;proj/pkg/mod.py:mid;proj/pkg/mod.py:leaf"
    assert truncated == 0
    # deeper than max_depth: LEAF-most frames kept behind the marker
    stack, truncated = fold_frames(_chain("a", "b", "c", "d", "e"), max_depth=2)
    assert truncated == 3
    parts = stack.split(";")
    assert parts[0] == TRUNCATED_FRAME
    assert [p.split(":")[1] for p in parts[1:]] == ["d", "e"]


def test_sampler_determinism_with_injected_clock():
    """Same fake frames + same clock sequence -> bit-identical tables on
    two independent samplers (the reproducibility contract)."""

    def run():
        reg = Registry(prefix="m3tpu_")
        now = [0.0]
        s = StackSampler(
            hz=0, bucket_seconds=10.0, window_seconds=60.0,
            clock=lambda: now[0], registry=reg,
        )
        for tick in range(25):
            now[0] = tick * 0.25
            s.sample_once(
                frames={
                    1: _chain("serve", "fetch", "decode"),
                    2: _chain("serve", "flush" if tick % 3 else "seal"),
                }
            )
        return s.profile(seconds=60)

    a, b = run(), run()
    assert a["folded"] == b["folded"] and a["samples"] == b["samples"]
    assert a["samples"] == 50  # 25 ticks x 2 threads


def test_bounded_table_and_truncation_counters():
    reg = Registry(prefix="m3tpu_")
    s = StackSampler(
        hz=0, max_stacks=2, max_depth=3, clock=lambda: 0.0, registry=reg
    )
    s.sample_once(now=0.0, frames={1: _chain("a", "x")})
    s.sample_once(now=0.0, frames={1: _chain("b", "x")})
    # third DISTINCT stack in the same bucket folds into [overflow]
    s.sample_once(now=0.0, frames={1: _chain("c", "x")})
    folded = s.profile()["folded"]
    assert folded[OVERFLOW_STACK] == 1 and len(folded) == 3
    assert _counter_value(reg, "m3tpu_profile_stacks_truncated_total") == 1
    # deep stack: frame truncation is counted
    s.sample_once(now=0.0, frames={1: _chain("a", "x", "y", "z", "w")})
    assert _counter_value(reg, "m3tpu_profile_frames_truncated_total") == 2
    assert _counter_value(reg, "m3tpu_profile_samples_total") == 4


def test_windowed_retention_drops_old_buckets():
    reg = Registry(prefix="m3tpu_")
    now = [5.0]
    s = StackSampler(
        hz=0, bucket_seconds=10.0, window_seconds=30.0,
        clock=lambda: now[0], registry=reg,
    )
    s.sample_once(frames={1: _chain("old")})
    now[0] = 95.0
    s.sample_once(frames={1: _chain("new")})  # eviction runs here
    folded = s.profile(seconds=600)  # clamped to the window
    assert [k.split(":")[-1] for k in folded["folded"]] == ["new"]
    # a narrower ask only merges covering buckets
    assert s.profile(seconds=10)["folded"]


def test_profile_golden_contains_synthetic_hot_frame():
    """A REAL sample (sys._current_frames) of this thread must fold a
    stack through the known hot frame, root-first."""
    s = StackSampler(hz=0, clock=lambda: 0.0)

    def _synthetic_hot_frame_xyz():
        return s.sample_once(now=0.0)

    assert _synthetic_hot_frame_xyz() >= 1
    folded = s.profile()["folded"]
    hot = [st for st in folded if "_synthetic_hot_frame_xyz" in st]
    assert hot, list(folded)
    stack = hot[0]
    # root-first folded order: the test fn sits above the hot helper,
    # which sits above the sampler's own collection frame
    assert stack.index("test_profile_golden") < stack.index(
        "_synthetic_hot_frame_xyz"
    ) < stack.index("sample_once")


def test_folded_text_format():
    assert folded_text({"a;b": 3, "c": 5}) == "c 5\na;b 3\n"
    assert folded_text({}) == ""


def test_sampler_errors_counted_never_raised():
    reg = Registry(prefix="m3tpu_")
    s = StackSampler(hz=0, clock=lambda: 0.0, registry=reg)

    class Boom:
        @property
        def f_code(self):
            raise RuntimeError("torn frame")

        f_back = None

    class BoomFrames(dict):
        def items(self):
            raise RuntimeError("no frames")

    assert s.sample_once(now=0.0, frames=BoomFrames()) == 0
    assert s.sample_once(now=0.0, frames={1: Boom()}) == 0
    assert _counter_value(reg, "m3tpu_profile_errors_total") == 2


def test_process_profile_install_surface():
    prev = profiling.installed()
    try:
        profiling.install(None)
        empty = process_profile()
        assert empty["enabled"] is False and empty["folded"] == {}
        s = StackSampler(hz=0, instance="me", clock=lambda: 0.0)
        s.sample_once(now=0.0, frames={1: _chain("f")})
        profiling.install(s)
        assert process_profile()["samples"] == 1
        # the dbnode wire op serves the same shape
        from m3_tpu.net.server import NodeService

        out = NodeService(None).op_profile({"seconds": 30})
        assert out["instance"] == "me" and out["samples"] == 1
    finally:
        profiling.install(prev)


# --- device tier: HLO cost capture (CPU tolerant) + memory split ---


def test_kernel_cost_capture_once_per_signature():
    import jax
    import jax.numpy as jnp

    reg = Registry(prefix="m3tpu_")
    prof = KernelProfiler("cost_probe", registry=reg, sample_rate=1.0)
    assert prof.capture_costs  # sampling on => cost capture on
    fn = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
    x = jnp.zeros((32, 32))
    with prof.dispatch(("k", x.shape), cost=(fn, (x,), {})) as d:
        d.done(fn(x))
    captures = _counter_value(
        reg, "m3tpu_kernel_cost_captures_total", {"kernel": "cost_probe"}
    )
    errors = _counter_value(
        reg, "m3tpu_kernel_cost_errors_total", {"kernel": "cost_probe"}
    )
    # CPU-backend tolerant: a backend without cost analysis counts an
    # error instead of raising; when it works, flops/bytes are recorded
    assert captures + errors == 1
    if captures:
        cost = prof.cost_analysis()
        (row,) = cost.values()
        assert row["flops"] >= 0.0 and row["bytes_accessed"] >= 0.0
        assert _counter_value(
            reg, "m3tpu_kernel_flops", {"kernel": "cost_probe"}
        ) == row["flops"]
    # same signature again: not a compile, no second capture
    with prof.dispatch(("k", x.shape), cost=(fn, (x,), {})) as d:
        d.done(fn(x))
    assert _counter_value(
        reg, "m3tpu_kernel_cost_captures_total", {"kernel": "cost_probe"}
    ) + _counter_value(
        reg, "m3tpu_kernel_cost_errors_total", {"kernel": "cost_probe"}
    ) == 1


def test_kernel_cost_capture_off_by_default():
    reg = Registry(prefix="m3tpu_")
    prof = KernelProfiler("cost_off", registry=reg, sample_rate=0.0)
    assert not prof.capture_costs
    assert prof.capture_cost("k", None) is None  # no-op, no error counted
    assert _counter_value(
        reg, "m3tpu_kernel_cost_errors_total", {"kernel": "cost_off"}
    ) == 0


def test_kernel_cost_env_zero_forces_capture_off(monkeypatch):
    # M3_TPU_PROFILE_COST=0 must win over an active sampling rate (the
    # documented opt-out of the extra per-signature AOT compile)
    monkeypatch.setenv("M3_TPU_PROFILE_COST", "0")
    reg = Registry(prefix="m3tpu_")
    prof = KernelProfiler("cost_forced_off", registry=reg, sample_rate=1.0)
    assert not prof.capture_costs
    monkeypatch.setenv("M3_TPU_PROFILE_COST", "1")
    prof = KernelProfiler("cost_forced_on", registry=reg, sample_rate=0.0)
    assert prof.capture_costs


def test_kernel_cost_capture_tolerates_broken_lowerable():
    reg = Registry(prefix="m3tpu_")
    prof = KernelProfiler("cost_broken", registry=reg, capture_costs=True)

    class NotLowerable:
        pass

    assert prof.capture_cost("k", NotLowerable()) is None
    assert _counter_value(
        reg, "m3tpu_kernel_cost_errors_total", {"kernel": "cost_broken"}
    ) == 1


def test_device_memory_split(tmp_path):
    from m3_tpu.resident import ResidentOptions
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(
        str(tmp_path), num_shards=2, commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=1 << 22),
    )
    db.create_namespace("d", NamespaceOptions())
    try:
        # before any admission: the lazy pool buffer must NOT be forced
        # into existence by accounting
        out = collect_device_memory(db)
        assert out["resident_pool"] == 0
        sid = db.write_tagged("d", ((b"__name__", b"g"),), T0, 1.0)
        db.write_batch("d", [(sid, T0 + i * 10 * NANOS, float(i)) for i in range(64)])
        db.flush("d", T0 + 4 * 3600 * NANOS)
        out = collect_device_memory(db)
        assert out["resident_pool"] > 0
        assert out["total_live_jax_bytes"] >= out["resident_pool"]
        assert set(out) >= {"resident_pool", "decoded_cache", "other"}
        # the gauges published for exposition / selfmon
        from m3_tpu.utils.instrument import DEFAULT

        fam = DEFAULT.collect()["m3tpu_device_memory_bytes"]
        kinds = {c["labels"]["kind"]: c["value"] for c in fam["children"]}
        assert kinds["resident_pool"] == out["resident_pool"]
    finally:
        db.close()
    # db-less processes (aggregator) still account live buffers
    assert "other" in collect_device_memory(None)


# --- fleet tier: merge with per-instance tags + dead peers ---


def _prof(folded):
    return {"enabled": True, "folded": folded, "samples": sum(folded.values())}


def test_merge_profiles_by_stack_with_instance_tags():
    merged = merge_profiles(
        [
            ("node0", _prof({"serve;decode": 3, "serve;flush": 1})),
            ("node1", _prof({"serve;decode": 2})),
        ]
    )
    assert merged["folded"] == {"serve;decode": 5, "serve;flush": 1}
    assert merged["byInstance"]["serve;decode"] == {"node0": 3, "node1": 2}


def test_fleet_profile_merges_and_counts_dead_peer():
    class Peer:
        def profile(self, seconds=None):
            return _prof({"serve;decode": 4})

    class DeadPeer:
        def profile(self, seconds=None):
            raise ConnectionError("down")

    out = collect_fleet_profile(
        "coord0", _prof({"http;render": 2}),
        {"node0": Peer(), "node1": DeadPeer()}, seconds=30,
    )
    assert out["instances"] == ["coord0", "node0"]
    assert list(out["errors"]) == ["node1"]
    assert "down" in out["errors"]["node1"]
    assert out["folded"] == {"http;render": 2, "serve;decode": 4}
    assert out["samples"] == 6


def test_coordinator_fleet_profile_surface(tmp_path):
    from m3_tpu.services.coordinator import Coordinator

    prev = profiling.installed()
    coord = None
    try:
        coord = Coordinator(base_dir=str(tmp_path))
        coord.instance_id = "coordX"
        s = StackSampler(hz=0, instance="coordX", clock=lambda: 0.0)
        s.sample_once(now=0.0, frames={1: _chain("http", "render")})
        profiling.install(s)

        class Peer:
            def profile(self, seconds=None):
                return _prof({"rpc;decode": 7})

        coord.peer_source = lambda: {"nodeY": Peer()}
        out = coord.fleet_profile(seconds=15)
        assert set(out["instances"]) == {"coordX", "nodeY"}
        assert out["folded"]["rpc;decode"] == 7
        assert any("render" in st for st in out["folded"])

        # a broken topology source must be visible, not silently served
        # as a healthy single-node fleet
        def broken():
            raise RuntimeError("placement watch torn")

        coord.peer_source = broken
        out = coord.fleet_profile(seconds=15)
        assert out["instances"] == ["coordX"]
        assert "placement watch torn" in out["errors"]["peer_source"]
    finally:
        profiling.install(prev)
        if coord is not None:
            coord.db.close()


# --- satellite: per-shard residency heat ---


def test_shard_heat_cap_and_counters():
    from m3_tpu.resident.heat import OVERFLOW_SHARD, ShardHeat

    reg = Registry(prefix="m3tpu_")
    heat = ShardHeat(registry=reg, cap=2)
    heat.charge(0, hits=3)
    heat.charge(1, misses=1, streamed_bytes=100)
    heat.charge(7, hits=1)  # past the cap: collapses into __overflow__
    dump = heat.dump()
    assert dump["0"]["hits"] == 3
    assert dump["1"]["misses"] == 1 and dump["1"]["streamedBytes"] == 100
    assert dump[OVERFLOW_SHARD]["hits"] == 1 and "7" not in dump
    assert _counter_value(reg, "m3tpu_resident_shard_overflow_total") == 1
    assert _counter_value(
        reg, "m3tpu_resident_shard_hits_total", {"shard": "0"}
    ) == 3


def test_shard_heat_through_query_routing(tmp_path):
    """The integration seam: resident fetches charge hits per shard,
    buffered overlays charge misses, the streamed scan fallback charges
    per-shard bytes — all visible in resident_stats' shard_heat."""
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.query.promql import Matcher
    from m3_tpu.resident import ResidentOptions
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(
        str(tmp_path), num_shards=2, commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=1 << 22),
    )
    db.create_namespace("h", NamespaceOptions())
    try:
        for i in range(8):
            tags = ((b"__name__", b"heat_gauge"), (b"series", b"%02d" % i))
            sid = db.write_tagged("h", tags, T0, float(i))
            db.write_batch(
                "h", [(sid, T0 + (j + 1) * 10 * NANOS, float(j)) for j in range(32)]
            )
        db.flush("h", T0 + 4 * 3600 * NANOS)
        storage = M3Storage(db, "h")
        matchers = [Matcher("__name__", "=", "heat_gauge")]
        span = (T0, T0 + 40 * 10 * NANOS)

        base = {k: dict(v) for k, v in db.resident_stats()["shard_heat"].items()}

        out = storage.scan_totals(matchers, *span)
        assert out["path"] == "resident"
        heat = db.resident_stats()["shard_heat"]
        hits = sum(v["hits"] for v in heat.values()) - sum(
            v["hits"] for v in base.values()
        )
        assert hits >= 8  # one lane per series, across both shards

        # buffered overlay forces the streamed path: miss + streamed bytes
        db.write_tagged("h", ((b"__name__", b"heat_gauge"),
                              (b"series", b"00")), T0 + 33 * 10 * NANOS, 5.0)
        out = storage.scan_totals(matchers, *span)
        assert out["path"] == "streamed"
        heat = db.resident_stats()["shard_heat"]
        assert sum(v["misses"] for v in heat.values()) > sum(
            v["misses"] for v in base.values()
        )
        assert sum(v["streamedBytes"] for v in heat.values()) > sum(
            v["streamedBytes"] for v in base.values()
        )
    finally:
        db.close()


# --- selfmon round-trip: m3tpu_profile_* stored and queryable ---


def test_profile_metrics_selfmon_roundtrip(tmp_path):
    from m3_tpu.query.engine import Engine
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.selfmon import RESERVED_NS, DatabaseSink, SelfMonCollector
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=2)
    db.create_namespace(RESERVED_NS, NamespaceOptions())
    db.bootstrap()
    try:
        reg = Registry(prefix="m3tpu_")
        s = StackSampler(hz=0, clock=lambda: 0.0, registry=reg)
        for _ in range(3):
            s.sample_once(now=0.0, frames={1: _chain("serve", "decode")})
        coll = SelfMonCollector(
            DatabaseSink(db), interval=3600, instance="node0",
            component="dbnode", registry=reg, clock=lambda: T0,
        )
        written, errors = coll.scrape_once()
        assert errors == 0 and written > 0
        eng = Engine(M3Storage(db, RESERVED_NS))
        r = eng.query_instant("m3tpu_profile_samples_total", T0 + NANOS)
        assert len(r.metas) == 1
        assert float(np.asarray(r.values)[0, -1]) == 3.0
        # profiler health is alertable: the error counter rides along
        r = eng.query_instant("m3tpu_profile_errors_total", T0 + NANOS)
        assert len(r.metas) == 1
        assert float(np.asarray(r.values)[0, -1]) == 0.0
    finally:
        db.close()
