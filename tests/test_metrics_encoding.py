"""Metrics wire encoding + aggregator socket ingress tests
(unaggregated_encoder.go + server/rawtcp round-trip semantics)."""

import time

import pytest

from m3_tpu.aggregator.aggregator import Aggregator
from m3_tpu.aggregator.server import AggregatorClient, AggregatorIngestServer
from m3_tpu.metrics.encoding import (
    UnaggregatedMessage,
    decode_batch,
    decode_message,
    encode_batch,
    encode_message,
)
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.types import AggregationType, MetricType, Untimed

NANOS = 1_000_000_000
W = 10 * NANOS
T0 = 1_600_000_000 * NANOS // W * W


def _messages():
    return [
        UnaggregatedMessage(
            Untimed(type=MetricType.COUNTER, id=b"requests", counter_value=5),
            T0 + NANOS,
            (StoragePolicy.parse("10s:2d"), StoragePolicy.parse("1m0s:40d")),
            (AggregationType.SUM, AggregationType.COUNT),
        ),
        UnaggregatedMessage(
            Untimed(
                type=MetricType.TIMER,
                id=b"latency",
                batch_timer_values=[1.5, 2.5, 9.0],
                annotation=b"ann",
            ),
            T0 + 2 * NANOS,
        ),
        UnaggregatedMessage(
            Untimed(type=MetricType.GAUGE, id=b"temp", gauge_value=-3.25),
            T0 + 3 * NANOS,
            timed=True,
        ),
    ]


def test_message_roundtrip():
    for msg in _messages():
        got, end = decode_message(encode_message(msg))
        assert got == msg
        assert end == len(encode_message(msg))


def test_batch_roundtrip():
    msgs = _messages()
    assert decode_batch(encode_batch(msgs)) == msgs


def test_corrupt_batch_detected():
    raw = bytearray(encode_batch(_messages()))
    raw[4] = 99  # bad kind byte
    with pytest.raises(ValueError):
        decode_batch(bytes(raw))


def test_socket_ingest_to_flush_roundtrip():
    """encode -> socket -> aggregate -> flush: the full tier boundary."""
    out = []
    agg = Aggregator(
        num_shards=4,
        default_policies=(StoragePolicy.parse("10s:2d"),),
        flush_handler=out.extend,
    )
    server = AggregatorIngestServer(agg)
    server.start()
    try:
        client = AggregatorClient([(server.host, server.port)], num_shards=4)
        for i in range(10):
            client.send(
                UnaggregatedMessage(
                    Untimed(type=MetricType.COUNTER, id=b"reqs", counter_value=2),
                    T0 + i * NANOS,
                )
            )
        client.send(
            UnaggregatedMessage(
                Untimed(type=MetricType.GAUGE, id=b"temp", gauge_value=7.0),
                T0 + NANOS,
            )
        )
        deadline = time.time() + 10
        while server.received < 11 and time.time() < deadline:
            time.sleep(0.01)
        assert server.received == 11 and server.decode_errors == 0
        agg.flush(T0 + W)
        sums = {
            m.suffixed_id: m.value
            for m in out
            if m.id == b"reqs" and m.agg_type == AggregationType.SUM
        }
        assert sums == {b"reqs.sum": 20.0}
        gauges = [m for m in out if m.id == b"temp" and m.agg_type == AggregationType.LAST]
        assert [m.value for m in gauges] == [7.0]
        client.close()
    finally:
        server.stop()


def test_aggregator_service_binary_end_to_end(tmp_path):
    """aggregator process ingests over TCP and forwards flushed rollups to a
    dbnode process (the full m3 metrics path as real processes)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(mod, *a):
        proc = subprocess.Popen(
            [sys.executable, "-m", mod, *a],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
            cwd=repo,
        )
        line = proc.stdout.readline()
        assert line.startswith("LISTENING"), line
        _, host, port = line.split()
        return proc, host, int(port)

    db_proc, db_host, db_port = spawn(
        "m3_tpu.services.dbnode",
        "--base-dir", str(tmp_path / "db"), "--no-mediator",
    )
    agg_proc, agg_host, agg_port = spawn(
        "m3_tpu.services.aggregator",
        "--flush-interval-secs", "0.1",
        "--forward", f"{db_host}:{db_port}",
    )
    try:
        client = AggregatorClient([(agg_host, agg_port)])
        now = time.time_ns()
        for _ in range(5):
            client.send(
                UnaggregatedMessage(
                    Untimed(type=MetricType.COUNTER, id=b"e2e.reqs", counter_value=3),
                    now - 60 * NANOS,  # an already-complete window
                )
            )
        client.close()

        from m3_tpu.net.client import RemoteNode

        node = RemoteNode(db_host, db_port)
        deadline = time.time() + 20
        dps = []
        while time.time() < deadline:
            dps = node.read("default", b"e2e.reqs.sum", 0, 2**62)
            if dps:
                break
            time.sleep(0.1)
        assert [dp.value for dp in dps] == [15.0]
        node.close()
    finally:
        agg_proc.kill()
        db_proc.kill()
        agg_proc.wait(timeout=10)
        db_proc.wait(timeout=10)
