"""m3em agents + dtest destructive scenarios over REAL dbnode processes
(reference: src/m3em/agent, src/cmd/tools/dtest)."""

import signal
import sys
import time

import pytest

from m3_tpu.cluster.topology import ConsistencyLevel
from m3_tpu.testing.m3em import AgentClient, AgentServer


def test_agent_lifecycle_and_file_transfer(tmp_path):
    srv = AgentServer(str(tmp_path / "agent"))
    try:
        client = AgentClient("127.0.0.1", srv.port)
        hb = client.heartbeat()
        assert hb["ok"] and hb["processes"] == {}

        out = client.setup(
            "t1",
            [sys.executable, "-c", "import time; time.sleep(60)"],
            files={"conf/app.yml": b"key: value\n"},
        )
        assert (tmp_path / "agent" / "t1" / "conf" / "app.yml").read_bytes() == b"key: value\n"

        started = client.start("t1")
        pid = started["pid"]
        assert pid > 0
        hb = client.heartbeat()
        assert hb["processes"]["t1"]["running"] is True

        stopped = client.stop("t1", sig=signal.SIGTERM)
        assert stopped["stopped"] is True
        hb = client.heartbeat()
        assert hb["processes"]["t1"]["running"] is False

        client.teardown("t1")
        assert not (tmp_path / "agent" / "t1").exists()
    finally:
        srv.close()


def test_agent_rejects_path_escape(tmp_path):
    import urllib.error

    srv = AgentServer(str(tmp_path / "agent"))
    try:
        client = AgentClient("127.0.0.1", srv.port)
        with pytest.raises(urllib.error.HTTPError):
            client.setup("t1", ["true"], files={"../../escape": b"x"})
    finally:
        srv.close()


@pytest.mark.slow
def test_dtest_kill_restart_bootstrap(tmp_path):
    """Destructive scenario: seed -> kill a node -> data still readable at
    quorum -> restart -> the node bootstraps from disk and serves again."""
    from m3_tpu.testing.dtest import DTestHarness

    h = DTestHarness(["d0", "d1"], str(tmp_path), num_shards=4, replica_factor=2)
    try:
        h.setup_all()
        h.start_all()
        # enough writes to cross the WAL's flush_every fsync batching: a
        # SIGKILL only guarantees the fsynced prefix (the fsync policy's
        # documented contract)
        written = h.seed(n_series=3, n_points=60)

        # kill d1: reads at ONE consistency still serve everything
        h.kill("d1")
        session = h.session(read_cl=ConsistencyLevel.ONE,
                            write_cl=ConsistencyLevel.ONE)
        for sid, vals in written.items():
            got = [dp.value for dp in session.fetch(sid, 0, 2**62)]
            assert got == vals

        # restart d1: it replays its commit log and serves its copy again
        h.restart("d1")
        node = h.nodes["d1"]
        deadline = time.monotonic() + 30
        recovered = {}
        while time.monotonic() < deadline:
            try:
                recovered = {
                    sid: [dp.value for dp in node.read("default", sid, 0, 2**62)]
                    for sid in written
                }
                if any(recovered.values()):
                    break
            except Exception:
                pass
            time.sleep(0.3)
        # SIGKILL durability contract: each recovered series is an exact
        # PREFIX of what was written (the fsynced portion of the WAL)
        for sid, vals in written.items():
            got = recovered.get(sid) or []
            assert got == vals[: len(got)], (sid, got[:5], vals[:5])
        assert any(recovered.values()), "restarted node served no data"
    finally:
        h.close()


def test_agent_panicmon_detects_silent_death(tmp_path):
    """x/panicmon + agent/heartbeater.go: a managed process that dies
    WITHOUT an operator stop request surfaces as an exit event in the
    heartbeat; operator-initiated stops do not."""
    import sys
    import time as _time

    srv = AgentServer(str(tmp_path / "agent"))
    try:
        client = AgentClient("127.0.0.1", srv.port)
        # target that exits on its own with code 3
        client.setup("dier", argv=[sys.executable, "-c", "import sys; sys.exit(3)"])
        client.start("dier")
        # target we stop deliberately
        client.setup("sleeper", argv=[sys.executable, "-c", "import time; time.sleep(60)"])
        client.start("sleeper")

        deadline = _time.time() + 10
        exits = []
        while _time.time() < deadline:
            hb = client.heartbeat()
            exits = hb.get("exits", [])
            if exits:
                break
            _time.sleep(0.1)
        assert [e["target"] for e in exits] == ["dier"]
        assert exits[0]["returncode"] == 3

        client.stop("sleeper")
        _time.sleep(0.5)
        hb = client.heartbeat()
        # the deliberate stop did NOT produce a new unexpected-exit event
        assert [e["target"] for e in hb["exits"]] == ["dier"]
    finally:
        srv.close()
