"""Cross-process elastic recovery over the NETWORKED control plane.

The judge's round-3 done-criterion for "network the control plane":
a multi-process cluster where a node is killed, the failure detector
promotes a spare, the replacement streams its shards from peers over
sockets, and reads regain quorum — with no fixture-side orchestration.

Real processes involved: 1 kvnode (etcd role, cluster/kv/etcd/store.go:54),
3 dbnodes + 1 spare dbnode (each watching the placement through the KV
long-poll watch, dbnode/topology/dynamic.go:107); the failure detector
runs here in the operator-automation role, talking only to the KV server.
"""

import time

import pytest

from m3_tpu.client.session import ConsistencyError
from m3_tpu.cluster.failure import FailureDetector
from m3_tpu.cluster.placement import ShardState
from m3_tpu.cluster.services import Services
from m3_tpu.cluster.topology import ConsistencyLevel
from m3_tpu.index.query import term
from m3_tpu.testing.proc_cluster import ProcCluster

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS
HOUR = 3600 * NANOS


def test_cross_process_kill_detect_replace_stream_quorum(tmp_path):
    cluster = ProcCluster(
        num_nodes=3,
        num_shards=4,
        replica_factor=3,
        heartbeat_timeout=1.0,
        base_dir=str(tmp_path),
    )
    try:
        session = cluster.session()
        series = []
        for i in range(8):  # span every shard
            tags = ((b"host", f"w{i}".encode()), (b"name", b"reqs"))
            sid = session.write_tagged(tags, T0 + NANOS, float(i))
            session.write(sid, T0 + 2 * NANOS, float(i) + 0.5)
            series.append((sid, tags))

        # spare process: advertises + heartbeats, owns nothing
        cluster.spawn_spare("node3")

        # operator-automation: failure detector over the REMOTE kv only
        services = Services(cluster.kv, heartbeat_timeout=1.0)
        detector = FailureDetector(
            services,
            cluster.placement_svc,
            grace=0.5,
            spares=["node3"],
        )

        # SIGKILL node1: heartbeats stop; no fixture cleanup of its state
        cluster.nodes["node1"].proc.kill()
        cluster.nodes["node1"].proc.wait(timeout=10)

        deadline = time.time() + 30
        replaced = None
        while time.time() < deadline and replaced is None:
            for ev in detector.check():
                if ev.kind == "replaced":
                    replaced = ev
            time.sleep(0.1)
        assert replaced is not None, "failure detector never replaced node1"
        assert replaced.instance_id == "node1"
        assert replaced.replacement_id == "node3"

        # the replacement must peers-bootstrap all its shards and CAS them
        # AVAILABLE itself (storage/cluster_db.py) — poll the placement
        deadline = time.time() + 30
        while time.time() < deadline:
            p = cluster.placement_svc.get()
            inst = p.instances.get("node3")
            if (
                inst is not None
                and "node1" not in p.instances
                and inst.shards
                and all(
                    a.state == ShardState.AVAILABLE for a in inst.shards.values()
                )
            ):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"replacement never became AVAILABLE: {p.to_dict()}")

        cluster.wait_for_shards()

        # reads at ALL consistency require node3 to actually serve the
        # streamed data (node0+node2 alone cannot satisfy ALL)
        session = cluster.session(
            write_cl=ConsistencyLevel.ALL, read_cl=ConsistencyLevel.ALL
        )
        res = session.fetch_tagged(term(b"name", b"reqs"), T0, T0 + HOUR)
        assert len(res) == len(series)
        for _, _, dps in res:
            assert len(dps) == 2

        # the healed cluster accepts ALL-consistency writes
        sid0 = series[0][0]
        session.write(sid0, T0 + 3 * NANOS, 99.0)
        vals = [dp.value for dp in session.fetch(sid0, T0, T0 + HOUR)]
        assert vals[-1] == 99.0 and len(vals) == 3
    finally:
        cluster.close()


def test_replacement_survives_immediate_kill_after_available(tmp_path):
    """The replacement CASes its shards AVAILABLE only after a WAL
    durability barrier (bootstrap_shards → flush_wals): SIGKILL it the
    moment it reports AVAILABLE, restart it on the same data dir, and its
    own bootstrap chain must replay the peers-streamed copy."""
    from m3_tpu.index.query import term as term_q

    cluster = ProcCluster(
        num_nodes=3, num_shards=4, replica_factor=3,
        heartbeat_timeout=1.0, base_dir=str(tmp_path),
    )
    try:
        session = cluster.session()
        for i in range(8):
            session.write_tagged(
                ((b"host", f"w{i}".encode()), (b"name", b"reqs")), T0 + NANOS, float(i)
            )
        cluster.spawn_spare("node3")
        detector = FailureDetector(
            Services(cluster.kv, heartbeat_timeout=1.0),
            cluster.placement_svc, grace=0.5, spares=["node3"],
        )
        cluster.nodes["node1"].proc.kill()
        cluster.nodes["node1"].proc.wait(timeout=10)
        deadline = time.time() + 30
        while time.time() < deadline:
            detector.check()
            p = cluster.placement_svc.get()
            inst = p.instances.get("node3")
            if inst and inst.shards and all(
                a.state == ShardState.AVAILABLE for a in inst.shards.values()
            ):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("replacement never became AVAILABLE")
        cluster.nodes["node3"].proc.kill()
        cluster.nodes["node3"].proc.wait(timeout=10)
        cluster.restart("node3")
        res = cluster.nodes["node3"].client.fetch_tagged(
            "default", term_q(b"name", b"reqs"), T0, T0 + HOUR
        )
        assert len(res) == 8
        assert sum(len(d) for _, _, d in res) == 8
    finally:
        cluster.close()


def test_cross_process_node_add_streams_from_donors(tmp_path):
    """Placement add-instance over real processes: the new node's OWN
    placement watch triggers peers streaming from the donor replicas
    (cluster_add_one_node_test.go pattern, but across processes)."""
    from m3_tpu.cluster.placement import add_instance

    cluster = ProcCluster(
        num_nodes=2,
        num_shards=4,
        replica_factor=2,
        heartbeat_timeout=2.0,
        base_dir=str(tmp_path),
    )
    try:
        session = cluster.session()
        sids = []
        for i in range(6):
            tags = ((b"host", f"h{i}".encode()), (b"name", b"cpu"))
            sids.append(session.write_tagged(tags, T0 + NANOS, float(i)))

        cluster.spawn_spare("node2")
        # operator adds the instance; shards move INITIALIZING w/ sources
        while True:
            p, version = cluster.placement_svc.get_versioned()
            add_instance(p, "node2")
            p.instances["node2"].endpoint = cluster.nodes["node2"].endpoint
            try:
                cluster.placement_svc.check_and_set(p, version)
                break
            except ValueError:
                continue

        deadline = time.time() + 30
        while time.time() < deadline:
            p = cluster.placement_svc.get()
            inst = p.instances.get("node2")
            if inst and inst.shards and all(
                a.state == ShardState.AVAILABLE for a in inst.shards.values()
            ):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"node2 never AVAILABLE: {p.to_dict()}")
        cluster.wait_for_shards()

        # every shard node2 owns must serve its streamed data directly
        p = cluster.placement_svc.get()
        node2 = cluster.nodes["node2"].client
        moved = set(p.instances["node2"].shards)
        streamed = []
        for shard in moved:
            streamed.extend(node2.stream_shard("default", shard))
        # at least the series hashed to moved shards are present with data
        from m3_tpu.utils.hash import shard_for

        expect = [s for s in sids if shard_for(s, 4) in moved]
        got_ids = {sid for sid, _, _ in streamed}
        assert set(expect) <= got_ids
    finally:
        cluster.close()
