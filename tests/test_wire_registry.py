"""Wire-registry sync guards, generated from the m3lint project model:
the dispatch tables in net/server.py (NodeService + middleware),
cluster/kv_service.py, cluster/raft.py, and the aggregator debug server
must stay in sync with wire.IDEMPOTENT_OPS / UNTRACED_OPS /
RETRYABLE_ETYPES — the AST-derived model is cross-checked against the
RUNTIME registries so neither can drift without failing here."""

import pytest

from m3_tpu.net import resilience, wire
from m3_tpu.net.server import DebugService, NodeService
from m3_tpu.cluster import raft
from m3_tpu.cluster.kv_service import KVService
from tools.m3lint import REPO_ROOT, load_files
from tools.m3lint.model import ProjectModel, is_mutating_op


@pytest.fixture(scope="module")
def model():
    contexts, errors = load_files(["m3_tpu", "tools"], REPO_ROOT)
    assert errors == []
    return ProjectModel(contexts)


def _op_methods(cls) -> set:
    return {m[3:] for m in dir(cls) if m.startswith("op_")}


def test_ast_registries_match_runtime(model):
    """The lint model reads the same sets the process executes — if the
    registry literal ever stops being statically parseable, this fails
    before the checker silently goes blind."""
    assert model.registry("IDEMPOTENT_OPS").ops == wire.IDEMPOTENT_OPS
    assert model.registry("UNTRACED_OPS").ops == wire.UNTRACED_OPS
    assert model.registry("RETRYABLE_ETYPES").ops == wire.RETRYABLE_ETYPES


def test_every_idempotent_op_is_dispatched(model):
    stale = sorted(wire.IDEMPOTENT_OPS - set(model.dispatched))
    assert stale == [], f"IDEMPOTENT_OPS entries nothing serves: {stale}"


def test_no_mutating_op_is_registered_idempotent():
    bad = sorted(op for op in wire.IDEMPOTENT_OPS if is_mutating_op(op))
    assert bad == [], f"mutating ops registered for transparent retry: {bad}"


def test_untraced_ops_are_idempotent_reads(model):
    """Poller ops excluded from tracing must be read/probe ops: a
    mutating op hidden from traces would be undebuggable."""
    assert wire.UNTRACED_OPS <= wire.IDEMPOTENT_OPS
    assert wire.UNTRACED_OPS <= set(model.dispatched)


def test_retryable_etypes_are_defined_exception_classes(model):
    from m3_tpu.storage import faults as storage_faults

    for name in wire.RETRYABLE_ETYPES:
        assert name in model.classes, f"{name} not defined anywhere"
        cls = (getattr(resilience, name, None) or getattr(raft, name, None)
               or getattr(storage_faults, name, None))
        assert cls is not None and issubclass(cls, Exception), name


def test_dbnode_dispatch_table_in_sync():
    node_ops = _op_methods(NodeService)
    unclassified = sorted(
        op
        for op in node_ops
        if op not in wire.IDEMPOTENT_OPS and not is_mutating_op(op)
    )
    assert unclassified == [], (
        f"NodeService ops with undeclared retry semantics: {unclassified}"
    )
    # the writes must never be transparently retried
    writes = {op for op in node_ops if op.startswith("write")}
    assert writes and not (writes & wire.IDEMPOTENT_OPS)


def test_kv_dispatch_table_in_sync():
    kv_ops = _op_methods(KVService)
    unclassified = sorted(
        op
        for op in kv_ops
        if op not in wire.IDEMPOTENT_OPS and not is_mutating_op(op)
    )
    assert unclassified == [], (
        f"KVService ops with undeclared retry semantics: {unclassified}"
    )
    # reads are registered, mutations are not
    assert {"kv_get", "kv_keys", "kv_get_prefix", "kv_watch"} <= wire.IDEMPOTENT_OPS
    assert not ({"kv_set", "kv_cas", "kv_delete"} & wire.IDEMPOTENT_OPS)


def test_raft_kv_dispatch_table_in_sync():
    raft_ops = _op_methods(raft.RaftKVService)
    unclassified = sorted(
        op
        for op in raft_ops
        if op not in wire.IDEMPOTENT_OPS and not is_mutating_op(op)
    )
    assert unclassified == []
    # the raft protocol RPCs are duplicate-safe by design and registered
    assert {"raft_vote", "raft_append", "raft_snapshot"} <= wire.IDEMPOTENT_OPS
    assert "raft_configure" not in wire.IDEMPOTENT_OPS


def test_aggregator_debug_server_ops_in_sync(model):
    """The aggregator's --debug-port RPC surface is DebugService behind
    the middleware: health + traces + profile string-dispatch plus the
    universal metrics op — all registered idempotent probes."""
    svc = DebugService()
    assert svc.handle({"op": "health"})["ok"] is True
    for op in ("health", "traces", "metrics", "profile"):
        assert op in wire.IDEMPOTENT_OPS
        assert op in model.dispatched


def test_profile_op_registered_everywhere(model):
    """The continuous-profiling wire op (m3_tpu/profiling/): dispatched
    by the dbnode NodeService AND the DebugService (aggregator debug
    port), registered idempotent (reading the folded table is
    duplicate-safe; sampling continues regardless), and never mutating."""
    assert "profile" in wire.IDEMPOTENT_OPS
    assert not is_mutating_op("profile")
    assert "profile" in _op_methods(NodeService)
    sites = {rel for rel, _ in model.dispatched["profile"]}
    assert any(rel.endswith("net/server.py") for rel in sites)
    # a process with no sampler installed answers an explicit empty
    # profile — the fleet merge must see "nothing here", not an error
    from m3_tpu import profiling

    installed = profiling.installed()
    profiling.install(None)
    try:
        out = DebugService().handle({"op": "profile", "seconds": 5})
        assert out["enabled"] is False and out["folded"] == {}
    finally:
        profiling.install(installed)


def test_client_literal_ops_all_served(model):
    unknown = sorted(set(model.client_calls) - set(model.dispatched))
    assert unknown == [], f"client calls ops nothing dispatches: {unknown}"
