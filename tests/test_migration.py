"""Elastic placement: warm residency migration + zero-downtime resharding.

Covers the raw-fileset migration surface in storage/fs.py (manifest,
chunked resumable fetch, checkpoint-last commit, digest verification),
Database.admit_imported_fileset warm admission, the decoded peers stream's
exclude_blocks dedupe, the resident pool's heat-driven rebalance and
source-side drop_shard, the O(1) buffered-block summary behind
has_buffered_overlap, and the ClusterDatabase handoff orchestration
end-to-end over fake peers — including source death mid-stream falling
back to the decoded rebuild without wedging INITIALIZING.
"""

from __future__ import annotations

import random
import time
from types import SimpleNamespace

import pytest

from m3_tpu.cluster.kv import KVStore
from m3_tpu.cluster.placement import (
    PlacementService,
    ShardState,
    add_instance,
    build_initial_placement,
)
from m3_tpu.resident import ResidentOptions, ResidentPool
from m3_tpu.storage import fs
from m3_tpu.storage.cluster_db import ClusterDatabase
from m3_tpu.storage.database import Database, NamespaceOptions
from m3_tpu.utils.instrument import DEFAULT as METRICS

NANOS = 1_000_000_000
HOUR = 3600 * NANOS
T0 = 1_600_000_000 * NANOS


def _counter_total(name: str, **label_filter) -> float:
    fam = METRICS.collect().get(f"m3tpu_{name}")
    if fam is None:
        return 0.0
    return sum(
        c["value"]
        for c in fam["children"]
        if all(c["labels"].get(k) == v for k, v in label_filter.items())
    )


def _mkdb(path, num_shards=2, resident=True, **ns_kw):
    db = Database(
        str(path),
        num_shards=num_shards,
        commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=8 << 20) if resident else None,
    )
    db.create_namespace("ns", NamespaceOptions(**ns_kw))
    return db


def _ingest(db, n_series=8, n_points=30, seed=0):
    from m3_tpu.rules.rules import encode_tags_id

    rng = random.Random(seed)
    sids = []
    for i in range(n_series):
        tags = ((b"__name__", b"g"), (b"s", b"%03d" % i))
        sid = encode_tags_id(tags)
        db.write_tagged("ns", tags, T0, float(i))
        for j in range(n_points - 1):
            db.write(
                "ns", sid, T0 + (j + 1) * 10 * NANOS, rng.uniform(-100, 100)
            )
        sids.append(sid)
    return sids


# ---------- fs migration surface ----------


def _migrate_fileset(src_base, dst_base, fid, chunk=97, stop_after=None):
    """Chunk-copy one fileset's streamable roles; returns chunks moved.
    ``stop_after`` aborts mid-transfer (simulated source death)."""
    moved = 0
    for suffix in fs.MIGRATION_SUFFIXES:
        offset = fs.migration_file_size(dst_base, fid, suffix)
        while True:
            data, eof = fs.read_fileset_chunk(src_base, fid, suffix, offset, chunk)
            if data:
                fs.append_fileset_chunk(dst_base, fid, suffix, offset, data)
                offset += len(data)
                moved += 1
                if stop_after is not None and moved >= stop_after:
                    return moved
            if eof:
                break
    return moved


def test_manifest_and_chunked_fetch_roundtrip(tmp_path):
    src = _mkdb(tmp_path / "src", resident=False)
    _ingest(src)
    src.flush("ns", T0 + 4 * HOUR)
    manifest = fs.migration_manifest(src.base, "ns", 0)
    assert manifest, "flushed shard must list at least one fileset"
    for entry in manifest:
        assert set(entry["files"]) == set(fs.MIGRATION_SUFFIXES)
        fid = fs.FilesetID("ns", 0, entry["blockStart"], entry["volume"])
        # the checkpoint never rides the manifest: commit writes it locally
        assert "checkpoint" not in entry["files"]
        _migrate_fileset(src.base, str(tmp_path / "dst"), fid)
        assert not fs.fileset_complete(str(tmp_path / "dst"), fid)  # pre-commit
        fs.commit_imported_fileset(str(tmp_path / "dst"), fid)
        assert fs.fileset_complete(str(tmp_path / "dst"), fid)
        for suffix in fs.MIGRATION_SUFFIXES:
            with open(fs._path(src.base, fid, suffix), "rb") as f:
                want = f.read()
            with open(fs._path(str(tmp_path / "dst"), fid, suffix), "rb") as f:
                assert f.read() == want, f"{suffix} bytes differ"
    src.close()


def test_fetch_resumes_at_partial_offset(tmp_path):
    src = _mkdb(tmp_path / "src", resident=False)
    _ingest(src)
    src.flush("ns", T0 + 4 * HOUR)
    entry = fs.migration_manifest(src.base, "ns", 0)[0]
    fid = fs.FilesetID("ns", 0, entry["blockStart"], entry["volume"])
    dst = str(tmp_path / "dst")
    # source dies after 3 chunks ...
    _migrate_fileset(src.base, dst, fid, chunk=31, stop_after=3)
    partial = sum(
        fs.migration_file_size(dst, fid, s) for s in fs.MIGRATION_SUFFIXES
    )
    assert 0 < partial < sum(entry["files"].values())
    # ... the next attempt resumes at the local byte offsets, no re-fetch
    _migrate_fileset(src.base, dst, fid, chunk=31)
    fs.commit_imported_fileset(dst, fid)
    assert fs.fileset_complete(dst, fid)
    # resume offset mismatch is an importer race, not silent corruption
    with pytest.raises(ValueError):
        fs.append_fileset_chunk(dst, fid, "data", 1, b"x")
    src.close()


def test_commit_digest_mismatch_deletes_partial(tmp_path):
    src = _mkdb(tmp_path / "src", resident=False)
    _ingest(src)
    src.flush("ns", T0 + 4 * HOUR)
    entry = fs.migration_manifest(src.base, "ns", 0)[0]
    fid = fs.FilesetID("ns", 0, entry["blockStart"], entry["volume"])
    dst = str(tmp_path / "dst")
    _migrate_fileset(src.base, dst, fid)
    # flip one payload byte: commit must refuse and start the retry clean
    path = fs._path(dst, fid, "data")
    with open(path, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(ValueError):
        fs.commit_imported_fileset(dst, fid)
    assert not fs.fileset_complete(dst, fid)
    assert fs.migration_file_size(dst, fid, "data") == 0  # deleted, not kept
    src.close()


# ---------- warm admission + stream dedupe ----------


def test_admit_imported_fileset_warms_pool_and_reads_bit_exact(tmp_path):
    src = _mkdb(tmp_path / "src")
    sids = _ingest(src)
    src.flush("ns", T0 + 4 * HOUR)
    dst = _mkdb(tmp_path / "dst")
    dst.bootstrap()
    for entry in fs.migration_manifest(src.base, "ns", 0):
        fid = fs.FilesetID("ns", 0, entry["blockStart"], entry["volume"])
        _migrate_fileset(src.base, dst.base, fid)
        fs.commit_imported_fileset(dst.base, fid)
        assert dst.admit_imported_fileset("ns", 0, fid) > 0
    st = dst.resident_stats()
    assert st["entries"] > 0, "import must warm the resident pool"
    sh = dst.namespaces["ns"].shards[0]
    assert not sh.has_buffered_overlap(T0, T0 + 4 * HOUR)  # nothing re-buffered
    span = (T0 - HOUR, T0 + 4 * HOUR)
    moved = 0
    for sid in sids:
        want = src.read("ns", sid, *span)
        if src.namespaces["ns"].shard_for(sid).id != 0:
            continue
        moved += 1
        got = dst.read("ns", sid, *span)
        assert [(d.timestamp, d.value) for d in got] == [
            (d.timestamp, d.value) for d in want
        ]
    assert moved > 0
    # the imported series are queryable by tags: the reindex step ran
    from m3_tpu.index.query import TermQuery

    res = dst.query_ids("ns", TermQuery(b"__name__", b"g"), *span)
    assert len(res.docs) >= moved
    src.close()
    dst.close()


def test_stream_shard_excludes_migrated_blocks_but_keeps_buffered(tmp_path):
    db = _mkdb(tmp_path / "db", resident=False)
    sids = _ingest(db)
    db.flush("ns", T0 + 4 * HOUR)
    shard0 = {s for s in sids if db.namespaces["ns"].shard_for(s).id == 0}
    bs = (T0 // (2 * HOUR)) * (2 * HOUR)
    # a cold write lands a buffered overlay INSIDE the excluded block
    cold_sid = sorted(shard0)[0]
    db.write("ns", cold_sid, T0 + 5 * NANOS, 12345.0)
    full = {sid: dps for sid, _t, dps in db.stream_shard("ns", 0)}
    excl = {sid: dps for sid, _t, dps in db.stream_shard("ns", 0, exclude_blocks=[bs])}
    assert set(full) == shard0
    # sealed content of the excluded block is deduped away ...
    assert len(excl.get(cold_sid, [])) < len(full[cold_sid])
    # ... but the buffered overlay still streams: it is NOT in the fileset
    assert any(
        d.timestamp == T0 + 5 * NANOS and d.value == 12345.0
        for d in excl.get(cold_sid, [])
    )
    for sid in shard0 - {cold_sid}:
        assert sid not in excl or not excl[sid]
    db.close()


# ---------- O(1) buffered-block summary (plan eligibility) ----------


def test_buffered_summary_tracks_fill_flush_and_expiry(tmp_path):
    db = _mkdb(tmp_path / "db", resident=False)
    sh = db.namespaces["ns"].shards[0]
    assert not sh.has_buffered_overlap(T0, T0 + 24 * HOUR)
    sids = _ingest(db)
    assert sh.has_buffered_overlap(T0, T0 + HOUR)
    assert not sh.has_buffered_overlap(T0 + 4 * HOUR, T0 + 6 * HOUR)
    db.flush("ns", T0 + 4 * HOUR)  # warm+cold flush evicts every bucket
    assert not sh.has_buffered_overlap(T0, T0 + 24 * HOUR)
    assert sh._buffered_blocks == {}
    # a cold write re-fills exactly one block's summary entry
    cold_sid = next(s for s in sids if db.namespaces["ns"].shard_for(s).id == 0)
    db.write("ns", cold_sid, T0 + 7 * NANOS, 1.0)
    assert sh.has_buffered_overlap(T0, T0 + HOUR)
    assert len(sh._buffered_blocks) == 1
    db.flush("ns", T0 + 4 * HOUR)  # cold flush bumps the volume, evicts
    assert sh._buffered_blocks == {}
    # retention tick expiry decrements the summary too
    db.write("ns", cold_sid, T0 + 6 * HOUR, 2.0)
    assert sh.has_buffered_overlap(T0 + 6 * HOUR, T0 + 8 * HOUR)
    db.tick(T0 + 6 * HOUR + db.namespaces["ns"].opts.retention_nanos + 4 * HOUR)
    assert sh._buffered_blocks == {}
    db.close()


def test_plan_eligibility_flips_as_buffers_fill_and_flush(tmp_path):
    """The fused-plan gate (plan:buffer-overlay) must flip OFF when a live
    write overlays the span and back ON once the overlay seals — driven
    by the O(1) summary, not a walk of every series buffer."""
    import numpy as np

    from m3_tpu.index.device import IndexDeviceOptions
    from m3_tpu.query.engine import Engine
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.query import stats as query_stats

    db = Database(
        str(tmp_path / "db"),
        num_shards=2,
        commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=16 << 20),
        index_device_options=IndexDeviceOptions(max_bytes=64 << 20),
    )
    db.create_namespace("ns", NamespaceOptions())
    _ingest(db, n_series=4, n_points=40)
    db.flush("ns", T0 + 4 * HOUR)
    eng = Engine(M3Storage(db, "ns"))
    span = (T0 + 60 * NANOS, T0 + 290 * NANOS, 10 * NANOS)

    def run(explain=False):
        qs = query_stats.start('sum(rate(g[1m]))')
        assert qs is not None
        if explain:
            qs.record_routing = True
        try:
            res = eng.query_range('sum(rate(g[1m]))', *span)
        finally:
            query_stats.finish(qs, 0.0)
        return np.asarray(res.values), qs

    v0, qs0 = run()
    assert qs0.plan_fallbacks == 0  # sealed span: fused plan eligible
    db.write("ns", b"overlay-sid", T0 + 120 * NANOS, 7.0)  # buffer fills
    v1, qs1 = run(explain=True)
    assert qs1.plan_fallbacks >= 1
    assert any(
        r["reason"] == "plan:buffer-overlay"
        for r in qs1.routing
        if r["path"] == "staged"
    )
    db.flush("ns", T0 + 4 * HOUR)  # overlay seals: eligibility returns
    _v2, qs2 = run()
    assert qs2.plan_fallbacks == 0
    db.close()


# ---------- pool rebalance + source-side drop ----------


def _pool():
    return ResidentPool(
        ResidentOptions(
            max_bytes=1 << 14, page_words=16, side_bytes=1 << 20,
            side_page_chunks=4,
        )
    )


def _admit(pool, shard, n, nbytes=512, ns="ns"):
    from m3_tpu.codec.m3tsz import Encoder

    for i in range(n):
        enc = Encoder(T0)
        t = T0
        for j in range(nbytes // 10):
            t += NANOS
            enc.encode(t, float(i * 1000 + j))
        pool.admit_block(
            ns, shard, T0 + i * 2 * HOUR, 0,
            [(b"s%d-%d" % (shard, i), enc.stream(), 64)],
        )


def test_rebalance_sheds_cold_shard_toward_heat(tmp_path):
    pool = _pool()
    _admit(pool, 0, 6)
    _admit(pool, 1, 6)
    before = pool.stats()
    usage0 = pool.shard_usage()
    assert set(usage0) == {("ns", 0), ("ns", 1)}
    # all observed demand on shard 1: shard 0 is over its weighted share
    evicted = pool.rebalance({"1": {"hits": 1000.0, "misses": 0.0}})
    assert evicted > 0
    after = pool.stats()
    assert after["rebalance_evictions"] == before["rebalance_evictions"] + evicted
    usage = pool.shard_usage()
    assert usage.get(("ns", 0), 0) < usage0[("ns", 0)]
    assert usage.get(("ns", 1), 0) == usage0[("ns", 1)]  # hot shard untouched
    # idempotent at the fixpoint: a second pass with the same heat is ~quiet
    assert pool.rebalance({"1": {"hits": 1000.0, "misses": 0.0}}) == 0


def test_rebalance_single_shard_is_noop():
    pool = _pool()
    _admit(pool, 0, 4)
    assert pool.rebalance({"0": {"hits": 10.0}}) == 0


def test_drop_shard_frees_only_that_shard():
    pool = _pool()
    _admit(pool, 0, 3)
    _admit(pool, 1, 3)
    n = pool.drop_shard(None, 0)
    assert n == 3
    usage = pool.shard_usage()
    assert ("ns", 0) not in usage and ("ns", 1) in usage
    assert pool.drop_shard(None, 0) == 0  # idempotent


# ---------- ClusterDatabase handoff orchestration (fake peers) ----------


class _FakePeer:
    """In-process stand-in for net.client.RemoteNode over one source db."""

    def __init__(self, db, log, fail_fetch=False):
        self.db = db
        self.log = log
        self.fail_fetch = fail_fetch

    def resident_stats(self):
        return self.db.resident_stats()

    def migrate_manifest(self, ns, shard):
        return fs.migration_manifest(self.db.base, ns, shard)

    def migrate_fetch(self, ns, shard, block_start, volume, suffix, offset,
                      max_bytes, _timeout=None):
        if self.fail_fetch:
            raise ConnectionError("source died mid-stream")
        fid = fs.FilesetID(ns, shard, block_start, volume)
        data, eof = fs.read_fileset_chunk(
            self.db.base, fid, suffix, offset, max_bytes
        )
        self.log.setdefault("fetches", []).append((suffix, offset))
        return {"data": data, "eof": eof}

    def stream_shard(self, ns, shard, exclude_blocks=None):
        self.log.setdefault("streams", []).append(
            (shard, tuple(exclude_blocks or ()))
        )
        return self.db.stream_shard(ns, shard, exclude_blocks or ())

    def close(self):
        pass


def _wait_available(svc, node_id, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        p = svc.get()
        inst = p.instances.get(node_id)
        if inst and inst.shards and all(
            a.state == ShardState.AVAILABLE for a in inst.shards.values()
        ):
            return p
        time.sleep(0.05)
    raise AssertionError(f"{node_id} never reached AVAILABLE: {svc.get().to_dict()}")


def _handoff_fixture(tmp_path, fail_fetch=False):
    src = _mkdb(tmp_path / "src")
    sids = _ingest(src)
    src.flush("ns", T0 + 4 * HOUR)
    src.bootstrap()
    dst = _mkdb(tmp_path / "dst")
    dst.bootstrap()
    kv = KVStore()
    svc = PlacementService(kv)
    p = build_initial_placement(["src"], 2, 1)
    p.instances["src"].endpoint = "src"
    svc.set(p)
    log: dict = {}
    peers = {"src": _FakePeer(src, log, fail_fetch=fail_fetch)}
    cdb = ClusterDatabase(
        dst, "dst", svc,
        node_service=SimpleNamespace(assigned_shards=set()),
        peer_factory=lambda ep: peers[ep],
        retry_secs=0.2,
        migration_chunk_bytes=113,  # force many resumable chunks
    )
    return src, dst, svc, cdb, log, sids


def test_cluster_handoff_migrates_warm_then_cuts_over(tmp_path):
    src, dst, svc, cdb, log, sids = _handoff_fixture(tmp_path)
    base_filesets = _counter_total("migration_filesets_total")
    base_failures = _counter_total("migration_stream_failures_total")
    cdb.start()
    try:
        p = svc.get()
        p = add_instance(p, "dst")
        p.instances["dst"].endpoint = "dst"
        svc.set(p)
        final = _wait_available(svc, "dst")
        moved = sorted(final.instances["dst"].shards)
        assert moved, "add_instance must hand shards to the new node"
        # sealed filesets arrived as raw bytes and were committed
        for shard in moved:
            for entry in fs.migration_manifest(src.base, "ns", shard):
                fid = fs.FilesetID(
                    "ns", shard, entry["blockStart"], entry["volume"]
                )
                assert fs.fileset_complete(dst.base, fid)
        assert _counter_total("migration_filesets_total") > base_filesets
        assert _counter_total("migration_stream_failures_total") == base_failures
        assert _counter_total("migration_streamed_bytes_total", peer="src") > 0
        # the decoded stream ran WITH the migrated blocks excluded ...
        assert log["streams"], "peers bootstrap must still stream buffers"
        assert all(excl for _s, excl in log["streams"])
        # ... so nothing sealed re-buffered: the new owner's first scan of
        # a migrated block is resident-eligible (warm before cutover)
        for shard in moved:
            sh = dst.namespaces["ns"].shards[shard]
            assert not sh.has_buffered_overlap(T0, T0 + 4 * HOUR)
        assert dst.resident_stats()["entries"] > 0
        # bit-identical reads on the new owner
        span = (T0 - HOUR, T0 + 4 * HOUR)
        checked = 0
        for sid in sids:
            if src.namespaces["ns"].shard_for(sid).id not in moved:
                continue
            want = [(d.timestamp, d.value) for d in src.read("ns", sid, *span)]
            got = [(d.timestamp, d.value) for d in dst.read("ns", sid, *span)]
            assert got == want
            checked += 1
        assert checked > 0
    finally:
        cdb.stop()
        src.close()
        dst.close()


def test_source_death_mid_stream_falls_back_counted(tmp_path):
    """Every migrate_fetch fails: the shard must still reach AVAILABLE via
    the decoded fileset-driven rebuild, and the fallback is counted."""
    src, dst, svc, cdb, log, sids = _handoff_fixture(tmp_path, fail_fetch=True)
    base_failures = _counter_total("migration_stream_failures_total")
    cdb.start()
    try:
        p = svc.get()
        p = add_instance(p, "dst")
        p.instances["dst"].endpoint = "dst"
        svc.set(p)
        final = _wait_available(svc, "dst")
        moved = sorted(final.instances["dst"].shards)
        assert _counter_total("migration_stream_failures_total") > base_failures
        # nothing was committed, so nothing is excluded: full decoded rebuild
        assert log["streams"] and all(excl == () for _s, excl in log["streams"])
        span = (T0 - HOUR, T0 + 4 * HOUR)
        checked = 0
        for sid in sids:
            if src.namespaces["ns"].shard_for(sid).id not in moved:
                continue
            want = [(d.timestamp, d.value) for d in src.read("ns", sid, *span)]
            got = [(d.timestamp, d.value) for d in dst.read("ns", sid, *span)]
            assert got == want
            checked += 1
        assert checked > 0
        # a partially-admitted block is never visible: either the import
        # committed (excluded) or left no trace (checkpoint-last)
        for shard in moved:
            assert fs.migration_manifest(dst.base, "ns", shard) == [] or all(
                fs.fileset_complete(
                    dst.base,
                    fs.FilesetID("ns", shard, e["blockStart"], e["volume"]),
                )
                for e in fs.migration_manifest(dst.base, "ns", shard)
            )
    finally:
        cdb.stop()
        src.close()
        dst.close()


def test_source_side_drops_residency_on_shards_lost(tmp_path):
    """The donor's ClusterDatabase must free the handed-off shard's
    residency once the placement stops assigning it."""
    db = _mkdb(tmp_path / "db")
    _ingest(db)
    db.flush("ns", T0 + 4 * HOUR)
    assert db.resident_stats()["entries"] > 0
    kv = KVStore()
    svc = PlacementService(kv)
    p = build_initial_placement(["src"], 2, 1)
    p.instances["src"].endpoint = "src"
    svc.set(p)
    cdb = ClusterDatabase(
        db, "src", svc, node_service=SimpleNamespace(assigned_shards=set())
    )
    cdb.start()
    try:
        shards_with_entries = {
            s for (_ns, s) in db.resident_pool.shard_usage()
        }
        lost = sorted(shards_with_entries)[0]
        p = svc.get()
        del p.instances["src"].shards[lost]
        svc.set(p)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(
                s != lost for (_ns, s) in db.resident_pool.shard_usage()
            ):
                break
            time.sleep(0.05)
        assert all(s != lost for (_ns, s) in db.resident_pool.shard_usage())
    finally:
        cdb.stop()
        db.close()
