"""mmap'd immutable index segments (index/disk_segment.py — the fst
segment's role: segment/fst/segment.go:181), merge compaction, and the
postings-list LRU cache (postings_list_cache.go:59)."""

import os

import numpy as np
import pytest

from m3_tpu.index.disk_segment import DiskSegment, write_disk_segment
from m3_tpu.index.ns_index import NamespaceIndex
from m3_tpu.index.postings_cache import PostingsListCache
from m3_tpu.index.query import conj, neg, regexp, search_segment, term
from m3_tpu.index.query import FieldQuery
from m3_tpu.index.segment import Document, MutableSegment, merge_segments

NANOS = 1_000_000_000
HOUR = 3600 * NANOS
T0 = 1_600_000_000 * NANOS


def _mseg(n=500, prefix=""):
    m = MutableSegment()
    for i in range(n):
        m.insert(
            Document(
                f"{prefix}id{i}".encode(),
                (
                    (b"host", f"h{i % 7}".encode()),
                    (b"name", f"metric_{i % 13}".encode()),
                ),
            )
        )
    return m


def test_disk_segment_roundtrip(tmp_path):
    sealed = _mseg().seal()
    path = write_disk_segment(str(tmp_path / "seg.idx"), sealed)
    disk = DiskSegment(path)
    assert len(disk) == len(sealed)
    assert disk.fields() == sealed.fields()
    for f in sealed.fields():
        assert disk.terms(f) == list(sealed.terms(f))
        for t in sealed.terms(f):
            np.testing.assert_array_equal(
                np.asarray(disk.postings(f, t)), np.asarray(sealed.postings(f, t))
            )
    for i in (0, 1, 250, 499):
        assert disk.doc(i) == sealed.docs[i]
    # missing lookups
    assert disk.postings(b"host", b"nope").size == 0
    assert disk.postings(b"ghost", b"x").size == 0


@pytest.mark.parametrize(
    "q",
    [
        term(b"host", b"h3"),
        regexp(b"name", b"metric_1[0-2]"),
        FieldQuery(b"host"),
        conj(term(b"host", b"h1"), regexp(b"name", b"metric_.*")),
        conj(term(b"host", b"h1"), neg(term(b"name", b"metric_3"))),
    ],
)
def test_disk_matches_sealed_search(tmp_path, q):
    sealed = _mseg().seal()
    disk = DiskSegment(write_disk_segment(str(tmp_path / "s.idx"), sealed))
    np.testing.assert_array_equal(
        search_segment(disk, q), search_segment(sealed, q)
    )


def test_merge_segments_dedupes_by_id():
    a = _mseg(50)
    b = _mseg(80)  # overlaps a's ids
    merged = merge_segments([a.seal(), b.seal()])
    assert len(merged) == 80
    assert len(merged.postings(b"host", b"h0")) == len(b.seal().postings(b"host", b"h0"))


def test_ns_index_persists_mmap_and_reloads(tmp_path):
    ix = NamespaceIndex(block_size_nanos=HOUR)
    for i in range(300):
        ix.write(
            f"s{i}".encode(),
            ((b"host", f"h{i % 5}".encode()), (b"name", b"cpu")),
            T0 + (i % 2) * 10 * NANOS,
        )
    paths = ix.persist_before(str(tmp_path), "ns", T0 + 2 * HOUR)
    assert paths and all(p.endswith(".idx") for p in paths)
    # the in-memory block now serves from the mmap'd segment
    bs = (T0 // HOUR) * HOUR
    from m3_tpu.index.disk_segment import DiskSegment as DS

    assert isinstance(ix.blocks[bs].sealed[0], DS)
    r = ix.query(term(b"host", b"h2"), T0 - HOUR, T0 + HOUR)
    assert len(r.docs) == 60

    ix2 = NamespaceIndex(block_size_nanos=HOUR)
    loaded = ix2.load_persisted(str(tmp_path), "ns")
    assert bs in loaded
    r2 = ix2.query(regexp(b"host", b"h[12]"), T0 - HOUR, T0 + HOUR)
    assert len(r2.docs) == 120
    agg = ix2.aggregate_query(None, T0 - HOUR, T0 + HOUR)
    assert agg[b"name"] == {b"cpu"}


def test_postings_cache_hits_on_repeated_regexp(tmp_path):
    ix = NamespaceIndex(block_size_nanos=HOUR)
    for i in range(200):
        ix.write(f"s{i}".encode(), ((b"host", f"h{i % 5}".encode()),), T0)
    ix.persist_before(str(tmp_path), "ns", T0 + 2 * HOUR)  # immutable now
    q = regexp(b"host", b"h[0-3]")
    r1 = ix.query(q, T0 - HOUR, T0 + HOUR)
    misses = ix.postings_cache.misses
    r2 = ix.query(q, T0 - HOUR, T0 + HOUR)
    assert [d.id for d in r1.docs] == [d.id for d in r2.docs]
    assert ix.postings_cache.hits >= 1
    assert ix.postings_cache.misses == misses  # second run fully cached


def test_mutable_segments_bypass_cache():
    cache = PostingsListCache()
    m = _mseg(50)
    out1 = search_segment(m, regexp(b"host", b"h1"), cache)
    m.insert(Document(b"new", ((b"host", b"h1"),)))
    out2 = search_segment(m, regexp(b"host", b"h1"), cache)
    assert len(out2) == len(out1) + 1  # fresh result, not a stale cache hit
    assert len(cache) == 0


def test_cache_lru_eviction():
    cache = PostingsListCache(capacity=2)
    cache.put(("a",), np.zeros(1, np.int32))
    cache.put(("b",), np.zeros(2, np.int32))
    cache.get(("a",))
    cache.put(("c",), np.zeros(3, np.int32))  # evicts b (LRU)
    assert cache.get(("b",)) is None
    assert cache.get(("a",)) is not None
    assert cache.get(("c",)) is not None
