"""Lock-order race harness (m3_tpu/testing/lockcheck): the contrived
AB/BA inversion must fail with a readable cycle report even though the
sequential execution never deadlocks, and a lock held across a
registered blocking boundary must trip the boundary rule."""

import queue
import threading

import pytest

from m3_tpu.testing.lockcheck import LockCheck, LockOrderError


def test_ab_ba_inversion_reports_cycle():
    chk = LockCheck()
    a = chk.lock("A")
    b = chk.lock("B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    # SEQUENTIAL thread runs: the deadlocking interleaving never executes,
    # the order inversion is still caught from the merged graph
    for fn in (ab, ba):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join(timeout=10)

    with pytest.raises(LockOrderError) as exc:
        chk.assert_clean()
    msg = str(exc.value)
    assert "cycle" in msg
    assert "A" in msg and "B" in msg
    # the report carries acquisition sites, not just lock names
    assert "test_lockcheck.py" in msg


def test_consistent_order_is_clean():
    chk = LockCheck()
    a = chk.lock("A")
    b = chk.lock("B")

    def ab():
        with a:
            with b:
                pass

    for _ in range(2):
        t = threading.Thread(target=ab, daemon=True)
        t.start()
        t.join(timeout=10)
    chk.assert_clean()
    assert chk.cycles() == []


def test_rlock_reentry_adds_no_self_edge():
    chk = LockCheck()
    r = chk.rlock("R")
    with r:
        with r:
            pass
    chk.assert_clean()


def test_three_lock_rotation_cycle():
    chk = LockCheck()
    locks = [chk.lock(n) for n in ("L0", "L1", "L2")]

    def pair(i, j):
        with locks[i]:
            with locks[j]:
                pass

    for i, j in ((0, 1), (1, 2), (2, 0)):
        t = threading.Thread(target=pair, args=(i, j), daemon=True)
        t.start()
        t.join(timeout=10)
    cycles = chk.cycles()
    assert len(cycles) == 1 and len(cycles[0]) == 3
    with pytest.raises(LockOrderError):
        chk.assert_clean()


def test_blocking_boundary_while_holding_lock():
    chk = LockCheck()
    lock = chk.lock("shard")

    def fake_block_until_ready(x):
        return x

    wrapped = chk.wrap_blocking(fake_block_until_ready, "jax.block_until_ready")
    with lock:
        assert wrapped(7) == 7  # still calls through
    with pytest.raises(LockOrderError) as exc:
        chk.assert_clean()
    msg = str(exc.value)
    assert "jax.block_until_ready" in msg and "shard" in msg


def test_blocking_boundary_without_lock_is_clean():
    chk = LockCheck()
    lock = chk.lock("shard")
    with lock:
        pass
    chk.boundary("socket send")  # nothing held -> fine
    chk.assert_clean()


def test_instrumented_patches_condition_and_queue():
    """Locks created inside the patch window — including those inside
    threading.Condition/Event and queue.Queue — are tracked, and
    Condition.wait's release/reacquire keeps bookkeeping truthful."""
    with LockCheck.instrumented() as chk:
        cond = threading.Condition()
        q: queue.Queue = queue.Queue()
        done = threading.Event()

        def consumer():
            with cond:
                cond.wait(timeout=5)
            q.get(timeout=5)
            done.set()

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        with cond:
            cond.notify_all()
        q.put(1)
        assert done.wait(timeout=10)
        t.join(timeout=10)
    chk.assert_clean()
    # the patch is rolled back
    assert threading.Lock is not None and not hasattr(threading.Lock(), "_check")


def test_instrumented_catches_inversion_in_patched_code():
    with LockCheck.instrumented() as chk:
        a = threading.Lock()
        b = threading.Lock()

        def run(first, second):
            with first:
                with second:
                    pass

        for pair in ((a, b), (b, a)):
            t = threading.Thread(target=run, args=pair, daemon=True)
            t.start()
            t.join(timeout=10)
    with pytest.raises(LockOrderError):
        chk.assert_clean()
