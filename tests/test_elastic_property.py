"""Seeded lifecycle property: cold/backfill writes interleaved with cold
flush, repair, peer streaming, and retention ticks must keep the decoded
cache, the resident pool, and the device index coherent — every cold-flush
volume bump invalidates superseded entries on all tiers, and the
resident-vs-streamed scan totals stay bit-exact throughout (satellite of
the elastic-placement PR: these are exactly the storms a migration-warmed
node lives through)."""

from __future__ import annotations

import random

import numpy as np
import pytest

from m3_tpu.index.device import IndexDeviceOptions
from m3_tpu.query.engine import Engine
from m3_tpu.query.m3_storage import M3Storage
from m3_tpu.query.promql import Matcher
from m3_tpu.resident import ResidentOptions
from m3_tpu.rules.rules import encode_tags_id
from m3_tpu.storage import fs
from m3_tpu.storage import repair as repair_mod
from m3_tpu.storage.database import Database, NamespaceOptions
from m3_tpu.storage.repair import repair_database

NANOS = 1_000_000_000
HOUR = 3600 * NANOS
BSZ = 2 * HOUR
T0 = 1_600_000_000 * NANOS
NS_OPTS = dict(retention_nanos=12 * HOUR, block_size_nanos=BSZ)


class _RepairPeer:
    def __init__(self, db):
        self.db = db

    def block_metadata(self, ns, shard):
        return repair_mod.block_metadata(self.db, ns, shard)

    def stream_series_blocks(self, ns, shard, items):
        return repair_mod.stream_series_blocks(self.db, ns, items)


def _no_superseded_volumes(db):
    """Every cache/pool entry's volume is the block's LATEST fileset
    volume — the cold-flush bump invalidated everything below it — and
    the DISK holds exactly one volume per block: the bump deletes
    superseded filesets eagerly instead of leaving them for retention."""
    on_disk: dict[tuple[int, int], list[int]] = {}
    for shard in db.namespaces["ns"].shards:
        for fid in fs.list_fileset_volumes(db.base, "ns", shard.id):
            on_disk.setdefault((shard.id, fid.block_start), []).append(fid.volume)
    latest: dict[tuple[int, int], int] = {}
    for k, vols in on_disk.items():
        latest[k] = max(vols)
        assert len(vols) == 1, (
            f"disk holds superseded volumes {sorted(vols)} for "
            f"shard={k[0]} bs={k[1]} (eager cleanup should leave one)"
        )
    for name, od in (
        ("pool", db.resident_pool._od),
        ("cache", db.block_cache._od),
    ):
        for key in list(od):
            if key.namespace != "ns":
                continue
            want = latest.get((key.shard_id, key.block_start))
            assert want is not None and key.volume == want, (
                f"{name} holds superseded volume {key.volume} (latest {want}) "
                f"for shard={key.shard_id} bs={key.block_start}"
            )


def _totals(db, lo, hi):
    st = M3Storage(db, "ns")
    return st.scan_totals([Matcher("__name__", "=", "g")], lo, hi)


def _run_lifecycle(base_path, seed, steps=36, check_every=1):
    rng = random.Random(seed)
    live = Database(
        str(base_path / "live"),
        num_shards=2,
        commitlog_enabled=False,
        resident_options=ResidentOptions(max_bytes=16 << 20),
        index_device_options=IndexDeviceOptions(max_bytes=32 << 20),
    )
    oracle = Database(str(base_path / "oracle"), num_shards=2, commitlog_enabled=False)
    replica = Database(str(base_path / "rep"), num_shards=2, commitlog_enabled=False)
    dbs = (live, oracle, replica)
    for db in dbs:
        db.create_namespace("ns", NamespaceOptions(**NS_OPTS))

    series = []
    for i in range(6):
        tags = ((b"__name__", b"g"), (b"s", b"%03d" % i))
        sid = encode_tags_id(tags)
        for db in dbs:
            db.write_tagged("ns", tags, T0, float(i))
        series.append((sid, tags))

    now = T0 + 30 * 60 * NANOS
    flushed_blocks: set[int] = set()

    def write_all(tags, t, v):
        # tagged writes keep the series indexed in the block they land
        # in, so retention expiry of old index blocks never orphans data
        for db in dbs:
            db.write_tagged("ns", tags, t, v)

    def op_warm():
        for _ in range(rng.randrange(1, 6)):
            write_all(rng.choice(series)[1],
                      now - rng.randrange(0, 600) * NANOS,
                      rng.uniform(-50, 50))

    def op_backfill():
        if not flushed_blocks:
            return
        bs = rng.choice(sorted(flushed_blocks))
        if bs + BSZ <= now - NS_OPTS["retention_nanos"] + BSZ:
            return  # too old: a rejected cold write proves nothing here
        write_all(rng.choice(series)[1],
                  bs + rng.randrange(1, BSZ // NANOS) * NANOS,
                  rng.uniform(-50, 50))

    def op_flush():
        for db in dbs:
            db.flush("ns", now)
        for shard in live.namespaces["ns"].shards:
            for fid in fs.list_filesets(live.base, "ns", shard.id):
                flushed_blocks.add(fid.block_start)
        flushed_blocks.discard(max(flushed_blocks, default=0) + BSZ)
        _no_superseded_volumes(live)

    def op_repair():
        # points only the replica (and the oracle) hold: repair must
        # stream the diff into the live node
        for _ in range(rng.randrange(1, 4)):
            t = now - rng.randrange(0, 3600) * NANOS
            v = rng.uniform(-50, 50)
            tags = rng.choice(series)[1]
            for db in (oracle, replica):
                db.write_tagged("ns", tags, t, v)
        r = repair_database(live, "ns", [_RepairPeer(replica)])
        assert not r.peer_errors

    def op_peer_stream():
        for shard in (0, 1):
            a = {
                sid: [(d.timestamp, d.value) for d in dps]
                for sid, _t, dps in live.stream_shard("ns", shard)
            }
            b = {
                sid: [(d.timestamp, d.value) for d in dps]
                for sid, _t, dps in oracle.stream_shard("ns", shard)
            }
            assert a == b, f"peer stream diverged on shard {shard}"

    def op_tick():
        for db in dbs:
            db.tick(now)
        _no_superseded_volumes(live)

    ops = [op_warm, op_warm, op_backfill, op_flush, op_repair,
           op_peer_stream, op_tick]
    for _step in range(steps):
        now += rng.randrange(5, 45) * 60 * NANOS
        rng.choice(ops)()
        # the live-vs-oracle totals scan is the expensive half of a step;
        # the tier-1 run amortizes it (check_every>1), the slow seeds
        # keep per-step divergence localization
        if (_step + 1) % check_every and _step != steps - 1:
            continue
        lo, hi = now - 8 * HOUR, now
        tl, to = _totals(live, lo, hi), _totals(oracle, lo, hi)
        assert to["path"] == "streamed"
        assert {k: v for k, v in tl.items() if k != "path"} == {
            k: v for k, v in to.items() if k != "path"
        }, f"totals diverged after {_step} steps (seed {seed})"

    # settle: seal everything, then the whole span must run resident on
    # the live node and STILL match the streamed oracle bit-for-bit.
    # Advance to the next block boundary first so the block containing
    # `now` seals too — otherwise residency of the final span depends on
    # where the seeded walk happened to leave `now` within its block.
    now = ((now // BSZ) + 1) * BSZ
    for db in dbs:
        db.flush("ns", now)
    _no_superseded_volumes(live)
    lo, hi = now - 6 * HOUR, now - 1
    tl, to = _totals(live, lo, hi), _totals(oracle, lo, hi)
    if tl["count"]:
        assert tl["path"] == "resident", tl
    assert {k: v for k, v in tl.items() if k != "path"} == {
        k: v for k, v in to.items() if k != "path"
    }
    # engine-level parity: the fused/device-index path vs the host oracle
    el, eo = Engine(M3Storage(live, "ns")), Engine(M3Storage(oracle, "ns"))
    span = (now - 4 * HOUR, now - 2 * HOUR, 5 * 60 * NANOS)
    ql = np.asarray(el.query_range("sum(g)", *span).values)
    qo = np.asarray(eo.query_range("sum(g)", *span).values)
    assert np.array_equal(ql, qo, equal_nan=True)
    for db in dbs:
        db.close()


def test_interleaved_lifecycle_property(tmp_path):
    # trimmed shape for tier-1; the slow parametrization below runs the
    # full 36-step / per-step-checked lifecycle on three more seeds
    _run_lifecycle(tmp_path / "seed3", 3, steps=18, check_every=3)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 11, 29, 47])
def test_interleaved_lifecycle_property_more_seeds(tmp_path, seed):
    _run_lifecycle(tmp_path / f"seed{seed}", seed)
