"""m3msg socket transport: the shard-routed producer delivering over real
TCP connections with acks, outage queuing, and retry drains
(msg/protocol + consumer server roles)."""

import time

from m3_tpu.msg.bus import ConsumerService, Producer, Topic
from m3_tpu.msg.transport import ConsumerServer, RemoteConsumer


def _topic():
    return Topic(
        "agg_metrics",
        num_shards=8,
        consumer_services=[
            ConsumerService("ingest", "shared"),
            ConsumerService("mirror", "replicated"),
        ],
    )


def test_produce_over_sockets_shared_and_replicated():
    got_ingest, got_mirror_a, got_mirror_b = [], [], []
    servers = [
        ConsumerServer(lambda m: got_ingest.append(m.payload) or True),
        ConsumerServer(lambda m: got_mirror_a.append(m.payload) or True),
        ConsumerServer(lambda m: got_mirror_b.append(m.payload) or True),
    ]
    for s in servers:
        s.start()
    try:
        producer = Producer(_topic())
        producer.register(
            RemoteConsumer("ingest", "i0", servers[0].host, servers[0].port)
        )
        producer.register(
            RemoteConsumer("mirror", "m0", servers[1].host, servers[1].port)
        )
        producer.register(
            RemoteConsumer("mirror", "m1", servers[2].host, servers[2].port)
        )
        for i in range(10):
            producer.produce(i, b"payload-%d" % i)
        assert producer.num_unacked == 0
        assert sorted(got_ingest) == sorted(b"payload-%d" % i for i in range(10))
        # replicated: every instance received every message
        assert len(got_mirror_a) == 10 and len(got_mirror_b) == 10
    finally:
        for s in servers:
            s.stop()


def test_outage_queues_then_retry_drains():
    got = []
    server = ConsumerServer(lambda m: got.append(m.payload) or True)
    server.start()
    host, port = server.host, server.port
    topic = Topic("t", 4, [ConsumerService("ingest", "shared")])
    producer = Producer(topic)
    consumer = RemoteConsumer("ingest", "i0", host, port)
    producer.register(consumer)
    producer.produce(0, b"before")
    assert producer.num_unacked == 0

    server.stop()  # consumer service goes away
    producer.produce(1, b"during-1")
    producer.produce(2, b"during-2")
    assert producer.num_unacked == 2

    # service returns on the same port; the retry sweep delivers everything
    server2 = ConsumerServer(lambda m: got.append(m.payload) or True, port=port)
    server2.start()
    try:
        deadline = time.time() + 10
        while producer.num_unacked and time.time() < deadline:
            producer.retry_unacked()
            time.sleep(0.01)
        assert producer.num_unacked == 0
        assert sorted(got) == [b"before", b"during-1", b"during-2"]
    finally:
        server2.stop()
        consumer.close()


def test_replicated_mirror_outage_retries_per_instance():
    """One mirror acking must not swallow another mirror's missed delivery:
    unacked tracking is per instance for replicated services."""
    got_a, got_b = [], []
    sa = ConsumerServer(lambda m: got_a.append(m.payload) or True)
    sb = ConsumerServer(lambda m: got_b.append(m.payload) or True)
    sa.start()
    sb.start()
    b_port = sb.port
    topic = Topic("t", 4, [ConsumerService("mirror", "replicated")])
    producer = Producer(topic)
    producer.register(RemoteConsumer("mirror", "ma", sa.host, sa.port))
    producer.register(RemoteConsumer("mirror", "mb", sb.host, b_port))
    try:
        sb.stop()  # mirror b blips; a stays healthy
        producer.produce(0, b"m1")
        assert got_a == [b"m1"]
        assert producer.num_unacked == 1  # queued FOR b despite a's ack
        sb2 = ConsumerServer(lambda m: got_b.append(m.payload) or True, port=b_port)
        sb2.start()
        try:
            deadline = time.time() + 10
            while producer.num_unacked and time.time() < deadline:
                producer.retry_unacked()
                time.sleep(0.01)
            assert got_b == [b"m1"]
        finally:
            sb2.stop()
    finally:
        sa.stop()


def test_handler_failure_is_not_acked():
    fail = [True]
    got = []

    def handler(m):
        if fail[0]:
            return False
        got.append(m.payload)
        return True

    server = ConsumerServer(handler)
    server.start()
    try:
        topic = Topic("t", 2, [ConsumerService("ingest", "shared")])
        producer = Producer(topic)
        producer.register(RemoteConsumer("ingest", "i0", server.host, server.port))
        producer.produce(0, b"x")
        assert producer.num_unacked == 1  # nack -> queued
        fail[0] = False
        producer.retry_unacked()
        assert producer.num_unacked == 0 and got == [b"x"]
    finally:
        server.stop()
