"""InfluxDB line-protocol parsing + coordinator ingest route
(reference: src/query/api/v1/handler/influxdb/write.go)."""

import json
import urllib.request

import pytest

from m3_tpu.services.coordinator import Coordinator, serve
from m3_tpu.services.influx import LineProtocolError, parse_body, parse_line

T0 = 1_600_000_000


def test_parse_basic_line():
    m, tags, fields, ts = parse_line("cpu,host=a,dc=ny usage=0.5 1600000000000000000")
    assert m == "cpu"
    assert tags == {"host": "a", "dc": "ny"}
    assert fields == {"usage": 0.5}
    assert ts == 1600000000000000000


def test_parse_escapes_and_quotes():
    m, tags, fields, ts = parse_line(
        r'disk\ io,path=/var/a\,b used=12i,label="x y",ok=true'
    )
    assert m == "disk io"
    assert tags == {"path": "/var/a,b"}
    assert fields["used"] == 12.0
    assert fields["ok"] is True
    assert ts is None


def test_parse_body_field_naming_and_precision():
    pts = parse_body(
        "cpu,host=a value=1.5 1600000000\ncpu,host=a idle=2.0 1600000000",
        precision="s",
    )
    # field named "value" keeps the bare measurement name
    assert pts[0][0] == "cpu" and pts[1][0] == "cpu_idle"
    assert pts[0][2] == 1_600_000_000 * 10**9
    assert pts[0][3] == 1.5


def test_parse_body_drops_non_numeric_and_comments():
    pts = parse_body('# comment\ncpu s="str",ok=true,v=3 1\n', precision="s")
    assert [(p[0], p[3]) for p in pts] == [("cpu_v", 3.0)]


def test_parse_errors():
    for bad in ["cpu", "cpu,host 1", "cpu v=abc", "cpu v=1 notatime"]:
        with pytest.raises(LineProtocolError):
            parse_body(bad)
    with pytest.raises(LineProtocolError):
        parse_body("cpu v=1 1", precision="fortnights")


@pytest.fixture(scope="module")
def server():
    coord = Coordinator()
    srv, port = serve(coord)
    yield f"http://127.0.0.1:{port}", coord
    srv.shutdown()


def get_json(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def test_influx_write_then_query_and_search(server):
    base, coord = server
    lines = "\n".join(
        f"mem,host=h{j} used_percent={10.0 * j + i} {T0 + i * 10}"
        for j in range(2)
        for i in range(5)
    )
    req = urllib.request.Request(
        f"{base}/api/v1/influxdb/write?precision=s",
        data=lines.encode(),
        headers={"Content-Type": "text/plain"},
    )
    assert urllib.request.urlopen(req).status == 204

    out = get_json(
        f"{base}/api/v1/query?query=mem_used_percent&time={T0 + 40}"
    )
    vals = {
        r["metric"]["host"]: float(r["value"][1]) for r in out["data"]["result"]
    }
    assert vals == {"h0": 4.0, "h1": 14.0}

    found = get_json(f"{base}/api/v1/search?match[]={{__name__=\"mem_used_percent\"}}")
    assert found["status"] == "success"
    hosts = sorted(e["tags"]["host"] for e in found["data"])
    assert hosts == ["h0", "h1"]
