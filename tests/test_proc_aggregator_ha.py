"""Aggregator leader/follower HA across REAL processes over the networked
control plane (election_mgr.go + follower_flush_mgr.go semantics with
leased leadership instead of etcd sessions):

  two aggregator processes with mirrored input -> only the LEASED leader
  emits; SIGKILL the leader -> the follower's next flush pass takes over
  once the lease ages out, resuming from the shared flush times without
  re-emitting already-flushed windows.
"""

import sys
import time

from m3_tpu.aggregator.server import AggregatorClient
from m3_tpu.metrics.encoding import UnaggregatedMessage
from m3_tpu.metrics.types import MetricType, Untimed
from m3_tpu.rules.rules import encode_tags_id
from m3_tpu.testing.proc_cluster import ProcCluster, _spawn_listening


def test_leader_death_cross_process_takeover(tmp_path):
    cluster = ProcCluster(
        num_nodes=1, num_shards=4, replica_factor=1,
        heartbeat_timeout=2.0, base_dir=str(tmp_path),
    )
    aggs = []
    try:
        node = next(iter(cluster.nodes.values()))
        for iid in ("aggA", "aggB"):
            proc, host, port = _spawn_listening(
                [
                    sys.executable, "-m", "m3_tpu.services.aggregator",
                    "--port", "0", "--policy", "10s:2d",
                    "--flush-interval-secs", "0.4",
                    "--forward", node.endpoint,
                    "--kv-endpoint", cluster.kv_endpoint,
                    "--instance-id", iid,
                    "--election-lease-secs", "2.0",
                ],
                f"aggregator-{iid}",
            )
            aggs.append((proc, AggregatorClient([(host, port)])))

        tags = ((b"__name__", b"ha_metric"),)
        mid = encode_tags_id(tags)
        t0 = time.time_ns() - 60 * 10**9  # a minute ago: windows closed

        def send(t, v):
            # mirrored ingest: every replica sees every metric
            for _, client in aggs:
                client.send(
                    UnaggregatedMessage(
                        Untimed(MetricType.GAUGE, mid, gauge_value=v), t, timed=True
                    )
                )

        for i in range(6):  # one point per 10s window over 1 minute
            send(t0 + i * 10 * 10**9, float(i))

        # the direct-forward path writes UNTAGGED suffixed ids
        # (AggregatedMetric.suffixed_id): read the series directly
        sid = mid + b".last"  # gauge default aggregation

        def fetch_points():
            dps = node.client.read(
                "default", sid, t0 - 10**9, time.time_ns() + 120 * 10**9
            )
            return sorted(dp.value for dp in dps)

        deadline = time.time() + 20
        while time.time() < deadline:
            pts = fetch_points()
            if len(pts) >= 6:
                break
            time.sleep(0.3)
        # exactly once: both replicas aggregated, only the leader emitted
        assert pts == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0], pts

        # SIGKILL the leader (whichever holds the lease — kill aggA, which
        # campaigned first; if B somehow leads, the symmetric logic holds)
        aggs[0][0].kill()
        aggs[0][0].wait(timeout=10)

        # new data must land AFTER the dead leader's shared flush boundary
        # (anything older is late data both replicas correctly drop) — send
        # into the CURRENT window and wait for it to close + takeover
        t1 = time.time_ns()
        aggs[1][1].send(
            UnaggregatedMessage(
                Untimed(MetricType.GAUGE, mid, gauge_value=777.0), t1, timed=True
            )
        )
        deadline = time.time() + 40  # lease (2s) + window close (<=10s) + slack
        while time.time() < deadline:
            pts = fetch_points()
            if len(pts) >= 7:
                break
            time.sleep(0.3)
        # the FOLLOWER emitted the new window exactly once after takeover
        assert pts == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 777.0], pts
    finally:
        for proc, client in aggs:
            client.close()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        cluster.close()
