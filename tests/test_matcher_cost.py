"""Rule matcher service (metrics/matcher/match.go semantics) and per-query
cost limits (query/cost + x/cost semantics)."""

import json
import urllib.request

import pytest

from m3_tpu.block.core import make_tags
from m3_tpu.cluster.kv import KVStore
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.rules.filters import TagsFilter
from m3_tpu.rules.matcher import Matcher, set_namespaces, set_ruleset
from m3_tpu.rules.rules import MappingRule, RuleSet

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS


def _ruleset(policy="10s:2d", pattern="name:cpu*"):
    return RuleSet(
        mapping_rules=[
            MappingRule(
                name="cpu",
                filter=TagsFilter.parse(pattern),
                policies=(StoragePolicy.parse(policy),),
            )
        ]
    )


def test_matcher_watches_namespaces_and_rulesets():
    kv = KVStore()
    set_namespaces(kv, ["agg_ns"])
    set_ruleset(kv, "agg_ns", _ruleset())
    m = Matcher(kv)
    assert m.namespaces() == ["agg_ns"]
    tags = make_tags({"name": "cpu.user", "host": "a"})
    res = m.match("agg_ns", tags, T0)
    assert [str(p) for p in res.policies] == ["10s:2d"]
    # unmatched tags produce an empty result
    other = make_tags({"name": "mem", "host": "a"})
    assert m.match("agg_ns", other, T0).policies == ()


def test_matcher_cache_hit_and_invalidation_on_rule_update():
    kv = KVStore()
    set_namespaces(kv, ["ns"])
    set_ruleset(kv, "ns", _ruleset("10s:2d"))
    m = Matcher(kv)
    tags = make_tags({"name": "cpu.sys"})
    r1 = m.match("ns", tags, T0)
    r2 = m.match("ns", tags, T0)
    assert r1 is r2 and m.cache_hits == 1
    # publishing a new ruleset version invalidates the cache and the new
    # rules take effect without any matcher restart
    set_ruleset(kv, "ns", _ruleset("1m0s:40d"))
    r3 = m.match("ns", tags, T0)
    assert [str(p) for p in r3.policies] == ["1m:40d"]
    assert m.invalidations >= 2


def test_matcher_lru_capacity():
    from m3_tpu.rules.matcher import MatcherOptions

    kv = KVStore()
    set_namespaces(kv, ["ns"])
    set_ruleset(kv, "ns", _ruleset())
    m = Matcher(kv, MatcherOptions(cache_capacity=4))
    for i in range(10):
        m.match("ns", make_tags({"name": f"cpu{i}"}), T0)
    assert len(m._cache) == 4


def test_matcher_namespace_removal():
    kv = KVStore()
    set_namespaces(kv, ["a", "b"])
    set_ruleset(kv, "a", _ruleset())
    m = Matcher(kv)
    assert m.namespaces() == ["a", "b"]
    set_namespaces(kv, ["b"])
    assert m.namespaces() == ["b"]
    # removed namespace matches as empty
    assert m.match("a", make_tags({"name": "cpu"}), T0).policies == ()


def test_matcher_future_cutover_activates_with_time():
    """A rule with a future cutover must start matching once time passes it,
    despite the per-ID cache (active sets key on the cutover epoch)."""
    rule = MappingRule(
        name="cpu",
        filter=TagsFilter.parse("name:cpu*"),
        policies=(StoragePolicy.parse("10s:2d"),),
        cutover_nanos=T0 + 100 * NANOS,
    )
    kv = KVStore()
    set_namespaces(kv, ["ns"])
    set_ruleset(kv, "ns", RuleSet(mapping_rules=[rule]))
    m = Matcher(kv)
    tags = make_tags({"name": "cpu.user"})
    assert m.match("ns", tags, T0).policies == ()
    assert m.match("ns", tags, T0).policies == ()  # cached pre-cutover
    after = m.match("ns", tags, T0 + 200 * NANOS)
    assert [str(p) for p in after.policies] == ["10s:2d"]
    # both epochs stay independently cached
    assert m.match("ns", tags, T0 + 50 * NANOS).policies == ()


# --- cost limits ---


def test_enforcer_limits_and_global_release():
    from m3_tpu.query.cost import Enforcer, GlobalEnforcer, QueryLimitError, QueryLimits

    glob = GlobalEnforcer(QueryLimits(max_series=100))
    e = Enforcer(QueryLimits(max_series=10), glob)
    e.charge(8, 100)
    with pytest.raises(QueryLimitError):
        e.charge(5, 0)
    e.release()
    assert glob.series == 0  # released even after the failure


def test_engine_enforces_series_limit(tmp_path):
    from m3_tpu.query.cost import QueryLimitError, QueryLimits
    from m3_tpu.query.engine import Engine
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=2, commitlog_enabled=False)
    db.create_namespace("default", NamespaceOptions())
    for i in range(8):
        tags = make_tags({"__name__": "req", "host": f"h{i}"})
        db.write_tagged("default", tags, T0 + NANOS, float(i))
    storage = M3Storage(db, "default")
    limited = Engine(storage, limits=QueryLimits(max_series=4))
    with pytest.raises(QueryLimitError):
        limited.query_range("req", T0, T0 + 60 * NANOS, 10 * NANOS)
    # under the limit passes, and limits reset per query
    ok = Engine(storage, limits=QueryLimits(max_series=16))
    for _ in range(3):
        r = ok.query_range("req", T0, T0 + 60 * NANOS, 10 * NANOS)
        assert len(r.metas) == 8


def test_coordinator_returns_422_on_limit(tmp_path):
    import threading

    from m3_tpu.query.cost import QueryLimits
    from m3_tpu.services.coordinator import Coordinator, serve
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=2, commitlog_enabled=False)
    db.create_namespace("default", NamespaceOptions())
    for i in range(6):
        tags = make_tags({"__name__": "req", "host": f"h{i}"})
        db.write_tagged("default", tags, T0 + NANOS, float(i))
    coord = Coordinator(db=db, query_limits=QueryLimits(max_series=2))
    server, port = serve(coord, 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = (
            f"http://127.0.0.1:{port}/api/v1/query_range?query=req"
            f"&start={T0 // NANOS}&end={T0 // NANOS + 60}&step=10"
        )
        try:
            urllib.request.urlopen(url)
            code = 200
        except urllib.error.HTTPError as err:
            code = err.code
            body = json.load(err)
        assert code == 422
        assert "limit" in body["error"]
    finally:
        server.shutdown()
