"""Parity tests: batched JAX decoder vs the CPU reference codec.

The device-decode contract (ops/decode.py) is bit-exact timestamps and values
vs the CPU ReaderIterator, the TPU-side equivalent of the reference's
"bit-exact parity to the CPU iterator" requirement (BASELINE.md).
"""

import math
import random
import struct

import numpy as np
import pytest

from m3_tpu.codec.m3tsz import Encoder, decode, encode_series
from m3_tpu.ops.decode import decode_batched, finalize_decode
from m3_tpu.segment.batched import BatchedSegments
from m3_tpu.utils.xtime import Unit

START = 1_600_000_000 * 10**9


def assert_parity(streams, expected, int_optimized=True, default_unit=Unit.SECOND, maxp=None):
    seg = BatchedSegments.from_streams(streams)
    maxp = maxp or max((len(e) for e in expected), default=1) or 1
    res = decode_batched(
        seg.words,
        seg.num_bits,
        seg.initial_units(default_unit),
        max_points=maxp,
        int_optimized=int_optimized,
    )
    ts_out, vals_out, valid = finalize_decode(res)
    assert not np.asarray(res.err).any()
    for i, exp in enumerate(expected):
        assert valid[i].sum() == len(exp)
        for j, dp in enumerate(exp):
            assert ts_out[i, j] == dp.timestamp
            # bit-exact float64 comparison (NaN-safe)
            assert struct.pack("<d", dp.value) == struct.pack("<d", float(vals_out[i, j]))


def test_mixed_random_batch():
    random.seed(1)
    streams, expected = [], []
    for _ in range(40):
        n = random.randrange(1, 50)
        t = START + random.randrange(0, 100) * 10**9
        ts, vals = [], []
        for _ in range(n):
            t += random.choice([9, 10, 10, 10, 11, 30]) * 10**9
            ts.append(t)
            kind = random.random()
            if kind < 0.5:
                vals.append(float(random.randrange(-(10**6), 10**6)))
            elif kind < 0.8:
                vals.append(round(random.uniform(-1000, 1000), random.randrange(0, 5)))
            else:
                vals.append(random.uniform(-1e9, 1e9))
        data = encode_series(ts, vals, start_nanos=START)
        streams.append(data)
        expected.append(decode(data))
    assert_parity(streams, expected)


def test_time_unit_change():
    enc = Encoder(START)
    enc.encode(START + 10**9, 1.0, unit=Unit.SECOND)
    enc.encode(START + 10**9 + 250_000_000, 2.5, unit=Unit.MILLISECOND)
    enc.encode(START + 10**9 + 500_000_000, 3.0, unit=Unit.MILLISECOND)
    enc.encode(START + 3 * 10**9, 4.0, unit=Unit.SECOND)
    d = enc.stream()
    assert_parity([d], [decode(d)])


def test_unaligned_start_marker():
    start = START + 123
    enc = Encoder(start)
    enc.encode(start + 10**9, 7.0)
    enc.encode(start + 2 * 10**9, 8.0)
    d = enc.stream()
    assert_parity([d], [decode(d)])


def test_nanosecond_64bit_bucket():
    enc = Encoder(START, default_unit=Unit.NANOSECOND)
    ts = [START + 1, START + 2, START + 3 + 10**15, START + 4 + 10**15]
    for t, v in zip(ts, [1.0, 2.0, 3.0, 4.5]):
        enc.encode(t, v, unit=Unit.NANOSECOND)
    d = enc.stream()
    assert_parity([d], [decode(d, default_unit=Unit.NANOSECOND)], default_unit=Unit.NANOSECOND)


@pytest.mark.parametrize("int_optimized", [True, False])
def test_special_floats(int_optimized):
    vals = [0.0, -0.0, float("nan"), float("inf"), float("-inf"), 1e-300, 1e300, math.pi]
    ts = [START + (i + 1) * 10**9 for i in range(len(vals))]
    d = encode_series(ts, vals, start_nanos=START, int_optimized=int_optimized)
    assert_parity(
        [d], [decode(d, int_optimized=int_optimized)], int_optimized=int_optimized
    )


def test_repeats_and_mode_flips():
    random.seed(9)
    vals = (
        [5.0] * 10
        + [5.5, 6.5, math.e, 7.0]
        + [1000000.0 + random.choice([1, -1]) for _ in range(20)]
        + [42.0] * 5
    )
    ts = [START + (i + 1) * 10 * 10**9 for i in range(len(vals))]
    d = encode_series(ts, vals, start_nanos=START)
    assert_parity([d], [decode(d)])


def test_ragged_batch_with_empty_stream():
    s0 = encode_series([START + 10**9], [1.5], start_nanos=START)
    s2 = encode_series(
        [START + i * 10**9 for i in range(1, 100)],
        [float(i) for i in range(99)],
        start_nanos=START,
    )
    assert_parity([s0, b"", s2], [decode(s0), [], decode(s2)], maxp=100)


def test_annotation_stream_flags_err():
    enc = Encoder(START)
    enc.encode(START + 10**9, 1.0, annotation=b"x")
    seg = BatchedSegments.from_streams([enc.stream()])
    res = decode_batched(seg.words, seg.num_bits, seg.initial_units(), max_points=4)
    assert np.asarray(res.err)[0]
    assert not np.asarray(res.valid)[0].any()


def test_values_f32_close():
    ts = [START + (i + 1) * 10**9 for i in range(20)]
    vals = [math.sin(i / 3.0) * 100 for i in range(20)]
    d = encode_series(ts, vals, start_nanos=START)
    seg = BatchedSegments.from_streams([d])
    res = decode_batched(seg.words, seg.num_bits, seg.initial_units(), max_points=20)
    got = np.asarray(res.values_f32)[0]
    np.testing.assert_allclose(got, np.array(vals, np.float32), rtol=1e-5)


def test_segment_roundtrip_container():
    s = encode_series([START + 10**9, START + 2 * 10**9], [1.0, 2.0], start_nanos=START)
    seg = BatchedSegments.from_streams([s, b"ab"])
    assert seg.stream(0) == s
    assert seg.stream(1) == b"ab"
