"""Streaming pipeline tests: batched upload totals match the chunked oracle
and the fileset-fed path decodes straight off side tables (SURVEY §7.5
fetch→upload→kernel)."""

import functools

import jax
import numpy as np

from m3_tpu.codec.m3tsz import encode_series
from m3_tpu.ops.chunked import build_chunked, tile_chunked
from m3_tpu.parallel.scan import chunked_device_args, chunked_scan_aggregate
from m3_tpu.parallel.stream import (
    fileset_packed_batches,
    packed_batches,
    stream_aggregate,
)
from m3_tpu.utils.synthetic import synthetic_streams

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS


def _oracle_totals(batches):
    total_sum, total_count = 0.0, 0
    for batch in batches:
        fn = jax.jit(
            functools.partial(
                chunked_scan_aggregate,
                s=batch.num_series,
                c=batch.num_chunks,
                k=batch.k,
            )
        )
        out = fn(chunked_device_args(batch, device_put=False))
        total_sum += float(out.total_sum)
        total_count += int(out.total_count)
    return total_sum, total_count


def test_stream_totals_match_oracle():
    base = build_chunked(synthetic_streams(16, 60, seed=5), k=8)
    batches = [tile_chunked(base, 64) for _ in range(3)]
    want_sum, want_count = _oracle_totals(batches)
    totals = stream_aggregate(packed_batches(batches), prefetch=2)
    assert totals.batches == 3
    assert totals.total_count == want_count
    np.testing.assert_allclose(totals.total_sum, want_sum, rtol=1e-6)


def test_stream_prefetch_zero_still_correct():
    base = build_chunked(synthetic_streams(8, 30, seed=6), k=8)
    batches = [tile_chunked(base, 16) for _ in range(2)]
    want_sum, want_count = _oracle_totals(batches)
    totals = stream_aggregate(packed_batches(batches), prefetch=0)
    assert totals.total_count == want_count
    np.testing.assert_allclose(totals.total_sum, want_sum, rtol=1e-6)


def test_fileset_to_stream_path(tmp_path):
    """Disk → side tables → packed batches → kernel without a host prescan."""
    from m3_tpu.storage.fs import CHUNK_K, FilesetID, FilesetReader, write_fileset

    series = {
        f"s{i}".encode(): encode_series(
            [T0 + j * NANOS for j in range(40)],
            [float(i + j) for j in range(40)],
        )
        for i in range(20)
    }
    fid = FilesetID("ns", 0, T0, 0)
    write_fileset(str(tmp_path), fid, series, 2 * 3600 * NANOS, CHUNK_K)
    reader = FilesetReader(str(tmp_path), fid)

    totals = stream_aggregate(
        fileset_packed_batches([reader], batch_series=7), prefetch=1
    )
    assert totals.total_count == 20 * 40
    want = sum(float(i + j) for i in range(20) for j in range(40))
    np.testing.assert_allclose(totals.total_sum, want, rtol=1e-6)
