"""Cost-aware admission scheduler (query/scheduler.py): fast path,
priority ordering, queue-full eviction, deadline sheds, the DAGOR
overload gate, and the engine integration (QueryStats stamping + cost
memo feedback + typed QueryShedError surfacing)."""

import threading
import time

import pytest

from m3_tpu.query.scheduler import (
    SHED_DEADLINE,
    SHED_OVERLOAD,
    SHED_QUEUE_FULL,
    CostMemo,
    QueryScheduler,
    QueryShedError,
    tenant_pressure,
)
from m3_tpu.query.tenants import LEDGER, tenant_context
from m3_tpu.utils.instrument import DEFAULT as METRICS

NANOS = 1_000_000_000
T0 = 1_700_000_000 * NANOS


def _counter_total(name: str, **label_filter) -> float:
    fam = METRICS.collect().get(f"m3tpu_{name}")
    if fam is None:
        return 0.0
    total = 0.0
    for child in fam["children"]:
        if all(child["labels"].get(k) == v for k, v in label_filter.items()):
            total += child["value"]
    return total


def _join(threads, timeout=5.0):
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "admission thread wedged"


# --- fast path + scoring ---


def test_fast_path_admit_release():
    s = QueryScheduler(max_inflight=2, max_queue=4)
    s.admit("up", 10)
    s.admit("up", 10)
    snap = s.snapshot()
    assert snap["inflight"] == 2 and snap["queued"] == []
    s.release()
    s.release()
    assert s.snapshot()["inflight"] == 0


def test_score_terms():
    s = QueryScheduler()
    # cost term is bounded in [0, 1); aging is linearly negative
    assert 0.0 <= s.score("never_seen_tenant_xyz", 1.0) < 1.0
    assert s.score("never_seen_tenant_xyz", 1e12) < 1.0
    assert s.score("never_seen_tenant_xyz", 1.0, age=10.0) < 0.0
    # a tenant that keeps tripping limits dominates every other term
    LEDGER.charge("sched_score_bad", limit_rejections=50)
    assert tenant_pressure("sched_score_bad") > 0.9
    assert s.score("sched_score_bad", 1.0) > s.score(
        "never_seen_tenant_xyz", 1e12
    )


def test_cost_memo_lru_and_feedback():
    m = CostMemo(capacity=2)
    assert m.series_estimate("q1") == 1  # optimistic default
    m.observe("q1", 40)
    m.observe("q2", 7)
    assert m.estimate("q1", 100) == 100.0 * 40
    m.observe("q3", 3)  # q2 is LRU (q1 was touched by estimate)
    assert m.series_estimate("q2") == 1
    assert m.series_estimate("q1") == 40 and m.series_estimate("q3") == 3
    m.observe("q1", 0)  # non-positive observations are ignored
    assert m.series_estimate("q1") == 40


# --- queueing + priority ---


def test_release_admits_lowest_score_first():
    s = QueryScheduler(max_inflight=1, max_queue=8, max_queue_wait=5.0)
    s.admit("up", 1)  # occupy the only slot
    LEDGER.charge("sched_prio_bad", limit_rejections=30)
    LEDGER.charge("sched_prio_good", queries=30)
    order = []

    def enter(tenant):
        with tenant_context(tenant):
            s.admit("up", 1)
        order.append(tenant)

    threads = [
        threading.Thread(target=enter, args=(t,), daemon=True)
        for t in ("sched_prio_bad", "sched_prio_good")
    ]
    threads[0].start()
    # make sure the bad tenant is queued FIRST so ordering is by score,
    # not arrival
    deadline = time.monotonic() + 5.0
    while not s.snapshot()["queued"] and time.monotonic() < deadline:
        time.sleep(0.005)
    threads[1].start()
    while len(s.snapshot()["queued"]) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    s.release()  # frees one slot -> good admitted despite arriving later
    while not order and time.monotonic() < deadline:
        time.sleep(0.005)
    s.release()
    _join(threads)
    assert order == ["sched_prio_good", "sched_prio_bad"]
    s.release()


def test_queue_full_evicts_worst_scoring_entry():
    # watermark > 1 disables the overload fast gate so this test hits the
    # queue-full eviction path specifically
    s = QueryScheduler(
        max_inflight=1, max_queue=1, max_queue_wait=5.0,
        overload_watermark=2.0,
    )
    s.admit("up", 1)
    LEDGER.charge("sched_evict_bad", limit_rejections=30)
    admitted = []

    def innocent():
        with tenant_context("sched_evict_good"):
            s.admit("up", 1)
        admitted.append(True)

    t = threading.Thread(target=innocent, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while not s.snapshot()["queued"] and time.monotonic() < deadline:
        time.sleep(0.005)
    before = _counter_total(
        "query_shed_total", tenant="sched_evict_bad", reason=SHED_QUEUE_FULL
    )
    with tenant_context("sched_evict_bad"):
        with pytest.raises(QueryShedError) as ei:
            s.admit("up", 1)  # queue is full; worst score (us) is evicted
    assert ei.value.reason == SHED_QUEUE_FULL
    assert ei.value.tenant == "sched_evict_bad"
    assert (
        _counter_total(
            "query_shed_total",
            tenant="sched_evict_bad",
            reason=SHED_QUEUE_FULL,
        )
        > before
    )
    # the innocent entry survived the eviction and gets the next slot
    s.release()
    _join([t])
    assert admitted
    s.release()


def test_deadline_shed_stamps_record():
    from m3_tpu.query.stats import QueryStats

    s = QueryScheduler(max_inflight=1, max_queue=4, max_queue_wait=0.05)
    s.admit("up", 1)
    rec = QueryStats(query="up")
    t0 = time.monotonic()
    with tenant_context("sched_deadline_t"):
        with pytest.raises(QueryShedError) as ei:
            s.admit("up", 1, record=rec)
    assert ei.value.reason == SHED_DEADLINE
    assert 0.03 < time.monotonic() - t0 < 2.0
    assert rec.queue_state == "shed"
    assert s.snapshot()["queued"] == []  # shed entries leave the queue
    s.release()


def test_overload_gate_fast_fails_pressured_tenant_only():
    s = QueryScheduler(
        max_inflight=1, max_queue=4, overload_watermark=0.5,
        max_queue_wait=5.0,
    )
    s.admit("up", 1)
    LEDGER.charge("sched_gate_bad", limit_rejections=50)
    threads = []
    for i in range(2):  # fill the queue past the 0.5 * 4 watermark
        t = threading.Thread(
            target=lambda: s.admit("up", 1), daemon=True
        )
        t.start()
        threads.append(t)
    deadline = time.monotonic() + 5.0
    while len(s.snapshot()["queued"]) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    t0 = time.monotonic()
    with tenant_context("sched_gate_bad"):
        with pytest.raises(QueryShedError) as ei:
            s.admit("up", 1)
    assert ei.value.reason == SHED_OVERLOAD
    assert time.monotonic() - t0 < 1.0  # fast-fail, no queue wait
    # an innocent tenant at the same depth queues instead of shedding
    ok = []

    def innocent():
        with tenant_context("sched_gate_good"):
            s.admit("up", 1)
        ok.append(True)

    t = threading.Thread(target=innocent, daemon=True)
    t.start()
    while len(s.snapshot()["queued"]) < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(s.snapshot()["queued"]) == 3  # queued, not shed
    for _ in range(3):
        s.release()
    _join(threads + [t])
    assert ok
    for _ in range(3):
        s.release()


def test_ledger_charges_sheds():
    s = QueryScheduler(max_inflight=1, max_queue=4, max_queue_wait=0.02)
    s.admit("up", 1)
    base = (LEDGER.window_totals("sched_ledger_t") or {}).get("sheds", 0.0)
    with tenant_context("sched_ledger_t"):
        with pytest.raises(QueryShedError):
            s.admit("up", 1)
    assert LEDGER.window_totals("sched_ledger_t")["sheds"] == base + 1
    s.release()


# --- engine integration ---


def _mini_engine(tmp_path, scheduler):
    from m3_tpu.block.core import make_tags
    from m3_tpu.query.engine import Engine
    from m3_tpu.query.m3_storage import M3Storage
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=2, commitlog_enabled=False)
    db.create_namespace("default", NamespaceOptions())
    for i in range(4):
        tags = make_tags({"__name__": "sched_gauge", "i": str(i)})
        for j in range(10):
            db.write_tagged(
                "default", tags, T0 + j * 10 * NANOS, float(i + j)
            )
    return db, Engine(M3Storage(db, "default"), scheduler=scheduler)


def test_engine_admits_observes_and_stamps(tmp_path):
    from m3_tpu.query import stats

    s = QueryScheduler(max_inflight=4)
    db, engine = _mini_engine(tmp_path, s)
    try:
        res = engine.query_range("sched_gauge", T0, T0 + 90 * NANOS, 10 * NANOS)
        assert len(res.metas) == 4
        rec = next(
            r
            for r in reversed(stats.RING.dump())
            if r["query"] == "sched_gauge"
        )
        assert rec["queueState"] == "running"
        assert isinstance(rec["priority"], float)
        # the observed series count feeds the cost memo: the next
        # admission prices this query at its real cardinality
        assert s.costs.series_estimate("sched_gauge") == 4
        assert s.snapshot()["inflight"] == 0  # released in the finally
    finally:
        db.close()


def test_engine_shed_surfaces_typed_error(tmp_path):
    s = QueryScheduler(max_inflight=1, max_queue=4, max_queue_wait=0.05)
    db, engine = _mini_engine(tmp_path, s)
    try:
        s.admit("elsewhere", 1)  # saturate the only slot
        with tenant_context("sched_engine_t"):
            with pytest.raises(QueryShedError) as ei:
                engine.query_range(
                    "sched_gauge", T0, T0 + 90 * NANOS, 10 * NANOS
                )
        assert ei.value.reason == SHED_DEADLINE
        assert ei.value.tenant == "sched_engine_t"
        # the shed query never took (or leaked) a slot
        assert s.snapshot()["inflight"] == 1
        s.release()
        # the engine still works after a shed
        res = engine.query_range("sched_gauge", T0, T0 + 90 * NANOS, 10 * NANOS)
        assert len(res.metas) == 4
    finally:
        db.close()
