"""Network data plane tests: wire codec, socket node service, and a real
multi-process cluster (separate python processes on localhost sockets) for
quorum/node-down/restart behavior (rpc.thrift:44-87 surface,
tchannelthrift/node/service.go:449,626, dtest-style process cluster)."""

import math

import pytest

from m3_tpu.codec.m3tsz import Datapoint
from m3_tpu.index.query import conj, disj, neg, regexp, term
from m3_tpu.net import wire
from m3_tpu.utils.xtime import Unit

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS
HOUR = 3600 * NANOS


def test_wire_value_roundtrip():
    vals = [
        None,
        True,
        False,
        0,
        -(2**62),
        2**62,
        1.5,
        math.inf,
        b"",
        b"\x00\xffbytes",
        "unicode ☃",
        [1, [2, b"x"], {"a": None}],
        {"op": "write", "t": 123, "nested": {"k": [True, 2.5]}},
    ]
    for v in vals:
        assert wire.loads(wire.dumps(v)) == v
    # NaN needs special compare
    out = wire.loads(wire.dumps(float("nan")))
    assert math.isnan(out)


def test_wire_query_roundtrip():
    q = conj(
        term(b"name", b"cpu"),
        disj(regexp(b"host", b"web-.*"), term(b"host", b"db0")),
        neg(term(b"dc", b"east")),
    )
    assert wire.query_from_wire(wire.query_to_wire(q)) == q


def test_wire_datapoints_roundtrip():
    dps = [
        Datapoint(T0, 1.5),
        Datapoint(T0 + NANOS, -2.0, Unit.MILLISECOND),
        Datapoint(T0 + 2 * NANOS, 3.0, Unit.SECOND, b"ann"),
    ]
    assert wire.dps_from_wire(wire.dps_to_wire(dps)) == dps


@pytest.fixture
def served_db(tmp_path):
    from m3_tpu.net.client import RemoteNode
    from m3_tpu.net.server import NodeServer, NodeService
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=4)
    db.create_namespace("default", NamespaceOptions(block_size_nanos=HOUR))
    db.bootstrap()
    server = NodeServer(NodeService(db, node_id="n0", assigned_shards={0, 1, 2, 3}))
    server.start()
    client = RemoteNode("127.0.0.1", server.port, node_id="n0")
    yield db, client
    client.close()
    server.stop()
    db.close()


def test_node_service_roundtrip(served_db):
    db, client = served_db
    assert client.health()["bootstrapped"] is True
    assert client.owned_shards() == {0, 1, 2, 3}

    client.write("default", b"plain", T0 + NANOS, 42.0)
    dps = client.read("default", b"plain", T0, T0 + HOUR)
    assert [(dp.timestamp, dp.value) for dp in dps] == [(T0 + NANOS, 42.0)]

    tags = ((b"host", b"a"), (b"name", b"cpu"))
    sid = client.write_tagged("default", tags, T0 + 2 * NANOS, 7.0)
    assert isinstance(sid, bytes)
    res = client.fetch_tagged("default", term(b"name", b"cpu"), T0, T0 + HOUR)
    assert len(res) == 1
    got_sid, got_tags, got_dps = res[0]
    assert got_sid == sid and got_tags == tags
    assert [dp.value for dp in got_dps] == [7.0]

    ids = client.query_ids("default", term(b"host", b"a"), T0, T0 + HOUR)
    assert [bytes(d) for d, _ in ids["docs"]] == [sid] and ids["exhaustive"]

    streamed = client.stream_shard("default", db.namespaces["default"].shard_for(sid).id)
    assert any(s[0] == sid for s in streamed)


def test_node_service_remote_errors_are_per_request(served_db):
    from m3_tpu.net.client import RemoteError

    db, client = served_db
    with pytest.raises(RemoteError):
        client.write("nope", b"x", T0, 1.0)  # unknown namespace
    # the connection survives the failed request
    client.write("default", b"x", T0 + NANOS, 1.0)
    assert len(client.read("default", b"x", T0, T0 + HOUR)) == 1


def test_session_fetch_gates_on_touched_shard_only():
    """Weak #8 fix: a fully-down shard fails only reads that touch it."""
    from m3_tpu.cluster.topology import ConsistencyLevel
    from m3_tpu.client.session import ConsistencyError
    from m3_tpu.testing.cluster import LocalCluster
    from m3_tpu.utils.hash import shard_for

    cluster = LocalCluster(num_nodes=3, num_shards=6, replica_factor=1)
    session = cluster.session(
        write_cl=ConsistencyLevel.ONE, read_cl=ConsistencyLevel.ONE
    )
    # find two ids on shards owned by different nodes
    placement = cluster.placement_svc.get()

    def owner(sid):
        shard = shard_for(sid, 6)
        return placement.instances_for_shard(shard)[0].id

    ids = [f"s{i}".encode() for i in range(64)]
    a = next(s for s in ids if owner(s) == "node0")
    b = next(s for s in ids if owner(s) == "node1")
    session.write(a, T0 + NANOS, 1.0)
    session.write(b, T0 + NANOS, 2.0)

    cluster.nodes["node1"].is_up = False
    # shard of `a` is healthy: fetch succeeds
    assert [dp.value for dp in session.fetch(a, T0, T0 + HOUR)] == [1.0]
    # shard of `b` has zero live replicas: only ITS fetch fails
    with pytest.raises(ConsistencyError):
        session.fetch(b, T0, T0 + HOUR)


def test_multiprocess_cluster_quorum_and_restart(tmp_path):
    from m3_tpu.client.session import ConsistencyError
    from m3_tpu.testing.proc_cluster import ProcCluster

    cluster = ProcCluster(
        num_nodes=3, num_shards=4, replica_factor=3, base_dir=str(tmp_path)
    )
    try:
        session = cluster.session()
        tags = ((b"host", b"w1"), (b"name", b"reqs"))
        sid = session.write_tagged(tags, T0 + NANOS, 1.0)
        session.write(sid, T0 + 2 * NANOS, 2.0)

        res = session.fetch_tagged(term(b"name", b"reqs"), T0, T0 + HOUR)
        assert len(res) == 1
        assert [dp.value for dp in res[0][2]] == [1.0, 2.0]

        # kill one process: majority quorum still holds over sockets
        cluster.nodes["node2"].kill()
        session.write(sid, T0 + 3 * NANOS, 3.0)
        res = session.fetch_tagged(term(b"name", b"reqs"), T0, T0 + HOUR)
        assert [dp.value for dp in res[0][2]] == [1.0, 2.0, 3.0]

        # kill a second: majority (2/3) is unreachable
        cluster.nodes["node1"].kill()
        with pytest.raises(ConsistencyError):
            session.write(sid, T0 + 4 * NANOS, 4.0)
        with pytest.raises(ConsistencyError):
            session.fetch_tagged(term(b"name", b"reqs"), T0, T0 + HOUR)

        # restart node1: it bootstraps from its WAL and serves reads again.
        # the failed write above still landed on node0 (partial applies are
        # not undone, as in the reference), so the merged read includes 4.0
        cluster.restart("node1")
        session = cluster.session()
        res = session.fetch_tagged(term(b"name", b"reqs"), T0, T0 + HOUR)
        assert [dp.value for dp in res[0][2]] == [1.0, 2.0, 3.0, 4.0]
    finally:
        cluster.close()
