"""Bitstream + helper unit tests (OStream/IStream/varint/bits).

Mirrors the reference's ostream/istream unit coverage
(/root/reference/src/dbnode/encoding/{ostream,istream}_test.go) behaviorally.
"""

import pytest

from m3_tpu.codec.istream import IStream
from m3_tpu.codec.ostream import OStream
from m3_tpu.utils import varint
from m3_tpu.utils.bits import (
    bits_to_float,
    float_to_bits,
    leading_and_trailing_zeros,
    num_sig,
    sign_extend,
)


def test_write_bits_msb_first():
    os = OStream()
    os.write_bits(0b101, 3)
    os.write_bits(0b11111, 5)
    raw, pos = os.raw_bytes()
    assert raw == bytes([0b10111111])
    assert pos == 8


def test_write_byte_unaligned():
    os = OStream()
    os.write_bit(1)
    os.write_byte(0xFF)
    raw, pos = os.raw_bytes()
    assert raw == bytes([0b11111111, 0b10000000])
    assert pos == 1


def test_write_bits_64():
    os = OStream()
    v = 0x0123456789ABCDEF
    os.write_bits(v, 64)
    raw, pos = os.raw_bytes()
    assert raw == v.to_bytes(8, "big")
    assert pos == 8


def test_read_back_roundtrip():
    os = OStream()
    pieces = [(0b1, 1), (0xAB, 8), (0x3FF, 10), (0, 3), (0x0123456789ABCDEF, 64), (0b101, 3)]
    for v, n in pieces:
        os.write_bits(v, n)
    raw, _ = os.raw_bytes()
    ist = IStream(raw)
    for v, n in pieces:
        assert ist.read_bits(n) == v


def test_peek_does_not_consume():
    os = OStream()
    os.write_bits(0b110101, 6)
    os.write_bits(0xDEAD, 16)
    raw, _ = os.raw_bytes()
    ist = IStream(raw)
    assert ist.read_bits(2) == 0b11
    assert ist.peek_bits(4) == 0b0101
    assert ist.peek_bits(4) == 0b0101
    assert ist.read_bits(4) == 0b0101
    assert ist.read_bits(16) == 0xDEAD


def test_read_past_end_raises():
    ist = IStream(b"\xff")
    ist.read_bits(8)
    with pytest.raises(EOFError):
        ist.read_bits(1)
    with pytest.raises(EOFError):
        IStream(b"\x00").peek_bits(9)


@pytest.mark.parametrize("x", [0, 1, -1, 63, -64, 64, 1 << 40, -(1 << 40), 2**62, -(2**62)])
def test_varint_roundtrip(x):
    data = varint.put_varint(x)
    it = iter(data)
    assert varint.read_varint(lambda: next(it)) == x


def test_varint_go_vectors():
    # Go binary.PutVarint: zigzag then LEB128. PutVarint(0)=[0x00], (1)=[0x02],
    # (-1)=[0x01], (4)=[0x08], (-5)=[0x09].
    assert varint.put_varint(0) == b"\x00"
    assert varint.put_varint(1) == b"\x02"
    assert varint.put_varint(-1) == b"\x01"
    assert varint.put_varint(4) == b"\x08"
    assert varint.put_varint(-5) == b"\x09"


def test_num_sig():
    assert num_sig(0) == 0
    assert num_sig(1) == 1
    assert num_sig(0xFF) == 8
    assert num_sig(1 << 63) == 64


def test_leading_trailing():
    assert leading_and_trailing_zeros(0) == (64, 0)
    assert leading_and_trailing_zeros(1) == (63, 0)
    assert leading_and_trailing_zeros(1 << 63) == (0, 63)
    assert leading_and_trailing_zeros(0b1100) == (60, 2)


def test_sign_extend():
    assert sign_extend(0b0111, 4) == 7
    assert sign_extend(0b1000, 4) == -8
    assert sign_extend(0b1111, 4) == -1
    assert sign_extend((1 << 64) - 1, 64) == -1


def test_float_bits_roundtrip():
    for v in [0.0, -0.0, 1.5, -3.25, 1e300, float("inf")]:
        assert bits_to_float(float_to_bits(v)) == v
    assert float_to_bits(1.0) == 0x3FF0000000000000
