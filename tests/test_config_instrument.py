"""Config system (x/config), instrumentation (x/instrument), and runtime
reconfiguration (dbnode/runtime + kvconfig) tests."""

import dataclasses
import json
import threading
import urllib.request

import pytest

from m3_tpu.cluster.kv import KVStore
from m3_tpu.utils.config import ConfigError, loads_config
from m3_tpu.utils.instrument import Registry

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS


# --- config ---


@dataclasses.dataclass
class _Inner:
    port: int = 7201
    hosts: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Cfg:
    name: str
    inner: _Inner = dataclasses.field(default_factory=_Inner)
    ratio: float = 0.5
    debug: bool = False

    def validate(self):
        if not (0 <= self.ratio <= 1):
            raise ConfigError("ratio must be within [0, 1]")


def test_config_nested_and_defaults():
    cfg = loads_config(_Cfg, "name: svc\ninner:\n  port: 9000\n  hosts: [a, b]\n")
    assert cfg.name == "svc" and cfg.inner.port == 9000
    assert cfg.inner.hosts == ["a", "b"] and cfg.ratio == 0.5


def test_config_env_interpolation(monkeypatch):
    monkeypatch.setenv("M3_PORT", "1234")
    cfg = loads_config(_Cfg, "name: svc\ninner: {port: '${M3_PORT}'}\n")
    assert cfg.inner.port == 1234
    cfg = loads_config(_Cfg, "name: '${MISSING_VAR:fallback}'\n")
    assert cfg.name == "fallback"
    with pytest.raises(ConfigError):
        loads_config(_Cfg, "name: '${MISSING_VAR_NO_DEFAULT}'\n")


def test_config_unknown_key_and_required_and_validate():
    with pytest.raises(ConfigError, match="unknown keys"):
        loads_config(_Cfg, "name: x\nbogus: 1\n")
    with pytest.raises(ConfigError, match="required"):
        loads_config(_Cfg, "ratio: 0.2\n")
    with pytest.raises(ConfigError, match="ratio"):
        loads_config(_Cfg, "name: x\nratio: 2.0\n")
    with pytest.raises(ConfigError, match="expected bool|expected a bool"):
        loads_config(_Cfg, "name: x\ndebug: [1]\n")
    assert loads_config(_Cfg, "name: x\ndebug: 'true'\n").debug is True


# --- instrument ---


def test_registry_counters_gauges_histograms():
    reg = Registry(prefix="t_")
    reg.counter("reqs_total", "requests", {"op": "write"}).inc(3)
    reg.counter("reqs_total", labels={"op": "read"}).inc()
    reg.gauge("temp").set(21.5)
    h = reg.histogram("latency_secs", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    assert 't_reqs_total{op="write"} 3.0' in text
    assert 't_reqs_total{op="read"} 1.0' in text
    assert "t_temp 21.5" in text
    assert 't_latency_secs_bucket{le="0.1"} 1' in text
    assert 't_latency_secs_bucket{le="1.0"} 2' in text
    assert 't_latency_secs_bucket{le="+Inf"} 3' in text
    assert "t_latency_secs_count 3" in text


def test_metrics_flow_to_coordinator_endpoint(tmp_path):
    from m3_tpu.block.core import make_tags
    from m3_tpu.services.coordinator import Coordinator, serve
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=2, commitlog_enabled=False)
    db.create_namespace("default", NamespaceOptions())
    db.write_tagged("default", make_tags({"__name__": "x"}), T0, 1.0)
    coord = Coordinator(db=db)
    server, port = serve(coord, 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics"
        ).read().decode()
        assert "m3tpu_db_writes_total" in text
    finally:
        server.shutdown()


def test_node_rpc_metrics_op(tmp_path):
    from m3_tpu.net.client import RemoteNode
    from m3_tpu.net.server import NodeServer, NodeService
    from m3_tpu.storage.database import Database, NamespaceOptions

    db = Database(str(tmp_path), num_shards=1)
    db.create_namespace("default", NamespaceOptions())
    db.bootstrap()
    server = NodeServer(NodeService(db, node_id="n"))
    server.start()
    client = RemoteNode("127.0.0.1", server.port)
    try:
        client.write("default", b"s", T0, 1.0)
        text = client._call("metrics")
        assert "m3tpu_rpc_requests_total" in text
        assert 'op="write"' in text
    finally:
        client.close()
        server.stop()
        db.close()


# --- runtime reconfig ---


def test_runtime_options_manager_watch_and_apply(tmp_path):
    from m3_tpu.storage.database import Database, NamespaceOptions, NewSeriesLimitError
    from m3_tpu.storage.mediator import Mediator, MediatorOptions
    from m3_tpu.storage.runtime import (
        RuntimeOptions,
        RuntimeOptionsManager,
        set_runtime_options,
    )

    kv = KVStore()
    mgr = RuntimeOptionsManager(kv, RuntimeOptions())
    assert mgr.get().flush_interval_secs == 60.0

    db = Database(str(tmp_path), num_shards=1)
    db.create_namespace("ns", NamespaceOptions())
    db.bootstrap()
    med = Mediator(db, MediatorOptions(), runtime=mgr)
    mgr.watch(db.apply_runtime_options)

    # flip cadence + new-series limit through KV: applied live, no restart
    set_runtime_options(
        kv, flush_interval_secs=5.0, write_new_series_limit_per_sec=2
    )
    assert med.opts.flush_interval_nanos == 5 * NANOS
    db.write("ns", b"a", T0, 1.0)
    db.write("ns", b"b", T0, 1.0)
    with pytest.raises(NewSeriesLimitError):
        db.write("ns", b"c", T0, 1.0)
    # existing series still writable under the limit
    db.write("ns", b"a", T0 + NANOS, 2.0)
    # lift the limit
    set_runtime_options(kv, write_new_series_limit_per_sec=0)
    db.write("ns", b"c", T0, 1.0)
    db.close()


def test_coordinator_binary_with_yaml_config(tmp_path):
    import os
    import subprocess
    import sys

    cfg = tmp_path / "coordinator.yml"
    cfg.write_text(
        "port: 0\nnamespace: default\n"
        f"base_dir: {tmp_path / 'data'}\n"
        "limits:\n  max_series: 100\n"
    )
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "m3_tpu.services.coordinator", "--config", str(cfg)],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=repo,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("LISTENING"), line
        _, host, port = line.split()
        health = json.load(
            urllib.request.urlopen(f"http://{host}:{port}/health")
        )
        assert health["ok"] is True
        text = urllib.request.urlopen(
            f"http://{host}:{port}/metrics"
        ).read().decode()
        assert "m3tpu_" in text
    finally:
        proc.kill()
        proc.wait(timeout=10)
