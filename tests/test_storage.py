"""Storage node tests: buffer semantics, fileset checkpoint commit, WAL crash
replay, cold-flush volumes, bootstrap, device decode from filesets.
(Reference: src/dbnode/storage/, src/dbnode/persist/fs/.)"""

import os
import struct

import numpy as np
import pytest

from m3_tpu.codec.m3tsz import decode
from m3_tpu.ops.chunked import decode_chunked
from m3_tpu.ops.decode import finalize_decode
from m3_tpu.storage.commitlog import CommitLog, CommitLogEntry
from m3_tpu.storage.database import Database, NamespaceOptions
from m3_tpu.storage.fs import FilesetID, FilesetReader, fileset_complete, list_filesets, write_fileset
from m3_tpu.storage.series import SeriesBuffer
from m3_tpu.utils.xtime import Unit

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS
HOUR = 3600 * NANOS


def test_series_buffer_in_order_and_cold():
    buf = SeriesBuffer(b"s", 2 * HOUR)
    buf.write(T0 + 10 * NANOS, 1.0)
    buf.write(T0 + 20 * NANOS, 2.0)
    buf.write(T0 + 5 * NANOS, 0.5)  # out of order -> pending
    buf.write(T0 + 20 * NANOS, 3.0)  # duplicate ts -> last wins
    got = buf.read(T0, T0 + HOUR)
    assert [(dp.timestamp, dp.value) for dp in got] == [
        (T0 + 5 * NANOS, 0.5),
        (T0 + 10 * NANOS, 1.0),
        (T0 + 20 * NANOS, 3.0),
    ]


def test_fileset_checkpoint_commit(tmp_path):
    base = str(tmp_path)
    fid = FilesetID("ns", 0, T0)
    from m3_tpu.codec.m3tsz import encode_series

    series = {
        b"a": encode_series([T0 + i * NANOS for i in range(10)], [float(i) for i in range(10)]),
        b"b": encode_series([T0 + i * NANOS for i in range(5)], [2.0 * i for i in range(5)]),
    }
    write_fileset(base, fid, series, 2 * HOUR)
    assert fileset_complete(base, fid)
    r = FilesetReader(base, fid)
    assert sorted(r.series_ids) == [b"a", b"b"]
    assert decode(r.stream(b"a"))[3].value == 3.0
    assert r.stream(b"missing") is None

    # corrupt the digest -> checkpoint no longer validates
    digest_path = os.path.join(base, "data", "ns", "0", f"fileset-{T0}-0-digest.db")
    with open(digest_path, "ab") as f:
        f.write(b"x")
    assert not fileset_complete(base, fid)
    assert list_filesets(base, "ns", 0) == []


def test_fileset_missing_checkpoint_invisible(tmp_path):
    base = str(tmp_path)
    fid = FilesetID("ns", 1, T0)
    from m3_tpu.codec.m3tsz import encode_series

    write_fileset(base, fid, {b"a": encode_series([T0], [1.0])}, 2 * HOUR)
    os.remove(os.path.join(base, "data", "ns", "1", f"fileset-{T0}-0-checkpoint.db"))
    assert list_filesets(base, "ns", 1) == []


def test_fileset_device_decode(tmp_path):
    """Side tables in the fileset let the device decode without prescan."""
    base = str(tmp_path)
    fid = FilesetID("ns", 0, T0)
    from m3_tpu.codec.m3tsz import encode_series

    rng = np.random.default_rng(4)
    series = {}
    for i in range(7):
        n = int(rng.integers(3, 90))
        ts = [T0 + int(t) * NANOS for t in np.cumsum(rng.integers(1, 9, n))]
        series[f"s{i}".encode()] = encode_series(ts, np.round(rng.normal(0, 9, n), 2).tolist())
    write_fileset(base, fid, series, 2 * HOUR)

    r = FilesetReader(base, fid)
    sids = r.series_ids
    batch = r.chunked_batch(sids)
    ts, vals, valid = finalize_decode(decode_chunked(batch))
    for i, sid in enumerate(sids):
        want = decode(series[sid])
        got_t = ts[i][valid[i]]
        got_v = vals[i][valid[i]]
        assert len(got_t) == len(want)
        assert all(got_t[j] == want[j].timestamp for j in range(len(want)))
        assert all(got_v[j] == want[j].value for j in range(len(want)))


def test_commitlog_replay_and_torn_tail(tmp_path):
    wal_dir = str(tmp_path / "wal")
    cl = CommitLog(wal_dir, flush_every=1)
    entries = [
        CommitLogEntry(b"a", T0 + i * NANOS, float(i), Unit.SECOND, b"" if i else b"ann")
        for i in range(5)
    ]
    for e in entries:
        cl.write(e)
    cl.close()

    got = CommitLog.replay(wal_dir)
    assert len(got) == 5
    assert got[0].annotation == b"ann"
    assert got[4].value == 4.0

    # torn tail: truncate mid-record in the active segment
    seg = os.path.join(wal_dir, f"commitlog-{cl.active_seq}.wal")
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 7)
    got = CommitLog.replay(wal_dir)
    assert len(got) == 4  # last record dropped cleanly


def test_commitlog_corrupt_series_id_detected(tmp_path):
    """The record CRC covers series_id + payload: a flipped id byte stops
    replay instead of attributing datapoints to the wrong series."""
    wal_dir = str(tmp_path / "wal")
    cl = CommitLog(wal_dir, flush_every=1)
    cl.write(CommitLogEntry(b"victim-series", T0, 1.0))
    cl.close()
    seg = os.path.join(wal_dir, f"commitlog-{cl.active_seq}.wal")
    with open(seg, "r+b") as f:
        f.seek(4 + 10 + 2)  # into the series id bytes
        f.write(b"X")
    assert CommitLog.replay(wal_dir) == []


def test_commitlog_rotation_and_cleanup(tmp_path):
    wal_dir = str(tmp_path / "wal")
    cl = CommitLog(wal_dir, flush_every=1)
    cl.write(CommitLogEntry(b"a", T0, 1.0))
    cl.rotate()
    cl.write(CommitLogEntry(b"a", T0 + 10 * NANOS, 2.0))
    cl.rotate()
    cl.write(CommitLogEntry(b"a", T0 + 20 * NANOS, 3.0))
    assert len(cl.inactive_segments()) == 2
    # only the first segment's entry is "durable"
    removed = cl.cleanup(lambda e: e.time_nanos < T0 + 5 * NANOS)
    assert removed == 1
    got = CommitLog.replay(wal_dir)
    assert [e.value for e in got] == [2.0, 3.0]
    cl.close()


def test_database_write_flush_read_bootstrap(tmp_path):
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR, retention_nanos=48 * HOUR)
    db = Database(base, num_shards=4)
    db.create_namespace("metrics", opts)

    for i in range(100):
        db.write("metrics", f"series.{i % 10}".encode(), T0 + i * 60 * NANOS, float(i))

    # read from buffer
    dps = db.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps] == [3.0, 13.0, 23.0, 33.0, 43.0, 53.0, 63.0, 73.0, 83.0, 93.0]

    # flush the first complete block
    flushed = db.flush("metrics", T0 + 2 * HOUR)
    assert flushed
    # reads merge fileset + buffer identically
    dps2 = db.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps2] == [dp.value for dp in dps]

    # crash: new Database over same dir, bootstrap replays WAL + sees filesets
    db.close()
    db2 = Database(base, num_shards=4)
    db2.create_namespace("metrics", opts)
    stats = db2.bootstrap()
    assert stats["filesets"] >= 1
    dps3 = db2.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps3] == [dp.value for dp in dps]
    db2.close()


def test_cold_writes_new_volume(tmp_path):
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1, commitlog_enabled=False)
    db.create_namespace("ns", opts)

    db.write("ns", b"s", T0 + 10 * NANOS, 1.0)
    db.write("ns", b"s", T0 + 20 * NANOS, 2.0)
    db.flush("ns", T0 + 2 * HOUR)

    # cold write into the already-flushed block
    db.write("ns", b"s", T0 + 15 * NANOS, 1.5)
    db.flush("ns", T0 + 2 * HOUR)

    fids = list_filesets(base, "ns", 0)
    assert len(fids) == 1 and fids[0].volume == 1  # new volume wins
    dps = db.read("ns", b"s", T0, T0 + HOUR)
    assert [dp.value for dp in dps] == [1.0, 1.5, 2.0]


def test_crash_after_flush_keeps_active_block_writes(tmp_path):
    """ADVICE r1 (high): flush used to destroy WAL entries for the still-
    active block; a crash right after flush lost every buffered point."""
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1)
    db.create_namespace("ns", opts)
    db.write("ns", b"s", T0 + 10 * NANOS, 1.0)  # block 0 (flushed)
    db.write("ns", b"s", T0 + 2 * HOUR + NANOS, 2.0)  # active block
    db.flush("ns", T0 + 2 * HOUR)
    # crash (no close/snapshot): reopen and bootstrap
    db2 = Database(base, num_shards=1)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    dps = db2.read("ns", b"s", T0, T0 + 4 * HOUR)
    assert [dp.value for dp in dps] == [1.0, 2.0]
    db2.close()


def test_crash_after_flush_keeps_unflushed_cold_writes(tmp_path):
    """ADVICE r1 (high, part 2): bootstrap used to skip WAL entries whose
    block was flushed, dropping cold writes not yet cold-flushed."""
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1)
    db.create_namespace("ns", opts)
    db.write("ns", b"s", T0 + 10 * NANOS, 1.0)
    db.write("ns", b"s", T0 + 30 * NANOS, 3.0)
    db.flush("ns", T0 + 2 * HOUR)
    # cold write into the flushed block, then crash before the next flush
    # (WAL fsync is batched; force it so the crash is after durability)
    db.write("ns", b"s", T0 + 20 * NANOS, 2.0)
    db._commitlogs["ns"].flush()
    db2 = Database(base, num_shards=1)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    dps = db2.read("ns", b"s", T0, T0 + HOUR)
    assert [dp.value for dp in dps] == [1.0, 2.0, 3.0]
    # and the next flush makes it durable in a new volume
    db2.flush("ns", T0 + 2 * HOUR)
    db3 = Database(base, num_shards=1)
    db3.create_namespace("ns", opts)
    db3.bootstrap()
    assert [dp.value for dp in db3.read("ns", b"s", T0, T0 + HOUR)] == [1.0, 2.0, 3.0]
    db3.close()


def test_snapshot_bounds_wal_replay(tmp_path):
    """shard.go:2335 Snapshot: after a snapshot, sealed WAL segments are
    removed and bootstrap restores buffers from the snapshot + WAL tail."""
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=2)
    db.create_namespace("ns", opts)
    for i in range(20):
        db.write("ns", f"s{i % 4}".encode(), T0 + i * 60 * NANOS, float(i))
    n = db.snapshot("ns")
    assert n > 0
    # WAL fully covered by the snapshot
    for cl in db._commitlogs.values():
        assert cl.inactive_segments() == []
    # post-snapshot writes land in the WAL tail (force the batched fsync)
    db.write("ns", b"s0", T0 + HOUR, 99.0)
    db._commitlogs["ns"].flush()
    db2 = Database(base, num_shards=2)
    db2.create_namespace("ns", opts)
    stats = db2.bootstrap()
    assert stats["snapshot_records"] > 0
    assert [dp.value for dp in db2.read("ns", b"s0", T0 + HOUR, T0 + 2 * HOUR)] == [99.0]
    got = db2.read("ns", b"s1", T0, T0 + 2 * HOUR)
    assert [dp.value for dp in got] == [1.0, 5.0, 9.0, 13.0, 17.0]
    db2.close()


def test_restart_preserves_tagged_queryability(tmp_path):
    """VERDICT r1 #4: write_tagged → flush → reopen → fetch_tagged by term
    AND regexp must return the data (index rebuilt at bootstrap)."""
    from m3_tpu.block.core import make_tags
    from m3_tpu.index import query as idx_query

    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=2)
    db.create_namespace("ns", opts)
    for i in range(6):
        tags = make_tags({b"__name__": b"cpu_seconds", b"host": f"h{i}".encode()})
        db.write_tagged("ns", tags, T0 + i * NANOS, float(i))
    db.flush("ns", T0 + 2 * HOUR)
    db.close()

    db2 = Database(base, num_shards=2)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    res = db2.fetch_tagged(
        "ns", idx_query.term(b"__name__", b"cpu_seconds"), T0, T0 + 2 * HOUR
    )
    assert len(res) == 6
    assert sorted(dp.value for _, _, dps in res for dp in dps) == [float(i) for i in range(6)]
    res_re = db2.fetch_tagged("ns", idx_query.regexp(b"host", b"h[0-2]"), T0, T0 + 2 * HOUR)
    assert len(res_re) == 3
    db2.close()


def test_unaligned_flush_cutoff_keeps_partial_block_wal(tmp_path):
    """Cleanup coverage is block-aligned: flush with a mid-block cutoff must
    not delete WAL segments for the still-unflushed partial block."""
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1)
    db.create_namespace("ns", opts)
    db.write("ns", b"s", T0 + HOUR, 1.0)  # block [T0, T0+2h)
    db.flush("ns", T0 + HOUR + HOUR // 2)  # cutoff inside the block
    # crash + bootstrap: the point must survive
    db2 = Database(base, num_shards=1)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    assert [dp.value for dp in db2.read("ns", b"s", T0, T0 + 2 * HOUR)] == [1.0]
    db2.close()


def test_restart_does_not_rewrite_identical_volumes(tmp_path):
    """Replay skips entries already durable in a flushed fileset, so a
    restart followed by flush produces no spurious new volume."""
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1)
    db.create_namespace("ns", opts)
    db.write("ns", b"s", T0 + 10 * NANOS, 1.0)
    # extra write in the NEXT block keeps the WAL segment alive past cleanup
    db.write("ns", b"s", T0 + 2 * HOUR + NANOS, 2.0)
    db.flush("ns", T0 + 2 * HOUR)
    db._commitlogs["ns"].flush()

    db2 = Database(base, num_shards=1)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    # the flushed point was NOT re-buffered as a cold write
    shard = db2.namespaces["ns"].shards[0]
    buffered = shard.series[b"s"].buckets
    assert T0 - T0 % (2 * HOUR) not in buffered or not buffered[
        (T0 // (2 * HOUR)) * (2 * HOUR)
    ].num_writes
    db2.flush("ns", T0 + 2 * HOUR)
    fids = list_filesets(base, "ns", 0)
    assert [f.volume for f in fids if f.block_start == (T0 // (2 * HOUR)) * (2 * HOUR)] == [0]
    assert [dp.value for dp in db2.read("ns", b"s", T0, T0 + 4 * HOUR)] == [1.0, 2.0]
    db2.close()


def test_index_segments_persisted_and_loaded(tmp_path):
    """Index blocks flushed at WarmFlush load wholesale at bootstrap
    (storage/index.go:868 + m3ninx/persist) — no per-ID rebuild needed."""
    from m3_tpu.block.core import make_tags
    from m3_tpu.index import query as idx_query

    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1)
    db.create_namespace("ns", opts)
    for i in range(4):
        db.write_tagged(
            "ns",
            make_tags({b"app": b"api", b"pod": f"p{i}".encode()}),
            T0 + i * NANOS,
            float(i),
        )
    db.flush("ns", T0 + 2 * HOUR)
    seg_dir = os.path.join(base, "index", "ns")
    assert os.listdir(seg_dir)  # segment file written
    db.close()

    db2 = Database(base, num_shards=1)
    db2.create_namespace("ns", opts)
    db2.bootstrap()
    loaded = db2.namespaces["ns"].index.blocks
    assert any(blk.sealed for blk in loaded.values())
    res = db2.fetch_tagged("ns", idx_query.term(b"app", b"api"), T0, T0 + 2 * HOUR)
    assert len(res) == 4
    # aggregate (tag values) comes from the loaded segments too
    vals = db2.namespaces["ns"].index.aggregate_query(None, T0, T0 + 2 * HOUR)
    assert vals[b"pod"] == {b"p0", b"p1", b"p2", b"p3"}
    db2.close()


def test_tick_expires_retention(tmp_path):
    opts = NamespaceOptions(block_size_nanos=HOUR, retention_nanos=2 * HOUR)
    db = Database(str(tmp_path), num_shards=1, commitlog_enabled=False)
    db.create_namespace("ns", opts)
    db.write("ns", b"old", T0, 1.0)
    db.write("ns", b"new", T0 + 5 * HOUR, 2.0)
    db.tick(T0 + 5 * HOUR)
    shard = db.namespaces["ns"].shards[0]
    assert b"old" not in shard.series
    assert b"new" in shard.series


def test_commitlog_writer_failure_surfaces_not_hangs(tmp_path):
    """A dead write-behind writer (disk error) must surface on the next
    write()/flush() instead of hanging barrier waiters forever."""
    import os as _os

    import pytest as _pytest

    from m3_tpu.storage.commitlog import CommitLog, CommitLogEntry

    cl = CommitLog(str(tmp_path), flush_interval=3600.0, flush_every=10**9)
    cl.write(CommitLogEntry(b"s", 1, 1.0))
    cl.flush()
    # break the fd under the writer, then force an fsync through it
    _os.close(cl._f.fileno())
    with _pytest.raises(RuntimeError):
        cl.write(CommitLogEntry(b"s", 2, 2.0))
        cl.flush()  # the flush path re-raises the writer's stored failure
        # if neither raised (timing), a subsequent write must
        for _ in range(100):
            cl.write(CommitLogEntry(b"s", 3, 3.0))
    # close() is safe after failure (no hang)
    cl.close()
