"""Storage node tests: buffer semantics, fileset checkpoint commit, WAL crash
replay, cold-flush volumes, bootstrap, device decode from filesets.
(Reference: src/dbnode/storage/, src/dbnode/persist/fs/.)"""

import os
import struct

import numpy as np
import pytest

from m3_tpu.codec.m3tsz import decode
from m3_tpu.ops.chunked import decode_chunked
from m3_tpu.ops.decode import finalize_decode
from m3_tpu.storage.commitlog import CommitLog, CommitLogEntry
from m3_tpu.storage.database import Database, NamespaceOptions
from m3_tpu.storage.fs import FilesetID, FilesetReader, fileset_complete, list_filesets, write_fileset
from m3_tpu.storage.series import SeriesBuffer
from m3_tpu.utils.xtime import Unit

NANOS = 1_000_000_000
T0 = 1_600_000_000 * NANOS
HOUR = 3600 * NANOS


def test_series_buffer_in_order_and_cold():
    buf = SeriesBuffer(b"s", 2 * HOUR)
    buf.write(T0 + 10 * NANOS, 1.0)
    buf.write(T0 + 20 * NANOS, 2.0)
    buf.write(T0 + 5 * NANOS, 0.5)  # out of order -> pending
    buf.write(T0 + 20 * NANOS, 3.0)  # duplicate ts -> last wins
    got = buf.read(T0, T0 + HOUR)
    assert [(dp.timestamp, dp.value) for dp in got] == [
        (T0 + 5 * NANOS, 0.5),
        (T0 + 10 * NANOS, 1.0),
        (T0 + 20 * NANOS, 3.0),
    ]


def test_fileset_checkpoint_commit(tmp_path):
    base = str(tmp_path)
    fid = FilesetID("ns", 0, T0)
    from m3_tpu.codec.m3tsz import encode_series

    series = {
        b"a": encode_series([T0 + i * NANOS for i in range(10)], [float(i) for i in range(10)]),
        b"b": encode_series([T0 + i * NANOS for i in range(5)], [2.0 * i for i in range(5)]),
    }
    write_fileset(base, fid, series, 2 * HOUR)
    assert fileset_complete(base, fid)
    r = FilesetReader(base, fid)
    assert sorted(r.series_ids) == [b"a", b"b"]
    assert decode(r.stream(b"a"))[3].value == 3.0
    assert r.stream(b"missing") is None

    # corrupt the digest -> checkpoint no longer validates
    digest_path = os.path.join(base, "data", "ns", "0", f"fileset-{T0}-0-digest.db")
    with open(digest_path, "ab") as f:
        f.write(b"x")
    assert not fileset_complete(base, fid)
    assert list_filesets(base, "ns", 0) == []


def test_fileset_missing_checkpoint_invisible(tmp_path):
    base = str(tmp_path)
    fid = FilesetID("ns", 1, T0)
    from m3_tpu.codec.m3tsz import encode_series

    write_fileset(base, fid, {b"a": encode_series([T0], [1.0])}, 2 * HOUR)
    os.remove(os.path.join(base, "data", "ns", "1", f"fileset-{T0}-0-checkpoint.db"))
    assert list_filesets(base, "ns", 1) == []


def test_fileset_device_decode(tmp_path):
    """Side tables in the fileset let the device decode without prescan."""
    base = str(tmp_path)
    fid = FilesetID("ns", 0, T0)
    from m3_tpu.codec.m3tsz import encode_series

    rng = np.random.default_rng(4)
    series = {}
    for i in range(7):
        n = int(rng.integers(3, 90))
        ts = [T0 + int(t) * NANOS for t in np.cumsum(rng.integers(1, 9, n))]
        series[f"s{i}".encode()] = encode_series(ts, np.round(rng.normal(0, 9, n), 2).tolist())
    write_fileset(base, fid, series, 2 * HOUR)

    r = FilesetReader(base, fid)
    sids = r.series_ids
    batch = r.chunked_batch(sids)
    ts, vals, valid = finalize_decode(decode_chunked(batch))
    for i, sid in enumerate(sids):
        want = decode(series[sid])
        got_t = ts[i][valid[i]]
        got_v = vals[i][valid[i]]
        assert len(got_t) == len(want)
        assert all(got_t[j] == want[j].timestamp for j in range(len(want)))
        assert all(got_v[j] == want[j].value for j in range(len(want)))


def test_commitlog_replay_and_torn_tail(tmp_path):
    path = str(tmp_path / "wal")
    cl = CommitLog(path, flush_every=1)
    entries = [
        CommitLogEntry(b"a", T0 + i * NANOS, float(i), Unit.SECOND, b"" if i else b"ann")
        for i in range(5)
    ]
    for e in entries:
        cl.write(e)
    cl.close()

    got = CommitLog.replay(path)
    assert len(got) == 5
    assert got[0].annotation == b"ann"
    assert got[4].value == 4.0

    # torn tail: truncate mid-record
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)
    got = CommitLog.replay(path)
    assert len(got) == 4  # last record dropped cleanly


def test_database_write_flush_read_bootstrap(tmp_path):
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR, retention_nanos=48 * HOUR)
    db = Database(base, num_shards=4)
    db.create_namespace("metrics", opts)

    for i in range(100):
        db.write("metrics", f"series.{i % 10}".encode(), T0 + i * 60 * NANOS, float(i))

    # read from buffer
    dps = db.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps] == [3.0, 13.0, 23.0, 33.0, 43.0, 53.0, 63.0, 73.0, 83.0, 93.0]

    # flush the first complete block
    flushed = db.flush("metrics", T0 + 2 * HOUR)
    assert flushed
    # reads merge fileset + buffer identically
    dps2 = db.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps2] == [dp.value for dp in dps]

    # crash: new Database over same dir, bootstrap replays WAL + sees filesets
    db.close()
    db2 = Database(base, num_shards=4)
    db2.create_namespace("metrics", opts)
    stats = db2.bootstrap()
    assert stats["filesets"] >= 1
    dps3 = db2.read("metrics", b"series.3", T0, T0 + 3 * HOUR)
    assert [dp.value for dp in dps3] == [dp.value for dp in dps]
    db2.close()


def test_cold_writes_new_volume(tmp_path):
    base = str(tmp_path)
    opts = NamespaceOptions(block_size_nanos=2 * HOUR)
    db = Database(base, num_shards=1, commitlog_enabled=False)
    db.create_namespace("ns", opts)

    db.write("ns", b"s", T0 + 10 * NANOS, 1.0)
    db.write("ns", b"s", T0 + 20 * NANOS, 2.0)
    db.flush("ns", T0 + 2 * HOUR)

    # cold write into the already-flushed block
    db.write("ns", b"s", T0 + 15 * NANOS, 1.5)
    db.flush("ns", T0 + 2 * HOUR)

    fids = list_filesets(base, "ns", 0)
    assert len(fids) == 1 and fids[0].volume == 1  # new volume wins
    dps = db.read("ns", b"s", T0, T0 + HOUR)
    assert [dp.value for dp in dps] == [1.0, 1.5, 2.0]


def test_tick_expires_retention(tmp_path):
    opts = NamespaceOptions(block_size_nanos=HOUR, retention_nanos=2 * HOUR)
    db = Database(str(tmp_path), num_shards=1, commitlog_enabled=False)
    db.create_namespace("ns", opts)
    db.write("ns", b"old", T0, 1.0)
    db.write("ns", b"new", T0 + 5 * HOUR, 2.0)
    db.tick(T0 + 5 * HOUR)
    shard = db.namespaces["ns"].shards[0]
    assert b"old" not in shard.series
    assert b"new" in shard.series
