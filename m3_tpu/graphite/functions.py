"""Graphite function library over consolidated series.

Reference: /root/reference/src/query/graphite/native/builtin_functions.go
(~100 functions). This library implements the widely-used core as
vectorized numpy transforms over [T] rows; every function takes an eval
context (bounds/step) and returns a new series list. Names and semantics
follow graphite-web.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import numpy as np


@dataclass
class GSeries:
    name: str
    values: np.ndarray  # float64[T], NaN = no data

    def with_values(self, vals, name: str | None = None) -> "GSeries":
        return GSeries(name if name is not None else self.name, np.asarray(vals, float))


@dataclass
class Context:
    start_nanos: int
    step_nanos: int
    steps: int


NANOS = 1_000_000_000

_INTERVAL_RE = re.compile(r"^(-?\d+)(s|sec|secs|second|seconds|min|mins|minute|minutes|h|hour|hours|d|day|days|w|week|weeks|mon|month|months|y|year|years)$")
_UNIT_SECS = {
    "s": 1, "sec": 1, "secs": 1, "second": 1, "seconds": 1,
    "min": 60, "mins": 60, "minute": 60, "minutes": 60,
    "h": 3600, "hour": 3600, "hours": 3600,
    "d": 86400, "day": 86400, "days": 86400,
    "w": 604800, "week": 604800, "weeks": 604800,
    "mon": 2592000, "month": 2592000, "months": 2592000,
    "y": 31536000, "year": 31536000, "years": 31536000,
}


def parse_interval(s) -> int:
    """'5min' → nanos (common/time.go ParseInterval)."""
    if isinstance(s, (int, float)):
        return int(s * NANOS)
    m = _INTERVAL_RE.match(s.strip())
    if not m:
        raise ValueError(f"graphite: bad interval {s!r}")
    return int(m.group(1)) * _UNIT_SECS[m.group(2)] * NANOS


def _stack(series: list[GSeries]) -> np.ndarray:
    return np.vstack([s.values for s in series]) if series else np.zeros((0, 0))


def _nan_fn(fn, arr, axis=0):
    with np.errstate(all="ignore"):
        out = fn(arr, axis=axis)
    return out


def _combine(name: str, series, reducer) -> list[GSeries]:
    if not series:
        return []
    arr = _stack(series)
    all_nan = np.all(np.isnan(arr), axis=0)
    out = reducer(arr)
    out = np.where(all_nan, np.nan, out)
    inner = ",".join(s.name for s in series)
    return [GSeries(f"{name}({inner})", out)]


FUNCS: dict = {}


def func(*names):
    def deco(fn):
        for n in names:
            FUNCS[n] = fn
        return fn

    return deco


# --- combining ---


@func("sumSeries", "sum")
def sum_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("sumSeries", series, lambda a: _nan_fn(np.nansum, a))


@func("averageSeries", "avg")
def average_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("averageSeries", series, lambda a: _nan_fn(np.nanmean, a))


@func("maxSeries")
def max_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("maxSeries", series, lambda a: _nan_fn(np.nanmax, a))


@func("minSeries")
def min_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("minSeries", series, lambda a: _nan_fn(np.nanmin, a))


@func("medianSeries")
def median_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("medianSeries", series, lambda a: _nan_fn(np.nanmedian, a))


@func("stddevSeries")
def stddev_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("stddevSeries", series, lambda a: _nan_fn(np.nanstd, a))


@func("countSeries")
def count_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    if not series:
        return []
    arr = _stack(series)
    out = np.sum(~np.isnan(arr), axis=0).astype(float)
    return [GSeries(f"countSeries({','.join(s.name for s in series)})", out)]


@func("diffSeries")
def diff_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    if not series:
        return []
    rest = (
        _stack(series[1:]) if len(series) > 1
        else np.zeros((0, len(series[0].values)))
    )
    sub = _nan_fn(np.nansum, rest) if len(series) > 1 else 0.0
    out = series[0].values - sub
    return [GSeries(f"diffSeries({','.join(s.name for s in series)})", out)]


@func("multiplySeries")
def multiply_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("multiplySeries", series, lambda a: _nan_fn(np.nanprod, a))


@func("divideSeries")
def divide_series(ctx, dividends, divisors):
    if len(divisors) != 1:
        raise ValueError("divideSeries: divisor must be exactly one series")
    d = divisors[0].values
    out = []
    with np.errstate(all="ignore"):
        for s in dividends:
            vals = s.values / np.where(d == 0, np.nan, d)
            out.append(GSeries(f"divideSeries({s.name},{divisors[0].name})", vals))
    return out


@func("asPercent")
def as_percent(ctx, series, total=None):
    if total is None:
        tot = _nan_fn(np.nansum, _stack(series))
    elif isinstance(total, list):
        tot = _nan_fn(np.nansum, _stack(total))
    else:
        tot = float(total)
    out = []
    with np.errstate(all="ignore"):
        for s in series:
            out.append(s.with_values(100.0 * s.values / tot, f"asPercent({s.name})"))
    return out


# --- transform ---


@func("absolute")
def absolute(ctx, series):
    return [s.with_values(np.abs(s.values), f"absolute({s.name})") for s in series]


@func("scale")
def scale(ctx, series, factor):
    return [s.with_values(s.values * factor, f"scale({s.name},{factor:g})") for s in series]


@func("scaleToSeconds")
def scale_to_seconds(ctx, series, seconds):
    factor = seconds / (ctx.step_nanos / NANOS)
    return [
        s.with_values(s.values * factor, f"scaleToSeconds({s.name},{int(seconds)})")
        for s in series
    ]


@func("offset")
def offset(ctx, series, amount):
    return [s.with_values(s.values + amount, f"offset({s.name},{amount:g})") for s in series]


@func("invert")
def invert(ctx, series):
    with np.errstate(all="ignore"):
        return [
            s.with_values(
                np.where(s.values == 0, np.nan, 1.0 / s.values), f"invert({s.name})"
            )
            for s in series
        ]


@func("logarithm", "log")
def logarithm(ctx, series, base=10.0):
    with np.errstate(all="ignore"):
        return [
            s.with_values(
                np.where(s.values > 0, np.log(s.values) / math.log(base), np.nan),
                f"log({s.name},{base:g})",
            )
            for s in series
        ]


@func("pow")
def pow_(ctx, series, factor):
    with np.errstate(all="ignore"):
        return [s.with_values(np.power(s.values, factor), f"pow({s.name},{factor:g})") for s in series]


@func("derivative")
def derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan)
        out.append(s.with_values(d, f"derivative({s.name})"))
    return out


@func("nonNegativeDerivative")
def non_negative_derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan)
        d = np.where(d < 0, np.nan, d)
        out.append(s.with_values(d, f"nonNegativeDerivative({s.name})"))
    return out


@func("perSecond")
def per_second(ctx, series):
    step_s = ctx.step_nanos / NANOS
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan) / step_s
        d = np.where(d < 0, np.nan, d)
        out.append(s.with_values(d, f"perSecond({s.name})"))
    return out


@func("integral")
def integral(ctx, series):
    out = []
    for s in series:
        vals = np.nancumsum(s.values)
        vals = np.where(np.isnan(s.values) & (np.arange(len(vals)) == 0), np.nan, vals)
        out.append(s.with_values(vals, f"integral({s.name})"))
    return out


@func("keepLastValue")
def keep_last_value(ctx, series, limit=math.inf):
    out = []
    for s in series:
        vals = s.values.copy()
        last = np.nan
        gap = 0
        for i in range(len(vals)):
            if np.isnan(vals[i]):
                gap += 1
                if not math.isnan(last) and gap <= limit:
                    vals[i] = last
            else:
                last = vals[i]
                gap = 0
        out.append(s.with_values(vals, f"keepLastValue({s.name})"))
    return out


@func("transformNull")
def transform_null(ctx, series, default=0.0):
    return [
        s.with_values(
            np.where(np.isnan(s.values), default, s.values),
            f"transformNull({s.name},{default:g})",
        )
        for s in series
    ]


@func("timeShift")
def time_shift(ctx, series, interval):
    # engine pre-fetches with the shift applied; this renames only
    return [s.with_values(s.values, f"timeShift({s.name},{interval})") for s in series]


def _moving(name, reducer):
    def fn(ctx, series, window):
        # graphite-web: a bare number is a POINT count; strings are intervals
        if isinstance(window, (int, float)):
            n = max(int(window), 1)
        else:
            n = max(int(parse_interval(window) // ctx.step_nanos), 1)
        out = []
        for s in series:
            vals = s.values
            padded = np.concatenate([np.full(n - 1, np.nan), vals])
            windows = np.lib.stride_tricks.sliding_window_view(padded, n)
            with np.errstate(all="ignore"):
                mv = reducer(windows, axis=1)
            all_nan = np.all(np.isnan(windows), axis=1)
            mv = np.where(all_nan, np.nan, mv)
            out.append(s.with_values(mv, f"{name}({s.name},{window!r})"))
        return out

    return fn


FUNCS["movingAverage"] = _moving("movingAverage", np.nanmean)
FUNCS["movingSum"] = _moving("movingSum", np.nansum)
FUNCS["movingMax"] = _moving("movingMax", np.nanmax)
FUNCS["movingMin"] = _moving("movingMin", np.nanmin)
FUNCS["movingMedian"] = _moving("movingMedian", np.nanmedian)


@func("summarize")
def summarize(ctx, series, interval, fn="sum"):
    n = max(int(parse_interval(interval) // ctx.step_nanos), 1)
    def _last_valid(a, axis):
        idx = np.where(~np.isnan(a), np.arange(a.shape[1])[None, :], -1).max(axis=1)
        vals = a[np.arange(a.shape[0]), np.maximum(idx, 0)]
        return np.where(idx >= 0, vals, np.nan)

    red = {
        "sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
        "max": np.nanmax, "min": np.nanmin, "last": _last_valid,
    }[fn]
    out = []
    for s in series:
        t = len(s.values)
        pad = (-t) % n
        vals = np.concatenate([s.values, np.full(pad, np.nan)]).reshape(-1, n)
        with np.errstate(all="ignore"):
            summed = red(vals, axis=1)
        summed = np.where(np.all(np.isnan(vals), axis=1), np.nan, summed)
        # expand back to step grid (each bucket repeated)
        expanded = np.repeat(summed, n)[:t]
        out.append(s.with_values(expanded, f"summarize({s.name},{interval!r},{fn!r})"))
    return out


# --- filtering / sorting ---


def _series_agg(s: GSeries, how: str) -> float:
    with np.errstate(all="ignore"):
        if how == "max":
            return float(np.nanmax(s.values)) if not np.all(np.isnan(s.values)) else -math.inf
        if how == "min":
            return float(np.nanmin(s.values)) if not np.all(np.isnan(s.values)) else math.inf
        if how == "avg":
            return float(np.nanmean(s.values)) if not np.all(np.isnan(s.values)) else -math.inf
        if how == "total":
            return float(np.nansum(s.values))
        if how == "current":
            valid = s.values[~np.isnan(s.values)]
            return float(valid[-1]) if len(valid) else -math.inf
    raise ValueError(how)


@func("highestMax")
def highest_max(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "max"), reverse=True)[: int(n)]


@func("highestAverage")
def highest_average(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "avg"), reverse=True)[: int(n)]


@func("highestCurrent")
def highest_current(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "current"), reverse=True)[: int(n)]


@func("lowestAverage")
def lowest_average(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "avg"))[: int(n)]


@func("lowestCurrent")
def lowest_current(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "current"))[: int(n)]


@func("sortByMaxima")
def sort_by_maxima(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "max"), reverse=True)


@func("sortByMinima")
def sort_by_minima(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "min"))


@func("sortByTotal")
def sort_by_total(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "total"), reverse=True)


@func("sortByName")
def sort_by_name(ctx, series):
    return sorted(series, key=lambda s: s.name)


@func("limit")
def limit(ctx, series, n):
    return series[: int(n)]


@func("exclude")
def exclude(ctx, series, pattern):
    rx = re.compile(pattern)
    return [s for s in series if not rx.search(s.name)]


@func("grep")
def grep(ctx, series, pattern):
    rx = re.compile(pattern)
    return [s for s in series if rx.search(s.name)]


@func("maximumAbove")
def maximum_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "max") > n]


@func("maximumBelow")
def maximum_below(ctx, series, n):
    return [s for s in series if _series_agg(s, "max") < n]


@func("minimumAbove")
def minimum_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "min") > n]


@func("averageAbove")
def average_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "avg") > n]


@func("currentAbove")
def current_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "current") > n]


@func("removeAboveValue")
def remove_above_value(ctx, series, n):
    return [
        s.with_values(np.where(s.values > n, np.nan, s.values),
                      f"removeAboveValue({s.name},{n:g})")
        for s in series
    ]


@func("removeBelowValue")
def remove_below_value(ctx, series, n):
    return [
        s.with_values(np.where(s.values < n, np.nan, s.values),
                      f"removeBelowValue({s.name},{n:g})")
        for s in series
    ]


@func("nPercentile")
def n_percentile(ctx, series, n):
    out = []
    for s in series:
        with np.errstate(all="ignore"):
            p = np.nanpercentile(s.values, n) if not np.all(np.isnan(s.values)) else np.nan
        out.append(s.with_values(np.full_like(s.values, p), f"nPercentile({s.name},{n:g})"))
    return out


# --- naming / grouping ---


@func("alias")
def alias(ctx, series, name):
    return [GSeries(name, s.values) for s in series]


@func("aliasByNode")
def alias_by_node(ctx, series, *nodes):
    out = []
    for s in series:
        parts = _base_path(s.name).split(".")
        picked = [parts[int(n)] for n in nodes if -len(parts) <= int(n) < len(parts)]
        out.append(GSeries(".".join(picked), s.values))
    return out


@func("aliasSub")
def alias_sub(ctx, series, pattern, replacement):
    rx = re.compile(pattern)
    return [GSeries(rx.sub(replacement, s.name), s.values) for s in series]


def _base_path(name: str) -> str:
    """Strip function wrappers: f(g(a.b.c,...)) → a.b.c (node addressing
    works on the underlying path, like graphite's pathExpression)."""
    m = re.search(r"[A-Za-z_0-9\-.${}*?\[\]]+(?=[,)]|$)", name)
    inner = name
    while True:
        m2 = re.match(r"^[A-Za-z_][A-Za-z_0-9]*\((.*)\)$", inner)
        if not m2:
            break
        inner = m2.group(1).split(",")[0]
    return inner


@func("groupByNode")
def group_by_node(ctx, series, node, callback="average"):
    return group_by_nodes(ctx, series, callback, node)


@func("groupByNodes")
def group_by_nodes(ctx, series, callback, *nodes):
    groups: dict[str, list[GSeries]] = {}
    for s in series:
        parts = _base_path(s.name).split(".")
        key = ".".join(
            parts[int(n)] if -len(parts) <= int(n) < len(parts) else ""
            for n in nodes
        )
        groups.setdefault(key, []).append(s)
    out = []
    fn = FUNCS[
        {"sum": "sumSeries", "avg": "averageSeries", "average": "averageSeries",
         "max": "maxSeries", "min": "minSeries"}.get(callback, callback)
    ]
    for key in sorted(groups):
        combined = fn(ctx, groups[key])
        for s in combined:
            out.append(GSeries(key, s.values))
    return out


@func("constantLine")
def constant_line(ctx, value):
    return [GSeries(f"{value:g}", np.full(ctx.steps, float(value)))]


@func("randomWalkFunction", "randomWalk")
def random_walk(ctx, name="randomWalk"):
    # deterministic "random" walk (tests need reproducibility; the reference
    # uses it for demos only)
    t = np.arange(ctx.steps, dtype=float)
    return [GSeries(str(name), np.sin(t / 3.0))]
