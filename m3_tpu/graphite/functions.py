"""Graphite function library over consolidated series.

Reference: /root/reference/src/query/graphite/native/builtin_functions.go
(~100 functions). This library implements the widely-used core as
vectorized numpy transforms over [T] rows; every function takes an eval
context (bounds/step) and returns a new series list. Names and semantics
follow graphite-web.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import numpy as np


@dataclass
class GSeries:
    name: str
    values: np.ndarray  # float64[T], NaN = no data

    def with_values(self, vals, name: str | None = None) -> "GSeries":
        return GSeries(name if name is not None else self.name, np.asarray(vals, float))


@dataclass
class Context:
    start_nanos: int
    step_nanos: int
    steps: int


NANOS = 1_000_000_000

_INTERVAL_RE = re.compile(r"^(-?\d+)(s|sec|secs|second|seconds|min|mins|minute|minutes|h|hour|hours|d|day|days|w|week|weeks|mon|month|months|y|year|years)$")
_UNIT_SECS = {
    "s": 1, "sec": 1, "secs": 1, "second": 1, "seconds": 1,
    "min": 60, "mins": 60, "minute": 60, "minutes": 60,
    "h": 3600, "hour": 3600, "hours": 3600,
    "d": 86400, "day": 86400, "days": 86400,
    "w": 604800, "week": 604800, "weeks": 604800,
    "mon": 2592000, "month": 2592000, "months": 2592000,
    "y": 31536000, "year": 31536000, "years": 31536000,
}


def parse_interval(s) -> int:
    """'5min' → nanos (common/time.go ParseInterval)."""
    if isinstance(s, (int, float)):
        return int(s * NANOS)
    m = _INTERVAL_RE.match(s.strip())
    if not m:
        raise ValueError(f"graphite: bad interval {s!r}")
    return int(m.group(1)) * _UNIT_SECS[m.group(2)] * NANOS


def _stack(series: list[GSeries]) -> np.ndarray:
    return np.vstack([s.values for s in series]) if series else np.zeros((0, 0))


def _nan_fn(fn, arr, axis=0):
    with np.errstate(all="ignore"):
        out = fn(arr, axis=axis)
    return out


def _combine(name: str, series, reducer) -> list[GSeries]:
    if not series:
        return []
    arr = _stack(series)
    all_nan = np.all(np.isnan(arr), axis=0)
    out = reducer(arr)
    out = np.where(all_nan, np.nan, out)
    inner = ",".join(s.name for s in series)
    return [GSeries(f"{name}({inner})", out)]


FUNCS: dict = {}


def func(*names):
    def deco(fn):
        for n in names:
            FUNCS[n] = fn
        return fn

    return deco


# --- combining ---


@func("sumSeries", "sum")
def sum_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("sumSeries", series, lambda a: _nan_fn(np.nansum, a))


@func("averageSeries", "avg")
def average_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("averageSeries", series, lambda a: _nan_fn(np.nanmean, a))


@func("maxSeries")
def max_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("maxSeries", series, lambda a: _nan_fn(np.nanmax, a))


@func("minSeries")
def min_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("minSeries", series, lambda a: _nan_fn(np.nanmin, a))


@func("medianSeries")
def median_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("medianSeries", series, lambda a: _nan_fn(np.nanmedian, a))


@func("stddevSeries")
def stddev_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("stddevSeries", series, lambda a: _nan_fn(np.nanstd, a))


@func("countSeries")
def count_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    if not series:
        return []
    arr = _stack(series)
    out = np.sum(~np.isnan(arr), axis=0).astype(float)
    return [GSeries(f"countSeries({','.join(s.name for s in series)})", out)]


@func("diffSeries")
def diff_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    if not series:
        return []
    rest = (
        _stack(series[1:]) if len(series) > 1
        else np.zeros((0, len(series[0].values)))
    )
    sub = _nan_fn(np.nansum, rest) if len(series) > 1 else 0.0
    out = series[0].values - sub
    return [GSeries(f"diffSeries({','.join(s.name for s in series)})", out)]


@func("multiplySeries")
def multiply_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("multiplySeries", series, lambda a: _nan_fn(np.nanprod, a))


@func("divideSeries")
def divide_series(ctx, dividends, divisors):
    if len(divisors) != 1:
        raise ValueError("divideSeries: divisor must be exactly one series")
    d = divisors[0].values
    out = []
    with np.errstate(all="ignore"):
        for s in dividends:
            vals = s.values / np.where(d == 0, np.nan, d)
            out.append(GSeries(f"divideSeries({s.name},{divisors[0].name})", vals))
    return out


@func("asPercent")
def as_percent(ctx, series, total=None):
    if total is None:
        tot = _nan_fn(np.nansum, _stack(series))
    elif isinstance(total, list):
        tot = _nan_fn(np.nansum, _stack(total))
    else:
        tot = float(total)
    out = []
    with np.errstate(all="ignore"):
        for s in series:
            out.append(s.with_values(100.0 * s.values / tot, f"asPercent({s.name})"))
    return out


# --- transform ---


@func("absolute")
def absolute(ctx, series):
    return [s.with_values(np.abs(s.values), f"absolute({s.name})") for s in series]


@func("scale")
def scale(ctx, series, factor):
    return [s.with_values(s.values * factor, f"scale({s.name},{factor:g})") for s in series]


@func("scaleToSeconds")
def scale_to_seconds(ctx, series, seconds):
    factor = seconds / (ctx.step_nanos / NANOS)
    return [
        s.with_values(s.values * factor, f"scaleToSeconds({s.name},{int(seconds)})")
        for s in series
    ]


@func("offset")
def offset(ctx, series, amount):
    return [s.with_values(s.values + amount, f"offset({s.name},{amount:g})") for s in series]


@func("invert")
def invert(ctx, series):
    with np.errstate(all="ignore"):
        return [
            s.with_values(
                np.where(s.values == 0, np.nan, 1.0 / s.values), f"invert({s.name})"
            )
            for s in series
        ]


@func("logarithm", "log")
def logarithm(ctx, series, base=10.0):
    with np.errstate(all="ignore"):
        return [
            s.with_values(
                np.where(s.values > 0, np.log(s.values) / math.log(base), np.nan),
                f"log({s.name},{base:g})",
            )
            for s in series
        ]


@func("pow")
def pow_(ctx, series, factor):
    with np.errstate(all="ignore"):
        return [s.with_values(np.power(s.values, factor), f"pow({s.name},{factor:g})") for s in series]


@func("derivative")
def derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan)
        out.append(s.with_values(d, f"derivative({s.name})"))
    return out


@func("nonNegativeDerivative")
def non_negative_derivative(ctx, series):
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan)
        d = np.where(d < 0, np.nan, d)
        out.append(s.with_values(d, f"nonNegativeDerivative({s.name})"))
    return out


@func("perSecond")
def per_second(ctx, series):
    step_s = ctx.step_nanos / NANOS
    out = []
    for s in series:
        d = np.diff(s.values, prepend=np.nan) / step_s
        d = np.where(d < 0, np.nan, d)
        out.append(s.with_values(d, f"perSecond({s.name})"))
    return out


@func("integral")
def integral(ctx, series):
    out = []
    for s in series:
        vals = np.nancumsum(s.values)
        vals = np.where(np.isnan(s.values) & (np.arange(len(vals)) == 0), np.nan, vals)
        out.append(s.with_values(vals, f"integral({s.name})"))
    return out


@func("keepLastValue")
def keep_last_value(ctx, series, limit=math.inf):
    out = []
    for s in series:
        vals = s.values.copy()
        last = np.nan
        gap = 0
        for i in range(len(vals)):
            if np.isnan(vals[i]):
                gap += 1
                if not math.isnan(last) and gap <= limit:
                    vals[i] = last
            else:
                last = vals[i]
                gap = 0
        out.append(s.with_values(vals, f"keepLastValue({s.name})"))
    return out


@func("transformNull")
def transform_null(ctx, series, default=0.0):
    return [
        s.with_values(
            np.where(np.isnan(s.values), default, s.values),
            f"transformNull({s.name},{default:g})",
        )
        for s in series
    ]


@func("timeShift")
def time_shift(ctx, series, interval):
    # engine pre-fetches with the shift applied; this renames only
    return [s.with_values(s.values, f"timeShift({s.name},{interval})") for s in series]


def _moving(name, reducer):
    def fn(ctx, series, window):
        # graphite-web: a bare number is a POINT count; strings are intervals
        if isinstance(window, (int, float)):
            n = max(int(window), 1)
        else:
            n = max(int(parse_interval(window) // ctx.step_nanos), 1)
        out = []
        for s in series:
            vals = s.values
            padded = np.concatenate([np.full(n - 1, np.nan), vals])
            windows = np.lib.stride_tricks.sliding_window_view(padded, n)
            with np.errstate(all="ignore"):
                mv = reducer(windows, axis=1)
            all_nan = np.all(np.isnan(windows), axis=1)
            mv = np.where(all_nan, np.nan, mv)
            out.append(s.with_values(mv, f"{name}({s.name},{window!r})"))
        return out

    return fn


FUNCS["movingAverage"] = _moving("movingAverage", np.nanmean)
FUNCS["movingSum"] = _moving("movingSum", np.nansum)
FUNCS["movingMax"] = _moving("movingMax", np.nanmax)
FUNCS["movingMin"] = _moving("movingMin", np.nanmin)
FUNCS["movingMedian"] = _moving("movingMedian", np.nanmedian)


@func("summarize")
def summarize(ctx, series, interval, fn="sum"):
    n = max(int(parse_interval(interval) // ctx.step_nanos), 1)
    def _last_valid(a, axis):
        idx = np.where(~np.isnan(a), np.arange(a.shape[1])[None, :], -1).max(axis=1)
        vals = a[np.arange(a.shape[0]), np.maximum(idx, 0)]
        return np.where(idx >= 0, vals, np.nan)

    red = {
        "sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
        "max": np.nanmax, "min": np.nanmin, "last": _last_valid,
    }[fn]
    out = []
    for s in series:
        t = len(s.values)
        pad = (-t) % n
        vals = np.concatenate([s.values, np.full(pad, np.nan)]).reshape(-1, n)
        with np.errstate(all="ignore"):
            summed = red(vals, axis=1)
        summed = np.where(np.all(np.isnan(vals), axis=1), np.nan, summed)
        # expand back to step grid (each bucket repeated)
        expanded = np.repeat(summed, n)[:t]
        out.append(s.with_values(expanded, f"summarize({s.name},{interval!r},{fn!r})"))
    return out


# --- filtering / sorting ---


def _series_agg(s: GSeries, how: str) -> float:
    with np.errstate(all="ignore"):
        if how == "max":
            return float(np.nanmax(s.values)) if not np.all(np.isnan(s.values)) else -math.inf
        if how == "min":
            return float(np.nanmin(s.values)) if not np.all(np.isnan(s.values)) else math.inf
        if how == "avg":
            return float(np.nanmean(s.values)) if not np.all(np.isnan(s.values)) else -math.inf
        if how == "total":
            return float(np.nansum(s.values))
        if how == "current":
            valid = s.values[~np.isnan(s.values)]
            return float(valid[-1]) if len(valid) else -math.inf
    raise ValueError(how)


@func("highestMax")
def highest_max(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "max"), reverse=True)[: int(n)]


@func("highestAverage")
def highest_average(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "avg"), reverse=True)[: int(n)]


@func("highestCurrent")
def highest_current(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "current"), reverse=True)[: int(n)]


@func("lowestAverage")
def lowest_average(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "avg"))[: int(n)]


@func("lowestCurrent")
def lowest_current(ctx, series, n=1):
    return sorted(series, key=lambda s: _series_agg(s, "current"))[: int(n)]


@func("sortByMaxima")
def sort_by_maxima(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "max"), reverse=True)


@func("sortByMinima")
def sort_by_minima(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "min"))


@func("sortByTotal")
def sort_by_total(ctx, series):
    return sorted(series, key=lambda s: _series_agg(s, "total"), reverse=True)


@func("sortByName")
def sort_by_name(ctx, series):
    return sorted(series, key=lambda s: s.name)


@func("limit")
def limit(ctx, series, n):
    return series[: int(n)]


@func("exclude")
def exclude(ctx, series, pattern):
    rx = re.compile(pattern)
    return [s for s in series if not rx.search(s.name)]


@func("grep")
def grep(ctx, series, pattern):
    rx = re.compile(pattern)
    return [s for s in series if rx.search(s.name)]


@func("maximumAbove")
def maximum_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "max") > n]


@func("maximumBelow")
def maximum_below(ctx, series, n):
    return [s for s in series if _series_agg(s, "max") < n]


@func("minimumAbove")
def minimum_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "min") > n]


@func("averageAbove")
def average_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "avg") > n]


@func("currentAbove")
def current_above(ctx, series, n):
    return [s for s in series if _series_agg(s, "current") > n]


@func("removeAboveValue")
def remove_above_value(ctx, series, n):
    return [
        s.with_values(np.where(s.values > n, np.nan, s.values),
                      f"removeAboveValue({s.name},{n:g})")
        for s in series
    ]


@func("removeBelowValue")
def remove_below_value(ctx, series, n):
    return [
        s.with_values(np.where(s.values < n, np.nan, s.values),
                      f"removeBelowValue({s.name},{n:g})")
        for s in series
    ]


@func("nPercentile")
def n_percentile(ctx, series, n):
    out = []
    for s in series:
        with np.errstate(all="ignore"):
            p = np.nanpercentile(s.values, n) if not np.all(np.isnan(s.values)) else np.nan
        out.append(s.with_values(np.full_like(s.values, p), f"nPercentile({s.name},{n:g})"))
    return out


# --- naming / grouping ---


@func("alias")
def alias(ctx, series, name):
    return [GSeries(name, s.values) for s in series]


@func("aliasByNode")
def alias_by_node(ctx, series, *nodes):
    out = []
    for s in series:
        parts = _base_path(s.name).split(".")
        picked = [parts[int(n)] for n in nodes if -len(parts) <= int(n) < len(parts)]
        out.append(GSeries(".".join(picked), s.values))
    return out


@func("aliasSub")
def alias_sub(ctx, series, pattern, replacement):
    rx = re.compile(pattern)
    return [GSeries(rx.sub(replacement, s.name), s.values) for s in series]


def _base_path(name: str) -> str:
    """Strip function wrappers: f(g(a.b.c,...)) → a.b.c (node addressing
    works on the underlying path, like graphite's pathExpression)."""
    m = re.search(r"[A-Za-z_0-9\-.${}*?\[\]]+(?=[,)]|$)", name)
    inner = name
    while True:
        m2 = re.match(r"^[A-Za-z_][A-Za-z_0-9]*\((.*)\)$", inner)
        if not m2:
            break
        inner = m2.group(1).split(",")[0]
    return inner


@func("groupByNode")
def group_by_node(ctx, series, node, callback="average"):
    return group_by_nodes(ctx, series, callback, node)


@func("groupByNodes")
def group_by_nodes(ctx, series, callback, *nodes):
    groups: dict[str, list[GSeries]] = {}
    for s in series:
        parts = _base_path(s.name).split(".")
        key = ".".join(
            parts[int(n)] if -len(parts) <= int(n) < len(parts) else ""
            for n in nodes
        )
        groups.setdefault(key, []).append(s)
    out = []
    fn = FUNCS[
        {"sum": "sumSeries", "avg": "averageSeries", "average": "averageSeries",
         "max": "maxSeries", "min": "minSeries"}.get(callback, callback)
    ]
    for key in sorted(groups):
        combined = fn(ctx, groups[key])
        for s in combined:
            out.append(GSeries(key, s.values))
    return out


@func("constantLine")
def constant_line(ctx, value):
    return [GSeries(f"{value:g}", np.full(ctx.steps, float(value)))]


@func("randomWalkFunction", "randomWalk")
def random_walk(ctx, name="randomWalk"):
    # deterministic "random" walk (tests need reproducibility; the reference
    # uses it for demos only)
    t = np.arange(ctx.steps, dtype=float)
    return [GSeries(str(name), np.sin(t / 3.0))]


# ---------------------------------------------------------------------------
# round-4 breadth pass: the remaining reference registrations
# (builtin_functions.go MustRegisterFunction list)
# ---------------------------------------------------------------------------


@func("group")
def group(ctx, *lists):
    """Flatten several series lists into one (builtin_functions.go group)."""
    out = []
    for lst in lists:
        out.extend(lst)
    return out


@func("identity", "timeFunction")
def identity_fn(ctx, name="identity"):
    """Value at each step = the step's unix timestamp in seconds."""
    t = (ctx.start_nanos + ctx.step_nanos * np.arange(ctx.steps)) / NANOS
    return [GSeries(str(name), t.astype(float))]


@func("threshold")
def threshold(ctx, value, label=None, color=None):
    name = str(label) if label is not None else f"{float(value):g}"
    return [GSeries(name, np.full(ctx.steps, float(value)))]


@func("aggregateLine")
def aggregate_line(ctx, series, fn="avg"):
    """Constant line at the aggregate of the FIRST series
    (builtin_functions.go:1538)."""
    if not series:
        raise ValueError("aggregateLine: empty series list")
    v = _series_agg(series[0], str(fn))
    return [GSeries(f"aggregateLine({series[0].name},{v:.6f})",
                    np.full(ctx.steps, v))]


@func("fallbackSeries")
def fallback_series(ctx, series, fallback):
    """series if non-empty, else the fallback list."""
    return series if series else fallback


@func("dashed")
def dashed(ctx, series, dash_length=5):
    return [
        s.with_values(s.values, f"dashed({s.name}, {float(dash_length):g})")
        for s in series
    ]


@func("consolidateBy")
def consolidate_by(ctx, series, fn="average"):
    """Rendering-consolidation hint: renames; values pass through."""
    return [
        s.with_values(s.values, f'consolidateBy({s.name},"{fn}")') for s in series
    ]


@func("changed")
def changed_fn(ctx, series):
    """1 when the value changed vs the LAST NON-NULL value, else 0 — the
    reference carries previous across NaN gaps (common.Changed,
    basic_functions.go:251): [2, NaN, 3] → [0, 0, 1]."""
    out = []
    for s in series:
        v = s.values
        # forward-fill the previous non-null value
        idx = np.where(~np.isnan(v), np.arange(len(v)), -1)
        ffi = np.maximum.accumulate(idx)
        prev_i = np.concatenate([[-1], ffi[:-1]])
        prev = np.where(prev_i >= 0, v[np.maximum(prev_i, 0)], np.nan)
        ch = (~np.isnan(v)) & (~np.isnan(prev)) & (v != prev)
        out.append(s.with_values(ch.astype(float), f"changed({s.name})"))
    return out


@func("isNonNull")
def is_non_null(ctx, series):
    return [
        s.with_values((~np.isnan(s.values)).astype(float), f"isNonNull({s.name})")
        for s in series
    ]


@func("offsetToZero")
def offset_to_zero(ctx, series):
    out = []
    for s in series:
        m = np.nanmin(s.values) if not np.all(np.isnan(s.values)) else 0.0
        out.append(s.with_values(s.values - m, f"offsetToZero({s.name})"))
    return out


@func("squareRoot")
def square_root(ctx, series):
    with np.errstate(invalid="ignore"):
        return [
            s.with_values(np.sqrt(s.values), f"squareRoot({s.name})")
            for s in series
        ]


@func("rangeOfSeries")
def range_of_series(ctx, *lists):
    series = [s for lst in lists for s in lst]
    return _combine("rangeOfSeries", series,
                    lambda a: _nan_fn(np.nanmax, a) - _nan_fn(np.nanmin, a))


def _graphite_percentile(arr: np.ndarray, pct: float, interpolate=False, axis=0):
    """GetPercentile (common/percentiles.go:75): rank = ceil(p/100 * n) on
    the sorted non-NaN values; optional linear interpolation from the
    previous rank. NOT numpy's linear-interpolation percentile."""
    a = np.moveaxis(np.asarray(arr, float), axis, -1)
    sv = np.sort(a, axis=-1)  # NaNs sort to the end
    cnt = (~np.isnan(a)).sum(axis=-1)
    frac_rank = (pct / 100.0) * cnt
    rank = np.ceil(frac_rank).astype(int)
    ri = np.clip(rank - 1, 0, np.maximum(cnt - 1, 0))
    out = np.take_along_axis(sv, ri[..., None], axis=-1)[..., 0]
    if interpolate:
        prev = np.take_along_axis(
            sv, np.clip(rank - 2, 0, np.maximum(cnt - 1, 0))[..., None], axis=-1
        )[..., 0]
        frac = frac_rank - (rank - 1)
        out = np.where(rank > 1, prev + frac * (out - prev), out)
    return np.where(cnt > 0, out, np.nan)


@func("percentileOfSeries")
def percentile_of_series(ctx, series, n, interpolate=False):
    """Cross-series nth percentile per step (reference rank method)."""
    if not series:
        return []
    vals = _graphite_percentile(_stack(series), float(n), bool(interpolate), axis=0)
    name = f"percentileOfSeries({series[0].name},{float(n):g})"
    return [GSeries(name, vals)]


@func("removeEmptySeries")
def remove_empty_series(ctx, series):
    return [s for s in series if not np.all(np.isnan(s.values))]


@func("removeAbovePercentile")
def remove_above_percentile(ctx, series, n):
    out = []
    for s in series:
        if np.all(np.isnan(s.values)):
            out.append(s)
            continue
        p = _graphite_percentile(s.values, float(n))
        v = np.where(s.values > p, np.nan, s.values)
        out.append(s.with_values(v, f"removeAbovePercentile({s.name}, {float(n):g})"))
    return out


@func("removeBelowPercentile")
def remove_below_percentile(ctx, series, n):
    out = []
    for s in series:
        if np.all(np.isnan(s.values)):
            out.append(s)
            continue
        p = _graphite_percentile(s.values, float(n))
        v = np.where(s.values < p, np.nan, s.values)
        out.append(s.with_values(v, f"removeBelowPercentile({s.name}, {float(n):g})"))
    return out


@func("currentBelow")
def current_below(ctx, series, n):
    def last_val(s):
        v = s.values[~np.isnan(s.values)]
        return v[-1] if len(v) else np.nan
    return [s for s in series if not np.isnan(last_val(s)) and last_val(s) <= float(n)]


@func("mostDeviant")
def most_deviant(ctx, series, n):
    """Top-n series by population stddev (ignoring NaN)."""
    def dev(s):
        v = s.values[~np.isnan(s.values)]
        return float(np.std(v)) if len(v) else -1.0
    ranked = sorted(series, key=dev, reverse=True)
    return ranked[: int(n)]


@func("stdev", "stddev")
def stdev_fn(ctx, series, points, window_tolerance=0.1):
    """Moving population stddev over a point-count window
    (builtin_functions.go stdev: emit NaN until the window holds at least
    windowTolerance of its points)."""
    npts = max(int(points), 1)
    out = []
    for s in series:
        v = s.values
        padded = np.concatenate([np.full(npts - 1, np.nan), v])
        w = np.lib.stride_tricks.sliding_window_view(padded, npts)
        valid = ~np.isnan(w)
        cnt = valid.sum(axis=1)
        with np.errstate(all="ignore"):
            sd = np.where(cnt > 0, np.nanstd(np.where(valid, w, np.nan), axis=1), np.nan)
        sd = np.where(cnt >= max(1, int(np.ceil(float(window_tolerance) * npts))), sd, np.nan)
        out.append(s.with_values(sd, f"stddev({s.name},{npts})"))
    return out


@func("substr")
def substr(ctx, series, start=0, stop=0):
    out = []
    for s in series:
        parts = _base_path(s.name).split(".")
        a, b = int(start), int(stop)
        sel = parts[a:] if b == 0 else parts[a:b]
        out.append(s.with_values(s.values, ".".join(sel)))
    return out


@func("aliasByMetric")
def alias_by_metric(ctx, series):
    return [
        s.with_values(s.values, _base_path(s.name).split(".")[-1]) for s in series
    ]


@func("legendValue")
def legend_value(ctx, series, *value_types):
    out = []
    for s in series:
        name = s.name
        for vt in value_types:
            name += f" ({vt}: {_series_agg(s, str(vt)):g})"
        out.append(s.with_values(s.values, name))
    return out


@func("cactiStyle")
def cacti_style(ctx, series, system=None):
    out = []
    for s in series:
        cur = s.values[~np.isnan(s.values)]
        current = cur[-1] if len(cur) else np.nan
        mx = np.nanmax(s.values) if len(cur) else np.nan
        mn = np.nanmin(s.values) if len(cur) else np.nan
        out.append(s.with_values(
            s.values,
            f"{s.name} Current:{current:g} Max:{mx:g} Min:{mn:g}",
        ))
    return out


@func("sustainedAbove")
def sustained_above(ctx, series, threshold_v, interval):
    return _sustained(ctx, series, float(threshold_v), interval,
                      lambda v, t: v >= t,
                      float(threshold_v) - abs(float(threshold_v)),
                      "sustainedAbove")


@func("sustainedBelow")
def sustained_below(ctx, series, threshold_v, interval):
    return _sustained(ctx, series, float(threshold_v), interval,
                      lambda v, t: v <= t,
                      float(threshold_v) + abs(float(threshold_v)),
                      "sustainedBelow")


def _sustained(ctx, series, thresh, interval, cmp, zero_value, fname):
    """builtin_functions.go:401 sustainedCompare: emit the value only once
    the comparison has held for >= interval; else the zero value."""
    min_steps = max(int(parse_interval(interval) // ctx.step_nanos), 1)
    out = []
    for s in series:
        v = s.values
        ok = cmp(np.nan_to_num(v, nan=np.inf if fname == "sustainedBelow" else -np.inf), thresh)
        # run length of consecutive ok up to each index
        run = np.zeros(len(v), int)
        c = 0
        for i, o in enumerate(ok):
            c = c + 1 if o else 0
            run[i] = c
        vals = np.where(run >= min_steps, v, zero_value)
        out.append(s.with_values(vals, f"{fname}({s.name}, {thresh:f}, '{interval}')"))
    return out


@func("hitcount")
def hitcount(ctx, series, interval, align_to_interval=False):
    """Rate × time per bucket (builtin_functions.go:1042): estimates the
    number of hits per interval from a per-second rate series."""
    iv_s = parse_interval(interval) / NANOS
    step_s = ctx.step_nanos / NANOS
    out = []
    for s in series:
        total_s = ctx.steps * step_s
        buckets = int(np.ceil(total_s / iv_s))
        # buckets align to the series END (builtin_functions.go:1057
        # newStart = end - bucketCount*interval); empty buckets stay NaN
        new_start = total_s - buckets * iv_s
        acc = np.full(buckets, np.nan)

        def add(b, amount):
            acc[b] = amount if np.isnan(acc[b]) else acc[b] + amount

        start_s = np.arange(ctx.steps) * step_s - new_start
        end_s = start_s + step_s
        for i, v in enumerate(s.values):
            if np.isnan(v):
                continue
            b0 = max(int(start_s[i] // iv_s), 0)
            b1 = int(end_s[i] // iv_s)
            if b1 >= buckets:
                b1 = buckets - 1
                end_here = buckets * iv_s
            else:
                end_here = end_s[i]
            if b0 == b1:
                add(b0, v * (end_here - start_s[i]))
            else:
                add(b0, v * (iv_s * (b0 + 1) - start_s[i]))
                for j in range(b0 + 1, b1):
                    add(j, v * iv_s)
                rem = end_here - iv_s * b1
                if rem > 0:
                    add(b1, v * rem)
        out.append(GSeries(f'hitcount({s.name}, "{interval}")', acc))
    return out


@func("weightedAverage")
def weighted_average(ctx, series, weights, node):
    """Pair value/weight series by path node; sum(v*w)/sum(w) per step
    (aggregation_functions.go:317)."""
    def key(s):
        parts = _base_path(s.name).split(".")
        n = int(node)
        return parts[n] if -len(parts) <= n < len(parts) else ""
    vals = {key(s): s for s in series}
    wts = {key(s): s for s in weights}
    prods, ws = [], []
    for k in sorted(vals):
        if k not in wts:
            continue
        prods.append(vals[k].values * wts[k].values)
        ws.append(wts[k].values)
    if not prods:
        return []
    num = _nan_fn(np.nansum, np.stack(prods))
    den = _nan_fn(np.nansum, np.stack(ws))
    with np.errstate(all="ignore"):
        out = np.where(den != 0, num / den, np.nan)
    return [GSeries(f"weightedAverage({len(prods)} series)", out)]


def _with_wildcards(name, series, positions, reducer):
    groups: dict[str, list] = {}
    for s in series:
        parts = _base_path(s.name).split(".")
        kept = [p for i, p in enumerate(parts) if i not in positions]
        groups.setdefault(".".join(kept), []).append(s)
    out = []
    for k in sorted(groups):
        arr = _stack(groups[k])
        out.append(GSeries(k, _nan_fn(reducer, arr)))
    return out


@func("sumSeriesWithWildcards")
def sum_series_with_wildcards(ctx, series, *positions):
    return _with_wildcards("sum", series, {int(p) for p in positions}, np.nansum)


@func("averageSeriesWithWildcards")
def average_series_with_wildcards(ctx, series, *positions):
    return _with_wildcards("avg", series, {int(p) for p in positions}, np.nanmean)


# --- Holt-Winters family (builtin_functions.go:1222-1420) ---

_HW_ALPHA, _HW_BETA, _HW_GAMMA = 0.1, 0.0035, 0.1


def _hw_analysis(values: np.ndarray, season_steps: int):
    """Triple exponential smoothing exactly as holtWintersAnalysis — same
    constants, same NaN handling. NOTE: the reference bootstraps with an
    extra week of history (FetchWithBootstrap); this engine warms up over
    the requested range instead, so early predictions differ until one
    season of data has passed."""
    n = len(values)
    intercepts = np.full(n, np.nan)
    slopes = np.zeros(n)
    seasonals = np.zeros(n)
    predictions = np.full(n, np.nan)
    deviations = np.zeros(n)

    def last_seasonal(i):
        j = i - season_steps
        return seasonals[j] if j >= 0 else 0.0

    def last_deviation(i):
        j = i - season_steps
        return deviations[j] if j >= 0 else 0.0

    next_pred = np.nan
    for i in range(n):
        actual = values[i]
        if np.isnan(actual):
            intercepts[i] = np.nan
            predictions[i] = next_pred
            deviations[i] = 0.0
            next_pred = np.nan
            continue
        if i == 0:
            last_intercept, last_slope, prediction = actual, 0.0, actual
        else:
            last_intercept = intercepts[i - 1]
            last_slope = slopes[i - 1]
            if np.isnan(last_intercept):
                last_intercept = actual
            prediction = next_pred
        last_season = last_seasonal(i)
        intercept = _HW_ALPHA * (actual - last_season) + (1 - _HW_ALPHA) * (
            last_intercept + last_slope
        )
        intercepts[i] = intercept
        slope = _HW_BETA * (intercept - last_intercept) + (1 - _HW_BETA) * last_slope
        slopes[i] = slope
        seasonals[i] = _HW_GAMMA * (actual - intercept) + (1 - _HW_GAMMA) * last_season
        next_pred = intercept + slope + last_seasonal(i + 1)
        pred_for_dev = 0.0 if np.isnan(prediction) else prediction
        predictions[i] = prediction
        deviations[i] = _HW_GAMMA * abs(actual - pred_for_dev) + (
            1 - _HW_GAMMA
        ) * last_deviation(i)
    return predictions, deviations


def _hw_season_steps(ctx) -> int:
    return max(int(86400 * NANOS // ctx.step_nanos), 1)


@func("holtWintersForecast")
def holt_winters_forecast(ctx, series):
    season = _hw_season_steps(ctx)
    return [
        s.with_values(
            _hw_analysis(s.values, season)[0], f"holtWintersForecast({s.name})"
        )
        for s in series
    ]


@func("holtWintersConfidenceBands")
def holt_winters_confidence_bands(ctx, series, delta=3):
    season = _hw_season_steps(ctx)
    out = []
    for s in series:
        pred, dev = _hw_analysis(s.values, season)
        up = np.where(~np.isnan(pred), pred + float(delta) * dev, np.nan)
        lo = np.where(~np.isnan(pred), pred - float(delta) * dev, np.nan)
        out.append(s.with_values(lo, f"holtWintersConfidenceLower({s.name})"))
        out.append(s.with_values(up, f"holtWintersConfidenceUpper({s.name})"))
    return out


@func("holtWintersAberration")
def holt_winters_aberration(ctx, series, delta=3):
    season = _hw_season_steps(ctx)
    out = []
    for s in series:
        pred, dev = _hw_analysis(s.values, season)
        up = pred + float(delta) * dev
        lo = pred - float(delta) * dev
        v = s.values
        ab = np.zeros(len(v))
        with np.errstate(invalid="ignore"):
            above = (~np.isnan(v)) & (~np.isnan(up)) & (v > up)
            below = (~np.isnan(v)) & (~np.isnan(lo)) & (v < lo)
        ab[above] = (v - up)[above]
        ab[below] = (v - lo)[below]
        out.append(s.with_values(ab, f"holtWintersAberration({s.name})"))
    return out
