"""Graphite target expression parser.

Reference: /root/reference/src/query/graphite/lexer/ + native/expression.go —
targets are function calls over series paths:

    sumSeries(servers.web*.cpu.{user,system})
    movingAverage(scale(app.reqs, 0.1), '5min')

Grammar: expr := call | path | number | string | bool;
call := ident '(' expr (',' expr)* ')'. Paths may contain glob characters;
an ident followed by '(' is a function name, otherwise it's (part of) a
path. Keyword args (``alignToFrom=true``) parse as named arguments.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class PathExpr:
    pattern: str


@dataclass
class Number:
    value: float


@dataclass
class String:
    value: str


@dataclass
class Bool:
    value: bool


@dataclass
class Call:
    func: str
    args: list = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)


# one token of a target: strings, numbers, identifiers/paths, punctuation
_TOKEN = re.compile(
    r"""\s*(?:
      (?P<string>'[^']*'|"[^"]*")
    | (?P<number>-?\d+\.\d+|-?\.\d+|-?\d+(?![\w.{\[*?]))
    | (?P<path>(?:[A-Za-z_0-9\-.*?$%:]|\{[^}]*\}|\[[^\]]*\])+)
    | (?P<punct>[(),=])
    )""",
    re.VERBOSE,
)


def _lex(s: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"graphite: bad character at {pos}: {s[pos:]!r}")
        pos = m.end()
        kind = m.lastgroup
        out.append((kind, m.group(kind)))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens) -> None:
        self.toks = tokens
        self.i = 0

    @property
    def cur(self):
        return self.toks[self.i]

    def eat(self, kind=None, text=None):
        k, t = self.cur
        if kind is not None and k != kind:
            raise ValueError(f"graphite: expected {kind}, got {k} {t!r}")
        if text is not None and t != text:
            raise ValueError(f"graphite: expected {text!r}, got {t!r}")
        self.i += 1
        return t

    def parse(self):
        e = self.expr()
        if self.cur[0] != "eof":
            raise ValueError(f"graphite: trailing input {self.cur[1]!r}")
        return e

    def expr(self):
        k, t = self.cur
        if k == "string":
            self.eat()
            return String(t[1:-1])
        if k == "number":
            self.eat()
            return Number(float(t))
        if k == "path":
            self.eat()
            nxt_k, nxt_t = self.cur
            if nxt_k == "punct" and nxt_t == "(":
                return self.call(t)
            # paths with commas inside braces lex as one path token already;
            # plain identifiers true/false are booleans
            if t in ("true", "false"):
                return Bool(t == "true")
            return PathExpr(t)
        raise ValueError(f"graphite: unexpected token {t!r}")

    def call(self, name: str) -> Call:
        self.eat(text="(")
        node = Call(name)
        while self.cur[1] != ")":
            # keyword argument?
            if (
                self.cur[0] == "path"
                and self.toks[self.i + 1][1] == "="
                and re.fullmatch(r"[A-Za-z_][A-Za-z_0-9]*", self.cur[1])
            ):
                key = self.eat("path")
                self.eat(text="=")
                node.kwargs[key] = self.expr()
            else:
                node.args.append(self.expr())
            if self.cur[1] == ",":
                self.eat(text=",")
        self.eat(text=")")
        return node


def parse(target: str):
    return _Parser(_lex(target)).parse()
