"""Graphite path ↔ tags mapping and glob matching.

Reference: /root/reference/src/query/graphite/graphite/ — carbon metrics
like ``servers.web01.cpu.user`` store as tagged series with one tag per
path node (``__g0__=servers, __g1__=web01, ...``), so the reverse index
serves graphite queries; glob patterns (``*``, ``{a,b}``, ``[0-9]``, ``?``)
compile to per-node regexes (graphite/glob.go).
"""

from __future__ import annotations

import re

from ..index.query import AllQuery, Query, conj, regexp, term

# per-node tag names (graphite/tags.go TagName)
def node_tag(i: int) -> bytes:
    return f"__g{i}__".encode()


_COUNT_TAG = b"__gcount__"  # number of nodes, so a.b never matches a.b.c


def path_to_tags(path: str) -> tuple:
    nodes = path.split(".")
    tags = [(node_tag(i), n.encode()) for i, n in enumerate(nodes)]
    tags.append((_COUNT_TAG, str(len(nodes)).encode()))
    return tuple(sorted(tags))


def tags_to_path(tags) -> str:
    nodes = {}
    for k, v in tags:
        m = re.fullmatch(rb"__g(\d+)__", bytes(k))
        if m:
            nodes[int(m.group(1))] = bytes(v).decode()
    return ".".join(nodes[i] for i in sorted(nodes))


_GLOB_CHARS = set("*?{[")


def is_pattern(node: str) -> bool:
    return any(c in _GLOB_CHARS for c in node)


def glob_node_to_regex(node: str) -> str:
    """One path node's glob → regex source (graphite/glob.go semantics)."""
    out = []
    i = 0
    while i < len(node):
        c = node[i]
        if c == "*":
            out.append("[^.]*")
        elif c == "?":
            out.append("[^.]")
        elif c == "{":
            j = node.find("}", i)
            if j < 0:
                raise ValueError(f"unbalanced {{ in {node!r}")
            alts = node[i + 1 : j].split(",")
            out.append("(" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = node.find("]", i)
            if j < 0:
                raise ValueError(f"unbalanced [ in {node!r}")
            out.append(node[i : j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


def node_queries(nodes: list[str]) -> list[Query]:
    """Per-node term/regexp queries for the non-wildcard path nodes."""
    qs: list[Query] = []
    for i, node in enumerate(nodes):
        if node == "*":
            continue  # wildcard constrains nothing beyond node presence
        if is_pattern(node):
            qs.append(regexp(node_tag(i), glob_node_to_regex(node).encode()))
        else:
            qs.append(term(node_tag(i), node.encode()))
    return qs


def pattern_to_query(pattern: str) -> Query:
    """Glob path pattern → index query over the per-node tags."""
    nodes = pattern.split(".")
    qs = [term(_COUNT_TAG, str(len(nodes)).encode())] + node_queries(nodes)
    if len(qs) == 1:
        return qs[0]
    return conj(*qs)
