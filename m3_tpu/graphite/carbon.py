"""Carbon plaintext ingest: ``metric.path value timestamp\\n`` over TCP.

Reference: /root/reference/src/cmd/services/m3coordinator/ingest/carbon/
ingest.go — lines parse into (path, value, unix seconds); paths store as
per-node tagged series (paths.py) so the graphite engine and PromQL can
both query them. Malformed lines are counted and skipped, never fatal.
"""

from __future__ import annotations

import socket
import socketserver
import threading

from ..utils.instrument import DEFAULT as METRICS
from .paths import path_to_tags

NANOS = 1_000_000_000


def parse_line(line: bytes):
    """→ (path, value, time_nanos) or None for blank/comment lines."""
    line = line.strip()
    if not line or line.startswith(b"#"):
        return None
    parts = line.split()
    if len(parts) != 3:
        raise ValueError(f"carbon: expected 3 fields, got {len(parts)}")
    path = parts[0].decode()
    value = float(parts[1])
    ts = float(parts[2])
    return path, value, int(ts * NANOS)


class CarbonIngestServer:
    """Line-oriented TCP listener feeding Database.write_tagged."""

    def __init__(
        self, db, namespace: str = "graphite", host: str = "127.0.0.1", port: int = 0
    ) -> None:
        self.db = db
        self.namespace = namespace
        self.received = 0
        self.malformed = 0
        outer = self
        m_recv = METRICS.counter("carbon_lines_total", "carbon lines ingested")
        m_bad = METRICS.counter("carbon_malformed_total")

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    try:
                        parsed = parse_line(raw)
                    except ValueError:
                        outer.malformed += 1
                        m_bad.inc()
                        continue
                    if parsed is None:
                        continue
                    path, value, t_nanos = parsed
                    try:
                        outer.db.write_tagged(
                            outer.namespace, path_to_tags(path), t_nanos, value
                        )
                        outer.received += 1
                        m_recv.inc()
                    except Exception:
                        outer.malformed += 1
                        m_bad.inc()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m3tpu-carbon", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def send_lines(host: str, port: int, lines: list[str]) -> None:
    """Test/client helper: push plaintext lines at a carbon listener."""
    with socket.create_connection((host, port), timeout=10) as sock:
        payload = "".join(l if l.endswith("\n") else l + "\n" for l in lines)
        sock.sendall(payload.encode())
