"""Graphite render engine: targets → evaluated series over storage.

Reference: /root/reference/src/query/graphite/native/ — compile the target
expression, fetch path-matched series from tagged storage (per-node
``__gN__`` tags, storage/converter.go), consolidate onto the step grid,
and apply the function pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..block.core import Bounds
from ..query.engine import consolidate
from .functions import FUNCS, Context, GSeries, parse_interval
from .parser import Bool, Call, Number, PathExpr, String, parse
from .paths import pattern_to_query, tags_to_path

NANOS = 1_000_000_000
DEFAULT_LOOKBACK = 5 * 60 * NANOS


@dataclass
class GraphiteEngine:
    db: object
    namespace: str = "graphite"
    lookback_nanos: int = DEFAULT_LOOKBACK
    # optional query/cost.py Enforcer: charged at fetch depth, so an
    # oversized glob aborts before consolidation work happens
    enforcer: object = None

    def render(
        self, target: str, start_nanos: int, end_nanos: int, step_nanos: int
    ) -> list[GSeries]:
        steps = max(int((end_nanos - start_nanos) // step_nanos), 1)
        ctx = Context(start_nanos, step_nanos, steps)
        ast = parse(target)
        return self._eval(ast, ctx, shift_nanos=0)

    def find(self, pattern: str) -> list[dict]:
        """metrics/find: path completion at the next node level
        (graphite/storage find semantics)."""
        nodes = pattern.split(".")
        depth = len(nodes)
        from .paths import node_queries, node_tag

        from ..index.query import FieldQuery, conj

        qs = [FieldQuery(node_tag(depth - 1))] + node_queries(nodes)
        q = qs[0] if len(qs) == 1 else conj(*qs)
        result = self.db.query_ids(self.namespace, q, 0, 2**62)
        out: dict[str, bool] = {}
        for doc in result.docs:
            tags = dict(doc.fields)
            path_nodes = []
            i = 0
            while node_tag(i) in tags:
                path_nodes.append(tags[node_tag(i)].decode())
                i += 1
            prefix = ".".join(path_nodes[:depth])
            is_leaf = len(path_nodes) == depth
            out[prefix] = out.get(prefix, True) and is_leaf
        return [
            {"id": p, "text": p.rsplit(".", 1)[-1], "leaf": leaf}
            for p, leaf in sorted(out.items())
        ]

    # -- evaluation --

    def _eval(self, node, ctx: Context, shift_nanos: int) -> list[GSeries]:
        if isinstance(node, PathExpr):
            return self._fetch(node.pattern, ctx, shift_nanos)
        if isinstance(node, Call):
            return self._call(node, ctx, shift_nanos)
        raise ValueError(f"graphite: target must be a path or call, got {node!r}")

    def _call(self, call: Call, ctx: Context, shift_nanos: int) -> list[GSeries]:
        fn = FUNCS.get(call.func)
        if fn is None:
            raise ValueError(f"graphite: unsupported function {call.func!r}")
        inner_shift = shift_nanos
        if call.func == "timeShift":
            # timeShift('-1d') re-fetches the inner series shifted in time;
            # the function itself only renames (functions.py)
            interval = (
                self._scalar(call.args[1]) if len(call.args) > 1 else "-1d"
            )
            delta = parse_interval(interval)
            if isinstance(interval, str) and not interval.lstrip().startswith(("-", "+")):
                # graphite-web implies a minus: timeShift(s, '1d') = 1d AGO
                delta = -delta
            inner_shift = shift_nanos + delta
            series = self._eval(call.args[0], ctx, inner_shift)
            return fn(ctx, series, interval)
        args = []
        for a in call.args:
            if isinstance(a, (PathExpr, Call)):
                args.append(self._eval(a, ctx, inner_shift))
            else:
                args.append(self._scalar(a))
        kwargs = {k: self._scalar(v) for k, v in call.kwargs.items()}
        return fn(ctx, *args, **kwargs)

    def _scalar(self, node):
        if isinstance(node, Number):
            return node.value
        if isinstance(node, String):
            return node.value
        if isinstance(node, Bool):
            return node.value
        raise ValueError(f"graphite: expected a literal, got {node!r}")

    def _fetch(self, pattern: str, ctx: Context, shift_nanos: int) -> list[GSeries]:
        q = pattern_to_query(pattern)
        start = ctx.start_nanos + shift_nanos
        end = start + ctx.step_nanos * ctx.steps
        fetched = self.db.fetch_tagged(
            self.namespace, q, start - self.lookback_nanos, end
        )
        if self.enforcer is not None:
            self.enforcer.charge(
                len(fetched), sum(len(dps) for _, _, dps in fetched)
            )
        series = []
        for sid, tags, dps in fetched:
            times = np.asarray([dp.timestamp for dp in dps], np.int64)
            vals = np.asarray([dp.value for dp in dps], np.float64)
            series.append((tags, times, vals))
        bounds = Bounds(start, ctx.step_nanos, ctx.steps)
        result = consolidate(series, bounds, self.lookback_nanos)
        out = []
        for i, meta in enumerate(result.metas):
            out.append(GSeries(tags_to_path(meta.tags), np.asarray(result.values[i])))
        return sorted(out, key=lambda s: s.name)
