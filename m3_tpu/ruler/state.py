"""Alert state machine: inactive → pending → firing, with durable clocks.

One :class:`AlertRuleState` per alert rule tracks an :class:`ActiveAlert`
per result label set (the alert *instance*, keyed by a stable label
fingerprint). Transitions per evaluation:

- expression true, previously inactive → PENDING (``active_at`` = the
  evaluation timestamp); rules with ``for: 0`` skip straight to FIRING;
- PENDING and ``now - active_at >= for`` → FIRING (one ``firing``
  notification);
- FIRING and expression false → resolved (one ``resolved`` notification,
  instance removed);
- PENDING and expression false → back to inactive silently (the
  condition never held long enough to tell anyone).

Clock discipline (M3L004): the ``for:`` hold is arithmetic over
EVALUATION timestamps — data-clock nanos handed in by the scheduler, the
same instants the queries evaluate at — never ``time.time()`` readings
taken here. That makes the clocks durable: checkpointed ``active_at``
values stay meaningful across a coordinator restart or leader change
(a monotonic reading would not), which is what lets a restored ruler
continue a pending alert's hold instead of resetting it, and lets an
alert that fired before the restart stay fired without re-notifying
(notifications happen only on TRANSITIONS).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

NANOS = 1_000_000_000

INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"

# {{ $value }} / {{ $labels.<name> }} templating in labels/annotations
_TMPL_RE = re.compile(
    r"\{\{\s*\$(?:(value)|labels\.([a-zA-Z_][a-zA-Z0-9_]*))\s*\}\}"
)


def render_template(tmpl: str, labels: dict, value: float) -> str:
    """Expand ``{{ $value }}`` and ``{{ $labels.x }}`` (missing labels
    expand empty, matching Prometheus's zero-value semantics)."""

    def _sub(m: re.Match) -> str:
        if m.group(1):
            return format(value, "g")
        return str(labels.get(m.group(2), ""))

    return _TMPL_RE.sub(_sub, str(tmpl))


def fingerprint(labels: dict) -> str:
    """Stable alert-instance key: JSON of the sorted label items (JSON so
    it round-trips as a KV checkpoint dict key)."""
    return json.dumps(sorted(labels.items()), separators=(",", ":"))


@dataclass
class ActiveAlert:
    """One live alert instance (a PENDING or FIRING label set)."""

    labels: dict
    annotations: dict
    state: str
    active_at_nanos: int
    value: float = 0.0
    fired_at_nanos: int = 0

    def to_dict(self) -> dict:
        return {
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
            "state": self.state,
            "activeAt": self.active_at_nanos,
            "value": self.value,
            "firedAt": self.fired_at_nanos,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ActiveAlert":
        return cls(
            labels={str(k): str(v) for k, v in d.get("labels", {}).items()},
            annotations={
                str(k): str(v) for k, v in d.get("annotations", {}).items()
            },
            state=str(d.get("state", PENDING)),
            active_at_nanos=int(d.get("activeAt", 0)),
            value=float(d.get("value", 0.0)),
            fired_at_nanos=int(d.get("firedAt", 0)),
        )


@dataclass
class Transition:
    """A state change the notifier should hear about."""

    status: str  # "firing" | "resolved"
    alert: ActiveAlert


@dataclass
class AlertRuleState:
    """All live instances of one alert rule, keyed by fingerprint."""

    active: dict = field(default_factory=dict)  # fp -> ActiveAlert

    def evaluate(
        self, rule, rows: list, now_nanos: int
    ) -> list[Transition]:
        """Apply one evaluation result. ``rows`` is the instant vector as
        ``[(series_labels: dict, value: float), ...]`` (only series where
        the expression held); ``now_nanos`` is the evaluation timestamp.
        Returns the transitions (firing/resolved) in result order."""
        for_nanos = int(rule.for_secs * NANOS)
        transitions: list[Transition] = []
        seen: set = set()
        for series_labels, value in rows:
            # alert identity: series labels minus __name__, plus the
            # rule's (templated) labels, plus alertname — Prometheus's
            # ALERTS label algebra
            ident = {
                k: v for k, v in series_labels.items() if k != "__name__"
            }
            for k, v in rule.labels.items():
                ident[k] = render_template(v, series_labels, value)
            ident["alertname"] = rule.alert
            fp = fingerprint(ident)
            seen.add(fp)
            annotations = {
                k: render_template(v, series_labels, value)
                for k, v in rule.annotations.items()
            }
            cur = self.active.get(fp)
            if cur is None:
                cur = ActiveAlert(
                    labels=ident,
                    annotations=annotations,
                    state=PENDING,
                    active_at_nanos=now_nanos,
                    value=value,
                )
                self.active[fp] = cur
            else:
                cur.value = value
                cur.annotations = annotations
            if (
                cur.state == PENDING
                and now_nanos - cur.active_at_nanos >= for_nanos
            ):
                cur.state = FIRING
                cur.fired_at_nanos = now_nanos
                transitions.append(Transition("firing", cur))
        # instances whose condition cleared
        for fp in [fp for fp in self.active if fp not in seen]:
            gone = self.active.pop(fp)
            if gone.state == FIRING:
                transitions.append(Transition("resolved", gone))
        return transitions

    def counts(self) -> tuple[int, int]:
        """(pending, firing) instance counts."""
        pending = sum(1 for a in self.active.values() if a.state == PENDING)
        firing = sum(1 for a in self.active.values() if a.state == FIRING)
        return pending, firing

    # -- KV checkpoint codec --

    def to_dict(self) -> dict:
        return {fp: a.to_dict() for fp, a in self.active.items()}

    @classmethod
    def from_dict(cls, d: dict) -> "AlertRuleState":
        st = cls()
        for fp, raw in (d or {}).items():
            st.active[fp] = ActiveAlert.from_dict(raw)
        return st
