"""Notifier seam: where alert transitions leave the process.

A notifier receives a batch of alert events — Alertmanager webhook-shaped
dicts (``status``/``labels``/``annotations``/``startsAt``/``value``) —
and returns whether delivery succeeded. The ruler counts outcomes; a
failed delivery never stops evaluation (alerting must degrade to "state
visible at /api/v1/alerts" when the notification path is down, not take
the rule engine with it).

Two built-ins:

- :class:`LogNotifier` — structured lines via ``logging`` plus a bounded
  in-memory ring (the test/debug seam: what WOULD have been delivered);
- :class:`WebhookNotifier` — HTTP POST of the standard webhook payload,
  wrapped in the resilience plane's :class:`~m3_tpu.net.resilience.
  RetryPolicy` (decorrelated-jitter backoff + retry budget) under one
  per-delivery deadline, so a flapping receiver costs a bounded slice of
  the evaluation loop and a retry storm cannot amplify an outage.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from collections import deque

from ..net.resilience import RetryPolicy
from ..utils.instrument import DEFAULT as METRICS

log = logging.getLogger("m3tpu.ruler")


def rfc3339(nanos: int) -> str:
    """Epoch nanos → RFC3339 UTC timestamp (what Alertmanager-ecosystem
    receivers parse for startsAt/endsAt)."""
    from datetime import datetime, timezone

    return datetime.fromtimestamp(nanos / 1e9, tz=timezone.utc).isoformat()


def alert_event(status: str, alert) -> dict:
    """One state-transition event (ruler/state.Transition) as the
    Alertmanager webhook alert shape. ``startsAt`` is RFC3339 (the
    format real receivers parse); ``startsAtUnixNanos`` rides alongside
    for consumers that want the raw clock."""
    return {
        "status": status,  # "firing" | "resolved"
        "labels": dict(alert.labels),
        "annotations": dict(alert.annotations),
        "startsAt": rfc3339(alert.active_at_nanos),
        "startsAtUnixNanos": alert.active_at_nanos,
        "value": alert.value,
    }


class LogNotifier:
    """Log-sink notifier; keeps the last ``capacity`` events for
    inspection (tests and /debug surfaces read ``sent``)."""

    def __init__(self, capacity: int = 256) -> None:
        self._ring: deque = deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()

    @property
    def sent(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def notify(self, events: list[dict]) -> bool:
        with self._lock:
            self._ring.extend(events)
        for e in events:
            log.info(
                "alert %s %s value=%s",
                e["status"],
                e["labels"].get("alertname", "?"),
                e.get("value"),
            )
        return True


class WebhookNotifier:
    """POSTs ``{"version": "4", "alerts": [...]}`` to ``url``.

    One delivery gets ``timeout`` seconds TOTAL (deadline, not
    per-attempt): each attempt's socket timeout is the remaining budget,
    and retries follow ``policy`` (net/resilience.RetryPolicy — budgeted,
    so a dead receiver degrades to ~token_ratio extra attempts). All
    failures are counted, never raised."""

    def __init__(
        self,
        url: str,
        policy: RetryPolicy | None = None,
        timeout: float = 5.0,
    ) -> None:
        self.url = str(url)
        self.policy = policy or RetryPolicy(max_retries=2, max_backoff=0.5)
        self.timeout = float(timeout)
        self._m_sent = METRICS.counter(
            "ruler_webhook_deliveries_total",
            "alert webhook deliveries that got a 2xx",
        )
        self._m_failed = METRICS.counter(
            "ruler_webhook_failures_total",
            "alert webhook deliveries that exhausted their deadline or "
            "retry budget",
        )

    def notify(self, events: list[dict]) -> bool:
        body = json.dumps({"version": "4", "alerts": events}).encode()
        deadline = time.monotonic() + self.timeout
        attempt = 0
        prev_sleep = 0.0
        while True:
            attempt += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._m_failed.inc()
                return False
            try:
                req = urllib.request.Request(
                    self.url,
                    data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=remaining) as resp:
                    ok = 200 <= resp.status < 300
                if ok:
                    self.policy.on_success()
                    self._m_sent.inc()
                    return True
            except Exception as exc:
                # failed attempt: fall through to the retry decision
                # below, where suppressed retries are counted — this is
                # the loop's retryable-error path, not a swallow
                log.debug("webhook attempt %d failed: %s", attempt, exc)
            if not self.policy.allow_retry(attempt):
                self._m_failed.inc()
                return False
            prev_sleep = self.policy.backoff(attempt, prev_sleep)
            if prev_sleep > 0:
                time.sleep(min(prev_sleep, max(deadline - time.monotonic(), 0)))
