"""The ruler: periodic PromQL recording + alerting over stored series.

The coordinator-side rule engine that closes the self-monitoring loop:
PR 6 made the fleet's telemetry first-class stored series under
``_m3tpu``; the ruler is what ACTS on it (and on any other namespace).
Shape follows the Prometheus ruler paired with M3's versioned-ruleset
discipline (``m3_tpu/rules/`` does the same for aggregation rules):

- **one shared ruleset** — rule groups load from a YAML/JSON file at
  coordinator start and are mirrored into the etcd-style KV store under
  :data:`RULESET_KEY` (CAS-versioned, exactly the r2 RuleStore pattern),
  so every coordinator watching the key runs the same version and the
  ruleset survives coordinator failover;
- **per-group fixed-rate evaluation** — each group evaluates on its own
  schedule through the coordinator's existing per-namespace engine cache
  (``engine_for``), with the deterministic phase jitter of
  utils/schedule.py so group evals and fleet scrapes spread over the
  interval instead of herding the write path;
- **recording rules** write their derived (colon-named) series back
  through the NORMAL write path inside ``selfmon.guard.ruler_writer()``
  — the second sanctioned reserved-namespace writer, so derived
  ``_m3tpu`` series land next to their inputs while every other ingest
  surface still gets a typed error;
- **alert rules** run the inactive→pending→firing machine
  (ruler/state.py) with per-group firing state CHECKPOINTED to KV after
  each state change — a coordinator restart or leader change restores
  ``for:`` clocks and already-fired instances instead of resetting and
  re-notifying. A dead KV degrades loudly: evaluation continues from the
  in-memory state and every dropped checkpoint ticks
  ``m3tpu_ruler_checkpoint_failures_total``;
- **self-metrics** — per-group eval duration/failure/missed-tick series
  and active/pending/firing gauges, which the PR 6 collector stores like
  any other family: ruler health is itself alertable by a ruler rule.
"""

from __future__ import annotations

import threading
import time

from ..block.core import make_tags
from ..selfmon.guard import is_reserved, ruler_writer
from ..utils.instrument import DEFAULT as METRICS
from ..utils.schedule import FixedRateTicker
from .notify import LogNotifier, alert_event
from .rules import AlertRule, RecordingRule, groups_from_spec, groups_to_spec
from .state import AlertRuleState, FIRING, PENDING

NANOS = 1_000_000_000

RULESET_KEY = "_ruler/ruleset"
STATE_KEY_PREFIX = "_ruler/state/"

# eval latencies look like query latencies (the eval IS a query)
_EVAL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class RulerStore:
    """CAS-versioned KV mirror of the ruleset (r2/RuleStore pattern):
    the stored value is ``{"version": n, "groups": [...]}``."""

    def __init__(self, kv) -> None:
        self.kv = kv

    def get(self) -> tuple[dict | None, int]:
        """(spec, ruleset_version); (None, 0) when no ruleset is stored."""
        vv = self.kv.get(RULESET_KEY)
        if vv is None or not isinstance(vv.value, dict):
            return None, 0
        return vv.value, int(vv.value.get("version", 0))

    def set_spec(self, spec: dict) -> int:
        """Store ``spec`` (validated groups dict) as the next ruleset
        version; CAS loop against concurrent coordinators."""
        groups = spec.get("groups", [])
        while True:
            vv = self.kv.get(RULESET_KEY)
            cur_ver = 0
            if vv is not None and isinstance(vv.value, dict):
                cur_ver = int(vv.value.get("version", 0))
            value = {"version": cur_ver + 1, "groups": groups}
            try:
                self.kv.check_and_set(
                    RULESET_KEY, vv.version if vv is not None else 0, value
                )
                return cur_ver + 1
            except ValueError:
                continue  # lost the race; retry on fresh state

    def mirror(self, spec: dict) -> int:
        """Idempotent publish: bump the stored version only when the
        GROUPS differ (a coordinator restart with an unchanged rules file
        must not churn every peer's watch)."""
        cur, ver = self.get()
        if cur is not None and cur.get("groups") == spec.get("groups"):
            return ver
        return self.set_spec(spec)


class GroupRunner:
    """One rule group's evaluation loop + alert state + health record."""

    def __init__(self, group, ruler: "Ruler") -> None:
        self.group = group
        self.ruler = ruler
        self.states: dict[str, AlertRuleState] = {
            r.alert: AlertRuleState()
            for r in group.rules
            if isinstance(r, AlertRule)
        }
        # per-rule health for /api/v1/rules: name -> record
        self.health: dict[str, dict] = {
            self._rule_name(r): {
                "health": "unknown", "lastError": None,
                "lastEvaluationUnixNanos": 0, "evaluationTime": 0.0,
            }
            for r in group.rules
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_checkpoint: dict | None = None
        # guards self.states' AlertRuleState contents: the eval thread
        # mutates instance dicts while HTTP projection threads
        # (/api/v1/rules, /api/v1/alerts, /debug/dump) iterate them
        self._state_lock = threading.Lock()
        labels = {"group": group.name}
        self._m_eval = METRICS.histogram(
            "ruler_group_eval_duration_seconds",
            "wall time of one rule-group evaluation pass",
            labels=labels, buckets=_EVAL_BUCKETS,
        )
        self._m_failures = METRICS.counter(
            "ruler_eval_failures_total",
            "rule evaluations that raised (bad data, engine error)",
            labels=labels,
        )
        self._m_missed = METRICS.counter(
            "ruler_iterations_missed_total",
            "scheduled group evaluations skipped because the loop fell a "
            "full interval behind (eval slower than the group interval)",
            labels=labels,
        )
        self._m_samples = METRICS.counter(
            "ruler_recorded_samples_total",
            "derived datapoints written by recording rules",
            labels=labels,
        )
        self._g_active = METRICS.gauge(
            "ruler_alerts_active", "pending + firing alert instances",
            labels=labels,
        )
        self._g_pending = METRICS.gauge(
            "ruler_alerts_pending", "alert instances holding their for: clock",
            labels=labels,
        )
        self._g_firing = METRICS.gauge(
            "ruler_alerts_firing", "firing alert instances", labels=labels
        )

    @staticmethod
    def _rule_name(rule) -> str:
        return rule.record if isinstance(rule, RecordingRule) else rule.alert

    # -- lifecycle --

    def start(self) -> None:
        # the whole start rides under the ruler lock so it cannot
        # interleave with Ruler.stop(): a KV watch _apply racing stop()
        # must not leave evaluators running after stop() returned
        # (shutdown writes into a closing database). Thread creation is
        # non-blocking, so holding the lock here is cheap.
        with self.ruler._lock:
            if not self.ruler._started:
                return
            if self._thread is None:
                # a runner stopped by a ruler stop() keeps its state;
                # clear the stop latch so a later start() ticks again
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"ruler-{self.group.name}",
                )
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        ticker = FixedRateTicker(
            self.group.interval_secs,
            phase_key=f"ruler/{self.ruler.instance}/{self.group.name}",
            stop=self._stop,
            jitter=self.ruler.jitter,
        )
        while True:
            stopped, missed = ticker.wait_next()
            if stopped:
                return
            if missed:
                self._m_missed.inc(missed)
            self.eval_once(self.ruler.clock())

    # -- one evaluation pass (the testable seam, like scrape_once) --

    def eval_once(self, now_nanos: int) -> list[dict]:
        """Evaluate every rule in file order at ``now_nanos``; returns the
        notification events emitted this pass. Never raises — a bad rule
        is counted and recorded in its health entry, and the rest of the
        group still evaluates."""
        t0 = time.perf_counter()
        engine = self.ruler.engine_for(self.group.namespace)
        events: list[dict] = []
        state_changed = False
        for rule in self.group.rules:
            name = self._rule_name(rule)
            health = self.health[name]
            r0 = time.perf_counter()
            try:
                rows = self._rows(engine.query_instant(rule.expr, now_nanos))
                if isinstance(rule, RecordingRule):
                    self._record(rule, rows, now_nanos)
                else:
                    st = self.states[rule.alert]
                    with self._state_lock:
                        before = st.to_dict()
                        transitions = st.evaluate(rule, rows, now_nanos)
                        changed = bool(transitions) or st.to_dict() != before
                    events.extend(
                        alert_event(t.status, t.alert) for t in transitions
                    )
                    if changed:
                        state_changed = True
                health["health"] = "ok"
                health["lastError"] = None
            except Exception as exc:
                self._m_failures.inc()
                health["health"] = "err"
                health["lastError"] = f"{type(exc).__name__}: {exc}"
            health["lastEvaluationUnixNanos"] = now_nanos
            health["evaluationTime"] = time.perf_counter() - r0
        pending = firing = 0
        with self._state_lock:
            for st in self.states.values():
                p, f = st.counts()
                pending += p
                firing += f
        self._g_pending.set(float(pending))
        self._g_firing.set(float(firing))
        self._g_active.set(float(pending + firing))
        if events:
            self.ruler.dispatch(events)
        if state_changed or events:
            self.checkpoint(now_nanos)
        self._m_eval.observe(time.perf_counter() - t0)
        return events

    @staticmethod
    def _rows(result) -> list:
        """Engine Result → instant vector rows [(labels dict, value)];
        NaN rows (comparison filtered, no data in lookback) drop out."""
        import math

        import numpy as np

        vals = np.asarray(result.values)
        rows = []
        for i, meta in enumerate(result.metas):
            v = float(vals[i, -1]) if vals.size else float("nan")
            if math.isnan(v):
                continue
            labels = {
                k.decode("utf-8", "replace"): val.decode("utf-8", "replace")
                for k, val in meta.tags
            }
            rows.append((labels, v))
        return rows

    def _record(self, rule, rows: list, now_nanos: int) -> None:
        """Write a recording rule's instant vector back through the
        normal tagged write path as series named ``rule.record``."""
        if not rows:
            return
        entries = []
        for labels, value in rows:
            tags = {k: v for k, v in labels.items() if k != "__name__"}
            tags.update(rule.labels)
            tags["__name__"] = rule.record
            entries.append((make_tags(tags), now_nanos, value, 1))
        self.ruler.ensure_namespace(self.group.namespace)
        with ruler_writer():
            errs = self.ruler.db.write_tagged_batch(
                self.group.namespace, entries
            )
        failed = sum(1 for e in errs if e)
        if failed:
            raise RuntimeError(
                f"recording rule {rule.record!r}: {failed}/{len(entries)} "
                f"writes failed (first: {next(e for e in errs if e)})"
            )
        self._m_samples.inc(len(entries))

    # -- KV checkpoint (restart/failover durability) --

    def checkpoint(self, now_nanos: int) -> bool:
        """Persist this group's alert state to KV; False (and a loud
        counter tick) when the KV is unreachable — evaluation carries on
        from memory either way."""
        if self.ruler.kv is None or not self.states:
            return True
        with self._state_lock:
            rules_snap = {
                name: st.to_dict() for name, st in self.states.items()
            }
        snap = {"checkpointUnixNanos": now_nanos, "rules": rules_snap}
        if snap["rules"] == self._last_checkpoint:
            return True
        try:
            self.ruler.kv.set(STATE_KEY_PREFIX + self.group.name, snap)
        except Exception:
            self.ruler._m_checkpoint_failures.inc()
            return False
        self._last_checkpoint = snap["rules"]
        return True

    def restore(self, prior: "GroupRunner" = None) -> None:
        """Adopt alert state: from the prior in-memory runner on a live
        ruleset reload, else from the KV checkpoint (coordinator restart
        / leader change) — either way ``for:`` clocks and already-fired
        instances carry over, so nothing re-fires or resets."""
        if prior is not None:
            # deep-copy (serialize round-trip) rather than alias: the
            # prior runner's eval thread can outlive its stop() join
            # timeout on a slow query, and two evaluators mutating the
            # SAME ActiveAlert objects under different locks would tear
            # state — a lingering thread now only touches its own copy
            with prior._state_lock:
                carried = {
                    name: st.to_dict() for name, st in prior.states.items()
                }
                self._last_checkpoint = prior._last_checkpoint
            for name, raw in carried.items():
                if name in self.states:
                    self.states[name] = AlertRuleState.from_dict(raw)
            return
        if self.ruler.kv is None:
            return
        try:
            vv = self.ruler.kv.get(STATE_KEY_PREFIX + self.group.name)
        except Exception:
            self.ruler._m_checkpoint_failures.inc()
            return
        if vv is None or not isinstance(vv.value, dict):
            return
        rules = vv.value.get("rules", {})
        for name, raw in rules.items():
            if name in self.states:
                self.states[name] = AlertRuleState.from_dict(raw)
        self._last_checkpoint = {
            name: st.to_dict() for name, st in self.states.items()
        }

    # -- HTTP projections --

    def rule_dicts(self) -> list[dict]:
        out = []
        for rule in self.group.rules:
            name = self._rule_name(rule)
            h = self.health[name]
            base = {
                "name": name,
                "query": rule.expr,
                "health": h["health"],
                "lastError": h["lastError"],
                "lastEvaluation": h["lastEvaluationUnixNanos"] / 1e9,
                "evaluationTime": h["evaluationTime"],
                "labels": dict(rule.labels),
            }
            if isinstance(rule, RecordingRule):
                base["type"] = "recording"
            else:
                st = self.states[rule.alert]
                with self._state_lock:
                    pending, firing = st.counts()
                    alerts = self._alert_dicts(st)
                base.update(
                    type="alerting",
                    duration=rule.for_secs,
                    annotations=dict(rule.annotations),
                    state=(
                        "firing" if firing else
                        "pending" if pending else "inactive"
                    ),
                    alerts=alerts,
                )
            out.append(base)
        return out

    def alert_dicts(self) -> list[dict]:
        """Locked snapshot of every rule's active alert instances."""
        with self._state_lock:
            return [
                row
                for st in self.states.values()
                for row in self._alert_dicts(st)
            ]

    @staticmethod
    def _alert_dicts(st: AlertRuleState) -> list[dict]:
        """Caller holds ``_state_lock``."""
        return [
            {
                "labels": dict(a.labels),
                "annotations": dict(a.annotations),
                "state": a.state,
                "activeAt": a.active_at_nanos / 1e9,
                "value": a.value,
            }
            for a in st.active.values()
        ]


class Ruler:
    """The per-coordinator rule engine: owns the group runners, the KV
    ruleset watch, and the notifier fan-out.

    ``engine_for(namespace)`` and ``db`` are the coordinator's existing
    query/write surfaces; ``kv`` may be None (standalone coordinator: no
    shared ruleset, no durable checkpoints — still evaluates);
    ``ensure_namespace(ns)`` is the coordinator hook that creates the
    reserved namespace on demand; ``clock`` returns data-timestamp nanos
    (injectable for the lifecycle tests)."""

    def __init__(
        self,
        engine_for,
        db,
        kv=None,
        notifiers=None,
        instance: str = "",
        default_namespace: str = "default",
        ensure_namespace=None,
        clock=None,
        jitter: bool = True,
    ) -> None:
        self.engine_for = engine_for
        self.db = db
        self.kv = kv
        self.log_notifier = LogNotifier()
        self.notifiers = [self.log_notifier] + list(notifiers or ())
        self.instance = instance
        self.default_namespace = default_namespace
        self._ensure_namespace = ensure_namespace
        self.clock = clock or time.time_ns
        self.jitter = jitter
        self._lock = threading.Lock()
        self._runners: dict[str, GroupRunner] = {}
        self._started = False
        self._ruleset_version = 0
        self._unsub = None
        self._ensured: set = set()
        self._m_checkpoint_failures = METRICS.counter(
            "ruler_checkpoint_failures_total",
            "ruler KV operations (alert-state checkpoints, ruleset "
            "mirror/watch) dropped because the KV store was unreachable "
            "— evaluation continues from memory, loudly; a restart "
            "during a nonzero streak may reset for: clocks",
        )
        self._m_reloads = METRICS.counter(
            "ruler_ruleset_reloads_total",
            "ruleset (re)loads applied from the KV mirror or a file",
        )
        self._m_reload_errors = METRICS.counter(
            "ruler_ruleset_reload_errors_total",
            "ruleset updates rejected by validation (the previous "
            "ruleset keeps running)",
        )
        self._m_notifications = METRICS.counter(
            "ruler_notifications_total", "alert events handed to notifiers"
        )
        self._m_notification_failures = METRICS.counter(
            "ruler_notification_failures_total",
            "notifier deliveries that failed (per notifier per batch)",
        )

    # -- namespace hook --

    def ensure_namespace(self, ns: str) -> None:
        if ns in self._ensured:
            return
        if self._ensure_namespace is not None and is_reserved(ns):
            self._ensure_namespace(ns)
        self._ensured.add(ns)

    # -- ruleset management --

    def publish(self, spec: dict) -> int:
        """Validate + mirror a ruleset spec into KV (all coordinators
        pick it up via their watch), falling back to a direct local load
        when there is no KV. Returns the ruleset version."""
        groups = groups_from_spec(spec, self.default_namespace)
        if self.kv is None:
            self._apply(groups, version=self._ruleset_version + 1)
            return self._ruleset_version
        try:
            version = RulerStore(self.kv).mirror(groups_to_spec(groups))
        except Exception:
            # dead control plane at start: run the file's rules anyway —
            # alerting from local state beats not alerting; counted below
            self._m_checkpoint_failures.inc()
            self._apply(groups, version=self._ruleset_version + 1)
            return self._ruleset_version
        # apply OUR spec under the version mirror() assigned it (a fresh
        # get() here could race a concurrent publisher and pin ITS version
        # number onto OUR groups, wedging the watch's staleness check);
        # if someone else published a newer version meanwhile, the watch
        # delivers it and _on_ruleset supersedes this apply
        self._apply(groups, version=version)
        return self._ruleset_version

    def _on_ruleset(self, vv) -> None:
        """KV watch callback: another coordinator (or our own mirror)
        published a ruleset version."""
        value = getattr(vv, "value", None)
        if not isinstance(value, dict):
            return
        version = int(value.get("version", 0))
        with self._lock:
            # <= not ==: watch callbacks fire outside the KV store lock,
            # so deliveries can arrive out of order — a late v4 after v5
            # must not downgrade the live ruleset
            if version <= self._ruleset_version:
                return
        try:
            groups = groups_from_spec(value, self.default_namespace)
        except Exception:
            self._m_reload_errors.inc()
            return
        self._apply(groups, version=version)

    def _apply(self, groups, version: int) -> None:
        """Swap in a validated group list: stop removed/changed runners,
        carry alert state across by group+rule name, start the rest."""
        with self._lock:
            if self._runners and version <= self._ruleset_version:
                return  # stale/duplicate apply (the watch already won)
            old = self._runners
            new: dict[str, GroupRunner] = {}
            for g in groups:
                prior = old.get(g.name)
                if prior is not None and prior.group == g:
                    # unchanged group: keep the live runner untouched
                    new[g.name] = prior
                    continue
                # changed group: carry the prior runner's in-memory state;
                # brand-new group (restart/failover): restore falls back
                # to the durable KV checkpoint
                runner = GroupRunner(g, self)
                runner.restore(prior=prior)
                new[g.name] = runner
            self._runners = new
            self._ruleset_version = version
            started = self._started
            stale = [
                r for name, r in old.items()
                if new.get(name) is not r
            ]
        for r in stale:
            r.stop()
        # groups REMOVED from the ruleset take their durable checkpoint
        # with them — a future group reusing the name must not resurrect
        # obsolete alert state (spurious 'resolved' notifications for
        # alerts that never fired in the new incarnation)
        if self.kv is not None:
            for name in set(old) - set(new):
                try:
                    self.kv.delete(STATE_KEY_PREFIX + name)
                except Exception:
                    self._m_checkpoint_failures.inc()
        if started:
            for r in new.values():
                r.start()
        self._m_reloads.inc()

    # -- lifecycle --

    def start(self) -> "Ruler":
        with self._lock:
            if self._started:
                return self
            self._started = True
            runners = list(self._runners.values())
        if self.kv is not None and self._unsub is None:
            try:
                self._unsub = self.kv.watch(RULESET_KEY, self._on_ruleset)
            except Exception:
                # no live watch on a dead KV: the local ruleset still runs
                self._m_checkpoint_failures.inc()
        for r in runners:
            r.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._started = False
            runners = list(self._runners.values())
            unsub, self._unsub = self._unsub, None
        if unsub is not None:
            try:
                unsub()
            except Exception:
                # m3lint: disable=M3L007 -- best-effort watch teardown on shutdown
                pass
        for r in runners:
            r.stop()

    # -- notifications --

    def dispatch(self, events: list[dict]) -> None:
        self._m_notifications.inc(len(events))
        for notifier in self.notifiers:
            try:
                ok = notifier.notify(list(events))
            except Exception:
                ok = False
            if not ok:
                self._m_notification_failures.inc()

    # -- HTTP projections (Prometheus rules/alerts API shapes) --

    def runners(self) -> list[GroupRunner]:
        with self._lock:
            return list(self._runners.values())

    def rules_dict(self) -> dict:
        groups = [
            {
                "name": r.group.name,
                "namespace": r.group.namespace,
                "interval": r.group.interval_secs,
                "rules": r.rule_dicts(),
            }
            for r in self.runners()
        ]
        return {"groups": groups, "rulesetVersion": self._ruleset_version}

    def alerts_dict(self) -> dict:
        alerts = []
        for r in self.runners():
            alerts.extend(r.alert_dicts())
        return {"alerts": alerts}
