"""Rule model: recording + alerting rules in named groups.

Mirrors the Prometheus rule-file schema (rule_group / recording_rule /
alerting_rule) so existing rule files translate line for line, plus one
m3-ism: every group names the storage ``namespace`` its expressions
evaluate over (the coordinator routes it through its per-namespace
engine cache, so ``namespace: _m3tpu`` rules run over the fleet's own
stored telemetry — the self-monitoring loop this subsystem closes).

Validation happens at load time, loudly: a rule file with an unparsable
PromQL expression, a non-colon recording name (the ``level:metric:op``
convention is ENFORCED here, not suggested — selfmon/convert.py and
m3lint M3L005 rely on colon-form names meaning "derived by the ruler"),
or a duplicate group name never makes it into the KV mirror.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..selfmon.convert import is_recorded_name

NANOS = 1_000_000_000

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)?$")
_DURATION_MULT = {
    "ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0, None: 1.0
}

# alert/recording names also label the ruler's own per-group metrics and
# the ALERTS-style output; keep them to the same grammar Prometheus does
_ALERT_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def parse_duration(v) -> float:
    """'30s' / '5m' / '1.5h' / bare number (seconds) → seconds."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    m = _DURATION_RE.match(str(v).strip())
    if m is None:
        raise ValueError(f"bad duration {v!r}")
    return float(m.group(1)) * _DURATION_MULT[m.group(2)]


def _str_map(d, what: str) -> dict:
    if d is None:
        return {}
    if not isinstance(d, dict):
        raise ValueError(f"{what} must be a mapping, got {type(d).__name__}")
    return {str(k): str(v) for k, v in d.items()}


@dataclass(frozen=True)
class RecordingRule:
    """``record: <level:metric:op>  expr: <promql>  labels: {...}`` —
    each evaluation writes the expression's instant vector back through
    the normal write path as series named ``record`` (input labels kept,
    ``labels`` overriding), under the ruler writer context."""

    record: str
    expr: str
    labels: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"record": self.record, "expr": self.expr}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


@dataclass(frozen=True)
class AlertRule:
    """``alert: <Name>  expr: <promql>  for: <duration>`` — the instant
    vector's series are the alert instances; each runs the
    inactive→pending→firing state machine (ruler/state.py) with
    ``for_secs`` of sustained truth required before firing. ``labels`` /
    ``annotations`` values support ``{{ $value }}`` and
    ``{{ $labels.x }}`` templating."""

    alert: str
    expr: str
    for_secs: float = 0.0
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out: dict = {"alert": self.alert, "expr": self.expr}
        if self.for_secs:
            out["for"] = self.for_secs
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        return out


@dataclass(frozen=True)
class RuleGroup:
    """A named set of rules evaluated together on one fixed-rate
    schedule, in file order. A recording rule's output reaches later
    rules through the normal ingest path, not a same-tick overlay:
    synchronously visible on an embedded local store, next-tick across a
    cluster session's quorum write."""

    name: str
    interval_secs: float
    namespace: str
    rules: tuple = ()

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "interval": self.interval_secs,
            "namespace": self.namespace,
            "rules": [r.to_dict() for r in self.rules],
        }


def rule_from_dict(d: dict):
    if not isinstance(d, dict):
        raise ValueError(f"rule must be a mapping, got {type(d).__name__}")
    expr = d.get("expr")
    if not expr or not isinstance(expr, str):
        raise ValueError(f"rule {d!r} has no expr")
    # parse at load time: a bad expression must fail the file/KV update,
    # not every future evaluation tick
    from ..query.promql import parse as parse_promql

    parse_promql(expr)
    if "record" in d and "alert" in d:
        raise ValueError(f"rule {d!r} sets both record and alert")
    if "record" in d:
        record = str(d["record"])
        if not is_recorded_name(record):
            raise ValueError(
                f"recording rule name {record!r} must follow the "
                "level:metric:operation colon convention "
                "(selfmon/convert.RECORDED_NAME_RE)"
            )
        return RecordingRule(
            record=record, expr=expr, labels=_str_map(d.get("labels"), "labels")
        )
    if "alert" in d:
        name = str(d["alert"])
        if not _ALERT_NAME_RE.match(name):
            raise ValueError(f"bad alert name {name!r}")
        return AlertRule(
            alert=name,
            expr=expr,
            for_secs=parse_duration(d.get("for", 0)),
            labels=_str_map(d.get("labels"), "labels"),
            annotations=_str_map(d.get("annotations"), "annotations"),
        )
    raise ValueError(f"rule {d!r} is neither a record nor an alert rule")


def group_from_dict(d: dict, default_namespace: str = "default") -> RuleGroup:
    name = d.get("name")
    if not name:
        raise ValueError(f"rule group {d!r} has no name")
    interval = parse_duration(d.get("interval", 30))
    if interval <= 0:
        raise ValueError(f"group {name!r}: interval must be positive")
    # recording rules write derived series back into m3tsz second-unit
    # storage: a sub-second eval interval collapses consecutive recorded
    # samples onto one stored timestamp and flattens every rate() built
    # on them — reject at load, not at the thousandth silent flat eval
    from ..utils.schedule import check_telemetry_interval

    check_telemetry_interval(interval, f"rule group {name!r}")
    return RuleGroup(
        name=str(name),
        interval_secs=interval,
        namespace=str(d.get("namespace", default_namespace)),
        rules=tuple(rule_from_dict(r) for r in d.get("rules", ())),
    )


def groups_from_spec(spec: dict, default_namespace: str = "default") -> list:
    """A parsed rules file / KV ruleset value → validated RuleGroups."""
    if not isinstance(spec, dict):
        raise ValueError("rules spec must be a mapping with a 'groups' list")
    groups = [
        group_from_dict(g, default_namespace) for g in spec.get("groups", ())
    ]
    seen: set = set()
    for g in groups:
        if g.name in seen:
            raise ValueError(f"duplicate rule group name {g.name!r}")
        seen.add(g.name)
    return groups


def groups_to_spec(groups) -> dict:
    """Inverse of :func:`groups_from_spec` — the wire-safe dict form the
    KV mirror stores (JSON-clean: plain dicts/lists/strings/floats)."""
    return {"groups": [g.to_dict() for g in groups]}


def load_rules_file(path: str, default_namespace: str = "default") -> list:
    """Load + validate a rule file (YAML or JSON — JSON is a YAML subset,
    so one loader covers both, same as utils/config.py)."""
    import yaml

    with open(path, encoding="utf-8") as f:
        spec = yaml.safe_load(f.read()) or {}
    return groups_from_spec(spec, default_namespace)
