"""Built-in default rule groups: fleet invariants every deployment wants.

The storage durability group watches
``m3tpu_storage_corruption_total`` — the counter every corruption
detection path feeds (verify-on-read, the background scrubber, repair) —
through the same selfmon -> ruler path as user rules: the counter is
self-scraped into ``_m3tpu`` storage, the recordings below derive
colon-form burn-rate series from it, and the alerts read ONLY the
recordings. The target rate for corruption is zero, so "burn" is any
positive rate; the multi-window AND still buys the usual shape — the
short window gives reaction time and resolves the alert once detection
stops, the long window keeps the slow tier open while the incident is
triaged.

Groups are compiled from plain dicts through
:func:`~m3_tpu.ruler.rules.groups_from_spec`, so they get exactly the
load-time validation (PromQL parse, colon-name enforcement, interval
floor) a rules file would. :func:`default_rule_spec` exposes the dict
form for tooling; :func:`default_groups` the validated RuleGroups the
coordinator merges in (file groups win on name collision —
``--no-default-rules`` opts out entirely).
"""

from __future__ import annotations

from ..selfmon.guard import RESERVED_NS

#: reserved like SLO_GROUP: a rules file must not redefine it silently —
#: the coordinator skips the default when a file group takes the name
DURABILITY_GROUP = "storage_durability_default"

# (window token, recorded name) pairs — fast tier (5m/1h) pages, slow
# tier (6h/3d) tickets, mirroring slo.spec's default burn windows
_WINDOWS = ("5m", "1h", "6h", "3d")


def corruption_record_name(window: str) -> str:
    return f"storage:corruption:rate{window}"


def _corruption_expr(window: str) -> str:
    # or vector(0): a fleet with zero corruption must still record 0 —
    # the alert conditions below read the recording, and a no-data
    # recording would leave lookback resurrecting the last sample
    return (
        f"sum(rate(m3tpu_storage_corruption_total[{window}])) or vector(0)"
    )


def _burn_alert(name: str, short: str, long_: str, severity: str) -> dict:
    return {
        "alert": name,
        # multi-window AND over the recordings: corruption's error budget
        # is zero, so any positive detection rate is over-budget burn
        "expr": (
            f"({corruption_record_name(short)} > 0)"
            f" and ({corruption_record_name(long_)} > 0)"
        ),
        "for": 0,
        "labels": {
            "objective": "storage_durability",
            "severity": severity,
            "window": f"{short}/{long_}",
            "service": "dbnode",
        },
        "annotations": {
            "summary": (
                "storage corruption detected: "
                f"{{{{ $value }}}} corrupt files/sec over {short} "
                f"(sustained over {long_})"
            ),
        },
    }


def default_rule_spec(interval_secs: float = 30.0) -> dict:
    """The default groups as a rules-file-shaped dict (the
    ``groups_from_spec`` input schema, so it round-trips through the KV
    ruleset mirror like any file-sourced group)."""
    rules = [
        {
            "record": corruption_record_name(w),
            "expr": _corruption_expr(w),
            "labels": {"objective": "storage_durability"},
        }
        for w in _WINDOWS
    ]
    rules.append(
        _burn_alert("StorageDurabilityFastBurn", "5m", "1h", "page")
    )
    rules.append(
        _burn_alert("StorageDurabilitySlowBurn", "6h", "3d", "ticket")
    )
    return {
        "groups": [
            {
                "name": DURABILITY_GROUP,
                "interval": interval_secs,
                "namespace": RESERVED_NS,
                "rules": rules,
            }
        ]
    }


def default_groups(interval_secs: float = 30.0) -> list:
    """The validated default RuleGroups (same loader as rule files)."""
    from .rules import groups_from_spec

    return groups_from_spec(default_rule_spec(interval_secs), RESERVED_NS)


def default_durability_slo_spec() -> dict:
    """A matching SLO-spec fragment (``slo.spec.spec_from_dict`` schema):
    the probe-driven durability objective whose compiled rules complement
    the passive corruption-counter group above — spot-check reads prove
    bytes come back bit-identical, the counter group catches what the
    scrubber finds between probes. Merge into an ``--slo-config`` file or
    compile standalone."""
    return {
        "slos": [
            {
                "name": "storage_durability",
                "sli": "durability",
                "objective": 0.9999,
                "window": "1h",
                "service": "dbnode",
            }
        ],
        "eval_interval": 30,
        "probe_interval": 30,
    }
