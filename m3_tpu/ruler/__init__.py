"""Ruler: PromQL recording & alerting rules over stored namespaces —
the evaluation half of the self-monitoring loop (see ruler.py)."""

from .notify import LogNotifier, WebhookNotifier, alert_event
from .rules import (
    AlertRule,
    RecordingRule,
    RuleGroup,
    groups_from_spec,
    groups_to_spec,
    load_rules_file,
    parse_duration,
)
from .ruler import RULESET_KEY, STATE_KEY_PREFIX, GroupRunner, Ruler, RulerStore
from .state import (
    FIRING,
    INACTIVE,
    PENDING,
    ActiveAlert,
    AlertRuleState,
    Transition,
    render_template,
)

__all__ = [
    "AlertRule",
    "RecordingRule",
    "RuleGroup",
    "groups_from_spec",
    "groups_to_spec",
    "load_rules_file",
    "parse_duration",
    "Ruler",
    "RulerStore",
    "GroupRunner",
    "RULESET_KEY",
    "STATE_KEY_PREFIX",
    "LogNotifier",
    "WebhookNotifier",
    "alert_event",
    "ActiveAlert",
    "AlertRuleState",
    "Transition",
    "render_template",
    "INACTIVE",
    "PENDING",
    "FIRING",
]
