"""Device-side ingest: the write-path twin of the resident read pool.

``ColumnWriteBuffer`` (buffer.py) accumulates write batches into
per-shard ``(series_lane, slot)`` timestamp/value planes — ring-buffered
per block window, mirrored to device with the resident pool's
donation/epoch discipline — so seal hands CLEAN lanes straight to the
batched m3tsz encode kernel (ops/encode.py) and blocks are born
resident (resident/pool.admit_block_device) without a host encode or an
admission upload.
"""

from .buffer import ColumnWriteBuffer, IngestOptions, SealLane

__all__ = ["ColumnWriteBuffer", "IngestOptions", "SealLane"]
