"""Per-shard device column write buffer: the ingest half of residency.

Write batches append into per-block-window FRAMES of ``(series_lane,
slot)`` columns — timestamps, values, and a per-lane cleanliness flag —
host-staged as numpy and mirrored to device planes in batched syncs (one
scatter per sync, donation/epoch discipline borrowed from the resident
pool: a sync donates the plane buffers to the scatter when no reader
lease is active, else falls back to the functional copy).

The frames ring over block windows: at most ``IngestOptions.windows``
windows are open at once; a write landing outside every open window (too
old after its window sealed, or too new while the ring is full of
unsealed windows) SPILLS to the host path — counted by reason, never
silent. Likewise a full lane table ("lanes") or a full lane ("slots").
Spilled rows still live in the shard's ``SeriesBuffer`` (the read-path
truth, which every write also lands in); a spill just means that lane
seals through the host codec instead of the device encode kernel.

A lane is CLEAN while its appends arrive strictly time-ascending (no
duplicates, no out-of-order rows). Clean lanes ARE the merged point set
— sorted, unique — so seal feeds them to ops/encode.py without the
sort/dedup merge pass; one out-of-order append marks the lane dirty for
the window and seal falls back to the SeriesBuffer merge for that
series (counted).

Metric family: ``m3tpu_ingest_*`` (label policy M3L005 — the spill
counter's only label is ``reason``, a closed enum; series ids never
label metrics).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from ..utils.instrument import DEFAULT as METRICS

SPILL_REASONS = ("window", "lanes", "slots")


@dataclass(frozen=True)
class IngestOptions:
    """Sizing for one shard's column write buffer."""

    enabled: bool = True
    lanes: int = 1024  # series lanes per block-window frame
    slots: int = 1024  # samples per lane per window
    windows: int = 2  # block windows open at once (ring depth)
    # staged appends that trigger a device-plane sync; the seal path
    # syncs explicitly, so this only bounds aggregation-feed staleness
    sync_batch: int = 8192

    def __post_init__(self):
        if self.lanes < 1 or self.slots < 1 or self.windows < 1:
            raise ValueError("lanes, slots and windows must be positive")


class SealLane(dict):
    """One sealed clean lane: ``sid``, ``times``, ``values``, ``units``
    column views (dict for tooling-friendly dumps)."""

    __getattr__ = dict.__getitem__


class _Frame:
    """Host staging for one open block window."""

    __slots__ = (
        "block_start", "lane_of", "sids", "times", "values", "units",
        "counts", "clean", "last_time", "synced",
    )

    def __init__(self, block_start: int, lanes: int, slots: int) -> None:
        self.block_start = block_start
        self.lane_of: dict[bytes, int] = {}
        self.sids: list[bytes] = []
        self.times = np.zeros((lanes, slots), np.int64)
        self.values = np.zeros((lanes, slots), np.float64)
        self.units = np.zeros((lanes, slots), np.int8)
        self.counts = np.zeros(lanes, np.int32)
        self.clean = np.ones(lanes, bool)
        self.last_time = np.full(lanes, np.iinfo(np.int64).min, np.int64)
        # per-lane slot count already mirrored to the device planes
        self.synced = np.zeros(lanes, np.int32)


class ColumnWriteBuffer:
    """Device column write buffer for ONE shard (ring of `_Frame`s)."""

    def __init__(
        self, options: IngestOptions, block_size_nanos: int, registry=None
    ) -> None:
        self.options = options
        self.block_size_nanos = int(block_size_nanos)
        self._lock = threading.Lock()
        self._frames: dict[int, _Frame] = {}  # block_start -> frame
        # device planes per open window, built lazily at first sync:
        # block_start -> dict of uint32[lanes, slots] planes + counts
        self._planes: dict[int, dict] = {}
        self._staged_since_sync = 0
        # donation/epoch discipline (resident/pool.py): aggregation
        # readers lease the planes across their reductions; a sync
        # donates the plane buffers to its scatter only when no lease
        # is active, and new leases fence on the in-flight donation
        self._leases = 0
        self._donating = False
        self._fence = threading.Condition(self._lock)
        self.epoch = 0
        self.appends = 0
        self.spills = dict.fromkeys(SPILL_REASONS, 0)
        self.device_syncs = 0
        self.device_sync_bytes = 0
        self.sealed_clean_lanes = 0
        self.dirty_lane_fallbacks = 0
        reg = registry or METRICS
        self._m_appends = reg.counter(
            "ingest_appends_total", "rows accepted into the column write buffer"
        )
        self._m_spilled = {
            r: reg.counter(
                "ingest_spilled_total",
                "rows the column buffer could not take, by reason — the "
                "row still lives in the host SeriesBuffer and its lane "
                "seals through the host codec (window: outside every "
                "open ring window; lanes: lane table full; slots: lane "
                "at capacity)",
                labels={"reason": r},
            )
            for r in SPILL_REASONS
        }
        self._m_syncs = reg.counter(
            "ingest_device_syncs_total",
            "batched column-plane scatters (host staged tail -> device)",
        )
        self._m_sync_bytes = reg.counter(
            "ingest_device_sync_bytes_total",
            "bytes moved by column-plane syncs — the write path's ONLY "
            "host->device traffic; admission of the encoded pages moves "
            "zero (resident_upload_bytes_total stays flat on device seals)",
        )
        self._m_sealed = reg.counter(
            "ingest_sealed_clean_lanes_total",
            "lanes sealed clean: sorted/unique columns handed straight "
            "to the device encode kernel, no merge pass",
        )
        self._m_dirty = reg.counter(
            "ingest_dirty_lane_fallbacks_total",
            "lanes that went out-of-order or duplicated in-window: seal "
            "falls back to the SeriesBuffer merge for them",
        )

    # ---------- writes ----------

    def append_batch(self, sids: list, times, values, units) -> np.ndarray:
        """Append a write batch; returns a bool mask of ACCEPTED rows
        (rejected rows are spilled-by-reason; callers need no action —
        the SeriesBuffer already holds every row).

        Rows are grouped per (window, lane) so the host staging cost is
        one numpy slice assignment per group, not per row."""
        times = np.asarray(times, np.int64)
        values = np.asarray(values, np.float64)
        units = np.asarray(units, np.int8)
        n = len(times)
        accepted = np.zeros(n, bool)
        if not self.options.enabled or n == 0:
            return accepted
        bsz = self.block_size_nanos
        o = self.options
        with self._lock:
            lo_bs = (int(times.min()) // bsz) * bsz
            hi_bs = (int(times.max()) // bsz) * bsz
            if lo_bs == hi_bs:  # whole batch in one window: no grouping
                frame = self._frame_locked(lo_bs, n)
                if frame is not None:
                    self._append_frame_locked(
                        frame, None, sids, times, values, units, accepted
                    )
            else:
                starts = (times // bsz) * bsz
                for bs in dict.fromkeys(starts.tolist()):  # arrival order
                    rows = np.nonzero(starts == bs)[0]
                    frame = self._frame_locked(bs, len(rows))
                    if frame is None:
                        continue
                    self._append_frame_locked(
                        frame,
                        rows,
                        [sids[i] for i in rows.tolist()],
                        times[rows],
                        values[rows],
                        units[rows],
                        accepted,
                    )
            got = int(accepted.sum())
            self.appends += got
            self._staged_since_sync += got
            self._m_appends.inc(got)
            want_sync = self._staged_since_sync >= o.sync_batch
        if want_sync:
            self.sync()
        return accepted

    def _frame_locked(self, bs: int, n_rows: int):
        frame = self._frames.get(bs)
        if frame is None:
            if len(self._frames) >= self.options.windows:
                self._spill_locked("window", n_rows)
                return None
            frame = _Frame(bs, self.options.lanes, self.options.slots)
            self._frames[bs] = frame
        return frame

    def _append_frame_locked(
        self, frame, rows, sids, times, values, units, accepted
    ) -> None:
        """Stage one window's slice of a batch (``rows is None`` = the
        whole batch): lane lookup is the only per-row Python work (a
        C-level ``map`` over the sid list); slot assignment, the column
        scatters, and the cleanliness bookkeeping are grouped numpy
        ops."""
        o = self.options
        lane_of = frame.lane_of
        raw = list(map(lane_of.get, sids))
        if None in raw:  # new sids: assign lanes in arrival order
            for j, lane in enumerate(raw):
                if lane is None:
                    sid = sids[j]
                    lane = lane_of.get(sid)
                    if lane is None:
                        if len(frame.sids) >= o.lanes:
                            raw[j] = -1
                            continue
                        lane = len(frame.sids)
                        lane_of[sid] = lane
                        frame.sids.append(sid)
                    raw[j] = lane
            lanes_idx = np.asarray(raw, np.int64)
            full = lanes_idx < 0
            if full.any():
                self._spill_locked("lanes", int(full.sum()))
                keep = ~full
                lanes_idx = lanes_idx[keep]
                rows = np.nonzero(keep)[0] if rows is None else rows[keep]
                times, values, units = times[keep], values[keep], units[keep]
                if not len(lanes_idx):
                    return
        else:
            lanes_idx = np.asarray(raw, np.int64)
        # stable sort by lane keeps arrival order within each lane, so
        # slot positions and the dirty check see the original sequence
        order = np.argsort(lanes_idx, kind="stable")
        ls = lanes_idx[order]
        t, v, u = times[order], values[order], units[order]
        first = np.nonzero(np.r_[True, ls[1:] != ls[:-1]])[0]
        cnt = np.diff(np.append(first, len(ls)))
        cum = np.arange(len(ls)) - np.repeat(first, cnt)
        slot = frame.counts[ls].astype(np.int64) + cum
        fit = slot < o.slots
        if not fit.all():
            self._spill_locked("slots", int((~fit).sum()))
            # overflow is always a per-lane TAIL (slots ascend within a
            # lane), so groups stay contiguous after the filter
            order, ls, t, v, u, slot = (
                order[fit], ls[fit], t[fit], v[fit], u[fit], slot[fit]
            )
            if not len(ls):
                return
            first = np.nonzero(np.r_[True, ls[1:] != ls[:-1]])[0]
            cnt = np.diff(np.append(first, len(ls)))
        uniq = ls[first]
        frame.times[ls, slot] = t
        frame.values[ls, slot] = v
        frame.units[ls, slot] = u
        frame.counts[uniq] += cnt.astype(np.int32)
        prev = np.empty_like(t)
        prev[1:] = t[:-1]
        prev[first] = frame.last_time[uniq]
        viol = t <= prev
        if viol.any():
            frame.clean[np.unique(ls[viol])] = False
        frame.last_time[uniq] = np.maximum(
            frame.last_time[uniq], np.maximum.reduceat(t, first)
        )
        accepted[order if rows is None else rows[order]] = True

    def append(self, sid: bytes, t_nanos: int, value: float, unit: int) -> bool:
        return bool(self.append_batch([sid], [t_nanos], [value], [unit])[0])

    def _spill_locked(self, reason: str, count: int = 1) -> None:
        self.spills[reason] += count
        self._m_spilled[reason].inc(count)

    # ---------- device planes (aggregation feed) ----------

    def sync(self) -> int:
        """Mirror the staged column tail to the device planes — one
        scatter per open window, donated when no lease is active.
        Returns rows moved."""
        import jax
        import jax.numpy as jnp

        moved = 0
        with self._lock:
            work = []
            for bs, frame in self._frames.items():
                dirty = np.nonzero(frame.synced < frame.counts)[0]
                if len(dirty):
                    work.append((bs, frame, dirty))
            if not work:
                self._staged_since_sync = 0
                return 0
            donate = self._leases == 0
            if donate:
                self._donating = True
        try:
            for bs, frame, dirty in work:
                planes = self._planes.get(bs)
                if planes is None:
                    o = self.options
                    planes = {
                        # ts_hi / ts_lo / val_hi / val_lo as one stacked
                        # tensor: the sync moves ONE host->device staging
                        # buffer and runs ONE scatter for all four
                        "cols": jnp.zeros(
                            (4, o.lanes, o.slots), jnp.uint32
                        ),
                        "counts": jnp.zeros(o.lanes, jnp.int32),
                    }
                # stage only the dirty slot TAIL — one rectangular tile
                # covering [lo, lo+w) across the dirty lanes, w and the
                # lane count padded to powers of two so the scatter jit
                # compiles O(log^2) variants, not one per shape. Padding
                # restages rows/slots already on device with identical
                # values, which keeps the duplicate-index scatter exact.
                o = self.options
                lo = int(frame.synced[dirty].min())
                hi = int(frame.counts[dirty].max())
                w = 1 << max(hi - lo - 1, 0).bit_length()
                w = min(w, o.slots)
                lo = min(lo, o.slots - w)
                nd = 1 << max(len(dirty) - 1, 0).bit_length()
                pad = np.concatenate(
                    [dirty, np.repeat(dirty[-1], nd - len(dirty))]
                )
                ts = frame.times[pad, lo:lo + w].view(np.uint64)
                vb = frame.values[pad, lo:lo + w].view(np.uint64)
                m32 = np.uint64(0xFFFFFFFF)
                host = np.stack(
                    [
                        (ts >> np.uint64(32)).astype(np.uint32),
                        (ts & m32).astype(np.uint32),
                        (vb >> np.uint64(32)).astype(np.uint32),
                        (vb & m32).astype(np.uint32),
                    ]
                )
                counts_host = frame.counts[pad].copy()
                # m3lint: disable=M3L010 -- sanctioned host->device staging: dirty host tiles must cross PCIe once per sync; a donation-to-infeed path (ROADMAP) would cut this copy
                idx = jax.device_put(pad.astype(np.int32))
                # m3lint: disable=M3L010 -- sanctioned host->device staging (same boundary as idx above)
                lo_dev = jax.device_put(np.int32(lo))
                # m3lint: disable=M3L010 -- sanctioned host->device staging (same boundary as idx above)
                staged = jax.device_put(host)
                # m3lint: disable=M3L010 -- sanctioned host->device staging (same boundary as idx above)
                staged_c = jax.device_put(counts_host)
                nbytes = host.nbytes + counts_host.nbytes
                scatter = _scatter_tile4_donate if donate else _scatter_tile4
                new_cols, new_counts = scatter(
                    planes["cols"], planes["counts"], idx, lo_dev,
                    staged, staged_c,
                )
                new = {"cols": new_cols, "counts": new_counts}
                moved += int(
                    (frame.counts[dirty] - frame.synced[dirty]).sum()
                )
                with self._lock:
                    self._planes[bs] = new
                    frame.synced[dirty] = frame.counts[dirty]
                    self.epoch += 1
                    self.device_syncs += 1
                    self.device_sync_bytes += nbytes
                self._m_syncs.inc()
                self._m_sync_bytes.inc(nbytes)
        finally:
            with self._lock:
                self._staged_since_sync = 0
                if donate:
                    self._donating = False
                    self._fence.notify_all()
        return moved

    def lease(self):
        """Context manager: hold the device planes stable across a
        reader's reductions (syncs downgrade to functional copies)."""
        return _Lease(self)

    def window_planes(self, block_start: int):
        """Device planes + lane sid list for one open window (the
        aggregation tier's feed), or None before the first sync."""
        with self._lock:
            planes = self._planes.get(block_start)
            frame = self._frames.get(block_start)
            if planes is None or frame is None:
                return None
            cols = planes["cols"]
            view = {
                "ts_hi": cols[0],
                "ts_lo": cols[1],
                "val_hi": cols[2],
                "val_lo": cols[3],
                "counts": planes["counts"],
            }
            return view, list(frame.sids)

    # ---------- seal ----------

    def seal_window(self, block_start: int):
        """Close one window and hand back its lanes: ``(clean, dirty)``
        where ``clean`` is a list of :class:`SealLane` (sorted, unique —
        encode-kernel ready) and ``dirty`` the sids that must seal
        through the SeriesBuffer merge. The frame and its device planes
        are released."""
        with self._lock:
            frame = self._frames.pop(block_start, None)
            self._planes.pop(block_start, None)
            if frame is None:
                return [], []
            clean: list[SealLane] = []
            dirty: list[bytes] = []
            for lane, sid in enumerate(frame.sids):
                c = int(frame.counts[lane])
                if frame.clean[lane]:
                    clean.append(
                        SealLane(
                            sid=sid,
                            times=frame.times[lane, :c].copy(),
                            values=frame.values[lane, :c].copy(),
                            units=frame.units[lane, :c].astype(np.int32),
                        )
                    )
                else:
                    dirty.append(sid)
            self.sealed_clean_lanes += len(clean)
            self.dirty_lane_fallbacks += len(dirty)
            self._m_sealed.inc(len(clean))
            self._m_dirty.inc(len(dirty))
            self.epoch += 1
            return clean, dirty

    def drop_window(self, block_start: int) -> None:
        """Release a window without sealing (retention expiry)."""
        with self._lock:
            self._frames.pop(block_start, None)
            self._planes.pop(block_start, None)

    def open_windows(self) -> list[int]:
        with self._lock:
            return sorted(self._frames)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.options.enabled,
                "open_windows": sorted(self._frames),
                "appends": self.appends,
                "spills": dict(self.spills),
                "device_syncs": self.device_syncs,
                "device_sync_bytes": self.device_sync_bytes,
                "sealed_clean_lanes": self.sealed_clean_lanes,
                "dirty_lane_fallbacks": self.dirty_lane_fallbacks,
                "epoch": self.epoch,
            }


class _Lease:
    def __init__(self, buf: ColumnWriteBuffer) -> None:
        self._buf = buf

    def __enter__(self):
        buf = self._buf
        with buf._lock:
            while buf._donating:
                buf._fence.wait()
            buf._leases += 1
        return self

    def __exit__(self, *exc):
        buf = self._buf
        with buf._lock:
            buf._leases -= 1
        return False


def _tile4_set(b, c, i, lo, s, sc):
    """One dispatch for a sync: scatter the stacked column tile AND the
    per-lane counts."""
    import jax.numpy as jnp

    cols = lo + jnp.arange(s.shape[2], dtype=jnp.int32)
    return b.at[:, i[:, None], cols[None, :]].set(s), c.at[i].set(sc)


def _scatter_tile4(b, c, idx, lo, staged, staged_c):
    global _TILE_JIT
    import jax

    if _TILE_JIT is None:
        _TILE_JIT = jax.jit(_tile4_set)
    return _TILE_JIT(b, c, idx, lo, staged, staged_c)


def _scatter_tile4_donate(b, c, idx, lo, staged, staged_c):
    global _TILE_DONATE_JIT
    import jax

    if _TILE_DONATE_JIT is None:
        _TILE_DONATE_JIT = jax.jit(_tile4_set, donate_argnums=(0, 1))
    return _TILE_DONATE_JIT(b, c, idx, lo, staged, staged_c)


_TILE_JIT = None
_TILE_DONATE_JIT = None
