"""HBM-resident compressed series store: a per-device paged M3TSZ pool.

The memory-manager analogue of a paged KV cache in an inference stack,
applied to the scan-and-aggregate hot path: instead of streaming sealed
blocks' compressed bytes over PCIe on every scan (PROFILE.md's 50M×720
row is transfer-bound at ~1.1s/chip), the m3tsz bytes stay RESIDENT in
device memory — at compressed density (~1–2.4B/datapoint) a v5e-8 holds
the whole 50M-series working set — and scans decode straight from HBM.

Layout:

- ONE flat device buffer ``uint32[num_pages, page_words]`` under a byte
  budget (``ResidentOptions.max_bytes``). Page 0 is RESERVED and always
  zero: gather plans pad short lanes with it, so a gathered lane's word
  row is bit-identical to BatchedSegments' zero padding.
- fixed-size pages handed out by a free-list allocator; a sealed block's
  stream occupies ``ceil(bits / page_bits)`` consecutive page-table slots
  (the pages themselves need not be contiguous — the device gather
  reassembles them).
- SIDE PLANES: a second paged device buffer
  ``uint32[num_side_pages, side_page_chunks, N_SIDE_PLANES]`` holding the
  per-CHUNK decoder-state side table (ops/chunked.py snapshot_stream:
  byte offset, prev_time/prev_delta/prev_float_bits/prev_xor/int_val
  carries, time unit, sig/mult, is_float, and the v2 fast-chunk
  classification flags) for every resident lane. Side pages live and die
  with their data pages, so the CHUNK-parallel kernels
  (ops/chunked.decode_chunked_lanes) read both stream bytes and chunk
  metadata straight from residency — no host rebuild of chunk tables, no
  T-step whole-stream scan.
- a HOST-side page table: ``BlockKey(namespace, shard, series_id,
  block_start, volume) -> ResidentEntry(pages, side_pages, num_bits,
  n_chunks, chunk_k, max_span_bits, ...)`` — everything plan assembly
  needs as small int vectors; the ~40B/chunk metadata itself never
  leaves the device after admission.

Admission is batched at flush/seal time (storage/database.py): all of a
fileset's streams stage into one host array and land in one device scatter
(``pool.at[idx].set(staged)``), not a device_put per series. Side tables
ride the fileset's persisted ``side`` file when the caller has one, and
are prescanned AT ADMISSION (native/m3tsz.cc batch prescan when built)
otherwise. Eviction is LRU under the byte budget plus explicit
invalidation through the same hooks as the decoded-block cache
(cache/invalidation.py) — a written-to, superseded, or retention-expired
block is never resident.

Updates are in-place WHEN SAFE, functional otherwise: scans take a read
LEASE (``read_lease()``) around plan+decode; an admission that finds no
active lease donates the page buffers into the scatter (XLA aliases
input to output — true in-place, no transient copy), briefly fencing new
leases; an admission racing an active scan falls back to the functional
``.at[].set`` copy so the scan's snapshot stays bit-stable. Either way a
scan sees the old epoch or the fully-published one, never a
half-scattered page (``inplace_admissions`` / ``copy_admissions`` count
which path ran).

Concurrency: the page table, free lists, and counters are guarded by one
lock; ``plan_chunked`` snapshots the device buffer
references under it (callers hold a read lease across use).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from ..cache.block_cache import BlockKey
from ..storage.fs import CHUNK_K
from ..utils.instrument import DEFAULT as METRICS
from .heat import ShardHeat


class ResidentPoolError(ValueError):
    """Corrupt page-table state detected (satellite contract: corrupt
    metadata must raise, never read out-of-bounds or silently wrap)."""


# Packed per-chunk side-plane layout (ops/sideplane.py): 10 uint32 words
# per chunk instead of the original 16 one-field-per-word planes — the
# ROADMAP item 1 residual, -37.5% side-plane HBM at constant information.
# The resident chunked scan's device assembly unpacks these columns
# (parallel/scan.py via sideplane.unpack_side_planes).
from ..ops.sideplane import SIDE_WORDS as N_SIDE_PLANES
from ..ops.sideplane import pack_side_rows

_M64 = (1 << 64) - 1


def side_rows_from_snaps(snaps: list, block_start: int) -> np.ndarray | None:
    """Per-chunk snapshot dicts (ops/chunked.snapshot_stream or
    storage/fs.FilesetReader.side_table) -> packed uint32[n_chunks,
    N_SIDE_PLANES] device side-plane rows, or None when a chunk's state
    overflows the packed ranges (the lane then decodes streamed)."""
    return pack_side_rows(snaps, block_start)


@dataclass
class ResidentOptions:
    """Knobs for the paged resident store (x/config-style dataclass).

    ``max_bytes`` is the device byte budget for the page buffer (0
    disables the pool). ``page_words`` is the page size in uint32 words
    (default 512 words = 2KiB — one typical 720-point m3tsz block fits in
    1–2 pages). ``max_lane_pages`` caps one (series, block) lane's page
    span: the device gather width is ``max over lanes`` of the page
    count, so one pathological stream must not widen every lane's row.
    ``side_bytes`` budgets the per-chunk side planes (0 = same as
    ``max_bytes``: an m3tsz chunk of K=32 records is ~48B of stream vs
    64B of snapshot, so metadata-for-chunk-parallelism is roughly 1:1);
    ``side_page_chunks`` is the side-page granularity in chunks."""

    enabled: bool = True
    max_bytes: int = 0
    page_words: int = 512
    max_lane_pages: int = 64
    side_bytes: int = 0  # 0 = derive from max_bytes
    side_page_chunks: int = 16
    namespaces: list = field(default_factory=list)

    def validate(self) -> None:
        from ..utils.config import ConfigError

        if self.max_bytes < 0:
            raise ConfigError("resident.max_bytes must be >= 0")
        if self.page_words <= 0:
            raise ConfigError("resident.page_words must be > 0")
        if self.max_lane_pages <= 0:
            raise ConfigError("resident.max_lane_pages must be > 0")
        if self.side_bytes < 0:
            raise ConfigError("resident.side_bytes must be >= 0")
        if self.side_page_chunks <= 0:
            raise ConfigError("resident.side_page_chunks must be > 0")
        # ``enabled`` needs >1 page in BOTH planes (page 0 is reserved):
        # a small positive budget would otherwise pass validation and
        # silently disable the whole pool — reject it loudly instead
        if 0 < self.max_bytes < 2 * self.page_bytes:
            raise ConfigError(
                f"resident.max_bytes {self.max_bytes} is under two pages "
                f"({2 * self.page_bytes}B) — 0 disables the pool explicitly"
            )
        if 0 < self.side_bytes < 2 * self.side_page_bytes:
            raise ConfigError(
                f"resident.side_bytes {self.side_bytes} is under two side "
                f"pages ({2 * self.side_page_bytes}B) — 0 derives from "
                "max_bytes"
            )

    @property
    def page_bytes(self) -> int:
        return self.page_words * 4

    @property
    def num_pages(self) -> int:
        # page 0 is the reserved zero page; it still costs budget
        return self.max_bytes // self.page_bytes

    @property
    def side_page_bytes(self) -> int:
        return self.side_page_chunks * N_SIDE_PLANES * 4

    @property
    def num_side_pages(self) -> int:
        # side page 0 is the reserved zero page (padding lanes' chunk
        # slots resolve to it, yielding all-zero side rows = done lanes)
        budget = self.side_bytes or self.max_bytes
        return budget // self.side_page_bytes


class ResidentEntry(NamedTuple):
    """Page-table row for one resident (series, block, volume) lane."""

    pages: tuple  # page indices, stream order
    num_bits: int  # valid bits of the m3tsz stream
    nbytes: int  # stream length in bytes (occupancy accounting)
    side_pages: tuple = ()  # side-plane page indices, chunk order
    n_chunks: int = 0  # chunks in the side table (0 = no side planes)
    chunk_k: int = 0  # records per chunk the side table was built with
    max_span_bits: int = 0  # widest chunk span (window sizing)


class AdmitResult(NamedTuple):
    admitted: int
    rejected_span: int  # lanes over the max_lane_pages span limit
    rejected_budget: int  # lanes that could not fit even after eviction
    complete: bool  # every non-empty stream of the group is now resident


class ResidentPool:
    """Paged device pool of sealed blocks' compressed streams + chunk
    side planes."""

    def __init__(self, options: ResidentOptions | None = None, registry=None) -> None:
        self.options = options or ResidentOptions()
        self._lock = threading.Lock()
        # serializes admissions (the device-words chain, functional OR
        # donated); held across staging + upload so the TABLE lock above
        # never is — writes and scans keep flowing while a flush's pages
        # upload
        self._upload_lock = threading.Lock()
        self._od: "OrderedDict[BlockKey, ResidentEntry]" = OrderedDict()
        # admitted-but-not-yet-uploaded entries: invisible to readers
        # (plan_chunked would otherwise serve pages the scatter hasn't
        # written); published into _od after the upload completes, unless
        # an invalidation dropped them mid-upload
        self._pending: dict[BlockKey, ResidentEntry] = {}
        self._by_series: dict[tuple, set] = {}
        self._by_block: dict[tuple, set] = {}
        # (namespace, shard, block_start, volume) groups whose every
        # non-empty stream is resident: lets the query router treat a
        # page-table miss as "series absent from that fileset" instead of
        # "not resident" — dropped conservatively on any eviction or
        # invalidation touching the group
        self._complete: set[tuple] = set()
        # filesets whose admission rejected a lane for page span: they
        # can NEVER become complete at this max_lane_pages, so
        # read-through re-admission skips them instead of re-uploading
        # the fileset on every streamed query (a volume bump is a new
        # tuple and gets retried)
        self._span_incomplete: set[tuple] = set()
        # filesets a READ-THROUGH re-admission rejected for budget,
        # mapped to (data, side) free-list sizes at that failure:
        # retrying is a guaranteed rejection (re-admissions never evict)
        # until pages free up past a watermark in whichever plane was
        # binding, so _maybe_readmit skips the disk re-read until then —
        # self-healing, no invalidation hook
        self._budget_deferred: dict[tuple, tuple[int, int]] = {}
        # bumps on _reset_locked so an in-flight admission knows its
        # pages were already reclaimed by the reset
        self._generation = 0
        # free lists: every page except the reserved zero pages
        self._free: list[int] = list(range(self.options.num_pages - 1, 0, -1))
        self._free_side: list[int] = list(
            range(self.options.num_side_pages - 1, 0, -1)
        )
        self._words = None  # device uint32[num_pages, page_words], lazy
        self._side = None  # device uint32[side_pages, spc, N_SIDE_PLANES], lazy
        self._resident_bytes = 0  # sum of entries' stream bytes
        # scan/admit epoch fence: scans hold a read lease across
        # plan+decode; an admission donates the buffers (true in-place)
        # only when no lease is active, fencing new leases for the
        # duration of the scatter
        self._leases = 0
        self._donating = False
        self._fence = threading.Condition(self._lock)
        self.epoch = 0  # bumps on every buffer publish
        self.admissions = 0
        self.rejections = 0
        self.evictions = 0
        self.invalidations = 0
        self.upload_bytes = 0
        self.readmissions = 0
        self.inplace_admissions = 0
        self.copy_admissions = 0
        self.side_pack_overflows = 0
        self.rebalance_evictions = 0
        self.device_admissions = 0
        self.ingest_side_stage_bytes = 0
        reg = registry or METRICS
        self._m_admissions = reg.counter(
            "resident_admissions_total", "blocks admitted to the resident pool"
        )
        self._m_rejections = reg.counter(
            "resident_rejections_total", "blocks rejected at admission"
        )
        self._m_evictions = reg.counter(
            "resident_evictions_total", "LRU/budget evictions from the pool"
        )
        self._m_invalidations = reg.counter(
            "resident_invalidations_total", "entries dropped by invalidation hooks"
        )
        self._m_upload = reg.counter(
            "resident_upload_bytes_total",
            "host->device block bytes uploaded at admission (warm resident "
            "scans move ZERO such bytes — tests assert on this counter)",
        )
        self._m_readmissions = reg.counter(
            "resident_readmissions_total",
            "read-through re-admissions: streamed-fallback hits on sealed "
            "complete blocks pulled back into the pool",
        )
        self._m_inplace = reg.counter(
            "resident_inplace_admissions_total",
            "admissions whose scatter donated the page buffers (true "
            "in-place, no transient copy)",
        )
        self._m_copy = reg.counter(
            "resident_copy_admissions_total",
            "admissions that fell back to the functional copy because a "
            "scan lease was active",
        )
        self._m_rebalance_evictions = reg.counter(
            "resident_rebalance_evictions_total",
            "entries evicted by the heat-driven budget rebalance after a "
            "topology change (over-share shards shed LRU-oldest first)",
        )
        self._m_side_overflow = reg.counter(
            "resident_side_pack_overflows_total",
            "lanes admitted WITHOUT side planes because a chunk snapshot "
            "overflowed the packed 10-word layout (the lane decodes "
            "streamed; pathological block span or sample gap)",
        )
        self._m_device_admissions = reg.counter(
            "ingest_device_admissions_total",
            "born-resident admissions: lanes whose pages were encoded on "
            "device and scattered device->device (zero stream-byte upload "
            "— resident_upload_bytes_total does not move for these)",
        )
        self._m_side_stage = reg.counter(
            "ingest_side_stage_bytes_total",
            "packed side-plane row bytes staged host->device at "
            "born-resident admission (O(40B/chunk) metadata; the DATA "
            "pages never cross PCIe)",
        )
        self._g_bytes = reg.gauge("resident_pool_bytes", "compressed bytes resident")
        self._g_pages = reg.gauge("resident_pool_pages", "pages in use (excl. zero page)")
        self._g_free = reg.gauge("resident_pool_free_pages", "pages on the free list")
        self._g_entries = reg.gauge("resident_pool_entries", "page-table entries")
        self._g_side_pages = reg.gauge(
            "resident_side_pages", "side-plane pages in use (excl. zero page)"
        )
        self._g_occupancy = reg.gauge(
            "resident_pool_occupancy_ratio",
            "pages in use / pages total — with the gauges above, the "
            "self-scrape pipeline stores these as series, so occupancy/"
            "admission/eviction timelines are one PromQL query",
        )
        # per-shard residency heat (heat.py): charged by the query
        # router's resident-vs-streamed decisions, exposed in stats()
        # and as m3tpu_resident_shard_* counters — the measured signal
        # ROADMAP item 5's shard rebalance keys off
        self.heat = ShardHeat(registry=reg)

    # ---------- device buffers ----------

    @property
    def enabled(self) -> bool:
        o = self.options
        return o.enabled and o.num_pages > 1 and o.num_side_pages > 1

    def _ensure_words(self):
        """Allocate the device page buffer on first admission (a node with
        the mode on but nothing sealed yet pays no device memory)."""
        if self._words is None:
            import jax.numpy as jnp

            self._words = jnp.zeros(
                (self.options.num_pages, self.options.page_words), jnp.uint32
            )
        return self._words

    def _ensure_side(self):
        if self._side is None:
            import jax.numpy as jnp

            o = self.options
            self._side = jnp.zeros(
                (o.num_side_pages, o.side_page_chunks, N_SIDE_PLANES), jnp.uint32
            )
        return self._side

    def device_bytes(self) -> int:
        """Bytes the page + side buffers actually hold on device RIGHT
        NOW — 0 until first admission (never forces the lazy allocation:
        memory accounting must observe, not cause). Buffer snapshots for
        scans go through plan_chunked under a read_lease() — an in-place
        admission donates (deletes) un-leased buffers."""
        with self._lock:
            n = int(self._words.nbytes) if self._words is not None else 0
            n += int(self._side.nbytes) if self._side is not None else 0
            return n

    # ---------- scan/admit epoch fencing ----------

    @contextmanager
    def read_lease(self):
        """Scan-side fence: while any lease is held, admissions take the
        functional-copy path so the lease holder's buffer snapshots stay
        valid; while a donated scatter is in flight, new leases wait (the
        scatter is brief) so they observe either the old epoch or the
        fully-published one — never a half-scattered page."""
        with self._lock:
            while self._donating:
                self._fence.wait()
            self._leases += 1
        try:
            yield self
        finally:
            with self._lock:
                self._leases -= 1
                if self._leases == 0:
                    self._fence.notify_all()

    # ---------- admission ----------

    def admit_block(
        self,
        namespace: str,
        shard_id: int,
        block_start: int,
        volume: int,
        items: list,
        chunk_k: int = CHUNK_K,
        readmission: bool = False,
    ) -> AdmitResult:
        """Admit one sealed fileset block's streams in ONE batched upload.

        ``items``: ``[(series_id, stream_bytes, num_points_bound)]`` or
        ``[(series_id, stream_bytes, num_points_bound, side_snaps)]`` —
        empty streams are skipped (series absent from the block). When
        ``side_snaps`` (the per-chunk snapshot dicts of
        ops/chunked.snapshot_stream / storage/fs side tables) is absent,
        the chunk prescan + fast-chunk classification runs HERE, at
        admission time, so every resident lane carries device side planes
        and scans dispatch the chunk-parallel kernels. All staged pages
        land with a single host->device transfer + scatter per buffer.

        Three phases so the TABLE lock is held only for bookkeeping —
        never across staging, the upload, or an XLA scatter compile
        (writers invalidating and queries planning keep flowing while a
        flush's pages upload):

        1. under the table lock: allocate data + side pages (LRU-evicting
           published entries as needed) and park the new entries in
           ``_pending`` — invisible to readers, whose plan would
           otherwise gather pages the scatter hasn't written;
        2. no table lock: build the staging arrays and run the device
           scatters (serialized by the upload lock; donated in-place when
           no scan lease is active, functional copy otherwise);
        3. under the table lock: swap in the new buffers and publish
           surviving pending entries (an invalidation that raced the
           upload drops its entry instead of publishing stale bytes).
        """
        if not self.enabled:
            return AdmitResult(0, 0, 0, False)
        o = self.options
        if o.namespaces and namespace not in o.namespaces:
            return AdmitResult(0, 0, 0, False)
        page_bytes = o.page_bytes
        spc = o.side_page_chunks
        norm = [
            (it[0], it[1], it[2], it[3] if len(it) > 3 else None) for it in items
        ]
        # chunk prescan for items that arrived without a side table — the
        # pure host walk runs BEFORE any lock (native batch prescan when
        # built, ~50x the Python walk)
        missing = [i for i, it in enumerate(norm) if it[3] is None and it[1]]
        if missing:
            snaps_all = self._prescan([norm[i][1] for i in missing], chunk_k)
            for i, snaps in zip(missing, snaps_all):
                sid, stream, bound, _ = norm[i]
                norm[i] = (sid, stream, bound, snaps)
        # key, stream, pages, side_pages, packed side rows, chunk/span meta
        plan: list[tuple] = []
        rejected_span = 0
        side_overflows = 0
        for sid, stream, num_points, snaps in norm:
            if not stream:
                continue
            n_pages = -(-len(stream) // page_bytes)
            if n_pages > o.max_lane_pages:
                rejected_span += 1
                continue
            snaps = snaps or []
            rows = side_rows_from_snaps(snaps, block_start) if snaps else None
            if snaps and rows is None:
                # a chunk's decoder state overflows the packed 10-word
                # layout (pathological block span / sample gap): the lane
                # admits WITHOUT side planes and scans fall back streamed
                # for it — counted, never silent
                side_overflows += 1
                snaps = []
            n_chunks = len(snaps)
            max_span = max((p["span"] for p in snaps), default=0)
            n_side = -(-n_chunks // spc) if n_chunks else 0
            key = BlockKey(namespace, shard_id, bytes(sid), block_start, volume)
            plan.append((key, bytes(stream), n_pages, n_side, rows, n_chunks, max_span))
        if side_overflows:
            self.side_pack_overflows += side_overflows
            self._m_side_overflow.inc(side_overflows)
        rejected_budget = 0
        admitted = 0
        already_resident = 0
        batch_entries: list[tuple[BlockKey, ResidentEntry, bytes, list]] = []
        with self._upload_lock:
            with self._lock:
                for key, stream, n_pages, n_side, rows, n_chunks, max_span in plan:
                    if readmission:
                        cur = self._od.get(key)
                        if cur is not None:
                            # lane already resident at this exact key —
                            # one evicted shard-mate must not re-stage
                            # and re-upload the whole fileset's bytes;
                            # the lane was just streamed, so touch its
                            # LRU slot and count it toward completeness
                            self._od.move_to_end(key)
                            already_resident += 1
                            continue
                    # re-admissions fill FREE space only ("budget
                    # permitting"): evicting published entries for them
                    # would ping-pong a working set larger than the pool
                    alloc = self._alloc_locked(
                        n_pages, n_side, evict_ok=not readmission
                    )
                    if alloc is None:
                        rejected_budget += 1
                        continue
                    pages, side_pages = alloc
                    old = self._od.pop(key, None)
                    if old is not None:
                        self._unindex_locked(key, old)
                        self._free.extend(old.pages)
                        self._free_side.extend(old.side_pages)
                        self._resident_bytes -= old.nbytes
                    entry = ResidentEntry(
                        pages=tuple(pages),
                        num_bits=len(stream) * 8,
                        nbytes=len(stream),
                        side_pages=tuple(side_pages),
                        n_chunks=n_chunks,
                        chunk_k=chunk_k if n_chunks else 0,
                        max_span_bits=max_span,
                    )
                    self._pending[key] = entry
                    admitted += 1
                    batch_entries.append((key, entry, stream, rows))
            # ---- no table lock: stage + upload ----
            # Pending pages are off the free lists (never LRU-evicted), so
            # intra-batch cannibalization is impossible: each staged page
            # has exactly one owner and the scatter's indices are unique.
            # A racing invalidation can still DROP a pending entry; only
            # entries still pending at staging time get rows.
            staged_rows: list[np.ndarray] = []
            staged_idx: list[int] = []
            side_rows: list[np.ndarray] = []
            side_idx: list[int] = []
            staged_keys: set = set()
            with self._lock:
                generation = self._generation
            try:
                if batch_entries:
                    with self._lock:
                        survivors_snapshot = [
                            tup
                            for tup in batch_entries
                            if self._pending.get(tup[0]) is tup[1]
                        ]
                    for key, entry, stream, packed in survivors_snapshot:
                        staged_keys.add(key)
                        for j, p in enumerate(entry.pages):
                            row = np.zeros(o.page_words, np.uint32)
                            chunk = stream[j * page_bytes : (j + 1) * page_bytes]
                            padded = chunk + b"\x00" * (-len(chunk) % 4)
                            row[: len(padded) // 4] = np.frombuffer(
                                padded, ">u4"
                            ).astype(np.uint32)
                            staged_rows.append(row)
                            staged_idx.append(p)
                        if packed is not None and len(packed):
                            for j, sp in enumerate(entry.side_pages):
                                page = np.zeros((spc, N_SIDE_PLANES), np.uint32)
                                seg = packed[j * spc : (j + 1) * spc]
                                page[: len(seg)] = seg
                                side_rows.append(page)
                                side_idx.append(sp)
                    if staged_rows or side_rows:
                        # publishes the new buffers itself (under the same
                        # lock acquisition that lifts the donation fence)
                        self._upload(staged_rows, staged_idx, side_rows, side_idx)
            except BaseException:
                # staging/upload failed: this batch's pages are off the
                # free lists with nothing published — reclaim them here
                # (unless a donated-scatter failure already reset the
                # whole pool, rebuilding the free lists)
                with self._lock:
                    if self._generation == generation:
                        for key, entry, _stream, _snaps in batch_entries:
                            if self._pending.get(key) is entry:
                                del self._pending[key]
                            self._free.extend(entry.pages)
                            self._free_side.extend(entry.side_pages)
                        self._publish_locked()
                raise
            # ---- publish ----
            with self._lock:
                survivors = 0
                for key, entry, stream, _snaps in batch_entries:
                    present = self._pending.get(key) is entry
                    if present:
                        del self._pending[key]
                    if present and key in staged_keys:
                        survivors += 1
                        self._od[key] = entry
                        self._index_locked(key)
                        self._resident_bytes += entry.nbytes
                    else:
                        # invalidated mid-upload (or dropped before
                        # staging): never publish; the pages belong to
                        # this batch, so reclamation happens HERE, not in
                        # the invalidation hook
                        self._free.extend(entry.pages)
                        self._free_side.extend(entry.side_pages)
                complete = (
                    admitted + already_resident > 0
                    and rejected_span == 0
                    and rejected_budget == 0
                    and survivors + already_resident == len(plan)
                )
                group = (namespace, shard_id, block_start, volume)
                if complete:
                    self._complete.add(group)
                if rejected_span:
                    self._span_incomplete.add(group)
                if readmission:
                    if rejected_budget:
                        # cooldown watermark: retrying this fileset is a
                        # guaranteed rejection until EITHER free list
                        # grows past its size at THIS failure (whichever
                        # plane was binding; self-healing — no
                        # invalidation hook required)
                        self._budget_deferred[group] = (
                            len(self._free), len(self._free_side)
                        )
                    else:
                        self._budget_deferred.pop(group, None)
                self.admissions += admitted
                self.rejections += rejected_span + rejected_budget
                self._m_admissions.inc(admitted)
                if readmission and admitted:
                    self.readmissions += admitted
                    self._m_readmissions.inc(admitted)
                if rejected_span + rejected_budget:
                    self._m_rejections.inc(rejected_span + rejected_budget)
                self._publish_locked()
        return AdmitResult(admitted, rejected_span, rejected_budget, complete)

    def admit_block_device(
        self,
        namespace: str,
        shard_id: int,
        block_start: int,
        volume: int,
        words,
        items: list,
        chunk_k: int = CHUNK_K,
        host_items: list | None = None,
    ) -> AdmitResult:
        """Born-resident admission: seal pages that are ALREADY on device.

        ``words`` is the encode kernel's ``uint32[M, W]`` output
        (ops/encode.py) with W a multiple of ``page_words``; ``items`` is
        ``[(series_id, lane_row, nbytes, n_chunks, max_span_bits,
        packed_side_rows | None)]``. The data pages move device->device
        (a gather out of the encode buffer into the pool scatter) — the
        hot path uploads ZERO stream bytes, which is the whole point:
        ``resident_upload_bytes_total`` does not move. The packed side
        rows are O(40B/chunk) host metadata and stage under
        ``ingest_side_stage_bytes_total`` instead, so the zero-upload
        contract stays assertable while side staging stays visible.

        ``host_items`` carries the block's HOST-FALLBACK lanes
        (annotated/mixed/overflow — ``(sid, stream, num_points)`` like
        :meth:`admit_block`'s items): they ride the SAME three-phase
        batch so the group's completeness marker is computed over the
        union, never set by a partial subset. Their bytes stage
        host->device and count under ``resident_upload_bytes_total`` as
        usual — only device-encoded lanes are free.

        Same three phases and the same donation/epoch fence discipline
        as :meth:`admit_block`."""
        if not self.enabled:
            return AdmitResult(0, 0, 0, False)
        o = self.options
        if o.namespaces and namespace not in o.namespaces:
            return AdmitResult(0, 0, 0, False)
        page_bytes = o.page_bytes
        pw = o.page_words
        spc = o.side_page_chunks
        W = int(words.shape[1]) if items else pw
        if W % pw != 0:
            raise ResidentPoolError(
                f"device encode width {W} not a multiple of page_words {pw} "
                "(encode with round_words_to=pool.options.page_words)"
            )
        lane_pages = W // pw
        # plan rows: (key, src, nbytes, n_pages, n_side, rows, n_chunks,
        # max_span) — src is an int lane row (device) or bytes (host)
        plan: list[tuple] = []
        rejected_span = 0
        side_overflows = 0
        for sid, lane_row, nbytes, n_chunks, max_span, rows in items:
            if not nbytes:
                continue
            n_pages = -(-int(nbytes) // page_bytes)
            if n_pages > o.max_lane_pages or n_pages > lane_pages:
                rejected_span += 1
                continue
            if rows is None and n_chunks:
                # a chunk overflowed the packed layout: lane admits
                # without side planes and decodes streamed (counted)
                side_overflows += 1
                n_chunks = 0
            key = BlockKey(namespace, shard_id, bytes(sid), block_start, volume)
            plan.append(
                (key, int(lane_row), int(nbytes), n_pages,
                 -(-int(n_chunks) // spc) if n_chunks else 0,
                 rows if n_chunks else None, int(n_chunks), int(max_span))
            )
        for sid, stream, _num_points in host_items or []:
            if not stream:
                continue
            n_pages = -(-len(stream) // page_bytes)
            if n_pages > o.max_lane_pages:
                rejected_span += 1
                continue
            snaps = self._prescan([stream], chunk_k)[0]
            rows = side_rows_from_snaps(snaps, block_start) if snaps else None
            if snaps and rows is None:
                side_overflows += 1
                snaps = []
            n_chunks = len(snaps)
            max_span = max((p["span"] for p in snaps), default=0)
            key = BlockKey(namespace, shard_id, bytes(sid), block_start, volume)
            plan.append(
                (key, bytes(stream), len(stream), n_pages,
                 -(-n_chunks // spc) if n_chunks else 0,
                 rows, n_chunks, max_span)
            )
        if side_overflows:
            self.side_pack_overflows += side_overflows
            self._m_side_overflow.inc(side_overflows)
        rejected_budget = 0
        admitted = 0
        batch_entries: list[tuple] = []
        with self._upload_lock:
            with self._lock:
                for key, src, nbytes, n_pages, n_side, rows, n_chunks, max_span in plan:
                    alloc = self._alloc_locked(n_pages, n_side)
                    if alloc is None:
                        rejected_budget += 1
                        continue
                    pages, side_pages = alloc
                    old = self._od.pop(key, None)
                    if old is not None:
                        self._unindex_locked(key, old)
                        self._free.extend(old.pages)
                        self._free_side.extend(old.side_pages)
                        self._resident_bytes -= old.nbytes
                    entry = ResidentEntry(
                        pages=tuple(pages),
                        num_bits=nbytes * 8,
                        nbytes=nbytes,
                        side_pages=tuple(side_pages),
                        n_chunks=n_chunks,
                        chunk_k=chunk_k if n_chunks else 0,
                        max_span_bits=max_span,
                    )
                    self._pending[key] = entry
                    admitted += 1
                    batch_entries.append((key, entry, src, rows))
            src_rows: list[int] = []
            dst_pages: list[int] = []
            host_rows: list[np.ndarray] = []
            host_idx: list[int] = []
            side_rows_staged: list[np.ndarray] = []
            side_idx: list[int] = []
            staged_keys: set = set()
            with self._lock:
                generation = self._generation
            try:
                if batch_entries:
                    with self._lock:
                        survivors_snapshot = [
                            tup
                            for tup in batch_entries
                            if self._pending.get(tup[0]) is tup[1]
                        ]
                    for key, entry, src, rows in survivors_snapshot:
                        staged_keys.add(key)
                        if isinstance(src, int):
                            for j, p in enumerate(entry.pages):
                                src_rows.append(src * lane_pages + j)
                                dst_pages.append(p)
                        else:
                            for j, p in enumerate(entry.pages):
                                row = np.zeros(pw, np.uint32)
                                chunk = src[j * page_bytes : (j + 1) * page_bytes]
                                padded = chunk + b"\x00" * (-len(chunk) % 4)
                                row[: len(padded) // 4] = np.frombuffer(
                                    padded, ">u4"
                                ).astype(np.uint32)
                                host_rows.append(row)
                                host_idx.append(p)
                        if rows is not None and len(rows):
                            for j, sp in enumerate(entry.side_pages):
                                page = np.zeros((spc, N_SIDE_PLANES), np.uint32)
                                seg = rows[j * spc : (j + 1) * spc]
                                page[: len(seg)] = seg
                                side_rows_staged.append(page)
                                side_idx.append(sp)
                    if src_rows or host_rows or side_rows_staged:
                        self._upload_device(
                            words, src_rows, dst_pages, host_rows, host_idx,
                            side_rows_staged, side_idx,
                        )
            except BaseException:
                with self._lock:
                    if self._generation == generation:
                        for key, entry, _row, _rows in batch_entries:
                            if self._pending.get(key) is entry:
                                del self._pending[key]
                            self._free.extend(entry.pages)
                            self._free_side.extend(entry.side_pages)
                        self._publish_locked()
                raise
            with self._lock:
                survivors = 0
                dev_survivors = 0
                for key, entry, src, _rows in batch_entries:
                    present = self._pending.get(key) is entry
                    if present:
                        del self._pending[key]
                    if present and key in staged_keys:
                        survivors += 1
                        if isinstance(src, int):
                            dev_survivors += 1
                        self._od[key] = entry
                        self._index_locked(key)
                        self._resident_bytes += entry.nbytes
                    else:
                        self._free.extend(entry.pages)
                        self._free_side.extend(entry.side_pages)
                complete = (
                    admitted > 0
                    and rejected_span == 0
                    and rejected_budget == 0
                    and survivors == len(plan)
                )
                if complete:
                    self._complete.add((namespace, shard_id, block_start, volume))
                if rejected_span:
                    self._span_incomplete.add(
                        (namespace, shard_id, block_start, volume)
                    )
                self.admissions += admitted
                self.device_admissions += dev_survivors
                self.rejections += rejected_span + rejected_budget
                self._m_admissions.inc(admitted)
                self._m_device_admissions.inc(dev_survivors)
                if rejected_span + rejected_budget:
                    self._m_rejections.inc(rejected_span + rejected_budget)
                self._publish_locked()
        return AdmitResult(admitted, rejected_span, rejected_budget, complete)

    def _upload_device(
        self, words_src, src_rows: list, dst_pages: list, host_rows: list,
        host_idx: list, side_rows: list, side_idx: list
    ):
        """Device->device data publication + (tiny) side-plane staging —
        the born-resident half of :meth:`_upload`, same donation fence
        and epoch discipline, but the device-encoded pages never cross
        PCIe and ``upload_bytes`` does not move for them. Host-fallback
        rows of the same batch (``host_rows``) concatenate into the same
        scatter and DO count under ``upload_bytes``."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            words = self._ensure_words()
            side = self._ensure_side()
            donate = self._leases == 0
            if donate:
                self._donating = True
        try:
            new_words = new_side = None
            if src_rows or host_rows:
                pw = self.options.page_words
                parts = []
                if src_rows:
                    parts.append(
                        words_src.reshape(-1, pw)[np.asarray(src_rows, np.int32)]
                    )
                if host_rows:
                    staged_host = np.stack(host_rows)
                    self.upload_bytes += staged_host.nbytes
                    self._m_upload.inc(staged_host.nbytes)
                    parts.append(jax.device_put(staged_host))
                n = len(src_rows) + len(host_rows)
                n_pad = 1 << max(n - 1, 0).bit_length()
                if n_pad > n:
                    # padding rows re-write zeros into the reserved zero
                    # page, exactly like the host staging path
                    parts.append(jnp.zeros((n_pad - n, pw), jnp.uint32))
                gathered = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
                indices = np.zeros(n_pad, np.int32)
                indices[: len(src_rows)] = np.asarray(dst_pages, np.int32)
                indices[len(src_rows) : n] = np.asarray(host_idx, np.int32)
                new_words = _scatter(
                    words, jax.device_put(indices), gathered, donate
                )
            if side_rows:
                staged, indices = self._stage(
                    side_rows, side_idx,
                    (self.options.side_page_chunks, N_SIDE_PLANES),
                )
                self.ingest_side_stage_bytes += staged.nbytes
                self._m_side_stage.inc(staged.nbytes)
                new_side = _scatter(side, jax.device_put(indices),
                                    jax.device_put(staged), donate)
        except BaseException:
            with self._lock:
                if donate:
                    self._reset_locked()
                    self._donating = False
                    self._fence.notify_all()
            raise
        with self._lock:
            if new_words is not None:
                self._words = new_words
            if new_side is not None:
                self._side = new_side
            if new_words is not None or new_side is not None:
                self.epoch += 1
            if donate:
                self._donating = False
                self._fence.notify_all()
        if donate:
            self.inplace_admissions += 1
            self._m_inplace.inc()
        else:
            self.copy_admissions += 1
            self._m_copy.inc()

    @staticmethod
    def _prescan(streams: list, chunk_k: int) -> list:
        from .. import native

        if native.available():
            return native.prescan_batch(streams, k=chunk_k)
        from ..ops.chunked import snapshot_stream

        return [snapshot_stream(s, chunk_k) for s in streams]

    def _upload(self, rows: list, idx: list, side_rows: list, side_idx: list):
        """One host->device transfer + scatter per buffer for the batch —
        runs WITHOUT the table lock (serialized by the upload lock) and
        PUBLISHES the new buffers itself, under the SAME lock acquisition
        that lifts the donation fence: a lease waking on the fence must
        already see the published buffers, never the donated (deleted)
        old ones.

        When no scan lease is active the current buffers are DONATED to
        the scatter: XLA aliases input to output and writes the pages in
        place — the PR-3 transient copy is gone. While the donated
        scatter is in flight new leases wait on the fence (the old buffer
        no longer exists); an active lease instead downgrades this
        admission to the functional copy.

        If a scatter fails AFTER a donation consumed a buffer, every
        entry (published and pending) points into a deleted array — the
        pool resets (table dropped, buffers lazily re-zeroed) rather
        than bricking; read-through re-admission repopulates the hot
        set. The functional path keeps the old buffers on failure.

        The page count is padded to a power of two (extra rows re-write
        zeros into the reserved zero page) so the jitted scatter compiles
        once per bucket, not once per fileset size."""
        import jax

        with self._lock:
            words = self._ensure_words()
            side = self._ensure_side()
            donate = self._leases == 0
            if donate:
                self._donating = True
        try:
            new_words = new_side = None
            if rows:
                staged, indices = self._stage(rows, idx, (self.options.page_words,))
                self.upload_bytes += staged.nbytes
                self._m_upload.inc(staged.nbytes)
                new_words = _scatter(words, jax.device_put(indices),
                                     jax.device_put(staged), donate)
            if side_rows:
                staged, indices = self._stage(
                    side_rows, side_idx,
                    (self.options.side_page_chunks, N_SIDE_PLANES),
                )
                # side-plane staging is host->device transfer like the
                # data pages (~1:1 with stream bytes) — count it, or the
                # upload accounting under-reports admission cost ~2x and
                # the zero-transfer contract can't see side re-uploads
                self.upload_bytes += staged.nbytes
                self._m_upload.inc(staged.nbytes)
                new_side = _scatter(side, jax.device_put(indices),
                                    jax.device_put(staged), donate)
        except BaseException:
            with self._lock:
                if donate:
                    self._reset_locked()
                    self._donating = False
                    self._fence.notify_all()
            raise
        with self._lock:
            if new_words is not None:
                self._words = new_words
            if new_side is not None:
                self._side = new_side
            if new_words is not None or new_side is not None:
                self.epoch += 1
            if donate:
                self._donating = False
                self._fence.notify_all()
        if donate:
            self.inplace_admissions += 1
            self._m_inplace.inc()
        else:
            self.copy_admissions += 1
            self._m_copy.inc()

    @staticmethod
    def _stage(rows: list, idx: list, row_shape: tuple):
        n = len(rows)
        n_pad = 1 << max(n - 1, 0).bit_length() if n else 1
        staged = np.zeros((n_pad,) + row_shape, np.uint32)
        staged[:n] = np.stack(rows)
        indices = np.zeros(n_pad, np.int32)
        indices[:n] = np.asarray(idx, np.int32)
        return staged, indices

    def _alloc_locked(self, n_pages: int, n_side: int, evict_ok: bool = True):
        """Pop pages from both free lists, LRU-evicting until they fit
        (never evicting the reserved zero pages, which are not on the
        free lists). ``evict_ok=False`` admits only into free space —
        read-through re-admissions use it so a working set larger than
        the budget can't LRU-ping-pong (each scan evicting the previous
        scan's re-admissions). Returns (pages, side_pages) or None."""
        while len(self._free) < n_pages or len(self._free_side) < n_side:
            if not evict_ok or not self._evict_one_locked():
                return None
        return (
            [self._free.pop() for _ in range(n_pages)],
            [self._free_side.pop() for _ in range(n_side)],
        )

    def _evict_one_locked(self) -> bool:
        if not self._od:
            return False
        key, entry = self._od.popitem(last=False)
        self._unindex_locked(key, entry)
        self._free.extend(entry.pages)
        self._free_side.extend(entry.side_pages)
        self._resident_bytes -= entry.nbytes
        self.evictions += 1
        self._m_evictions.inc()
        return True

    # ---------- lookup / scan planning ----------

    def get(self, key: BlockKey) -> ResidentEntry | None:
        with self._lock:
            entry = self._od.get(key)
            if entry is not None:
                self._od.move_to_end(key)
            return entry

    def is_complete(self, namespace: str, shard_id: int, block_start: int, volume: int) -> bool:
        with self._lock:
            return (namespace, shard_id, block_start, volume) in self._complete

    def has_free_capacity(self) -> bool:
        """Cheap gate for read-through re-admission: free pages exist in
        BOTH planes. Re-admissions never evict (see _alloc_locked), so a
        full pool makes any attempt pointless — callers skip the fileset
        re-read entirely instead of paying disk I/O for a guaranteed
        budget rejection."""
        with self._lock:
            return bool(self._free) and bool(self._free_side)

    def never_completable(
        self, namespace: str, shard_id: int, block_start: int, volume: int
    ) -> bool:
        """True when a past admission of this fileset rejected a lane for
        page span — it can never reach the complete marker, so
        read-through re-admission would re-upload it on every streamed
        query for nothing."""
        with self._lock:
            return (namespace, shard_id, block_start, volume) in self._span_incomplete

    def budget_deferred(
        self, namespace: str, shard_id: int, block_start: int, volume: int
    ) -> bool:
        """True when a past read-through re-admission of this fileset was
        rejected for budget and NEITHER free list (data or side plane —
        either can be the binding constraint) has grown since: retrying
        would pay the whole-fileset disk re-read for another guaranteed
        rejection (re-admissions never evict). Any eviction or
        invalidation that frees pages in either plane past its recorded
        watermark lets the next streamed query retry (which refreshes
        the marker if it fails again)."""
        with self._lock:
            rec = self._budget_deferred.get(
                (namespace, shard_id, block_start, volume)
            )
            return (
                rec is not None
                and len(self._free) <= rec[0]
                and len(self._free_side) <= rec[1]
            )

    def __contains__(self, key: BlockKey) -> bool:
        with self._lock:
            return key in self._od

    def __len__(self) -> int:
        return len(self._od)

    def _entries_locked(self, keys: list):
        entries = []
        for key in keys:
            e = self._od.get(key)
            if e is None:
                return None
            self._od.move_to_end(key)
            entries.append(e)
        return entries

    def _check_entry(self, e: ResidentEntry) -> None:
        # entries are immutable NamedTuples and options never change, so
        # validation needs no lock — plan_chunked runs this O(lanes ×
        # pages) walk AFTER releasing the table lock (a 64k-lane bench
        # scan must not block writers/invalidations for its duration)
        o = self.options
        n = len(e.pages)
        if n > o.max_lane_pages:
            raise ResidentPoolError(
                f"page table entry spans {n} pages > limit {o.max_lane_pages}"
            )
        if n * o.page_words * 32 < e.num_bits:
            raise ResidentPoolError(
                f"page table entry holds {e.num_bits} bits in {n} pages "
                f"of {o.page_words * 32} bits"
            )
        for p in e.pages:
            if not 0 < p < o.num_pages:
                raise ResidentPoolError(
                    f"corrupt page index {p} (pool has {o.num_pages} pages)"
                )
        for p in e.side_pages:
            if not 0 < p < o.num_side_pages:
                raise ResidentPoolError(
                    f"corrupt side page index {p} "
                    f"(pool has {o.num_side_pages} side pages)"
                )
        if e.n_chunks > len(e.side_pages) * o.side_page_chunks:
            raise ResidentPoolError(
                f"side table holds {e.n_chunks} chunks in "
                f"{len(e.side_pages)} side pages"
            )

    def plan_chunked(self, keys: list) -> "ResidentChunkedPlan | None":
        """Assemble the CHUNK-parallel device gather inputs for ``keys``:
        page rows + side-page rows + per-series chunk counts, everything
        the device-side lane assembly (parallel/scan.py
        assemble_resident_lanes) needs to build a ChunkedBatch-shaped
        view by gather — O(series) host ints, no chunk table rebuild.

        Returns None when any key is not resident, lacks side planes, or
        the entries mix chunk sizes (the caller falls back to the
        streamed path). Callers hold read_lease() across plan + use."""
        from ..ops.chunked import window_words

        o = self.options
        with self._lock:
            if not self.enabled or self._words is None or self._side is None:
                return None
            entries = self._entries_locked(keys)
            if entries is None:
                return None
            words = self._words
            side = self._side
        for e in entries:
            self._check_entry(e)
        k = 0
        for e in entries:
            if e.n_chunks <= 0 or not e.side_pages:
                return None  # admitted without side planes
            if k == 0:
                k = e.chunk_k
            elif e.chunk_k != k:
                return None  # mixed chunk sizes: shapes would disagree
        if k <= 0:
            return None
        s = len(entries)
        c = max(e.n_chunks for e in entries)
        cw = window_words(max(e.max_span_bits for e in entries))
        # trailing zero-page columns so a window starting in the last
        # stream word can read its full cw span + alignment from zeros
        extra = -(-cw // o.page_words) + 1
        lp = max(len(e.pages) for e in entries) + extra
        sl = max(len(e.side_pages) for e in entries)
        page_rows = np.zeros((s, lp), np.int32)
        side_rows = np.zeros((s, sl), np.int32)
        n_chunks = np.zeros(s, np.int32)
        total_bits = np.zeros(s, np.int32)
        # per-series block_start as a u32 pair: the packed side planes
        # store prev_time block-relative, so the device unpack re-bases
        block_hi = np.zeros(s, np.uint32)
        block_lo = np.zeros(s, np.uint32)
        for i, (key, e) in enumerate(zip(keys, entries)):
            page_rows[i, : len(e.pages)] = e.pages
            side_rows[i, : len(e.side_pages)] = e.side_pages
            n_chunks[i] = e.n_chunks
            total_bits[i] = e.num_bits
            bs = int(key.block_start) & ((1 << 64) - 1)
            block_hi[i] = bs >> 32
            block_lo[i] = bs & 0xFFFFFFFF
        return ResidentChunkedPlan(
            words=words,
            side=side,
            page_rows=page_rows,
            side_rows=side_rows,
            n_chunks=n_chunks,
            total_bits=total_bits,
            block_hi=block_hi,
            block_lo=block_lo,
            chunk_k=k,
            num_chunks=c,
            window_words=cw,
            page_words=o.page_words,
            side_page_chunks=o.side_page_chunks,
        )

    # ---------- invalidation surface (cache/invalidation.py drives this) ----------

    def invalidate_series_block(
        self, namespace: str, shard_id: int, series_id: bytes, block_start: int
    ) -> int:
        """Drop every volume of one (series, block) — the write hook."""
        with self._lock:
            self._drop_pending_locked(
                lambda k: k.series_key
                == (namespace, shard_id, series_id, block_start)
            )
            keys = self._by_series.pop(
                (namespace, shard_id, series_id, block_start), None
            )
            return self._drop_locked(keys)

    def invalidate_block(
        self, namespace: str, shard_id: int, block_start: int, below_volume=None
    ) -> int:
        """Drop a block's entries across series; ``below_volume`` restricts
        to superseded volumes (cold-flush supersession)."""
        with self._lock:
            self._drop_pending_locked(
                lambda k: k.block_key == (namespace, shard_id, block_start)
                and (below_volume is None or k.volume < below_volume)
            )
            keys = self._by_block.get((namespace, shard_id, block_start))
            if keys is None:
                # entries may be gone while the complete marker lingers
                # (e.g. all evicted): still clear markers for the block
                self._drop_complete_locked(namespace, shard_id, block_start, below_volume)
                return 0
            if below_volume is not None:
                keys = {k for k in keys if k.volume < below_volume}
            else:
                keys = set(keys)
            self._drop_complete_locked(namespace, shard_id, block_start, below_volume)
            return self._drop_locked(keys)

    def drop_shard(self, namespace: str | None, shard_id: int) -> int:
        """Drop every entry of one shard — the SOURCE side of a shard
        handoff: once the placement stops assigning the shard here its
        residency is dead weight starving the shards this node still
        owns. ``namespace=None`` matches all namespaces."""
        with self._lock:
            self._drop_pending_locked(
                lambda k: k.shard_id == shard_id
                and (namespace is None or k.namespace == namespace)
            )
            keys = {
                k
                for k in self._od
                if k.shard_id == shard_id
                and (namespace is None or k.namespace == namespace)
            }
            for k in keys:
                self._drop_complete_locked(
                    k.namespace, k.shard_id, k.block_start, None
                )
            return self._drop_locked(keys)

    def clear(self) -> int:
        with self._lock:
            self._drop_pending_locked(lambda k: True)
            n = len(self._od)
            for entry in self._od.values():
                self._free.extend(entry.pages)
                self._free_side.extend(entry.side_pages)
            self._resident_bytes = 0
            self._od.clear()
            self._by_series.clear()
            self._by_block.clear()
            self._complete.clear()
            self._span_incomplete.clear()
            self._budget_deferred.clear()
            self.invalidations += n
            self._m_invalidations.inc(n)
            self._publish_locked()
            return n

    def shard_usage(self) -> dict[tuple[str, int], int]:
        """Resident bytes per (namespace, shard) across published entries
        — the heat-driven rebalancer's occupancy input."""
        with self._lock:
            usage: dict[tuple[str, int], int] = {}
            for key, entry in self._od.items():
                k = (key.namespace, key.shard_id)
                usage[k] = usage.get(k, 0) + entry.nbytes
            return usage

    def rebalance(self, heat: dict, slack: float = 0.10) -> int:
        """Heat-driven budget redistribution after a topology change:
        shards holding MORE than their heat-weighted share of the byte
        budget shed LRU-oldest entries first, freeing pages for gained
        hot shards' warm streaming and read-through re-admission.

        ``heat`` is ShardHeat.dump() shape ({shard_id_str: {"hits", ...}});
        a shard's weight is hits+misses (demand observed at the router),
        floored at 1 so an unmeasured shard keeps a sliver instead of
        being wiped. ``slack`` avoids churn at the boundary. Nothing is
        admitted here — admission stays flush/demand-driven; this only
        makes room where the heat says it is owed. Returns entries
        evicted (counted in ``resident_rebalance_evictions_total``)."""
        with self._lock:
            usage: dict[tuple[str, int], int] = {}
            for key, entry in self._od.items():
                k = (key.namespace, key.shard_id)
                usage[k] = usage.get(k, 0) + entry.nbytes
            if len(usage) <= 1:
                return 0  # one shard resident: nothing to redistribute
            weights = {}
            for k in usage:
                h = heat.get(str(k[1])) or {}
                weights[k] = max(
                    float(h.get("hits", 0)) + float(h.get("misses", 0)), 1.0
                )
            total_w = sum(weights.values())
            budget = float(self.options.max_bytes)
            victims: list = []
            for k, used in usage.items():
                target = budget * (weights[k] / total_w) * (1.0 + slack)
                over = float(used) - target
                if over <= 0:
                    continue
                for key, entry in self._od.items():  # LRU order: oldest first
                    if (key.namespace, key.shard_id) != k:
                        continue
                    victims.append(key)
                    over -= entry.nbytes
                    if over <= 0:
                        break
            for key in victims:
                entry = self._od.pop(key, None)
                if entry is None:
                    continue
                self._unindex_locked(key, entry)
                self._free.extend(entry.pages)
                self._free_side.extend(entry.side_pages)
                self._resident_bytes -= entry.nbytes
                self.evictions += 1
                self._m_evictions.inc()
                self.rebalance_evictions += 1
                self._m_rebalance_evictions.inc()
            if victims:
                self._publish_locked()
            return len(victims)

    def _reset_locked(self) -> None:
        """Last-resort recovery for a failed DONATED scatter: the old
        buffer may already be deleted, so every entry — published and
        pending — points into an unusable array. Drop the whole table,
        rebuild the free lists, and null the buffers (lazily re-zeroed
        on next use); read-through re-admission repopulates the hot set.
        Counted as invalidations, never silent."""
        n = len(self._od)
        self._od.clear()
        self._pending.clear()
        self._by_series.clear()
        self._by_block.clear()
        self._complete.clear()
        self._span_incomplete.clear()
        self._budget_deferred.clear()
        self._free = list(range(self.options.num_pages - 1, 0, -1))
        self._free_side = list(range(self.options.num_side_pages - 1, 0, -1))
        self._resident_bytes = 0
        self._words = None
        self._side = None
        self.epoch += 1
        self._generation += 1
        self.invalidations += n
        self._m_invalidations.inc(n)
        self._publish_locked()

    def _drop_pending_locked(self, match) -> None:
        """Drop matching in-flight admissions so stale data never
        publishes. Their pages stay OFF the free lists — the admitting
        thread owns them and reclaims at publish time (the scatter may
        still be writing them)."""
        for key in [k for k in self._pending if match(k)]:
            del self._pending[key]

    def _drop_complete_locked(self, namespace, shard_id, block_start, below_volume) -> None:
        for markers in (self._complete, self._span_incomplete):
            for g in [
                g
                for g in markers
                if g[0] == namespace
                and g[1] == shard_id
                and g[2] == block_start
                and (below_volume is None or g[3] < below_volume)
            ]:
                markers.discard(g)
        for g in [
            g
            for g in self._budget_deferred
            if g[0] == namespace
            and g[1] == shard_id
            and g[2] == block_start
            and (below_volume is None or g[3] < below_volume)
        ]:
            del self._budget_deferred[g]

    def _drop_locked(self, keys) -> int:
        if not keys:
            return 0
        dropped = 0
        for key in list(keys):
            entry = self._od.pop(key, None)
            if entry is None:
                continue
            self._unindex_locked(key, entry)
            self._free.extend(entry.pages)
            self._free_side.extend(entry.side_pages)
            self._resident_bytes -= entry.nbytes
            dropped += 1
        self.invalidations += dropped
        self._m_invalidations.inc(dropped)
        self._publish_locked()
        return dropped

    # ---------- bookkeeping ----------

    def _index_locked(self, key: BlockKey) -> None:
        self._by_series.setdefault(key.series_key, set()).add(key)
        self._by_block.setdefault(key.block_key, set()).add(key)

    def _unindex_locked(self, key: BlockKey, entry: ResidentEntry) -> None:
        for index, sub in (
            (self._by_series, key.series_key),
            (self._by_block, key.block_key),
        ):
            keys = index.get(sub)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del index[sub]
        # any entry leaving the pool makes its fileset group incomplete
        self._complete.discard(
            (key.namespace, key.shard_id, key.block_start, key.volume)
        )

    def _publish_locked(self) -> None:
        used = self.options.num_pages - 1 - len(self._free)
        side_used = self.options.num_side_pages - 1 - len(self._free_side)
        self._g_bytes.set(float(self._resident_bytes))
        self._g_pages.set(float(used))
        self._g_free.set(float(len(self._free)))
        self._g_entries.set(float(len(self._od)))
        self._g_side_pages.set(float(side_used))
        self._g_occupancy.set(used / max(self.options.num_pages - 1, 1))

    def stats(self) -> dict:
        with self._lock:
            o = self.options
            used_pages = o.num_pages - 1 - len(self._free)
            side_used = o.num_side_pages - 1 - len(self._free_side)
            resident_bytes = self._resident_bytes
            return {
                "enabled": self.enabled,
                "entries": len(self._od),
                "bytes": resident_bytes,
                "max_bytes": o.max_bytes,
                "page_bytes": o.page_bytes,
                "pages_used": used_pages,
                "pages_total": max(o.num_pages - 1, 0),
                "occupancy": used_pages / max(o.num_pages - 1, 1),
                "side_pages_used": side_used,
                "side_pages_total": max(o.num_side_pages - 1, 0),
                "side_page_bytes": o.side_page_bytes,
                "complete_blocks": len(self._complete),
                "admissions": self.admissions,
                "rejections": self.rejections,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "upload_bytes": self.upload_bytes,
                "readmissions": self.readmissions,
                "inplace_admissions": self.inplace_admissions,
                "copy_admissions": self.copy_admissions,
                "side_pack_overflows": self.side_pack_overflows,
                "rebalance_evictions": self.rebalance_evictions,
                "device_admissions": self.device_admissions,
                "ingest_side_stage_bytes": self.ingest_side_stage_bytes,
                "epoch": self.epoch,
                "shard_heat": self.heat.dump(),
            }


class ResidentChunkedPlan(NamedTuple):
    """Chunk-parallel device gather inputs (pool.plan_chunked): the
    host-side part is O(series) int vectors; windows and per-chunk lane
    metadata assemble ON DEVICE from ``words`` + ``side``."""

    words: object  # device uint32[num_pages, page_words]
    side: object  # device uint32[num_side_pages, spc, N_SIDE_PLANES]
    page_rows: np.ndarray  # int32[S, LP] incl. trailing zero-page columns
    side_rows: np.ndarray  # int32[S, SL] side-page index per slot
    n_chunks: np.ndarray  # int32[S]
    total_bits: np.ndarray  # int32[S]
    block_hi: np.ndarray  # uint32[S] block_start >> 32 (side-plane re-base)
    block_lo: np.ndarray  # uint32[S] block_start & 0xFFFFFFFF
    chunk_k: int  # records per chunk (uniform across the plan)
    num_chunks: int  # C = max chunks per series
    window_words: int  # cw (ops/chunked.window_words over max spans)
    page_words: int
    side_page_chunks: int


def _scatter(buf, indices, staged, donate: bool):
    """Page scatter (jitted lazily; module import stays light). The
    donated variant aliases input to output — true in-place on backends
    that support donation; jax silently falls back to a copy elsewhere."""
    import jax

    global _SCATTER_JIT, _SCATTER_DONATE_JIT
    if donate:
        if _SCATTER_DONATE_JIT is None:
            _SCATTER_DONATE_JIT = jax.jit(
                lambda w, i, s: w.at[i].set(s), donate_argnums=(0,)
            )
        return _SCATTER_DONATE_JIT(buf, indices, staged)
    if _SCATTER_JIT is None:
        _SCATTER_JIT = jax.jit(lambda w, i, s: w.at[i].set(s))
    return _SCATTER_JIT(buf, indices, staged)


_SCATTER_JIT = None
_SCATTER_DONATE_JIT = None
