"""HBM-resident compressed series store: a per-device paged M3TSZ pool.

The memory-manager analogue of a paged KV cache in an inference stack,
applied to the scan-and-aggregate hot path: instead of streaming sealed
blocks' compressed bytes over PCIe on every scan (PROFILE.md's 50M×720
row is transfer-bound at ~1.1s/chip), the m3tsz bytes stay RESIDENT in
device memory — at compressed density (~1–2.4B/datapoint) a v5e-8 holds
the whole 50M-series working set — and scans decode straight from HBM.

Layout:

- ONE flat device buffer ``uint32[num_pages, page_words]`` under a byte
  budget (``ResidentOptions.max_bytes``). Page 0 is RESERVED and always
  zero: gather plans pad short lanes with it, so a gathered lane's word
  row is bit-identical to BatchedSegments' zero padding.
- fixed-size pages handed out by a free-list allocator; a sealed block's
  stream occupies ``ceil(bits / page_bits)`` consecutive page-table slots
  (the pages themselves need not be contiguous — the device gather
  reassembles them).
- a HOST-side page table: ``BlockKey(namespace, shard, series_id,
  block_start, volume) -> ResidentEntry(pages, num_bits, initial_unit,
  num_points)`` — exactly the lane metadata ``ops.decode.decode_batched``
  needs, so a scan is one row gather + the existing decode kernel.

Admission is batched at flush/seal time (storage/database.py): all of a
fileset's streams stage into one host array and land in one device scatter
(``pool.at[idx].set(staged)``), not a device_put per series. Eviction is
LRU under the byte budget plus explicit invalidation through the same
hooks as the decoded-block cache (cache/invalidation.py) — a written-to,
superseded, or retention-expired block is never resident.

Updates are FUNCTIONAL (``.at[].set`` returns a new array, no donation):
a scan that snapshotted the previous buffer keeps reading consistent
bytes while an admission lands. The cost is one transient extra copy
during admission; donation (true in-place) is a TPU-side follow-up that
needs scan/admit epoch fencing.

Concurrency: the page table, free list, and counters are guarded by one
lock; ``plan_scan`` snapshots the device buffer reference under it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from ..cache.block_cache import BlockKey
from ..utils.instrument import DEFAULT as METRICS
from .heat import ShardHeat


class ResidentPoolError(ValueError):
    """Corrupt page-table state detected (satellite contract: corrupt
    metadata must raise, never read out-of-bounds or silently wrap)."""


@dataclass
class ResidentOptions:
    """Knobs for the paged resident store (x/config-style dataclass).

    ``max_bytes`` is the device byte budget for the page buffer (0
    disables the pool). ``page_words`` is the page size in uint32 words
    (default 512 words = 2KiB — one typical 720-point m3tsz block fits in
    1–2 pages). ``max_lane_pages`` caps one (series, block) lane's page
    span: the device gather width is ``max over lanes`` of the page
    count, so one pathological stream must not widen every lane's row."""

    enabled: bool = True
    max_bytes: int = 0
    page_words: int = 512
    max_lane_pages: int = 64
    namespaces: list = field(default_factory=list)

    def validate(self) -> None:
        from ..utils.config import ConfigError

        if self.max_bytes < 0:
            raise ConfigError("resident.max_bytes must be >= 0")
        if self.page_words <= 0:
            raise ConfigError("resident.page_words must be > 0")
        if self.max_lane_pages <= 0:
            raise ConfigError("resident.max_lane_pages must be > 0")

    @property
    def page_bytes(self) -> int:
        return self.page_words * 4

    @property
    def num_pages(self) -> int:
        # page 0 is the reserved zero page; it still costs budget
        return self.max_bytes // self.page_bytes


class ResidentEntry(NamedTuple):
    """Page-table row for one resident (series, block, volume) lane."""

    pages: tuple  # page indices, stream order
    num_bits: int  # valid bits of the m3tsz stream
    initial_unit: int  # initial time-unit code (BatchedSegments semantics)
    num_points: int  # upper bound on datapoints (n_chunks * chunk_k)
    nbytes: int  # stream length in bytes (occupancy accounting)


def _initial_unit(stream: bytes, default_unit_nanos: int = 1_000_000_000) -> int:
    """Mirror BatchedSegments.initial_units for one stream: the default
    unit applies only when the head 64-bit timestamp divides it."""
    if len(stream) < 8:
        return 0
    nt = int.from_bytes(stream[:8], "big")
    from ..utils.xtime import Unit

    return int(Unit.SECOND) if nt % default_unit_nanos == 0 else 0


class AdmitResult(NamedTuple):
    admitted: int
    rejected_span: int  # lanes over the max_lane_pages span limit
    rejected_budget: int  # lanes that could not fit even after eviction
    complete: bool  # every non-empty stream of the group is now resident


class ResidentPool:
    """Paged device pool of sealed blocks' compressed streams."""

    def __init__(self, options: ResidentOptions | None = None, registry=None) -> None:
        self.options = options or ResidentOptions()
        self._lock = threading.Lock()
        # serializes admissions (the functional device-words chain); held
        # across staging + upload so the TABLE lock above never is — writes
        # and scans keep flowing while a flush's pages upload
        self._upload_lock = threading.Lock()
        self._od: "OrderedDict[BlockKey, ResidentEntry]" = OrderedDict()
        # admitted-but-not-yet-uploaded entries: invisible to readers
        # (plan_scan would otherwise serve pages the scatter hasn't
        # written); published into _od after the upload completes, unless
        # an invalidation dropped them mid-upload
        self._pending: dict[BlockKey, ResidentEntry] = {}
        self._by_series: dict[tuple, set] = {}
        self._by_block: dict[tuple, set] = {}
        # (namespace, shard, block_start, volume) groups whose every
        # non-empty stream is resident: lets the query router treat a
        # page-table miss as "series absent from that fileset" instead of
        # "not resident" — dropped conservatively on any eviction or
        # invalidation touching the group
        self._complete: set[tuple] = set()
        # free list: every page except the reserved zero page
        self._free: list[int] = list(range(self.options.num_pages - 1, 0, -1))
        self._words = None  # device uint32[num_pages, page_words], lazy
        self._resident_bytes = 0  # sum of entries' stream bytes
        self.admissions = 0
        self.rejections = 0
        self.evictions = 0
        self.invalidations = 0
        self.upload_bytes = 0
        reg = registry or METRICS
        self._m_admissions = reg.counter(
            "resident_admissions_total", "blocks admitted to the resident pool"
        )
        self._m_rejections = reg.counter(
            "resident_rejections_total", "blocks rejected at admission"
        )
        self._m_evictions = reg.counter(
            "resident_evictions_total", "LRU/budget evictions from the pool"
        )
        self._m_invalidations = reg.counter(
            "resident_invalidations_total", "entries dropped by invalidation hooks"
        )
        self._m_upload = reg.counter(
            "resident_upload_bytes_total",
            "host->device block bytes uploaded at admission (warm resident "
            "scans move ZERO such bytes — tests assert on this counter)",
        )
        self._g_bytes = reg.gauge("resident_pool_bytes", "compressed bytes resident")
        self._g_pages = reg.gauge("resident_pool_pages", "pages in use (excl. zero page)")
        self._g_free = reg.gauge("resident_pool_free_pages", "pages on the free list")
        self._g_entries = reg.gauge("resident_pool_entries", "page-table entries")
        self._g_occupancy = reg.gauge(
            "resident_pool_occupancy_ratio",
            "pages in use / pages total — with the gauges above, the "
            "self-scrape pipeline stores these as series, so occupancy/"
            "admission/eviction timelines are one PromQL query",
        )
        # per-shard residency heat (heat.py): charged by the query
        # router's resident-vs-streamed decisions, exposed in stats()
        # and as m3tpu_resident_shard_* counters — the measured signal
        # ROADMAP item 5's shard rebalance keys off
        self.heat = ShardHeat(registry=reg)

    # ---------- device buffer ----------

    @property
    def enabled(self) -> bool:
        o = self.options
        return o.enabled and o.num_pages > 1

    def _ensure_words(self):
        """Allocate the device page buffer on first admission (a node with
        the mode on but nothing sealed yet pays no device memory)."""
        if self._words is None:
            import jax.numpy as jnp

            self._words = jnp.zeros(
                (self.options.num_pages, self.options.page_words), jnp.uint32
            )
        return self._words

    def device_words(self):
        """Snapshot of the device page buffer (functional updates: the
        reference stays internally consistent for the caller even if an
        admission lands concurrently)."""
        with self._lock:
            return self._ensure_words() if self.enabled else None

    def device_bytes(self) -> int:
        """Bytes the page buffer actually holds on device RIGHT NOW —
        0 until first admission (unlike device_words, this never forces
        the lazy allocation: memory accounting must observe, not cause)."""
        with self._lock:
            return int(self._words.nbytes) if self._words is not None else 0

    # ---------- admission ----------

    def admit_block(
        self,
        namespace: str,
        shard_id: int,
        block_start: int,
        volume: int,
        items: list,
    ) -> AdmitResult:
        """Admit one sealed fileset block's streams in ONE batched upload.

        ``items``: ``[(series_id, stream_bytes, num_points_bound)]`` —
        empty streams are skipped (series absent from the block). All
        staged pages land with a single host->device transfer + scatter.

        Three phases so the TABLE lock is held only for bookkeeping —
        never across staging, the upload, or an XLA scatter compile
        (writers invalidating and queries planning keep flowing while a
        flush's pages upload):

        1. under the table lock: allocate pages (LRU-evicting published
           entries as needed) and park the new entries in ``_pending`` —
           invisible to readers, whose plan would otherwise gather pages
           the scatter hasn't written;
        2. no table lock: build the staging array and run the device
           scatter (serialized by the upload lock — the functional words
           chain must not fork);
        3. under the table lock: swap in the new words buffer and publish
           surviving pending entries (an invalidation that raced the
           upload drops its entry instead of publishing stale bytes).
        """
        if not self.enabled:
            return AdmitResult(0, 0, 0, False)
        o = self.options
        if o.namespaces and namespace not in o.namespaces:
            return AdmitResult(0, 0, 0, False)
        page_bytes = o.page_bytes
        plan: list[tuple[BlockKey, bytes, int, int]] = []  # key, stream, pages, points
        rejected_span = 0
        for sid, stream, num_points in items:
            if not stream:
                continue
            n_pages = -(-len(stream) // page_bytes)
            if n_pages > o.max_lane_pages:
                rejected_span += 1
                continue
            key = BlockKey(namespace, shard_id, bytes(sid), block_start, volume)
            plan.append((key, bytes(stream), n_pages, int(num_points)))
        rejected_budget = 0
        admitted = 0
        batch_entries: list[tuple[BlockKey, ResidentEntry, bytes]] = []
        with self._upload_lock:
            with self._lock:
                for key, stream, n_pages, num_points in plan:
                    pages = self._alloc_locked(n_pages)
                    if pages is None:
                        rejected_budget += 1
                        continue
                    old = self._od.pop(key, None)
                    if old is not None:
                        self._unindex_locked(key, old)
                        self._free.extend(old.pages)
                        self._resident_bytes -= old.nbytes
                    entry = ResidentEntry(
                        pages=tuple(pages),
                        num_bits=len(stream) * 8,
                        initial_unit=_initial_unit(stream),
                        num_points=num_points,
                        nbytes=len(stream),
                    )
                    self._pending[key] = entry
                    admitted += 1
                    batch_entries.append((key, entry, stream))
                words = self._ensure_words() if batch_entries else None
            # ---- no table lock: stage + upload ----
            # Pending pages are off the free list (never LRU-evicted), so
            # intra-batch cannibalization is impossible: each staged page
            # has exactly one owner and the scatter's indices are unique.
            # A racing invalidation can still DROP a pending entry; only
            # entries still pending at staging time get rows.
            staged_rows: list[np.ndarray] = []
            staged_idx: list[int] = []
            staged_keys: set = set()
            new_words = None
            if batch_entries:
                with self._lock:
                    survivors_snapshot = [
                        (key, entry, stream)
                        for key, entry, stream in batch_entries
                        if self._pending.get(key) is entry
                    ]
                for key, entry, stream in survivors_snapshot:
                    staged_keys.add(key)
                    for j, p in enumerate(entry.pages):
                        row = np.zeros(o.page_words, np.uint32)
                        chunk = stream[j * page_bytes : (j + 1) * page_bytes]
                        padded = chunk + b"\x00" * (-len(chunk) % 4)
                        row[: len(padded) // 4] = np.frombuffer(
                            padded, ">u4"
                        ).astype(np.uint32)
                        staged_rows.append(row)
                        staged_idx.append(p)
                if staged_rows:
                    new_words = self._upload(words, staged_rows, staged_idx)
            # ---- publish ----
            with self._lock:
                if new_words is not None:
                    self._words = new_words
                survivors = 0
                for key, entry, stream in batch_entries:
                    present = self._pending.get(key) is entry
                    if present:
                        del self._pending[key]
                    if present and key in staged_keys:
                        survivors += 1
                        self._od[key] = entry
                        self._index_locked(key)
                        self._resident_bytes += entry.nbytes
                    else:
                        # invalidated mid-upload (or dropped before
                        # staging): never publish; the pages belong to
                        # this batch, so reclamation happens HERE, not in
                        # the invalidation hook
                        self._free.extend(entry.pages)
                complete = (
                    admitted > 0
                    and rejected_span == 0
                    and rejected_budget == 0
                    and survivors == len(plan)
                )
                if complete:
                    self._complete.add((namespace, shard_id, block_start, volume))
                self.admissions += admitted
                self.rejections += rejected_span + rejected_budget
                self._m_admissions.inc(admitted)
                if rejected_span + rejected_budget:
                    self._m_rejections.inc(rejected_span + rejected_budget)
                self._publish_locked()
        return AdmitResult(admitted, rejected_span, rejected_budget, complete)

    def _upload(self, words, rows: list, idx: list):
        """One host->device transfer + functional scatter for the batch —
        runs WITHOUT the table lock (serialized by the upload lock; the
        caller publishes the returned buffer under the table lock).

        The page count is padded to a power of two (extra rows re-write
        zeros into the reserved zero page) so the jitted scatter compiles
        once per bucket, not once per fileset size."""
        import jax

        n = len(rows)
        n_pad = 1 << max(n - 1, 0).bit_length() if n else 1
        staged = np.zeros((n_pad, self.options.page_words), np.uint32)
        staged[:n] = np.stack(rows)
        indices = np.zeros(n_pad, np.int32)
        indices[:n] = np.asarray(idx, np.int32)
        self.upload_bytes += staged.nbytes
        self._m_upload.inc(staged.nbytes)
        return _scatter_pages(words, jax.device_put(indices), jax.device_put(staged))

    def _alloc_locked(self, n_pages: int) -> list | None:
        """Pop ``n_pages`` from the free list, LRU-evicting until they fit
        (never evicting page 0, which is not on the free list)."""
        while len(self._free) < n_pages:
            if not self._evict_one_locked():
                return None
        return [self._free.pop() for _ in range(n_pages)]

    def _evict_one_locked(self) -> bool:
        if not self._od:
            return False
        key, entry = self._od.popitem(last=False)
        self._unindex_locked(key, entry)
        self._free.extend(entry.pages)
        self._resident_bytes -= entry.nbytes
        self.evictions += 1
        self._m_evictions.inc()
        return True

    # ---------- lookup / scan planning ----------

    def get(self, key: BlockKey) -> ResidentEntry | None:
        with self._lock:
            entry = self._od.get(key)
            if entry is not None:
                self._od.move_to_end(key)
            return entry

    def is_complete(self, namespace: str, shard_id: int, block_start: int, volume: int) -> bool:
        with self._lock:
            return (namespace, shard_id, block_start, volume) in self._complete

    def __contains__(self, key: BlockKey) -> bool:
        with self._lock:
            return key in self._od

    def __len__(self) -> int:
        return len(self._od)

    def plan_scan(self, keys: list) -> "ResidentScanPlan | None":
        """Assemble the device gather inputs for ``keys`` (one lane per
        key, in order). Returns None if any key is not resident.

        Validates every page index against the pool extent BEFORE the
        device gather — a corrupt page table raises ResidentPoolError
        rather than reading out-of-bounds rows (jnp indexing would clamp
        silently, turning corruption into wrong results)."""
        o = self.options
        with self._lock:
            if not self.enabled or self._words is None:
                return None
            entries = []
            for key in keys:
                e = self._od.get(key)
                if e is None:
                    return None
                self._od.move_to_end(key)
                entries.append(e)
            words = self._words
        num_pages = o.num_pages
        max_lane = 1
        for e in entries:
            n = len(e.pages)
            if n > o.max_lane_pages:
                raise ResidentPoolError(
                    f"page table entry spans {n} pages > limit {o.max_lane_pages}"
                )
            if n * o.page_words * 32 < e.num_bits:
                raise ResidentPoolError(
                    f"page table entry holds {e.num_bits} bits in {n} pages "
                    f"of {o.page_words * 32} bits"
                )
            max_lane = max(max_lane, n)
        s = len(entries)
        # +1 trailing zero-page column: the decoder's 4-word lookahead past
        # a lane's last stream word then reads zeros, bit-identical to
        # BatchedSegments' pad words
        rows = np.zeros((s, max_lane + 1), np.int32)
        num_bits = np.zeros(s, np.int32)
        units = np.zeros(s, np.int32)
        num_points = 0
        for i, e in enumerate(entries):
            for j, p in enumerate(e.pages):
                if not 0 < p < num_pages:
                    raise ResidentPoolError(
                        f"corrupt page index {p} (pool has {num_pages} pages)"
                    )
                rows[i, j] = p
            num_bits[i] = e.num_bits
            units[i] = e.initial_unit
            num_points = max(num_points, e.num_points)
        return ResidentScanPlan(
            words=words,
            page_rows=rows,
            num_bits=num_bits,
            initial_unit=units,
            max_points=max(num_points, 1),
        )

    # ---------- invalidation surface (cache/invalidation.py drives this) ----------

    def invalidate_series_block(
        self, namespace: str, shard_id: int, series_id: bytes, block_start: int
    ) -> int:
        """Drop every volume of one (series, block) — the write hook."""
        with self._lock:
            self._drop_pending_locked(
                lambda k: k.series_key
                == (namespace, shard_id, series_id, block_start)
            )
            keys = self._by_series.pop(
                (namespace, shard_id, series_id, block_start), None
            )
            return self._drop_locked(keys)

    def invalidate_block(
        self, namespace: str, shard_id: int, block_start: int, below_volume=None
    ) -> int:
        """Drop a block's entries across series; ``below_volume`` restricts
        to superseded volumes (cold-flush supersession)."""
        with self._lock:
            self._drop_pending_locked(
                lambda k: k.block_key == (namespace, shard_id, block_start)
                and (below_volume is None or k.volume < below_volume)
            )
            keys = self._by_block.get((namespace, shard_id, block_start))
            if keys is None:
                # entries may be gone while the complete marker lingers
                # (e.g. all evicted): still clear markers for the block
                self._drop_complete_locked(namespace, shard_id, block_start, below_volume)
                return 0
            if below_volume is not None:
                keys = {k for k in keys if k.volume < below_volume}
            else:
                keys = set(keys)
            self._drop_complete_locked(namespace, shard_id, block_start, below_volume)
            return self._drop_locked(keys)

    def clear(self) -> int:
        with self._lock:
            self._drop_pending_locked(lambda k: True)
            n = len(self._od)
            for entry in self._od.values():
                self._free.extend(entry.pages)
            self._resident_bytes = 0
            self._od.clear()
            self._by_series.clear()
            self._by_block.clear()
            self._complete.clear()
            self.invalidations += n
            self._m_invalidations.inc(n)
            self._publish_locked()
            return n

    def _drop_pending_locked(self, match) -> None:
        """Drop matching in-flight admissions so stale data never
        publishes. Their pages stay OFF the free list — the admitting
        thread owns them and reclaims at publish time (the scatter may
        still be writing them)."""
        for key in [k for k in self._pending if match(k)]:
            del self._pending[key]

    def _drop_complete_locked(self, namespace, shard_id, block_start, below_volume) -> None:
        for g in [
            g
            for g in self._complete
            if g[0] == namespace
            and g[1] == shard_id
            and g[2] == block_start
            and (below_volume is None or g[3] < below_volume)
        ]:
            self._complete.discard(g)

    def _drop_locked(self, keys) -> int:
        if not keys:
            return 0
        dropped = 0
        for key in list(keys):
            entry = self._od.pop(key, None)
            if entry is None:
                continue
            self._unindex_locked(key, entry)
            self._free.extend(entry.pages)
            self._resident_bytes -= entry.nbytes
            dropped += 1
        self.invalidations += dropped
        self._m_invalidations.inc(dropped)
        self._publish_locked()
        return dropped

    # ---------- bookkeeping ----------

    def _index_locked(self, key: BlockKey) -> None:
        self._by_series.setdefault(key.series_key, set()).add(key)
        self._by_block.setdefault(key.block_key, set()).add(key)

    def _unindex_locked(self, key: BlockKey, entry: ResidentEntry) -> None:
        for index, sub in (
            (self._by_series, key.series_key),
            (self._by_block, key.block_key),
        ):
            keys = index.get(sub)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del index[sub]
        # any entry leaving the pool makes its fileset group incomplete
        self._complete.discard(
            (key.namespace, key.shard_id, key.block_start, key.volume)
        )

    def _publish_locked(self) -> None:
        used = self.options.num_pages - 1 - len(self._free)
        self._g_bytes.set(float(self._resident_bytes))
        self._g_pages.set(float(used))
        self._g_free.set(float(len(self._free)))
        self._g_entries.set(float(len(self._od)))
        self._g_occupancy.set(used / max(self.options.num_pages - 1, 1))

    def stats(self) -> dict:
        with self._lock:
            o = self.options
            used_pages = o.num_pages - 1 - len(self._free)
            resident_bytes = self._resident_bytes
            return {
                "enabled": self.enabled,
                "entries": len(self._od),
                "bytes": resident_bytes,
                "max_bytes": o.max_bytes,
                "page_bytes": o.page_bytes,
                "pages_used": used_pages,
                "pages_total": max(o.num_pages - 1, 0),
                "occupancy": used_pages / max(o.num_pages - 1, 1),
                "complete_blocks": len(self._complete),
                "admissions": self.admissions,
                "rejections": self.rejections,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "upload_bytes": self.upload_bytes,
                "shard_heat": self.heat.dump(),
            }


class ResidentScanPlan(NamedTuple):
    """Device gather inputs for one resident scan (pool.plan_scan)."""

    words: object  # device uint32[num_pages, page_words]
    page_rows: np.ndarray  # int32[S, L] page index per lane slot (0 = zero page)
    num_bits: np.ndarray  # int32[S]
    initial_unit: np.ndarray  # int32[S]
    max_points: int


def _scatter_pages(words, indices, staged):
    """Functional page scatter (jitted lazily; module import stays light)."""
    import jax

    global _SCATTER_JIT
    if _SCATTER_JIT is None:
        _SCATTER_JIT = jax.jit(lambda w, i, s: w.at[i].set(s))
    return _SCATTER_JIT(words, indices, staged)


_SCATTER_JIT = None
