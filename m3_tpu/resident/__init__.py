"""HBM-resident compressed series store.

Keeps sealed blocks' M3TSZ bytes resident in device memory (a paged pool
under a byte budget, pool.py) and decodes them on read (scan.py): warm
scans move zero block bytes host->device, and series selection is a
device gather of page rows instead of a host select/pack. The design of
the reference TSDB's in-memory tier (M3/M3TSZ after Pelkonen et al.'s
Gorilla), restated as a paged KV-cache-style memory manager for the
scan-and-aggregate hot path.
"""

from .heat import ShardHeat
from .pool import (
    AdmitResult,
    ResidentChunkedPlan,
    ResidentEntry,
    ResidentOptions,
    ResidentPool,
    ResidentPoolError,
)
from .scan import resident_fetch_arrays, resident_scan_totals

__all__ = [
    "AdmitResult",
    "ResidentChunkedPlan",
    "ResidentEntry",
    "ResidentOptions",
    "ResidentPool",
    "ResidentPoolError",
    "ShardHeat",
    "resident_fetch_arrays",
    "resident_scan_totals",
]
