"""Decode-from-HBM scan orchestration over the paged resident pool.

Bridges the host page table (pool.py) and the device scan path
(parallel/scan.py): plans the gather, pads lanes into power-of-two jit
buckets, runs the decode, and reconstructs exact host arrays when the
caller needs datapoints rather than aggregates.

Bit-exactness contract: ``resident_scan_totals`` and
``streamed_scan_totals`` run the SAME decode kernel over the SAME padded
[S, T] shape (identical reduction trees), so on identical input streams
their float32 totals match bit for bit — the property tests assert exact
equality, not tolerance.
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils.instrument import DEFAULT as METRICS

# host->device block bytes moved by the STREAMED scan path (the fallback
# when matched blocks are not fully resident); warm resident scans leave
# this and resident_upload_bytes_total untouched — the zero-transfer
# acceptance test asserts on both counters
_M_STREAMED_BYTES = METRICS.counter(
    "scan_streamed_bytes_total",
    "host->device block bytes uploaded by the streamed scan fallback",
)

_MIN_LANES = 8  # also the forced CPU test mesh size (conftest)


def _pow2(n: int, lo: int = 1) -> int:
    return max(lo, 1 << max(int(n) - 1, 0).bit_length())


def _pad_lanes(page_rows, num_bits, units, s_pad: int):
    s, l = page_rows.shape
    rows = np.zeros((s_pad, l), np.int32)
    rows[:s] = page_rows
    nb = np.zeros(s_pad, np.int32)
    nb[:s] = num_bits
    un = np.zeros(s_pad, np.int32)
    un[:s] = units
    return rows, nb, un


def resident_scan_totals(pool, keys: list, mesh=None):
    """Scan-and-aggregate the resident lanes for ``keys`` (one lane per
    (series, block) key). Returns a ScanAggregates with the series arrays
    sliced back to ``len(keys)``, or None when any key is not resident.

    ``mesh``: shard the lanes across a device mesh (parallel/scan.py
    make_sharded_resident_scan, psum reduction unchanged); None = single
    device."""
    from ..parallel.scan import resident_scan_aggregate

    plan = pool.plan_scan(keys)
    if plan is None:
        return None
    s = len(keys)
    s_pad = _pow2(s, _MIN_LANES)
    if mesh is not None:
        n_dev = mesh.devices.size
        s_pad = _pow2(max(s_pad, n_dev), _MIN_LANES)
    rows, nb, un = _pad_lanes(plan.page_rows, plan.num_bits, plan.initial_unit, s_pad)
    max_points = _pow2(plan.max_points)
    if mesh is not None:
        aggs = _sharded_scan(mesh, max_points)(plan.words, rows, nb, un)
    else:
        aggs = resident_scan_aggregate(plan.words, rows, nb, un, max_points)
    return _slice_series(aggs, s)


@functools.lru_cache(maxsize=32)
def _sharded_scan(mesh, max_points: int):
    from ..parallel.scan import make_sharded_resident_scan

    return make_sharded_resident_scan(mesh, max_points)


def streamed_scan_totals(segments: list, point_bounds: list):
    """The streamed twin of resident_scan_totals: upload ``segments``
    (one m3tsz stream per lane) and run the same decode + aggregation
    with the same padding buckets (series_err carried the same way).
    Charges the uploaded bytes to scan_streamed_bytes_total."""
    import jax

    from ..parallel.scan import scan_aggregate_with_err
    from ..segment.batched import BatchedSegments

    s = len(segments)
    s_pad = _pow2(s, _MIN_LANES)
    batch = BatchedSegments.from_streams(list(segments) + [b""] * (s_pad - s))
    units = batch.initial_units()
    max_points = _pow2(max(point_bounds, default=1))
    words = jax.device_put(batch.words)
    _M_STREAMED_BYTES.inc(batch.words.nbytes)
    aggs = scan_aggregate_with_err(words, batch.num_bits, units, max_points)
    return _slice_series(aggs, s)


def _slice_series(aggs, s: int):
    return aggs._replace(
        series_sum=np.asarray(aggs.series_sum)[:s],
        series_count=np.asarray(aggs.series_count)[:s],
        series_min=np.asarray(aggs.series_min)[:s],
        series_max=np.asarray(aggs.series_max)[:s],
        series_last=np.asarray(aggs.series_last)[:s],
        series_err=(
            np.asarray(aggs.series_err)[:s] if aggs.series_err is not None else None
        ),
    )


def resident_fetch_arrays(pool, keys: list):
    """Exact datapoint reconstruction from HBM: decode the resident lanes
    for ``keys`` and return ``([(times i64[n], values f64[n])], err bool[S])``
    — bit-exact vs the host codec (ops/decode.finalize_decode), with
    ``err[i]`` flagging lanes the device decoder bailed on (annotated
    streams) so the caller can re-read those through the host path.

    Returns None when any key is not resident."""
    from ..ops.decode import decode_batched, finalize_decode
    from ..parallel.scan import gather_lane_words

    plan = pool.plan_scan(keys)
    if plan is None:
        return None
    s = len(keys)
    s_pad = _pow2(s, _MIN_LANES)
    rows, nb, un = _pad_lanes(plan.page_rows, plan.num_bits, plan.initial_unit, s_pad)
    words = gather_lane_words(plan.words, rows)
    res = decode_batched(words, nb, un, max_points=_pow2(plan.max_points))
    timestamps, values, valid = finalize_decode(res)
    err = np.asarray(res.err, bool)[:s]
    out = []
    for i in range(s):
        m = valid[i]
        out.append((timestamps[i][m], values[i][m]))
    return out, err
