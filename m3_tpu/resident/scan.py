"""Decode-from-HBM scan orchestration over the paged resident pool.

Bridges the host page table (pool.py) and the device scan path
(parallel/scan.py). Since the side planes landed in the pool (PR 11),
the resident scan is CHUNK-PARALLEL: plan_chunked hands over O(series)
int vectors, assemble_resident_packed builds the PackedLanes view by
device gather over page rows + side planes, and the SAME packed fused
kernel the streamed pipeline (parallel/stream.py) dispatches decodes it
— no host rebuild of chunk tables, no T-step whole-stream scan.

Bit-exactness contract: ``resident_scan_totals`` and
``streamed_scan_totals`` funnel through ONE shared decode + aggregation
path (parallel/scan.chunked_scan_aggregate_packed) over
identically-shaped, bit-identical packed lane arrays (the device
assembly mirrors ops/fused.pack_lane_inputs exactly, tile flags
included), so on identical input streams their float32 totals match bit
for bit — the property tests assert exact equality, not tolerance.
"""

from __future__ import annotations

import functools

import numpy as np

from ..storage.fs import CHUNK_K
from ..utils.instrument import DEFAULT as METRICS

# host->device block bytes moved by the STREAMED scan path (the fallback
# when matched blocks are not fully resident); warm resident scans leave
# this and resident_upload_bytes_total untouched — the zero-transfer
# acceptance test asserts on both counters
_M_STREAMED_BYTES = METRICS.counter(
    "scan_streamed_bytes_total",
    "host->device block bytes uploaded by the streamed scan fallback",
)

_MIN_LANES = 8  # also the forced CPU test mesh size (conftest)


def _pow2(n: int, lo: int = 1) -> int:
    return max(lo, 1 << max(int(n) - 1, 0).bit_length())


def resident_scan_totals(pool, keys: list, mesh=None, device_out: bool = False):
    """Scan-and-aggregate the resident lanes for ``keys`` (one lane per
    (series, block) key) through the chunk-parallel kernels. Returns a
    ScanAggregates with the series arrays sliced back to ``len(keys)``,
    or None when any key is not resident (or carries no side planes —
    the caller streams instead, keeping the parity contract trivially).

    ``mesh``: shard the lanes across a device mesh (parallel/scan.py
    make_sharded_resident_chunked_scan, psum reduction unchanged);
    None = single device. ``device_out``: skip the host conversion and
    return the PADDED device aggregates — callers that pipeline scans
    (bench, batched executors) drain results themselves so dispatch of
    scan N+1 overlaps compute of scan N."""
    from ..parallel.scan import RESIDENT_CHUNKED_PROF, pad_chunked_plan

    with pool.read_lease():
        plan = pool.plan_chunked(keys)
        if plan is None:
            return None
        s = len(keys)
        s_pad = _pow2(s, _MIN_LANES)
        if mesh is not None:
            n_dev = mesh.devices.size
            s_pad = _pow2(max(s_pad, n_dev), _MIN_LANES)
        vecs = pad_chunked_plan(plan, s_pad)
        shape_key = (plan.num_chunks, plan.chunk_k, plan.window_words,
                     plan.page_words, plan.side_page_chunks)
        if mesh is not None:
            fn = _sharded_chunked(mesh, *shape_key)
        else:
            fn = _packed_scan_fn(*shape_key)
        with RESIDENT_CHUNKED_PROF.dispatch(
            ("scan", s_pad, *shape_key, mesh is not None)
        ) as d:
            aggs = d.done(fn(plan.words, plan.side, *vecs))
        return aggs if device_out else _slice_series(aggs, s)


@functools.lru_cache(maxsize=32)
def _packed_scan_fn(c: int, k: int, cw: int, w: int, spc: int):
    """ONE jitted program per plan shape: PackedLanes assembly (device
    gathers over the pool + side planes) fused with the packed decode
    kernel — the gathered lane arrays never materialize between
    dispatches. The body is parallel/scan.resident_chunked_local_fn,
    shared with the sharded variant so the two paths can't diverge."""
    import jax

    from ..parallel.scan import resident_chunked_local_fn

    return jax.jit(resident_chunked_local_fn(c, k, cw, w, spc))


@functools.lru_cache(maxsize=32)
def _sharded_chunked(mesh, c: int, k: int, cw: int, w: int, spc: int):
    from ..parallel.scan import make_sharded_resident_chunked_scan

    return make_sharded_resident_chunked_scan(mesh, c, k, cw, w, spc)


def streamed_scan_totals(segments: list, k: int = CHUNK_K):
    """The streamed twin of resident_scan_totals: prescan + upload
    ``segments`` (one m3tsz stream per lane) as chunk lanes and run the
    same decode + aggregation with the same padding buckets (series_err
    carried the same way). Charges the uploaded bytes to
    scan_streamed_bytes_total. ``k`` must match the chunk size the
    resident path decodes with (the fileset's chunkK) for the bit-exact
    parity contract — the chunk decomposition sets the f32 reduction
    order."""
    import jax

    from ..ops.chunked import build_chunked
    from ..ops.fused import pack_lane_inputs
    from ..parallel.scan import chunked_scan_aggregate_packed

    s = len(segments)
    s_pad = _pow2(s, _MIN_LANES)
    batch = build_chunked(list(segments) + [b""] * (s_pad - s), k=k)
    packed = pack_lane_inputs(batch)
    windows4 = jax.device_put(packed.windows4)
    lanes4 = jax.device_put(packed.lanes4)
    tile_flags = jax.device_put(packed.tile_flags)
    # counter semantics: compressed BLOCK bytes the fallback had to move
    # off-pool (the quantity residency eliminates, matching the metric
    # name/help, shard heat, and the upload_bytes comparison) — NOT the
    # packed lane arrays, which duplicate overlapping window words
    # across chunks and would silently rescale dashboards several-fold
    _M_STREAMED_BYTES.inc(sum(len(seg) for seg in segments))
    aggs = chunked_scan_aggregate_packed(
        windows4, lanes4, tile_flags,
        n=packed.n, s=s_pad, c=batch.num_chunks, k=k,
        lane_order=packed.order, interpret=jax.default_backend() != "tpu",
    )
    return _slice_series(aggs, s)


_SERIES_FIELDS = (
    "series_sum", "series_count", "series_min", "series_max",
    "series_last", "series_err",
)


def _slice_series(aggs, s: int):
    out = {}
    for name in _SERIES_FIELDS:
        v = getattr(aggs, name)
        # m3lint: disable=M3L010 -- sanctioned end-of-scan host finalize: the one device->host copy after the fused dispatch (device_out=True is the zero-copy pipelining escape)
        out[name] = np.asarray(v)[:s] if v is not None else None
    return aggs._replace(**out)


def resident_fetch_arrays(pool, keys: list):
    """Exact datapoint reconstruction from HBM: decode the resident lanes
    for ``keys`` through the chunked kernel and return
    ``([(times i64[n], values f64[n])], err bool[S])`` — bit-exact vs the
    host codec (ops/decode.finalize_decode), with ``err[i]`` flagging
    lanes the device decoder bailed on (annotated streams) so the caller
    can re-read those through the host path.

    Returns None when any key is not resident."""
    from ..ops.chunked import decode_chunked_lanes
    from ..ops.decode import DecodeResult, finalize_decode
    from ..parallel.scan import RESIDENT_CHUNKED_PROF, assemble_resident_lanes

    with pool.read_lease():
        plan = pool.plan_chunked(keys)
        if plan is None:
            return None
        s = len(keys)
        s_pad = _pow2(s, _MIN_LANES)
        lane_args, s_pad = assemble_resident_lanes(plan, s_pad)
        c, k = plan.num_chunks, plan.chunk_k
        with RESIDENT_CHUNKED_PROF.dispatch(
            ("fetch", tuple(lane_args["windows"].shape), int(k))
        ) as d:
            res = d.done(decode_chunked_lanes(**lane_args, k=k))

    import jax.numpy as jnp

    rs = lambda x: x.reshape(s_pad, c * k)
    res = DecodeResult(
        ts_hi=rs(res.ts_hi),
        ts_lo=rs(res.ts_lo),
        val_hi=rs(res.val_hi),
        val_lo=rs(res.val_lo),
        point_is_float=rs(res.point_is_float),
        mult=rs(res.mult),
        valid=rs(res.valid),
        err=jnp.any(res.err.reshape(s_pad, c), axis=1),
        values_f32=rs(res.values_f32),
    )
    timestamps, values, valid = finalize_decode(res)
    err = np.asarray(res.err, bool)[:s]
    out = []
    for i in range(s):
        m = valid[i]
        out.append((timestamps[i][m], values[i][m]))
    return out, err
