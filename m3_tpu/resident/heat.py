"""Per-shard residency heat: the measured signal shard rebalancing needs.

ROADMAP item 5 (elastic placement) rebalances the resident budget across
a mesh "by per-shard heat" — this module measures that heat where the
routing decisions actually happen (query/m3_storage.py): per shard,

- ``hits``   — resident lanes served from the HBM pool,
- ``misses`` — fallbacks to the streamed path while the pool was on
  (evicted / never admitted / buffered overlay),
- ``streamed_bytes`` — block bytes the streamed scan fallback moved for
  that shard (the PCIe cost residency would have eliminated).

Exposed three ways: ``m3tpu_resident_shard_*{shard}`` counters (stored
as series via selfmon, so heat timelines are PromQL), the
``resident_stats`` debug op (``shard_heat``), and ``/debug/dump``.

Cardinality: the ``shard`` label value is a configured shard id —
bounded by ``--num-shards`` in practice — but ids reach this module from
query routing, so a hard cap (``M3_TPU_SHARD_HEAT_CAP``, default 1024)
collapses the excess into ``__overflow__``, counted loudly, the same
discipline as the tenant ledger.
"""

from __future__ import annotations

import os
import threading

from ..utils.instrument import DEFAULT as METRICS

OVERFLOW_SHARD = "__overflow__"


def _env_cap() -> int:
    try:
        return max(int(os.environ.get("M3_TPU_SHARD_HEAT_CAP", "1024")), 1)
    except ValueError:
        return 1024


class ShardHeat:
    """Capped per-shard hit/miss/streamed-bytes accounting."""

    def __init__(self, registry=None, cap: int | None = None) -> None:
        self._reg = registry or METRICS
        self.cap = _env_cap() if cap is None else max(int(cap), 1)
        self._lock = threading.Lock()
        # shard label value -> (hits, misses, streamed_bytes counters)
        self._counters: dict = {}
        self._m_overflow = self._reg.counter(
            "resident_shard_overflow_total",
            "heat charges collapsed into the __overflow__ shard past the "
            "per-shard cardinality cap (M3_TPU_SHARD_HEAT_CAP)",
        )

    def _handles(self, shard_id):
        key = str(shard_id)
        handles = self._counters.get(key)
        if handles is not None:
            return handles
        overflowed = False
        with self._lock:
            handles = self._counters.get(key)
            if handles is not None:
                return handles
            if len(self._counters) >= self.cap and key != OVERFLOW_SHARD:
                # collapse in place — NOT via recursion, which would
                # re-acquire this non-reentrant lock and deadlock
                overflowed = True
                key = OVERFLOW_SHARD
                handles = self._counters.get(key)
                if handles is not None:
                    self._m_overflow.inc()
                    return handles
            labels = {"shard": key}
            handles = self._counters[key] = (
                self._reg.counter(
                    "resident_shard_hits_total",
                    "resident lanes served from the HBM pool, per shard — "
                    "the heat signal shard rebalancing keys off",
                    labels=labels,
                ),
                self._reg.counter(
                    "resident_shard_misses_total",
                    "streamed fallbacks while the pool was on, per shard",
                    labels=labels,
                ),
                self._reg.counter(
                    "resident_shard_streamed_bytes_total",
                    "block bytes moved by the streamed scan fallback, per "
                    "shard (the transfer cost residency would remove)",
                    labels=labels,
                ),
            )
        if overflowed:
            self._m_overflow.inc()
        return handles

    def charge(
        self, shard_id, hits: int = 0, misses: int = 0, streamed_bytes: int = 0
    ) -> None:
        h, m, b = self._handles(shard_id)
        if hits:
            h.inc(hits)
        if misses:
            m.inc(misses)
        if streamed_bytes:
            b.inc(streamed_bytes)

    def dump(self) -> dict:
        """{shard: {"hits", "misses", "streamedBytes"}} — the
        resident_stats / /debug/dump shape."""
        with self._lock:
            items = list(self._counters.items())
        return {
            shard: {
                "hits": h.value,
                "misses": m.value,
                "streamedBytes": b.value,
            }
            for shard, (h, m, b) in sorted(items)
        }
