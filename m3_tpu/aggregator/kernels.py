"""Batched aggregation kernels: the TPU replacement for per-metric accumulator
objects.

Reference: /root/reference/src/aggregator/aggregation/{counter,timer,gauge}.go
accumulate one value at a time into per-(metric, policy, window) structs; the
CM quantile stream (quantile/cm/stream.go) maintains approximate quantiles
online. Here a whole flush interval of datapoints is aggregated at once:
segment reductions over (metric, window) keys for sum/count/min/max/sumSq/
last, and **exact** quantiles via a global sort — replacing the CM stream.

Quantile tolerance policy: the reference's CM stream guarantees rank error
within eps=1e-3; exact sorted quantiles are strictly more accurate, so any
consumer contract written against the CM stream holds. Parity tests compare
against exact quantiles with the reference's interpolation (statsite-style
floor rank, quantile/cm/stream.go:103-150 Quantile()).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics.types import AggregationType

F32 = jnp.float32
I32 = jnp.int32


class WindowedAggregates(NamedTuple):
    """[G] arrays keyed by dense (metric, window) group id."""

    sum: jnp.ndarray
    count: jnp.ndarray
    min: jnp.ndarray
    max: jnp.ndarray
    sum_sq: jnp.ndarray
    mean: jnp.ndarray
    stdev: jnp.ndarray
    last: jnp.ndarray


def window_keys(
    ids: np.ndarray, times_nanos: np.ndarray, window0_nanos: int, resolution_nanos: int, n_windows: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side exact i64 window bucketing → (keys, window_idx, time_order).

    keys = id * n_windows + window_idx (dense group key); time_order is an
    i32 within-window ordering value for `last` resolution (nanos offset
    clipped to i32 — windows are << 2s only for sub-second resolutions, where
    ns offsets still fit i32 after downshift)."""
    w = (times_nanos - window0_nanos) // resolution_nanos
    w = np.clip(w, 0, n_windows - 1)
    keys = ids.astype(np.int64) * n_windows + w
    # i32 keys only when they fit (grids past INT32_MAX groups keep i64 —
    # downstream pack_dense_groups indexes in i64 either way)
    if keys.size == 0 or int(keys.max()) <= np.iinfo(np.int32).max:
        keys = keys.astype(np.int32)
    off = times_nanos - (window0_nanos + w * resolution_nanos)
    # shift so the order value always fits i32 regardless of resolution
    shift = 0
    maxoff = int(off.max(initial=0))
    while maxoff >> shift > 0x3FFFFFFF:
        shift += 1
    return keys, w.astype(np.int32), (off >> shift).astype(np.int32)


@functools.partial(jax.jit, static_argnames=("n_groups",))
def aggregate_segments(keys, values, time_order, n_groups: int) -> WindowedAggregates:
    """Segment reductions per dense key. Matches counter/gauge Update()
    semantics: last takes the value with the greatest time_order (first
    arrival wins ties, gauge.go:57-66)."""
    keys = jnp.asarray(keys, I32)
    values = jnp.asarray(values, F32)
    n = n_groups

    s = jax.ops.segment_sum(values, keys, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(values), keys, num_segments=n)
    mn = jax.ops.segment_min(values, keys, num_segments=n)
    mx = jax.ops.segment_max(values, keys, num_segments=n)
    ss = jax.ops.segment_sum(values * values, keys, num_segments=n)

    # last: value at the greatest time_order; ties keep the EARLIEST arrival
    # (strictly-after wins — timestamp.After in gauge.go:58). Two-stage
    # segment argmax in i32 (no i64 on TPU): best order per group, then the
    # minimum arrival index among entries at that order.
    m = values.shape[0]
    idx = jnp.arange(m, dtype=I32)
    torder = jnp.asarray(time_order, I32)
    best = jax.ops.segment_max(torder, keys, num_segments=n)
    is_best = torder == jnp.take(best, keys, axis=0)
    first_best = jax.ops.segment_min(jnp.where(is_best, idx, m), keys, num_segments=n)
    last = jnp.take(values, jnp.clip(first_best, 0, m - 1))

    mean = jnp.where(c > 0, s / jnp.maximum(c, 1), 0.0)
    div = c * (c - 1)
    stdev = jnp.sqrt(
        jnp.maximum((c * ss - s * s) / jnp.where(div == 0, 1, div), 0.0)
    )
    stdev = jnp.where(div == 0, 0.0, stdev)
    empty = c == 0
    return WindowedAggregates(
        sum=jnp.where(empty, 0.0, s),
        count=c,
        min=jnp.where(empty, jnp.nan, mn),
        max=jnp.where(empty, jnp.nan, mx),
        sum_sq=jnp.where(empty, 0.0, ss),
        mean=mean,
        stdev=stdev,
        last=jnp.where(empty, jnp.nan, last),
    )


@functools.partial(jax.jit, static_argnames=("n_groups", "qs"))
def segment_quantiles(keys, values, n_groups: int, qs: tuple) -> jnp.ndarray:
    """Exact per-group quantiles via one global sort.

    Returns [len(qs), G]. Interpolation matches the CM stream's Quantile()
    (quantile/cm/stream.go): rank = q*(n-1) floor/ceil linear interpolation
    on the sorted values."""
    keys = jnp.asarray(keys, I32)
    values = jnp.asarray(values, F32)
    n = values.shape[0]
    g = n_groups if isinstance(n_groups, int) else int(n_groups)

    # stable sort by (key, value): sort values first, then stable-sort by key
    order1 = jnp.argsort(values, stable=True)
    k1 = jnp.take(keys, order1)
    order2 = jnp.argsort(k1, stable=True)
    perm = jnp.take(order1, order2)
    sv = jnp.take(values, perm)  # values sorted within each key run

    counts = jax.ops.segment_sum(jnp.ones((n,), I32), keys, num_segments=g)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix

    outs = []
    for q in qs:
        rank = q * jnp.maximum(counts - 1, 0).astype(F32)
        lo = jnp.floor(rank).astype(I32)
        hi = jnp.minimum(lo + 1, jnp.maximum(counts - 1, 0))
        frac = rank - lo.astype(F32)
        vlo = jnp.take(sv, jnp.clip(starts + lo, 0, n - 1))
        vhi = jnp.take(sv, jnp.clip(starts + hi, 0, n - 1))
        outs.append(jnp.where(counts > 0, vlo + (vhi - vlo) * frac, jnp.nan))
    return jnp.stack(outs)


# --- dense (TPU-first) rollup path -----------------------------------------
# jax.ops.segment_* lower to scatters and TPU scatters/gathers are
# pathological (measured ~12M dp/s at 60M samples — slower than host numpy).
# The flush path owns its data host-side anyway, so it densifies to
# [G, P] (P = max points per group, bounded by window/resolution) with
# vectorized numpy, and the device does pure vector reductions + an axis
# sort — no scatter, no gather, nothing data-dependent.


def pack_dense_groups(keys, values, time_order, n_groups: int):
    """Host densification: (keys[n], values[n], time_order[n]) →
    (vals[G, P], torder[G, P], valid[G, P]) with NaN/0 padding. Arrival
    order within a group is preserved (stable sort) so `last` tie-breaking
    keeps first-arrival-wins semantics."""
    keys = np.asarray(keys, np.int64)
    values = np.asarray(values, np.float32)
    torder = np.asarray(time_order, np.int32)
    n = len(keys)
    counts = np.bincount(keys, minlength=n_groups)
    p = max(int(counts.max(initial=0)), 1)
    order = np.argsort(keys, kind="stable")
    ks = keys[order]
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    pos = np.arange(n, dtype=np.int64) - starts[ks]
    vals = np.full((n_groups, p), np.nan, np.float32)
    tor = np.zeros((n_groups, p), np.int32)
    vals[ks, pos] = values[order]
    tor[ks, pos] = torder[order]
    return vals, tor, ~np.isnan(vals)


@jax.jit
def aggregate_dense(vals, torder, valid) -> WindowedAggregates:
    """WindowedAggregates over dense [G, P] groups — identical semantics to
    aggregate_segments (counter/gauge Update), pure vector ops."""
    vals = jnp.asarray(vals, F32)
    valid = jnp.asarray(valid)
    torder = jnp.asarray(torder, I32)
    v0 = jnp.where(valid, vals, 0.0)
    c = jnp.sum(valid, axis=1).astype(F32)
    s = jnp.sum(v0, axis=1)
    ss = jnp.sum(v0 * v0, axis=1)
    mn = jnp.min(jnp.where(valid, vals, jnp.inf), axis=1)
    mx = jnp.max(jnp.where(valid, vals, -jnp.inf), axis=1)
    # last: greatest time_order; ties keep the EARLIEST arrival (gauge.go:58
    # strictly-after wins). Select-via-compare, no gathers.
    p = vals.shape[1]
    pos = jnp.arange(p, dtype=I32)[None, :]
    t_eff = jnp.where(valid, torder, jnp.iinfo(jnp.int32).min)
    best_t = jnp.max(t_eff, axis=1)
    is_best = t_eff == best_t[:, None]
    first_pos = jnp.min(jnp.where(is_best, pos, p), axis=1)
    sel = is_best & (pos == first_pos[:, None])
    last = jnp.sum(jnp.where(sel, v0, 0.0), axis=1)

    mean = jnp.where(c > 0, s / jnp.maximum(c, 1), 0.0)
    div = c * (c - 1)
    stdev = jnp.sqrt(jnp.maximum((c * ss - s * s) / jnp.where(div == 0, 1, div), 0.0))
    stdev = jnp.where(div == 0, 0.0, stdev)
    empty = c == 0
    return WindowedAggregates(
        sum=jnp.where(empty, 0.0, s),
        count=c,
        min=jnp.where(empty, jnp.nan, mn),
        max=jnp.where(empty, jnp.nan, mx),
        sum_sq=jnp.where(empty, 0.0, ss),
        mean=mean,
        stdev=stdev,
        last=jnp.where(empty, jnp.nan, last),
    )


@functools.partial(jax.jit, static_argnames=("qs",))
def dense_quantiles(vals, valid, qs: tuple) -> jnp.ndarray:
    """Exact per-group quantiles over dense [G, P]: one vectorized sort
    along the P axis + select-via-compare rank interpolation. Matches
    segment_quantiles / the CM stream's Quantile() interpolation."""
    vals = jnp.asarray(vals, F32)
    valid = jnp.asarray(valid)
    p = vals.shape[1]
    sv = jnp.sort(jnp.where(valid, vals, jnp.inf), axis=1)  # NaN-pads last
    counts = jnp.sum(valid, axis=1).astype(F32)
    pos = jnp.arange(p, dtype=F32)[None, :]
    outs = []
    for q in qs:
        rank = q * jnp.maximum(counts - 1.0, 0.0)
        lo = jnp.floor(rank)
        hi = jnp.minimum(lo + 1.0, jnp.maximum(counts - 1.0, 0.0))
        frac = (rank - lo)[:, None]
        vlo = jnp.sum(jnp.where(pos == lo[:, None], sv, 0.0), axis=1)
        vhi = jnp.sum(jnp.where(pos == hi[:, None], sv, 0.0), axis=1)
        outs.append(
            jnp.where(counts > 0, vlo + (vhi - vlo) * frac[:, 0], jnp.nan)
        )
    return jnp.stack(outs)


def value_of(agg: WindowedAggregates, quantiles: dict, atype: AggregationType, g):
    """counter/timer/gauge ValueOf dispatch (counter.go:96-120 etc)."""
    q = atype.quantile()
    if q is not None:
        return quantiles[q][g]
    return {
        AggregationType.LAST: agg.last,
        AggregationType.MIN: agg.min,
        AggregationType.MAX: agg.max,
        AggregationType.MEAN: agg.mean,
        AggregationType.COUNT: agg.count,
        AggregationType.SUM: agg.sum,
        AggregationType.SUMSQ: agg.sum_sq,
        AggregationType.STDEV: agg.stdev,
    }[atype][g]
