"""Cormode–Muthukrishnan targeted-quantile stream (host-side).

Reference: /root/reference/src/aggregator/aggregation/quantile/cm/stream.go
(statsite-derived biased-quantiles sketch). The device aggregation path
(kernels.py) computes EXACT quantiles by sorting whole windows on the TPU —
strictly more accurate and the framework default — but the streaming sketch
matters where windows never materialize (host-side forwarding stages,
collector pre-aggregation), so the reference's component exists here with
the same contract: targeted quantiles with per-target error eps.

Algorithm (Cormode & Muthukrishnan, "Effective Computation of Biased
Quantiles over Data Streams"): a sorted list of (value, g, delta) samples;
inserts buffer and merge in sorted order; compress() merges adjacent
samples whose combined weight stays within the invariant f(r, n); query(q)
walks cumulative weights to the first sample crossing the target rank.
"""

from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass
class _Sample:
    value: float
    g: int  # rank gap to the previous sample
    delta: int  # rank uncertainty


class QuantileStream:
    """Targeted quantiles: quantiles q with error eps on each target.

    insert() amortizes through a buffer; samples stay O((1/eps) log(eps n)).
    """

    def __init__(self, quantiles=(0.5, 0.95, 0.99), eps: float = 0.01,
                 buffer_size: int = 512) -> None:
        if not quantiles:
            raise ValueError("need at least one target quantile")
        self.targets = tuple(sorted(float(q) for q in quantiles))
        if any(q <= 0.0 or q >= 1.0 for q in self.targets):
            raise ValueError("quantiles must be in (0, 1)")
        self.eps = eps
        self._samples: list[_Sample] = []
        self._buffer: list[float] = []
        self._buffer_size = buffer_size
        self.n = 0

    # invariant f(r, n): allowed weight span for a sample at rank r
    def _invariant(self, r: float, n: int) -> float:
        out = math.inf
        for q in self.targets:
            if r < q * n:
                err = 2 * self.eps * (n - r) / (1 - q)
            else:
                err = 2 * self.eps * r / q
            out = min(out, err)
        return max(out, 1.0)

    def insert(self, value: float) -> None:
        self._buffer.append(float(value))
        if len(self._buffer) >= self._buffer_size:
            self._flush_buffer()

    def _flush_buffer(self) -> None:
        if not self._buffer:
            return
        self._buffer.sort()
        samples = self._samples
        idx = 0
        for v in self._buffer:
            while idx < len(samples) and samples[idx].value <= v:
                idx += 1
            if idx == 0 or idx == len(samples):
                delta = 0
            else:
                # stream.go insert(): delta = successor.numRanks +
                # successor.delta - 1. Neighbor-based (not invariant-based)
                # deltas keep freshly inserted regions mergeable — with
                # invariant-based deltas a monotone stream's sketch
                # degenerates to a near-full buffer.
                nxt = samples[idx]
                delta = nxt.g + nxt.delta - 1
            samples.insert(idx, _Sample(v, 1, max(delta, 0)))
            idx += 1
            self.n += 1
        self._buffer.clear()
        self._compress()

    def _compress(self) -> None:
        # Back-to-front merge pass, mirroring the reference's compress cursor
        # (stream.go walks from the tail maintaining exact minRank). Merging
        # s[i] into its successor cascades naturally on monotone streams, and
        # the rank used for the invariant is the sample's true pre-merge
        # minRank — no double counting of absorbed weight.
        samples = self._samples
        if len(samples) < 3:
            return
        ranks = []  # ranks[i] = exact cumulative g through samples[i]
        acc = 0
        for s in samples:
            acc += s.g
            ranks.append(acc)
        out_rev = [samples[-1]]
        for i in range(len(samples) - 2, 0, -1):
            s = samples[i]
            nxt = out_rev[-1]
            max_rank = ranks[i] + s.delta  # stream.go compress(): maxRank
            if s.g + nxt.g + nxt.delta <= self._invariant(max_rank, self.n):
                nxt.g += s.g
            else:
                out_rev.append(s)
        out_rev.append(samples[0])
        self._samples = out_rev[::-1]

    def query(self, q: float) -> float:
        self._flush_buffer()
        samples = self._samples
        if not samples:
            return math.nan
        if len(samples) == 1:
            return samples[0].value
        target = q * self.n + self._invariant(q * self.n, self.n) / 2
        r = 0.0
        for i in range(1, len(samples)):
            r += samples[i - 1].g
            if r + samples[i].g + samples[i].delta > target:
                return samples[i - 1].value
        return samples[-1].value

    def flush(self) -> None:
        self._flush_buffer()

    @property
    def num_samples(self) -> int:
        return len(self._samples) + len(self._buffer)

    def min(self) -> float:
        self._flush_buffer()
        return self._samples[0].value if self._samples else math.nan

    def max(self) -> float:
        self._flush_buffer()
        return self._samples[-1].value if self._samples else math.nan
