"""Streaming aggregator: sharded ingest → windowed batch rollups → flush.

Reference: /root/reference/src/aggregator/aggregator/ — `aggregator.Aggregator`
(aggregator.go:66+ AddUntimed/AddTimed/AddForwarded), murmur3 shard routing
(:354 shardFor), per-(metric, policy) timed windows (generic_elem.go), and the
leader flush manager draining windows on resolution boundaries
(leader_flush_mgr.go:70).

TPU-native inversion: instead of per-metric accumulator objects updated one
value at a time, each shard buffers (id, time, value) columns per storage
policy and a flush drains whole windows through the segment kernels
(kernels.py) in one device call. Entry bookkeeping (id interning) is host-side
dict work, exactly the role the reference's entry.go hashmap plays.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable

import numpy as np

from ..metrics.policy import StoragePolicy
from ..metrics.types import AggregationType, MetricType, Untimed
from ..utils.hash import shard_for
from .kernels import (
    aggregate_dense,
    dense_quantiles,
    pack_dense_groups,
    window_keys,
)


@dataclass
class AggregatedMetric:
    """One flushed datapoint (metric/aggregated/types.go Metric)."""

    id: bytes
    time_nanos: int  # window END, like elems flush (generic_elem.go timestamps)
    value: float
    policy: StoragePolicy
    agg_type: AggregationType

    @property
    def suffixed_id(self) -> bytes:
        """id + '.' + type string (types_options.go suffix scheme)."""
        return self.id + b"." + self.agg_type.type_string.encode()


class _PolicyBuffer:
    """Pending raw values for one storage policy within one shard.

    Growable numpy COLUMNS (amortized-doubling appends), not Python
    lists: ingest appends whole value batches with one slice store, and
    a drain hands the segment kernels contiguous array views with zero
    list→array conversion on the flush path — the aggregation tier's
    equivalent of the ingest column planes."""

    __slots__ = ("ids", "times", "values", "types", "n")

    def __init__(self, cap: int = 256) -> None:
        self.ids = np.empty(cap, np.int32)
        self.times = np.empty(cap, np.int64)
        self.values = np.empty(cap, np.float32)
        self.types = np.empty(cap, np.int32)
        self.n = 0

    def _grow(self, need: int) -> None:
        cap = len(self.ids)
        if self.n + need <= cap:
            return
        new_cap = max(cap * 2, self.n + need)
        for name in ("ids", "times", "values", "types"):
            a = getattr(self, name)
            b = np.empty(new_cap, a.dtype)
            b[: self.n] = a[: self.n]
            setattr(self, name, b)

    def extend(self, idx: int, time_nanos: int, values, mtype: int) -> None:
        k = len(values)
        self._grow(k)
        n = self.n
        self.ids[n : n + k] = idx
        self.times[n : n + k] = time_nanos
        self.values[n : n + k] = values
        self.types[n : n + k] = mtype
        self.n = n + k

    def compact(self, keep: np.ndarray) -> None:
        """Retain only ``keep``-masked rows (the unflushed tail), in
        place — fancy-index RHS copies before the slice store."""
        nk = int(keep.sum())
        n = self.n
        self.ids[:nk] = self.ids[:n][keep]
        self.times[:nk] = self.times[:n][keep]
        self.values[:nk] = self.values[:n][keep]
        self.types[:nk] = self.types[:n][keep]
        self.n = nk


class _Shard:
    """aggregatorShard (shard.go): owns interned metric entries + buffers.

    Interned ids play the role of entry.go's per-metric entries: each
    carries a last-write stamp (TTL expiry, entry.go maybeExpire) and a
    token-bucket state for the per-metric value rate limit
    (rate_limit.go)."""

    def __init__(self) -> None:
        self.id_index: dict[bytes, int] = {}
        self.ids: list[bytes] = []
        self.metric_types: list[MetricType] = []
        self.agg_overrides: dict[int, tuple[AggregationType, ...]] = {}
        self.buffers: dict[StoragePolicy, _PolicyBuffer] = {}
        self.last_write: list[int] = []  # nanos, per interned id
        self.rl_tokens: list[float] = []
        self.rl_stamp: list[int] = []  # last refill nanos
        self.rate_limited = 0  # values dropped by the per-entry limit

    def intern(self, mid: bytes, mtype: MetricType) -> int:
        idx = self.id_index.get(mid)
        if idx is None:
            idx = len(self.ids)
            self.id_index[mid] = idx
            self.ids.append(mid)
            self.metric_types.append(mtype)
            self.last_write.append(0)
            self.rl_tokens.append(0.0)
            self.rl_stamp.append(-1)  # -1 = never refilled (0 is a valid time)
        return idx

    def admit(self, idx: int, n_values: int, now_nanos: int, limit: float | None) -> bool:
        """Token-bucket admission at ``limit`` values/sec (burst = one
        second's worth); None = unlimited. A write is admitted whenever the
        bucket is non-empty and may overdraw it (a batch larger than the
        burst is throttled by the resulting debt, not dropped forever)."""
        self.last_write[idx] = max(self.last_write[idx], now_nanos)
        if limit is None:
            return True
        if self.rl_stamp[idx] < 0:
            self.rl_tokens[idx] = limit  # first write: full bucket
        else:
            # out-of-order writes must not rewind the stamp (a rewound
            # stamp hands the next in-order write a spurious refill)
            elapsed = max(now_nanos - self.rl_stamp[idx], 0)
            self.rl_tokens[idx] = min(
                limit, self.rl_tokens[idx] + limit * (elapsed / 1e9)
            )
        self.rl_stamp[idx] = max(self.rl_stamp[idx], now_nanos)
        if self.rl_tokens[idx] > 0:
            self.rl_tokens[idx] -= n_values
            return True
        self.rate_limited += n_values
        return False

    def has_pending(self) -> bool:
        return any(buf.n for buf in self.buffers.values())

    def expire_entries(self, before_nanos: int) -> int:
        """Drop interned ids idle since ``before_nanos`` (entry TTL,
        entry.go ShouldExpire). Only safe with no pending buffered values
        (buffer rows hold indexes); callers run this right after a drain."""
        if self.has_pending():
            return 0
        keep = [
            i for i in range(len(self.ids)) if self.last_write[i] >= before_nanos
        ]
        if len(keep) == len(self.ids):
            return 0
        expired = len(self.ids) - len(keep)
        remap = {old: new for new, old in enumerate(keep)}
        self.ids = [self.ids[i] for i in keep]
        self.metric_types = [self.metric_types[i] for i in keep]
        self.last_write = [self.last_write[i] for i in keep]
        self.rl_tokens = [self.rl_tokens[i] for i in keep]
        self.rl_stamp = [self.rl_stamp[i] for i in keep]
        self.id_index = {mid: i for i, mid in enumerate(self.ids)}
        self.agg_overrides = {
            remap[i]: v for i, v in self.agg_overrides.items() if i in remap
        }
        return expired

    def add(
        self,
        mid: bytes,
        mtype: MetricType,
        time_nanos: int,
        values,
        policies,
        aggregations: tuple[AggregationType, ...] | None = None,
        rate_limit: float | None = None,
    ) -> None:
        idx = self.intern(mid, mtype)
        if aggregations:
            self.agg_overrides[idx] = aggregations
        if not isinstance(values, (list, tuple)):
            values = [values]
        if not self.admit(idx, len(values), time_nanos, rate_limit):
            return
        for policy in policies:
            buf = self.buffers.get(policy)
            if buf is None:
                buf = self.buffers[policy] = _PolicyBuffer()
            buf.extend(idx, time_nanos, values, int(mtype))


class Aggregator:
    """AddUntimed/AddTimed + flush (aggregator.go:181-267).

    ``flush_handler`` receives list[AggregatedMetric] — the seam where the
    reference hands results to m3msg producers (aggregator/handler/)."""

    def __init__(
        self,
        num_shards: int = 16,
        default_policies: tuple[StoragePolicy, ...] = (),
        flush_handler: Callable[[list[AggregatedMetric]], None] | None = None,
        election=None,
        flush_times=None,
        value_rate_limit: float | None = None,
        entry_ttl_nanos: int | None = None,
    ) -> None:
        self.num_shards = num_shards
        self.shards = [_Shard() for _ in range(num_shards)]
        self.default_policies = default_policies or (StoragePolicy.parse("10s:2d"),)
        self.flush_handler = flush_handler
        # Replicated deployment: an election.ElectionManager decides which
        # replica emits at each flush pass, and a FlushTimesStore shares the
        # leader's progress so followers prune instead of emit and a
        # takeover resumes exactly where the old leader stopped
        # (election_mgr.go:43, follower_flush_mgr.go:70). Standalone
        # (election=None) is always leader.
        self.election = election
        self.flush_times = flush_times
        # per-metric value rate limit (values/sec, entry.go rate_limit role)
        self.value_rate_limit = value_rate_limit
        # idle interned entries older than this are dropped after a drain
        # (entry.go ShouldExpire + close cycle)
        self.entry_ttl_nanos = entry_ttl_nanos
        self.expired_entries = 0
        # late datapoints a replicated leader dropped because their window
        # was already flushed (observability for the replication caveat)
        self.dropped_late = 0
        # aggregates drained but not yet delivered (flush_handler raised);
        # retried at the next flush so a transient downstream outage doesn't
        # lose windows in standalone mode
        self._pending_emit: list[AggregatedMetric] = []
        # pending output dropped on leadership loss (the takeover leader
        # re-emits those windows from its own mirror)
        self.dropped_pending = 0
        # ingest servers call add_* from handler threads while a flush loop
        # drains; one lock guards the column buffers (entry.go lock role)
        self._lock = threading.Lock()
        # passthrough lane (AddPassthrough): leadership as observed at the
        # last flush pass (standalone: always leader)
        self._last_leader = election is None
        self.passthrough_count = 0
        self.passthrough_follower_noops = 0
        # undelivered passthrough metrics (no follower mirror: retried at
        # every flush regardless of leadership)
        self._pending_passthrough: list[AggregatedMetric] = []

    def shard_for(self, mid: bytes) -> int:
        return shard_for(mid, self.num_shards)

    # --- ingest (AddUntimed aggregator.go:181, AddTimed :219) ---

    def add_untimed(
        self,
        metric: Untimed,
        time_nanos: int,
        policies: tuple[StoragePolicy, ...] | None = None,
        aggregations: tuple[AggregationType, ...] | None = None,
    ) -> None:
        shard = self.shards[self.shard_for(metric.id)]
        if metric.type == MetricType.COUNTER:
            values = [float(metric.counter_value)]
        elif metric.type == MetricType.TIMER:
            values = list(metric.batch_timer_values)
        else:
            values = [metric.gauge_value]
        with self._lock:
            shard.add(
                metric.id,
                metric.type,
                time_nanos,
                values,
                policies or self.default_policies,
                aggregations,
                rate_limit=self.value_rate_limit,
            )

    def add_timed(
        self,
        mid: bytes,
        mtype: MetricType,
        time_nanos: int,
        value: float,
        policies: tuple[StoragePolicy, ...] | None = None,
        aggregations: tuple[AggregationType, ...] | None = None,
    ) -> None:
        with self._lock:
            self.shards[self.shard_for(mid)].add(
                mid, mtype, time_nanos, [value],
                policies or self.default_policies, aggregations,
                rate_limit=self.value_rate_limit,
            )

    def add_timed_batch(self, rows) -> None:
        """Batched AddTimed: ``rows`` is ``[(mid, mtype, time_nanos,
        value, policies, aggregations)]``. One lock acquisition for the
        whole batch — the handler-thread half of the column-buffer
        design (per-row locking capped ingest the same way the per-point
        write path did on the dbnode side)."""
        with self._lock:
            for mid, mtype, time_nanos, value, policies, aggregations in rows:
                self.shards[self.shard_for(mid)].add(
                    mid, mtype, time_nanos, [value],
                    policies or self.default_policies, aggregations,
                    rate_limit=self.value_rate_limit,
                )

    # AddForwarded: multi-stage rollup input — same buffer path, the pipeline
    # stage lives in rules (forwarded_writer.go equivalence).
    add_forwarded = add_timed

    def add_passthrough(
        self,
        mid: bytes,
        time_nanos: int,
        value: float,
        policy: StoragePolicy,
        agg_type: AggregationType = AggregationType.LAST,
    ) -> None:
        """AddPassthrough (aggregator.go:267-302): an ALREADY-AGGREGATED
        metric is written straight through with its storage policy — no
        windowing, no re-aggregation. Follower replicas no-op (mirrored
        ingest must not double-emit; the reference checks ElectionState the
        same way); leadership is the cached last flush-pass observation,
        matching the reference's cached election state rather than a KV
        round trip per metric."""
        if not self._last_leader:
            self.passthrough_follower_noops += 1
            return
        m = AggregatedMetric(mid, time_nanos, value, policy, agg_type)
        if self.flush_handler is not None:
            try:
                self.flush_handler([m])
            except Exception:
                # transient downstream outage: park for retry at the next
                # flush. A DEDICATED queue, not _pending_emit — windowed
                # pending is dropped on leadership loss (the new leader
                # re-emits from its mirror), but followers no-op'd this
                # passthrough metric, so NO replica holds it: it must
                # retry here regardless of leadership (at-least-once)
                with self._lock:
                    self._pending_passthrough.append(m)
        self.passthrough_count += 1

    @property
    def is_leader(self) -> bool:
        return self.election is None or self.election.is_leader

    # --- flush (leader_flush_mgr.go drains windows per resolution;
    # follower_flush_mgr.go prunes up to the leader's flush times) ---

    def flush(self, up_to_nanos: int) -> list[AggregatedMetric]:
        # campaigning at flush time means takeover is observed within one
        # flush interval of the old leader's session expiring
        leader = self.election.elect() if self.election is not None else True
        self._last_leader = leader  # cached for the passthrough lane
        leader_times = self.flush_times.get() if self.flush_times is not None else {}
        flushed_boundaries: dict[str, int] = {}
        out: list[AggregatedMetric] = []
        with self._lock:
            self._drain(
                leader, up_to_nanos, leader_times, flushed_boundaries, out
            )
        # delivery BEFORE recording progress: if the handler raises (or the
        # process dies here), the shared flush times don't advance, so
        # followers keep their mirror of these windows and a takeover
        # re-emits them instead of losing them. Standalone (no followers),
        # undelivered aggregates stay in _pending_emit and retry next flush.
        # pending handoffs under the lock (ingest threads append
        # passthrough retries concurrently)
        with self._lock:
            pending, self._pending_emit = self._pending_emit, []
            pt_pending, self._pending_passthrough = self._pending_passthrough, []
        if not leader and pending:
            # leadership lost with undelivered WINDOWED output: the flush
            # times for those windows never advanced, so the NEW leader
            # re-emits them from its mirror — retrying here would
            # double-deliver. (Passthrough retries are NOT dropped: no
            # replica mirrors them.)
            self.dropped_pending += len(pending)
            pending = []
        if self.flush_handler and (out or pending or pt_pending):
            to_send = pt_pending + pending + out
            try:
                self.flush_handler(to_send)
            except Exception:
                with self._lock:
                    self._pending_passthrough = pt_pending + self._pending_passthrough
                    self._pending_emit = pending + out + self._pending_emit
                raise
        if leader and self.flush_times is not None and flushed_boundaries:
            from ..cluster.kv import FenceError

            try:
                self.flush_times.update(
                    flushed_boundaries,
                    fence=self.election.fence if self.election is not None else None,
                )
            except FenceError:
                # leadership was superseded between elect() and here (e.g. a
                # long stall): the new leader re-emits these windows from its
                # mirror, so dropping the stale progress write is the safe,
                # exactly-once-preserving outcome
                pass
        if self.entry_ttl_nanos is not None:
            # drained buffers make expiry safe; idle entries release their
            # interned id slots (entry.go TTL close cycle)
            with self._lock:
                for shard in self.shards:
                    self.expired_entries += shard.expire_entries(
                        up_to_nanos - self.entry_ttl_nanos
                    )
        return out

    @property
    def rate_limited(self) -> int:
        return sum(s.rate_limited for s in self.shards)

    def _drain(self, leader, up_to_nanos, leader_times, flushed_boundaries, out):
        for shard in self.shards:
            for policy, buf in shard.buffers.items():
                if not buf.n:
                    continue
                res = policy.resolution.window_nanos
                pkey = str(policy)
                prev_bound = leader_times.get(pkey, 0)
                if leader:
                    boundary = (up_to_nanos // res) * res
                else:
                    # follower warm standby: drop ONLY what the leader has
                    # durably flushed; everything else stays buffered so a
                    # takeover can flush it
                    boundary = prev_bound
                times = buf.times[: buf.n]
                flushable = times < boundary
                if not flushable.any():
                    continue
                # fancy indexing copies, so the drained columns survive
                # the in-place compaction below
                ids = buf.ids[: buf.n][flushable]
                vals = buf.values[: buf.n][flushable]
                ts = times[flushable]
                types = buf.types[: buf.n][flushable]
                buf.compact(~flushable)  # retain unflushed tail
                if leader:
                    # windows the previous leader already emitted (per the
                    # shared flush times) are discarded, not re-emitted
                    emit = ts >= prev_bound
                    if emit.any():
                        out.extend(
                            self._flush_policy(
                                shard, policy, ids[emit], ts[emit],
                                vals[emit], types[emit], res,
                            )
                        )
                    self.dropped_late += int((~emit).sum())
                    flushed_boundaries[pkey] = max(
                        boundary, flushed_boundaries.get(pkey, 0)
                    )

    def _flush_policy(self, shard, policy, ids, ts, vals, types, res) -> list[AggregatedMetric]:
        w0 = int(ts.min() // res) * res
        n_windows = int(ts.max() // res) - int(w0 // res) + 1
        n_metrics = len(shard.ids)
        keys, widx, torder = window_keys(ids, ts, w0, res, n_windows)
        n_groups = n_metrics * n_windows
        # dense TPU path: host densify → vector reductions (segment_* would
        # lower to device scatters, see kernels.py dense section)
        dvals, dtor, dvalid = pack_dense_groups(keys, vals, torder, n_groups)
        agg = aggregate_dense(dvals, dtor, dvalid)

        # quantiles only for groups containing timer values
        need_q = sorted(
            {
                q
                for i in range(n_metrics)
                for t in (
                    shard.agg_overrides.get(i) or shard.metric_types[i].default_aggregations()
                )
                for q in [t.quantile()]
                if q is not None
            }
        )
        quantiles = {}
        if need_q:
            qvals = np.asarray(dense_quantiles(dvals, dvalid, tuple(need_q)))
            quantiles = {q: qvals[i] for i, q in enumerate(need_q)}

        count = np.asarray(agg.count)
        host = {
            AggregationType.LAST: np.asarray(agg.last),
            AggregationType.MIN: np.asarray(agg.min),
            AggregationType.MAX: np.asarray(agg.max),
            AggregationType.MEAN: np.asarray(agg.mean),
            AggregationType.COUNT: count,
            AggregationType.SUM: np.asarray(agg.sum),
            AggregationType.SUMSQ: np.asarray(agg.sum_sq),
            AggregationType.STDEV: np.asarray(agg.stdev),
        }
        out = []
        present = np.unique(keys)
        for g in present:
            midx, wi = divmod(int(g), n_windows)
            window_end = w0 + (wi + 1) * res
            aggs = shard.agg_overrides.get(midx) or shard.metric_types[
                midx
            ].default_aggregations()
            for atype in aggs:
                q = atype.quantile()
                v = quantiles[q][g] if q is not None else host[atype][g]
                out.append(
                    AggregatedMetric(
                        id=shard.ids[midx],
                        time_nanos=window_end,
                        value=float(v),
                        policy=policy,
                        agg_type=atype,
                    )
                )
        return out
