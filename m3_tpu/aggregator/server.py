"""Aggregator ingest server + client: the tier's socket boundary.

Reference: /root/reference/src/aggregator/server/rawtcp/server.go — a raw TCP
listener decoding the unaggregated metrics stream into AddUntimed/AddTimed —
and src/aggregator/client/client.go — the shard-routing writer the
coordinator's downsampler uses. Framing is metrics/encoding's length-prefixed
messages, streamed one-way per connection (fire-and-forget, like rawtcp).
"""

from __future__ import annotations

import socket
import socketserver
import threading

from ..metrics.encoding import (
    KIND_AGGREGATED,
    AggregatedMessage,
    UnaggregatedMessage,
    decode_aggregated,
    decode_message,
    encode_aggregated,
    encode_message,
)
from ..metrics.types import MetricType
from ..net.wire import FrameDecoder, pack_frame
from ..utils.hash import shard_for
from ..utils.instrument import DEFAULT as METRICS

MAX_MSG = 64 * 1024 * 1024


class AggregatorIngestServer:
    """rawtcp server: stream of length-prefixed unaggregated messages."""

    def __init__(self, aggregator, host: str = "127.0.0.1", port: int = 0) -> None:
        self.aggregator = aggregator
        self.received = 0
        self.decode_errors = 0
        # fleet scrape surface: the stream has no request/response channel,
        # so ingest health rides the process registry (served by the
        # aggregator binary's --debug-port RPC `metrics` op)
        self._m_received = METRICS.counter(
            "aggregator_messages_total", "ingested metric messages",
            labels={"component": "aggregator"},
        )
        self._m_decode_errors = METRICS.counter(
            "aggregator_decode_errors_total", "undecodable ingest payloads",
            labels={"component": "aggregator"},
        )
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                frames = FrameDecoder(max_frame=MAX_MSG)
                while True:
                    try:
                        chunk = self.request.recv(1 << 20)
                    except OSError:
                        return
                    if not chunk:
                        return
                    try:
                        payloads = frames.feed(chunk)
                    except ValueError:
                        return  # poisoned stream; drop connection
                    for payload in payloads:
                        try:
                            if payload and payload[0] == KIND_AGGREGATED:
                                # passthrough lane: already-aggregated
                                # metrics skip re-aggregation entirely
                                am, _ = decode_aggregated(payload)
                                outer.aggregator.add_passthrough(
                                    am.id, am.time_nanos, am.value,
                                    am.policy, am.agg_type,
                                )
                            else:
                                msg, _ = decode_message(payload)
                                outer._apply(msg)
                            outer.received += 1
                            outer._m_received.inc()
                        except Exception:
                            outer.decode_errors += 1
                            outer._m_decode_errors.inc()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def _apply(self, msg: UnaggregatedMessage) -> None:
        policies = msg.policies or None
        aggs = msg.aggregations or None
        if msg.timed:
            m = msg.metric
            if m.type == MetricType.COUNTER:
                values = [float(m.counter_value)]
            elif m.type == MetricType.TIMER:
                values = list(m.batch_timer_values)
            else:
                values = [m.gauge_value]
            for v in values:
                self.aggregator.add_timed(
                    m.id, m.type, msg.time_nanos, v, policies, aggs
                )
        else:
            self.aggregator.add_untimed(msg.metric, msg.time_nanos, policies, aggs)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m3tpu-agg-ingest", daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class AggregatorClient:
    """Shard-routing writer over persistent sockets (client/client.go).

    Instances own disjoint shard ranges of a ``num_shards`` space; a metric
    routes by murmur3 shard of its id. With one instance, everything goes
    there (the common single-aggregator deployment)."""

    def __init__(self, endpoints: list[tuple[str, int]], num_shards: int = 16) -> None:
        self.endpoints = endpoints
        self.num_shards = num_shards
        self._socks: list[socket.socket | None] = [None] * len(endpoints)
        # per-endpoint locks: a down instance (blocking in connect) must not
        # stall sends routed to healthy instances
        self._locks = [threading.Lock() for _ in endpoints]

    def _sock(self, idx: int) -> socket.socket:
        sock = self._socks[idx]
        if sock is None:
            host, port = self.endpoints[idx]
            sock = socket.create_connection((host, port), timeout=10)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[idx] = sock
        return sock

    def _instance_for(self, mid: bytes) -> int:
        return shard_for(mid, self.num_shards) % len(self.endpoints)

    def send(self, msg) -> None:
        if isinstance(msg, AggregatedMessage):
            # passthrough lane: already-aggregated, shard-routed unchanged
            frame = pack_frame(encode_aggregated(msg))
            mid = msg.id
        else:
            frame = pack_frame(encode_message(msg))
            mid = msg.metric.id
        idx = self._instance_for(mid)
        with self._locks[idx]:
            try:
                self._sock(idx).sendall(frame)
            except OSError:
                # one reconnect attempt (stale connection)
                self._socks[idx] = None
                self._sock(idx).sendall(frame)

    def close(self) -> None:
        for idx, lock in enumerate(self._locks):
            with lock:
                if self._socks[idx] is not None:
                    self._socks[idx].close()
                    self._socks[idx] = None
