"""Aggregator leader election + shared flush-time bookkeeping.

Reference: /root/reference/src/aggregator/aggregator/election_mgr.go:43 —
each aggregator replica campaigns on a per-shard-set election; exactly one
leader flushes, followers run warm standby. follower_flush_mgr.go:70 — the
leader persists per-window flush times to KV; followers prune their mirrored
buffers up to the leader's flush times instead of emitting, so a takeover
flushes every window exactly once (nothing lost, nothing doubled).
"""

from __future__ import annotations

from ..cluster.services import LeaderElection


class FlushTimesStore:
    """KV-backed map of policy key -> last flushed window boundary (nanos).

    The role of flushTimesManager persisting flush times to the cluster KV
    (flush_times_mgr.go): followers read it to know what the leader already
    emitted; a new leader resumes from it."""

    def __init__(self, kv, scope: str) -> None:
        self.kv = kv
        self.key = f"_flush_times/{scope}"

    def get(self) -> dict[str, int]:
        vv = self.kv.get(self.key)
        return dict(vv.value) if vv and vv.value else {}

    def update(self, updates: dict[str, int], fence=None) -> None:
        """Merge updates, keeping the max boundary per policy (CAS loop).

        ``fence`` is the leader's (lease_key, holder, token): the KV store
        rejects the write (FenceError) if the writer's lease was superseded
        — a deposed leader resuming from a GC pause cannot clobber the new
        leader's flush progress."""
        for _ in range(16):
            vv = self.kv.get(self.key)
            cur = dict(vv.value) if vv and vv.value else {}
            for k, boundary in updates.items():
                cur[k] = max(boundary, cur.get(k, 0))
            try:
                self.kv.check_and_set(
                    self.key, vv.version if vv else 0, cur, fence=fence
                )
                return
            except (ValueError, KeyError):
                continue  # raced another writer; re-read and retry
        raise RuntimeError("flush times CAS contention")


class ElectionManager:
    """Campaign/observe leadership for one aggregator replica."""

    def __init__(
        self, kv, scope: str, instance_id: str, lease_secs: float = 10.0
    ) -> None:
        self.election = LeaderElection(
            kv, f"aggregator/{scope}", lease_secs=lease_secs
        )
        self.instance_id = instance_id

    def elect(self) -> bool:
        """Campaign; returns whether this instance is now the leader.
        Aggregators call this at each flush pass, so leadership loss or
        takeover is observed within one flush interval (election_mgr.go
        checkCampaignState)."""
        return self.election.campaign(self.instance_id)

    @property
    def is_leader(self) -> bool:
        return self.election.leader() == self.instance_id

    @property
    def fence(self):
        """(lease_key, holder, token) proving this instance's leadership;
        attached to flush-time writes so a deposed leader is fenced out."""
        return self.election.fence(self.instance_id)

    def resign(self) -> None:
        self.election.resign(self.instance_id)
