"""In-process downsampler: rule match → aggregation → pipeline → flush.

Reference: /root/reference/src/cmd/services/m3coordinator/downsample/ — the
coordinator embeds an aggregator (`NewDownsampler` options.go:547); incoming
writes pass through metrics_appender.go (rule match, rollup id construction)
into aggregation elems, and flushed values go to storage via flush_handler.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..block.core import Tags
from ..metrics.policy import StoragePolicy
from ..metrics.transformation import APPLY
from ..metrics.types import AggregationType, MetricType
from ..rules.rules import ActiveRuleSet, RuleSet, encode_tags_id
from .aggregator import AggregatedMetric, Aggregator


@dataclass
class Downsampler:
    """downsamplerAndWriter's downsample half (ingest/write.go:138)."""

    ruleset: RuleSet
    aggregator: Aggregator = field(default_factory=Aggregator)
    # storage sink for flushed aggregated metrics (flush_handler.go)
    sink: Callable[[list[AggregatedMetric]], None] | None = None
    auto_mapping_policies: tuple[StoragePolicy, ...] = ()
    # rollup pipelines keyed by flushed metric identity
    _pipelines: dict[bytes, tuple] = field(default_factory=dict)
    _carry: dict[tuple, tuple] = field(default_factory=dict)
    # active-snapshot cache: ``RuleSet.active_at`` builds a FRESH
    # ActiveRuleSet (losing its per-ID match cache) every call, which
    # re-ran every rule filter on every write. Keyed by the set of
    # active rule indices, so a cutover lands on its exact boundary
    # while every write inside a snapshot reuses the cached matcher —
    # and with it the per-ID forward_match results.
    _active_cache: dict[tuple, ActiveRuleSet] = field(default_factory=dict)
    # tags -> encoded metric id (encode_tags per write dominated after
    # the matcher cache; ids are immutable per tag set)
    _id_cache: dict[Tags, bytes] = field(default_factory=dict)

    def _active_for(self, time_nanos: int) -> ActiveRuleSet:
        key = (
            self.ruleset.version,
            tuple(
                i
                for i, r in enumerate(self.ruleset.mapping_rules)
                if r.cutover_nanos <= time_nanos
            ),
            tuple(
                i
                for i, r in enumerate(self.ruleset.rollup_rules)
                if r.cutover_nanos <= time_nanos
            ),
        )
        active = self._active_cache.get(key)
        if active is None:
            active = self._active_cache[key] = self.ruleset.active_at(time_nanos)
        return active

    def _id_for(self, tags: Tags) -> bytes:
        mid = self._id_cache.get(tags)
        if mid is None:
            mid = self._id_cache[tags] = encode_tags_id(tags)
        return mid

    def write(
        self,
        tags: Tags,
        time_nanos: int,
        value: float,
        mtype: MetricType = MetricType.GAUGE,
    ) -> bool:
        """Returns False when a drop policy matched (metric not persisted
        unaggregated — ingest/write.go shouldWrite)."""
        return self.write_batch([(tags, time_nanos, value, mtype)])[0]

    def write_batch(self, rows) -> list[bool]:
        """Batched ingest: rule evaluation runs once per distinct tag set
        (cached matcher + cached encoded ids), and the aggregator takes
        its lock ONCE for the whole batch instead of per metric. ``rows``
        is ``[(tags, time_nanos, value, mtype)]``; returns the per-row
        keep mask (False = a drop policy matched)."""
        keep: list[bool] = []
        adds: list[tuple] = []
        for tags, time_nanos, value, mtype in rows:
            m = self._active_for(time_nanos).forward_match(tags)
            mid = self._id_for(tags)
            policies = m.policies or self.auto_mapping_policies
            if policies:
                adds.append(
                    (mid, mtype, time_nanos, value, policies,
                     m.aggregations or None)
                )
            for rtags, target in m.rollups:
                rid = self._id_for(rtags)
                self._pipelines[rid] = target.pipeline
                adds.append(
                    (
                        rid,
                        MetricType.GAUGE
                        if mtype == MetricType.GAUGE
                        else MetricType.COUNTER,
                        time_nanos,
                        value,
                        target.policies
                        or policies
                        or self.aggregator.default_policies,
                        target.aggregations or None,
                    )
                )
            keep.append(not m.drop)
        if adds:
            self.aggregator.add_timed_batch(adds)
        return keep

    def flush(self, up_to_nanos: int) -> list[AggregatedMetric]:
        flushed = self.aggregator.flush(up_to_nanos)
        out = []
        # apply rollup pipelines across consecutive flush windows, carrying
        # the previous datapoint across flush() calls (forwarded_writer.go
        # keeps equivalent per-elem state)
        by_key: dict[tuple, list[AggregatedMetric]] = {}
        for m in flushed:
            pipeline = self._pipelines.get(m.id, ())
            if not pipeline:
                out.append(m)
            else:
                by_key.setdefault((m.id, m.policy, m.agg_type), []).append(m)
        for key, ms in by_key.items():
            ms.sort(key=lambda m: m.time_nanos)
            pipeline = self._pipelines[key[0]]
            times = np.asarray([m.time_nanos for m in ms], np.int64)
            values = np.asarray([m.value for m in ms], np.float64)
            carry = self._carry.get(key)
            if carry is not None:
                times = np.concatenate([[carry[0]], times])
                values = np.concatenate([[carry[1]], values])
            t, v = times, values
            for op in pipeline:
                t, v = APPLY[int(op)](t, v)
            self._carry[key] = (int(times[-1]), float(values[-1]))
            start = 1 if carry is not None else 0
            for i in range(start, len(ms) + start):
                if not np.isnan(v[i]):
                    m = ms[i - start]
                    out.append(
                        AggregatedMetric(
                            id=m.id,
                            time_nanos=int(t[i]),
                            value=float(v[i]),
                            policy=m.policy,
                            agg_type=m.agg_type,
                        )
                    )
        if self.sink and out:
            self.sink(out)
        return out
