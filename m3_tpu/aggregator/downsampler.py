"""In-process downsampler: rule match → aggregation → pipeline → flush.

Reference: /root/reference/src/cmd/services/m3coordinator/downsample/ — the
coordinator embeds an aggregator (`NewDownsampler` options.go:547); incoming
writes pass through metrics_appender.go (rule match, rollup id construction)
into aggregation elems, and flushed values go to storage via flush_handler.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..block.core import Tags
from ..metrics.policy import StoragePolicy
from ..metrics.transformation import APPLY
from ..metrics.types import AggregationType, MetricType
from ..rules.rules import ActiveRuleSet, RuleSet, encode_tags_id
from .aggregator import AggregatedMetric, Aggregator


@dataclass
class Downsampler:
    """downsamplerAndWriter's downsample half (ingest/write.go:138)."""

    ruleset: RuleSet
    aggregator: Aggregator = field(default_factory=Aggregator)
    # storage sink for flushed aggregated metrics (flush_handler.go)
    sink: Callable[[list[AggregatedMetric]], None] | None = None
    auto_mapping_policies: tuple[StoragePolicy, ...] = ()
    # rollup pipelines keyed by flushed metric identity
    _pipelines: dict[bytes, tuple] = field(default_factory=dict)
    _carry: dict[tuple, tuple] = field(default_factory=dict)

    def write(
        self,
        tags: Tags,
        time_nanos: int,
        value: float,
        mtype: MetricType = MetricType.GAUGE,
    ) -> bool:
        """Returns False when a drop policy matched (metric not persisted
        unaggregated — ingest/write.go shouldWrite)."""
        active: ActiveRuleSet = self.ruleset.active_at(time_nanos)
        m = active.forward_match(tags)
        mid = encode_tags_id(tags)

        policies = m.policies or self.auto_mapping_policies
        if policies:
            self.aggregator.add_timed(
                mid, mtype, time_nanos, value, policies=policies, aggregations=m.aggregations or None
            )
        for rtags, target in m.rollups:
            rid = encode_tags_id(rtags)
            self._pipelines[rid] = target.pipeline
            self.aggregator.add_timed(
                rid,
                MetricType.GAUGE if mtype == MetricType.GAUGE else MetricType.COUNTER,
                time_nanos,
                value,
                policies=target.policies or policies or self.aggregator.default_policies,
                aggregations=target.aggregations or None,
            )
        return not m.drop

    def flush(self, up_to_nanos: int) -> list[AggregatedMetric]:
        flushed = self.aggregator.flush(up_to_nanos)
        out = []
        # apply rollup pipelines across consecutive flush windows, carrying
        # the previous datapoint across flush() calls (forwarded_writer.go
        # keeps equivalent per-elem state)
        by_key: dict[tuple, list[AggregatedMetric]] = {}
        for m in flushed:
            pipeline = self._pipelines.get(m.id, ())
            if not pipeline:
                out.append(m)
            else:
                by_key.setdefault((m.id, m.policy, m.agg_type), []).append(m)
        for key, ms in by_key.items():
            ms.sort(key=lambda m: m.time_nanos)
            pipeline = self._pipelines[key[0]]
            times = np.asarray([m.time_nanos for m in ms], np.int64)
            values = np.asarray([m.value for m in ms], np.float64)
            carry = self._carry.get(key)
            if carry is not None:
                times = np.concatenate([[carry[0]], times])
                values = np.concatenate([[carry[1]], values])
            t, v = times, values
            for op in pipeline:
                t, v = APPLY[int(op)](t, v)
            self._carry[key] = (int(times[-1]), float(values[-1]))
            start = 1 if carry is not None else 0
            for i in range(start, len(ms) + start):
                if not np.isnan(v[i]):
                    m = ms[i - start]
                    out.append(
                        AggregatedMetric(
                            id=m.id,
                            time_nanos=int(t[i]),
                            value=float(v[i]),
                            policy=m.policy,
                            agg_type=m.agg_type,
                        )
                    )
        if self.sink and out:
            self.sink(out)
        return out
