"""Cross-instance metric forwarding: multi-stage rollups between
aggregator processes.

Reference: /root/reference/src/aggregator/aggregator/forwarded_writer.go —
a rollup pipeline's intermediate output is not flushed to storage but
FORWARDED (as timed metrics) to the aggregator instance owning the rollup
metric's shard, where the next stage aggregates it. Here a ForwardingHandler
plugs into Aggregator.flush_handler and ships flushed aggregates over the
rawtcp-role ingest socket (aggregator/server.py) as timed unaggregated
messages, shard-routed by the destination id.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..metrics.encoding import UnaggregatedMessage
from ..metrics.types import AggregationType, MetricType, Untimed
from .server import AggregatorClient


@dataclass
class ForwardingRule:
    """Which flushed metrics forward, and how they rename (the pipeline's
    next-stage input id)."""

    suffix: bytes = b""  # only ids ending in this forward (b"" = all)
    rename: bytes | None = None  # replacement id; None keeps suffixed_id
    # how the NEXT stage aggregates the forwarded values (pipeline op);
    # forwarded partials are summed by default
    aggregations: tuple = (AggregationType.SUM,)


class ForwardingHandler:
    """Aggregator.flush_handler that forwards matching aggregates to the
    next aggregation stage over the wire; non-matching metrics fall through
    to ``local_handler`` (the storage/m3msg egress)."""

    def __init__(
        self,
        endpoints: list[tuple[str, int]],
        rules: list[ForwardingRule] | None = None,
        local_handler=None,
        num_shards: int = 16,
    ) -> None:
        self.client = AggregatorClient(endpoints, num_shards=num_shards)
        self.rules = rules or [ForwardingRule()]
        self.local_handler = local_handler
        self.forwarded = 0
        # messages that failed to send (endpoint down): retried on the next
        # flush by THIS handler. The handler never raises into the
        # aggregator's _pending_emit batch retry — that would re-forward
        # messages already delivered over TCP and double-count downstream.
        self._pending_send: list = []

    def _rule_for(self, suffixed_id: bytes) -> ForwardingRule | None:
        for rule in self.rules:
            if suffixed_id.endswith(rule.suffix):
                return rule
        return None

    def _send(self, msg: UnaggregatedMessage) -> bool:
        try:
            self.client.send(msg)
        except OSError:
            self._pending_send.append(msg)
            return False
        self.forwarded += 1
        return True

    def __call__(self, metrics) -> None:
        # local egress FIRST: if it raises, nothing has been forwarded yet,
        # so the aggregator's batch retry is safe (per-message forwarding
        # failures never raise — they queue in _pending_send instead)
        passthrough = []
        to_forward = []
        for m in metrics:
            # match on the type-suffixed id (edge.reqs.sum), the form the
            # next stage would ingest
            rule = self._rule_for(m.suffixed_id)
            if rule is None:
                passthrough.append(m)
                continue
            out_id = rule.rename if rule.rename is not None else m.suffixed_id
            # carry the SOURCE policy: with multiple storage policies the
            # flush emits one aggregate per policy, and the next stage must
            # keep them in separate per-policy buffers (summing them
            # together would double count)
            to_forward.append(
                UnaggregatedMessage(
                    Untimed(type=MetricType.GAUGE, id=out_id, gauge_value=m.value),
                    m.time_nanos,
                    policies=(m.policy,),
                    aggregations=tuple(rule.aggregations),
                    timed=True,
                )
            )
        if self.local_handler is not None and passthrough:
            self.local_handler(passthrough)
        retry, self._pending_send = self._pending_send, []
        for msg in retry + to_forward:
            self._send(msg)

    def close(self) -> None:
        self.client.close()
