"""Fused chunked decode + aggregation: the flagship TPU kernel.

Round-1 decode materialized 7 u64 [S, T] outputs from the scan and aggregated
afterwards — every step streamed a multi-hundred-MB carry plus outputs through
HBM. Here the whole K-step decode loop runs with its state resident on-chip
and only per-LANE aggregates (sum/count/min/max/last) leave the kernel:

  - Pallas path (TPU): grid over lane tiles of 8x128; each program loads its
    tile's window columns into VMEM once and runs the K-record loop as a
    fori_loop, state in vector registers/VMEM. HBM traffic = windows once +
    [N] accumulators once.
  - jnp path (CPU fallback + oracle): identical math as a lax.scan with
    accumulators in the carry and NO per-step outputs.

Record semantics are decode.py's branchless M3TSZ step (reference hot loop:
/root/reference/src/dbnode/encoding/m3tsz/iterator.go:64, istream.go:97);
aggregation matches parallel/scan._aggregate_decoded.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.instrument import KernelProfiler
from . import u64
from .chunked import _fetch4_select, _window_columns
from .decode import (
    DecodeState,
    _decode_timestamp,
    _decode_value,
    _decode_value_fast,
    _extract,
    _extract32,
    _int32_val_to_f32,
    _int_val_to_f32,
    _read_xor,
    _ts_consumed_fast,
)

I32 = jnp.int32
U32 = jnp.uint32
F32 = jnp.float32

# device-tier observability for the fused lane-aggregate kernels (see
# ops/chunked.PROFILER): the dispatch key carries the backend
# (pallas/jnp), so compile attribution separates the Mosaic kernel from
# the lax.scan fallback while one kernel label covers the path
PROFILER_FUSED = KernelProfiler("fused_lane_agg")
PROFILER_PACKED = KernelProfiler("packed_lane_agg")

LANE_TILE = (8, 128)  # native f32/i32 VPU tile
TILE_LANES = LANE_TILE[0] * LANE_TILE[1]
# rows per packed-kernel grid program: taller blocks amortize per-program
# grid/DMA overhead (measured best at 24-32); must be a multiple of 8
# (sublane tiling)
ROWS_DEFAULT = int(os.environ.get("M3_TPU_TILE_ROWS", "32"))


class LaneAggregates(NamedTuple):
    """Per-lane (= per chunk) reductions emitted by the fused kernel."""

    sum: jnp.ndarray  # f32[N]
    count: jnp.ndarray  # i32[N]
    min: jnp.ndarray  # f32[N] (+inf where empty)
    max: jnp.ndarray  # f32[N] (-inf where empty)
    last: jnp.ndarray  # f32[N] (value of last valid record in the lane)
    err: jnp.ndarray  # bool/i32[N]


def _init_state(rel_pos, num_bits, prev_time, prev_delta, prev_float_bits,
                prev_xor, int_val, time_unit, sig, mult, is_float):
    as_pair = lambda p: (jnp.asarray(p[0], U32), jnp.asarray(p[1], U32))
    shape = rel_pos.shape
    return DecodeState(
        pos=jnp.zeros(shape, I32),
        done=jnp.asarray(num_bits, I32) <= jnp.asarray(rel_pos, I32),
        err=jnp.zeros(shape, bool),
        prev_time=as_pair(prev_time),
        prev_delta=as_pair(prev_delta),
        time_unit=jnp.asarray(time_unit, I32),
        prev_float_bits=as_pair(prev_float_bits),
        prev_xor=as_pair(prev_xor),
        int_val=as_pair(int_val),
        mult=jnp.asarray(mult, I32),
        sig=jnp.asarray(sig, I32),
        is_float=jnp.asarray(is_float, bool),
    )


def _fused_step(fetch4, nb, nt0, first_chunk_i32, int_optimized, carry, idx):
    """Decode ONE record for every lane and fold it into the accumulators.

    ``first_chunk_i32`` is int32, not bool: every value closed over by the
    loop body is threaded through the while-op carry, and Mosaic cannot
    round-trip i1 vector carries (it stores them as i8 and the trunc back is
    unsupported). Mask math stays in int32 until the final compare.
    """
    state, acc = carry
    s_sum, s_cnt, s_min, s_max, s_last = acc
    first_vec = (first_chunk_i32 * jnp.where(idx == 0, I32(1), I32(0))) != 0
    was_active = ~state.done & ~state.err
    state, _ = _decode_timestamp(fetch4, nb, state, first_vec, nt=nt0)
    ts_active = ~state.done & ~state.err
    state = _decode_value(fetch4, state, first_vec, int_optimized)
    now_active = ~state.done & ~state.err
    valid = was_active & ts_active & now_active

    if int_optimized:
        point_is_float = state.is_float
        val = u64.select(point_is_float, state.prev_float_bits, state.int_val)
        v = jnp.where(
            point_is_float,
            u64.f64_bits_to_f32(val),
            _int_val_to_f32(val, state.mult),
        )
    else:
        v = u64.f64_bits_to_f32(state.prev_float_bits)
    s_sum = s_sum + jnp.where(valid, v, F32(0))
    s_cnt = s_cnt + valid.astype(I32)
    s_min = jnp.minimum(s_min, jnp.where(valid, v, F32(jnp.inf)))
    s_max = jnp.maximum(s_max, jnp.where(valid, v, F32(-jnp.inf)))
    s_last = jnp.where(valid, v, s_last)
    return state, (s_sum, s_cnt, s_min, s_max, s_last)


def _run_lane_tile(windows_cols, rel_pos, num_bits, first, prev_time, prev_delta,
                   prev_float_bits, prev_xor, int_val, time_unit, sig, mult,
                   is_float, k: int, cw: int, int_optimized: bool,
                   use_scan: bool, unroll: bool = False) -> LaneAggregates:
    """Shared body: decode K records over one set of lanes (any shape) with
    window columns already materialized, accumulating aggregates."""
    rel_pos = jnp.asarray(rel_pos, I32)
    fetch4 = functools.partial(_fetch4_select, windows_cols, cw, rel_pos)
    state = _init_state(rel_pos, num_bits, prev_time, prev_delta,
                        prev_float_bits, prev_xor, int_val, time_unit, sig,
                        mult, is_float)
    first_chunk_i32 = jnp.asarray(first).astype(I32)
    nb = jnp.asarray(num_bits, I32) - rel_pos
    zero_pos = jnp.zeros_like(rel_pos)
    nt0 = _extract(fetch4(zero_pos), 0, 64)

    shape = rel_pos.shape
    acc0 = (
        jnp.zeros(shape, F32),
        jnp.zeros(shape, I32),
        jnp.full(shape, jnp.inf, F32),
        jnp.full(shape, -jnp.inf, F32),
        jnp.full(shape, jnp.nan, F32),
    )
    step = functools.partial(
        _fused_step, fetch4, nb, nt0, first_chunk_i32, int_optimized
    )
    if use_scan:
        (state, acc), _ = jax.lax.scan(
            lambda c, i: (step(c, i), None), (state, acc0), jnp.arange(k)
        )
    else:
        # Mosaic can't round-trip i1 vectors through a fori_loop carry, so
        # bool state fields travel as int32 and are re-compared each step.
        def pack(st):
            return st._replace(
                done=st.done.astype(I32), err=st.err.astype(I32),
                is_float=st.is_float.astype(I32),
            )

        def unpack(st):
            return st._replace(
                done=st.done != 0, err=st.err != 0, is_float=st.is_float != 0
            )

        def body(i, c):
            st, ac = c
            st, ac = step((unpack(st), ac), i)
            return pack(st), ac

        # fully unrolled on hardware: Mosaic schedules the straight-line
        # record bodies much better than the rolled loop (+16% measured);
        # Pallas only supports unroll=1 or unroll=num_steps. Interpret mode
        # keeps the rolled loop (the interpreter executes per-op, and the
        # 24x traced body is pathologically slow there).
        state, acc = jax.lax.fori_loop(
            0, k, body, (pack(state), acc0), unroll=k if unroll else 1
        )
        state = unpack(state)
    s_sum, s_cnt, s_min, s_max, s_last = acc
    return LaneAggregates(
        sum=s_sum, count=s_cnt, min=s_min, max=s_max, last=s_last, err=state.err
    )


def _run_lane_tile_fast(windows_cols, rel_pos, num_bits, int_val, sig, mult,
                        k: int, cw: int, unroll: bool = False) -> LaneAggregates:
    """Specialized K-record body for host-classified fast chunks (see
    ops/chunked.py prescan flags): every record is marker-free and int-mode,
    the time unit is constant in {s, ms}, the value path is int32-safe, and
    the chunk holds exactly k records (or the lane is empty).

    Skips the float-XOR path, full-float extracts, marker/time-unit logic,
    f64->f32 conversion, per-record done/err bookkeeping (the active mask is
    constant per lane) — and the TIMESTAMP VALUES themselves: aggregates are
    the kernel's only output, so a timestamp record contributes nothing but
    its consumed-bit count (_ts_consumed_fast)."""
    rel_pos = jnp.asarray(rel_pos, I32)
    shape = rel_pos.shape
    active = jnp.asarray(num_bits, I32) > rel_pos  # empty/padding lanes: False
    # minimal carry: pos + the fields fast records can change (no done/err/
    # float/timestamp planes; bool-free so the Mosaic i1 hazard never arises)
    state0 = (
        jnp.zeros(shape, I32),  # pos
        # int32-safe by classification: only the low word carries the value
        jax.lax.bitcast_convert_type(jnp.asarray(int_val[1], U32), I32),
        jnp.asarray(sig, I32),
        jnp.asarray(mult, I32),
    )
    acc0 = (
        jnp.zeros(shape, F32),
        jnp.zeros(shape, I32),
        jnp.full(shape, jnp.inf, F32),
        jnp.full(shape, -jnp.inf, F32),
        jnp.full(shape, jnp.nan, F32),
    )
    active_i = active.astype(I32)
    # a fast record consumes at most 36 (ts) + 80 (value) = 116 bits, so the
    # cursor before record j is statically bounded — early records need only
    # a shallow barrel (see _fetch4_select max_widx)
    MAX_REC_BITS = 116

    def body(c, ts_widx, val_widx):
        (pos, iv, sg, ml), acc = c
        s_sum, s_cnt, s_min, s_max, s_last = acc
        ws_ts = _fetch4_select(windows_cols, cw, rel_pos, pos, max_widx=ts_widx)
        pos = pos + _ts_consumed_fast(ws_ts)
        st = DecodeState(
            pos=pos, done=None, err=None, prev_time=None, prev_delta=None,
            time_unit=None, prev_float_bits=None, prev_xor=None,
            int_val=iv, mult=ml, sig=sg, is_float=None,
        )
        fetch_val = functools.partial(
            _fetch4_select, windows_cols, cw, rel_pos, max_widx=val_widx
        )
        st = _decode_value_fast(fetch_val, st)
        v = _int32_val_to_f32(st.int_val, st.mult)
        s_sum = s_sum + jnp.where(active, v, F32(0))
        s_cnt = s_cnt + active_i
        s_min = jnp.minimum(s_min, jnp.where(active, v, F32(jnp.inf)))
        s_max = jnp.maximum(s_max, jnp.where(active, v, F32(-jnp.inf)))
        s_last = jnp.where(active, v, s_last)
        return (
            (st.pos, st.int_val, st.sig, st.mult),
            (s_sum, s_cnt, s_min, s_max, s_last),
        )

    if unroll:
        carry = (state0, acc0)
        for j in range(k):
            ts_widx = (31 + MAX_REC_BITS * j) >> 5
            val_widx = (31 + MAX_REC_BITS * j + 36) >> 5
            carry = body(carry, ts_widx, val_widx)
        _state, acc = carry
    else:
        _state, acc = jax.lax.fori_loop(
            0, k, lambda _i, c: body(c, None, None), (state0, acc0)
        )
    s_sum, s_cnt, s_min, s_max, s_last = acc
    return LaneAggregates(
        sum=s_sum, count=s_cnt, min=s_min, max=s_max, last=s_last,
        err=jnp.zeros(shape, bool),
    )


def _run_lane_tile_fast_float(windows_cols, rel_pos, num_bits,
                              prev_float_bits, prev_xor,
                              k: int, cw: int, unroll: bool = False) -> LaneAggregates:
    """Specialized K-record body for FLOAT-MODE fast chunks (fast_float
    classification, ops/chunked.py): every record is marker-free with the
    stream in float mode at the chunk start and after every record, unit
    constant in {s, ms}. The only value formats are therefore
    "1" + Gorilla XOR (NO_UPDATE) and the 2-bit "01" repeat — no int
    paths, no mode-transition full floats, no marker peeks, no done/err
    planes.
    Timestamps contribute only their consumed width (_ts_consumed_fast)."""
    rel_pos = jnp.asarray(rel_pos, I32)
    shape = rel_pos.shape
    active = jnp.asarray(num_bits, I32) > rel_pos
    pfb0 = (jnp.asarray(prev_float_bits[0], U32), jnp.asarray(prev_float_bits[1], U32))
    pxr0 = (jnp.asarray(prev_xor[0], U32), jnp.asarray(prev_xor[1], U32))
    state0 = (jnp.zeros(shape, I32), pfb0, pxr0)
    acc0 = (
        jnp.zeros(shape, F32),
        jnp.zeros(shape, I32),
        jnp.full(shape, jnp.inf, F32),
        jnp.full(shape, -jnp.inf, F32),
        jnp.full(shape, jnp.nan, F32),
    )
    active_i = active.astype(I32)
    # ts <= 36 bits; value <= 1 + 14 + 64 = 79 bits
    MAX_REC_BITS = 36 + 79

    def body(c, ts_widx, val_widx):
        (pos, pfb, pxr), acc = c
        s_sum, s_cnt, s_min, s_max, s_last = acc
        ws_ts = _fetch4_select(windows_cols, cw, rel_pos, pos, max_widx=ts_widx)
        pos = pos + _ts_consumed_fast(ws_ts)
        ws = _fetch4_select(windows_cols, cw, rel_pos, pos, max_widx=val_widx)
        # OPCODE_UPDATE = 0: the only update record a fast_float chunk can
        # contain is "01" (update+repeat, 2 bits); NO_UPDATE = 1 prefixes
        # the Gorilla XOR record at offset 1
        repeat = _extract32(ws, 0, 1) == 0
        nb, nx, consumed = _read_xor(ws, 1, pfb, pxr)
        pfb = u64.select(repeat, pfb, nb)
        pxr = u64.select(repeat, pxr, nx)
        pos = pos + jnp.where(repeat, 2, 1 + consumed)
        v = u64.f64_bits_to_f32(pfb)
        s_sum = s_sum + jnp.where(active, v, F32(0))
        s_cnt = s_cnt + active_i
        s_min = jnp.minimum(s_min, jnp.where(active, v, F32(jnp.inf)))
        s_max = jnp.maximum(s_max, jnp.where(active, v, F32(-jnp.inf)))
        s_last = jnp.where(active, v, s_last)
        return ((pos, pfb, pxr), (s_sum, s_cnt, s_min, s_max, s_last))

    if unroll:
        carry = (state0, acc0)
        for j in range(k):
            ts_widx = (31 + MAX_REC_BITS * j) >> 5
            val_widx = (31 + MAX_REC_BITS * j + 36) >> 5
            carry = body(carry, ts_widx, val_widx)
        _state, acc = carry
    else:
        _state, acc = jax.lax.fori_loop(
            0, k, lambda _i, c: body(c, None, None), (state0, acc0)
        )
    s_sum, s_cnt, s_min, s_max, s_last = acc
    return LaneAggregates(
        sum=s_sum, count=s_cnt, min=s_min, max=s_max, last=s_last,
        err=jnp.zeros(shape, bool),
    )


# ---------------------------------------------------------------------------
# jnp fallback path (CPU tests, oracle, non-TPU backends)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("k", "int_optimized"))
def lane_aggregates_jnp(
    windows, rel_pos, num_bits, first, prev_time, prev_delta, prev_float_bits,
    prev_xor, int_val, time_unit, sig, mult, is_float, k: int,
    int_optimized: bool = True,
) -> LaneAggregates:
    windows = jnp.asarray(windows, U32)
    cols = _window_columns(windows)
    return _run_lane_tile(
        cols, rel_pos, num_bits, first, prev_time, prev_delta, prev_float_bits,
        prev_xor, int_val, time_unit, sig, mult, is_float, k,
        windows.shape[1], int_optimized, use_scan=True,
    )


# ---------------------------------------------------------------------------
# Pallas TPU kernel — packed layout (the fast path)
# ---------------------------------------------------------------------------
#
# Profiling on real TPU showed the original kernel below is DMA-issue bound,
# not compute bound: each grid program pulled 24 strided window columns + 17
# separate 4KB lane arrays + 6 outputs (~47 small DMAs, ~7us/program), while
# the decode math itself was fully hidden. The packed layout moves the same
# bytes in 3 large contiguous DMAs per program: windows [tiles, CW, 8, 128],
# all 17 per-lane state fields in one u32 plane stack [tiles, NLANE, 8, 128],
# and one f32 [tiles, 6, 8, 128] output block.

# Order of the u32 planes in the packed lane array.
PACKED_LANE_PLANES = (
    "rel_pos", "num_bits", "first",
    "prev_time_hi", "prev_time_lo", "prev_delta_hi", "prev_delta_lo",
    "prev_float_bits_hi", "prev_float_bits_lo", "prev_xor_hi", "prev_xor_lo",
    "int_val_hi", "int_val_lo",
    "time_unit", "sig", "mult", "is_float",
)
NLANE = len(PACKED_LANE_PLANES)


class PackedLanes(NamedTuple):
    """Host-packed kernel inputs (see pack_lane_inputs)."""

    windows4: np.ndarray  # u32[tiles, CW, R, 128]
    lanes4: np.ndarray  # u32[tiles, NLANE, R, 128]
    tile_flags: np.ndarray  # i32[tiles]: 0 general, 1 every lane int-fast,
    #                         2 every lane float-fast
    n: int  # true lane count (before tile padding)
    order: str  # "c" (chunk-major), "s" (series-major), "sorted"
    inv: np.ndarray | None = None  # "sorted": i32[S]; original series i's
    #                                results sit at packed row inv[i]


def pack_lane_inputs(batch, order: str = "c", rows: int = ROWS_DEFAULT) -> PackedLanes:
    """Pack a ChunkedBatch's lane arrays into the kernel's DMA-friendly
    layout on the host (numpy; one-time per batch / done at fileset load).

    ``order="c"`` lays lanes out chunk-major (lane j = chunk_idx * S +
    series_idx): a tile then holds the SAME chunk position across ~1024
    series, so host-classified fast chunks (ChunkedBatch.fast) cluster into
    homogeneous tiles and the kernel picks the specialized body per tile.
    Series-major ("s") keeps the original ordering (mixed tiles, general
    body everywhere).

    ``order="sorted"`` additionally PERMUTES THE SERIES AXIS so series rich
    in fast chunks pack first: on a MIXED workload (float-mode series
    interleaved with int gauges) chunk-major tiles would all contain some
    slow lane and the whole batch would fall to the general body; sorting
    series by fast-chunk count reclusters the fast majority into
    homogeneous tiles. Permuting whole series (not individual lanes) keeps
    the per-series reduction a plain reshape — only the [S]-sized output
    arrays need a small inverse gather (PackedLanes.inv; a full [S*C] lane
    gather measured ~325 ms at 8M lanes on TPU, 8x the decode itself)."""
    windows = np.asarray(batch.windows, np.uint32)
    n, cw = windows.shape
    s, c = batch.num_series, batch.num_chunks

    perm_series = None
    inv_series = None
    if order == "sorted":
        fast_lanes = getattr(batch, "fast", None)
        ff_lanes = getattr(batch, "fast_float", None)
        int_cnt = (
            np.asarray(fast_lanes, bool).reshape(s, c).sum(axis=1)
            if fast_lanes is not None
            else np.zeros(s, np.int64)
        )
        flt_cnt = (
            np.asarray(ff_lanes, bool).reshape(s, c).sum(axis=1)
            if ff_lanes is not None
            else np.zeros(s, np.int64)
        )
        # group series by dominant class (int-fast, then float-fast, then
        # slow) so each class's tiles stay homogeneous; stable order within
        group = np.where(
            (int_cnt > 0) & (int_cnt >= flt_cnt), 0, np.where(flt_cnt > 0, 1, 2)
        )
        perm_series = np.argsort(group, kind="stable")
        inv_series = np.argsort(perm_series).astype(np.int32)

    def reorder(x):
        if order == "s":
            return x
        xs = x.reshape((s, c) + x.shape[1:])
        if perm_series is not None:
            xs = xs[perm_series]
        return np.ascontiguousarray(xs.swapaxes(0, 1).reshape(x.shape))

    if rows <= 0 or rows % 8:
        raise ValueError(f"rows must be a positive multiple of 8, got {rows}")
    tile_lanes = rows * 128
    tiles = -(-n // tile_lanes)
    npad = tiles * tile_lanes
    r, cc = rows, 128

    wpad = np.zeros((npad, cw), np.uint32)
    wpad[:n] = reorder(windows)
    windows4 = np.ascontiguousarray(
        wpad.reshape(tiles, r, cc, cw).transpose(0, 3, 1, 2)
    )

    def u32(x):
        x = np.asarray(x)
        if x.dtype == np.bool_:
            return x.astype(np.uint32)
        return x.astype(np.int32, copy=False).view(np.uint32)

    def plane(name):
        if name.endswith("_hi") or name.endswith("_lo"):
            pair = getattr(batch, name[:-3])
            return pair[0] if name.endswith("_hi") else pair[1]
        return getattr(batch, name)

    fields = [u32(reorder(np.asarray(plane(name)))) for name in PACKED_LANE_PLANES]
    lpad = np.zeros((NLANE, npad), np.uint32)
    for i, f in enumerate(fields):
        lpad[i, :n] = f
    lanes4 = np.ascontiguousarray(
        lpad.reshape(NLANE, tiles, r, cc).transpose(1, 0, 2, 3)
    )

    # tile class: 1 = every lane int-fast, 2 = every lane float-fast,
    # 0 = mixed/slow (general body). Padding lanes are wildcard-fast.
    def _pad_flags(arr):
        if arr is None:
            return np.zeros(npad, bool)
        p = np.ones(npad, bool)  # padding lanes never force a tile slow
        p[:n] = reorder(np.asarray(arr, bool))
        return p

    int_tiles = (
        _pad_flags(getattr(batch, "fast", None))
        .reshape(tiles, tile_lanes)
        .all(axis=1)
    )
    flt_tiles = (
        _pad_flags(getattr(batch, "fast_float", None))
        .reshape(tiles, tile_lanes)
        .all(axis=1)
    )
    tile_flags = np.where(int_tiles, 1, np.where(flt_tiles, 2, 0)).astype(np.int32)
    return PackedLanes(
        windows4=windows4, lanes4=lanes4, tile_flags=tile_flags, n=n,
        order=order, inv=inv_series,
    )


def _compiler_params(pltpu):
    """Mosaic compiler params across pallas API generations: the class was
    TPUCompilerParams before jax 0.6 renamed it CompilerParams."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=("arbitrary",))


def _pallas_kernel_packed(
    k, cw, int_optimized, unroll, specialize, flag_ref, win_ref, lane_ref, out_ref
):
    from jax.experimental import pallas as pl

    cols = [win_ref[0, j] for j in range(cw)]
    zero = jnp.zeros(win_ref.shape[2:], U32)
    cols = cols + [zero, zero, zero]
    ln = lambda name: lane_ref[0, PACKED_LANE_PLANES.index(name)]
    pair = lambda name: (ln(name + "_hi"), ln(name + "_lo"))
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, I32)

    def write(agg):
        out_ref[0, 0] = agg.sum
        # count <= k << 2^24, so f32 carries it exactly through the packed block
        out_ref[0, 1] = agg.count.astype(F32)
        out_ref[0, 2] = agg.min
        out_ref[0, 3] = agg.max
        out_ref[0, 4] = agg.last
        out_ref[0, 5] = agg.err.astype(F32)

    def general():
        write(
            _run_lane_tile(
                cols,
                as_i32(ln("rel_pos")),
                as_i32(ln("num_bits")),
                ln("first") != 0,
                pair("prev_time"),
                pair("prev_delta"),
                pair("prev_float_bits"),
                pair("prev_xor"),
                pair("int_val"),
                as_i32(ln("time_unit")),
                as_i32(ln("sig")),
                as_i32(ln("mult")),
                ln("is_float") != 0,
                k,
                cw,
                int_optimized,
                use_scan=False,
                unroll=unroll,
            )
        )

    if not specialize:
        general()
        return

    flag = flag_ref[pl.program_id(0)]
    pl.when(flag == 0)(general)

    @pl.when(flag == 1)
    def _fast():
        write(
            _run_lane_tile_fast(
                cols,
                as_i32(ln("rel_pos")),
                as_i32(ln("num_bits")),
                pair("int_val"),
                as_i32(ln("sig")),
                as_i32(ln("mult")),
                k,
                cw,
                unroll=unroll,
            )
        )

    @pl.when(flag == 2)
    def _fast_float():
        write(
            _run_lane_tile_fast_float(
                cols,
                as_i32(ln("rel_pos")),
                as_i32(ln("num_bits")),
                pair("prev_float_bits"),
                pair("prev_xor"),
                k,
                cw,
                unroll=unroll,
            )
        )


@functools.partial(
    jax.jit,
    static_argnames=("n", "k", "int_optimized", "interpret", "specialize"),
)
def lane_aggregates_packed(
    windows4, lanes4, tile_flags=None, n: int = 0, k: int = 0,
    int_optimized: bool = True, interpret: bool = False, specialize: bool = True,
) -> LaneAggregates:
    """Fast path: 3 contiguous DMAs per grid program (see module note).

    ``tile_flags`` (i32[tiles], from pack_lane_inputs) selects the
    specialized all-int marker-free body per tile; None or
    ``specialize=False`` compiles the general body only."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    windows4 = jnp.asarray(windows4, U32)
    lanes4 = jnp.asarray(lanes4, U32)
    tiles, cw = windows4.shape[0], windows4.shape[1]
    rows = windows4.shape[2]
    npad = tiles * rows * 128
    if tile_flags is None:
        tile_flags = jnp.zeros((tiles,), I32)
        specialize = False
    tile_flags = jnp.asarray(tile_flags, I32)

    # the tile flags ride scalar prefetch (SMEM); index maps gain the scalar
    # ref as a trailing arg per PrefetchScalarGridSpec convention
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((1, cw, rows, 128), lambda i, _f: (i, 0, 0, 0)),
            pl.BlockSpec((1, NLANE, rows, 128), lambda i, _f: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 6, rows, 128), lambda i, _f: (i, 0, 0, 0)),
    )
    outs = pl.pallas_call(
        functools.partial(
            _pallas_kernel_packed, k, cw, int_optimized, not interpret, specialize
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tiles, 6, rows, 128), F32),
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(tile_flags, windows4, lanes4)
    s_sum, s_cnt, s_min, s_max, s_last, s_err = (
        outs[:, i].reshape(npad)[:n] for i in range(6)
    )
    return LaneAggregates(
        sum=s_sum, count=s_cnt.astype(I32), min=s_min, max=s_max,
        last=s_last, err=s_err != 0,
    )


# ---------------------------------------------------------------------------
# Pallas TPU kernel — original per-field layout (kept for comparison/tests)
# ---------------------------------------------------------------------------


def _pallas_kernel(k, cw, int_optimized, unroll, win_ref, rel_ref, nbits_ref, first_ref,
                   pt_hi, pt_lo, pd_hi, pd_lo, pfb_hi, pfb_lo, pxr_hi, pxr_lo,
                   iv_hi, iv_lo, tu_ref, sig_ref, mult_ref, isf_ref,
                   sum_ref, cnt_ref, min_ref, max_ref, last_ref, err_ref):
    cols = [win_ref[j, 0] for j in range(cw)]
    zero = jnp.zeros(LANE_TILE, U32)
    cols = cols + [zero, zero, zero]
    agg = _run_lane_tile(
        cols,
        rel_ref[0],
        nbits_ref[0],
        first_ref[0] != 0,
        (pt_hi[0], pt_lo[0]),
        (pd_hi[0], pd_lo[0]),
        (pfb_hi[0], pfb_lo[0]),
        (pxr_hi[0], pxr_lo[0]),
        (iv_hi[0], iv_lo[0]),
        tu_ref[0],
        sig_ref[0],
        mult_ref[0],
        isf_ref[0] != 0,
        k,
        cw,
        int_optimized,
        use_scan=False,
        unroll=unroll,
    )
    sum_ref[0] = agg.sum
    cnt_ref[0] = agg.count
    min_ref[0] = agg.min
    max_ref[0] = agg.max
    last_ref[0] = agg.last
    err_ref[0] = agg.err.astype(I32)


@functools.partial(
    jax.jit, static_argnames=("k", "int_optimized", "interpret")
)
def lane_aggregates_pallas(
    windows, rel_pos, num_bits, first, prev_time, prev_delta, prev_float_bits,
    prev_xor, int_val, time_unit, sig, mult, is_float, k: int,
    int_optimized: bool = True, interpret: bool = False,
) -> LaneAggregates:
    """Tiled Pallas execution over [N] lanes (N padded to 1024 multiples).

    Host-side callers should pass numpy/jnp arrays; padding lanes decode
    zero bits and contribute identity values to every aggregate.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    windows = jnp.asarray(windows, U32)
    n, cw = windows.shape
    tiles = -(-n // TILE_LANES)
    npad = tiles * TILE_LANES

    def pad_to(x, fill=0):
        x = jnp.asarray(x)
        if x.shape[0] == npad:
            return x
        return jnp.concatenate(
            [x, jnp.full((npad - x.shape[0],) + x.shape[1:], fill, x.dtype)]
        )

    # windows transposed to [CW, tiles, 8, 128] so each column is a clean tile
    w = pad_to(windows).T.reshape(cw, tiles, *LANE_TILE)

    def lanes(x, fill=0, dtype=None):
        x = pad_to(jnp.asarray(x) if dtype is None else jnp.asarray(x, dtype), fill)
        return x.reshape(tiles, *LANE_TILE)

    args = [
        w,
        lanes(rel_pos),
        lanes(num_bits),
        lanes(jnp.asarray(first).astype(I32)),
        lanes(prev_time[0]), lanes(prev_time[1]),
        lanes(prev_delta[0]), lanes(prev_delta[1]),
        lanes(prev_float_bits[0]), lanes(prev_float_bits[1]),
        lanes(prev_xor[0]), lanes(prev_xor[1]),
        lanes(int_val[0]), lanes(int_val[1]),
        lanes(time_unit),
        lanes(sig),
        lanes(mult),
        lanes(jnp.asarray(is_float).astype(I32)),
    ]

    lane_spec = pl.BlockSpec((1, *LANE_TILE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    win_spec = pl.BlockSpec((cw, 1, *LANE_TILE), lambda i: (0, i, 0, 0), memory_space=pltpu.VMEM)
    out_shape = [
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), F32),
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), I32),
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), F32),
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), F32),
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), F32),
        jax.ShapeDtypeStruct((tiles, *LANE_TILE), I32),
    ]
    outs = pl.pallas_call(
        functools.partial(_pallas_kernel, k, cw, int_optimized, not interpret),
        grid=(tiles,),
        in_specs=[win_spec] + [lane_spec] * (len(args) - 1),
        out_specs=[lane_spec] * 6,
        out_shape=out_shape,
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(*args)
    s_sum, s_cnt, s_min, s_max, s_last, s_err = (o.reshape(npad)[:n] for o in outs)
    return LaneAggregates(
        sum=s_sum, count=s_cnt, min=s_min, max=s_max, last=s_last, err=s_err != 0
    )
