"""Vectorized uint64 emulation as (hi, lo) uint32 pairs.

TPUs have no native 64-bit integers, but the M3TSZ stream is defined over
64-bit words (float64 bit patterns, unix-nano timestamps — SURVEY.md §2.5,
reference /root/reference/src/dbnode/encoding/m3tsz/). Every 64-bit quantity
on device is a pair of uint32 arrays; all ops are elementwise and shape-
polymorphic so they vectorize over the series axis for free.

Shift amounts are data-dependent vectors; XLA leaves shifts >= bit width
undefined, so every variable shift here is clamped and masked explicitly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as _np
from jax import lax

_np_int = _np.integer

U32 = jnp.uint32
# np scalar, NOT jnp: a module-level jnp scalar is a concrete device array
# that Pallas kernels would capture as an illegal closed-over constant.
MASK32 = _np.uint32(0xFFFFFFFF)


def u64(hi, lo):
    return jnp.asarray(hi, U32), jnp.asarray(lo, U32)


def from_u32(x):
    x = jnp.asarray(x, U32)
    return jnp.zeros_like(x), x


def from_i32(x):
    """Sign-extend an int32 vector into a 64-bit pair (two's complement)."""
    x32 = jnp.asarray(x, jnp.int32)
    hi = jnp.where(x32 < 0, MASK32, _np.uint32(0))
    return hi, x32.astype(U32)


def const(v: int, shape=(), dtype=U32):
    v &= (1 << 64) - 1
    return (
        jnp.full(shape, (v >> 32) & 0xFFFFFFFF, dtype),
        jnp.full(shape, v & 0xFFFFFFFF, dtype),
    )


def add(a, b):
    ah, al = a
    bh, bl = b
    lo = al + bl
    carry = (lo < al).astype(U32)
    hi = ah + bh + carry
    return hi, lo


def neg(a):
    ah, al = a
    return add((~ah, ~al), const(1))


def sub(a, b):
    return add(a, neg(b))


def bxor(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def band(a, b):
    return a[0] & b[0], a[1] & b[1]


def bor(a, b):
    return a[0] | b[0], a[1] | b[1]


def eq(a, b):
    return (a[0] == b[0]) & (a[1] == b[1])


def is_zero(a):
    return (a[0] == 0) & (a[1] == 0)


def lt_u(a, b):
    """Unsigned 64-bit less-than."""
    return (a[0] < b[0]) | ((a[0] == b[0]) & (a[1] < b[1]))


def is_neg(a):
    """Sign bit of a two's-complement pair."""
    return (a[0] >> 31) != 0


def shl(a, s):
    """Logical shift left by amounts s in [0, 64] (vector or Python int)."""
    hi, lo = a
    if isinstance(s, (int, _np_int)):
        s = int(s)
        if s == 0:
            return hi, lo
        if s < 32:
            return (hi << U32(s)) | (lo >> U32(32 - s)), lo << U32(s)
        if s == 32:
            return lo, jnp.zeros_like(lo)
        if s < 64:
            return lo << U32(s - 32), jnp.zeros_like(lo)
        return jnp.zeros_like(hi), jnp.zeros_like(lo)
    s = jnp.asarray(s, U32)
    # NOT jnp.minimum: unsigned vector min lowers to an i8->i1 trunc that
    # Mosaic rejects inside fori_loop bodies (Pallas kernel path).
    s1 = jnp.where(s < U32(31), s, U32(31))
    hi_a = (hi << s1) | jnp.where(s1 == 0, U32(0), lo >> (U32(32) - s1))
    lo_a = lo << s1
    s2 = jnp.clip(s.astype(jnp.int32) - 32, 0, 31).astype(U32)
    hi_b = lo << s2
    lt32 = s < 32
    ge64 = s >= 64
    out_hi = jnp.where(lt32, hi_a, jnp.where(ge64, U32(0), hi_b))
    out_lo = jnp.where(lt32, lo_a, U32(0))
    return out_hi, out_lo


def shr(a, s):
    """Logical shift right by amounts s in [0, 64] (vector or Python int)."""
    hi, lo = a
    if isinstance(s, (int, _np_int)):
        s = int(s)
        if s == 0:
            return hi, lo
        if s < 32:
            return hi >> U32(s), (lo >> U32(s)) | (hi << U32(32 - s))
        if s == 32:
            return jnp.zeros_like(hi), hi
        if s < 64:
            return jnp.zeros_like(hi), hi >> U32(s - 32)
        return jnp.zeros_like(hi), jnp.zeros_like(lo)
    s = jnp.asarray(s, U32)
    # NOT jnp.minimum: unsigned vector min lowers to an i8->i1 trunc that
    # Mosaic rejects inside fori_loop bodies (Pallas kernel path).
    s1 = jnp.where(s < U32(31), s, U32(31))
    lo_a = (lo >> s1) | jnp.where(s1 == 0, U32(0), hi << (U32(32) - s1))
    hi_a = hi >> s1
    s2 = jnp.clip(s.astype(jnp.int32) - 32, 0, 31).astype(U32)
    lo_b = hi >> s2
    lt32 = s < 32
    ge64 = s >= 64
    out_hi = jnp.where(lt32, hi_a, U32(0))
    out_lo = jnp.where(lt32, lo_a, jnp.where(ge64, U32(0), lo_b))
    return out_hi, out_lo


def sar(a, s):
    """Arithmetic shift right by vector amounts s in [0, 64]."""
    hi, lo = a
    sign = is_neg(a)
    h, l = shr(a, s)
    # Fill vacated high bits with ones when negative.
    ones = (jnp.full_like(h, 0xFFFFFFFF), jnp.full_like(l, 0xFFFFFFFF))
    fh, fl = shl(ones, jnp.asarray(64, U32) - jnp.asarray(s, U32))
    out_hi = jnp.where(sign, h | fh, h)
    out_lo = jnp.where(sign, l | fl, l)
    return out_hi, out_lo


def sign_extend(a, num_bits):
    """Sign-extend the low ``num_bits`` of a pair (encoding.go SignExtend)."""
    s = jnp.asarray(64, U32) - jnp.asarray(num_bits, U32)
    return sar(shl(a, s), s)


def clz32(x):
    return lax.clz(x.astype(jnp.int32)).astype(jnp.int32)


def ctz32(x):
    """Count trailing zeros of uint32; 32 for zero input."""
    x = jnp.asarray(x, U32)
    lowbit = x & (~x + U32(1))
    return jnp.where(x == 0, jnp.int32(32), 31 - clz32(lowbit))


def clz(a):
    hi, lo = a
    return jnp.where(hi != 0, clz32(hi), 32 + clz32(lo))


def ctz(a):
    hi, lo = a
    # Matches reference LeadingAndTrailingZeros: trailing zeros of 0 is 0 there,
    # but full-pair ctz of 0 would be 64; callers guard the zero case.
    return jnp.where(lo != 0, ctz32(lo), 32 + ctz32(hi))


def mul_u32(a, m):
    """64-bit pair times a uint32 vector (mod 2^64)."""
    hi, lo = a
    m = jnp.asarray(m, U32)
    p_hi, p_lo = umul32_wide(lo, m)
    return hi * m + p_hi, p_lo


def umul32_wide(a, b):
    """Full 32x32 -> 64 unsigned multiply as (hi, lo)."""
    a = jnp.asarray(a, U32)
    b = jnp.asarray(b, U32)
    a0 = a & U32(0xFFFF)
    a1 = a >> 16
    b0 = b & U32(0xFFFF)
    b1 = b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & U32(0xFFFF)) + (p10 & U32(0xFFFF))
    lo = (p00 & U32(0xFFFF)) | (mid << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def u32_to_f32(x):
    """uint32 -> float32 value conversion via int32 halves.

    Mosaic (Pallas TPU) has no uint32->float32 convert; 16-bit halves cast
    exactly through int32 and recombine without precision loss beyond f32's
    own 24-bit mantissa."""
    x = jnp.asarray(x, U32)
    hi = (x >> U32(16)).astype(jnp.int32).astype(jnp.float32)
    lo = (x & U32(0xFFFF)).astype(jnp.int32).astype(jnp.float32)
    return hi * jnp.float32(65536.0) + lo


def to_f32(a):
    """Approximate signed 64-bit pair -> float32 (for on-device aggregation)."""
    hi, lo = a
    hi_signed = hi.astype(jnp.int32).astype(jnp.float32)
    return hi_signed * jnp.float32(4294967296.0) + u32_to_f32(lo)


def f64_bits_to_f32(a):
    """Interpret a pair as float64 bits and convert the value to float32.

    Values outside float32 range become +/-inf; subnormal float64 flush toward
    zero. NaN and inf are preserved. Used only for on-device f32 aggregation —
    bit-exact results flow through the (hi, lo) pairs themselves.
    """
    hi, lo = a
    sign = jnp.where((hi >> 31) != 0, jnp.float32(-1.0), jnp.float32(1.0))
    exp = ((hi >> 20) & U32(0x7FF)).astype(jnp.int32)
    mant = (hi & U32(0xFFFFF)).astype(jnp.int32).astype(jnp.float32) * jnp.float32(
        2.0**32
    ) + u32_to_f32(lo)
    frac = mant * jnp.float32(2.0**-52)
    # Exact power-of-two scaling: bitcast (e+127)<<23 rather than jnp.exp2,
    # which is a polynomial approximation on some backends (CPU) and loses
    # ~2^-18 relative accuracy at large exponents.
    def pow2(e_int):
        bits = ((e_int + 127).astype(jnp.uint32)) << U32(23)
        return lax.bitcast_convert_type(bits, jnp.float32)

    e = jnp.clip(exp - 1023, -149, 128)
    e1 = jnp.clip(e, -126, 127)
    magnitude = (jnp.float32(1.0) + frac) * pow2(e1) * pow2(e - e1)
    magnitude = jnp.where(exp == 0, frac * pow2(jnp.full_like(exp, -126)), magnitude)
    special = exp == 0x7FF
    inf = jnp.float32(jnp.inf)
    nan = jnp.float32(jnp.nan)
    magnitude = jnp.where(special, jnp.where(mant == 0, inf, nan), magnitude)
    return sign * magnitude


def select(pred, a, b):
    """Elementwise select between two pairs."""
    return jnp.where(pred, a[0], b[0]), jnp.where(pred, a[1], b[1])
