"""Compensated (float-float) summation for device aggregates.

Reference context: the reference's query math runs in float64 end to end
(src/query/functions/temporal/aggregation.go:62-267, ts.Datapoints are
float64). TPUs are f32-native (f64 is software-emulated and slow), so this
framework's aggregation paths default to f32 — fine per-window, but a
cross-series sum over tens of millions of values accumulates rounding.
This module provides the documented-precision option (TOLERANCE.md):

- ``two_sum(a, b)``: Knuth's error-free transformation — s = fl(a+b) and
  the EXACT rounding error e, so (s, e) represents a+b exactly.
- ``compensated_sum(x, axis)``: binary-tree reduction carrying (hi, lo)
  float-float pairs; the returned pair is within 1 ulp of the exact sum
  for n ≤ 2^24 addends (vs O(log n) ulp for XLA's plain tree sum and
  O(n) ulp for sequential f32).
- ``dd_add(a, b)``: combine two (hi, lo) pairs — also the cross-chip
  reduction operator: psum hi and lo separately, then renormalize.

Everything is shape-polymorphic jnp and TPU-friendly: log2(n) vectorized
combine levels, no data-dependent control flow.
"""

from __future__ import annotations

import jax.numpy as jnp


def two_sum(a, b):
    """Error-free transformation: a + b = s + e exactly (Knuth 2Sum)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def fast_two_sum(a, b):
    """Dekker's version; requires |a| >= |b| (used for renormalization)."""
    s = a + b
    e = b - (s - a)
    return s, e


def dd_add(a, b):
    """(hi, lo) + (hi, lo) → normalized (hi, lo)."""
    s, e = two_sum(a[0], b[0])
    e = e + (a[1] + b[1])
    return fast_two_sum(s, e)


def compensated_sum(x, axis: int = -1):
    """Float-float tree sum along ``axis``; returns (hi, lo) arrays with
    that axis reduced. hi + lo is within ~1 ulp of the exact f32-input sum.
    """
    x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    hi = x
    lo = jnp.zeros_like(x)
    # pad to a power of two with exact zeros
    p = 1
    while p < n:
        p *= 2
    if p != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, p - n)]
        hi = jnp.pad(hi, pad)
        lo = jnp.pad(lo, pad)
    while hi.shape[-1] > 1:
        half = hi.shape[-1] // 2
        a = (hi[..., :half], lo[..., :half])
        b = (hi[..., half:], lo[..., half:])
        hi, lo = dd_add(a, b)
    return hi[..., 0], lo[..., 0]


def compensated_value(pair) -> jnp.ndarray:
    """Collapse (hi, lo) to the closest single float."""
    return pair[0] + pair[1]
