"""Batched M3TSZ ENCODE on device: the write-path twin of ops/chunked.py.

The read path decodes chunk-parallel straight from HBM residency
(decode_chunked_lanes); this module closes the loop by ENCODING sealed
blocks lane-parallel on device, so a flush's streams are born as
resident-pool pages instead of host-encoded bytes uploaded over PCIe.
``codec/m3tsz.py`` stays the bit-exactness oracle: for every lane this
kernel accepts, its output bytes are IDENTICAL to the host encoder's
(tests/test_encode.py proves the roundtrip and fileset byte-identity),
and every lane it cannot express (annotations, time-unit changes,
non-second-aligned starts, int/float mode mixing, >i32 magnitudes)
falls back to the host codec at seal — correctness never depends on the
classifier, only throughput does.

Shape of the kernel (one jit per (T, W) bucket):

- host ``classify_lanes`` gates each lane INT-FAST (every value hits the
  ``convert_to_int_float`` quick path, |value| and |diff| fit int32) or
  FLOAT-FAST (every value probes float, so the stream is pure XOR
  records after the first) — the same two regimes ops/chunked.py's fast
  chunk bodies decode;
- per-record emission is decomposed into at most 8 fixed SLOTS of <=32
  bits each (first-timestamp hi/lo, dod opcode, dod value, value
  control, sig/meaningful header, value hi, value lo). Slot contents
  are elementwise given the sig-tracker state; the ONLY sequential
  state is the int significant-bits hysteresis (IntSigBitsTracker),
  carried by a T-step ``lax.scan`` vectorized across lanes — the XOR
  chain's prev-bits/prev-xor are a shift and a host forward-fill;
- an exclusive cumsum of slot bit-lengths turns slots into bit offsets
  (chunk boundaries fall out as every CHUNK_K-th record's offset — the
  packed side planes ride for free), and two scatter-adds per slot pack
  the bits MSB-first into big-endian uint32 words, the exact layout
  ``_fetch4_select`` reads back. Different slots never share a bit, so
  add IS or. A final 11-bit slot writes the EOS marker; truncating the
  word row at ceil(bits/8) bytes reproduces ``Encoder.stream()``'s
  canonical tail byte-for-byte.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import numpy as np

NANOS_PER_SECOND = 1_000_000_000
I32_MAX = 2_147_483_647
CHUNK_K_DEFAULT = 32

# int-mode significant-bit hysteresis (codec/m3tsz.py)
_SIG_DIFF_THRESHOLD = 3
_SIG_REPEAT_THRESHOLD = 5

KIND_NONE = 0  # host-codec fallback lane
KIND_INT = 1
KIND_FLOAT = 2

_M64 = (1 << 64) - 1


def probe_is_float(v: np.ndarray) -> np.ndarray:
    """Vectorized ``convert_to_int_float(v, 0)[2]``: True where the host
    probe keeps the value in float mode. Bit-exact with the scalar probe
    (same modf/nextafter ladder, mult 0..6, MAX_OPT_INT cutoff)."""
    v = np.asarray(v, np.float64)
    frac, _ = np.modf(v)
    # quick path: already an int and below float64(MaxInt64)
    decided_int = (v < float(2**63)) & (frac == 0)
    val = np.abs(v)
    for _ in range(7):  # mult = 0..MAX_MULT
        active = ~decided_int & (val < 10.0**13)
        if not active.any():
            break
        frac, i = np.modf(val)
        hit = (
            (frac == 0)
            | ((frac < 0.1) & (np.nextafter(val, 0.0) <= i))
            | ((frac > 0.9) & (np.nextafter(val, i + 1.0) >= i + 1.0))
        )
        decided_int |= active & hit
        val = np.where(active, val * 10.0, val)
    return ~decided_int


class LaneClass(NamedTuple):
    kind: int  # KIND_NONE / KIND_INT / KIND_FLOAT
    reason: str  # why a lane fell back (counter labels / debugging)


def classify_lane(t: np.ndarray, v: np.ndarray, u: np.ndarray) -> LaneClass:
    """Gate one merged lane (times int64 nanos, values float64, unit
    ints) for the device encoder. Conservative: anything the kernel
    cannot reproduce BIT-EXACTLY against codec/m3tsz.py is KIND_NONE."""
    n = len(t)
    if n == 0:
        return LaneClass(KIND_NONE, "empty")
    if not (np.asarray(u) == 1).all():  # Unit.SECOND only
        return LaneClass(KIND_NONE, "unit")
    t = np.asarray(t, np.int64)
    if t[0] < 0 or (t % NANOS_PER_SECOND != 0).any():
        # an unaligned START makes initial_time_unit NONE (the first
        # record then emits a time-unit marker the kernel does not
        # speak); an unaligned LATER timestamp makes the dod
        # normalization lossy, so the decoder's reconstructed prev_time
        # diverges from the raw column and the side-row carries would
        # not match snapshot_stream
        return LaneClass(KIND_NONE, "unaligned")
    if n > 1 and not (t[1:] > t[:-1]).all():
        return LaneClass(KIND_NONE, "unsorted")
    deltas = np.concatenate([np.zeros(1, np.int64), np.diff(t)])
    dd = deltas - np.concatenate([np.zeros(1, np.int64), deltas[:-1]])
    dod = np.where(dd >= 0, dd // NANOS_PER_SECOND, -((-dd) // NANOS_PER_SECOND))
    if (np.abs(dod) > I32_MAX).any():
        return LaneClass(KIND_NONE, "dod_overflow")
    v = np.asarray(v, np.float64)
    frac, _ = np.modf(v)
    quick_int = (v < float(2**63)) & (frac == 0)
    if quick_int.all():
        with np.errstate(invalid="ignore"):
            if not (np.abs(v) <= I32_MAX).all():
                return LaneClass(KIND_NONE, "int_overflow")
        iv = v.astype(np.int64)
        if n > 1 and (np.abs(np.diff(iv)) > I32_MAX).any():
            return LaneClass(KIND_NONE, "diff_overflow")
        return LaneClass(KIND_INT, "")
    if probe_is_float(v).all():
        return LaneClass(KIND_FLOAT, "")
    return LaneClass(KIND_NONE, "mixed_mode")


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

_SLOTS = 8  # per-record emission slots, each <= 32 bits
# worst-case record widths (bits): rec0 float 65+1+64; later float
# 36+3+12+64; later int 36+3+9+33 — float dominates
_REC0_BITS = 130
_REC_BITS = 115
_EOS_BITS = 11


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def words_bound(T: int, round_words_to: int = 1) -> int:
    bits = _REC0_BITS + _REC_BITS * max(T - 1, 0) + _EOS_BITS + 31
    return _round_up(max(bits // 32, 1), round_words_to)


@lru_cache(maxsize=32)
def _build_kernel(T: int, W: int, K: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import u64

    U32 = jnp.uint32
    C = max((T + K - 1) // K, 1)

    def kernel(
        t0_hi, t0_lo,  # u32[M] first-timestamp nanos pair
        dod,  # i32[T, M] normalized delta-of-delta (dod[0] == 0)
        valid,  # bool[T, M]
        float_lane,  # bool[M]
        absval,  # u32[T, M] |v0| at rec0, |prev - cur| after (int lanes)
        negbit,  # u32[T, M] sign opcode bit (1 = decoder ADDS)
        int_repeat,  # bool[T, M] prev == cur (int lanes, j > 0)
        vb_hi, vb_lo,  # u32[T, M] IEEE-754 bits (float lanes)
        pxr_hi, pxr_lo,  # u32[T, M] prev_xor BEFORE record j (host ffill)
    ):
        M = t0_hi.shape[0]
        j_idx = jnp.arange(T, dtype=jnp.int32)[:, None]
        rec0 = (j_idx == 0) & valid
        later = (j_idx > 0) & valid

        # --- int sig tracker: the one truly sequential piece ---
        sig_in = (jnp.int32(32) - u64.clz32(absval)).astype(jnp.int32)
        active = valid & ~float_lane[None, :] & ~(int_repeat & (j_idx > 0))
        is_rec0_row = j_idx == 0

        def step(carry, x):
            ns, ch, nl = carry
            sig, r0, act = x
            gt = sig > ns
            low = (ns - sig) >= _SIG_DIFF_THRESHOLD
            ch_l = jnp.where(nl == 0, sig, jnp.maximum(ch, sig))
            nl_l = nl + 1
            hit = nl_l >= _SIG_REPEAT_THRESHOLD
            ns_low = jnp.where(hit, ch_l, ns)
            nl_l = jnp.where(hit, 0, nl_l)
            new_sig = jnp.where(gt, sig, jnp.where(low, ns_low, ns))
            ch_n = jnp.where(low, ch_l, ch)
            nl_n = jnp.where(gt, nl, jnp.where(low, nl_l, 0))
            # first record: write_int_sig(sig) only, counters untouched
            new_sig = jnp.where(r0, sig, new_sig)
            ch_n = jnp.where(r0, ch, ch_n)
            nl_n = jnp.where(r0, nl, nl_n)
            ns2 = jnp.where(act, new_sig, ns)
            ch2 = jnp.where(act, ch_n, ch)
            nl2 = jnp.where(act, nl_n, nl)
            return (ns2, ch2, nl2), (ns, ns2)

        z = jnp.zeros((M,), jnp.int32)
        (_, _, _), (ns_before, ns_after) = lax.scan(
            step,
            (z, z, z),
            (sig_in, jnp.broadcast_to(is_rec0_row, (T, M)), active),
        )

        # --- timestamp slots (elementwise) ---
        l_tsh = jnp.where(rec0, 32, 0)
        v_tsh = jnp.where(rec0, t0_hi[None, :], U32(0))
        l_tsl = jnp.where(rec0, 32, 0)
        v_tsl = jnp.where(rec0, t0_lo[None, :], U32(0))
        zero = dod == 0
        b7 = (dod >= -64) & (dod <= 63)
        b9 = (dod >= -256) & (dod <= 255)
        b12 = (dod >= -2048) & (dod <= 2047)
        l_op = jnp.where(zero, 1, jnp.where(b7, 2, jnp.where(b9, 3, 4)))
        v_op = jnp.where(zero, 0, jnp.where(b7, 2, jnp.where(b9, 6, jnp.where(b12, 14, 15)))).astype(U32)
        l_dv = jnp.where(zero, 0, jnp.where(b7, 7, jnp.where(b9, 9, jnp.where(b12, 12, 32))))
        dmask = jnp.where(
            l_dv == 0, U32(0), U32(0xFFFFFFFF) >> (U32(32) - l_dv.astype(U32))
        )
        v_dv = dod.astype(U32) & dmask
        l_op = jnp.where(valid, l_op, 0)
        l_dv = jnp.where(valid, l_dv, 0)

        # --- int value slots ---
        width = jnp.where(is_rec0_row, sig_in, ns_after)
        upd = later & (ns_before != ns_after)
        # ctrl: rec0 '0'; repeat '01'; update '000'; steady '1'
        i_ctrl_v = jnp.where(
            rec0, U32(0), jnp.where(int_repeat, U32(1), jnp.where(upd, U32(0), U32(1)))
        )
        i_ctrl_l = jnp.where(
            rec0, 1, jnp.where(int_repeat, 2, jnp.where(upd, 3, 1))
        )
        # sig/mult header: UPDATE_SIG+NON_ZERO+6bits(sig-1)+NO_UPDATE_MULT
        hdr9 = U32(0x180) | ((width.astype(U32) - U32(1)) << U32(1))
        i_hdr_v = jnp.where(rec0 & (sig_in > 0), hdr9, jnp.where(upd, hdr9, U32(0)))
        i_hdr_l = jnp.where(
            rec0, jnp.where(sig_in > 0, 9, 2), jnp.where(upd, 9, 0)
        )
        i_val_v = (negbit << width.astype(U32)) | absval
        i_val_l = 1 + width
        irep = int_repeat & later
        i_hdr_v = jnp.where(irep, U32(0), i_hdr_v)
        i_hdr_l = jnp.where(irep, 0, i_hdr_l)
        i_val_v = jnp.where(irep, U32(0), i_val_v)
        i_val_l = jnp.where(irep, 0, i_val_l)

        # --- float value slots ---
        pvb_hi = jnp.concatenate([vb_hi[:1], vb_hi[:-1]], axis=0)
        pvb_lo = jnp.concatenate([vb_lo[:1], vb_lo[:-1]], axis=0)
        f_rep = later & (vb_hi == pvb_hi) & (vb_lo == pvb_lo)
        x_hi = vb_hi ^ pvb_hi
        x_lo = vb_lo ^ pvb_lo
        pl = u64.clz((pxr_hi, pxr_lo))
        pt = u64.ctz((pxr_hi, pxr_lo))
        cl = u64.clz((x_hi, x_lo))
        ct = u64.ctz((x_hi, x_lo))
        contained = (cl >= pl) & (ct >= pt)
        len_c = 64 - pl - pt
        nm = 64 - cl - ct
        pay_c = u64.shr((x_hi, x_lo), pt.astype(U32))
        pay_u = u64.shr((x_hi, x_lo), ct.astype(U32))
        flen = jnp.where(contained, len_c, nm)
        pay_hi = jnp.where(contained, pay_c[0], pay_u[0])
        pay_lo = jnp.where(contained, pay_c[1], pay_u[1])
        f_ctrl_v = jnp.where(
            rec0, U32(1), jnp.where(f_rep, U32(1), jnp.where(contained, U32(6), U32(7)))
        )
        f_ctrl_l = jnp.where(rec0, 1, jnp.where(f_rep, 2, 3))
        f_hdr_v = jnp.where(
            later & ~f_rep & ~contained,
            (cl.astype(U32) << U32(6)) | (nm.astype(U32) - U32(1)),
            U32(0),
        )
        f_hdr_l = jnp.where(later & ~f_rep & ~contained, 12, 0)
        f_vhi_v = jnp.where(rec0, vb_hi, jnp.where(f_rep, U32(0), pay_hi))
        f_vhi_l = jnp.where(rec0, 32, jnp.where(f_rep, 0, jnp.maximum(flen - 32, 0)))
        f_vlo_v = jnp.where(rec0, vb_lo, jnp.where(f_rep, U32(0), pay_lo))
        f_vlo_l = jnp.where(rec0, 32, jnp.where(f_rep, 0, jnp.minimum(flen, 32)))

        # --- merge lanes, mask invalid records ---
        fl = float_lane[None, :]

        def pick(fv, iv_):
            return jnp.where(fl, fv, iv_)

        v_ctrl = pick(f_ctrl_v, i_ctrl_v)
        l_ctrl = jnp.where(valid, pick(f_ctrl_l, i_ctrl_l), 0)
        v_hdr = pick(f_hdr_v, i_hdr_v)
        l_hdr = jnp.where(valid, pick(f_hdr_l, i_hdr_l), 0)
        v_vhi = pick(f_vhi_v, i_val_v)
        l_vhi = jnp.where(valid, pick(f_vhi_l, i_val_l), 0)
        v_vlo = pick(f_vlo_v, U32(0))
        l_vlo = jnp.where(valid, pick(f_vlo_l, 0), 0)

        vals = jnp.stack([v_tsh, v_tsl, v_op, v_dv, v_ctrl, v_hdr, v_vhi, v_vlo], 1)
        lens = jnp.stack([l_tsh, l_tsl, l_op, l_dv, l_ctrl, l_hdr, l_vhi, l_vlo], 1)
        vals = vals.reshape(T * _SLOTS, M)
        lens = lens.reshape(T * _SLOTS, M).astype(jnp.int32)
        # trailing EOS marker slot (9-bit opcode 0x100 + 2-bit value 0)
        vals = jnp.concatenate([vals, jnp.full((1, M), 0x400, U32)], 0)
        lens = jnp.concatenate([lens, jnp.full((1, M), _EOS_BITS, jnp.int32)], 0)

        inc = jnp.cumsum(lens, axis=0)
        offs = inc - lens  # exclusive
        total_bits = inc[-1]
        chunk_offs = offs[:: K * _SLOTS][:C]
        chunk_sigs = ns_before[::K][:C]

        # --- emission: two scatter-adds per slot into big-endian words ---
        b = (offs & 31).astype(jnp.int32)
        end = b + lens
        shl_hi = jnp.clip(32 - end, 0, 31).astype(U32)
        shr_hi = jnp.clip(end - 32, 0, 31).astype(U32)
        hi = jnp.where(end <= 32, vals << shl_hi, vals >> shr_hi)
        shl_lo = jnp.clip(64 - end, 0, 31).astype(U32)
        lo = jnp.where(end > 32, vals << shl_lo, U32(0))
        hi = jnp.where(lens > 0, hi, U32(0))
        lo = jnp.where(lens > 0, lo, U32(0))
        w = (offs >> 5).astype(jnp.int32)
        lane = jnp.broadcast_to(jnp.arange(M, dtype=jnp.int32)[None, :], w.shape)
        flat_hi = (lane * W + w).reshape(-1)
        flat_lo = (lane * W + w + 1).reshape(-1)
        out = jnp.zeros((M * W,), U32)
        out = out.at[flat_hi].add(hi.reshape(-1), mode="drop")
        out = out.at[flat_lo].add(lo.reshape(-1), mode="drop")
        return out.reshape(M, W), total_bits, chunk_offs, chunk_sigs

    return jax.jit(kernel)


class EncodeResult(NamedTuple):
    """Device-encoded lane batch. ``words`` stays on device (the
    resident pool admits it without re-upload); everything else is
    small host metadata."""

    words: object  # device uint32[M, W]
    total_bits: np.ndarray  # int64[M], EOS included
    nbytes: np.ndarray  # int64[M] finalized stream length
    chunk_offs: np.ndarray  # int64[Cmax, M] bit offset at each chunk start
    chunk_sigs: np.ndarray  # int32[Cmax, M] tracker num_sig at chunk start
    n_chunks: np.ndarray  # int32[M]
    kinds: np.ndarray  # int8[M] KIND_INT / KIND_FLOAT
    counts: np.ndarray  # int32[M]
    chunk_k: int

    def streams(self) -> list[bytes]:
        """Finalized m3tsz byte streams — ONE device->host transfer for
        the whole batch (fileset persistence / oracle tests), never on
        the admission hot path."""
        host = np.asarray(self.words).astype(">u4")
        return [
            host[m].tobytes()[: int(self.nbytes[m])] for m in range(host.shape[0])
        ]


def encode_lanes(
    lanes: list,
    kinds,
    k: int = CHUNK_K_DEFAULT,
    round_words_to: int = 1,
) -> EncodeResult | None:
    """Encode classified lanes on device. ``lanes`` is a list of
    ``(times int64[N], values float64[N])``; ``kinds[i]`` must be
    KIND_INT or KIND_FLOAT (run :func:`classify_lane` first). Returns
    None for an empty batch."""
    M = len(lanes)
    if M == 0:
        return None
    kinds = np.asarray(kinds, np.int8)
    counts = np.asarray([len(t) for t, _ in lanes], np.int32)
    T = int(counts.max())
    # pad T to buckets so the jit cache stays small
    T_pad = max(8, 1 << int(np.ceil(np.log2(T))))
    W = words_bound(T_pad, round_words_to)

    t0 = np.zeros(M, np.uint64)
    dod = np.zeros((T_pad, M), np.int32)
    valid = np.zeros((T_pad, M), bool)
    absval = np.zeros((T_pad, M), np.uint32)
    negbit = np.zeros((T_pad, M), np.uint32)
    int_repeat = np.zeros((T_pad, M), bool)
    vb_hi = np.zeros((T_pad, M), np.uint32)
    vb_lo = np.zeros((T_pad, M), np.uint32)
    pxr_hi = np.zeros((T_pad, M), np.uint32)
    pxr_lo = np.zeros((T_pad, M), np.uint32)

    for m, (t, v) in enumerate(lanes):
        t = np.asarray(t, np.int64)
        v = np.asarray(v, np.float64)
        n = len(t)
        t0[m] = np.uint64(t[0])
        valid[:n, m] = True
        deltas = np.concatenate([np.zeros(1, np.int64), np.diff(t)])
        dd = deltas - np.concatenate([np.zeros(1, np.int64), deltas[:-1]])
        dod[:n, m] = np.where(
            dd >= 0, dd // NANOS_PER_SECOND, -((-dd) // NANOS_PER_SECOND)
        ).astype(np.int32)
        if kinds[m] == KIND_INT:
            iv = v.astype(np.int64)
            d = np.concatenate([iv[:1], iv[:-1] - iv[1:]])
            absval[:n, m] = np.abs(d).astype(np.uint32)
            # rec0: OPCODE_NEGATIVE written when v0 >= 0 (decode adds);
            # later: when prev - cur < 0 (decode adds |d| -> cur > prev)
            nb = np.where(d < 0, 1, 0)
            nb[0] = 1 if iv[0] >= 0 else 0
            negbit[:n, m] = nb
            int_repeat[1:n, m] = d[1:] == 0
        else:
            vb = v.view(np.uint64)
            vb_hi[:n, m] = (vb >> np.uint64(32)).astype(np.uint32)
            vb_lo[:n, m] = (vb & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            if n > 1:
                # prev_xor BEFORE record j: forward fill of nonzero xors,
                # seeded with the first value's bits (write_full_float)
                src = np.concatenate([vb[:1], vb[1:] ^ vb[:-1]])
                updated = np.concatenate([[True], vb[1:] != vb[:-1]])
                last = np.maximum.accumulate(np.where(updated, np.arange(n), 0))
                px_after = src[last]
                pxr = np.concatenate([np.zeros(1, np.uint64), px_after[:-1]])
                pxr_hi[:n, m] = (pxr >> np.uint64(32)).astype(np.uint32)
                pxr_lo[:n, m] = (pxr & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    kern = _build_kernel(T_pad, W, k)
    words, total_bits, chunk_offs, chunk_sigs = kern(
        (t0 >> np.uint64(32)).astype(np.uint32),
        (t0 & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        dod, valid, kinds == KIND_FLOAT,
        absval, negbit, int_repeat,
        vb_hi, vb_lo, pxr_hi, pxr_lo,
    )
    total_bits = np.asarray(total_bits, np.int64)
    return EncodeResult(
        words=words,
        total_bits=total_bits,
        nbytes=(total_bits + 7) // 8,
        chunk_offs=np.asarray(chunk_offs, np.int64),
        chunk_sigs=np.asarray(chunk_sigs, np.int32),
        n_chunks=((counts + k - 1) // k).astype(np.int32),
        kinds=kinds,
        counts=counts,
        chunk_k=k,
    )


def lane_max_span(result: EncodeResult, m: int) -> int:
    """Widest chunk span in bits for lane ``m`` (resident-pool window
    sizing) — matches snapshot_stream's post-hoc ``span``: offset deltas
    with the final chunk extending to the padded stream end
    (``nbytes * 8``, EOS and byte padding included)."""
    nc = int(result.n_chunks[m])
    if nc == 0:
        return 0
    offs = result.chunk_offs[:nc, m]
    ends = np.concatenate(
        [offs[1:], np.asarray([int(result.nbytes[m]) * 8], np.int64)]
    )
    return int((ends - offs).max())


def side_rows_for(
    result: EncodeResult, lanes: list, block_start: int
) -> list:
    """Packed 10-word side rows per lane, bit-identical to
    ``pack_side_rows(snapshot_stream(stream))`` for every device-encoded
    lane (None where a chunk overflows the packed ranges — that lane
    admits without side planes and decodes streamed)."""
    from .sideplane import pack_side_rows_vec

    k = result.chunk_k
    out = []
    for m, (t, v) in enumerate(lanes):
        t = np.asarray(t, np.int64)
        v = np.asarray(v, np.float64)
        n = int(result.counts[m])
        nc = int(result.n_chunks[m])
        ci = np.arange(nc)
        j = ci * k  # records consumed before each chunk
        off = result.chunk_offs[:nc, m]
        prev_time = np.where(j > 0, t[np.maximum(j - 1, 0)], 0).astype(np.uint64)
        pd = np.zeros(nc, np.uint64)
        ge2 = j >= 2
        pd[ge2] = (t[j[ge2] - 1] - t[j[ge2] - 2]).astype(np.uint64)
        full = (j + k) <= n
        if result.kinds[m] == KIND_INT:
            iv = v.astype(np.int64)
            int_val = np.where(j > 0, iv[np.maximum(j - 1, 0)], 0).astype(np.uint64)
            sig = result.chunk_sigs[:nc, m]
            rows = pack_side_rows_vec(
                off, prev_time, pd, np.ones(nc, np.uint64),
                np.zeros(nc, np.uint64), np.zeros(nc, np.uint64), int_val,
                sig, np.zeros(nc, np.uint64), np.zeros(nc, bool),
                full, np.zeros(nc, bool), block_start,
            )
        else:
            vb = v.view(np.uint64)
            pfb = np.zeros(nc, np.uint64)
            pxr = np.zeros(nc, np.uint64)
            if n > 1 or nc > 0:
                src = np.concatenate([vb[:1], vb[1:] ^ vb[:-1]])
                updated = np.concatenate([[True], vb[1:] != vb[:-1]])
                last = np.maximum.accumulate(np.where(updated, np.arange(n), 0))
                px_after = src[last]
                gt0 = j > 0
                pfb[gt0] = vb[j[gt0] - 1]
                pxr[gt0] = px_after[j[gt0] - 1]
            # chunk 0's snapshot predates the first record: is_float is
            # still False and fast_float needs float mode AT chunk start
            rows = pack_side_rows_vec(
                off, prev_time, pd, np.ones(nc, np.uint64),
                pfb, pxr, np.zeros(nc, np.uint64),
                np.zeros(nc, np.uint64), np.zeros(nc, np.uint64), j > 0,
                np.zeros(nc, bool), full & (ci > 0), block_start,
            )
        out.append(rows)
    return out


def encode_block(points: list, block_start: int, k: int = CHUNK_K_DEFAULT,
                 round_words_to: int = 1):
    """Convenience seal-path entry: classify + encode + side rows.

    ``points`` is a list of per-lane ``(times, values, units)`` triples.
    Returns ``(kinds int8[L], result EncodeResult | None, lane_index
    int32[L], side_rows list)`` where ``lane_index[i]`` is the row of
    lane i in the encode batch, or -1 for host-fallback lanes."""
    kinds = np.zeros(len(points), np.int8)
    for i, (t, v, u) in enumerate(points):
        kinds[i] = classify_lane(t, v, u).kind
    lane_index = np.full(len(points), -1, np.int32)
    eligible = [i for i in range(len(points)) if kinds[i] != KIND_NONE]
    lane_index[eligible] = np.arange(len(eligible), dtype=np.int32)
    lanes = [(points[i][0], points[i][1]) for i in eligible]
    result = encode_lanes(
        lanes, kinds[eligible], k=k, round_words_to=round_words_to
    )
    side = side_rows_for(result, lanes, block_start) if result is not None else []
    return kinds, result, lane_index, side
