"""Batched M3TSZ decode as a JAX program (jit/TPU-compatible).

The CPU iterator (/root/reference/src/dbnode/encoding/m3tsz/iterator.go) is a
sequential bit-stream walk; the TPU design parallelizes ACROSS series and
scans WITHIN each series (SURVEY.md §2.5, §7): one `lax.scan` step decodes one
datapoint record for every series simultaneously. All control flow is
branchless — every possible record interpretation is computed from a fetched
bit window and the right one selected — because XLA traces a single static
program.

64-bit quantities (timestamps, float64 bit patterns) are (hi, lo) uint32
pairs via ops.u64 since TPUs have no native 64-bit integers.

Device-decode contract (vs the CPU reference decoder):
- bit-exact timestamps and value *state* (float bits / int value + multiplier)
  surfaced as integer pairs; `finalize_decode` reconstructs bit-exact float64
  values on host.
- annotations are not supported on device (streams carrying them set the
  per-series `err` flag); the host ReaderIterator handles those.
- time units second/ms/us/ns are supported, including mid-stream time-unit
  change markers.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.xtime import Unit
from . import u64

U32 = jnp.uint32
I32 = jnp.int32

# Marker scheme constants (encoding/scheme.go:28-38).
_MARKER_OPCODE = 0x100
_MARKER_BITS = 11
_EOS = 0
_ANNOTATION = 1
_TIME_UNIT = 2

# Unit code -> nanos multiplier; only s/ms/us/ns decodable on device.
# Selected with where-chains (not jnp.take): per-lane gathers from tiny
# tables don't lower well on TPU/Pallas, selects do.
_UNIT_NANOS = np.zeros(9, dtype=np.uint32)
_UNIT_NANOS[Unit.SECOND] = 1_000_000_000
_UNIT_NANOS[Unit.MILLISECOND] = 1_000_000
_UNIT_NANOS[Unit.MICROSECOND] = 1_000
_UNIT_NANOS[Unit.NANOSECOND] = 1


def _unit_nanos(unit):
    out = jnp.zeros_like(unit).astype(U32)
    for code in (Unit.SECOND, Unit.MILLISECOND, Unit.MICROSECOND, Unit.NANOSECOND):
        out = jnp.where(unit == int(code), U32(_UNIT_NANOS[code]), out)
    return out


def _unit_default_bits(unit):
    # Default dod bucket width: 32 bits for s/ms, 64 for us/ns (scheme.go:47-52).
    is32 = (unit == int(Unit.SECOND)) | (unit == int(Unit.MILLISECOND))
    return jnp.where(is32, I32(32), I32(64))


class DecodeState(NamedTuple):
    pos: jnp.ndarray  # int32[S] bit cursor
    done: jnp.ndarray  # bool[S]
    err: jnp.ndarray  # bool[S]
    prev_time: tuple  # u64[S] unix nanos
    prev_delta: tuple  # u64[S] signed nanos
    time_unit: jnp.ndarray  # int32[S]
    prev_float_bits: tuple  # u64[S]
    prev_xor: tuple  # u64[S]
    int_val: tuple  # u64[S] signed current int value
    mult: jnp.ndarray  # int32[S]
    sig: jnp.ndarray  # int32[S]
    is_float: jnp.ndarray  # bool[S]


class DecodeResult(NamedTuple):
    """[S, T] outputs; see finalize_decode for host-side value reconstruction."""

    ts_hi: jnp.ndarray
    ts_lo: jnp.ndarray
    val_hi: jnp.ndarray  # float64 bits OR signed int64 value, per point_is_float
    val_lo: jnp.ndarray
    point_is_float: jnp.ndarray  # bool[S, T]
    mult: jnp.ndarray  # int32[S, T] decimal multiplier exponent for int points
    valid: jnp.ndarray  # bool[S, T]
    err: jnp.ndarray  # bool[S] series hit a decode error / unsupported feature
    values_f32: jnp.ndarray  # float32[S, T] approximate values for aggregation


def _pick4(ws, k):
    """Select ws[k], ws[k+1], ws[k+2] from a 4-word window, 0 beyond."""
    zero = jnp.zeros_like(ws[0])
    opts = list(ws) + [zero, zero, zero]

    def pick(i):
        # i is a traced int32 vector in 0..5
        r = zero
        for j in range(6):
            r = jnp.where(i == j, opts[j], r)
        return r

    return pick(k), pick(k + 1), pick(k + 2)


def _extract(ws, start, n):
    """Read ``n`` (<=64) bits at bit offset ``start`` within a 4-word window.

    Valid as long as start + n <= 97 (4 words minus the <=31-bit base shift).
    Returns a u64 pair holding the bits right-aligned.

    ``start``/``n`` may be Python ints: record formats put most fields at
    compile-time-constant offsets, and a static start turns the word pick +
    alignment into plain shifts (the dynamic path costs ~20 vector selects).
    """
    if isinstance(start, (int, np.integer)):
        start = int(start)
        k, r = start >> 5, start & 31
        zero = jnp.zeros_like(ws[0])
        opts = list(ws) + [zero, zero, zero]
        w0, w1, w2 = opts[k], opts[k + 1], opts[k + 2]
        if r == 0:
            hi, lo = w0, w1
        else:
            hi = (w0 << U32(r)) | (w1 >> U32(32 - r))
            lo = (w1 << U32(r)) | (w2 >> U32(32 - r))
    else:
        start = jnp.asarray(start, I32)
        k = start >> 5
        r = (start & 31).astype(U32)
        w0, w1, w2 = _pick4(ws, k)
        nz = r != 0
        hi = (w0 << r) | jnp.where(nz, w1 >> (U32(32) - r), U32(0))
        lo = (w1 << r) | jnp.where(nz, w2 >> (U32(32) - r), U32(0))
    if isinstance(n, (int, np.integer)):
        return u64.shr((hi, lo), 64 - int(n))
    return u64.shr((hi, lo), jnp.asarray(64, I32) - jnp.asarray(n, I32))


def _extract32(ws, start, n):
    """As _extract but returns the low word (n <= 32)."""
    return _extract(ws, start, n)[1]


def _fetch4(words, pos):
    """Gather 4 consecutive words starting at pos//32 for each series."""
    widx = jnp.clip(pos >> 5, 0, words.shape[1] - 1)
    base = words.shape[1] - 1

    def take(off):
        idx = jnp.clip(widx + off, 0, base)
        return jnp.take_along_axis(words, idx[:, None], axis=1)[:, 0]

    ws = (take(0), take(1), take(2), take(3))
    # Align to the in-word bit offset so extracts are relative to `pos`.
    r = (pos & 31).astype(U32)
    nz = r != 0
    inv = U32(32) - r

    def sh(a, b):
        return (a << r) | jnp.where(nz, b >> inv, U32(0))

    return (sh(ws[0], ws[1]), sh(ws[1], ws[2]), sh(ws[2], ws[3]), ws[3] << r)


def _decode_timestamp(fetch4, num_bits, state, first, nt=None):
    """One timestamp record for all series. Returns (state', became_done).

    ``nt`` is the (hi, lo) 64-bit first timestamp; callers hoist its fetch
    out of the scan (it is only consumed on the first record, at pos 0)."""
    pos = state.pos
    if nt is None:
        ws0 = fetch4(pos)
        nt = _extract(ws0, 0, 64)
    pos = jnp.where(first, pos + 64, pos)
    prev_time = u64.select(first, nt, state.prev_time)

    ws = fetch4(pos)
    # --- marker peek (11 bits; zero padding can never look like a marker) ---
    in_range = (pos + _MARKER_BITS) <= num_bits
    peek = _extract32(ws, 0, _MARKER_BITS)
    is_marker = in_range & (peek >> 2 == _MARKER_OPCODE)
    marker_val = (peek & 3).astype(I32)
    eos = is_marker & (marker_val == _EOS)
    ann = is_marker & (marker_val == _ANNOTATION)
    tu_marker = is_marker & (marker_val == _TIME_UNIT)

    # --- time-unit marker: 8-bit unit byte follows ---
    new_unit = _extract32(ws, _MARKER_BITS, 8).astype(I32)
    tu_supported = (new_unit >= 1) & (new_unit <= 4)
    tu_changed = tu_marker & tu_supported & (new_unit != state.time_unit)
    time_unit = jnp.where(tu_marker & tu_supported, new_unit, state.time_unit)
    # offset of the dod record within the window: 0, or 19 after a marker
    _TU_DOD_OFF = _MARKER_BITS + 8
    dod_off = jnp.where(tu_marker, _TU_DOD_OFF, 0)

    # --- dod decode (fully static extracts; offsets are 0 or 19) ---
    # changed path: raw 64-bit nanos (timestamp_iterator.go:228-238); only
    # consumed when tu_changed, i.e. when the dod sits at the static offset 19
    dod_changed = _extract(ws, _TU_DOD_OFF, 64)

    # bucket path: the 16 head bits cover opcode + the 7/9/12-bit payloads,
    # so the small buckets are static shifts of one selected head word
    head16 = jnp.where(
        tu_marker,
        _extract32(ws, _TU_DOD_OFF, 16),
        _extract32(ws, 0, 16),
    )
    b0 = (head16 >> 15) & 1
    b1 = (head16 >> 14) & 1
    b2 = (head16 >> 13) & 1
    b3 = (head16 >> 12) & 1
    zero_dod = b0 == 0
    sel7 = (b0 == 1) & (b1 == 0)
    sel9 = (b0 == 1) & (b1 == 1) & (b2 == 0)
    sel12 = (b0 == 1) & (b1 == 1) & (b2 == 1) & (b3 == 0)
    default_bits = _unit_default_bits(time_unit)
    nbits = jnp.where(
        sel7, 7, jnp.where(sel9, 9, jnp.where(sel12, 12, default_bits))
    ).astype(I32)
    opbits = jnp.where(sel7, 2, jnp.where(sel9, 3, 4)).astype(I32)
    d7 = (((head16 >> 7) & U32(0x7F)).astype(I32) ^ 0x40) - 0x40
    d9 = (((head16 >> 4) & U32(0x1FF)).astype(I32) ^ 0x100) - 0x100
    d12 = ((head16 & U32(0xFFF)).astype(I32) ^ 0x800) - 0x800
    d_small = jnp.where(sel7, d7, jnp.where(sel9, d9, d12))
    # default bucket: 32-bit (s/ms) or 64-bit (us/ns) payload at dod_off + 4
    raw32 = jnp.where(
        tu_marker,
        _extract32(ws, _TU_DOD_OFF + 4, 32),
        _extract32(ws, 4, 32),
    ).astype(I32)
    raw64 = u64.select(tu_marker, _extract(ws, _TU_DOD_OFF + 4, 64), _extract(ws, 4, 64))
    dod_def = u64.select(default_bits == 32, u64.from_i32(raw32), raw64)
    dod_norm = u64.select(sel7 | sel9 | sel12, u64.from_i32(d_small), dod_def)
    unit_nanos = _unit_nanos(time_unit)
    dod_bucket = u64.mul_u32(dod_norm, unit_nanos)
    bucket_consumed = jnp.where(zero_dod, 1, opbits + nbits)

    dod = u64.select(tu_changed, u64.sign_extend(dod_changed, jnp.full_like(pos, 64)), dod_bucket)
    dod = u64.select(zero_dod & ~tu_changed, u64.const(0, dod[0].shape), dod)
    consumed = dod_off + jnp.where(tu_changed, 64, bucket_consumed)

    unit_ok = (time_unit >= 1) & (time_unit <= 4)
    err_now = (ann | ~unit_ok | (tu_marker & ~tu_supported)) & ~state.done & ~eos

    prev_delta = u64.add(state.prev_delta, dod)
    prev_time = u64.add(prev_time, prev_delta)
    prev_delta = u64.select(tu_changed, u64.const(0, prev_delta[0].shape), prev_delta)

    active = ~state.done & ~state.err & ~eos & ~err_now
    new_pos = jnp.where(active, pos + consumed, state.pos)
    state = state._replace(
        pos=new_pos,
        done=state.done | eos,
        err=state.err | err_now,
        prev_time=u64.select(active, prev_time, state.prev_time),
        prev_delta=u64.select(active, prev_delta, state.prev_delta),
        time_unit=jnp.where(active, time_unit, state.time_unit),
    )
    return state, eos


# ---------------------------------------------------------------------------
# Fast-path record decode: host-classified chunks (ops/chunked.py prescan
# flags) that contain ONLY int-mode value records, NO markers/annotations,
# a constant time unit in {s, ms} (32-bit default dod bucket), full k
# records, and are not the first chunk of their stream. The kernel picks
# this body per tile (ops/fused.py); the general functions above remain the
# semantics oracle.
# ---------------------------------------------------------------------------


def _ts_consumed_fast(ws):
    """Marker-free timestamp record WIDTH: 7/9/12-bit buckets + 32-bit
    default ({s, ms} units by classification).

    The fused kernel emits only per-lane aggregates — timestamp VALUES never
    leave it — so the fast body skips the dod value, the unit multiply, and
    both 64-bit accumulator adds entirely: the record's only effect is how
    many bits it consumed. Returns i32 consumed (4 head bits decide it)."""
    head4 = _extract32(ws, 0, 4)
    b0 = (head4 >> 3) & 1
    b1 = (head4 >> 2) & 1
    b2 = (head4 >> 1) & 1
    zero_dod = b0 == 0
    sel7 = (b0 == 1) & (b1 == 0)
    sel9 = (b0 == 1) & (b1 == 1) & (b2 == 0)
    return jnp.where(
        zero_dod,
        1,
        jnp.where(
            sel7, 9, jnp.where(sel9, 12, jnp.where((head4 & 1) == 0, 16, 36))
        ),
    ).astype(I32)


def _decode_value_fast(fetch4, state):
    """Int-mode-only value record: repeat / stay-int / update-int.

    Fast chunks are additionally classified int32-safe (sig <= 31 and
    int_val within int32 for every record — snapshot_stream), so the whole
    value path runs in single-word 32-bit arithmetic: ``state.int_val`` here
    is an i32 vector, the sig-bit diff is one aligned word read, and the
    update is a plain i32 add."""
    pos = state.pos
    ws = fetch4(pos)
    head2 = _extract32(ws, 0, 2)
    b0 = (head2 >> 1) & 1
    b1 = head2 & 1
    repeat = (b0 == 0) & (b1 == 1)
    to_int = (b0 == 0) & (b1 == 0)  # update, not repeat; float excluded

    hdr12 = _extract32(ws, 3, 12)
    h_sig, h_mult, h_consumed, _ = _read_int_header12(hdr12, state.sig, state.mult)
    diff_off = jnp.where(to_int, 3 + h_consumed, 1)  # < 32 always
    diff_sig = jnp.where(to_int, h_sig, state.sig)
    # sign + <=31-bit diff from two words (diff_off in [1, 17] so the word
    # shift amounts are always in range and never zero)
    r = diff_off.astype(U32)
    hi32 = (ws[0] << r) | (ws[1] >> (U32(32) - r))
    bit32 = (ws[1] << r) >> 31  # window bit diff_off + 32
    sign_bit = hi32 >> 31
    body = (hi32 << 1) | bit32  # bits [diff_off+1, diff_off+33)
    n = diff_sig.astype(U32)
    diff = jnp.where(
        n == 0, U32(0), body >> (U32(32) - jnp.where(n == 0, U32(1), n))
    )
    diff_i = diff.astype(I32)
    delta = jnp.where(sign_bit == 1, diff_i, -diff_i)
    d_int_val = state.int_val + delta

    new_int_val = jnp.where(repeat, state.int_val, d_int_val)
    new_sig = jnp.where(to_int, h_sig, state.sig)
    new_mult = jnp.where(to_int, h_mult, state.mult)
    consumed = jnp.where(
        repeat,
        2,
        jnp.where(to_int, 3 + h_consumed + 1 + h_sig, 2 + state.sig),
    ).astype(I32)
    return state._replace(
        pos=pos + consumed,
        int_val=new_int_val,
        sig=new_sig,
        mult=new_mult,
    )


def _read_int_header12(hb, sig, mult):
    """sig/mult update header (iterator.go readIntSigMult) decoded from its
    12 head bits ``hb`` (the header never exceeds 12 bits: sig part <= 8,
    mult part <= 4), so every field is a static shift of one word. Bit 11 of
    ``hb`` is the first header bit. Returns (sig', mult', consumed, invalid)."""
    upd = ((hb >> 11) & 1) == 1
    zero_sig = ((hb >> 10) & 1) == 0  # OpcodeZeroSig == 0x0
    sig_m1 = ((hb >> 4) & U32(0x3F)).astype(I32)
    new_sig = jnp.where(upd, jnp.where(zero_sig, 0, sig_m1 + 1), sig)
    sig_consumed = jnp.where(upd, jnp.where(zero_sig, 2, 8), 1)

    # mult header at sig_consumed in {1, 2, 8}: static shifts, value select
    is1 = ~upd
    is2 = upd & zero_sig
    b_mult_upd = jnp.where(is1, (hb >> 10) & 1, jnp.where(is2, (hb >> 9) & 1, (hb >> 3) & 1))
    mult_v = jnp.where(
        is1,
        ((hb >> 7) & U32(7)).astype(I32),
        jnp.where(is2, ((hb >> 6) & U32(7)).astype(I32), (hb & U32(7)).astype(I32)),
    )
    mupd = b_mult_upd == 1
    new_mult = jnp.where(mupd, mult_v, mult)
    consumed = sig_consumed + jnp.where(mupd, 4, 1)
    mult_invalid = mupd & (mult_v > 6)
    return new_sig, new_mult, consumed, mult_invalid


def _read_int_diff(ws, off, sig, int_val):
    """Sign + sig-bit diff (iterator.go readIntValDiff). ``off`` may be a
    Python int (static extracts) or traced. Returns (int_val', consumed)."""
    sign_bit = _extract32(ws, off, 1)
    diff = _extract(ws, off + 1, sig)
    # opcodeNegative(1) means "add |diff|" (see iterator.go:162-169 semantics).
    delta = u64.select(sign_bit == 1, diff, u64.neg(diff))
    return u64.add(int_val, delta), 1 + sig


def _read_xor(ws, off: int, prev_float_bits, prev_xor):
    """XOR float record (float_encoder_iterator.go:117-166). ``off`` is the
    record-format constant (Python int) so all starts are static.

    Returns (prev_float_bits', prev_xor', consumed)."""
    c0 = _extract32(ws, off, 1)
    c1 = _extract32(ws, off + 1, 1)
    zero_path = c0 == 0
    contained = (c0 == 1) & (c1 == 0)

    # contained: reuse prev leading/trailing window
    prev_nonzero = ~u64.is_zero(prev_xor)
    prev_lead = jnp.where(prev_nonzero, u64.clz(prev_xor), 64)
    prev_trail = jnp.where(prev_nonzero, u64.ctz(prev_xor), 0)
    nm_c = jnp.clip(64 - prev_lead - prev_trail, 0, 64)
    bits_c = _extract(ws, off + 2, nm_c)
    xor_c = u64.shl(bits_c, prev_trail)
    consumed_c = 2 + nm_c

    # uncontained: 6-bit lead, 6-bit (nm-1), nm bits
    lead_u = _extract32(ws, off + 2, 6).astype(I32)
    nm_u = _extract32(ws, off + 8, 6).astype(I32) + 1
    bits_u = _extract(ws, off + 14, nm_u)
    trail_u = jnp.clip(64 - lead_u - nm_u, 0, 64)
    xor_u = u64.shl(bits_u, trail_u)
    consumed_u = 14 + nm_u

    xor = u64.select(contained, xor_c, xor_u)
    xor = u64.select(zero_path, u64.const(0, xor[0].shape), xor)
    consumed = jnp.where(zero_path, 1, jnp.where(contained, consumed_c, consumed_u))
    new_bits = u64.bxor(prev_float_bits, xor)
    return new_bits, xor, consumed


def _decode_value(fetch4, state, first, int_optimized: bool):
    """One value record for all series (iterator.go readFirstValue/readNextValue)."""
    pos = state.pos
    ws = fetch4(pos)

    if not int_optimized:
        full = _extract(ws, 0, 64)
        nb, nx, consumed = _read_xor(ws, 0, state.prev_float_bits, state.prev_xor)
        new_bits = u64.select(first, full, nb)
        new_xor = u64.select(first, full, nx)
        consumed = jnp.where(first, 64, consumed)
        active = ~state.done & ~state.err
        return state._replace(
            pos=jnp.where(active, pos + consumed, state.pos),
            prev_float_bits=u64.select(active, new_bits, state.prev_float_bits),
            prev_xor=u64.select(active, new_xor, state.prev_xor),
            is_float=jnp.ones_like(state.is_float),
        )

    # ---- int-optimized scheme ----
    # FIRST record: mode bit, then full float or int header+diff.
    head3 = _extract32(ws, 0, 3)  # first 3 bits cover every mode peek below
    f_mode = (head3 >> 2) & 1  # 1 = float (opcodeFloatMode)
    first_is_float = f_mode == 1

    # NEXT record opcodes.
    b0 = (head3 >> 2) & 1  # 0 = update, 1 = no update
    b1 = (head3 >> 1) & 1  # update: 1 = repeat
    b2 = head3 & 1  # update+norepeat: 1 = float mode
    upd = b0 == 0
    repeat = upd & (b1 == 1)
    to_float = upd & ~repeat & (b2 == 1)
    to_int = upd & ~repeat & (b2 == 0)
    stay = ~upd

    sel_first_float = first & first_is_float
    sel_first_int = first & ~first_is_float
    sel_to_float = ~first & to_float
    sel_to_int = ~first & to_int
    sel_stay_float = ~first & stay & state.is_float
    sel_stay_int = ~first & stay & ~state.is_float
    sel_repeat = ~first & repeat

    # A record consumes AT MOST ONE of each sub-record kind, at an offset
    # determined by its selector — so each kind is read once at a selected
    # offset instead of once per path:
    #   full float: at 1 (first) or 3 (update->float)
    #   int header: at 1 (first) or 3 (update->int); <=12 bits, static shifts
    #   int diff:   after the header (first/update->int) or at 1 (stay-int)
    #   xor:        at 1 (stay-float)
    full = u64.select(first, _extract(ws, 1, 64), _extract(ws, 3, 64))
    takes_header = sel_first_int | sel_to_int
    hdr12 = jnp.where(first, _extract32(ws, 1, 12), _extract32(ws, 3, 12))
    h_sig, h_mult, h_consumed, h_mult_bad = _read_int_header12(hdr12, state.sig, state.mult)
    diff_off = jnp.where(
        first, 1 + h_consumed, jnp.where(to_int, 3 + h_consumed, 1)
    )
    diff_sig = jnp.where(takes_header, h_sig, state.sig)
    diff_base = u64.select(first, u64.const(0, pos.shape), state.int_val)
    d_int_val, d_consumed = _read_int_diff(ws, diff_off, diff_sig, diff_base)
    x_bits, x_xor, x_consumed = _read_xor(ws, 1, state.prev_float_bits, state.prev_xor)

    first_consumed = jnp.where(first_is_float, 65, 1 + h_consumed + d_consumed)
    next_consumed = jnp.where(
        repeat,
        2,
        jnp.where(
            to_float,
            3 + 64,
            jnp.where(
                to_int,
                3 + h_consumed + d_consumed,
                jnp.where(state.is_float, 1 + x_consumed, 1 + d_consumed),
            ),
        ),
    )

    # ---- merge first/next ----
    consumed = jnp.where(first, first_consumed, next_consumed)

    # Boolean algebra, not jnp.where(pred, True/False, ...): bool splat
    # constants lower to i8 vectors Mosaic can't truncate back to i1.
    new_is_float = (sel_first_float | sel_to_float) | (
        ~(sel_first_int | sel_to_int) & state.is_float
    )

    # float bits: full float on first/to_float; XOR result when staying float.
    takes_full = sel_first_float | sel_to_float
    new_float_bits = u64.select(takes_full, full, state.prev_float_bits)
    new_float_bits = u64.select(sel_stay_float, x_bits, new_float_bits)
    new_xor = u64.select(takes_full, full, state.prev_xor)
    new_xor = u64.select(sel_stay_float, x_xor, new_xor)

    takes_diff = sel_first_int | sel_to_int | sel_stay_int
    new_int_val = u64.select(takes_diff, d_int_val, state.int_val)

    new_sig = jnp.where(takes_header, h_sig, state.sig)
    new_mult = jnp.where(takes_header, h_mult, state.mult)
    err_now = takes_header & h_mult_bad

    active = ~state.done & ~state.err & ~err_now
    return state._replace(
        pos=jnp.where(active, pos + consumed, state.pos),
        err=state.err | (err_now & ~state.done),
        prev_float_bits=u64.select(active, new_float_bits, state.prev_float_bits),
        prev_xor=u64.select(active, new_xor, state.prev_xor),
        int_val=u64.select(active, new_int_val, state.int_val),
        sig=jnp.where(active, new_sig, state.sig),
        mult=jnp.where(active, new_mult, state.mult),
        # Boolean algebra, not jnp.where: select_n with i1 *operands* lowers
        # through an i8 vector Mosaic cannot truncate back to i1.
        is_float=(active & new_is_float) | (~active & state.is_float),
    )


@functools.partial(jax.jit, static_argnames=("max_points", "int_optimized"))
def decode_batched(
    words,
    num_bits,
    initial_unit,
    max_points: int,
    int_optimized: bool = True,
) -> DecodeResult:
    """Decode up to ``max_points`` datapoints from every series' stream.

    Args:
      words: uint32[S, W] big-endian-packed streams (BatchedSegments.words).
      num_bits: int32[S] valid bits per stream.
      initial_unit: int32[S] initial time unit codes (BatchedSegments helper;
        mirrors initialTimeUnit nt-divisibility in timestamp_iterator.go:115-134).
      max_points: static scan length T.
    """
    words = jnp.asarray(words, U32)
    num_bits = jnp.asarray(num_bits, I32)
    initial_unit = jnp.asarray(initial_unit, I32)
    s = words.shape[0]
    fetch4 = functools.partial(_fetch4, words)
    zero_pair = u64.const(0, (s,))

    zero_pos = jnp.zeros((s,), I32)
    nt0 = _extract(fetch4(zero_pos), 0, 64)
    state = DecodeState(
        pos=zero_pos,
        done=num_bits <= 0,
        err=jnp.zeros((s,), bool),
        prev_time=zero_pair,
        prev_delta=zero_pair,
        time_unit=initial_unit,
        prev_float_bits=zero_pair,
        prev_xor=zero_pair,
        int_val=zero_pair,
        mult=jnp.zeros((s,), I32),
        sig=jnp.zeros((s,), I32),
        is_float=jnp.zeros((s,), bool),
    )

    def step(state, idx):
        first = idx == 0
        was_active = ~state.done & ~state.err
        first_vec = jnp.full((s,), False) | first
        state, _ = _decode_timestamp(fetch4, num_bits, state, first_vec, nt=nt0)
        ts_active = ~state.done & ~state.err
        state = _decode_value(fetch4, state, first_vec, int_optimized)
        now_active = ~state.done & ~state.err
        valid = was_active & ts_active & now_active

        point_is_float = jnp.logical_or(not int_optimized, state.is_float)
        val = u64.select(point_is_float, state.prev_float_bits, state.int_val)
        out = (
            state.prev_time[0],
            state.prev_time[1],
            val[0],
            val[1],
            point_is_float,
            state.mult,
            valid,
        )
        return state, out

    final_state, outs = jax.lax.scan(step, state, jnp.arange(max_points))
    ts_hi, ts_lo, val_hi, val_lo, pif, mult, valid = outs
    # scan stacks on axis 0 ([T, S]); transpose to [S, T].
    tr = lambda x: jnp.swapaxes(x, 0, 1)
    val_pair = (tr(val_hi), tr(val_lo))
    values_f32 = jnp.where(
        tr(pif),
        u64.f64_bits_to_f32(val_pair),
        _int_val_to_f32(val_pair, tr(mult)),
    )
    return DecodeResult(
        ts_hi=tr(ts_hi),
        ts_lo=tr(ts_lo),
        val_hi=val_pair[0],
        val_lo=val_pair[1],
        point_is_float=tr(pif),
        mult=tr(mult),
        valid=tr(valid),
        err=final_state.err,
        values_f32=jnp.where(tr(valid), values_f32, jnp.float32(jnp.nan)),
    )


def _mult_reciprocal(mult, like):
    """10^-mult as a correctly-rounded f32 select chain (mult in [0, 6])."""
    rcp = jnp.full_like(like, 1.0)
    for m, s in enumerate((1.0, 0.1, 0.01, 1e-3, 1e-4, 1e-5, 1e-6)):
        if m:
            rcp = jnp.where(mult == m, jnp.float32(s), rcp)
    return rcp


def _int32_val_to_f32(iv, mult):
    """Fast-path conversion: int32-safe int_val -> f32 * 10^-mult."""
    v = iv.astype(jnp.float32)
    return v * _mult_reciprocal(mult, v)


def _int_val_to_f32(pair, mult):
    """Approximate int-mode value for f32 aggregation: int_val * 10^-mult.

    Multiply-by-reciprocal, not divide: a VPU divide costs an order of
    magnitude more than a multiply and this runs once per record per lane in
    the fused kernel. The reciprocal constants are correctly rounded f32, so
    the result differs from a true divide by <= 1 ulp — inside the
    documented approximation of the f32 aggregation path (bit-exact values
    travel as (hi, lo) pairs)."""
    v = u64.to_f32(pair)
    return v * _mult_reciprocal(mult, v)


def finalize_decode(res: DecodeResult):
    """Host-side bit-exact reconstruction: int64 nanos + float64 values.

    Integer-mode points become int_val / 10^mult in float64 — identical
    arithmetic to the CPU iterator's convertFromIntFloat (m3tsz.go:120-126),
    so results match the reference decoder bit for bit.
    """
    ts_hi = np.asarray(res.ts_hi, np.uint64)
    ts_lo = np.asarray(res.ts_lo, np.uint64)
    timestamps = ((ts_hi << np.uint64(32)) | ts_lo).astype(np.int64)

    val_hi = np.asarray(res.val_hi, np.uint64)
    val_lo = np.asarray(res.val_lo, np.uint64)
    raw = (val_hi << np.uint64(32)) | val_lo
    float_vals = raw.view(np.float64)

    int_vals = raw.astype(np.int64).astype(np.float64)
    scale = np.power(10.0, np.asarray(res.mult, np.int64))
    int_vals = int_vals / scale

    pif = np.asarray(res.point_is_float, bool)
    values = np.where(pif, float_vals, int_vals)
    valid = np.asarray(res.valid, bool)
    return timestamps, values, valid
