"""Packed per-chunk side-plane layout: 10 uint32 words per chunk.

One chunk's decoder-state snapshot (ops/chunked.snapshot_stream) used to
occupy 16 one-field-per-word uint32 planes in the resident pool's side
buffer. The five 64-bit carries need their 10 words, but everything else
is small: the bit offset fits 21 bits, ``prev_time`` is block-relative
(a chunk's carry timestamp lies inside its block, so 44 bits of
block-relative nanos cover any block up to ~4.8h), ``prev_delta`` is an
inter-sample gap (45 bits ≈ 9.7h), and the mode/unit/classification
fields fit a byte and change between them. Packing those into two words
cuts the side-plane HBM footprint 37.5% at constant information — the
ROADMAP item 1 residual — and the same layout rides the fileset ``side``
file (v3) so admission stages rows without re-walking streams.

Layout (word index -> contents, bit ranges high:low):

====  =======================================================
w0-1  ``prev_float_bits`` hi, lo
w2-3  ``prev_xor`` hi, lo
w4-5  ``int_val`` hi, lo
w6    ``rel_prev_time`` bits 31:0  (prev_time - block_start)
w7    ``prev_delta`` bits 31:0
w8    ``off``[31:11] | ``time_unit``[10:8] | ``sig``[7:2] | ``flags``[1:0]
w9    ``rel_prev_time`` bits 43:32 [31:20] | ``prev_delta`` bits
      44:32 [19:7] | ``pt_zero``[6] | ``mult``[5:1] | ``is_float``[0]
====  =======================================================

``pt_zero`` disambiguates the first chunk's pristine carry
(``prev_time == 0``, which block-relative storage cannot express) from a
sample exactly at block start. ``flags`` keeps the v2 fast-chunk
classification bits (1 = int-fast, 2 = float-fast).

A snapshot any field of which overflows the packed ranges cannot be
represented — :func:`pack_side_rows` returns ``None`` and the caller
degrades that lane to the streamed decode path (admission counts it).
The ranges hold for every stream the encoder emits at default settings;
overflow needs a pathological block size or sample gap.

All-zero rows (the reserved zero side page, padding lanes) unpack to the
all-zero decoder state the streamed packer uses for padding lanes, so
zero-page indirection keeps meaning "empty lane".
"""

from __future__ import annotations

import numpy as np

SIDE_WORDS = 10

# packed field capacities (exclusive upper bounds)
OFF_BITS = 21
RT_BITS = 44  # block-relative prev_time
PD_BITS = 45  # prev_delta
TU_BITS, SIG_BITS, MULT_BITS = 3, 6, 5

_M64 = (1 << 64) - 1
_M32 = 0xFFFFFFFF


def pack_side_row(p: dict, block_start: int):
    """One snapshot dict -> tuple of 10 uint32 words, or None when any
    field overflows the packed ranges (the lane then has no side planes
    and decodes streamed)."""
    off = int(p["off"])
    tu = int(p["time_unit"])
    sig = int(p["sig"])
    mult = int(p["mult"])
    pt = int(p["prev_time"]) & _M64
    pd = int(p["prev_delta"]) & _M64
    if (
        off >= 1 << OFF_BITS
        or tu >= 1 << TU_BITS
        or sig >= 1 << SIG_BITS
        or mult >= 1 << MULT_BITS
        or pd >= 1 << PD_BITS
    ):
        return None
    if pt == 0:
        rel, ptz = 0, 1
    else:
        rel = pt - (int(block_start) & _M64)
        ptz = 0
        if rel < 0 or rel >= 1 << RT_BITS:
            return None
    pfb = int(p["prev_float_bits"]) & _M64
    pxr = int(p["prev_xor"]) & _M64
    iv = int(p["int_val"]) & _M64
    flags = (1 if p.get("fast") else 0) | (2 if p.get("fast_float") else 0)
    w8 = (off << 11) | (tu << 8) | (sig << 2) | flags
    w9 = (
        ((rel >> 32) << 20)
        | ((pd >> 32) << 7)
        | (ptz << 6)
        | (mult << 1)
        | int(bool(p["is_float"]))
    )
    return (
        pfb >> 32, pfb & _M32,
        pxr >> 32, pxr & _M32,
        iv >> 32, iv & _M32,
        rel & _M32,
        pd & _M32,
        w8, w9,
    )


def pack_side_rows(snaps: list, block_start: int) -> np.ndarray | None:
    """Snapshot dicts -> uint32[n_chunks, SIDE_WORDS], or None when ANY
    chunk overflows (side planes are all-or-nothing per lane: a partial
    side table cannot seed the chunk-parallel decode)."""
    rows = np.zeros((len(snaps), SIDE_WORDS), np.uint32)
    for j, p in enumerate(snaps):
        packed = pack_side_row(p, block_start)
        if packed is None:
            return None
        rows[j] = packed
    return rows


def pack_side_rows_vec(
    off,
    prev_time,
    prev_delta,
    time_unit,
    prev_float_bits,
    prev_xor,
    int_val,
    sig,
    mult,
    is_float,
    fast,
    fast_float,
    block_start: int,
) -> np.ndarray | None:
    """Vectorized :func:`pack_side_rows`: per-chunk field ARRAYS (one
    element per chunk, 64-bit fields as uint64) -> uint32[n_chunks,
    SIDE_WORDS], or None when any chunk overflows the packed ranges —
    bit-identical to the dict packer for every row it accepts. This is
    the device-encode seal path's packer (ops/encode.py): the encode
    kernel hands back columnar snapshot state, so packing stays one
    round of numpy ops instead of a per-chunk dict walk."""
    off = np.asarray(off, np.uint64)
    pt = np.asarray(prev_time, np.uint64)
    pd = np.asarray(prev_delta, np.uint64)
    tu = np.asarray(time_unit, np.uint64)
    sig = np.asarray(sig, np.uint64)
    mult = np.asarray(mult, np.uint64)
    pfb = np.asarray(prev_float_bits, np.uint64)
    pxr = np.asarray(prev_xor, np.uint64)
    iv = np.asarray(int_val, np.uint64)
    if (
        (off >= 1 << OFF_BITS).any()
        or (tu >= 1 << TU_BITS).any()
        or (sig >= 1 << SIG_BITS).any()
        or (mult >= 1 << MULT_BITS).any()
        or (pd >= 1 << PD_BITS).any()
    ):
        return None
    ptz = pt == 0
    # uint64 wraparound turns a prev_time below block_start into a huge
    # rel, caught by the same range check as the dict packer's rel < 0
    rel = np.where(ptz, np.uint64(0), pt - np.uint64(int(block_start) & _M64))
    if (rel >= 1 << RT_BITS).any():
        return None
    flags = np.where(np.asarray(fast, bool), np.uint64(1), np.uint64(0)) | np.where(
        np.asarray(fast_float, bool), np.uint64(2), np.uint64(0)
    )
    w8 = (off << np.uint64(11)) | (tu << np.uint64(8)) | (sig << np.uint64(2)) | flags
    w9 = (
        ((rel >> np.uint64(32)) << np.uint64(20))
        | ((pd >> np.uint64(32)) << np.uint64(7))
        | (np.where(ptz, np.uint64(1), np.uint64(0)) << np.uint64(6))
        | (mult << np.uint64(1))
        | np.where(np.asarray(is_float, bool), np.uint64(1), np.uint64(0))
    )
    rows = np.empty((off.shape[0], SIDE_WORDS), np.uint32)
    s32 = np.uint64(32)
    m32 = np.uint64(_M32)
    for j, col in enumerate(
        (pfb >> s32, pfb & m32, pxr >> s32, pxr & m32, iv >> s32, iv & m32,
         rel & m32, pd & m32, w8, w9)
    ):
        rows[:, j] = col.astype(np.uint32)
    return rows


def unpack_side_rows(rows: np.ndarray, block_start: int) -> list[dict]:
    """Host inverse of :func:`pack_side_rows` (the fileset side-file v3
    read path): packed rows -> snapshot dicts, bit-exact for every row
    the packer accepted. ``span``/``total_bits`` are offset bookkeeping
    the caller adds (storage/fs.side_table)."""
    rows = np.asarray(rows, np.uint64)
    out = []
    for r in rows:
        w8 = int(r[8])
        w9 = int(r[9])
        rel = ((w9 >> 20) << 32) | int(r[6])
        ptz = (w9 >> 6) & 1
        out.append(
            dict(
                off=w8 >> 11,
                prev_time=0 if ptz else (int(block_start) + rel) & _M64,
                prev_delta=(((w9 >> 7) & 0x1FFF) << 32) | int(r[7]),
                prev_float_bits=(int(r[0]) << 32) | int(r[1]),
                prev_xor=(int(r[2]) << 32) | int(r[3]),
                int_val=(int(r[4]) << 32) | int(r[5]),
                time_unit=(w8 >> 8) & 7,
                sig=(w8 >> 2) & 0x3F,
                mult=(w9 >> 1) & 0x1F,
                is_float=bool(w9 & 1),
                fast=bool(w8 & 1),
                fast_float=bool(w8 & 2),
            )
        )
    return out


def unpack_side_planes(side, block, valid):
    """Device-side unpack: packed side rows -> the decoder-state lane
    planes (ops/chunked.LANE_FIELDS names plus ``off``/``flags``).

    ``side`` u32[N, SIDE_WORDS] gathered rows; ``block`` (hi, lo)
    u32[N] per-lane block_start pair; ``valid`` bool[N]. Invalid lanes
    zero every plane — bit-identical to the streamed packer's padding
    lanes (all-zero state), whatever garbage the zero-page gather or the
    block_start base would otherwise contribute.
    """
    import jax.numpy as jnp

    from . import u64

    U32 = jnp.uint32
    z = jnp.zeros_like(side[:, 0])

    def gate(x):
        return jnp.where(valid, x, z.astype(x.dtype))

    w8 = side[:, 8]
    w9 = side[:, 9]
    rel = (w9 >> U32(20), side[:, 6])
    ptz = (w9 >> U32(6)) & U32(1)
    pt = u64.add(rel, (gate(block[0]), gate(block[1])))
    pt = u64.select(ptz != 0, (z, z), pt)
    pd = ((w9 >> U32(7)) & U32(0x1FFF), side[:, 7])
    planes = {
        "off": gate(w8 >> U32(11)),
        "prev_time": (gate(pt[0]), gate(pt[1])),
        "prev_delta": (gate(pd[0]), gate(pd[1])),
        "prev_float_bits": (gate(side[:, 0]), gate(side[:, 1])),
        "prev_xor": (gate(side[:, 2]), gate(side[:, 3])),
        "int_val": (gate(side[:, 4]), gate(side[:, 5])),
        "time_unit": gate((w8 >> U32(8)) & U32(7)),
        "sig": gate((w8 >> U32(2)) & U32(0x3F)),
        "mult": gate((w9 >> U32(1)) & U32(0x1F)),
        "is_float": gate(w9 & U32(1)),
        "flags": gate(w8 & U32(3)),
    }
    return planes
