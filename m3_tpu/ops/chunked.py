"""Chunked M3TSZ decode: side-table-indexed, gather-free device scan.

The TPU redesign of the reference's sequential iterator
(/root/reference/src/dbnode/encoding/m3tsz/iterator.go): streams are split
into chunks of K records, each chunk carrying a ~40-byte snapshot of the
decoder state at its start (SURVEY.md §7 hard part #1 — "host prescan index
of record offsets stored alongside segments at encode time"). Decode then
runs as a K-step `lax.scan` over S×C chunk-lanes:

  - sequential dependence is confined WITHIN a chunk (K steps instead of T);
  - every chunk reads bits from its own small word window, so the per-step
    bit fetch is a narrow [N, CW] take instead of a strided HBM gather over
    the full [S, W] stream matrix;
  - lane parallelism multiplies by C = ceil(T/K), which keeps the VPU busy
    even for few-series batches.

Side tables come from the encoder (it walks the stream anyway) or from a
one-time host prescan for foreign streams; on-device results are bit-identical
to the CPU iterator either way.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..codec.m3tsz import DEFAULT_INT_OPTIMIZATION, ReaderIterator, initial_time_unit
from ..utils.instrument import KernelProfiler
from ..utils.xtime import Unit
from . import u64
from .decode import DecodeResult, DecodeState, _decode_timestamp, _decode_value, _int_val_to_f32

I32 = jnp.int32
U32 = jnp.uint32

# device-tier observability for the chunked decode kernel: first-call
# compile attribution + sampled block_until_ready-bounded dispatch wall
# time (M3_TPU_PROFILE_SAMPLE_RATE) in m3tpu_kernel_dispatch_seconds
# {kernel="chunked_decode"}; eager callers (parallel/scan.py) dispatch
# through this — inside an outer jit trace they must not (wall time there
# measures tracing, and blocking on tracers is impossible)
PROFILER = KernelProfiler("chunked_decode")

# Decoder-state fields stored as (hi, lo) uint32 pairs.
STATE_PAIR_FIELDS = ("prev_time", "prev_delta", "prev_float_bits", "prev_xor", "int_val")
# Every per-lane field of ChunkedBatch, in decode_chunked_lanes order.
LANE_FIELDS = (
    "windows",
    "rel_pos",
    "num_bits",
    "first",
    *STATE_PAIR_FIELDS,
    "time_unit",
    "sig",
    "mult",
    "is_float",
)


def lane_kwargs(batch: "ChunkedBatch", transform=None) -> dict:
    """ChunkedBatch → decode_chunked_lanes kwargs; ``transform`` maps each
    array (applied to both halves of pair fields)."""
    t = transform or (lambda x: x)
    out = {}
    for f in LANE_FIELDS:
        v = getattr(batch, f)
        out[f] = (t(v[0]), t(v[1])) if f in STATE_PAIR_FIELDS else t(v)
    return out


def _split64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    x = x.astype(np.uint64)
    return (x >> np.uint64(32)).astype(np.uint32), (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)


@dataclass
class ChunkedBatch:
    """Flattened [S*C] chunk lanes + per-chunk decoder-state side table."""

    windows: np.ndarray  # uint32[N, CW]
    rel_pos: np.ndarray  # int32[N] bit offset of chunk start within window
    num_bits: np.ndarray  # int32[N] window-relative valid bit bound
    first: np.ndarray  # bool[N] first chunk of its series
    prev_time: tuple  # (hi, lo) uint32[N]
    prev_delta: tuple
    prev_float_bits: tuple
    prev_xor: tuple
    int_val: tuple
    time_unit: np.ndarray  # int32[N]
    sig: np.ndarray
    mult: np.ndarray
    is_float: np.ndarray  # bool[N]
    k: int
    num_series: int
    num_chunks: int  # C per series (uniform, zero-padded)
    # host-classified fast chunks (all-int, marker-free, constant {s,ms}
    # unit, exactly k records — see snapshot_stream); empty padding lanes
    # are fast=True so they never force a mixed tile slow
    fast: np.ndarray = None  # bool[N]
    # float-mode analogue: marker-free XOR/repeat records, float at chunk
    # start and after every record (the float-specialized kernel body)
    fast_float: np.ndarray = None  # bool[N]

    @property
    def num_lanes(self) -> int:
        return self.windows.shape[0]


def snapshot_stream(
    data: bytes,
    k: int,
    int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
    default_unit: Unit = Unit.SECOND,
) -> list[dict]:
    """Host prescan of one stream: decoder-state snapshot every ``k`` records.

    This is the side table our fileset format persists next to each stream
    (persisted by storage/fs.py); the encoder path can also emit it directly
    at flush time since it walks the stream anyway."""
    it = ReaderIterator(data, int_optimized=int_optimized, default_unit=default_unit)
    per: list[dict] = []
    nrec = 0
    total_bits = len(data) * 8
    # fast-chunk classification (device kernel specialization, ops/fused.py):
    # a chunk is fast iff all k records are marker-free int-mode records with
    # a constant {s, ms} time unit; tracked record by record below.
    # fast_float: the float-mode analogue — every record marker-free and
    # float-mode with the chunk ALREADY in float mode at its start, so the
    # device sees only "1"+XOR (OPCODE_NO_UPDATE=1) or "01" repeat
    # (OPCODE_UPDATE=0 + OPCODE_REPEAT=1) records; an int→float transition
    # record carries a full float the float body can't parse — requiring
    # is_float at start AND after every record excludes it.
    chunk_fast = True
    chunk_fast_float = True
    chunk_start_float = False
    chunk_recs = 0

    def snap():
        st = it.stream
        ts = it.ts_iterator
        unit = ts.time_unit
        if nrec == 0 and len(data) >= 8:
            nt = int.from_bytes(data[:8], "big")
            unit = initial_time_unit(nt, default_unit)
        return dict(
            off=st.byte_pos * 8 + st.bit_pos,
            prev_time=ts.prev_time & 0xFFFFFFFFFFFFFFFF,
            prev_delta=ts.prev_time_delta & 0xFFFFFFFFFFFFFFFF,
            time_unit=int(unit),
            prev_float_bits=it.float_iter.prev_float_bits,
            prev_xor=it.float_iter.prev_xor,
            int_val=int(it.int_val) & 0xFFFFFFFFFFFFFFFF,
            sig=it.sig,
            mult=it.mult,
            is_float=it.is_float,
        )

    while True:
        pending = snap() if nrec % k == 0 else None
        if pending is not None and per:
            # the previous chunk just completed all k records: seal its flag
            per[-1]["fast"] = chunk_fast and chunk_recs == k
            per[-1]["fast_float"] = (
                chunk_fast_float and chunk_start_float and chunk_recs == k
            )
        if pending is not None:
            chunk_fast, chunk_recs = True, 0
            chunk_fast_float = True
            chunk_start_float = bool(it.is_float) and int_optimized
        markers_before = it.ts_iterator.num_markers
        if not it.next():
            # no record followed: don't emit an empty trailing chunk
            break
        if pending is not None:
            per.append(pending)
        nrec += 1
        chunk_recs += 1
        marker_seen = it.ts_iterator.num_markers != markers_before
        unit_ok = int(it.ts_iterator.time_unit) in (
            int(Unit.SECOND), int(Unit.MILLISECOND)
        )
        if (
            marker_seen
            or it.is_float
            or not unit_ok
            or not int_optimized
            # int32-safety: the specialized body runs the whole int path in
            # 32-bit (sig <= 31, value in i32 range after every record; the
            # chunk's starting value is the previous record's, also checked)
            or it.sig > 31
            or abs(it.int_val) > 2147483647
        ):
            chunk_fast = False
        if marker_seen or not it.is_float or not unit_ok or not int_optimized:
            chunk_fast_float = False
        if it.ts_iterator.done or it.err is not None:
            break
    if per and chunk_recs > 0:
        # seal the trailing chunk; a break exactly on a boundary (chunk_recs
        # == 0 after reset) means the last chunk was already sealed above
        per[-1]["fast"] = chunk_fast and chunk_recs == k
        per[-1]["fast_float"] = (
            chunk_fast_float and chunk_start_float and chunk_recs == k
        )
    offs = [p["off"] for p in per] + [total_bits]
    for i, p in enumerate(per):
        p["span"] = offs[i + 1] - offs[i]
        p["total_bits"] = total_bits
        p.setdefault("fast", False)
        p.setdefault("fast_float", False)
    return per


def window_words(max_span_bits: int, min_window_words: int = 0) -> int:
    """Window width (uint32 words) covering the widest chunk span plus 4
    lookahead words and up to 31 bits of alignment slack. ONE shared
    definition: the streamed assembler below and the resident pool's
    device-side assembly (m3_tpu/resident/) must agree on cw or their
    window arrays — and therefore their f32 reduction trees — diverge."""
    cw = (31 + max_span_bits + 31) // 32 + 4
    return max(cw, min_window_words, 6)


def assemble_chunked(
    streams: list[bytes], snaps: list[list[dict]], k: int, min_window_words: int = 0
) -> ChunkedBatch:
    """Pack streams + per-chunk snapshots into the dense lane arrays."""
    s = len(streams)
    c = max((len(p) for p in snaps), default=1)
    c = max(c, 1)
    n = s * c
    max_span = max((p["span"] for per in snaps for p in per), default=0)
    cw = window_words(max_span, min_window_words)

    windows = np.zeros((n, cw), np.uint32)
    rel = np.zeros(n, np.int32)
    nbits = np.zeros(n, np.int32)
    first = np.zeros(n, bool)
    pt = np.zeros(n, np.uint64)
    pd = np.zeros(n, np.uint64)
    pfb = np.zeros(n, np.uint64)
    pxr = np.zeros(n, np.uint64)
    iv = np.zeros(n, np.uint64)
    tu = np.zeros(n, np.int32)
    sig = np.zeros(n, np.int32)
    mult = np.zeros(n, np.int32)
    isf = np.zeros(n, bool)
    fast = np.ones(n, bool)  # empty padding lanes stay fast
    fast_float = np.ones(n, bool)  # likewise

    for si, (data, per) in enumerate(zip(streams, snaps)):
        padded = (
            np.frombuffer(data + b"\x00" * (-len(data) % 4), dtype=">u4").astype(np.uint32)
            if data
            else np.zeros(0, np.uint32)
        )
        for ci, p in enumerate(per):
            i = si * c + ci
            w0 = p["off"] >> 5
            rel[i] = p["off"] & 31
            seg = padded[w0 : w0 + cw]
            windows[i, : len(seg)] = seg
            nbits[i] = max(0, min(p["total_bits"] - (w0 << 5), cw * 32))
            first[i] = ci == 0
            pt[i] = p["prev_time"]
            pd[i] = p["prev_delta"]
            pfb[i] = p["prev_float_bits"]
            pxr[i] = p["prev_xor"]
            iv[i] = p["int_val"]
            tu[i] = p["time_unit"]
            sig[i] = p["sig"]
            mult[i] = p["mult"]
            isf[i] = p["is_float"]
            # the first chunk decodes the 64-bit head + first-value format
            # the fast bodies don't implement
            fast[i] = bool(p.get("fast", False)) and ci != 0
            fast_float[i] = bool(p.get("fast_float", False)) and ci != 0

    return ChunkedBatch(
        windows=windows,
        rel_pos=rel,
        num_bits=nbits,
        first=first,
        prev_time=_split64(pt),
        prev_delta=_split64(pd),
        prev_float_bits=_split64(pfb),
        prev_xor=_split64(pxr),
        int_val=_split64(iv),
        time_unit=tu,
        sig=sig,
        mult=mult,
        is_float=isf,
        k=k,
        num_series=s,
        num_chunks=c,
        fast=fast,
        fast_float=fast_float,
    )


def build_chunked(
    streams: list[bytes],
    k: int = 32,
    int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
    default_unit: Unit = Unit.SECOND,
    min_window_words: int = 0,
) -> ChunkedBatch:
    """Prescan + assemble (see snapshot_stream / assemble_chunked). Uses the
    native C++ prescanner (native/m3tsz.cc, ~50x the Python walk) when built."""
    from .. import native

    if native.available():
        snaps = native.prescan_batch(
            streams, k=k, default_unit=int(default_unit), int_optimized=int_optimized
        )
    else:
        snaps = [
            snapshot_stream(d, k, int_optimized=int_optimized, default_unit=default_unit)
            for d in streams
        ]
    return assemble_chunked(streams, snaps, k, min_window_words=min_window_words)


def tile_chunked(batch: ChunkedBatch, n_series: int) -> ChunkedBatch:
    """Tile a small unique batch up to n_series (bench helper)."""
    reps = -(-n_series // batch.num_series)
    cut = n_series * batch.num_chunks

    def t(x):
        return np.tile(np.asarray(x), (reps,) + (1,) * (np.asarray(x).ndim - 1))[:cut]

    return ChunkedBatch(
        **lane_kwargs(batch, transform=t),
        k=batch.k,
        num_series=n_series,
        num_chunks=batch.num_chunks,
        fast=t(batch.fast) if batch.fast is not None else None,
        fast_float=t(batch.fast_float) if batch.fast_float is not None else None,
    )


def pad_series(batch: ChunkedBatch, multiple: int) -> ChunkedBatch:
    """Pad with EMPTY series (zero-bit lanes decode zero records) so the
    series count divides a mesh size — the query fan-out's matched count is
    arbitrary, the mesh's shard axis is not. Empty lanes match
    build_chunked's padding exactly (all-zero state, fast=True) so they
    route through the fast kernel body and contribute nothing."""
    pad = (-batch.num_series) % multiple
    if pad == 0:
        return batch
    n_new = pad * batch.num_chunks

    def t(x):
        x = np.asarray(x)
        z = np.zeros((n_new,) + x.shape[1:], x.dtype)
        return np.concatenate([x, z])

    kw = lane_kwargs(batch, transform=t)
    return ChunkedBatch(
        **kw,
        k=batch.k,
        num_series=batch.num_series + pad,
        num_chunks=batch.num_chunks,
        fast=(
            np.concatenate([np.asarray(batch.fast), np.ones(n_new, bool)])
            if batch.fast is not None
            else None
        ),
        fast_float=(
            np.concatenate([np.asarray(batch.fast_float), np.ones(n_new, bool)])
            if batch.fast_float is not None
            else None
        ),
    )


def select_series(batch: ChunkedBatch, series_idx) -> ChunkedBatch:
    """Query-fanout gather: a new ChunkedBatch holding only the selected
    series (index query postings → decode, the config-5 fan-out shape).
    Host-side numpy fancy indexing over the series-major lane layout."""
    sel = np.asarray(series_idx, np.int64)
    c = batch.num_chunks
    lanes = (sel[:, None] * c + np.arange(c)[None, :]).ravel()

    def g(x):
        # np.take is ~20% faster than fancy indexing for these row gathers
        # (contiguous output, no intermediate index normalization)
        return np.take(np.asarray(x), lanes, axis=0)

    return ChunkedBatch(
        **lane_kwargs(batch, transform=g),
        k=batch.k,
        num_series=int(sel.size),
        num_chunks=c,
        fast=g(batch.fast) if batch.fast is not None else None,
        fast_float=g(batch.fast_float) if batch.fast_float is not None else None,
    )


def _window_columns(windows):
    """Pre-split the [N, CW] window into CW+3 column vectors (zero-padded).

    Device gathers are catastrophically slow on TPU (XLA lowers them to
    scalar dynamic-slices), so the per-step fetch is a pure vector select
    chain over these columns instead."""
    n, cw = windows.shape
    zero = jnp.zeros((n,), U32)
    cols = [windows[:, j] for j in range(cw)] + [zero, zero, zero]
    return cols


def _fetch4_select(cols, cw, base_rel, pos, max_widx: int | None = None):
    """Aligned 4-word fetch via a barrel shift over the lane-private window
    columns — O(CW + 4 log CW) VPU selects, no gather.

    One shared barrel shifter (high bit first, narrowing the live candidate
    list to 4 + remaining-shift entries each stage) replaces four independent
    select trees: ~46 selects vs ~124 at CW=24.

    ``max_widx`` (static) bounds the word index the caller can reach — for
    unrolled record loops the cursor after j records is statically bounded,
    so early records need far fewer barrel stages."""
    p = base_rel + pos
    widx = p >> 5
    zero = jnp.zeros_like(cols[0])
    bound = cw - 1 if max_widx is None else min(max_widx, cw - 1)
    cand = list(cols[: min(bound + 4, cw + 3)])
    while len(cand) < 4:
        cand.append(zero)
    if bound <= 0:
        s = 0  # cursor provably in word 0: no barrel stages at all
    else:
        s = 1
        while s * 2 <= bound:
            s *= 2
    while s >= 1:
        flag = (widx & s) != 0
        width = min(4 + s - 1, len(cand))
        cand = [
            jnp.where(flag, cand[i + s] if i + s < len(cand) else zero, cand[i])
            for i in range(width)
        ]
        s //= 2
    ws = (cand[0], cand[1], cand[2], cand[3])
    r = (p & 31).astype(U32)
    nz = r != 0
    inv = U32(32) - r

    def sh(a, b):
        return (a << r) | jnp.where(nz, b >> inv, U32(0))

    return (sh(ws[0], ws[1]), sh(ws[1], ws[2]), sh(ws[2], ws[3]), ws[3] << r)


@functools.partial(jax.jit, static_argnames=("k", "int_optimized"))
def decode_chunked_lanes(
    windows,
    rel_pos,
    num_bits,
    first,
    prev_time,
    prev_delta,
    prev_float_bits,
    prev_xor,
    int_val,
    time_unit,
    sig,
    mult,
    is_float,
    k: int,
    int_optimized: bool = True,
) -> DecodeResult:
    """K-step scan over chunk lanes. Same record semantics as
    decode.decode_batched; only the fetch and initial state differ."""
    windows = jnp.asarray(windows, U32)
    rel_pos = jnp.asarray(rel_pos, I32)
    n = windows.shape[0]
    cols = _window_columns(windows)
    fetch4 = functools.partial(_fetch4_select, cols, windows.shape[1], rel_pos)
    as_pair = lambda p: (jnp.asarray(p[0], U32), jnp.asarray(p[1], U32))

    state = DecodeState(
        pos=jnp.zeros((n,), I32),
        done=jnp.asarray(num_bits, I32) <= jnp.asarray(rel_pos, I32),
        err=jnp.zeros((n,), bool),
        prev_time=as_pair(prev_time),
        prev_delta=as_pair(prev_delta),
        time_unit=jnp.asarray(time_unit, I32),
        prev_float_bits=as_pair(prev_float_bits),
        prev_xor=as_pair(prev_xor),
        int_val=as_pair(int_val),
        mult=jnp.asarray(mult, I32),
        sig=jnp.asarray(sig, I32),
        is_float=jnp.asarray(is_float, bool),
    )
    first_chunk = jnp.asarray(first, bool)
    nb = jnp.asarray(num_bits, I32) - rel_pos  # bits available from chunk start
    from .decode import _extract

    zero_pos = jnp.zeros((n,), I32)
    nt0 = _extract(fetch4(zero_pos), 0, 64)

    def step(state, idx):
        first_vec = first_chunk & (idx == 0)
        was_active = ~state.done & ~state.err
        state, _ = _decode_timestamp(fetch4, nb, state, first_vec, nt=nt0)
        ts_active = ~state.done & ~state.err
        state = _decode_value(fetch4, state, first_vec, int_optimized)
        now_active = ~state.done & ~state.err
        valid = was_active & ts_active & now_active
        point_is_float = jnp.logical_or(not int_optimized, state.is_float)
        val = u64.select(point_is_float, state.prev_float_bits, state.int_val)
        out = (
            state.prev_time[0],
            state.prev_time[1],
            val[0],
            val[1],
            point_is_float,
            state.mult,
            valid,
        )
        return state, out

    final_state, outs = jax.lax.scan(step, state, jnp.arange(k))
    ts_hi, ts_lo, val_hi, val_lo, pif, mlt, valid = outs
    tr = lambda x: jnp.swapaxes(x, 0, 1)
    val_pair = (tr(val_hi), tr(val_lo))
    values_f32 = jnp.where(
        tr(pif),
        u64.f64_bits_to_f32(val_pair),
        _int_val_to_f32(val_pair, tr(mlt)),
    )
    return DecodeResult(
        ts_hi=tr(ts_hi),
        ts_lo=tr(ts_lo),
        val_hi=val_pair[0],
        val_lo=val_pair[1],
        point_is_float=tr(pif),
        mult=tr(mlt),
        valid=tr(valid),
        err=final_state.err,
        values_f32=jnp.where(tr(valid), values_f32, jnp.float32(jnp.nan)),
    )


def decode_chunked(batch: ChunkedBatch, int_optimized: bool = True) -> DecodeResult:
    """Decode a ChunkedBatch; outputs reshaped to [S, C*K] per-series rows."""
    res = decode_chunked_lanes(
        **lane_kwargs(batch), k=batch.k, int_optimized=int_optimized
    )
    s, c, k = batch.num_series, batch.num_chunks, batch.k

    def rs(x):
        return x.reshape(s, c * k)

    return DecodeResult(
        ts_hi=rs(res.ts_hi),
        ts_lo=rs(res.ts_lo),
        val_hi=rs(res.val_hi),
        val_lo=rs(res.val_lo),
        point_is_float=rs(res.point_is_float),
        mult=rs(res.mult),
        valid=rs(res.valid),
        err=jnp.any(res.err.reshape(s, c), axis=1),
        values_f32=rs(res.values_f32),
    )
