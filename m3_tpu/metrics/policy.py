"""Storage policies: resolution + retention.

Reference: /root/reference/src/metrics/policy/storage_policy.go — string form
"<resolution>:<retention>" e.g. "10s:2d" (:85-167), with optional
"<resolution>@<precision>" resolution form (resolution.go).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

NANOS = 1_000_000_000

_UNITS = {
    "ns": 1,
    "us": 1_000,
    "µs": 1_000,
    "ms": 1_000_000,
    "s": NANOS,
    "m": 60 * NANOS,
    "h": 3600 * NANOS,
    "d": 24 * 3600 * NANOS,
}

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h|d)")


def parse_duration(s: str) -> int:
    """Go-style duration ("10s", "1m30s", "2d") → nanos."""
    if not s:
        raise ValueError("empty duration")
    pos = 0
    total = 0
    while pos < len(s):
        m = _DUR_RE.match(s, pos)
        if not m:
            raise ValueError(f"invalid duration {s!r}")
        total += int(float(m.group(1)) * _UNITS[m.group(2)])
        pos = m.end()
    return total


def format_duration(nanos: int) -> str:
    for unit in ("d", "h", "m", "s", "ms", "us", "ns"):
        u = _UNITS[unit]
        if nanos >= u and nanos % u == 0:
            return f"{nanos // u}{unit}"
    return f"{nanos}ns"


@dataclass(frozen=True, order=True)
class Resolution:
    window_nanos: int

    def __str__(self) -> str:
        return format_duration(self.window_nanos)


@dataclass(frozen=True, order=True)
class Retention:
    period_nanos: int

    def __str__(self) -> str:
        return format_duration(self.period_nanos)


@dataclass(frozen=True, order=True)
class StoragePolicy:
    resolution: Resolution
    retention: Retention

    def __str__(self) -> str:
        return f"{self.resolution}:{self.retention}"

    @staticmethod
    def parse(s: str) -> "StoragePolicy":
        parts = s.split(":")
        if len(parts) != 2:
            raise ValueError(f"invalid storage policy {s!r}")
        res = parts[0].split("@")[0]  # precision suffix accepted, implied
        return StoragePolicy(
            Resolution(parse_duration(res)), Retention(parse_duration(parts[1]))
        )


def parse_policy(s: str) -> StoragePolicy:
    return StoragePolicy.parse(s)
