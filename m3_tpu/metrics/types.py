"""Metrics domain model: aggregation types and metric types.

Reference: /root/reference/src/metrics/aggregation/type.go (type ids and
validity per metric kind, :25-175) and src/metrics/metric/types.go.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field


class AggregationType(enum.IntEnum):
    # Order matches type.go:32-55 so wire ids are compatible.
    UNKNOWN = 0
    LAST = 1
    MIN = 2
    MAX = 3
    MEAN = 4
    MEDIAN = 5
    COUNT = 6
    SUM = 7
    SUMSQ = 8
    STDEV = 9
    P10 = 10
    P20 = 11
    P30 = 12
    P40 = 13
    P50 = 14
    P60 = 15
    P70 = 16
    P80 = 17
    P90 = 18
    P95 = 19
    P99 = 20
    P999 = 21
    P9999 = 22

    def quantile(self) -> float | None:
        return _QUANTILES.get(self)

    def is_valid_for_counter(self) -> bool:
        # type.go:140-146
        return self in (
            AggregationType.MIN,
            AggregationType.MAX,
            AggregationType.MEAN,
            AggregationType.COUNT,
            AggregationType.SUM,
            AggregationType.SUMSQ,
            AggregationType.STDEV,
        )

    def is_valid_for_gauge(self) -> bool:
        return self in (
            AggregationType.LAST,
            AggregationType.MIN,
            AggregationType.MAX,
            AggregationType.MEAN,
            AggregationType.COUNT,
            AggregationType.SUM,
            AggregationType.SUMSQ,
            AggregationType.STDEV,
        )

    def is_valid_for_timer(self) -> bool:
        return self != AggregationType.UNKNOWN and self != AggregationType.LAST

    @property
    def type_string(self) -> str:
        # types_options.go defaultTypeStringsMap (lower/upper for min/max)
        return _TYPE_STRINGS.get(self, self.name.lower())


_QUANTILES = {
    AggregationType.MEDIAN: 0.5,
    AggregationType.P10: 0.1,
    AggregationType.P20: 0.2,
    AggregationType.P30: 0.3,
    AggregationType.P40: 0.4,
    AggregationType.P50: 0.5,
    AggregationType.P60: 0.6,
    AggregationType.P70: 0.7,
    AggregationType.P80: 0.8,
    AggregationType.P90: 0.9,
    AggregationType.P95: 0.95,
    AggregationType.P99: 0.99,
    AggregationType.P999: 0.999,
    AggregationType.P9999: 0.9999,
}

_TYPE_STRINGS = {
    AggregationType.LAST: "last",
    AggregationType.SUM: "sum",
    AggregationType.SUMSQ: "sum_sq",
    AggregationType.MEAN: "mean",
    AggregationType.MIN: "lower",
    AggregationType.MAX: "upper",
    AggregationType.COUNT: "count",
    AggregationType.STDEV: "stdev",
    AggregationType.MEDIAN: "median",
    AggregationType.P50: "p50",
    AggregationType.P95: "p95",
    AggregationType.P99: "p99",
}

# Defaults per metric type (types_options.go:125-143)
DEFAULT_COUNTER_AGGREGATIONS = (AggregationType.SUM,)
DEFAULT_TIMER_AGGREGATIONS = (
    AggregationType.SUM,
    AggregationType.SUMSQ,
    AggregationType.MEAN,
    AggregationType.MIN,
    AggregationType.MAX,
    AggregationType.COUNT,
    AggregationType.STDEV,
    AggregationType.MEDIAN,
    AggregationType.P50,
    AggregationType.P95,
    AggregationType.P99,
)
DEFAULT_GAUGE_AGGREGATIONS = (AggregationType.LAST,)


class MetricType(enum.IntEnum):
    UNKNOWN = 0
    COUNTER = 1
    TIMER = 2
    GAUGE = 3

    def default_aggregations(self):
        return {
            MetricType.COUNTER: DEFAULT_COUNTER_AGGREGATIONS,
            MetricType.TIMER: DEFAULT_TIMER_AGGREGATIONS,
            MetricType.GAUGE: DEFAULT_GAUGE_AGGREGATIONS,
        }.get(self, ())


def stdev(count, sum_sq, s):
    """Sample stdev exactly as aggregation/common.go:29-36 (0 when n < 2)."""
    div = count * (count - 1)
    if div == 0:
        return 0.0
    return math.sqrt((count * sum_sq - s * s) / div)


@dataclass
class Untimed:
    """Untimed metric union (metric/unaggregated/types.go)."""

    type: MetricType
    id: bytes
    counter_value: int = 0
    batch_timer_values: list[float] = field(default_factory=list)
    gauge_value: float = 0.0
    annotation: bytes = b""


@dataclass
class Timed:
    """Timed metric (metric/aggregated/types.go)."""

    type: MetricType
    id: bytes
    time_nanos: int
    value: float
    annotation: bytes = b""
