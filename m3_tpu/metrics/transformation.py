"""Transformation ops for rollup pipelines.

Reference: /root/reference/src/metrics/transformation/{unary,binary}.go.
Vectorized over [T] window sequences: binary ops consume (prev, curr)
adjacent flushes; emptyDatapoint becomes NaN.
"""

from __future__ import annotations

import numpy as np

NANOS = 1_000_000_000


def absolute(times: np.ndarray, values: np.ndarray):
    return times, np.abs(values)


def add(times: np.ndarray, values: np.ndarray):
    """binary.go add: curr + prev (NaN prev treated as 0 reset... reference
    returns curr when prev is NaN via emptyDatapoint guard)."""
    prev = np.concatenate([[np.nan], values[:-1]])
    out = np.where(np.isnan(prev), values, values + prev)
    return times, out


def _binary_guard(times, values):
    prev_v = np.concatenate([[np.nan], values[:-1]])
    prev_t = np.concatenate([[np.iinfo(np.int64).max], times[:-1]])
    bad = (prev_t >= times) | np.isnan(prev_v) | np.isnan(values)
    return prev_v, prev_t, bad


def per_second(times: np.ndarray, values: np.ndarray):
    prev_v, prev_t, bad = _binary_guard(times, values)
    diff = values - prev_v
    bad |= diff < 0
    dt = (times - prev_t).astype(np.float64)
    out = np.where(bad, np.nan, diff * NANOS / np.where(dt == 0, 1, dt))
    return times, out


def increase(times: np.ndarray, values: np.ndarray):
    prev_v, prev_t, bad = _binary_guard(times, values)
    diff = values - prev_v
    bad |= diff < 0
    return times, np.where(bad, np.nan, diff)


def reset(times: np.ndarray, values: np.ndarray):
    """unary.go reset: emit 0 (used to mark counter resets downstream)."""
    return times, np.zeros_like(values)


APPLY = {
    1: absolute,  # TransformationType.ABSOLUTE
    2: per_second,
    3: increase,
    4: add,
    5: reset,
}


def apply_pipeline(pipeline, times: np.ndarray, values: np.ndarray):
    for op in pipeline:
        times, values = APPLY[int(op)](times, values)
    return times, values
