"""Unaggregated metrics wire encoding: the aggregation tier's ingress format.

Reference: /root/reference/src/metrics/encoding/protobuf/ —
unaggregated_encoder.go / unaggregated_iterator.go encode a stream of
length-prefixed MetricWithMetadatas messages (counter/timer/gauge union +
staged metadatas carrying storage policies and aggregation types). This
framework defines its own compact layout with the same information content:

    message := u8 kind | payload
    untimed := u8 mtype | u32 id_len | id | i64 time_nanos
             | union (i64 counter / u32 n f64* timers / f64 gauge)
             | u32 ann_len | ann
             | u8 n_policies (u32 res_nanos_s? -> i64 window, i64 retention)*
             | u8 n_aggs (u8 agg_type)*
    timed   := like untimed with a single f64 value

Policies/aggregations empty means "use the receiver's defaults", matching
the DefaultStagedMetadatas fast path the reference optimizes for.
"""

from __future__ import annotations

import struct
from io import BytesIO

from .policy import Resolution, Retention, StoragePolicy
from .types import AggregationType, MetricType, Untimed

KIND_UNTIMED = 1
KIND_TIMED = 2

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


class UnaggregatedMessage:
    """One ingress message: an untimed/timed metric + routing metadata."""

    def __init__(
        self,
        metric: Untimed,
        time_nanos: int,
        policies: tuple[StoragePolicy, ...] = (),
        aggregations: tuple[AggregationType, ...] = (),
        timed: bool = False,
    ) -> None:
        self.metric = metric
        self.time_nanos = time_nanos
        self.policies = tuple(policies)
        self.aggregations = tuple(aggregations)
        self.timed = timed

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, UnaggregatedMessage)
            and self.metric == other.metric
            and self.time_nanos == other.time_nanos
            and self.policies == other.policies
            and self.aggregations == other.aggregations
            and self.timed == other.timed
        )

    def __repr__(self) -> str:  # debugging aid
        return (
            f"UnaggregatedMessage({self.metric!r}, t={self.time_nanos}, "
            f"policies={self.policies}, aggs={self.aggregations})"
        )


def encode_message(msg: UnaggregatedMessage) -> bytes:
    out = BytesIO()
    out.write(_U8.pack(KIND_TIMED if msg.timed else KIND_UNTIMED))
    m = msg.metric
    out.write(_U8.pack(int(m.type)))
    out.write(_U32.pack(len(m.id)))
    out.write(m.id)
    out.write(_I64.pack(msg.time_nanos))
    if m.type == MetricType.COUNTER:
        out.write(_I64.pack(int(m.counter_value)))
    elif m.type == MetricType.TIMER:
        out.write(_U32.pack(len(m.batch_timer_values)))
        for v in m.batch_timer_values:
            out.write(_F64.pack(v))
    else:
        out.write(_F64.pack(m.gauge_value))
    ann = m.annotation or b""
    out.write(_U32.pack(len(ann)))
    out.write(ann)
    out.write(_U8.pack(len(msg.policies)))
    for p in msg.policies:
        out.write(_I64.pack(p.resolution.window_nanos))
        out.write(_I64.pack(p.retention.period_nanos))
    out.write(_U8.pack(len(msg.aggregations)))
    for a in msg.aggregations:
        out.write(_U8.pack(int(a)))
    return out.getvalue()


def decode_message(buf: bytes, pos: int = 0) -> tuple[UnaggregatedMessage, int]:
    kind = buf[pos]
    pos += 1
    if kind not in (KIND_UNTIMED, KIND_TIMED):
        raise ValueError(f"bad message kind {kind}")
    mtype = MetricType(buf[pos])
    pos += 1
    (id_len,) = _U32.unpack_from(buf, pos)
    pos += 4
    mid = bytes(buf[pos : pos + id_len])
    pos += id_len
    (t,) = _I64.unpack_from(buf, pos)
    pos += 8
    counter, timers, gauge = 0, [], 0.0
    if mtype == MetricType.COUNTER:
        (counter,) = _I64.unpack_from(buf, pos)
        pos += 8
    elif mtype == MetricType.TIMER:
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        timers = [
            _F64.unpack_from(buf, pos + 8 * i)[0] for i in range(n)
        ]
        pos += 8 * n
    else:
        (gauge,) = _F64.unpack_from(buf, pos)
        pos += 8
    (ann_len,) = _U32.unpack_from(buf, pos)
    pos += 4
    ann = bytes(buf[pos : pos + ann_len])
    pos += ann_len
    n_pol = buf[pos]
    pos += 1
    policies = []
    for _ in range(n_pol):
        (window,) = _I64.unpack_from(buf, pos)
        (period,) = _I64.unpack_from(buf, pos + 8)
        pos += 16
        policies.append(StoragePolicy(Resolution(window), Retention(period)))
    n_agg = buf[pos]
    pos += 1
    aggs = tuple(AggregationType(buf[pos + i]) for i in range(n_agg))
    pos += n_agg
    metric = Untimed(
        type=mtype,
        id=mid,
        counter_value=counter,
        batch_timer_values=timers,
        gauge_value=gauge,
        annotation=ann,
    )
    return (
        UnaggregatedMessage(
            metric,
            t,
            tuple(policies),
            aggs,
            timed=kind == KIND_TIMED,
        ),
        pos,
    )


def encode_batch(msgs) -> bytes:
    """Length-prefixed concatenation (the unaggregated_iterator framing)."""
    out = BytesIO()
    for m in msgs:
        payload = encode_message(m)
        out.write(_U32.pack(len(payload)))
        out.write(payload)
    return out.getvalue()


def decode_batch(buf: bytes) -> list[UnaggregatedMessage]:
    msgs = []
    pos = 0
    while pos < len(buf):
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        msg, end = decode_message(buf, pos)
        if end - pos != n:
            raise ValueError(f"message length mismatch ({end - pos} != {n})")
        msgs.append(msg)
        pos += n
    return msgs


# ---------------------------------------------------------------------------
# Aggregated codec: flushed (already aggregated) metrics on the wire —
# reference src/metrics/encoding/protobuf/aggregated_encoder.go (the format
# aggregator flush handlers hand to m3msg producers).
# ---------------------------------------------------------------------------

KIND_AGGREGATED = 3


class AggregatedMessage:
    """One flushed datapoint + its storage policy (metric/aggregated)."""

    def __init__(
        self,
        mid: bytes,
        time_nanos: int,
        value: float,
        policy: StoragePolicy,
        agg_type: AggregationType = AggregationType.LAST,
    ) -> None:
        self.id = mid
        self.time_nanos = time_nanos
        self.value = float(value)
        self.policy = policy
        self.agg_type = agg_type

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, AggregatedMessage)
            and self.id == other.id
            and self.time_nanos == other.time_nanos
            and self.value == other.value
            and self.policy == other.policy
            and self.agg_type == other.agg_type
        )

    def __repr__(self) -> str:
        return (
            f"AggregatedMessage({self.id!r}, t={self.time_nanos}, "
            f"v={self.value}, {self.policy}, {self.agg_type.name})"
        )


def encode_aggregated(msg: AggregatedMessage) -> bytes:
    out = BytesIO()
    out.write(_U8.pack(KIND_AGGREGATED))
    out.write(_U32.pack(len(msg.id)))
    out.write(msg.id)
    out.write(_I64.pack(msg.time_nanos))
    out.write(_F64.pack(msg.value))
    out.write(_I64.pack(msg.policy.resolution.window_nanos))
    out.write(_I64.pack(msg.policy.retention.period_nanos))
    out.write(_U8.pack(int(msg.agg_type)))
    return out.getvalue()


def decode_aggregated(buf: bytes, pos: int = 0) -> tuple[AggregatedMessage, int]:
    (kind,) = _U8.unpack_from(buf, pos)
    pos += 1
    if kind != KIND_AGGREGATED:
        raise ValueError(f"not an aggregated message (kind {kind})")
    (id_len,) = _U32.unpack_from(buf, pos)
    pos += 4
    mid = buf[pos : pos + id_len]
    pos += id_len
    (t,) = _I64.unpack_from(buf, pos)
    pos += 8
    (v,) = _F64.unpack_from(buf, pos)
    pos += 8
    (res,) = _I64.unpack_from(buf, pos)
    pos += 8
    (ret,) = _I64.unpack_from(buf, pos)
    pos += 8
    (at,) = _U8.unpack_from(buf, pos)
    pos += 1
    return (
        AggregatedMessage(
            mid, t, v, StoragePolicy(Resolution(res), Retention(ret)),
            AggregationType(at),
        ),
        pos,
    )


def encode_aggregated_batch(msgs) -> bytes:
    out = BytesIO()
    for m in msgs:
        payload = encode_aggregated(m)
        out.write(_U32.pack(len(payload)))
        out.write(payload)
    return out.getvalue()


def decode_aggregated_batch(buf: bytes) -> list[AggregatedMessage]:
    msgs = []
    pos = 0
    while pos < len(buf):
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        msg, end = decode_aggregated(buf, pos)
        if end - pos != n:
            raise ValueError(f"message length mismatch ({end - pos} != {n})")
        msgs.append(msg)
        pos += n
    return msgs
