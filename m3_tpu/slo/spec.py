"""Declarative SLO specs: the config the fleet is operated against.

A spec file (YAML or JSON, ``--slo-config`` on the coordinator) names
objectives over the fleet's own stored telemetry (``_m3tpu``):

.. code-block:: yaml

    eval_interval: 15s        # rule eval + status cadence (>= 1s)
    probe_interval: 15s       # freshness/durability probe cadence
    windows:
      fast: [5m, 1h]          # page: short AND long window both burn
      slow: [6h, 3d]          # ticket: sustained slow burn
    burn_thresholds:
      fast: 14.4              # Google SRE workbook defaults
      slow: 6.0
    slos:
      - name: query_availability
        sli: availability     # non-5xx fraction of non-shed queries
        objective: 0.999
        window: 1h            # error-budget window
        per_tenant: true      # also record/alert per tenant
      - name: query_latency
        sli: latency          # fraction of queries under threshold
        objective: 0.99
        threshold: 0.25       # seconds; must be a duration bucket bound
        window: 1h
      - name: write_freshness
        sli: freshness        # probe: ingest -> readable lag bound
        objective: 0.99
        threshold: 5.0        # max acceptable lag seconds
        window: 1h
      - name: read_durability
        sli: durability       # probe: bit-identical spot-check reads
        objective: 0.9999
        window: 1h

Validation happens at load, loudly (the same posture as the ruler's
rule files): a sub-second interval is rejected against the m3tsz
second-unit floor (utils/schedule.check_telemetry_interval), a latency
threshold that is not an actual ``m3tpu_query_duration_seconds`` bucket
bound is rejected (the compiled SLI would silently select an empty
bucket series), and objective names must be snake_case slugs because
they become recording-rule name segments (``slo:<name>:ratio_rate5m``)
and ``objective`` label values.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..query.stats import QUERY_DURATION_BUCKETS
from ..ruler.rules import parse_duration
from ..utils.schedule import check_telemetry_interval

SLI_KINDS = ("availability", "latency", "freshness", "durability")
# probe-driven SLIs measure the system by acting on it; ratio SLIs are
# compiled purely from telemetry the fleet already stores about itself
PROBE_SLIS = ("freshness", "durability")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# window suffix grammar for recording-rule names: "5m" -> "rate5m".
# Round multiples render with their natural unit; anything else renders
# in whole seconds ("90s") — every form matches the colon-name segment
# regex because it is appended to "ratio_rate".
_UNITS = ((86400, "d"), (3600, "h"), (60, "m"))


def window_name(secs: float) -> str:
    """Seconds -> the compact duration token used in rule names and
    status keys (300 -> "5m", 3600 -> "1h", 90 -> "90s")."""
    s = int(secs)
    if s != secs or s <= 0:
        raise ValueError(f"window must be a positive whole-second count, got {secs!r}")
    for unit, tok in _UNITS:
        if s % unit == 0:
            return f"{s // unit}{tok}"
    return f"{s}s"


@dataclass(frozen=True)
class Objective:
    """One SLO: an SLI kind, a target, and an error-budget window."""

    name: str
    sli: str
    objective: float
    window_secs: float
    threshold: float | None = None  # latency: seconds; freshness: max lag
    per_tenant: bool = False
    service: str = "coordinator"

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "sli": self.sli,
            "objective": self.objective,
            "window": window_name(self.window_secs),
            "service": self.service,
        }
        if self.threshold is not None:
            out["threshold"] = self.threshold
        if self.per_tenant:
            out["perTenant"] = True
        return out


@dataclass(frozen=True)
class SLOSpec:
    """The validated spec: objectives + burn windows + cadences."""

    objectives: tuple = ()
    fast_windows: tuple = (300.0, 3600.0)
    slow_windows: tuple = (21600.0, 259200.0)
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    eval_interval: float = 15.0
    probe_interval: float = 15.0

    def burn_windows(self) -> tuple:
        """((short, long, threshold, severity) per alert tier)."""
        return (
            (self.fast_windows[0], self.fast_windows[1], self.fast_burn, "page"),
            (self.slow_windows[0], self.slow_windows[1], self.slow_burn, "ticket"),
        )

    def windows_for(self, obj: Objective) -> list:
        """Every distinct window the objective needs a ratio recording
        for: both burn tiers plus the budget window, ascending."""
        ws = {
            self.fast_windows[0], self.fast_windows[1],
            self.slow_windows[0], self.slow_windows[1],
            obj.window_secs,
        }
        return sorted(ws)

    def to_dict(self) -> dict:
        return {
            "slos": [o.to_dict() for o in self.objectives],
            "windows": {
                "fast": [window_name(w) for w in self.fast_windows],
                "slow": [window_name(w) for w in self.slow_windows],
            },
            "burn_thresholds": {"fast": self.fast_burn, "slow": self.slow_burn},
            "eval_interval": self.eval_interval,
            "probe_interval": self.probe_interval,
        }


def _window_pair(raw, default: tuple, what: str) -> tuple:
    if raw is None:
        return default
    if not isinstance(raw, (list, tuple)) or len(raw) != 2:
        raise ValueError(f"{what} windows must be a [short, long] pair, got {raw!r}")
    short, long_ = (parse_duration(v) for v in raw)
    if not 0 < short < long_:
        raise ValueError(
            f"{what} windows must satisfy 0 < short < long, got {raw!r}"
        )
    for w in (short, long_):
        check_telemetry_interval(w, f"{what} burn window")
        window_name(w)  # must render as a rule-name token
    return (short, long_)


def objective_from_dict(d: dict) -> Objective:
    if not isinstance(d, dict):
        raise ValueError(f"slo entry must be a mapping, got {type(d).__name__}")
    name = str(d.get("name", ""))
    if not _NAME_RE.match(name):
        raise ValueError(
            f"slo name {name!r} must be a snake_case slug "
            "([a-z][a-z0-9_]*): it becomes a recording-rule name segment "
            "and an objective label value"
        )
    sli = str(d.get("sli", ""))
    if sli not in SLI_KINDS:
        raise ValueError(f"slo {name!r}: unknown sli {sli!r} (one of {SLI_KINDS})")
    objective = float(d.get("objective", 0))
    if not 0.0 < objective < 1.0:
        raise ValueError(
            f"slo {name!r}: objective must be in (0, 1), got {objective!r}"
        )
    window = parse_duration(d.get("window", "1h"))
    check_telemetry_interval(window, f"slo {name!r} budget window")
    window_name(window)
    threshold = d.get("threshold")
    if sli == "latency":
        if threshold is None:
            raise ValueError(f"slo {name!r}: latency slis need a threshold")
        threshold = float(threshold)
        if threshold not in QUERY_DURATION_BUCKETS:
            raise ValueError(
                f"slo {name!r}: latency threshold {threshold!r}s is not a "
                "m3tpu_query_duration_seconds bucket bound "
                f"{QUERY_DURATION_BUCKETS} — the compiled SLI selects the "
                "le=<threshold> bucket series, so an off-bucket threshold "
                "would silently measure nothing"
            )
    elif sli == "freshness":
        threshold = float(threshold if threshold is not None else 5.0)
        if threshold <= 0:
            raise ValueError(f"slo {name!r}: freshness threshold must be positive")
    elif threshold is not None:
        raise ValueError(f"slo {name!r}: {sli} slis take no threshold")
    per_tenant = bool(d.get("per_tenant", False))
    if per_tenant and sli != "availability":
        # only the availability events (completed/failed counters) carry a
        # tenant label in storage; a per-tenant latency/probe SLI would
        # compile to an expression over series that do not exist
        raise ValueError(f"slo {name!r}: per_tenant applies to availability slis only")
    return Objective(
        name=name,
        sli=sli,
        objective=objective,
        window_secs=window,
        threshold=threshold,
        per_tenant=per_tenant,
        service=str(d.get("service", "coordinator")),
    )


def spec_from_dict(spec: dict) -> SLOSpec:
    if not isinstance(spec, dict):
        raise ValueError("slo spec must be a mapping with an 'slos' list")
    objectives = tuple(objective_from_dict(o) for o in spec.get("slos", ()))
    if not objectives:
        raise ValueError("slo spec names no objectives")
    seen: set = set()
    for o in objectives:
        if o.name in seen:
            raise ValueError(f"duplicate slo name {o.name!r}")
        seen.add(o.name)
    windows = spec.get("windows") or {}
    fast = _window_pair(windows.get("fast"), (300.0, 3600.0), "fast")
    slow = _window_pair(windows.get("slow"), (21600.0, 259200.0), "slow")
    thresholds = spec.get("burn_thresholds") or {}
    fast_burn = float(thresholds.get("fast", 14.4))
    slow_burn = float(thresholds.get("slow", 6.0))
    for label, v in (("fast", fast_burn), ("slow", slow_burn)):
        if v <= 1.0:
            raise ValueError(
                f"{label} burn threshold must exceed 1 (burn 1.0 is the "
                f"steady-state budget spend), got {v!r}"
            )
    eval_interval = parse_duration(spec.get("eval_interval", 15))
    probe_interval = parse_duration(spec.get("probe_interval", 15))
    for what, iv in (("eval", eval_interval), ("probe", probe_interval)):
        if iv <= 0:
            raise ValueError(f"slo {what} interval must be positive")
        check_telemetry_interval(iv, f"slo {what}")
    return SLOSpec(
        objectives=objectives,
        fast_windows=fast,
        slow_windows=slow,
        fast_burn=fast_burn,
        slow_burn=slow_burn,
        eval_interval=eval_interval,
        probe_interval=probe_interval,
    )


def load_slo_file(path: str) -> SLOSpec:
    """Load + validate an SLO config (YAML, or JSON as its subset)."""
    import yaml

    with open(path, encoding="utf-8") as f:
        raw = yaml.safe_load(f.read()) or {}
    return spec_from_dict(raw)
