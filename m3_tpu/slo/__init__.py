"""Fleet SLO engine: declarative SLIs, error budgets, burn-rate alerts.

The pipeline (ISSUE 17 / ROADMAP item 5):

- spec.py    — ``--slo-config`` model: objectives over four SLI kinds
               (availability, latency, freshness, durability), validated
               loudly at load;
- compile.py — objectives → one ruler RuleGroup over ``_m3tpu``:
               colon-form ratio recordings per window plus multi-window
               multi-burn-rate alerts (fast 5m/1h page, slow 6h/3d
               ticket) and a budget-exhaustion alert;
- budget.py  — the pure error-budget arithmetic both the engine and the
               compiled alert expressions derive from;
- engine.py  — the runtime: budget/burn gauges and edge-triggered
               violation counts read back from rule-derived storage,
               freshness/durability probes, and the live status surface
               (``/api/v1/slo``, ``/debug/slo``) joined to firing alerts.
"""

from .budget import budget_remaining, burn_rate, error_budget, exhaustion_secs
from .compile import SLO_GROUP, compile_groups, compile_objective, record_name
from .engine import SLOEngine
from .spec import (
    Objective,
    SLOSpec,
    load_slo_file,
    objective_from_dict,
    spec_from_dict,
    window_name,
)

__all__ = [
    "SLOEngine",
    "SLOSpec",
    "Objective",
    "SLO_GROUP",
    "budget_remaining",
    "burn_rate",
    "compile_groups",
    "compile_objective",
    "error_budget",
    "exhaustion_secs",
    "load_slo_file",
    "objective_from_dict",
    "record_name",
    "spec_from_dict",
    "window_name",
]
