"""The SLO engine: error-budget accounting over rule-derived storage.

The engine owns no SLI math of its own at runtime — the ratio series
are recorded by the compiled rule group (compile.py) through the ruler,
into ``_m3tpu``, and the engine's status loop just reads them back
(``engine_for("_m3tpu")``, the same per-namespace engine cache every
query takes) and applies budget.py's arithmetic:

- ``m3tpu_slo_budget_remaining_ratio{objective[,tenant]}`` gauge,
- ``m3tpu_slo_burn_rate{objective,window}`` gauge,
- ``m3tpu_slo_violations_total{objective}`` counter (edge-triggered on
  budget exhaustion, not level-sampled — one violation per incident),

and a ``status_dict()`` surface (``/api/v1/slo``, ``/debug/slo``,
``slo.json`` in the debug dump) that joins each objective's live budget
numbers to the burn-rate alerts currently pending/firing for it.

Active SLIs (freshness, durability) are measured by probes that act on
the data plane like a client would:

- freshness: write a canary datapoint whose VALUE is its write time,
  read it back, and score the observed ingest->readable lag against the
  objective's threshold;
- durability: write a seeded golden series once, then re-read the whole
  range every probe tick and require bit-identical values (the same
  spot-check the migration/ingest gates assert cross-process).

Probe outcomes are plain registry counters (``m3tpu_slo_probe_*``);
the selfmon scrape stores them and the compiled ratio rules consume
them — active and passive SLIs ride ONE pipeline.
"""

from __future__ import annotations

import random
import threading
import time

from ..query import stats as query_stats
from ..selfmon.guard import RESERVED_NS
from ..utils.instrument import DEFAULT as METRICS
from ..utils.schedule import FixedRateTicker
from .budget import budget_remaining, burn_rate, error_budget, exhaustion_secs
from .compile import compile_groups, record_name
from .spec import PROBE_SLIS, SLOSpec, window_name

NANOS = 1_000_000_000

# durability golden series shape: written once at start, re-read whole
# every probe tick. Seeded full-precision values — the claim is
# bit-identity, so the payload must exercise real mantissas.
_GOLDEN_POINTS = 16
_GOLDEN_SPACING_SECS = 2
_GOLDEN_AGE_SECS = 600


class SLOEngine:
    """Budget accounting + probes + the live status surface."""

    def __init__(
        self,
        spec: SLOSpec,
        engine_for,
        db,
        ruler=None,
        namespace: str = "default",
        instance: str = "coordinator0",
        clock=None,
        seed: int = 17,
    ) -> None:
        self.spec = spec
        self.engine_for = engine_for
        self.db = db
        self.ruler = ruler
        self.namespace = namespace
        self.instance = instance
        self.clock = clock or time.time_ns
        self.seed = seed
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        # per-objective last-computed status rows (objective name -> row)
        self._status: dict[str, dict] = {
            o.name: {"name": o.name, "sliRatio": None, "budgetRemaining": None}
            for o in spec.objectives
        }
        self._last_tick_nanos = 0
        self._exhausted: set = set()  # edge-trigger memory for violations
        self._probe_counts: dict[str, list] = {
            o.name: [0, 0] for o in spec.objectives if o.sli in PROBE_SLIS
        }
        self._freshness_first_write: float | None = None
        self._golden: list | None = None  # [(time_nanos, value)] written
        self._probe_seq = 0
        self._m_violations = {
            o.name: METRICS.counter(
                "slo_violations_total",
                "error-budget exhaustions (edge-triggered per incident)",
                labels={"objective": o.name},
            )
            for o in spec.objectives
        }

    # -- generated rules --

    def rule_groups(self) -> list:
        return compile_groups(self.spec)

    # -- lifecycle --

    def start(self) -> "SLOEngine":
        if self._threads:
            return self
        self._stop.clear()
        self._seed_golden()
        query_stats.set_slo_resolver(self._objectives_for_tenant)
        for name, target, interval in (
            ("slo-status", self._status_loop, self.spec.eval_interval),
            ("slo-probe", self._probe_loop, self.spec.probe_interval),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        query_stats.set_slo_resolver(None)
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    def _status_loop(self) -> None:
        ticker = FixedRateTicker(
            self.spec.eval_interval,
            phase_key=f"slo-status/{self.instance}",
            stop=self._stop,
        )
        while True:
            stopped, _ = ticker.wait_next()
            if stopped:
                return
            self.tick_status(self.clock())

    def _probe_loop(self) -> None:
        ticker = FixedRateTicker(
            self.spec.probe_interval,
            phase_key=f"slo-probe/{self.instance}",
            stop=self._stop,
        )
        while True:
            stopped, _ = ticker.wait_next()
            if stopped:
                return
            self.tick_probes(self.clock())

    # -- satellite: tenant -> objectives join for the query debug rows --

    def _objectives_for_tenant(self, tenant: str) -> list:
        """Objectives a tenant's queries count against: the query-path
        SLIs (availability/latency). Probe SLIs measure the engine's own
        canaries, so no client query counts against them."""
        return [
            o.name for o in self.spec.objectives if o.sli not in PROBE_SLIS
        ]

    # -- budget accounting (one status pass; the testable seam) --

    def _instant_rows(self, name: str, now_nanos: int) -> list:
        """[(labels, value)] for a recorded series at ``now_nanos`` —
        the ruler's own Result->rows projection, shared."""
        from ..ruler.ruler import GroupRunner

        engine = self.engine_for(RESERVED_NS)
        return GroupRunner._rows(engine.query_instant(name, now_nanos))

    def tick_status(self, now_nanos: int) -> dict:
        """Recompute every objective's budget numbers from the recorded
        ratio series. Never raises; a failed read keeps the previous
        numbers and marks the row stale (the status surface must stay up
        exactly when the fleet is in trouble)."""
        for obj in self.spec.objectives:
            row: dict = {
                "name": obj.name,
                "sli": obj.sli,
                "service": obj.service,
                "objective": obj.objective,
                "budgetWindow": window_name(obj.window_secs),
                "errorBudget": error_budget(obj.objective),
            }
            try:
                burns: dict = {}
                for w in self.spec.windows_for(obj):
                    rows = self._instant_rows(record_name(obj.name, w), now_nanos)
                    agg = self._aggregate(rows)
                    if agg is not None:
                        burns[window_name(w)] = burn_rate(agg, obj.objective)
                    if w == obj.window_secs:
                        self._apply_budget(obj, row, rows, agg)
                row["burnRates"] = burns
                for wname, b in burns.items():
                    METRICS.gauge(
                        "slo_burn_rate",
                        "error-budget spend multiple per rate window",
                        labels={"objective": obj.name, "window": wname},
                    ).set(b)
                row["stale"] = False
            except Exception as exc:
                prev = self._status.get(obj.name, {})
                row.update(
                    {
                        k: prev.get(k)
                        for k in ("sliRatio", "budgetRemaining", "burnRates",
                                  "perTenant", "exhaustionSecs")
                        if k in prev
                    }
                )
                row["stale"] = True
                row["lastError"] = f"{type(exc).__name__}: {exc}"
            if obj.name in self._probe_counts:
                good, total = self._probe_counts[obj.name]
                row["probes"] = {"good": good, "total": total}
            row["violations"] = self._m_violations[obj.name].value
            with self._lock:
                self._status[obj.name] = row
        with self._lock:
            self._last_tick_nanos = now_nanos
        return self.status_dict()

    @staticmethod
    def _aggregate(rows: list):
        """One scalar SLI out of an instant vector: the worst series
        (budget math must not let a healthy tenant average away a
        burning one)."""
        vals = [v for _, v in rows if v == v]
        return min(vals) if vals else None

    def _apply_budget(self, obj, row: dict, rows: list, agg) -> None:
        row["sliRatio"] = agg
        if agg is None:
            row["budgetRemaining"] = None
            return
        remaining = budget_remaining(agg, obj.objective)
        row["budgetRemaining"] = remaining
        row["exhaustionSecs"] = exhaustion_secs(agg, obj.objective, obj.window_secs)
        METRICS.gauge(
            "slo_budget_remaining_ratio",
            "fraction of the window's error budget left",
            labels={"objective": obj.name},
        ).set(remaining)
        if obj.per_tenant:
            per_tenant = {}
            for labels, v in rows:
                tenant = labels.get("tenant", "")
                if not tenant:
                    continue
                t_remaining = budget_remaining(v, obj.objective)
                per_tenant[tenant] = {
                    "sliRatio": v,
                    "budgetRemaining": t_remaining,
                    "burnRate": burn_rate(v, obj.objective),
                }
                METRICS.gauge(
                    "slo_budget_remaining_ratio",
                    "fraction of the window's error budget left",
                    labels={"objective": obj.name, "tenant": tenant},
                ).set(t_remaining)
            row["perTenant"] = per_tenant
        # edge-triggered violation accounting: one tick per incident
        if remaining <= 0.0:
            if obj.name not in self._exhausted:
                self._exhausted.add(obj.name)
                self._m_violations[obj.name].inc()
        else:
            self._exhausted.discard(obj.name)

    # -- probes --

    def _write_canary(self, tags: dict, points: list) -> int:
        """Data-plane canary write (NOT the selfmon guard context: the
        probe must take the same path a client write takes). Returns the
        error count."""
        from ..block.core import make_tags

        entries = [(make_tags(tags), t, v, 1) for t, v in points]
        errs = self.db.write_tagged_batch(self.namespace, entries)
        return sum(1 for e in errs if e)

    def _seed_golden(self) -> None:
        if self._golden is not None:
            return
        rng = random.Random(self.seed)
        t0 = self.clock() - _GOLDEN_AGE_SECS * NANOS
        self._golden = [
            (t0 + i * _GOLDEN_SPACING_SECS * NANOS, rng.random())
            for i in range(_GOLDEN_POINTS)
        ]
        try:
            self._write_canary(
                {"__name__": "slo_canary_durability", "instance": self.instance},
                self._golden,
            )
        except Exception:
            # m3lint: disable=M3L007 -- an unseeded golden set fails every durability probe loudly (total grows, good does not), which IS the signal
            pass

    def _count_probe(self, obj, ok: bool) -> None:
        labels = {"objective": obj.name, "kind": obj.sli}
        METRICS.counter(
            "slo_probe_total", "slo probe attempts", labels=labels
        ).inc()
        counts = self._probe_counts[obj.name]
        counts[1] += 1
        if ok:
            METRICS.counter(
                "slo_probe_good_total", "slo probes within objective",
                labels=labels,
            ).inc()
            counts[0] += 1

    def tick_probes(self, now_nanos: int) -> None:
        """One probe pass for every active-SLI objective. Never raises;
        a probe that errors scores bad — an unreadable canary IS the
        outage being measured."""
        for obj in self.spec.objectives:
            if obj.sli == "freshness":
                self._probe_freshness(obj, now_nanos)
            elif obj.sli == "durability":
                self._probe_durability(obj, now_nanos)

    def _probe_freshness(self, obj, now_nanos: int) -> None:
        self._probe_seq += 1
        wrote = False
        try:
            errs = self._write_canary(
                {"__name__": "slo_canary_freshness", "instance": self.instance},
                [(now_nanos, now_nanos / 1e9)],
            )
            wrote = errs == 0
        except Exception:
            wrote = False
        if self._freshness_first_write is None and wrote:
            self._freshness_first_write = now_nanos / 1e9
        try:
            rows = self._data_rows(
                f'slo_canary_freshness{{instance="{self.instance}"}}', now_nanos
            )
            latest = max((v for _, v in rows), default=None)
        except Exception:
            latest = None
        if latest is None:
            # nothing readable: only bad once a canary has been out
            # longer than the lag bound (startup grace)
            first = self._freshness_first_write
            if first is None or now_nanos / 1e9 - first <= obj.threshold:
                return
            self._count_probe(obj, False)
            return
        lag = now_nanos / 1e9 - latest
        self._count_probe(obj, wrote and lag <= obj.threshold)

    def _probe_durability(self, obj, now_nanos: int) -> None:
        golden = self._golden or []
        if not golden:
            self._count_probe(obj, False)
            return
        try:
            import numpy as np

            engine = self.engine_for(self.namespace)
            start = golden[0][0]
            step = _GOLDEN_SPACING_SECS * NANOS
            result = engine.query_range(
                f'slo_canary_durability{{instance="{self.instance}"}}',
                start, golden[-1][0], step,
            )
            vals = np.asarray(result.values)
            ok = (
                len(result.metas) == 1
                and vals.shape == (1, len(golden))
                # bit-identical: exact float equality, no tolerance
                and all(
                    float(vals[0, i]) == v for i, (_, v) in enumerate(golden)
                )
            )
        except Exception:
            ok = False
        self._count_probe(obj, ok)

    def _data_rows(self, query: str, now_nanos: int) -> list:
        from ..ruler.ruler import GroupRunner

        engine = self.engine_for(self.namespace)
        return GroupRunner._rows(engine.query_instant(query, now_nanos))

    # -- status surface --

    def _alerts_for(self, name: str) -> list:
        if self.ruler is None:
            return []
        return [
            a
            for a in self.ruler.alerts_dict().get("alerts", [])
            if a.get("labels", {}).get("objective") == name
        ]

    def status_dict(self) -> dict:
        """Live objective status joined to the firing/pending burn-rate
        alerts (what /api/v1/slo serves)."""
        with self._lock:
            rows = [dict(self._status[o.name]) for o in self.spec.objectives]
            last = self._last_tick_nanos
        for row in rows:
            row["alerts"] = self._alerts_for(row["name"])
        return {
            "instance": self.instance,
            "lastTickUnixNanos": last,
            "evalIntervalSecs": self.spec.eval_interval,
            "probeIntervalSecs": self.spec.probe_interval,
            "objectives": rows,
        }

    def debug_dict(self) -> dict:
        """The /debug/slo payload: status plus the compiled rule plane
        (what the operator walks alert -> objective -> rules with)."""
        out = self.status_dict()
        out["spec"] = self.spec.to_dict()
        out["generatedRules"] = [g.to_dict() for g in self.rule_groups()]
        return out
