"""Compile SLO objectives into ruler rule groups over ``_m3tpu``.

Each objective becomes:

- one **ratio recording rule per window** the spec needs (both burn
  tiers' short+long windows plus the budget window), named in the
  enforced colon form ``slo:<name>:ratio_rate<window>`` and labeled
  ``objective=<name>`` — these are the ONLY series the budget engine
  and the alert expressions read, so the whole SLO plane keys off
  rule-derived storage, not live process state;
- one **multi-window burn-rate alert per tier**: the page fires only
  when the short AND the long fast window both burn past the fast
  threshold (the AND is literal PromQL ``and`` over the two recorded
  ratios — the short window gives reaction time, the long window keeps
  a blip from paging, and the long window draining below threshold is
  what resolves the alert: hysteresis for free);
- one **budget-exhaustion alert** over the budget window.

Ratio SLI expressions by kind:

- availability: completed / (completed + shed + failed) over the
  coordinator's ``m3tpu_query_{completed,shed,failed}_total`` counters
  (shed-typed 503s and 5xx-style failures are the unavailability; 422
  cost rejections are the caller's query being too expensive, not the
  service being down, so they count in neither class).
  ``or``-union keeps a side with no samples from erasing the ratio
  (classic empty-vector-join failure), while a fully idle window stays
  no-data rather than a fake 100%.
- latency: the ``le=<threshold>`` bucket fraction of
  ``m3tpu_query_duration_seconds`` — p99-under-threshold style.
- freshness / durability: good/total over the SLO engine's own probe
  counters (``m3tpu_slo_probe_*``), which ride the same selfmon scrape
  as every other counter — one uniform ratio pipeline for passive and
  active SLIs.
"""

from __future__ import annotations

from ..ruler.rules import AlertRule, RecordingRule, RuleGroup
from ..selfmon.convert import format_le
from ..selfmon.guard import RESERVED_NS
from .budget import error_budget
from .spec import Objective, SLOSpec, window_name

# the generated group's reserved name: merged rule files must not collide
SLO_GROUP = "slo"


def record_name(obj_name: str, window_secs: float) -> str:
    return f"slo:{obj_name}:ratio_rate{window_name(window_secs)}"


def _avail_expr(window: str, per_tenant: bool) -> str:
    # non-5xx fraction of NON-SHED traffic: a deliberate load-shed (503
    # from the admission scheduler, counted in m3tpu_query_shed_total) is
    # capacity policy doing its job, not unavailability — it must not
    # burn the error budget. Only served-and-failed queries are bad.
    good = f"rate(m3tpu_query_completed_total[{window}])"
    fail = f"rate(m3tpu_query_failed_total[{window}])"
    if per_tenant:
        g = f"sum by (tenant) ({good})"
        f_ = f"sum by (tenant) ({fail})"
        # or-union each side with the other's zeroed labels: a tenant
        # with completions but no failures (or the reverse) must not
        # drop out of the inner join the + performs
        num = f"({g} or {f_} * 0)"
        bad = f"({f_} or {g} * 0)"
        # trailing `or`: a tenant whose window saw NO traffic at all
        # (0/0 — both counters flat, rates zero) delivers its objective.
        # Without it the division drops the row, the recording stops
        # emitting, and the tenant's LAST ratio (possibly a burning 0)
        # gets resurrected by instant-query lookback for minutes after
        # recovery — burn stays pinned, pages never resolve by value,
        # and the budget cannot drain
        return f"{num} / ({num} + {bad}) or ({num} * 0 + 1)"
    g = f"(sum({good}) or vector(0))"
    b = f"(sum({fail}) or vector(0))"
    return f"{g} / ({g} + {b}) or vector(1)"


def _latency_expr(window: str, threshold: float) -> str:
    # clamp_max: numerator and denominator ride separately-scraped
    # series, so a _count sample missing a window (scrape skew under
    # churn) would push the raw ratio past 1
    le = format_le(threshold)
    return (
        "clamp_max("
        f'sum(rate(m3tpu_query_duration_seconds_bucket{{le="{le}"}}[{window}]))'
        f" / sum(rate(m3tpu_query_duration_seconds_count[{window}])), 1)"
    )


def _probe_expr(window: str, name: str) -> str:
    sel = f'{{objective="{name}"}}'
    return (
        "clamp_max("
        f"sum(rate(m3tpu_slo_probe_good_total{sel}[{window}]))"
        f" / sum(rate(m3tpu_slo_probe_total{sel}[{window}])), 1)"
    )


def ratio_expr(obj: Objective, window_secs: float) -> str:
    w = window_name(window_secs)
    if obj.sli == "availability":
        return _avail_expr(w, obj.per_tenant)
    if obj.sli == "latency":
        return _latency_expr(w, obj.threshold)
    return _probe_expr(w, obj.name)


def _burn_cond(obj: Objective, window_secs: float, threshold: float) -> str:
    """``burn_rate(window) > threshold`` over the RECORDED ratio — the
    budget.burn_rate definition inlined as PromQL."""
    budget = error_budget(obj.objective)
    return (
        f"(1 - {record_name(obj.name, window_secs)}) / {budget:.10g}"
        f" > {threshold:.10g}"
    )


def compile_objective(obj: Objective, spec: SLOSpec) -> list:
    rules = [
        RecordingRule(
            record=record_name(obj.name, w),
            expr=ratio_expr(obj, w),
            labels={"objective": obj.name},
        )
        for w in spec.windows_for(obj)
    ]
    for short, long_, threshold, severity in spec.burn_windows():
        alert = "SLOFastBurn" if severity == "page" else "SLOSlowBurn"
        rules.append(
            AlertRule(
                alert=f"{alert}_{obj.name}",
                # the multi-window AND gate: both the reactive short
                # window and the smoothing long window must burn
                expr=(
                    f"({_burn_cond(obj, short, threshold)})"
                    f" and ({_burn_cond(obj, long_, threshold)})"
                ),
                for_secs=0.0,
                labels={
                    "objective": obj.name,
                    "severity": severity,
                    "window": f"{window_name(short)}/{window_name(long_)}",
                    "service": obj.service,
                },
                annotations={
                    "summary": (
                        f"{obj.name}: burning {{{{ $value }}}}x the error "
                        f"budget over {window_name(short)} and {window_name(long_)}"
                    ),
                },
            )
        )
    rules.append(
        AlertRule(
            alert=f"SLOBudgetExhausted_{obj.name}",
            expr=_burn_cond(obj, obj.window_secs, 1.0),
            for_secs=0.0,
            labels={
                "objective": obj.name,
                "severity": "page",
                "window": window_name(obj.window_secs),
                "service": obj.service,
            },
            annotations={
                "summary": (
                    f"{obj.name}: error budget for the "
                    f"{window_name(obj.window_secs)} window is exhausted "
                    "(burn {{ $value }}x)"
                ),
            },
        )
    )
    return rules


def compile_groups(spec: SLOSpec) -> list:
    """The whole spec as ONE rule group (recordings evaluate before the
    alerts that read them — group rules run in file order)."""
    rules: list = []
    for obj in spec.objectives:
        rules.extend(compile_objective(obj, spec))
    return [
        RuleGroup(
            name=SLO_GROUP,
            interval_secs=spec.eval_interval,
            namespace=RESERVED_NS,
            rules=tuple(rules),
        )
    ]
