"""Error-budget arithmetic: the pure math under the SLO engine.

Definitions (Google SRE workbook, ch. 5 "Alerting on SLOs"):

- error budget  = ``1 - objective`` — the fraction of events allowed to
  be bad over the budget window;
- burn rate     = ``(1 - sli) / (1 - objective)`` — how many multiples
  of the steady-state budget spend the current bad-event fraction
  represents (burn 1.0 spends exactly the budget over the window,
  burn 14.4 over a 1h window spends ~2% of a 30d budget in that hour);
- budget remaining = ``1 - (1 - sli_over_window) / (1 - objective)`` —
  what is left of the window's budget given the window's observed SLI.

Everything here is a pure function of (sli, objective) so the golden
tests in tests/test_slo.py pin the arithmetic exactly; the engine and
the compiled alert expressions both derive from these definitions, and
the multi-window gate (fast fires only when the SHORT and the LONG
window both burn) is what keeps a brief blip from paging.
"""

from __future__ import annotations


def error_budget(objective: float) -> float:
    """The allowed bad fraction: ``1 - objective``."""
    if not 0.0 < objective < 1.0:
        raise ValueError(f"objective must be in (0, 1), got {objective!r}")
    return 1.0 - objective


def burn_rate(sli: float, objective: float) -> float:
    """Budget-spend multiple for an observed SLI over some window.

    1.0 = spending exactly the budget; >1 = on track to exhaust it
    before the window ends. An SLI above the objective burns < 1 (and
    a perfect SLI burns 0 — never negative: over-delivery does not
    refill the budget)."""
    return max(0.0, (1.0 - sli)) / error_budget(objective)


def budget_remaining(sli: float, objective: float) -> float:
    """Fraction of the window's error budget left, given the window's
    SLI. 1.0 = untouched, 0.0 = exhausted; clamped at 0 below (an SLI
    past exhaustion reports 0, not a negative balance — the violation
    counter carries "how often", the gauge carries "how much left")."""
    return max(0.0, 1.0 - burn_rate(sli, objective))


def exhaustion_secs(sli: float, objective: float, window_secs: float):
    """Seconds until the window's budget is gone at the current burn
    rate, or ``None`` when the current burn never exhausts it (burn
    <= 1). The operator-facing "time to act" number."""
    rate = burn_rate(sli, objective)
    if rate <= 1.0:
        return None
    return float(window_secs) / rate
