"""SessionDatabase: the storage-Database surface backed by a cluster
Session over the live placement.

Reference: the coordinator never embeds storage — it reaches dbnodes
through the cluster-aware client (src/dbnode/client/session.go), resolving
topology from the KV-watched placement (src/dbnode/topology/dynamic.go:107)
and fanning out per consistency level. This adapter gives the coordinator
(and anything else written against the Database surface) that same remote
data plane: point it at the control-plane KV, and writes/reads route to the
node processes named by the placement.
"""

from __future__ import annotations

import threading

from dataclasses import dataclass

from ..cluster.placement import Placement, PlacementService
from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..utils.xtime import Unit
from .session import Session


@dataclass
class IndexDoc:
    id: bytes
    fields: tuple


@dataclass
class IndexQueryResult:
    docs: list
    exhaustive: bool


class SessionDatabase:
    """Database-surface adapter over placement-routed cluster sessions."""

    def __init__(
        self,
        kv,
        namespaces: tuple[str, ...] = ("default",),
        write_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY,
        read_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY,
        placement_name: str = "default",
    ) -> None:
        self.kv = kv
        self._ns_names = tuple(namespaces)
        self.write_consistency = write_consistency
        self.read_consistency = read_consistency
        self.placement_svc = PlacementService(kv, name=placement_name)
        self._lock = threading.Lock()
        self._placement: Placement | None = None
        self._have_placement = threading.Event()
        self._nodes: dict = {}
        self._sessions: dict[str, Session] = {}
        self._unsub = self.placement_svc.watch(self._on_placement)

    # the coordinator probes `"graphite" in db.namespaces`
    @property
    def namespaces(self):
        return self._ns_names

    @property
    def bootstrapped(self) -> bool:
        with self._lock:
            return self._placement is not None

    def remote_nodes(self) -> dict:
        """Snapshot of the placement-routed node stubs (instance id →
        RemoteNode) — the coordinator's self-scrape collector pulls each
        peer's registry over the universal ``metrics`` RPC op from here,
        so the scrape set tracks placement changes live."""
        with self._lock:
            return dict(self._nodes)

    def _on_placement(self, p: Placement) -> None:
        from ..net.client import RemoteNode

        nodes = {}
        for nid, inst in p.instances.items():
            if not inst.endpoint:
                continue
            nodes[nid] = RemoteNode.connect(inst.endpoint, node_id=nid)
        with self._lock:
            old = self._nodes
            self._placement = p
            self._nodes = nodes
            self._sessions.clear()
        self._have_placement.set()
        for node in old.values():
            try:
                node.close()
            except Exception:
                # m3lint: disable=M3L007 -- best-effort close of stubs replaced by a placement change; sockets are daemonized either way
                pass

    def _session(self, ns: str) -> Session:
        # a coordinator can come up before the operator writes the first
        # placement (or before the watch's first delivery) — block briefly
        # rather than failing ingest during boot
        if not self._have_placement.wait(timeout=10.0):
            raise RuntimeError("no placement yet (is the control plane up?)")
        with self._lock:
            if self._placement is None:
                raise RuntimeError("no placement yet (is the control plane up?)")
            sess = self._sessions.get(ns)
            if sess is None:
                sess = Session(
                    topology=TopologyMap(self._placement),
                    nodes=self._nodes,
                    namespace=ns,
                    write_consistency=self.write_consistency,
                    read_consistency=self.read_consistency,
                )
                self._sessions[ns] = sess
            return sess

    # --- Database surface ---

    def write(self, ns, sid, t, v, unit=Unit.SECOND):
        return self._session(ns).write(sid, t, v, unit)

    def write_tagged(self, ns, tags, t, v, unit=Unit.SECOND):
        return self._session(ns).write_tagged(tags, t, v, unit)

    def write_tagged_batch(self, ns, entries):
        """Batched ingest through per-host queues (host_queue.go seam) —
        one RPC per host per flush instead of one per datapoint. Per-entry
        quorum failures surface as ConsistencyError strings, matching the
        storage Database's per-entry error contract."""
        try:
            _, errs = self._session(ns).try_write_batch_tagged(
                [(tags, t, v, unit) for tags, t, v, unit in entries]
            )
            return errs
        except Exception as exc:  # transport/topology failure: all entries
            return [f"{type(exc).__name__}: {exc}"] * len(entries)

    def read(self, ns, sid, start, end):
        return self._session(ns).fetch(sid, start, end)

    def fetch_tagged(self, ns, query, start, end, limit=None):
        return [
            (sid, tags, dps)
            for sid, tags, dps in self._session(ns).fetch_tagged(
                query, start, end, limit=limit
            )
        ]

    def fetch_tagged_arrays(self, ns, query, start, end, limit=None):
        """Array variant of fetch_tagged — the surface the query adapter
        consumes (on the local Database it is served by the decoded-block
        cache; here remote datapoints materialize into arrays once). The
        materialization is this mode's decode stage: the per-query stats
        record attributes it so cluster-mode slow queries show decode cost
        too (the remote node's own stages stay in its process)."""
        import numpy as np

        from ..query import stats as query_stats

        res = self.fetch_tagged(ns, query, start, end, limit=limit)
        with query_stats.stage("decode"):
            return [
                (
                    sid,
                    tags,
                    (
                        np.asarray([dp.timestamp for dp in dps], np.int64),
                        np.asarray([dp.value for dp in dps], np.float64),
                    ),
                )
                for sid, tags, dps in res
            ]

    def query_ids(self, ns, query, start, end, limit=None):
        docs, exhaustive = self._session(ns).query_ids(query, start, end, limit=limit)
        return IndexQueryResult(
            docs=[IndexDoc(did, fields) for did, fields in docs],
            exhaustive=exhaustive,
        )

    def aggregate_query(self, ns, query, start, end, field_filter=None):
        if query is None:  # "all docs" — the wire codec needs a real AST node
            from ..index.query import AllQuery

            query = AllQuery()
        return self._session(ns).aggregate_query(
            query, start, end, field_filter=field_filter
        )

    def close(self) -> None:
        if self._unsub is not None:
            self._unsub()
            self._unsub = None
        with self._lock:
            nodes = dict(self._nodes)
            self._nodes.clear()
            self._sessions.clear()
        for node in nodes.values():
            try:
                node.close()
            except Exception:
                # m3lint: disable=M3L007 -- best-effort socket teardown on shutdown; the process is exiting
                pass
