"""Cluster-aware client session: replicated quorum writes/reads.

Reference: /root/reference/src/dbnode/client/ — session.Open
(session.go:505), Write fan-out to every replica of the shard
(writeAttemptWithRLock :1068), consistency-level result gating (:1789-1815),
FetchTagged across replicas with series merge/dedupe
(encoding/series_iterator.go), and peer streaming for bootstrap/repair
(FetchBootstrapBlocksFromPeers :2033).

Nodes are in-process storage nodes (testing/cluster.py) or any object with
the same surface — the transport seam where the reference speaks
TChannel/Thrift.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..utils.hash import shard_for
from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit


class ConsistencyError(Exception):
    def __init__(self, op: str, achieved: int, required: int, errors: list) -> None:
        super().__init__(
            f"{op}: consistency not achieved ({achieved}/{required}): {errors}"
        )
        self.achieved = achieved
        self.required = required


class _PendingWrite:
    """One enqueued write awaiting its host-queue flush."""

    __slots__ = ("entry", "event", "error")

    def __init__(self, entry) -> None:
        self.entry = entry
        self.event = threading.Event()
        self.error: str | None = None


class HostQueue:
    """Per-host asynchronous write queue (host_queue.go): writes buffer
    here and flush to the host as ONE write_tagged_batch RPC when the batch
    fills or the flush interval elapses — the data plane stops paying one
    synchronous round trip per datapoint. Per-entry errors come back with
    the batch so the session still counts quorum per datapoint.

    Reference: /root/reference/src/dbnode/client/host_queue.go (op batching
    + drain loop), session.go:1068 writeAttempt enqueueing per-shard ops."""

    def __init__(
        self,
        node,
        namespace: str,
        batch_size: int = 128,
        flush_interval: float = 0.005,
    ) -> None:
        self.node = node
        self.namespace = namespace
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self._buf: list[_PendingWrite] = []
        self._cv = threading.Condition()
        self._stop = False
        self._flush_req = False  # flush_now() latch: a bare notify is lost
        # when the worker isn't parked in a wait
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"host-queue-{getattr(node, 'id', '?')}",
        )
        self._thread.start()

    def enqueue(self, pw: _PendingWrite) -> None:
        with self._cv:
            self._buf.append(pw)
            # wake on the FIRST item (arms the flush-interval timer) and on
            # a full batch; in between the loop sleeps on the interval
            if len(self._buf) == 1 or len(self._buf) >= self.batch_size:
                self._cv.notify()

    def flush_now(self) -> None:
        with self._cv:
            self._flush_req = True
            self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._buf and not self._stop:
                    # idle: no timeout — zero wakeups until work arrives.
                    # A flush_now that raced an in-flight _flush (nothing
                    # left to send) must not leak its latch into the NEXT
                    # batch's fill window
                    self._flush_req = False
                    self._cv.wait()
                if (
                    self._buf
                    and len(self._buf) < self.batch_size
                    and not self._stop
                    and not self._flush_req
                ):
                    # partial batch: give it one flush interval to fill
                    self._cv.wait(self.flush_interval)
                if self._stop and not self._buf:
                    return
                batch, self._buf = self._buf, []
                self._flush_req = False
            if batch:
                self._flush(batch)

    def _flush(self, batch: list[_PendingWrite]) -> None:
        try:
            if hasattr(self.node, "write_tagged_batch"):
                errs = self.node.write_tagged_batch(
                    self.namespace, [pw.entry for pw in batch]
                )
            else:  # node without the batch op: per-entry fallback
                errs = []
                for pw in batch:
                    tags, t, v, unit = pw.entry
                    try:
                        self.node.write_tagged(self.namespace, tags, t, v, Unit(unit))
                        errs.append(None)
                    except Exception as exc:
                        errs.append(str(exc))
        except Exception as exc:  # transport failure fails the whole batch
            errs = [f"{type(exc).__name__}: {exc}"] * len(batch)
        for pw, err in zip(batch, errs):
            pw.error = err
            pw.event.set()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)


@dataclass
class Session:
    topology: TopologyMap
    nodes: dict  # instance id -> node (testing/cluster.Node or RPC stub)
    namespace: str = "default"
    write_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    read_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    # per-host async write queues, created lazily by write_batch_tagged;
    # creation is lock-guarded — racing writers must not each construct a
    # HostQueue (the loser's worker thread would leak and its enqueued
    # writes would miss future flush_now() calls)
    _queues: dict = field(default_factory=dict, repr=False)
    _queues_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    @property
    def num_shards(self) -> int:
        return self.topology.placement.num_shards

    def _shard(self, sid: bytes) -> int:
        return shard_for(sid, self.num_shards)

    def _fanout(self, op_name: str, shard: int, required: int, call,
                readable_only: bool = False):
        """Try ``call(node)`` on every replica of ``shard``; a raising
        replica must not abort the fan-out — remaining replicas can still
        reach quorum (session.go:1068). Returns the per-replica results;
        raises ConsistencyError when fewer than ``required`` succeed.

        ``readable_only`` gates on shard state: an INITIALIZING replica is
        still bootstrapping the shard and must not serve reads for it
        (topology readable-shard filtering; writes go to every replica so
        the initializing one doesn't miss data).

        Inside a traced request (an active span on this thread) the fan-out
        gets a span per replica attempt tagged {replica, shard}, so
        /debug/traces shows exactly which copies served a quorum op;
        untraced writes pay nothing."""
        traced = TRACER.active()
        success, errors, results = 0, [], []
        for host in self.topology.hosts_for_shard(shard, readable_only=readable_only):
            node = self.nodes.get(host)
            if node is None or not node.is_up:
                errors.append(f"{host}: down")
                continue
            span = (
                TRACER.span(f"client.{op_name}.replica", replica=host, shard=shard)
                if traced
                else NOOP_SPAN
            )
            try:
                with span:
                    results.append(call(node))
                success += 1
            except Exception as exc:
                errors.append(f"{host}: {exc}")
        if success < required:
            raise ConsistencyError(op_name, success, required, errors)
        return results

    # --- writes (session.go:977-1100) ---

    def write_tagged(self, tags, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> bytes:
        from ..rules.rules import encode_tags_id

        sid = encode_tags_id(tags)
        self._fanout(
            "write",
            self._shard(sid),
            self.write_consistency.required(self.topology.replicas),
            lambda node: node.write_tagged(self.namespace, tags, t_nanos, value, unit),
        )
        return sid

    def write(self, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        self._fanout(
            "write",
            self._shard(sid),
            self.write_consistency.required(self.topology.replicas),
            lambda node: node.write(self.namespace, sid, t_nanos, value, unit),
        )

    # --- batched writes over per-host queues (host_queue.go data plane) ---

    def _host_queue(self, host: str) -> HostQueue | None:
        q = self._queues.get(host)
        if q is not None:
            return q
        node = self.nodes.get(host)
        if node is None:
            return None
        with self._queues_lock:
            q = self._queues.get(host)  # racing writer won while we waited
            if q is None:
                q = self._queues[host] = HostQueue(node, self.namespace)
            return q

    def try_write_batch_tagged(
        self, entries, timeout: float = 30.0
    ) -> tuple[list[bytes], list[str | None]]:
        """Batched tagged writes with PER-ENTRY outcomes: every entry fans
        out to its shard's replicas through per-host queues (one RPC per
        host per flush, not one per datapoint), then quorum is counted PER
        ENTRY from the returned per-element errors. ``entries``:
        (tags, t_nanos, value) or (tags, t_nanos, value, unit). Returns
        (series ids, per-entry error-or-None) — entries that achieved
        quorum are good even when neighbors failed."""
        from ..rules.rules import encode_tags_id

        required = self.write_consistency.required(self.topology.replicas)
        sids: list[bytes] = []
        errs: list[str | None] = []
        pendings: list[list[_PendingWrite]] = []
        touched: set[str] = set()
        for e in entries:
            tags, t, v = e[0], e[1], e[2]
            unit = int(e[3]) if len(e) > 3 else int(Unit.SECOND)
            sid = encode_tags_id(tags)
            sids.append(sid)
            per_entry: list[_PendingWrite] = []
            for host in self.topology.hosts_for_shard(self._shard(sid)):
                node = self.nodes.get(host)
                if node is None or not node.is_up:
                    continue
                q = self._host_queue(host)
                if q is None:
                    continue
                pw = _PendingWrite((tags, t, v, unit))
                q.enqueue(pw)
                per_entry.append(pw)
                touched.add(host)
            errs.append(
                None if len(per_entry) >= required
                else f"replicas down ({len(per_entry)}/{required})"
            )
            pendings.append(per_entry)
        for host in touched:
            self._queues[host].flush_now()
        for i, per_entry in enumerate(pendings):
            if errs[i] is not None:
                continue
            ok = 0
            last_err = None
            for pw in per_entry:
                pw.event.wait(timeout)
                if pw.event.is_set() and pw.error is None:
                    ok += 1
                else:
                    last_err = pw.error or "timeout"
            if ok < required:
                errs[i] = f"quorum {ok}/{required}: {last_err}"
        return sids, errs

    def write_batch_tagged(self, entries, timeout: float = 30.0) -> list[bytes]:
        """try_write_batch_tagged, raising ConsistencyError if ANY entry
        missed its write quorum (single-write call-site semantics)."""
        sids, errs = self.try_write_batch_tagged(entries, timeout=timeout)
        failed = [i for i, e in enumerate(errs) if e is not None]
        if failed:
            raise ConsistencyError(
                "write_batch", len(entries) - len(failed), len(entries),
                [f"{len(failed)} entries under quorum (first: {errs[failed[0]]})"],
            )
        return sids

    def close(self) -> None:
        for q in self._queues.values():
            q.stop()
        self._queues.clear()

    # --- reads (session.go:1269-1530 + series_iterator replica merge) ---

    def fetch(self, sid: bytes, start_nanos: int, end_nanos: int):
        """Fetch one series by ID. Consistency gates ONLY on the shard this
        ID lives in (session.go:1789-1815 readConsistencyAchieved over the
        attempted shard) — other shards being down cannot fail this read.

        Replicas ship COMPRESSED segments (fetch_blocks, the fetchBlocksRaw
        role); the merge runs client-side through the encoding iterator
        stack — per-replica MultiReaderIterator, replica-dedupe
        SeriesIterator (encoding/series_iterator.go)."""
        from ..codec.iterator import MultiReaderIterator, SeriesIterator

        replies = self._fanout(
            "fetch",
            self._shard(sid),
            self.read_consistency.required(self.topology.replicas),
            lambda node: node.fetch_blocks(self.namespace, sid, start_nanos, end_nanos),
            readable_only=True,
        )
        it = SeriesIterator(
            sid,
            [MultiReaderIterator(segments) for segments in replies],
            start_nanos=start_nanos,
            end_nanos=end_nanos,
        )
        return list(it)

    def fetch_tagged(self, query, start_nanos: int, end_nanos: int,
                     limit: int | None = None):
        """Fan out to replicas of every shard; merge + dedupe series across
        replicas (last-written value wins on equal timestamps, the
        SeriesIterator default). ``limit`` caps the merged series count."""
        required = self.read_consistency.required(self.topology.replicas)
        traced = TRACER.active()
        fanout_span = (
            TRACER.span("client.fetch_tagged", namespace=self.namespace)
            if traced
            else NOOP_SPAN
        )
        by_series: dict[bytes, tuple] = {}
        responded_by_shard: dict[int, int] = {}
        with fanout_span:
            for host, node in self.nodes.items():
                if not node.is_up:
                    continue
                span = (
                    TRACER.span("client.fetch_tagged.replica", replica=host)
                    if traced
                    else NOOP_SPAN
                )
                try:
                    with span:
                        res = node.fetch_tagged(
                            self.namespace, query, start_nanos, end_nanos,
                            limit=limit,
                        )
                except Exception:
                    continue
                # count this replica only for shards whose copy here is
                # READABLE per the placement — an INITIALIZING replica is
                # still bootstrapping and must not count toward read
                # consistency
                owned = node.owned_shards()
                for shard in owned:
                    if host in self.topology.hosts_for_shard(shard, readable_only=True):
                        responded_by_shard[shard] = responded_by_shard.get(shard, 0) + 1
                for sid, tags, dps in res:
                    cur = by_series.get(sid)
                    if cur is None:
                        by_series[sid] = (tags, {dp.timestamp: dp for dp in dps})
                    else:
                        merged = cur[1]
                        for dp in dps:
                            merged.setdefault(dp.timestamp, dp)
        # consistency check over EVERY shard in the placement — a shard whose
        # replicas are all down has zero responders and must fail the read,
        # not silently return partial results (session.go:1789-1815)
        for shard in range(self.num_shards):
            count = responded_by_shard.get(shard, 0)
            if count < required:
                raise ConsistencyError("read", count, required, [f"shard {shard}"])
        out = []
        for sid in sorted(by_series):
            tags, merged = by_series[sid]
            out.append((sid, tags, [merged[t] for t in sorted(merged)]))
        if limit is not None and len(out) > limit:
            out = out[:limit]
        return out

    # --- index-only reads (QueryIDs / AggregateQuery fan-out) ---

    def query_ids(self, query, start_nanos: int, end_nanos: int,
                  limit: int | None = None):
        """Fan out the index query; union docs by id. Requires at least one
        live replica overall (index listings are best-effort breadth, like
        the reference's aggregate paths). Returns (docs, exhaustive):
        ``limit`` applies to the MERGED union (the per-node limit alone
        would let N nodes return N×limit series past the cost cap), and
        exhaustive is False when this or any node truncated."""
        docs: dict[bytes, tuple] = {}
        responded = 0
        exhaustive = True
        for node in self.nodes.values():
            if not node.is_up:
                exhaustive = False  # a down replica may hold unseen docs
                continue
            try:
                res = node.query_ids(self.namespace, query, start_nanos,
                                     end_nanos, limit=limit)
            except Exception:
                # an unreachable placed replica may hold docs no one else
                # returned — the union can no longer claim completeness
                exhaustive = False
                continue
            responded += 1
            if not res.get("exhaustive", True):
                exhaustive = False
            for did, fields in res.get("docs", []):
                docs.setdefault(
                    bytes(did), tuple((bytes(k), bytes(v)) for k, v in fields)
                )
        if responded == 0:
            raise ConsistencyError("query_ids", 0, 1, ["no replica responded"])
        out = [(did, docs[did]) for did in sorted(docs)]
        if limit is not None and len(out) > limit:
            out = out[:limit]
            exhaustive = False
        return out, exhaustive

    def aggregate_query(self, query, start_nanos: int, end_nanos: int,
                        field_filter=None):
        """Union of tag name → value sets across replicas."""
        out: dict[bytes, set[bytes]] = {}
        responded = 0
        for node in self.nodes.values():
            if not node.is_up:
                continue
            try:
                agg = node.aggregate_query(
                    self.namespace, query, start_nanos, end_nanos,
                    field_filter=field_filter,
                )
            except Exception:
                continue  # best-effort breadth; zero responders still raise
            responded += 1
            for k, vs in agg.items():
                out.setdefault(k, set()).update(vs)
        if responded == 0:
            raise ConsistencyError("aggregate_query", 0, 1, ["no replica responded"])
        return out

    # --- peer streaming (peers bootstrapper / repair seam) ---

    def stream_shard_from_peer(self, peer_id: str, shard: int):
        """FetchBootstrapBlocksFromPeers: raw series streams for one shard."""
        node = self.nodes[peer_id]
        return node.stream_shard(self.namespace, shard)
