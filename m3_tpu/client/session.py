"""Cluster-aware client session: replicated quorum writes/reads.

Reference: /root/reference/src/dbnode/client/ — session.Open
(session.go:505), Write fan-out to every replica of the shard
(writeAttemptWithRLock :1068), consistency-level result gating (:1789-1815),
FetchTagged across replicas with series merge/dedupe
(encoding/series_iterator.go), and peer streaming for bootstrap/repair
(FetchBootstrapBlocksFromPeers :2033).

Nodes are in-process storage nodes (testing/cluster.py) or any object with
the same surface — the transport seam where the reference speaks
TChannel/Thrift.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from concurrent.futures import wait as _futures_wait
from dataclasses import dataclass, field

from ..cluster.topology import ConsistencyLevel, TopologyMap
from ..net.resilience import HealthProber, HedgeBudget, LatencyEstimator
from ..net.wire import IDEMPOTENT_OPS
from ..utils.hash import shard_for
from ..utils.instrument import DEFAULT as METRICS
from ..utils.trace import NOOP_SPAN, TRACER
from ..utils.xtime import Unit


class ConsistencyError(Exception):
    def __init__(self, op: str, achieved: int, required: int, errors: list) -> None:
        super().__init__(
            f"{op}: consistency not achieved ({achieved}/{required}): {errors}"
        )
        self.achieved = achieved
        self.required = required


class ReplicaResults(list):
    """Per-replica results of one fan-out; ``degraded`` is True when an
    UNSTRICT level accepted fewer than the preferred replica count."""

    degraded: bool = False


class TaggedResult(list):
    """fetch_tagged result rows; ``exhaustive`` is False when the read
    degraded below its preferred consistency (UNSTRICT_MAJORITY) — the
    rows are exactly what the responding replicas serve, but a silent
    replica may hold datapoints nobody returned."""

    exhaustive: bool = True


class SeriesResult(list):
    """Datapoints of a single-series fetch; ``exhaustive`` carries the
    same degraded-read marker as TaggedResult.exhaustive."""

    exhaustive: bool = True


def _session_retries(op: str):
    return METRICS.counter(
        "session_op_retries_total",
        "session-level fan-out retry rounds re-attempting failed replicas",
        labels={"op": op},
    )


_HEDGE_HELP = {
    "issued": "hedged backup replica requests issued for stragglers",
    "won": "hedged backup requests whose response arrived first",
    "wasted": "hedged backup requests beaten by (or abandoned with) the "
              "primary leg",
}


def _session_hedges(kind: str, op: str):
    # m3lint: disable=M3L005 -- kind is a _HEDGE_HELP literal key (issued/won/wasted): a closed three-name set
    return METRICS.counter(
        f"session_hedges_{kind}_total", _HEDGE_HELP[kind], labels={"op": op}
    )


class _Hedger:
    """Per-fan-out hedged-request state ("The Tail at Scale" backup
    requests, idempotent read ops only).

    Once the fan-out is one response short of quorum (``near_quorum``) and
    a pending replica has been in flight longer than its own per-(peer,
    op) p95 estimate, ONE backup request is issued to the next-best
    straggler (lowest p95 first) — first response per host wins, the loser
    leg is abandoned, and a loser's late error is never surfaced as a
    replica error. Issue volume is capped by the session's
    :class:`HedgeBudget` (≤ token_ratio extra load).

    All methods run on the fan-out's caller thread (the wait loop), so the
    per-host bookkeeping needs no locking.
    """

    def __init__(self, session: "Session", op_name: str, spawn, near_quorum):
        self.session = session
        self.op = op_name
        self.spawn = spawn              # host -> Future (one backup twin)
        self.near_quorum = near_quorum
        self.started: dict[str, float] = {}   # primary-leg submit time
        self.legs: dict[str, int] = {}        # outstanding legs per host
        self.resolved: set[str] = set()       # hosts with a delivered result
        self.attempted: set[str] = set()      # hosts we already tried to hedge
        self.hedge_futs: dict = {}            # Future -> host (backup legs)
        self.unresolved: set[str] = set()     # issued hedges with no outcome yet

    def note_submit(self, host: str) -> None:
        self.started[host] = time.monotonic()
        self.legs[host] = self.legs.get(host, 0) + 1

    def _threshold(self, host: str) -> float | None:
        """Elapsed time past which ``host`` counts as straggling: its own
        p95, floored by ``hedge_min_delay`` so ordinary sub-millisecond
        jitter can never burn the hedge budget."""
        p95 = self.session.latency.p95(host, self.op)
        if p95 is None:
            return None
        return max(p95, self.session.hedge_min_delay)

    def _candidates(self, pending_hosts, now: float) -> list[str]:
        out = []
        for host in pending_hosts:
            if host in self.attempted or host in self.resolved:
                continue
            thr = self._threshold(host)
            if thr is not None and now - self.started.get(host, now) > thr:
                out.append(host)
        return out

    def next_event(self, pending_hosts, now: float) -> float | None:
        """Earliest moment a pending host crosses its straggler threshold
        (so the wait loop can wake exactly then instead of sleeping to the
        deadline)."""
        fire = None
        for host in pending_hosts:
            if host in self.attempted or host in self.resolved:
                continue
            thr = self._threshold(host)
            if thr is None:
                continue
            at = self.started.get(host, now) + thr
            if fire is None or at < fire:
                fire = at
        return fire

    def maybe_hedge(self, pending_hosts, now: float) -> dict:
        """Issue at most ONE budget-gated backup per wake, to the
        best-ranked straggler; returns {Future: host} to join the wait."""
        for host in self.session.latency.rank(
            self._candidates(pending_hosts, now), self.op
        ):
            self.attempted.add(host)
            if not self.session.hedge_budget.try_spend():
                return {}
            fut = self.spawn(host)
            self.legs[host] = self.legs.get(host, 0) + 1
            self.hedge_futs[fut] = host
            self.unresolved.add(host)
            _session_hedges("issued", self.op).inc()
            # the wait loop runs on the query's own thread, so the active
            # QueryStats record (if any) is this thread's — surface the
            # hedge on /debug/active_queries
            from ..query import stats as query_stats

            st = query_stats.current()
            if st is not None and st.queue_state == "running":
                st.queue_state = "hedged"
            return {fut: host}
        return {}

    def on_success(self, fut, host: str) -> bool:
        """First success per host is delivered; a loser twin's late result
        is dropped (never double-merged). Returns whether to deliver."""
        if host in self.resolved:
            return False
        self.resolved.add(host)
        started = self.started.get(host)
        if started is not None:
            self.session.latency.record(
                host, self.op, time.monotonic() - started
            )
        self.session.hedge_budget.on_success()
        if host in self.unresolved:
            self.unresolved.discard(host)
            kind = "won" if fut in self.hedge_futs else "wasted"
            _session_hedges(kind, self.op).inc()
        return True

    def on_error(self, fut, host: str) -> bool:
        """A leg's error surfaces only when the host has no other live leg
        and no delivered result. Returns whether to deliver the error."""
        self.legs[host] = self.legs.get(host, 1) - 1
        if fut in self.hedge_futs and host in self.unresolved:
            self.unresolved.discard(host)
            _session_hedges("wasted", self.op).inc()
        if host in self.resolved:
            return False
        return self.legs.get(host, 0) <= 0

    def finish(self) -> None:
        """Fan-out over: hedges that never produced an outcome (both legs
        abandoned) were pure extra load."""
        for _ in range(len(self.unresolved)):
            _session_hedges("wasted", self.op).inc()
        self.unresolved.clear()


class _DaemonPool:
    """Persistent DAEMON worker threads behind concurrent.futures Futures.

    Why not ThreadPoolExecutor: fan-outs deliberately abandon stragglers
    (first-quorum-wins), and the executor's workers are non-daemon and
    joined by its atexit hook — an abandoned replica call blocked in a
    socket read would stall interpreter exit for its full timeout. Daemon
    workers don't, and a persistent pool avoids paying a thread spawn per
    replica attempt on the data-plane hot path. Workers spawn on demand up
    to ``max_workers``; a worker stuck on an abandoned call simply leaves
    one less slot until its bounded socket timeout fires."""

    def __init__(self, max_workers: int) -> None:
        import queue as _queue

        self._max = max_workers
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._lock = threading.Lock()
        self._threads = 0
        self._inflight = 0  # submitted, not yet finished

    def submit(self, fn, *args) -> Future:
        # the submitter's ambient deadline (coordinator HTTP timeout →
        # resilience.deadline_scope) is a thread-local, so it must be
        # captured here and re-established inside the worker — otherwise
        # every fanned-out replica call would budget as if the caller
        # were willing to wait forever
        from ..net.resilience import current_deadline

        deadline = current_deadline()
        fut: Future = Future()
        with self._lock:
            # invariant: threads >= min(max, inflight) — every
            # concurrently-submitted task has a worker (an "is a worker
            # idle?" heuristic undercounts when tasks are queued faster
            # than workers park, serializing a fan-out behind one thread)
            self._inflight += 1
            if self._threads < min(self._max, self._inflight):
                self._threads += 1
                threading.Thread(
                    target=self._run, daemon=True, name="session-fanout"
                ).start()
        self._q.put((fut, fn, args, deadline))
        return fut

    def _run(self) -> None:
        from ..net.resilience import deadline_scope

        while True:
            item = self._q.get()
            if item is None:  # close() sentinel
                with self._lock:
                    self._threads -= 1
                return
            fut, fn, args, deadline = item
            try:
                if fut.set_running_or_notify_cancel():
                    try:
                        with deadline_scope(deadline):
                            fut.set_result(fn(*args))
                    except BaseException as exc:
                        fut.set_exception(exc)
            finally:
                with self._lock:
                    self._inflight -= 1

    def close(self) -> None:
        """Ask every worker to exit (one sentinel each); workers stuck on
        an abandoned call pick theirs up when the call's timeout fires —
        or never, harmlessly, since they are daemon threads."""
        with self._lock:
            n = self._threads
        for _ in range(n):
            self._q.put(None)


class _PendingWrite:
    """One enqueued write awaiting its host-queue flush."""

    __slots__ = ("entry", "event", "error")

    def __init__(self, entry) -> None:
        self.entry = entry
        self.event = threading.Event()
        self.error: str | None = None


class HostQueue:
    """Per-host asynchronous write queue (host_queue.go): writes buffer
    here and flush to the host as ONE write_tagged_batch RPC when the batch
    fills or the flush interval elapses — the data plane stops paying one
    synchronous round trip per datapoint. Per-entry errors come back with
    the batch so the session still counts quorum per datapoint.

    Reference: /root/reference/src/dbnode/client/host_queue.go (op batching
    + drain loop), session.go:1068 writeAttempt enqueueing per-shard ops."""

    def __init__(
        self,
        node,
        namespace: str,
        batch_size: int = 128,
        flush_interval: float = 0.005,
    ) -> None:
        self.node = node
        self.namespace = namespace
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self._buf: list[_PendingWrite] = []
        self._cv = threading.Condition()
        self._stop = False
        self._flush_req = False  # flush_now() latch: a bare notify is lost
        # when the worker isn't parked in a wait
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"host-queue-{getattr(node, 'id', '?')}",
        )
        self._thread.start()

    def enqueue(self, pw: _PendingWrite) -> None:
        with self._cv:
            self._buf.append(pw)
            # wake on the FIRST item (arms the flush-interval timer) and on
            # a full batch; in between the loop sleeps on the interval
            if len(self._buf) == 1 or len(self._buf) >= self.batch_size:
                self._cv.notify()

    def flush_now(self) -> None:
        with self._cv:
            self._flush_req = True
            self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._buf and not self._stop:
                    # idle: no timeout — zero wakeups until work arrives.
                    # A flush_now that raced an in-flight _flush (nothing
                    # left to send) must not leak its latch into the NEXT
                    # batch's fill window
                    self._flush_req = False
                    self._cv.wait()
                if (
                    self._buf
                    and len(self._buf) < self.batch_size
                    and not self._stop
                    and not self._flush_req
                ):
                    # partial batch: give it one flush interval to fill
                    self._cv.wait(self.flush_interval)
                if self._stop and not self._buf:
                    return
                batch, self._buf = self._buf, []
                self._flush_req = False
            if batch:
                self._flush(batch)

    def _flush(self, batch: list[_PendingWrite]) -> None:
        try:
            if hasattr(self.node, "write_tagged_batch"):
                errs = self.node.write_tagged_batch(
                    self.namespace, [pw.entry for pw in batch]
                )
            else:  # node without the batch op: per-entry fallback
                errs = []
                for pw in batch:
                    tags, t, v, unit = pw.entry
                    try:
                        self.node.write_tagged(self.namespace, tags, t, v, Unit(unit))
                        errs.append(None)
                    except Exception as exc:
                        errs.append(str(exc))
        except Exception as exc:  # transport failure fails the whole batch
            errs = [f"{type(exc).__name__}: {exc}"] * len(batch)
        for pw, err in zip(batch, errs):
            pw.error = err
            pw.event.set()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)


@dataclass
class Session:
    topology: TopologyMap
    nodes: dict  # instance id -> node (testing/cluster.Node or RPC stub)
    namespace: str = "default"
    write_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    read_consistency: ConsistencyLevel = ConsistencyLevel.MAJORITY
    # per-host async write queues, created lazily by write_batch_tagged;
    # creation is lock-guarded — racing writers must not each construct a
    # HostQueue (the loser's worker thread would leak and its enqueued
    # writes would miss future flush_now() calls)
    _queues: dict = field(default_factory=dict, repr=False)
    _queues_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )
    # resilience knobs: one wall-clock bound per fan-out (a hung replica
    # costs at most fanout_timeout, never a serial stall), plus up to
    # op_retries extra ROUNDS re-attempting only the replicas that failed.
    # Session-level rounds are distinct from the RPC client's transparent
    # retries: datapoint writes are idempotent UPSERTS at the storage layer
    # (same series+timestamp overwrites), so deliberately re-sending a
    # failed replica's write here is safe even though the RPC layer must
    # never transparently re-send a write op.
    fanout_timeout: float = 10.0
    op_retries: int = 2
    op_retry_backoff: float = 0.05
    # once quorum is reached, stragglers get this much longer before the
    # fan-out stops waiting for them (first-quorum-wins: a hung replica
    # costs quorum-time + grace, not fanout_timeout)
    straggler_grace: float = 0.25
    # hedged backup requests for straggling replicas of IDEMPOTENT read
    # ops ("Tail at Scale"): None → the M3_TPU_HEDGE env decides (set 0 to
    # force-disable, e.g. for an unhedged baseline probe). The budget caps
    # hedges at ~token_ratio (5%) of served responses; the estimator holds
    # the per-(peer, op) p95 that defines "straggling".
    hedge_enabled: bool | None = None
    # floor under the per-peer p95 trigger: a replica is never hedged
    # before this much elapsed time, so healthy sub-millisecond fan-outs
    # don't spend budget on scheduler jitter
    hedge_min_delay: float = 0.01
    hedge_budget: HedgeBudget = field(default_factory=HedgeBudget, repr=False)
    latency: LatencyEstimator = field(
        default_factory=LatencyEstimator, repr=False
    )
    _prober: HealthProber | None = field(default=None, repr=False)
    _pool_obj: _DaemonPool | None = field(default=None, repr=False)
    _pool_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    @property
    def num_shards(self) -> int:
        return self.topology.placement.num_shards

    def _shard(self, sid: bytes) -> int:
        return shard_for(sid, self.num_shards)

    def _pool(self) -> _DaemonPool:
        with self._pool_lock:
            if self._pool_obj is None:
                self._pool_obj = _DaemonPool(
                    max(8, 4 * self.topology.replicas)
                )
            return self._pool_obj

    def _hedging_enabled(self) -> bool:
        if self.hedge_enabled is None:
            self.hedge_enabled = os.environ.get("M3_TPU_HEDGE", "1") != "0"
        return self.hedge_enabled

    def _make_hedger(self, op_name: str, spawn, near_quorum) -> _Hedger | None:
        """A hedger for this fan-out, or None when hedging is disabled or
        the op is not provably idempotent (a backup request that might be
        applied twice is only safe for reads — writes already have their
        own upsert-based session retry rounds)."""
        if op_name not in IDEMPOTENT_OPS or not self._hedging_enabled():
            return None
        return _Hedger(self, op_name, spawn, near_quorum)

    def _collect_first_quorum(self, futs: dict, deadline: float,
                              quorum, on_result, on_error,
                              hedger: _Hedger | None = None) -> set:
        """ONE wait loop for every fan-out (first-quorum-wins): until
        ``quorum()`` holds the wait runs to ``deadline``; after that,
        stragglers get ``straggler_grace`` and are then abandoned (their
        worker finishes — and releases its socket — in the background).
        ``futs`` maps Future -> host; completed futures dispatch to
        ``on_result(host, value)`` / ``on_error(host, exc)``. Returns the
        abandoned futures.

        With a ``hedger``, a pending replica past its p95 estimate gets a
        backup request instead of being passively waited out — both one
        short of quorum (the straggler is blocking the result) AND during
        the post-quorum ``straggler_grace`` window (the straggler is
        stalling the merge); the backup future joins the wait and the
        first response per host wins (the hedger suppresses the loser
        leg's result/error)."""
        waiting = set(futs)
        abandoned: set = set()
        quorum_at: float | None = None
        while waiting:
            if hedger is not None and hedger.resolved:
                # a resolved host's other legs (the hedge race's loser —
                # an abandoned twin, or a primary the twin out-ran) have
                # nothing left to deliver: on_success/on_error would
                # suppress them anyway. Drop them so the post-quorum
                # grace wait ends when every HOST is settled instead of
                # blocking on a leg whose result is already discarded.
                stale = {f for f in waiting if futs[f] in hedger.resolved}
                if stale:
                    abandoned |= stale
                    waiting -= stale
                    continue
            now = time.monotonic()
            until = deadline
            if quorum():
                if quorum_at is None:
                    quorum_at = now
                until = min(deadline, quorum_at + self.straggler_grace)
            if hedger is not None and hedger.near_quorum():
                pending_hosts = {futs[f] for f in waiting}
                for fut, host in hedger.maybe_hedge(pending_hosts, now).items():
                    futs[fut] = host
                    waiting.add(fut)
                nxt = hedger.next_event(
                    {futs[f] for f in waiting}, now
                )
                if nxt is not None:
                    # wake when the earliest straggler crosses its
                    # threshold (a small floor so a just-crossed
                    # threshold cannot spin)
                    until = min(until, max(nxt, now + 0.001))
            if now >= until:
                break
            done, waiting = _futures_wait(
                waiting, timeout=until - now, return_when="FIRST_COMPLETED"
            )
            for fut in done:
                host = futs[fut]
                try:
                    value = fut.result()
                except Exception as exc:
                    if hedger is None or hedger.on_error(fut, host):
                        on_error(host, exc)
                else:
                    if hedger is None or hedger.on_success(fut, host):
                        on_result(host, value)
        return waiting | abandoned

    def _next_round(self, op: str, round_no: int, deadline: float) -> bool:
        """Shared retry-round bookkeeping for every fan-out: False when
        the round budget or the op deadline is spent; otherwise counts the
        retry and sleeps this round's backoff (bounded by the deadline)."""
        if round_no > self.op_retries or time.monotonic() >= deadline:
            return False
        _session_retries(op).inc()
        time.sleep(
            min(self.op_retry_backoff * round_no,
                max(0.0, deadline - time.monotonic()))
        )
        return True

    def start_health_probes(self, interval: float = 0.25,
                            probe_timeout: float = 1.0) -> HealthProber:
        """Background prober driving open circuit breakers back closed
        (RemoteNode fleets): a recovered host rejoins fan-outs within
        ~interval instead of waiting for live traffic to probe it."""
        if self._prober is None:
            self._prober = HealthProber(
                self.nodes, interval=interval, probe_timeout=probe_timeout
            ).start()
        return self._prober

    def _replica_call(self, op_name: str, host: str, shard, call, node, ctx,
                      hedge: bool = False):
        """One replica attempt, run on a fan-out worker thread; ``ctx`` is
        the caller thread's trace context (thread-local span stacks do not
        follow threads), so traced fan-outs still render one tree tagged
        {replica, shard} — a hedged backup leg joins the same stitched
        trace tagged ``hedge=1``."""
        if ctx is not None:
            attrs = {"replica": host, "shard": shard}
            if hedge:
                attrs["hedge"] = "1"
            span = TRACER.span_from_context(
                f"client.{op_name}.replica", ctx, **attrs
            )
        else:
            span = NOOP_SPAN
        with span:
            return call(node)

    def _fanout(self, op_name: str, shard: int, required: int, call,
                readable_only: bool = False, unstrict: bool = False):
        """Call ``call(node)`` on every replica of ``shard`` IN PARALLEL;
        a raising or hanging replica must not abort (or stall) the fan-out
        — remaining replicas can still reach quorum (session.go:1068,
        "Tail at Scale": never serialize behind the slowest copy). Returns
        per-replica results in placement order; raises ConsistencyError
        when fewer than ``required`` succeed — accounting is
        first-quorum-wins: once ``required`` replicas have succeeded the
        op is good regardless of what stragglers do later.

        Replicas that fail are re-attempted for up to ``op_retries``
        extra rounds within the same ``fanout_timeout`` window (safe for
        writes: datapoint writes are storage-level upserts).

        ``readable_only`` gates on shard state: an INITIALIZING replica is
        still bootstrapping the shard and must not serve reads for it.
        ``unstrict`` (UNSTRICT_MAJORITY reads) degrades to the replicas
        that DID respond — at least one — instead of raising."""
        hosts = self.topology.hosts_for_shard(shard, readable_only=readable_only)
        ctx = TRACER.current_context()
        deadline = time.monotonic() + self.fanout_timeout
        ok: dict[str, object] = {}  # host -> result
        errors: list[str] = []
        hedger = self._make_hedger(
            op_name,
            spawn=lambda host: self._pool().submit(
                self._replica_call, op_name, host, shard, call,
                self.nodes[host], ctx, True,
            ),
            near_quorum=lambda: len(ok) >= required - 1,
        )
        pending = list(hosts)
        round_no = 0
        while True:
            round_no += 1
            errors = []
            futs = {}
            for host in pending:
                node = self.nodes.get(host)
                if node is None or not node.is_up:
                    errors.append(f"{host}: down")
                    continue
                futs[self._pool().submit(
                    self._replica_call, op_name, host, shard, call, node, ctx
                )] = host
                if hedger is not None:
                    hedger.note_submit(host)
            abandoned = self._collect_first_quorum(
                futs, deadline,
                quorum=lambda: len(ok) >= required,
                on_result=ok.__setitem__,
                on_error=lambda host, exc: errors.append(f"{host}: {exc}"),
                hedger=hedger,
            )
            timed_out: set[str] = set()
            for fut in abandoned:
                host = futs[fut]
                # a host whose OTHER leg already delivered (hedge winner's
                # abandoned twin) is not an error; twins dedupe to one line
                if host in ok or host in timed_out:
                    continue
                timed_out.add(host)
                errors.append(f"{host}: no reply within the fan-out window")
            if len(ok) >= required:
                break
            pending = [h for h in hosts if h not in ok]
            if not any(
                self.nodes.get(h) is not None and self.nodes[h].is_up
                for h in pending
            ):
                break  # nothing left to retry against
            if not self._next_round(op_name, round_no, deadline):
                break
        if hedger is not None:
            hedger.finish()
        results = ReplicaResults(ok[h] for h in hosts if h in ok)
        if len(ok) < required:
            if unstrict and len(ok) >= 1:
                results.degraded = True
                return results
            raise ConsistencyError(op_name, len(ok), required, errors)
        return results

    # --- writes (session.go:977-1100) ---

    def write_tagged(self, tags, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> bytes:
        from ..rules.rules import encode_tags_id

        sid = encode_tags_id(tags)
        self._fanout(
            "write",
            self._shard(sid),
            self.write_consistency.required(self.topology.replicas),
            lambda node: node.write_tagged(self.namespace, tags, t_nanos, value, unit),
        )
        return sid

    def write(self, sid: bytes, t_nanos: int, value: float, unit: Unit = Unit.SECOND) -> None:
        self._fanout(
            "write",
            self._shard(sid),
            self.write_consistency.required(self.topology.replicas),
            lambda node: node.write(self.namespace, sid, t_nanos, value, unit),
        )

    # --- batched writes over per-host queues (host_queue.go data plane) ---

    def _host_queue(self, host: str) -> HostQueue | None:
        q = self._queues.get(host)
        if q is not None:
            return q
        node = self.nodes.get(host)
        if node is None:
            return None
        with self._queues_lock:
            q = self._queues.get(host)  # racing writer won while we waited
            if q is None:
                q = self._queues[host] = HostQueue(node, self.namespace)
            return q

    def try_write_batch_tagged(
        self, entries, timeout: float = 30.0
    ) -> tuple[list[bytes], list[str | None]]:
        """Batched tagged writes with PER-ENTRY outcomes: every entry fans
        out to its shard's replicas through per-host queues (one RPC per
        host per flush, not one per datapoint), then quorum is counted PER
        ENTRY from the returned per-element errors. ``entries``:
        (tags, t_nanos, value) or (tags, t_nanos, value, unit). Returns
        (series ids, per-entry error-or-None) — entries that achieved
        quorum are good even when neighbors failed.

        ``timeout`` is ONE monotonic deadline shared by the whole batch
        (not per pending write — the old per-write wait made the worst
        case entries × replicas × timeout). Entries still short of quorum
        inside the deadline get up to ``op_retries`` extra rounds
        re-enqueued ONLY to the replicas that failed (safe: datapoint
        writes are storage-level upserts)."""
        from ..rules.rules import encode_tags_id

        required = self.write_consistency.required(self.topology.replicas)
        deadline = time.monotonic() + timeout
        sids: list[bytes] = []
        prepared: list[tuple[tuple, list[str]]] = []  # (entry, replica hosts)
        for e in entries:
            tags, t, v = e[0], e[1], e[2]
            unit = int(e[3]) if len(e) > 3 else int(Unit.SECOND)
            sid = encode_tags_id(tags)
            sids.append(sid)
            prepared.append(
                ((tags, t, v, unit),
                 self.topology.hosts_for_shard(self._shard(sid)))
            )
        ok_hosts: list[set[str]] = [set() for _ in prepared]
        last_err: list[str | None] = [None] * len(prepared)
        round_no = 0
        while True:
            round_no += 1
            pending: list[tuple[int, str, _PendingWrite]] = []
            touched: set[str] = set()
            for i, (entry, hosts) in enumerate(prepared):
                if len(ok_hosts[i]) >= required:
                    continue
                for host in hosts:
                    if host in ok_hosts[i]:
                        continue
                    node = self.nodes.get(host)
                    if node is None or not node.is_up:
                        continue
                    q = self._host_queue(host)
                    if q is None:
                        continue
                    pw = _PendingWrite(entry)
                    q.enqueue(pw)
                    pending.append((i, host, pw))
                    touched.add(host)
            for host in touched:
                self._queues[host].flush_now()
            for i, host, pw in pending:
                pw.event.wait(max(0.0, deadline - time.monotonic()))
                if pw.event.is_set() and pw.error is None:
                    ok_hosts[i].add(host)
                else:
                    last_err[i] = pw.error or "timeout"
            short = [i for i in range(len(prepared))
                     if len(ok_hosts[i]) < required]
            if not short or not self._next_round("write_batch", round_no, deadline):
                break
        errs: list[str | None] = []
        for i in range(len(prepared)):
            n_ok = len(ok_hosts[i])
            if n_ok >= required:
                errs.append(None)
            elif last_err[i] is None:
                errs.append(f"replicas down ({n_ok}/{required})")
            else:
                errs.append(f"quorum {n_ok}/{required}: {last_err[i]}")
        return sids, errs

    def write_batch_tagged(self, entries, timeout: float = 30.0) -> list[bytes]:
        """try_write_batch_tagged, raising ConsistencyError if ANY entry
        missed its write quorum (single-write call-site semantics)."""
        sids, errs = self.try_write_batch_tagged(entries, timeout=timeout)
        failed = [i for i, e in enumerate(errs) if e is not None]
        if failed:
            raise ConsistencyError(
                "write_batch", len(entries) - len(failed), len(entries),
                [f"{len(failed)} entries under quorum (first: {errs[failed[0]]})"],
            )
        return sids

    def close(self) -> None:
        if self._prober is not None:
            self._prober.stop()
            self._prober = None
        for q in self._queues.values():
            q.stop()
        self._queues.clear()
        with self._pool_lock:
            if self._pool_obj is not None:
                # daemon workers: close() just asks them to exit; abandoned
                # stragglers can't stall this call or interpreter exit
                self._pool_obj.close()
                self._pool_obj = None

    # --- reads (session.go:1269-1530 + series_iterator replica merge) ---

    def fetch(self, sid: bytes, start_nanos: int, end_nanos: int):
        """Fetch one series by ID. Consistency gates ONLY on the shard this
        ID lives in (session.go:1789-1815 readConsistencyAchieved over the
        attempted shard) — other shards being down cannot fail this read.

        Replicas ship COMPRESSED segments (fetch_blocks, the fetchBlocksRaw
        role); the merge runs client-side through the encoding iterator
        stack — per-replica MultiReaderIterator, replica-dedupe
        SeriesIterator (encoding/series_iterator.go)."""
        from ..codec.iterator import MultiReaderIterator, SeriesIterator

        replies = self._fanout(
            "fetch",
            self._shard(sid),
            self.read_consistency.required(self.topology.replicas),
            lambda node: node.fetch_blocks(self.namespace, sid, start_nanos, end_nanos),
            readable_only=True,
            unstrict=self.read_consistency.unstrict,
        )
        it = SeriesIterator(
            sid,
            [MultiReaderIterator(segments) for segments in replies],
            start_nanos=start_nanos,
            end_nanos=end_nanos,
        )
        out = SeriesResult(it)
        out.exhaustive = not replies.degraded
        return out

    def fetch_tagged(self, query, start_nanos: int, end_nanos: int,
                     limit: int | None = None):
        """Fan out to replicas of every shard IN PARALLEL (one hung host
        costs at most ``fanout_timeout``, never a serial stall); merge +
        dedupe series across replicas (last-written value wins on equal
        timestamps, the SeriesIterator default). ``limit`` caps the merged
        series count. Failed hosts are re-attempted for up to
        ``op_retries`` rounds (reads are idempotent).

        Under UNSTRICT_MAJORITY a shard short of quorum — but with at
        least ONE responding readable replica — degrades instead of
        raising: the result carries ``exhaustive = False`` and is exactly
        what the responding replicas serve (bit-identical to a MAJORITY
        read over just those replicas)."""
        required = self.read_consistency.required(self.topology.replicas)
        unstrict = self.read_consistency.unstrict
        traced = TRACER.active()
        fanout_span = (
            TRACER.span("client.fetch_tagged", namespace=self.namespace)
            if traced
            else NOOP_SPAN
        )
        # captured INSIDE the span (below): replica spans must parent to
        # client.fetch_tagged, and the span only becomes current on entry
        ctx = None

        def one(host, node, hedge=False):
            if ctx is not None:
                attrs = {"replica": host}
                if hedge:
                    attrs["hedge"] = "1"
                span = TRACER.span_from_context(
                    "client.fetch_tagged.replica", ctx, **attrs
                )
            else:
                span = NOOP_SPAN
            with span:
                res = node.fetch_tagged(
                    self.namespace, query, start_nanos, end_nanos, limit=limit
                )
                return res, node.owned_shards()

        responses: dict[str, tuple] = {}  # host -> (series rows, owned shards)
        # per-shard quorum accounting accumulates AS responses arrive: a
        # replica counts only for shards whose copy there is READABLE per
        # the placement — an INITIALIZING replica is still bootstrapping
        # and must not count toward read consistency
        responded_by_shard: dict[int, int] = {}

        def record(host: str, result: tuple) -> None:
            responses[host] = result
            for shard in result[1]:
                if host in self.topology.hosts_for_shard(shard, readable_only=True):
                    responded_by_shard[shard] = responded_by_shard.get(shard, 0) + 1

        def quorum_met() -> bool:
            return all(
                responded_by_shard.get(s, 0) >= required
                for s in range(self.num_shards)
            )

        def near_quorum() -> bool:
            # one response short everywhere: any single pending host's
            # reply could complete the read, so a straggler is worth a
            # hedged backup leg
            return all(
                responded_by_shard.get(s, 0) >= required - 1
                for s in range(self.num_shards)
            )

        hedger = self._make_hedger(
            "fetch_tagged",
            spawn=lambda host: self._pool().submit(
                one, host, self.nodes[host], True
            ),
            near_quorum=near_quorum,
        )

        with fanout_span:
            ctx = TRACER.current_context() if traced else None
            deadline = time.monotonic() + self.fanout_timeout
            pending = list(self.nodes)
            round_no = 0
            while True:
                round_no += 1
                futs = {}
                for host in pending:
                    node = self.nodes[host]
                    if not node.is_up:
                        continue
                    futs[self._pool().submit(one, host, node)] = host
                    if hedger is not None:
                        hedger.note_submit(host)
                # first-quorum-wins, like _fanout, with the per-shard
                # responder count as the quorum predicate: one hung
                # replica costs quorum-time + grace, not fanout_timeout
                self._collect_first_quorum(
                    futs, deadline, quorum=quorum_met,
                    on_result=record, on_error=lambda host, exc: None,
                    hedger=hedger,
                )
                pending = [h for h in self.nodes if h not in responses]
                if (
                    quorum_met()
                    or not any(self.nodes[h].is_up for h in pending)
                    or not self._next_round("fetch_tagged", round_no, deadline)
                ):
                    break
        if hedger is not None:
            hedger.finish()
        # consistency check over EVERY shard in the placement — a shard whose
        # replicas are all down has zero responders and must fail the read,
        # not silently return partial results (session.go:1789-1815).
        # UNSTRICT_MAJORITY degrades a short-but-nonzero shard to the
        # replicas that responded, marked non-exhaustive.
        degraded = False
        for shard in range(self.num_shards):
            count = responded_by_shard.get(shard, 0)
            if count < required:
                if unstrict and count >= 1:
                    degraded = True
                    continue
                raise ConsistencyError("read", count, required, [f"shard {shard}"])
        # merge in a FIXED host order (self.nodes iteration order), not
        # completion order — concurrent arrival must not change which
        # replica wins an equal-timestamp dedupe
        by_series: dict[bytes, tuple] = {}
        for host in self.nodes:
            if host not in responses:
                continue
            res, _ = responses[host]
            for sid, tags, dps in res:
                cur = by_series.get(sid)
                if cur is None:
                    by_series[sid] = (tags, {dp.timestamp: dp for dp in dps})
                else:
                    merged = cur[1]
                    for dp in dps:
                        merged.setdefault(dp.timestamp, dp)
        out = TaggedResult()
        out.exhaustive = not degraded
        for sid in sorted(by_series):
            tags, merged = by_series[sid]
            out.append((sid, tags, [merged[t] for t in sorted(merged)]))
        if limit is not None and len(out) > limit:
            del out[limit:]
        return out

    # --- index-only reads (QueryIDs / AggregateQuery fan-out) ---

    def query_ids(self, query, start_nanos: int, end_nanos: int,
                  limit: int | None = None):
        """Fan out the index query; union docs by id. Requires at least one
        live replica overall (index listings are best-effort breadth, like
        the reference's aggregate paths). Returns (docs, exhaustive):
        ``limit`` applies to the MERGED union (the per-node limit alone
        would let N nodes return N×limit series past the cost cap), and
        exhaustive is False when this or any node truncated."""
        docs: dict[bytes, tuple] = {}
        responded = 0
        exhaustive = True
        for node in self.nodes.values():
            if not node.is_up:
                exhaustive = False  # a down replica may hold unseen docs
                continue
            try:
                res = node.query_ids(self.namespace, query, start_nanos,
                                     end_nanos, limit=limit)
            except Exception:
                # an unreachable placed replica may hold docs no one else
                # returned — the union can no longer claim completeness
                exhaustive = False
                continue
            responded += 1
            if not res.get("exhaustive", True):
                exhaustive = False
            for did, fields in res.get("docs", []):
                docs.setdefault(
                    bytes(did), tuple((bytes(k), bytes(v)) for k, v in fields)
                )
        if responded == 0:
            raise ConsistencyError("query_ids", 0, 1, ["no replica responded"])
        out = [(did, docs[did]) for did in sorted(docs)]
        if limit is not None and len(out) > limit:
            out = out[:limit]
            exhaustive = False
        return out, exhaustive

    def aggregate_query(self, query, start_nanos: int, end_nanos: int,
                        field_filter=None):
        """Union of tag name → value sets across replicas."""
        out: dict[bytes, set[bytes]] = {}
        responded = 0
        for node in self.nodes.values():
            if not node.is_up:
                continue
            try:
                agg = node.aggregate_query(
                    self.namespace, query, start_nanos, end_nanos,
                    field_filter=field_filter,
                )
            except Exception:
                continue  # best-effort breadth; zero responders still raise
            responded += 1
            for k, vs in agg.items():
                out.setdefault(k, set()).update(vs)
        if responded == 0:
            raise ConsistencyError("aggregate_query", 0, 1, ["no replica responded"])
        return out

    # --- peer streaming (peers bootstrapper / repair seam) ---

    def stream_shard_from_peer(self, peer_id: str, shard: int):
        """FetchBootstrapBlocksFromPeers: raw series streams for one shard."""
        node = self.nodes[peer_id]
        return node.stream_shard(self.namespace, shard)
