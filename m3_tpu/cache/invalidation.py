"""Invalidation hooks: mutable/buffered data is never served stale.

Contract (mirrors M3's immutable-fileset model): the ONLY cacheable unit
is a sealed fileset block — buffers never enter the cache, and the read
path always overlays live buffer data on top of cached arrays (newest
wins). That makes the fileset entries correct by construction; these
hooks exist to (a) keep the contract airtight when buffered state for a
cached block changes (write/repair → conservative drop), (b) reclaim
bytes for entries that can never hit again (cold-flush supersession —
persist/fs/merger.go writes a NEW volume; tick expiry deletes filesets
past retention — shard.go:663 tickAndExpire), and (c) give operators a
full flush (clear).

The SAME hooks drive every resident tier: the decoded-block cache
(block_cache.py) and the HBM-resident compressed pool
(m3_tpu/resident/pool.py) expose the same targeted-invalidation surface
(invalidate_series_block / invalidate_block / clear), so one hook call
keeps both coherent — a written-to, superseded, or expired block is
never resident ANYWHERE.

Every hook is a no-op without targets, so storage wiring stays
unconditional.
"""

from __future__ import annotations


class CacheInvalidator:
    """Targeted invalidation surface over the node's resident tiers:
    the decoded-block cache and/or the compressed resident pool (each
    may be None)."""

    def __init__(self, cache=None, pool=None) -> None:
        self.cache = cache
        self.pool = pool

    def _targets(self):
        # len() without the target lock is a cheap hint: an empty tier
        # (the common case on the hot write path) skips its lock
        out = []
        if self.cache is not None and len(self.cache) > 0:
            out.append(self.cache)
        if self.pool is not None and len(self.pool) > 0:
            out.append(self.pool)
        return out

    def on_write(self, namespace: str, shard_id: int, series_id: bytes, block_start: int) -> int:
        """Shard.write / write_batch: a datapoint landed in (series, block).
        The buffered point overlays cached fileset arrays at read time, so
        entries are not stale — but drop them anyway: the contract is that
        a written-to block is re-merged from source on next read (and the
        resident scan must fall back to the streamed path, which sees the
        buffer overlay)."""
        dropped = 0
        for t in self._targets():
            dropped += t.invalidate_series_block(
                namespace, shard_id, series_id, block_start
            )
        return dropped

    def on_flush(self, namespace: str, shard_id: int, fileset_ids) -> int:
        """warm_flush/cold_flush: each flushed FilesetID supersedes every
        lower volume of its block (cold flush merges into a new volume);
        superseded entries can never hit again — reclaim their bytes."""
        targets = self._targets()
        dropped = 0
        for fid in fileset_ids:
            for t in targets:
                dropped += t.invalidate_block(
                    namespace, shard_id, fid.block_start, below_volume=fid.volume
                )
        return dropped

    def on_tick_expire(self, namespace: str, shard_id: int, block_starts) -> int:
        """Tick retention expiry: the fileset is deleted off disk."""
        targets = self._targets()
        dropped = 0
        for bs in block_starts:
            for t in targets:
                dropped += t.invalidate_block(namespace, shard_id, bs)
        return dropped

    def on_repair(self, namespace: str, shard_id: int, series_id: bytes, block_start: int) -> int:
        """Repair streamed+merged a block from a peer: same conservative
        drop as a write (repair points route through the write path, which
        already fires on_write per point; this hook covers the block once
        more so repaired blocks re-merge even when every streamed point was
        skipped as a cold-write reject)."""
        dropped = 0
        for t in self._targets():
            dropped += t.invalidate_series_block(
                namespace, shard_id, series_id, block_start
            )
        return dropped
