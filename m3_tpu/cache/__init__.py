"""Device-resident decoded-block cache.

The repeated-query analog of KV-cache residency management in an
inference stack: sealed immutable fileset blocks decode once, the
(times, values, valid) arrays stay device-placeable and hot, and a byte
budget (HBM-style cost accounting) evicts least-recently-used entries.
Mirrors M3's caching on the same path — the postings-list LRU
(src/dbnode/storage/index/postings_list_cache.go) and per-shard seeker
cache (persist/fs/seek_manager.go) — but for decoded datapoints, where
the scan-and-aggregate hot path spends its time.
"""

from .block_cache import BlockCache, BlockKey, DecodedBlock
from .invalidation import CacheInvalidator
from .policy import AdmissionPolicy, CacheOptions

__all__ = [
    "AdmissionPolicy",
    "BlockCache",
    "BlockKey",
    "CacheInvalidator",
    "CacheOptions",
    "DecodedBlock",
]
