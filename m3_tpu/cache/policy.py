"""Admission policy + configuration for the decoded-block cache.

Reference: M3 gates what its caches may hold — the postings-list cache
admits only immutable segments (postings_list_cache.go), the wired list
caps resident blocks (block/wired_list.go). Here admission is explicit
policy: only SEALED fileset blocks are cacheable (the caller enforces
that by construction — buffers never reach the cache), plus a minimum
decoded size (tiny blocks cost more in bookkeeping than re-decode) and
an optional namespace allowlist.

``CacheOptions`` is a plain dataclass, loadable through the YAML config
system (`m3_tpu/utils/config.py` ``loads_config``) like every other
service config block::

    cache:
      enabled: true
      max_bytes: 268435456
      min_block_bytes: 0
      namespaces: [default]
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class CacheOptions:
    """Decoded-block cache knobs (x/config-style dataclass schema).

    ``max_bytes`` is the byte budget for decoded arrays (HBM-style cost
    accounting: an entry costs the sum of its arrays' nbytes plus a fixed
    per-entry overhead). ``min_block_bytes`` rejects blocks whose decoded
    size is below the threshold. ``namespaces`` empty means all
    namespaces are cacheable."""

    enabled: bool = True
    max_bytes: int = 256 * 1024 * 1024
    min_block_bytes: int = 0
    namespaces: list = field(default_factory=list)

    def validate(self) -> None:
        from ..utils.config import ConfigError

        if self.max_bytes < 0:
            raise ConfigError("cache.max_bytes must be >= 0")
        if self.min_block_bytes < 0:
            raise ConfigError("cache.min_block_bytes must be >= 0")


class AdmissionPolicy:
    """Decides whether a decoded block may enter the cache."""

    def __init__(self, options: CacheOptions) -> None:
        self.options = options
        self._namespaces = frozenset(options.namespaces or ())

    def admit(self, key, nbytes: int) -> bool:
        """``key`` is a BlockKey; ``nbytes`` the entry's decoded cost."""
        o = self.options
        if not o.enabled or o.max_bytes <= 0:
            return False
        if nbytes > o.max_bytes:
            return False  # an entry larger than the whole budget
        if nbytes < o.min_block_bytes:
            return False
        if self._namespaces and key.namespace not in self._namespaces:
            return False
        return True
