"""Byte-budget LRU cache of decoded block arrays with read-through fetch.

Reference: M3 keeps repeated work off the hot read path with two caches —
the postings-list LRU (src/dbnode/storage/index/postings_list_cache.go:59)
and the per-shard seeker cache / wired list (persist/fs/seek_manager.go,
block/wired_list.go:77). Both key on immutable state. This cache is the
decoded-datapoint analog: one entry per sealed fileset block per series,
keyed (namespace, shard_id, series_id, block_start, volume), holding the
decoded ``times``/``values``/``valid`` ndarrays device-placeable and
ready for the vmapped aggregation kernels. The volume in the key makes
cold-flush supersession self-invalidating (a merged block goes out as a
NEW volume — persist/fs/merger.go); explicit hooks (invalidation.py)
reclaim superseded and expired entries' bytes eagerly.

Concurrency: ``get_or_decode`` is single-flight per key — concurrent
readers of the same cold block decode once, the rest wait on the
decoder's event and read the cached entry.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, NamedTuple

import numpy as np

from ..utils.instrument import DEFAULT as METRICS

# fixed per-entry bookkeeping cost added to the arrays' nbytes (key,
# OrderedDict slot, index sets) so many tiny entries can't blow past the
# budget on overhead alone
ENTRY_OVERHEAD_BYTES = 256


class BlockKey(NamedTuple):
    """Identity of one sealed, immutable decoded block."""

    namespace: str
    shard_id: int
    series_id: bytes
    block_start: int
    volume: int

    @property
    def series_key(self) -> tuple:
        return (self.namespace, self.shard_id, self.series_id, self.block_start)

    @property
    def block_key(self) -> tuple:
        return (self.namespace, self.shard_id, self.block_start)


class DecodedBlock:
    """Decoded arrays of one block: ``times`` i64, ``values`` f64,
    ``units`` u8, ``valid`` bool — the dense device-placeable layout the
    scan-and-aggregate kernels consume. Arrays are frozen (non-writeable)
    on construction: entries are shared across readers. ``valid`` is
    materialized lazily (a decode yields all-valid points; the mask only
    costs memory once a device-packing consumer asks for it) and counts
    toward ``nbytes`` only when passed explicitly."""

    __slots__ = ("times", "values", "units", "_valid", "nbytes")

    def __init__(self, times, values, units, valid=None) -> None:
        self.times = np.ascontiguousarray(times, np.int64)
        self.values = np.ascontiguousarray(values, np.float64)
        self.units = np.ascontiguousarray(units, np.uint8)
        self._valid = None if valid is None else np.ascontiguousarray(valid, bool)
        for arr in (self.times, self.values, self.units, self._valid):
            if arr is not None:
                arr.flags.writeable = False
        self.nbytes = (
            self.times.nbytes
            + self.values.nbytes
            + self.units.nbytes
            + (self._valid.nbytes if self._valid is not None else 0)
            + ENTRY_OVERHEAD_BYTES
        )

    @property
    def valid(self) -> np.ndarray:
        if self._valid is None:
            mask = np.ones(len(self.times), bool)
            mask.flags.writeable = False
            self._valid = mask
        return self._valid

    def __len__(self) -> int:
        return len(self.times)

    def triple(self) -> tuple:
        """(times, values, units) — the merge_segment_arrays input shape."""
        return (self.times, self.values, self.units)


class _UncacheableMarker:
    """Negative-cache sentinel: the block decoded to something the cache
    cannot hold (an annotated stream). Sealed blocks are immutable, so
    uncacheable is a durable property of the key — remembering it saves a
    full decode-and-discard on every subsequent read. Invalidation and
    volume supersession purge sentinels like any entry."""

    __slots__ = ()
    nbytes = ENTRY_OVERHEAD_BYTES

    def __len__(self) -> int:  # pragma: no cover - uniformity only
        return 0


UNCACHEABLE = _UncacheableMarker()


class BlockCache:
    """LRU of DecodedBlock entries under a byte budget."""

    def __init__(self, options=None, policy=None, registry=None) -> None:
        from .policy import AdmissionPolicy, CacheOptions

        self.options = options or CacheOptions()
        self.policy = policy or AdmissionPolicy(self.options)
        self._lock = threading.Lock()
        self._od: "OrderedDict[BlockKey, DecodedBlock]" = OrderedDict()
        # secondary indexes for O(1) targeted invalidation off the hot
        # write path: series_key/block_key -> live BlockKeys
        self._by_series: dict[tuple, set] = {}
        self._by_block: dict[tuple, set] = {}
        self._inflight: dict[BlockKey, threading.Event] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        reg = registry or METRICS
        self._m_hits = reg.counter("block_cache_hits_total", "decoded-block cache hits")
        self._m_misses = reg.counter("block_cache_misses_total", "decoded-block cache misses")
        self._m_evictions = reg.counter(
            "block_cache_evictions_total", "byte-budget LRU evictions"
        )
        self._m_invalidations = reg.counter(
            "block_cache_invalidations_total", "entries dropped by invalidation hooks"
        )
        self._g_bytes = reg.gauge("block_cache_bytes", "decoded bytes resident")
        self._g_entries = reg.gauge("block_cache_entries", "entries resident")

    # ---------- core ----------

    def get(self, key: BlockKey) -> DecodedBlock | None:
        with self._lock:
            entry = self._od.get(key)
            if entry is None:
                self.misses += 1
                self._m_misses.inc()
                return None
            self._od.move_to_end(key)
            self.hits += 1
            self._m_hits.inc()
            return None if entry is UNCACHEABLE else entry

    def get_or_decode(
        self, key: BlockKey, decode: Callable[[], "DecodedBlock | None"]
    ) -> DecodedBlock | None:
        """Read-through fetch: return the cached entry or run ``decode``
        exactly once per key across racing threads. ``decode`` returning
        None marks the block uncacheable (e.g. annotated streams) — the
        None propagates, and a negative sentinel is cached so later reads
        skip the decode-and-discard (sealed blocks are immutable; only
        invalidation or supersession can change the verdict)."""
        while True:
            with self._lock:
                entry = self._od.get(key)
                if entry is not None:
                    self._od.move_to_end(key)
                    self.hits += 1
                    self._m_hits.inc()
                    return None if entry is UNCACHEABLE else entry
                event = self._inflight.get(key)
                if event is None:
                    event = self._inflight[key] = threading.Event()
                    owner = True
                else:
                    owner = False
            if owner:
                try:
                    block = decode()
                    if block is not None:
                        self.put(key, block)
                    else:
                        self._mark_uncacheable(key)
                    return block
                finally:
                    with self._lock:
                        self._inflight.pop(key, None)
                        self.misses += 1
                        self._m_misses.inc()
                    event.set()
            # another thread is decoding this key: wait, then re-check (the
            # entry may have been rejected by admission — loop makes this
            # thread the next owner rather than spinning)
            event.wait()

    def put(self, key: BlockKey, block: DecodedBlock) -> bool:
        """Insert under admission policy + byte budget; True if resident."""
        if len(block) == 0:
            # an absent/empty series in a fileset: a resident marker per
            # (series, block, volume) would flood the LRU on sparse
            # workloads (each costs ENTRY_OVERHEAD_BYTES), while re-probing
            # absence is a cheap bloom-filter hit
            return False
        if not self.policy.admit(key, block.nbytes):
            return False
        with self._lock:
            resident = self._insert_locked(key, block)
            self._publish_gauges()
            return resident

    def _mark_uncacheable(self, key: BlockKey) -> None:
        """Negative-cache a key whose decode can't be held (sentinel;
        bypasses admission — overhead-only cost, no payload)."""
        with self._lock:
            self._insert_locked(key, UNCACHEABLE)
            self._publish_gauges()

    def _insert_locked(self, key: BlockKey, block) -> bool:
        old = self._od.pop(key, None)
        if old is not None:
            self._unindex(key, old)
            self.bytes -= old.nbytes
        self._od[key] = block
        self._index(key)
        self.bytes += block.nbytes
        while self.bytes > self.options.max_bytes and len(self._od) > 1:
            victim, gone = self._od.popitem(last=False)
            self._unindex(victim, gone)
            self.bytes -= gone.nbytes
            self.evictions += 1
            self._m_evictions.inc()
        if self.bytes > self.options.max_bytes:
            # the sole survivor is this entry itself and it busts the
            # budget (admit() bounds it by max_bytes, but a concurrent
            # options change could shrink the budget)
            self._od.pop(key, None)
            self._unindex(key, block)
            self.bytes -= block.nbytes
            self.evictions += 1
            self._m_evictions.inc()
            return False
        return True

    # ---------- invalidation surface (see invalidation.py for wiring) ----------

    def invalidate_series_block(
        self, namespace: str, shard_id: int, series_id: bytes, block_start: int
    ) -> int:
        """Drop every volume of one (series, block) — the write hook."""
        with self._lock:
            keys = self._by_series.pop(
                (namespace, shard_id, series_id, block_start), None
            )
            return self._drop_locked(keys)

    def invalidate_block(
        self, namespace: str, shard_id: int, block_start: int, below_volume=None
    ) -> int:
        """Drop a whole block's entries across series; ``below_volume``
        restricts to superseded volumes (cold-flush supersession)."""
        with self._lock:
            keys = self._by_block.get((namespace, shard_id, block_start))
            if keys is None:
                return 0
            if below_volume is not None:
                keys = {k for k in keys if k.volume < below_volume}
            return self._drop_locked(set(keys))

    def clear(self) -> int:
        with self._lock:
            n = len(self._od)
            self._od.clear()
            self._by_series.clear()
            self._by_block.clear()
            self.bytes = 0
            self.invalidations += n
            self._m_invalidations.inc(n)
            self._publish_gauges()
            return n

    def _drop_locked(self, keys) -> int:
        if not keys:
            return 0
        dropped = 0
        for key in keys:
            entry = self._od.pop(key, None)
            if entry is None:
                continue
            self._unindex(key, entry)
            self.bytes -= entry.nbytes
            dropped += 1
        self.invalidations += dropped
        self._m_invalidations.inc(dropped)
        self._publish_gauges()
        return dropped

    # ---------- bookkeeping ----------

    def _index(self, key: BlockKey) -> None:
        self._by_series.setdefault(key.series_key, set()).add(key)
        self._by_block.setdefault(key.block_key, set()).add(key)

    def _unindex(self, key: BlockKey, entry: DecodedBlock) -> None:
        for index, sub in (
            (self._by_series, key.series_key),
            (self._by_block, key.block_key),
        ):
            keys = index.get(sub)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del index[sub]

    def _publish_gauges(self) -> None:
        self._g_bytes.set(float(self.bytes))
        self._g_entries.set(float(len(self._od)))

    def __len__(self) -> int:
        return len(self._od)

    def __contains__(self, key: BlockKey) -> bool:
        with self._lock:
            return key in self._od

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "entries": len(self._od),
                "bytes": self.bytes,
                "max_bytes": self.options.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "hit_rate": (self.hits / lookups) if lookups else 0.0,
            }
