"""Synthetic series generation for benches/tests.

Reference counterpart: the integration data generators and m3nsch load-gen
datums (/root/reference/src/dbnode/integration/generate/,
src/m3nsch/datums/). Generates gauge-like series, encodes them with the CPU
M3TSZ encoder, and tiles them into a BatchedSegments matrix so large series
counts don't pay per-series Python encode cost.
"""

from __future__ import annotations

import numpy as np

from ..codec.m3tsz import encode_series
from ..segment.batched import BatchedSegments
from ..utils.xtime import Unit

NANOS = 1_000_000_000


def synthetic_streams(
    n_unique: int,
    n_points: int,
    start_nanos: int = 1_600_000_000 * NANOS,
    step_nanos: int = 10 * NANOS,
    seed: int = 0,
    kind: str = "gauge",
) -> list[bytes]:
    """Encode ``n_unique`` synthetic series of ``n_points`` datapoints each.

    kind:
      gauge  — random-walk floats with ~2 decimal places (int-optimizable)
      counter— monotonically increasing integer-ish values
      float  — full-precision floats (exercise the XOR path)
    """
    rng = np.random.default_rng(seed)
    ts = start_nanos + step_nanos * np.arange(n_points, dtype=np.int64)
    unit = Unit.SECOND if step_nanos % NANOS == 0 else Unit.MILLISECOND
    # Jitter in whole units of the encode unit (sub-unit deltas would be
    # truncated by timestamp normalization) so non-zero dod buckets are
    # actually exercised.
    jitter = rng.integers(-2, 3, size=(n_unique, n_points)) * unit.nanos()
    jitter[:, 0] = 0
    all_t = ts[None, :] + jitter
    if kind == "gauge":
        all_v = np.round(50 + np.cumsum(rng.normal(0, 1, (n_unique, n_points)), axis=1), 2)
    elif kind == "counter":
        all_v = np.cumsum(rng.integers(0, 100, (n_unique, n_points)), axis=1).astype(np.float64)
    else:
        all_v = rng.normal(0, 1, (n_unique, n_points))

    from .. import native

    if native.available():
        return native.encode_batch(
            all_t.ravel(),
            all_v.ravel(),
            np.full(n_unique, n_points, np.int32),
            default_unit=int(unit),
        )
    return [
        encode_series(all_t[i].tolist(), all_v[i].tolist(), unit=unit)
        for i in range(n_unique)
    ]


def tiled_batch(
    n_series: int,
    n_points: int,
    n_unique: int = 64,
    seed: int = 0,
    kind: str = "gauge",
) -> BatchedSegments:
    """A BatchedSegments of ``n_series`` rows built by tiling n_unique encoded
    streams — cheap way to build million-series batches for device benches."""
    streams = synthetic_streams(n_unique, n_points, seed=seed, kind=kind)
    base = BatchedSegments.from_streams(streams)
    reps = (n_series + n_unique - 1) // n_unique
    words = np.tile(base.words, (reps, 1))[:n_series]
    num_bits = np.tile(base.num_bits, reps)[:n_series]
    return BatchedSegments(words=words, num_bits=num_bits)
