"""Synthetic series generation for benches/tests.

Reference counterpart: the integration data generators and m3nsch load-gen
datums (/root/reference/src/dbnode/integration/generate/,
src/m3nsch/datums/). Generates gauge-like series, encodes them with the CPU
M3TSZ encoder, and tiles them into a BatchedSegments matrix so large series
counts don't pay per-series Python encode cost.
"""

from __future__ import annotations

import numpy as np

from ..codec.m3tsz import encode_series
from ..segment.batched import BatchedSegments
from ..utils.xtime import Unit

NANOS = 1_000_000_000


def synthetic_streams(
    n_unique: int,
    n_points: int,
    start_nanos: int = 1_600_000_000 * NANOS,
    step_nanos: int = 10 * NANOS,
    seed: int = 0,
    kind: str = "gauge",
) -> list[bytes]:
    """Encode ``n_unique`` synthetic series of ``n_points`` datapoints each.

    kind:
      gauge  — random-walk floats with ~2 decimal places (int-optimizable)
      counter— monotonically increasing integer-ish values
      float  — full-precision floats (exercise the XOR path)
    """
    rng = np.random.default_rng(seed)
    ts = start_nanos + step_nanos * np.arange(n_points, dtype=np.int64)
    unit = Unit.SECOND if step_nanos % NANOS == 0 else Unit.MILLISECOND
    # Jitter in whole units of the encode unit (sub-unit deltas would be
    # truncated by timestamp normalization) so non-zero dod buckets are
    # actually exercised.
    jitter = rng.integers(-2, 3, size=(n_unique, n_points)) * unit.nanos()
    jitter[:, 0] = 0
    all_t = ts[None, :] + jitter
    if kind == "gauge":
        all_v = np.round(50 + np.cumsum(rng.normal(0, 1, (n_unique, n_points)), axis=1), 2)
    elif kind == "counter":
        all_v = np.cumsum(rng.integers(0, 100, (n_unique, n_points)), axis=1).astype(np.float64)
    else:
        all_v = rng.normal(0, 1, (n_unique, n_points))

    from .. import native

    if native.available():
        return native.encode_batch(
            all_t.ravel(),
            all_v.ravel(),
            np.full(n_unique, n_points, np.int32),
            default_unit=int(unit),
        )
    return [
        encode_series(all_t[i].tolist(), all_v[i].tolist(), unit=unit)
        for i in range(n_unique)
    ]


def tiled_batch(
    n_series: int,
    n_points: int,
    n_unique: int = 64,
    seed: int = 0,
    kind: str = "gauge",
) -> BatchedSegments:
    """A BatchedSegments of ``n_series`` rows built by tiling n_unique encoded
    streams — cheap way to build million-series batches for device benches."""
    streams = synthetic_streams(n_unique, n_points, seed=seed, kind=kind)
    base = BatchedSegments.from_streams(streams)
    reps = (n_series + n_unique - 1) // n_unique
    words = np.tile(base.words, (reps, 1))[:n_series]
    num_bits = np.tile(base.num_bits, reps)[:n_series]
    return BatchedSegments(words=words, num_bits=num_bits)


def synthetic_mixed_streams(
    n_unique: int,
    n_points: int,
    start_nanos: int = 1_600_000_000 * NANOS,
    step_nanos: int = 10 * NANOS,
    seed: int = 0,
    frac_float: float = 0.30,
    frac_counter: float = 0.08,
    frac_tu_change: float = 0.05,
    frac_annotation: float = 0.02,
) -> list[bytes]:
    """A REALISTIC mixed workload (the honest bench input, vs the
    homogeneous all-int tiled gauges): by default 30% float-mode series
    (Gorilla XOR value path), 8% counters, 5% streams with a mid-stream
    time-unit change, 2% with annotations, remainder int-optimizable
    gauges with varied scale/precision (0-3 decimal places, amplitudes
    over 4 orders of magnitude) so value entropy resembles production
    metrics rather than 64 identical generators.

    The class sequence is deterministically shuffled so tiling N uniques
    to millions of series interleaves classes the way a real shard does."""
    rng = np.random.default_rng(seed)
    ts = start_nanos + step_nanos * np.arange(n_points, dtype=np.int64)
    unit = Unit.SECOND if step_nanos % NANOS == 0 else Unit.MILLISECOND
    jitter = rng.integers(-2, 3, size=(n_unique, n_points)) * unit.nanos()
    jitter[:, 0] = 0
    all_t = ts[None, :] + jitter

    n_float = int(n_unique * frac_float)
    n_counter = int(n_unique * frac_counter)
    n_tu = int(n_unique * frac_tu_change)
    n_ann = int(n_unique * frac_annotation)
    n_gauge = n_unique - n_float - n_counter - n_tu - n_ann
    kinds = (
        ["gauge"] * n_gauge + ["float"] * n_float + ["counter"] * n_counter
        + ["tu"] * n_tu + ["ann"] * n_ann
    )
    rng.shuffle(kinds)

    out: list[bytes] = []
    from ..codec.m3tsz import Encoder

    for i, kind in enumerate(kinds):
        t_row = all_t[i]
        if kind == "gauge":
            decimals = int(rng.integers(0, 4))
            scale = 10.0 ** rng.integers(0, 5)
            vals = np.round(
                scale * (1 + 0.02 * np.cumsum(rng.normal(0, 1, n_points))),
                decimals,
            )
        elif kind == "counter":
            vals = np.cumsum(rng.integers(0, 1000, n_points)).astype(np.float64)
        else:  # float / tu / ann: full-precision values (XOR path)
            vals = rng.lognormal(0, 2, n_points)
        if kind == "tu":
            # switch s -> ms halfway (time-unit-change marker + 64-bit dod)
            enc = Encoder(int(t_row[0]))
            half = n_points // 2
            for j in range(n_points):
                u = unit if j < half else Unit.MILLISECOND
                enc.encode(int(t_row[j]), float(vals[j]), unit=u)
            out.append(enc.stream())
        elif kind == "ann":
            enc = Encoder(int(t_row[0]))
            ann_at = set(rng.integers(0, n_points, 3).tolist())
            for j in range(n_points):
                enc.encode(
                    int(t_row[j]), float(vals[j]), unit=unit,
                    annotation=b"deploy" if j in ann_at else None,
                )
            out.append(enc.stream())
        else:
            out.append(encode_series(t_row.tolist(), vals.tolist(), unit=unit))
    return out
