"""Tag wire codec: length-prefixed canonical tag serialization.

The reference stores tags (and builds series IDs) with a length-prefixed
binary format — 2-byte magic, uint16 tag count, uint16-length-prefixed
name/value bytes per tag (/root/reference/src/x/serialize/encoder.go:55-191)
— precisely so tag bytes containing separator characters can never collide.
This module is that format for the TPU framework: the encoded form IS the
canonical series ID stored in filesets, the WAL, and the reverse index.

Tags are sorted by name on encode so equal tag sets map to equal IDs
regardless of insertion order (the reference sorts IDs upstream in
models.Tags / metric ID construction).
"""

from __future__ import annotations

import struct

Tags = tuple[tuple[bytes, bytes], ...]

MAGIC = 0x4D35  # own format marker; role of the reference's headerMagicNumber
_HDR = struct.Struct("<HH")  # magic, tag count
_LEN = struct.Struct("<H")

MAX_TAGS = 0xFFFF
MAX_LEN = 0xFFFF


def encode_tags(tags) -> bytes:
    """Serialize tags (any iterable of (name, value) byte pairs), sorted by
    name then value. Raises ValueError past the uint16 wire limits
    (encoder.go enforces TagSerializationLimits the same way)."""
    pairs = sorted((bytes(k), bytes(v)) for k, v in tags)
    if len(pairs) > MAX_TAGS:
        raise ValueError(f"too many tags: {len(pairs)}")
    parts = [_HDR.pack(MAGIC, len(pairs))]
    for k, v in pairs:
        if len(k) > MAX_LEN or len(v) > MAX_LEN:
            raise ValueError("tag name/value exceeds uint16 length limit")
        parts.append(_LEN.pack(len(k)))
        parts.append(k)
        parts.append(_LEN.pack(len(v)))
        parts.append(v)
    return b"".join(parts)


def decode_tags(buf: bytes) -> Tags:
    """Inverse of encode_tags (decoder.go). Raises ValueError on a malformed
    or truncated buffer."""
    if len(buf) < _HDR.size:
        raise ValueError("tag buffer too short")
    magic, count = _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad tag magic 0x{magic:04x}")
    pos = _HDR.size
    out = []
    for _ in range(count):
        if pos + _LEN.size > len(buf):
            raise ValueError("truncated tag name length")
        (klen,) = _LEN.unpack_from(buf, pos)
        pos += _LEN.size
        k = buf[pos : pos + klen]
        if len(k) != klen:
            raise ValueError("truncated tag name")
        pos += klen
        if pos + _LEN.size > len(buf):
            raise ValueError("truncated tag value length")
        (vlen,) = _LEN.unpack_from(buf, pos)
        pos += _LEN.size
        v = buf[pos : pos + vlen]
        if len(v) != vlen:
            raise ValueError("truncated tag value")
        pos += vlen
        out.append((k, v))
    if pos != len(buf):
        raise ValueError(f"trailing bytes after {count} tags")
    return tuple(out)


def is_tag_id(buf: bytes) -> bool:
    """Cheap check that a series ID is in the tag wire format."""
    return len(buf) >= _HDR.size and _HDR.unpack_from(buf, 0)[0] == MAGIC
