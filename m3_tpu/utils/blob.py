"""Atomic checksummed blob files.

Shared framing for small durable state files (buffer snapshots, persisted
index segments): ``<u32 magic><body><u32 crc32(magic+body)>`` written to a
temp file, fsync'd, then atomically os.replace'd into place. Readers get the
body back only if magic and CRC check out — a torn or corrupt file reads as
absent, which is the recovery semantic every caller wants (the reference's
digest/checkpoint pairing plays this role for filesets, persist/fs/fs.go).
"""

from __future__ import annotations

import os
import struct
import zlib

_U32 = struct.Struct("<I")


def write_atomic_checked_blob(path: str, magic: int, body: bytes) -> None:
    # lazy import: the storage fault seam (storage/faults.py) owns the
    # write-temp -> fsync -> rename primitive so injected disk faults
    # reach blob writers too; utils must not import storage at load time
    from ..storage.faults import DISK

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    head = _U32.pack(magic)
    blob = head + body + _U32.pack(zlib.crc32(head + body))
    DISK.write_durable(path, blob)


def read_checked_blob(path: str, magic: int) -> bytes | None:
    """Body bytes, or None when missing/torn/corrupt/wrong-magic."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if len(blob) < 2 * _U32.size:
        return None
    (got_magic,) = _U32.unpack_from(blob, 0)
    if got_magic != magic:
        return None
    body, (crc,) = blob[_U32.size : -_U32.size], _U32.unpack(blob[-_U32.size :])
    if zlib.crc32(blob[: -_U32.size]) != crc:
        return None
    return body
