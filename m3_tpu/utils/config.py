"""Config system: YAML → typed dataclass configs with env interpolation.

Reference: /root/reference/src/x/config/config.go — services load YAML with
``${ENV_VAR:default}`` expansion, strict unknown-key detection, and
validation, into per-service config structs (cmd/services/*/config). Here
the schema IS a dataclass tree: nested dataclasses map to nested mappings,
unknown keys raise, missing keys use dataclass defaults (required fields
without defaults raise).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, get_args, get_origin, get_type_hints

import yaml

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::([^}]*))?\}")


class ConfigError(ValueError):
    pass


def _interpolate(text: str) -> str:
    def repl(m: re.Match) -> str:
        name, default = m.group(1), m.group(2)
        val = os.environ.get(name)
        if val is None:
            if default is None:
                raise ConfigError(f"environment variable {name} is not set")
            return default
        return val

    return _ENV_RE.sub(repl, text)


def _coerce(value: Any, typ: Any, path: str) -> Any:
    if dataclasses.is_dataclass(typ):
        if value is None:
            value = {}
        if not isinstance(value, dict):
            raise ConfigError(f"{path}: expected a mapping, got {type(value).__name__}")
        return _build(typ, value, path)
    origin = get_origin(typ)
    if origin in (list, tuple):
        if value is None:
            return origin()
        if not isinstance(value, (list, tuple)):
            raise ConfigError(f"{path}: expected a list")
        (item_t, *_rest) = get_args(typ) or (Any,)
        out = [
            _coerce(v, item_t, f"{path}[{i}]") for i, v in enumerate(value)
        ]
        return tuple(out) if origin is tuple else out
    if origin is dict:
        return dict(value or {})
    # Optional[X] / unions: try each member
    if origin is not None and str(origin) in ("typing.Union", "<class 'types.UnionType'>"):
        last_err = None
        for member in get_args(typ):
            if member is type(None):
                if value is None:
                    return None
                continue
            try:
                return _coerce(value, member, path)
            except (ConfigError, TypeError, ValueError) as exc:
                last_err = exc
        raise ConfigError(f"{path}: no union member matched ({last_err})")
    if typ is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        raise ConfigError(f"{path}: expected a bool")
    if typ in (int, float, str):
        try:
            return typ(value)
        except (TypeError, ValueError):
            raise ConfigError(
                f"{path}: expected {typ.__name__}, got {value!r}"
            ) from None
    return value


def _build(cls, data: dict, path: str = ""):
    hints = get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ConfigError(
            f"{path or cls.__name__}: unknown keys {sorted(unknown)} "
            f"(known: {sorted(fields)})"
        )
    kwargs = {}
    for name, f in fields.items():
        sub_path = f"{path}.{name}" if path else name
        if name in data:
            kwargs[name] = _coerce(data[name], hints[name], sub_path)
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            raise ConfigError(f"{sub_path}: required key missing")
    obj = cls(**kwargs)
    validate = getattr(obj, "validate", None)
    if callable(validate):
        validate()
    return obj


def load_config(cls, path: str):
    """Read a YAML file into the dataclass ``cls`` with env interpolation."""
    with open(path) as f:
        text = f.read()
    return loads_config(cls, text)


def loads_config(cls, text: str):
    data = yaml.safe_load(_interpolate(text)) or {}
    if not isinstance(data, dict):
        raise ConfigError("top-level config must be a mapping")
    return _build(cls, data)
