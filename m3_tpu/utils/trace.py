"""In-process + cross-process request tracing: sampled spans in a bounded
ring buffer, with Dapper-style context propagation over the RPC layer.

Reference: the reference threads opentracing through its contexts
(/root/reference/src/x/context/context.go StartSampledTraceSpan,
src/dbnode/server wiring of jaeger/lightstep tracers) and exposes debug
dumps (x/debug). This framework keeps the same shape without external
backends: a process-wide sampled tracer whose finished spans land in a ring
buffer served by the coordinator's /debug/traces route and bundled into the
/debug/dump archive.

Usage::

    from m3_tpu.utils.trace import TRACER
    with TRACER.span("db.write", namespace=ns):
        ...

Spans nest through a thread-local stack: a span started while another is
open on the same thread becomes its child. Across threads or processes the
stack does NOT follow — extract the active context with
``TRACER.current_context()`` on the parent side and adopt it with
``TRACER.span_from_context(name, ctx)`` on the other side (the net/ RPC
layer does exactly this, so a query fanning out coordinator → dbnode
replicas produces ONE stitched trace).

Configuration (read once at import for the process-wide ``TRACER``):

    M3_TPU_TRACE_SAMPLE_RATE   root-span sample rate in [0, 1] (default 1.0)
    M3_TPU_TRACE_CAPACITY      finished-span ring capacity (default 4096)
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class Span:
    trace_id: int
    span_id: int
    parent_id: int | None
    name: str
    start_nanos: int
    end_nanos: int | None = None
    tags: dict = field(default_factory=dict)
    error: str | None = None

    @property
    def duration_nanos(self) -> int | None:
        if self.end_nanos is None:
            return None
        return self.end_nanos - self.start_nanos

    def to_dict(self) -> dict:
        return {
            "traceId": f"{self.trace_id:016x}",
            "spanId": f"{self.span_id:016x}",
            "parentId": f"{self.parent_id:016x}" if self.parent_id else None,
            "name": self.name,
            "startNanos": self.start_nanos,
            "durationNanos": self.duration_nanos,
            "tags": {k: str(v) for k, v in self.tags.items()},
            "error": self.error,
        }


class _ActiveSpan:
    """Context manager binding a span to the thread-local stack."""

    def __init__(self, tracer: "Tracer", span: Span | None) -> None:
        self.tracer = tracer
        self.span = span  # None = unsampled (no-op)

    def set_tag(self, key: str, value) -> "_ActiveSpan":
        if self.span is not None:
            self.span.tags[key] = value
        return self

    def __enter__(self) -> "_ActiveSpan":
        if self.span is not None:
            self.tracer._stack().append(self.span)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.span is None:
            return
        stack = self.tracer._stack()
        if stack and stack[-1] is self.span:
            stack.pop()
        self.span.end_nanos = time.time_ns()
        if exc is not None:
            self.span.error = f"{exc_type.__name__}: {exc}"
        self.tracer._record(self.span)


class Tracer:
    """Process tracer: sample_rate in [0, 1], ring buffer of finished spans.

    ``started``/``sampled`` counters and the span-id sequence are guarded by
    one lock — spans start on many threads concurrently (RPC handler
    threads, host-queue flushers), so the read-modify-writes must not race.
    Span ids count up from a random 62-bit base so ids minted by different
    PROCESSES joining one trace don't collide.
    """

    def __init__(self, sample_rate: float = 1.0, capacity: int = 4096) -> None:
        self.sample_rate = sample_rate
        self.finished: deque[Span] = deque(maxlen=capacity)
        self._ids = itertools.count(random.getrandbits(62) | 1)
        self._local = threading.local()
        self._lock = threading.Lock()
        self.started = 0
        self.sampled = 0

    @classmethod
    def from_env(cls) -> "Tracer":
        """Build a tracer from M3_TPU_TRACE_SAMPLE_RATE / M3_TPU_TRACE_CAPACITY
        (malformed values fall back to the defaults rather than killing the
        process at import)."""
        try:
            rate = float(os.environ.get("M3_TPU_TRACE_SAMPLE_RATE", "1.0"))
        except ValueError:
            rate = 1.0
        try:
            capacity = int(os.environ.get("M3_TPU_TRACE_CAPACITY", "4096"))
        except ValueError:
            capacity = 4096
        return cls(sample_rate=min(max(rate, 0.0), 1.0), capacity=max(capacity, 1))

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def active(self) -> bool:
        """Whether a sampled span is open on THIS thread (hot paths gate
        optional child spans on this so untraced operations pay nothing)."""
        return bool(self._stack())

    def current_context(self) -> dict | None:
        """Wire-propagatable context of the innermost active span, or None.

        The dict shape is what net/wire's inject/extract helpers carry:
        {"trace_id": int, "span_id": int, "sampled": bool}.
        """
        stack = self._stack()
        if not stack:
            return None
        top = stack[-1]
        return {"trace_id": top.trace_id, "span_id": top.span_id, "sampled": True}

    def span(self, name: str, **tags) -> _ActiveSpan:
        parent = self._stack()[-1] if self._stack() else None
        with self._lock:
            self.started += 1
            if parent is None and self.sample_rate < 1.0:
                if random.random() >= self.sample_rate:
                    return _ActiveSpan(self, None)
            self.sampled += 1
            span_id = next(self._ids)
        sp = Span(
            trace_id=parent.trace_id if parent else span_id,
            span_id=span_id,
            parent_id=parent.span_id if parent else None,
            name=name,
            start_nanos=time.time_ns(),
            tags=tags,
        )
        return _ActiveSpan(self, sp)

    def span_from_context(self, name: str, ctx: dict | None, **tags) -> _ActiveSpan:
        """Start a span whose parent is a REMOTE (or cross-thread) span.

        ``ctx`` is a dict from :meth:`current_context` carried over the wire;
        the new span joins that trace instead of rooting a new one, so the
        server side of an RPC stitches into the client's tree. ``ctx`` of
        None falls back to the normal local-parent path; an EXPLICITLY
        unsampled context (sampled=False) is a no-op — the upstream decided
        not to trace this request, and rooting a fresh local trace here
        would litter every downstream ring with orphan spans.
        """
        if ctx is None:
            return self.span(name, **tags)
        if not ctx.get("sampled", True):
            with self._lock:
                self.started += 1
            return _ActiveSpan(self, None)
        with self._lock:
            self.started += 1
            self.sampled += 1
            span_id = next(self._ids)
        sp = Span(
            trace_id=int(ctx["trace_id"]),
            span_id=span_id,
            parent_id=int(ctx["span_id"]),
            name=name,
            start_nanos=time.time_ns(),
            tags=tags,
        )
        return _ActiveSpan(self, sp)

    def _record(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)

    def dump(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            spans = list(self.finished)
        if limit is not None:
            spans = spans[-limit:] if limit > 0 else []
        return [s.to_dict() for s in spans]


# process-wide default (the reference hangs its tracer off instrument opts);
# sample rate / capacity configurable via M3_TPU_TRACE_* env vars
TRACER = Tracer.from_env()

# shared no-op span (what span() returns when unsampled): for callers that
# decide themselves not to trace something
NOOP_SPAN = _ActiveSpan(None, None)
