"""Instrumentation: process metrics registry with Prometheus exposition.

Reference: /root/reference/src/x/instrument/ — every service carries an
instrument.Options scope emitting counters/gauges/timers about itself
(tally → Prometheus). Here: a Registry of Counter/Gauge/Histogram handles
with label sets, rendered in the Prometheus text format by services'
/metrics endpoints (coordinator HTTP route, dbnode RPC op).
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
from dataclasses import dataclass, field


def _escape_label_value(v) -> str:
    """Prometheus text exposition label-value escaping: backslash, double
    quote, and line feed must be escaped (exposition_formats.md) — regex
    matchers used as label values otherwise corrupt the whole scrape."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_exemplar(ex: tuple) -> str:
    """OpenMetrics exemplar suffix for a bucket sample:
    `` # {trace_id="...",tenant="..."} value timestamp`` — the trace-ID
    link the 0.0.4 format can only serve out-of-band via
    /debug/exemplars. ``ex`` is Histogram.exemplars' tuple form
    (value, trace_id, unix_nanos, tenant)."""
    v, trace_id, unix_nanos, tenant = ex
    labels = [("trace_id", trace_id)]
    if tenant is not None:
        labels.append(("tenant", tenant))
    return f" # {_fmt_labels(tuple(labels))} {v} {unix_nanos / 1e9:.9f}"


class Counter:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self._v = v

    def add(self, n: float) -> None:
        """Relative adjust (in-flight style gauges): must not lose updates
        under concurrent RPC handler threads."""
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10
)


class Histogram:
    def __init__(self, buckets=DEFAULT_BUCKETS) -> None:
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        # bucket index -> (value, trace_id, unix_nanos, tenant): the LAST
        # traced observation per bucket (OpenMetrics-exemplar role) — a
        # slow bucket links straight to its stitched trace in
        # /debug/traces and its /debug/slow_queries record, and carries
        # the tenant the observation was attributed to. Kept out of the
        # text exposition (the 0.0.4 format has no exemplar grammar;
        # tools/check_metrics validates every line) — served by collect()
        # and /debug/exemplars.
        self.exemplars: dict[int, tuple[float, str, int, str | None]] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, trace_id: str | None = None,
                tenant: str | None = None) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            self.counts[i] += 1
            self.sum += v
            self.total += 1
            if trace_id is not None:
                self.exemplars[i] = (v, trace_id, time.time_ns(), tenant)

    def snapshot(self) -> tuple[list[int], float, int]:
        """(counts, sum, total) read atomically vs concurrent observe() —
        exposition must not report a count/sum pair from different instants."""
        with self._lock:
            return list(self.counts), self.sum, self.total

    def exemplar_rows(self) -> list[dict]:
        """Exemplars as rows keyed by the bucket's ``le`` bound."""
        with self._lock:
            items = sorted(self.exemplars.items())
        out = []
        for i, (v, tid, ts, tenant) in items:
            le = self.buckets[i] if i < len(self.buckets) else float("inf")
            row = {"le": le, "value": v, "traceId": tid, "timeUnixNanos": ts}
            if tenant is not None:
                row["tenant"] = tenant
            out.append(row)
        return out


@dataclass
class _Family:
    kind: str  # counter | gauge | histogram
    help: str
    children: dict = field(default_factory=dict)  # labels tuple -> metric


class Registry:
    """tally.Scope-equivalent: named metric families with label children."""

    def __init__(self, prefix: str = "") -> None:
        self.prefix = prefix
        self._fams: dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _family(self, name: str, kind: str, help_: str) -> _Family:
        with self._lock:
            fam = self._fams.get(name)
            if fam is None:
                fam = _Family(kind, help_)
                self._fams[name] = fam
            elif fam.kind != kind:
                raise ValueError(f"metric {name} already registered as {fam.kind}")
            return fam

    def _child(self, name: str, kind: str, help_: str, labels: dict | None, ctor):
        fam = self._family(name, kind, help_)
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            child = fam.children.get(key)
            if child is None:
                child = ctor()
                fam.children[key] = child
            return child

    def counter(self, name: str, help: str = "", labels: dict | None = None) -> Counter:
        return self._child(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", labels: dict | None = None) -> Gauge:
        return self._child(name, "gauge", help, labels, Gauge)

    def histogram(
        self, name: str, help: str = "", labels: dict | None = None, buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._child(
            name, "histogram", help, labels, lambda: Histogram(buckets)
        )

    def collect(self) -> dict:
        """Structured snapshot of every family — the machine-readable
        sibling of :meth:`expose` (bench.py's metrics JSON line and
        tools/check_metrics.py consume this instead of re-parsing text).

        Returns {name: {"kind", "help", "children": [{"labels", ...}]}}
        where counter/gauge children carry {"value"} and histogram children
        {"sum", "count", "buckets": [[le, cumulative_count], ...]}.
        """
        with self._lock:
            fams = {
                n: (f.kind, f.help, dict(f.children))
                for n, f in sorted(self._fams.items())
            }
        out: dict = {}
        for name, (kind, help_, children) in fams.items():
            rows = []
            for labels, m in sorted(children.items()):
                row: dict = {"labels": dict(labels)}
                if kind in ("counter", "gauge"):
                    row["value"] = m.value
                else:
                    counts, h_sum, h_total = m.snapshot()
                    acc, buckets = 0, []
                    for b, c in zip(m.buckets, counts):
                        acc += c
                        buckets.append([float(b), acc])
                    buckets.append([float("inf"), h_total])
                    row.update(sum=h_sum, count=h_total, buckets=buckets)
                    exemplars = m.exemplar_rows()
                    if exemplars:
                        row["exemplars"] = exemplars
                rows.append(row)
            out[f"{self.prefix}{name}"] = {
                "kind": kind, "help": help_, "children": rows
            }
        return out

    def expose_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition (``/metrics`` content
        negotiation: ``Accept: application/openmetrics-text``).

        Differences from :meth:`expose` the spec mandates:

        - a counter FAMILY is named without the ``_total`` suffix in its
          HELP/TYPE lines while its sample keeps it (``# TYPE x counter``
          + ``x_total 1``) — our counter families are all registered with
          the suffix, so it is stripped for the metadata lines;
        - histogram bucket samples carry their exemplars inline
          (``... # {trace_id="..."} value timestamp``) — the trace-ID
          exemplars the 0.0.4 format can only serve via /debug/exemplars;
        - the exposition ends with the mandatory ``# EOF`` terminator
          (its absence is how a consumer detects a truncated scrape).
        """
        lines = []
        with self._lock:
            fams = {
                n: (f.kind, f.help, dict(f.children))
                for n, f in sorted(self._fams.items())
            }
        for name, (kind, help_, children) in fams.items():
            full = f"{self.prefix}{name}"
            fam = full
            if kind == "counter" and fam.endswith("_total"):
                fam = fam[: -len("_total")]
            if help_:
                lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {kind}")
            for labels, m in sorted(children.items()):
                ls = _fmt_labels(labels)
                if kind == "counter":
                    lines.append(f"{fam}_total{ls} {m.value}")
                elif kind == "gauge":
                    lines.append(f"{fam}{ls} {m.value}")
                else:
                    counts, h_sum, h_total = m.snapshot()
                    with m._lock:
                        exemplars = dict(m.exemplars)
                    acc = 0
                    for i, (b, c) in enumerate(zip(m.buckets, counts)):
                        acc += c
                        lb = tuple(list(labels) + [("le", repr(float(b)))])
                        line = f"{fam}_bucket{_fmt_labels(lb)} {acc}"
                        ex = exemplars.get(i)
                        if ex is not None:
                            line += _fmt_exemplar(ex)
                        lines.append(line)
                    lb = tuple(list(labels) + [("le", "+Inf")])
                    line = f"{fam}_bucket{_fmt_labels(lb)} {h_total}"
                    ex = exemplars.get(len(m.buckets))
                    if ex is not None:
                        line += _fmt_exemplar(ex)
                    lines.append(line)
                    lines.append(f"{fam}_sum{ls} {h_sum}")
                    lines.append(f"{fam}_count{ls} {h_total}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            fams = {
                n: (f.kind, f.help, dict(f.children))
                for n, f in sorted(self._fams.items())
            }
        for name, (kind, help_, children) in fams.items():
            full = f"{self.prefix}{name}"
            if help_:
                lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {kind}")
            for labels, m in sorted(children.items()):
                ls = _fmt_labels(labels)
                if kind in ("counter", "gauge"):
                    lines.append(f"{full}{ls} {m.value}")
                else:
                    counts, h_sum, h_total = m.snapshot()
                    acc = 0
                    for b, c in zip(m.buckets, counts):
                        acc += c
                        lb = tuple(list(labels) + [("le", repr(float(b)))])
                        lines.append(f"{full}_bucket{_fmt_labels(lb)} {acc}")
                    lb = tuple(list(labels) + [("le", "+Inf")])
                    lines.append(f"{full}_bucket{_fmt_labels(lb)} {h_total}")
                    lines.append(f"{full}_sum{ls} {h_sum}")
                    lines.append(f"{full}_count{ls} {h_total}")
        return "\n".join(lines) + "\n"


# the process-default registry (instrument.NewOptions default scope)
DEFAULT = Registry(prefix="m3tpu_")


class JitTracker:
    """JAX hot-path compile observability: first call with an unseen static
    signature is a jit cache miss, so its wall time ≈ compile time (jax
    dispatch blocks on compilation; execution itself is async and cheap to
    dispatch). Feeds m3tpu_jit_compiles_total / m3tpu_jit_compile_seconds_total
    {kernel=...} so BENCH rounds can attribute warmup cost to the right
    kernel without importing jax here.

    Usage::

        _JIT = JitTracker("temporal_fused")
        with _JIT.track((funcs, values.shape, window)):
            out = _fused_call(...)
    """

    def __init__(self, kernel: str, registry: Registry | None = None) -> None:
        reg = registry or DEFAULT
        self.kernel = kernel
        self._compiles = reg.counter(
            "jit_compiles_total", "jit cache misses", {"kernel": kernel}
        )
        self._seconds = reg.counter(
            "jit_compile_seconds_total",
            "wall seconds spent in first-call jit compilation",
            {"kernel": kernel},
        )
        self._seen: set = set()
        self._lock = threading.Lock()

    def track(self, key):
        return _JitCall(self, key)

    def _observe(self, key, elapsed: float) -> bool:
        """Record a first-call compile; returns whether THIS call was the
        first sighting of ``key`` (i.e. its wall time is compile time)."""
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
        self._compiles.inc()
        self._seconds.inc(elapsed)
        return True


class _JitCall:
    def __init__(self, tracker: JitTracker, key) -> None:
        self.tracker = tracker
        self.key = key

    def __enter__(self) -> "_JitCall":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.tracker._observe(self.key, time.perf_counter() - self._t0)


# device-seconds attribution hook: query/tenants.py installs a callable
# ``(kernel, seconds)`` invoked for every SAMPLED, non-compile profiled
# dispatch, charging device time to the tenant context active on the
# dispatching thread. A settable seam (not an import) because this module
# sits below the query layer — utils must not import m3_tpu.query.
_KERNEL_ATTRIBUTION = None


def set_kernel_attribution(fn) -> None:
    global _KERNEL_ATTRIBUTION
    _KERNEL_ATTRIBUTION = fn


# per-query device-dispatch counter hook: query/stats.py installs a
# callable ``(kernel)`` invoked for EVERY profiled kernel dispatch
# (sampled or not), charging it to the query record active on the
# dispatching thread — the seam the one-dispatch fused query pipeline's
# acceptance check counts through. Same settable-seam shape as
# _KERNEL_ATTRIBUTION: utils must not import m3_tpu.query.
_DISPATCH_COUNTER = None


def set_dispatch_counter(fn) -> None:
    global _DISPATCH_COUNTER
    _DISPATCH_COUNTER = fn


# kernel dispatch latencies span ~10µs (a warm tiny batch on CPU) to whole
# seconds (a cold 50M-series scan): finer low end than the RPC buckets
KERNEL_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _env_sample_rate() -> float:
    """M3_TPU_PROFILE_SAMPLE_RATE in [0, 1]; default 0 (profiling off —
    a sampled dispatch pays a block_until_ready, so the fleet default is
    zero-overhead and the knob is explicit)."""
    try:
        rate = float(os.environ.get("M3_TPU_PROFILE_SAMPLE_RATE", "0"))
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def _env_cost_flag() -> bool | None:
    """M3_TPU_PROFILE_COST: force HLO cost capture on ("1") or off ("0")
    regardless of the sampling rate; unset (None) defers to 'capture iff
    the profiler samples' (cost capture pays one extra AOT lower+compile
    per signature, so it follows the same explicit-opt-in as sampling)."""
    raw = os.environ.get("M3_TPU_PROFILE_COST", "")
    if raw == "1":
        return True
    if raw == "0":
        return False
    return None


class KernelProfiler(JitTracker):
    """Device-tier dispatch observability: JitTracker's compile attribution
    plus SAMPLED wall-time profiles of every kernel dispatch.

    JAX dispatch is async — wall time around the call measures Python
    dispatch, not device work — so a profiled sample bounds the dispatch
    with ``jax.block_until_ready`` on the result and records the whole
    span in ``m3tpu_kernel_dispatch_seconds{kernel=...}``. Sampling is
    DETERMINISTIC (dispatch ``n`` is sampled iff ``floor(n·rate)`` advances
    over ``floor((n−1)·rate)``), so profiles are reproducible run to run
    and exactly ``rate`` of dispatches pay the sync. First-call compiles
    are excluded from the dispatch histogram — their wall time is XLA
    compilation and lands in the existing jit_compile counters instead.

    Usage::

        _PROF = KernelProfiler("m3tsz_decode")
        with _PROF.dispatch((words.shape, max_points)) as d:
            d.done(decode_batched(...))
    """

    def __init__(self, kernel: str, registry: Registry | None = None,
                 sample_rate: float | None = None,
                 capture_costs: bool | None = None) -> None:
        super().__init__(kernel, registry=registry)
        reg = registry or DEFAULT
        self.sample_rate = (
            _env_sample_rate() if sample_rate is None
            else min(max(float(sample_rate), 0.0), 1.0)
        )
        # HLO cost capture (continuous profiling's device tier): on when
        # the profiler samples, force-on/off via M3_TPU_PROFILE_COST=1/0
        # — decided ONCE at construction so tests that poke sample_rate
        # at runtime don't surprise-pay the extra AOT compile
        if capture_costs is None:
            env_flag = _env_cost_flag()
            capture_costs = (
                env_flag if env_flag is not None else self.sample_rate > 0.0
            )
        self.capture_costs = bool(capture_costs)
        labels = {"kernel": kernel}
        self._dispatches = reg.counter(
            "kernel_dispatches_total", "kernel dispatches", labels
        )
        self._hist = reg.histogram(
            "kernel_dispatch_seconds",
            "block_until_ready-bounded wall time of SAMPLED kernel "
            "dispatches (M3_TPU_PROFILE_SAMPLE_RATE; compiles excluded)",
            labels,
            buckets=KERNEL_BUCKETS,
        )
        self._g_flops = reg.gauge(
            "kernel_flops",
            "XLA cost-analysis FLOPs of this kernel's most recent "
            "compilation (Compiled.cost_analysis; with dispatch-seconds "
            "and bytes this turns device time into work done)",
            labels,
        )
        self._g_bytes_accessed = reg.gauge(
            "kernel_bytes_accessed",
            "XLA cost-analysis bytes accessed of this kernel's most "
            "recent compilation",
            labels,
        )
        self._m_cost_captures = reg.counter(
            "kernel_cost_captures_total",
            "HLO cost analyses captured (once per compilation signature)",
            labels,
        )
        self._m_cost_errors = reg.counter(
            "kernel_cost_errors_total",
            "cost-analysis captures that failed (backend without cost "
            "analysis, AOT path unavailable) — capture is best-effort "
            "and never breaks a dispatch",
            labels,
        )
        self._n = 0  # dispatch sequence (guarded by JitTracker._lock)
        self._costs: dict = {}  # compilation key -> {"flops", "bytes_accessed"}
        self._cost_seen: set = set()

    def _next_sampled(self) -> bool:
        rate = self.sample_rate
        with self._lock:
            self._n += 1
            n = self._n
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        return math.floor(n * rate) > math.floor((n - 1) * rate)

    def dispatch(self, key=None, cost=None) -> "_Dispatch":
        """``cost``: optional ``(jitted_fn, args, kwargs)`` — when this
        dispatch turns out to be the first-call compile of ``key`` and
        cost capture is on, the compiled executable's HLO cost analysis
        is recorded via :meth:`capture_cost`."""
        return _Dispatch(self, key, cost)

    def capture_cost(self, key, fn, *args, **kwargs):
        """Record ``fn``'s compiled HLO cost analysis ONCE per
        compilation ``key``: ``fn.lower(*args).compile().cost_analysis()``
        (the jax AOT path — one extra trace+compile per signature, which
        is why capture follows the profiling opt-in). Tolerant of
        backends without cost analysis (errors counted, never raised).
        Returns the ``{"flops", "bytes_accessed"}`` dict or None."""
        if not self.capture_costs:
            return None
        with self._lock:
            if key in self._cost_seen:
                return self._costs.get(key)
            self._cost_seen.add(key)
        # the AOT lower/compile runs OUTSIDE the lock (M3L001 discipline:
        # an XLA compile under a lock stalls every concurrent dispatch)
        try:
            analysis = fn.lower(*args, **kwargs).compile().cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            if analysis is None:
                analysis = {}
            cost = {
                "flops": float(analysis.get("flops", 0.0)),
                "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
            }
        except Exception:
            self._m_cost_errors.inc()
            return None
        with self._lock:
            self._costs[key] = cost
        self._g_flops.set(cost["flops"])
        self._g_bytes_accessed.set(cost["bytes_accessed"])
        self._m_cost_captures.inc()
        return cost

    def cost_analysis(self) -> dict:
        """Captured per-compilation costs, keyed by the dispatch key's
        string form (the debug-surface shape)."""
        with self._lock:
            return {str(k): dict(v) for k, v in self._costs.items()}


class _Dispatch:
    """One profiled kernel dispatch; call ``done(result)`` with the device
    output so a sampled dispatch can block on it."""

    __slots__ = ("profiler", "key", "cost", "sampled", "result", "_t0")

    def __init__(self, profiler: KernelProfiler, key, cost=None) -> None:
        self.profiler = profiler
        self.key = key
        self.cost = cost  # (jitted_fn, args, kwargs) for HLO cost capture
        self.sampled = profiler._next_sampled()
        self.result = None

    def done(self, result):
        self.result = result
        return result

    def __enter__(self) -> "_Dispatch":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return
        prof = self.profiler
        prof._dispatches.inc()
        counter = _DISPATCH_COUNTER
        if counter is not None:
            counter(prof.kernel)
        compiled = False
        if self.key is not None:
            compiled = prof._observe(self.key, time.perf_counter() - self._t0)
        if compiled and self.cost is not None:
            # first sighting of this signature = the compile just
            # happened: capture its HLO cost analysis once (no-op when
            # cost capture is off)
            fn, args, kwargs = self.cost
            prof.capture_cost(self.key, fn, *args, **(kwargs or {}))
        if self.sampled and not compiled:
            if self.result is not None:
                try:
                    import jax

                    jax.block_until_ready(self.result)
                except ImportError:  # host-only result: nothing to sync
                    pass
            elapsed = time.perf_counter() - self._t0
            prof._hist.observe(elapsed)
            hook = _KERNEL_ATTRIBUTION
            if hook is not None:
                hook(prof.kernel, elapsed)
