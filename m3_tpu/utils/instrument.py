"""Instrumentation: process metrics registry with Prometheus exposition.

Reference: /root/reference/src/x/instrument/ — every service carries an
instrument.Options scope emitting counters/gauges/timers about itself
(tally → Prometheus). Here: a Registry of Counter/Gauge/Histogram handles
with label sets, rendered in the Prometheus text format by services'
/metrics endpoints (coordinator HTTP route, dbnode RPC op).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    def __init__(self) -> None:
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self) -> float:
        return self._v


DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10
)


class Histogram:
    def __init__(self, buckets=DEFAULT_BUCKETS) -> None:
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            self.counts[i] += 1
            self.sum += v
            self.total += 1


@dataclass
class _Family:
    kind: str  # counter | gauge | histogram
    help: str
    children: dict = field(default_factory=dict)  # labels tuple -> metric


class Registry:
    """tally.Scope-equivalent: named metric families with label children."""

    def __init__(self, prefix: str = "") -> None:
        self.prefix = prefix
        self._fams: dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _family(self, name: str, kind: str, help_: str) -> _Family:
        with self._lock:
            fam = self._fams.get(name)
            if fam is None:
                fam = _Family(kind, help_)
                self._fams[name] = fam
            elif fam.kind != kind:
                raise ValueError(f"metric {name} already registered as {fam.kind}")
            return fam

    def _child(self, name: str, kind: str, help_: str, labels: dict | None, ctor):
        fam = self._family(name, kind, help_)
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            child = fam.children.get(key)
            if child is None:
                child = ctor()
                fam.children[key] = child
            return child

    def counter(self, name: str, help: str = "", labels: dict | None = None) -> Counter:
        return self._child(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", labels: dict | None = None) -> Gauge:
        return self._child(name, "gauge", help, labels, Gauge)

    def histogram(
        self, name: str, help: str = "", labels: dict | None = None, buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._child(
            name, "histogram", help, labels, lambda: Histogram(buckets)
        )

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            fams = {
                n: (f.kind, f.help, dict(f.children))
                for n, f in sorted(self._fams.items())
            }
        for name, (kind, help_, children) in fams.items():
            full = f"{self.prefix}{name}"
            if help_:
                lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} {kind}")
            for labels, m in sorted(children.items()):
                ls = _fmt_labels(labels)
                if kind in ("counter", "gauge"):
                    lines.append(f"{full}{ls} {m.value}")
                else:
                    acc = 0
                    for b, c in zip(m.buckets, m.counts):
                        acc += c
                        lb = tuple(list(labels) + [("le", repr(float(b)))])
                        lines.append(f"{full}_bucket{_fmt_labels(lb)} {acc}")
                    lb = tuple(list(labels) + [("le", "+Inf")])
                    lines.append(f"{full}_bucket{_fmt_labels(lb)} {m.total}")
                    lines.append(f"{full}_sum{ls} {m.sum}")
                    lines.append(f"{full}_count{ls} {m.total}")
        return "\n".join(lines) + "\n"


# the process-default registry (instrument.NewOptions default scope)
DEFAULT = Registry(prefix="m3tpu_")
