"""Fixed-rate scheduling with deterministic phase jitter.

Periodic fleet loops (the self-scrape collector, the ruler's per-group
evaluation) must not drift and must not align: a ``stop.wait(interval)``
loop accumulates per-iteration work time into its period (N scrapes of
50ms work at a 10s interval lag a full tick behind after ~200 iterations),
and every process waking at ``t0 + k*interval`` with the same t0 phase
thundering-herds the shared write path once per interval fleet-wide.

:class:`FixedRateTicker` fixes both: ticks fire at the absolute monotonic
instants ``start + phase + k*interval`` (work time eats into the wait, not
the period), and ``phase`` is a DETERMINISTIC per-instance fraction of the
interval — hashed from a caller-supplied key (instance id, group name) so
a restarted process keeps its slot and the fleet's ticks spread uniformly
over the interval instead of stacking.

A loop that falls more than a full interval behind (a long GC pause, a
stalled sink) SKIPS the missed ticks rather than firing them back-to-back
— catching up by bursting is exactly the herd the phase spread prevents —
and reports how many were skipped so callers can count them loudly.
"""

from __future__ import annotations

import threading
import time

from .hash import murmur3_32

# The floor for any periodic loop that STORES series about the fleet
# (self-scrape collector, ruler group evaluation, SLO status/probes).
# Stored timestamps ride the m3tsz SECOND-unit delta encoding, so two
# samples of one series closer than 1s collapse onto the same stored
# timestamp — the series stays queryable but every rate()/increase()
# over it flattens, which silently falsifies exactly the derived
# signals (error rates, burn rates) these loops exist to produce.
# Config loaders reject sub-second intervals LOUDLY against this
# constant instead of degrading; loops that never store series
# (health probes, failure detectors) are exempt.
MIN_TELEMETRY_INTERVAL_SECS = 1.0


def check_telemetry_interval(interval: float, what: str) -> float:
    """Validate a stored-telemetry loop interval at config load.

    Returns the interval; raises ``ValueError`` naming the caller's
    config knob when ``interval`` is positive but under the m3tsz
    second-unit floor (see :data:`MIN_TELEMETRY_INTERVAL_SECS`)."""
    iv = float(interval)
    if 0 < iv < MIN_TELEMETRY_INTERVAL_SECS:
        raise ValueError(
            f"{what} interval {iv!r}s is below the "
            f"{MIN_TELEMETRY_INTERVAL_SECS:g}s floor: stored timestamps "
            "ride m3tsz SECOND-unit deltas, so sub-second samples "
            "collapse onto one stored timestamp and flatten every "
            "rate() derived from this telemetry"
        )
    return iv


def phase_fraction(key: str) -> float:
    """Deterministic jitter fraction in [0, 1) for a scheduling key.

    murmur3 (the shard hash — stable across processes and runs, unlike
    Python's randomized ``hash``) of the key, scaled to a fraction: the
    same instance always lands on the same phase, and distinct instances
    spread ~uniformly."""
    return (murmur3_32(key.encode("utf-8", "replace")) % (1 << 20)) / float(1 << 20)


class FixedRateTicker:
    """Absolute-schedule tick source for a periodic daemon loop.

    Usage::

        ticker = FixedRateTicker(interval, phase_key=instance, stop=stop_evt)
        while True:
            stopped, missed = ticker.wait_next()
            if stopped:
                break
            if missed:
                missed_counter.inc(missed)
            do_work()

    ``clock`` is injectable (monotonic seconds) for tests; the stop event
    doubles as the wait primitive so ``stop.set()`` interrupts a sleeping
    loop immediately.
    """

    def __init__(
        self,
        interval: float,
        phase_key: str = "",
        stop: threading.Event | None = None,
        clock=time.monotonic,
        jitter: bool = True,
    ) -> None:
        if interval <= 0:
            raise ValueError(f"interval must be positive, got {interval!r}")
        self.interval = float(interval)
        self.stop = stop if stop is not None else threading.Event()
        self.clock = clock
        self.phase = (
            phase_fraction(phase_key) * self.interval if jitter else 0.0
        )
        self._start = self.clock()
        self._k = 0  # last fired tick index

    def next_deadline(self) -> float:
        """Absolute (monotonic) instant of the next scheduled tick."""
        return self._start + self.phase + (self._k + 1) * self.interval

    def wait_next(self) -> tuple[bool, int]:
        """Block until the next scheduled tick (or stop). Returns
        ``(stopped, missed)`` where ``missed`` counts whole intervals
        skipped because the loop fell behind schedule."""
        self._k += 1
        target = self._start + self.phase + self._k * self.interval
        now = self.clock()
        missed = 0
        if now > target:
            missed = int((now - target) // self.interval)
            if missed:
                self._k += missed
                target = self._start + self.phase + self._k * self.interval
        delay = max(0.0, target - now)
        stopped = self.stop.wait(delay) if delay > 0 else self.stop.is_set()
        return bool(stopped), missed
