"""64-bit integer/float bit twiddling used throughout the codec.

Parity with reference helpers in /root/reference/src/dbnode/encoding/encoding.go
(NumSig, LeadingAndTrailingZeros, SignExtend) plus float64<->uint64 bit casts.
All functions operate on plain Python ints masked to 64 bits.
"""

from __future__ import annotations

import struct

MASK64 = (1 << 64) - 1


def float_to_bits(v: float) -> int:
    """math.Float64bits: IEEE-754 bit pattern of a float64 as uint64."""
    return struct.unpack("<Q", struct.pack("<d", v))[0]


def bits_to_float(b: int) -> float:
    """math.Float64frombits."""
    return struct.unpack("<d", struct.pack("<Q", b & MASK64))[0]


def num_sig(v: int) -> int:
    """Number of significant bits in a uint64 (encoding.go NumSig)."""
    return (v & MASK64).bit_length()


def leading_zeros64(v: int) -> int:
    v &= MASK64
    return 64 - v.bit_length()


def trailing_zeros64(v: int) -> int:
    v &= MASK64
    if v == 0:
        return 0  # matches LeadingAndTrailingZeros(0) == (64, 0)
    return (v & -v).bit_length() - 1


def leading_and_trailing_zeros(v: int) -> tuple[int, int]:
    v &= MASK64
    if v == 0:
        return 64, 0
    return leading_zeros64(v), trailing_zeros64(v)


def sign_extend(v: int, num_bits: int) -> int:
    """Sign-extend the top bit of an unsigned ``num_bits`` value (encoding.go SignExtend)."""
    v &= (1 << num_bits) - 1
    if num_bits < 64 and v & (1 << (num_bits - 1)):
        return v - (1 << num_bits)
    if num_bits == 64 and v & (1 << 63):
        return v - (1 << 64)
    return v


def to_uint64(v: int) -> int:
    """Interpret a Python int as a two's-complement uint64 (Go uint64(x))."""
    return v & MASK64


def to_int64(v: int) -> int:
    """Interpret a uint64 bit pattern as an int64 (Go int64(x))."""
    v &= MASK64
    return v - (1 << 64) if v & (1 << 63) else v
