"""Go-compatible signed varints (encoding/binary PutVarint/ReadVarint).

Used for annotation length prefixes in the M3TSZ stream
(/root/reference/src/dbnode/encoding/m3tsz/timestamp_encoder.go:158-163).
Zig-zag maps signed to unsigned, then LEB128 little-endian 7-bit groups.
"""

from __future__ import annotations


def put_varint(x: int) -> bytes:
    """Encode a signed int like Go's binary.PutVarint."""
    # Zig-zag: x >= 0 -> 2x, x < 0 -> -2x-1.
    if x >= 0:
        ux = x << 1
    else:
        ux = ((-x) << 1) - 1
    out = bytearray()
    while ux >= 0x80:
        out.append((ux & 0x7F) | 0x80)
        ux >>= 7
    out.append(ux)
    return bytes(out)


def read_varint(read_byte) -> int:
    """Decode a signed varint; ``read_byte`` is a callable returning one int byte."""
    ux = 0
    shift = 0
    while True:
        b = read_byte()
        ux |= (b & 0x7F) << shift
        if b < 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("varint overflows 64 bits")
    x = ux >> 1
    if ux & 1:
        x = -x - 1
    return x
