"""Pure-Python snappy block format codec.

Prometheus remote read/write bodies are snappy-compressed protobuf
(/root/reference/src/query/api/v1/handler/prometheus/remote/write.go:257).
No snappy wheel ships in this environment, so: full-spec decompression, and
spec-valid literal-only compression (a legal snappy stream — every
decompressor accepts it; ratio 1.0 plus small framing overhead).
"""

from __future__ import annotations


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    if not data:
        return b""
    total, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("snappy: zero copy offset")
        start = len(out) - offset
        if start < 0:
            raise ValueError("snappy: offset before start")
        # overlapping copies are byte-at-a-time semantics
        for _ in range(length):
            out.append(out[start])
            start += 1
    if len(out) != total:
        raise ValueError(f"snappy: size mismatch {len(out)} != {total}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """Literal-only encoding: header + literal chunks (max 2^32-1 each)."""
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    n = len(data)
    if n == 0:
        return bytes(out)
    while pos < n:
        chunk = data[pos : pos + 65536]
        length = len(chunk)
        if length <= 60:
            out.append((length - 1) << 2)
        else:
            out.append(61 << 2)  # 2-byte length literal
            out += (length - 1).to_bytes(2, "little")
        out += chunk
        pos += length
    return bytes(out)
