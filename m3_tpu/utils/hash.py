"""murmur3-32 — the shard hash function.

Reference: sharding/shardset.go:149 `DefaultHashFn` = murmur3.Sum32(id) %
numShards (github.com/m3db/stackmurmur3). Both a scalar and a numpy-batch
implementation so host shard routing matches the reference placement exactly.
"""

from __future__ import annotations

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    h = seed & M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * _C1) & M32
        k = _rotl32(k, 15)
        k = (k * _C2) & M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & M32
    k = 0
    tail = data[nblocks * 4 :]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & M32
        k = _rotl32(k, 15)
        k = (k * _C2) & M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & M32
    h ^= h >> 16
    return h


def shard_for(id_bytes: bytes, num_shards: int) -> int:
    """sharding/shardset.go:149 DefaultHashFn."""
    return murmur3_32(id_bytes) % num_shards


def murmur3_32_batch(ids: list[bytes], seed: int = 0) -> np.ndarray:
    return np.asarray([murmur3_32(b, seed) for b in ids], np.uint32)
