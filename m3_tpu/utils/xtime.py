"""Time units and normalization helpers.

Behavioral parity with the reference's time unit model
(/root/reference/src/x/time/unit.go:31-41,177-185): units are small integer
codes stored on the wire (a single byte after a time-unit marker), each with a
duration in nanoseconds. ``None`` (0) is a placeholder, not a real unit.
"""

from __future__ import annotations

import enum


class Unit(enum.IntEnum):
    """Wire-stable time unit codes (reference src/x/time/unit.go:31-41)."""

    NONE = 0
    SECOND = 1
    MILLISECOND = 2
    MICROSECOND = 3
    NANOSECOND = 4
    MINUTE = 5
    HOUR = 6
    DAY = 7
    YEAR = 8

    def is_valid(self) -> bool:
        return self in _UNIT_NANOS

    def nanos(self) -> int:
        """Duration of one unit in nanoseconds (unit.go:177-185)."""
        try:
            return _UNIT_NANOS[self]
        except KeyError:
            raise ValueError(f"invalid time unit {self!r}")


_UNIT_NANOS = {
    Unit.SECOND: 1_000_000_000,
    Unit.MILLISECOND: 1_000_000,
    Unit.MICROSECOND: 1_000,
    Unit.NANOSECOND: 1,
    Unit.MINUTE: 60 * 1_000_000_000,
    Unit.HOUR: 3600 * 1_000_000_000,
    Unit.DAY: 24 * 3600 * 1_000_000_000,
    Unit.YEAR: 365 * 24 * 3600 * 1_000_000_000,
}


def to_normalized(duration_nanos: int, unit: Unit) -> int:
    """Convert a duration in nanos to a count of ``unit``s (truncating)."""
    u = unit.nanos()
    # Go integer division truncates toward zero; Python floor-divides.
    q = abs(duration_nanos) // u
    return q if duration_nanos >= 0 else -q


def from_normalized(value: int, unit: Unit) -> int:
    """Convert a count of ``unit``s back to nanoseconds."""
    return value * unit.nanos()


def initial_time_unit(start_nanos: int, unit: Unit) -> Unit:
    """Pick the initial stream time unit (m3tsz/timestamp_encoder.go:208-219).

    ``unit`` is usable only when the start time is an exact multiple of it;
    otherwise the stream starts with no unit and the first write emits a
    time-unit marker.
    """
    if not unit.is_valid():
        return Unit.NONE
    if start_nanos % unit.nanos() == 0:
        return unit
    return Unit.NONE
