"""m3msg wire transport: framed messages + acks over TCP.

Reference: /root/reference/src/msg/protocol/proto/ (message / ack
round-trip) and consumer/server — the bus's Producer routes to Consumer
objects; RemoteConsumer is that surface over a socket, so the same producer
code drives in-process queues in tests and real connections in deployment.
Frames are net.wire values: {"id", "shard", "payload"} → {"ack": id}.
"""

from __future__ import annotations

import socket
import socketserver
import threading

from ..net import wire
from .bus import Message


class ConsumerServer:
    """Socket front end for one consumer-service instance: decode message
    frames, hand to the handler, ack on success."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0) -> None:
        self.handler = handler  # Message -> bool (True = ack)
        self.received = 0
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        try:
                            req = wire.recv_frame(self.request)
                        except (ConnectionError, OSError, ValueError):
                            return
                        msg = Message(
                            shard=req["shard"], payload=req["payload"], id=req["id"]
                        )
                        outer.received += 1
                        try:
                            ok = bool(outer.handler(msg))
                        except Exception:
                            ok = False
                        try:
                            wire.send_frame(self.request, {"ack": req["id"], "ok": ok})
                        except (ConnectionError, OSError):
                            return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="m3tpu-msg-consumer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # shutdown() only stops new accepts; sever live connections too so a
        # stopped consumer really goes away (its handler threads exit on the
        # closed socket)
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()
            self._conns.clear()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class RemoteConsumer:
    """bus.Consumer surface over a socket: deliver() sends the frame and
    waits for the ack, returning False on any transport failure (the
    producer's unacked queue + retry sweep then take over)."""

    def __init__(
        self, service: str, instance_id: str, host: str, port: int,
        timeout: float = 10.0,
    ) -> None:
        self.service = service
        self.id = instance_id
        self.host = host
        self.port = port
        self.timeout = timeout
        self.is_up = True
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def deliver(self, msg: Message) -> bool:
        if not self.is_up:
            return False
        with self._lock:
            for fresh in (False, True):
                try:
                    if self._sock is None or fresh:
                        if self._sock is not None:
                            self._sock.close()
                        self._sock = self._connect()
                    # m3lint: disable=M3L001 -- the lock IS this consumer's single ack-paired socket (one in-flight delivery per connection); a waiter needs the same socket, so blocking here is the delivery semantics, not a shared-state pile-up
                    wire.send_frame(
                        self._sock,
                        {"id": msg.id, "shard": msg.shard, "payload": msg.payload},
                    )
                    resp = wire.recv_frame(self._sock)
                    return bool(resp.get("ok")) and resp.get("ack") == msg.id
                except (ConnectionError, OSError, ValueError):
                    if self._sock is not None:
                        self._sock.close()
                        self._sock = None
                    continue
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
