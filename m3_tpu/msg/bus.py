"""Message bus: topics, shard-routed producers, acked at-least-once delivery.

Reference: /root/reference/src/msg/ — topic.Service (topics + consumer
services in KV, topic/), producer.Producer/Writer (producer/types.go:65,121;
per-consumer-service writers, shard→consumer routing, ref-counted messages,
ack tracking with retry in producer/writer/), consumer with ack flush
(consumer/consumer.go). The wire protocol (size-prefixed protobuf over TCP,
protocol/proto) is replaced by in-process queues behind the same seams; a
network transport can slot in at Consumer.deliver.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..cluster.kv import KVStore


@dataclass
class ConsumerService:
    name: str
    consumption_type: str = "shared"  # shared | replicated (topic/types.go)


@dataclass
class Topic:
    name: str
    num_shards: int = 64
    consumer_services: list[ConsumerService] = field(default_factory=list)


class TopicService:
    """topic.Service: topics stored in KV (topic/service.go)."""

    def __init__(self, kv: KVStore) -> None:
        self.kv = kv

    def add(self, topic: Topic) -> None:
        self.kv.set(
            f"_topics/{topic.name}",
            {
                "numShards": topic.num_shards,
                "consumers": [
                    {"name": c.name, "type": c.consumption_type}
                    for c in topic.consumer_services
                ],
            },
        )

    def get(self, name: str) -> Topic | None:
        vv = self.kv.get(f"_topics/{name}")
        if vv is None:
            return None
        return Topic(
            name,
            vv.value["numShards"],
            [ConsumerService(c["name"], c["type"]) for c in vv.value["consumers"]],
        )


@dataclass
class Message:
    shard: int
    payload: bytes
    id: int = 0
    acked: bool = False


class Consumer:
    """A consumer instance of one consumer service; processes + acks."""

    def __init__(self, service: str, instance_id: str, handler: Callable[[Message], bool]) -> None:
        self.service = service
        self.id = instance_id
        self.handler = handler  # returns True to ack
        self.is_up = True

    def deliver(self, msg: Message) -> bool:
        if not self.is_up:
            return False
        return bool(self.handler(msg))


class Producer:
    """producer.Producer: route by shard to each consumer service, track
    unacked messages, retry on a deadline (producer/writer/message_writer.go)."""

    def __init__(self, topic: Topic, retry_interval: float = 0.05, max_retries: int = 8) -> None:
        self.topic = topic
        self.retry_interval = retry_interval
        self.max_retries = max_retries
        self._consumers: dict[str, list[Consumer]] = {}
        self._next_id = 0
        # (msg, service, target instance id or None, attempts)
        self._unacked: list[tuple[Message, str, str | None, int]] = []
        self._lock = threading.RLock()

    def register(self, consumer: Consumer) -> None:
        with self._lock:
            self._consumers.setdefault(consumer.service, []).append(consumer)

    def _route(self, service: str, shard: int) -> list[Consumer]:
        cs = self._consumers.get(service, [])
        if not cs:
            return []
        svc = next((c for c in self.topic.consumer_services if c.name == service), None)
        if svc and svc.consumption_type == "replicated":
            return cs  # every instance gets every shard (replicated topic)
        return [cs[shard % len(cs)]]  # shared: shard-owned instance

    def produce(self, shard: int, payload: bytes) -> int:
        """At-least-once: deliver to each consumer service; queue failures.
        Replicated services track acks PER INSTANCE — one mirror acking must
        not swallow another mirror's missed delivery."""
        with self._lock:
            self._next_id += 1
            mid = self._next_id
        for svc in self.topic.consumer_services:
            msg = Message(shard=shard % self.topic.num_shards, payload=payload, id=mid)
            replicated = svc.consumption_type == "replicated"
            targets = self._route(svc.name, msg.shard)
            any_ok = False
            for c in targets:
                ok = c.deliver(msg)
                any_ok = any_ok or ok
                if replicated and not ok:
                    with self._lock:
                        self._unacked.append((msg, svc.name, c.id, 0))
            if not any_ok and (not replicated or not targets):
                # shared service failure OR a (replicated) service with no
                # registered instances yet: queue and re-route at retry time
                with self._lock:
                    self._unacked.append((msg, svc.name, None, 0))
        return mid

    def retry_unacked(self) -> int:
        """One retry sweep; returns messages still unacked. The reference
        runs this on a timer (message_writer retryBatch)."""
        with self._lock:
            pending = self._unacked
            self._unacked = []
        still = []
        replicated_services = {
            c.name for c in self.topic.consumer_services
            if c.consumption_type == "replicated"
        }
        for msg, service, target_id, attempts in pending:
            if target_id is None:
                targets = self._route(service, msg.shard)
            else:
                targets = [
                    c
                    for c in self._consumers.get(service, [])
                    if c.id == target_id
                ]
            if target_id is None and service in replicated_services:
                # a replicated entry queued before instances registered:
                # every mirror must receive it; failures requeue per mirror
                if not targets and attempts + 1 < self.max_retries:
                    still.append((msg, service, None, attempts + 1))
                for c in targets:
                    if not c.deliver(msg) and attempts + 1 < self.max_retries:
                        still.append((msg, service, c.id, attempts + 1))
                continue
            delivered = any(c.deliver(msg) for c in targets)
            if not delivered and attempts + 1 < self.max_retries:
                still.append((msg, service, target_id, attempts + 1))
        with self._lock:
            self._unacked.extend(still)
        return len(self._unacked)

    @property
    def num_unacked(self) -> int:
        with self._lock:
            return len(self._unacked)
