"""Device-mesh helpers for the sharded scan runtime.

The reference distributes series by murmur3(seriesID) mod N virtual shards and
assigns shards to nodes via placements (/root/reference/src/dbnode/sharding/
shardset.go:149, src/cluster/placement/). The TPU-native equivalent maps the
shard axis onto a 1-D `jax.sharding.Mesh` axis named "shard": series batches
are laid out [series, time] and sharded along axis 0; cross-series aggregation
rides ICI via psum over the "shard" axis.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def series_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the series/shard axis.

    Args:
      n_devices: take the first N available devices (default: all).
      devices: explicit device list (overrides n_devices).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def series_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [series, ...] arrays: split axis 0 across the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
