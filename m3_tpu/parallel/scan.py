"""Sharded scan-and-aggregate: the framework's flagship execution path.

Reference counterpart: the coordinator fan-out query path — index query →
per-shard ReadEncoded → client-side decode → temporal functions → cross-series
aggregation (/root/reference/src/query/storage/fanout/storage.go:76,156 and
src/query/functions/). Here the whole post-index pipeline is one SPMD program:
each device decodes its slice of the series axis (BatchedSegments sharded on
axis 0), reduces within series (time axis), and cross-series aggregates ride
ICI via `jax.lax.psum` over the "shard" mesh axis.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6 top-level API
    from jax import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental namespace, check_rep knob
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"

from ..ops.chunked import ChunkedBatch, decode_chunked_lanes
from ..ops.chunked import PROFILER as CHUNKED_PROF
from ..ops.decode import decode_batched
from ..utils.instrument import KernelProfiler
from .mesh import SHARD_AXIS, series_mesh


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map: new jax calls it check_vma, old jax
    check_rep — semantics (skip the replication check) are the same."""
    kw = {_SHARD_MAP_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

# device-tier observability for the batched decode kernel: first-call
# compile attribution (m3tpu_jit_compiles_total{kernel="m3tsz_decode"})
# plus sampled block_until_ready-bounded dispatch wall time under
# M3_TPU_PROFILE_SAMPLE_RATE (m3tpu_kernel_dispatch_seconds)
_JIT_DECODE = KernelProfiler("m3tsz_decode")


class ScanAggregates(NamedTuple):
    """Per-series reductions plus replicated cross-series totals."""

    series_sum: jnp.ndarray  # f32[S] sum_over_time per series
    series_count: jnp.ndarray  # i32[S] valid datapoints per series
    series_min: jnp.ndarray  # f32[S]
    series_max: jnp.ndarray  # f32[S]
    series_last: jnp.ndarray  # f32[S]
    total_sum: jnp.ndarray  # f32[] cross-series (psum over shard axis)
    total_count: jnp.ndarray  # i32[]
    total_min: jnp.ndarray  # f32[]
    total_max: jnp.ndarray  # f32[]
    series_err: jnp.ndarray | None = None  # bool[S] device decode bailed
    #   (annotations etc.) — stitch_host_errors() recomputes those series


def _aggregate_decoded(vals, valid, with_psum):
    """Per-series + cross-series reductions over decoded [S, T] values."""
    zero = jnp.where(valid, vals, 0.0)
    s_sum = jnp.sum(zero, axis=1)
    s_count = jnp.sum(valid.astype(jnp.int32), axis=1)
    s_min = jnp.min(jnp.where(valid, vals, jnp.inf), axis=1)
    s_max = jnp.max(jnp.where(valid, vals, -jnp.inf), axis=1)
    # last valid value per series
    t = vals.shape[1]
    last_idx = jnp.max(jnp.where(valid, jnp.arange(t)[None, :], -1), axis=1)
    s_last = jnp.take_along_axis(zero, jnp.maximum(last_idx, 0)[:, None], axis=1)[:, 0]
    s_last = jnp.where(last_idx >= 0, s_last, jnp.nan)

    has = s_count > 0
    t_sum = jnp.sum(jnp.where(has, s_sum, 0.0))
    t_count = jnp.sum(s_count)
    t_min = jnp.min(jnp.where(has, s_min, jnp.inf))
    t_max = jnp.max(jnp.where(has, s_max, -jnp.inf))
    if with_psum:
        t_sum = jax.lax.psum(t_sum, SHARD_AXIS)
        t_count = jax.lax.psum(t_count, SHARD_AXIS)
        t_min = jax.lax.pmin(t_min, SHARD_AXIS)
        t_max = jax.lax.pmax(t_max, SHARD_AXIS)
    t_min = jnp.where(t_count > 0, t_min, jnp.nan)
    t_max = jnp.where(t_count > 0, t_max, jnp.nan)
    return ScanAggregates(
        series_sum=s_sum,
        series_count=s_count,
        series_min=jnp.where(has, s_min, jnp.nan),
        series_max=jnp.where(has, s_max, jnp.nan),
        series_last=s_last,
        total_sum=t_sum,
        total_count=t_count,
        total_min=t_min,
        total_max=t_max,
    )


def _is_tracing(x) -> bool:
    """True when ``x`` is an abstract tracer — i.e. this Python frame is
    running under an outer jit/shard_map trace, where wall time measures
    tracing (microseconds), not the XLA compile that happens later at the
    outer jit boundary. Compile attribution would be wrong there."""
    try:
        from jax.core import Tracer
    except ImportError:  # jax moved/renamed it: skip tracking, never break
        return True
    return isinstance(x, Tracer)


def _local_scan_aggregate(words, num_bits, initial_unit, *, max_points, with_psum):
    if _is_tracing(words):
        res = decode_batched(words, num_bits, initial_unit, max_points=max_points)
    else:
        # eager call: the first invocation per signature blocks on the jit
        # compile of decode_batched (tracked), and sampled dispatches are
        # block_until_ready-bounded for the dispatch histogram; cost=
        # captures the compiled HLO's flops/bytes once per signature when
        # profiling is on (m3tpu_kernel_flops / _bytes_accessed)
        with _JIT_DECODE.dispatch(
            (tuple(words.shape), int(max_points)),
            cost=(decode_batched, (words, num_bits, initial_unit),
                  {"max_points": max_points}),
        ) as d:
            res = d.done(decode_batched(
                words, num_bits, initial_unit, max_points=max_points
            ))
    return _aggregate_decoded(res.values_f32, res.valid, with_psum)


def scan_aggregate(words, num_bits, initial_unit, max_points: int) -> ScanAggregates:
    """Single-device decode + aggregate (no collectives)."""
    return _local_scan_aggregate(
        words, num_bits, initial_unit, max_points=max_points, with_psum=False
    )


def chunked_scan_aggregate(lane_args: dict, s: int, c: int, k: int, with_psum=False):
    """Flagship fast path: side-table chunked decode (ops/chunked.py) +
    aggregation. ``lane_args`` are ChunkedBatch fields as (device) arrays."""
    if _is_tracing(lane_args["windows"]):
        res = decode_chunked_lanes(**lane_args, k=k)
    else:
        with CHUNKED_PROF.dispatch(
            (tuple(lane_args["windows"].shape), int(k)),
            cost=(decode_chunked_lanes, (), {**lane_args, "k": k}),
        ) as d:
            res = d.done(decode_chunked_lanes(**lane_args, k=k))
    vals = res.values_f32.reshape(s, c * k)
    valid = res.valid.reshape(s, c * k)
    return _aggregate_decoded(vals, valid, with_psum)


def _aggregates_from_lanes(
    lane_agg, s: int, c: int, with_psum: bool, lane_order: str = "s",
    inv=None, precise: bool = False, unpermute_series: bool = True,
) -> ScanAggregates:
    """Reduce per-lane (per-chunk) aggregates [S*C] to ScanAggregates.

    ``lane_order``: "s" = series-major (lane = s*C + c), "c" = chunk-major
    (lane = c*S + s, the specialized packed kernel layout), "sorted" =
    chunk-major with the SERIES axis permuted fast-first; ``inv`` (i32[S])
    gathers the per-series outputs back to original order — an [S] gather,
    not an [S*C] one (TPU gathers are expensive)."""
    unperm = lambda x: x
    if lane_order == "sorted":
        rs = lambda x: x.reshape(c, s).T
        if unpermute_series:
            # [S]-sized gather (~20 ms/262k series on TPU) — callers that
            # only consume cross-series totals (order-independent) pass
            # unpermute_series=False and unpermute fetched arrays on host
            # with PackedLanes.inv when needed
            inv_d = jnp.asarray(inv)
            unperm = lambda x: x[inv_d]
    elif lane_order == "c":
        rs = lambda x: x.reshape(c, s).T
    else:
        rs = lambda x: x.reshape(s, c)
    l_sum, l_cnt = rs(lane_agg.sum), rs(lane_agg.count)
    l_min, l_max, l_last = rs(lane_agg.min), rs(lane_agg.max), rs(lane_agg.last)
    s_err = None
    if getattr(lane_agg, "err", None) is not None:
        s_err = jnp.any(rs(jnp.asarray(lane_agg.err).astype(jnp.int32)) != 0, axis=1)
    if precise:
        # float-float tree sums (ops/precise.py): per-series and the
        # cross-series total carry (hi, lo) pairs — ~1 ulp of exact vs
        # O(log n) ulp for the plain tree (TOLERANCE.md)
        from ..ops import precise as pr

        sp_hi, sp_lo = pr.compensated_sum(l_sum, axis=1)
        s_sum = sp_hi + sp_lo
    else:
        s_sum = jnp.sum(l_sum, axis=1)
    s_count = jnp.sum(l_cnt, axis=1)
    s_min = jnp.min(l_min, axis=1)
    s_max = jnp.max(l_max, axis=1)
    # last = value of the last chunk that saw any valid record
    cidx = jnp.arange(c)[None, :]
    last_c = jnp.max(jnp.where(l_cnt > 0, cidx, -1), axis=1)
    s_last = jnp.take_along_axis(l_last, jnp.maximum(last_c, 0)[:, None], axis=1)[:, 0]
    s_last = jnp.where(last_c >= 0, s_last, jnp.nan)

    has = s_count > 0
    if precise:
        from ..ops import precise as pr

        t_pair = pr.compensated_sum(jnp.where(has, sp_hi, 0.0)[None, :], axis=1)
        t_lo_pair = pr.compensated_sum(jnp.where(has, sp_lo, 0.0)[None, :], axis=1)
        t_pair = pr.dd_add(
            (t_pair[0][0], t_pair[1][0]), (t_lo_pair[0][0], t_lo_pair[1][0])
        )
        t_sum = None  # assembled below (pair form survives the psum)
    else:
        t_sum = jnp.sum(jnp.where(has, s_sum, 0.0))
    t_count = jnp.sum(s_count)
    t_min = jnp.min(jnp.where(has, s_min, jnp.inf))
    t_max = jnp.max(jnp.where(has, s_max, -jnp.inf))
    if with_psum:
        if precise:
            from ..ops import precise as pr

            # psum hi and lo separately; renormalize after the collective
            t_pair = pr.fast_two_sum(
                jax.lax.psum(t_pair[0], SHARD_AXIS),
                jax.lax.psum(t_pair[1], SHARD_AXIS),
            )
        else:
            t_sum = jax.lax.psum(t_sum, SHARD_AXIS)
        t_count = jax.lax.psum(t_count, SHARD_AXIS)
        t_min = jax.lax.pmin(t_min, SHARD_AXIS)
        t_max = jax.lax.pmax(t_max, SHARD_AXIS)
    if precise:
        t_sum = t_pair[0] + t_pair[1]
    t_min = jnp.where(t_count > 0, t_min, jnp.nan)
    t_max = jnp.where(t_count > 0, t_max, jnp.nan)
    return ScanAggregates(
        series_sum=unperm(s_sum),
        series_count=unperm(s_count),
        series_min=unperm(jnp.where(has, s_min, jnp.nan)),
        series_max=unperm(jnp.where(has, s_max, jnp.nan)),
        series_last=unperm(s_last),
        total_sum=t_sum,
        total_count=t_count,
        total_min=t_min,
        total_max=t_max,
        series_err=unperm(s_err) if s_err is not None else None,
    )


def stitch_host_errors(aggs: ScanAggregates, stream_for) -> ScanAggregates:
    """Query-layer stitch for device-erred lanes: series whose device
    decode bailed (annotations and other host-only features set the
    per-lane err flag, ops/decode.py) are recomputed through the host
    codec and patched into the aggregate block; totals are rebuilt from
    the patched per-series arrays in float64.

    ``stream_for(series_idx) -> bytes`` returns the series' encoded
    stream (the caller owns the segment source)."""
    import numpy as np

    from ..codec.m3tsz import decode

    if aggs.series_err is None:
        return aggs
    err = np.asarray(aggs.series_err).astype(bool)
    idxs = np.nonzero(err)[0]
    if idxs.size == 0:
        return aggs
    s_sum = np.asarray(aggs.series_sum).copy()
    s_cnt = np.asarray(aggs.series_count).copy()
    s_min = np.asarray(aggs.series_min).copy()
    s_max = np.asarray(aggs.series_max).copy()
    s_last = np.asarray(aggs.series_last).copy()
    for i in idxs:
        dps = decode(stream_for(int(i)))
        if not dps:
            s_sum[i] = 0.0
            s_cnt[i] = 0
            s_min[i] = s_max[i] = s_last[i] = np.nan
            continue
        vals32 = np.asarray([dp.value for dp in dps], np.float32)
        s_sum[i] = np.float32(np.sum(vals32.astype(np.float64)))
        s_cnt[i] = len(vals32)
        s_min[i] = vals32.min()
        s_max[i] = vals32.max()
        s_last[i] = vals32[-1]
    has = s_cnt > 0
    return ScanAggregates(
        series_sum=s_sum,
        series_count=s_cnt,
        series_min=s_min,
        series_max=s_max,
        series_last=s_last,
        total_sum=np.float32(np.sum(s_sum[has].astype(np.float64))),
        total_count=int(s_cnt.sum()),
        total_min=np.float32(np.min(s_min[has])) if has.any() else np.float32(np.nan),
        total_max=np.float32(np.max(s_max[has])) if has.any() else np.float32(np.nan),
        series_err=np.zeros_like(err),
    )


def chunked_scan_aggregate_fused(
    lane_args: dict, s: int, c: int, k: int, with_psum=False, backend: str = "auto"
):
    """Fused flagship path (ops/fused.py): the K-step decode runs with state
    on-chip and only per-lane aggregates leave the kernel. ``backend``:
    "pallas" (TPU kernel), "jnp" (lax.scan fallback), or "auto"."""
    from ..ops import fused

    if backend == "auto":
        # Mosaic kernels are TPU-only; every other backend (cpu, gpu) takes
        # the lax.scan fallback rather than attempting a pltpu lowering.
        backend = "pallas" if jax.default_backend() == "tpu" else "jnp"
    fn = fused.lane_aggregates_pallas if backend == "pallas" else fused.lane_aggregates_jnp
    if _is_tracing(lane_args["windows"]):
        lane_agg = fn(**lane_args, k=k)
    else:
        with fused.PROFILER_FUSED.dispatch(
            (backend, tuple(lane_args["windows"].shape), int(k))
        ) as d:
            lane_agg = d.done(fn(**lane_args, k=k))
    return _aggregates_from_lanes(lane_agg, s, c, with_psum)


def chunked_scan_aggregate_packed(
    windows4, lanes4, tile_flags=None, n: int = 0, s: int = 0, c: int = 0,
    k: int = 0, with_psum=False, interpret: bool = False,
    lane_order: str = "c", inv=None, precise: bool = False,
    unpermute_series: bool = True,
):
    """Packed-layout flagship path: 3 contiguous DMAs per Pallas grid program
    (ops/fused.py packed kernel). Inputs come from fused.pack_lane_inputs;
    ``tile_flags`` routes homogeneous fast tiles through the specialized
    all-int body; ``inv`` (with lane_order="sorted") gathers the fast-first
    permuted lanes back to series order."""
    from ..ops import fused

    if _is_tracing(windows4):
        lane_agg = fused.lane_aggregates_packed(
            windows4, lanes4, tile_flags, n=n, k=k, interpret=interpret
        )
    else:
        with fused.PROFILER_PACKED.dispatch(
            (tuple(windows4.shape), int(n), int(k))
        ) as d:
            lane_agg = d.done(fused.lane_aggregates_packed(
                windows4, lanes4, tile_flags, n=n, k=k, interpret=interpret
            ))
    return _aggregates_from_lanes(
        lane_agg, s, c, with_psum, lane_order=lane_order, inv=inv,
        precise=precise, unpermute_series=unpermute_series,
    )


def chunked_device_args(batch: ChunkedBatch, device_put=True) -> dict:
    """ChunkedBatch → kwargs for decode_chunked_lanes, device-resident."""
    import jax as _jax

    from ..ops.chunked import lane_kwargs

    put = (lambda x: _jax.device_put(jnp.asarray(x))) if device_put else jnp.asarray
    return lane_kwargs(batch, transform=put)


def make_sharded_chunked_scan(mesh, s: int, c: int, k: int):
    """Sharded flagship path: chunked decode + aggregate over the mesh.

    Lane arrays are [S*C] series-major, so sharding axis 0 across N devices
    keeps whole series on one device as long as S % N == 0 (pad with empty
    series otherwise). Cross-series totals psum over the shard axis.
    """
    n_dev = mesh.devices.size
    if s % n_dev != 0:
        raise ValueError(f"series count {s} not divisible by mesh size {n_dev}")

    def local(lane_args):
        return chunked_scan_aggregate(lane_args, s // n_dev, c, k, with_psum=True)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),),
        out_specs=ScanAggregates(
            series_sum=P(SHARD_AXIS),
            series_count=P(SHARD_AXIS),
            series_min=P(SHARD_AXIS),
            series_max=P(SHARD_AXIS),
            series_last=P(SHARD_AXIS),
            total_sum=P(),
            total_count=P(),
            total_min=P(),
            total_max=P(),
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def make_sharded_scan(mesh, max_points: int):
    """Build a pjit'd scan-and-aggregate over ``mesh``'s shard axis.

    Inputs must have a series count divisible by the mesh size (pad with
    num_bits==0 series — zero-length streams decode to no valid points and
    drop out of every reduction).
    """
    fn = shard_map(
        functools.partial(
            _local_scan_aggregate, max_points=max_points, with_psum=True
        ),
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=ScanAggregates(
            series_sum=P(SHARD_AXIS),
            series_count=P(SHARD_AXIS),
            series_min=P(SHARD_AXIS),
            series_max=P(SHARD_AXIS),
            series_last=P(SHARD_AXIS),
            total_sum=P(),
            total_count=P(),
            total_min=P(),
            total_max=P(),
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_scan_aggregate(
    words, num_bits, initial_unit, max_points: int, mesh=None
) -> ScanAggregates:
    mesh = mesh if mesh is not None else series_mesh()
    return make_sharded_scan(mesh, max_points)(words, num_bits, initial_unit)


# ---------------------------------------------------------------------------
# Decode-from-HBM, chunk-parallel: lane assembly by device gather over the
# resident pool's page buffer + side planes (m3_tpu/resident/pool.py)
# ---------------------------------------------------------------------------
#
# The whole-stream resident scan below decodes with a T-step lax.scan and
# measured 0.17x the chunked kernel even on CPU (PROFILE.md). Here the
# per-chunk side tables are ALREADY device-resident (paged in at
# admission), so a scan assembles the ChunkedBatch/PackedLanes lane view —
# windows, rel_pos/num_bits, decoder-state carries, classification flags —
# by pure device gathers from O(series)-sized host int vectors and
# dispatches the same chunked/packed kernels the streamed path uses.

RESIDENT_CHUNKED_PROF = KernelProfiler("resident_chunked_assemble")


def _resident_gather(pool_words, side_words, page_rows, side_rows,
                     n_chunks, total_bits, block_hi, block_lo,
                     si, ci, cw: int, w: int, spc: int):
    """Shared gather core for both lane layouts: (si, ci) lane->chunk
    coordinate vectors -> (planes dict, windows [N, CW], rel, nbits,
    valid). ``planes`` are the decoder-state lane planes unpacked from
    the packed 10-word side rows (ops/sideplane.py; prev_time re-based
    off the per-series block_start pair). Every array is built to be
    BIT-IDENTICAL to what ops/chunked.assemble_chunked produces for the
    same streams (windows zeroed on invalid lanes, all-zero state for
    padding) so the shared decode programs yield bit-identical results."""
    from ..ops.sideplane import SIDE_WORDS, unpack_side_planes

    page_rows = jnp.asarray(page_rows, jnp.int32)
    side_rows = jnp.asarray(side_rows, jnp.int32)
    lp = page_rows.shape[1]
    sl = side_rows.shape[1]
    valid = ci < jnp.asarray(n_chunks, jnp.int32)[si]
    # side slot: page-granular indirection (chunk ci sits at slot ci%spc
    # of side page ci//spc); invalid lanes hit reserved zero page 0
    sp = jnp.take(side_rows.reshape(-1), si * sl + jnp.where(valid, ci, 0) // spc)
    slot = jnp.where(valid, sp * spc + ci % spc, 0)
    side = jnp.take(
        jnp.asarray(side_words, jnp.uint32).reshape(-1, SIDE_WORDS),
        slot, axis=0,
    )  # [N, SIDE_WORDS] packed rows
    bs = (
        jnp.asarray(block_hi, jnp.uint32)[si],
        jnp.asarray(block_lo, jnp.uint32)[si],
    )
    planes = unpack_side_planes(side, bs, valid)
    off = planes["off"].astype(jnp.int32)
    w0 = off >> 5
    rel = off & 31
    tb = jnp.asarray(total_bits, jnp.int32)[si]
    nbits = jnp.where(valid, jnp.clip(tb - (w0 << 5), 0, cw * 32), 0)
    # windows: two gathers — word position -> page (tiny int table), then
    # page*W + word%W into the flat pool. Trailing zero-page columns in
    # page_rows guarantee w0 + cw - 1 stays in range and reads zeros.
    j = jnp.arange(cw, dtype=jnp.int32)[None, :]
    wabs = w0[:, None] + j  # [N, CW] absolute word index within the lane
    page = jnp.take(page_rows.reshape(-1), si[:, None] * lp + wabs // w)
    words = jnp.take(
        jnp.asarray(pool_words, jnp.uint32).reshape(-1), page * w + wabs % w
    )
    windows = jnp.where(valid[:, None], words, jnp.uint32(0))
    return planes, windows, rel, nbits, valid


def _assemble_resident_lanes_traced(pool_words, side_words, page_rows,
                                    side_rows, n_chunks, total_bits,
                                    block_hi, block_lo,
                                    c: int, cw: int, w: int, spc: int) -> dict:
    """Traced body: resident plan arrays -> decode_chunked_lanes kwargs
    (series-major lane order, ChunkedBatch layout)."""
    s = page_rows.shape[0]
    n = s * c
    lane = jnp.arange(n, dtype=jnp.int32)
    si = lane // c
    ci = lane % c
    planes, windows, rel, nbits, valid = _resident_gather(
        pool_words, side_words, page_rows, side_rows, n_chunks, total_bits,
        block_hi, block_lo, si, ci, cw, w, spc,
    )
    return dict(
        windows=windows,
        rel_pos=rel,
        num_bits=nbits,
        first=valid & (ci == 0),
        prev_time=planes["prev_time"],
        prev_delta=planes["prev_delta"],
        prev_float_bits=planes["prev_float_bits"],
        prev_xor=planes["prev_xor"],
        int_val=planes["int_val"],
        time_unit=planes["time_unit"].astype(jnp.int32),
        sig=planes["sig"].astype(jnp.int32),
        mult=planes["mult"].astype(jnp.int32),
        is_float=planes["is_float"] != 0,
    )


_assemble_resident_lanes_jit = jax.jit(
    _assemble_resident_lanes_traced, static_argnames=("c", "cw", "w", "spc")
)


def assemble_resident_lanes(plan, s_pad: int | None = None) -> tuple[dict, int]:
    """Eager entry: a ResidentChunkedPlan -> (decode_chunked_lanes lane
    kwargs on device, padded series count). ``s_pad`` pads the series
    axis with empty lanes (page row 0 / side page 0 -> zero windows,
    nbits 0) exactly like the streamed path's b"" padding streams."""
    s = plan.page_rows.shape[0]
    s_pad = s if s_pad is None else max(s_pad, s)
    vecs = pad_chunked_plan(plan, s_pad)
    key = (s_pad, plan.num_chunks, plan.window_words)
    with RESIDENT_CHUNKED_PROF.dispatch(key) as d:
        lane_args = d.done(_assemble_resident_lanes_jit(
            plan.words, plan.side, *vecs,
            c=plan.num_chunks, cw=plan.window_words, w=plan.page_words,
            spc=plan.side_page_chunks,
        ))
    return lane_args, s_pad


def _assemble_resident_packed_traced(pool_words, side_words, page_rows,
                                     side_rows, n_chunks, total_bits,
                                     block_hi, block_lo,
                                     c: int, cw: int, w: int, spc: int,
                                     rows: int):
    """Traced body: resident plan arrays -> the packed kernel's layout
    (ops/fused.pack_lane_inputs, chunk-major "c" order): windows4
    u32[tiles, CW, R, 128], lanes4 u32[tiles, NLANE, R, 128], tile_flags
    i32[tiles]. Mirrors the host packer EXACTLY — chunk-major lane j maps
    to (series j%S, chunk j//S), tile-padding lanes are zero/wildcard-fast,
    first chunks are never fast — so on the same streams both packings are
    bit-identical and the kernel's specialization decisions agree."""
    from ..ops.fused import NLANE, PACKED_LANE_PLANES

    s = page_rows.shape[0]
    n = s * c
    tile_lanes = rows * 128
    tiles = -(-n // tile_lanes)
    npad = tiles * tile_lanes
    j = jnp.arange(npad, dtype=jnp.int32)
    inb = j < n
    si = jnp.where(inb, j % s, 0)
    ci = jnp.where(inb, j // s, c)  # padding lanes: ci==c is never valid
    planes, windows, rel, nbits, valid = _resident_gather(
        pool_words, side_words, page_rows, side_rows, n_chunks, total_bits,
        block_hi, block_lo, si, ci, cw, w, spc,
    )
    first = valid & (ci == 0)

    def u32_plane(name):
        if name == "rel_pos":
            return rel.astype(jnp.uint32)
        if name == "num_bits":
            return nbits.astype(jnp.uint32)
        if name == "first":
            return first.astype(jnp.uint32)
        if name.endswith("_hi"):
            return planes[name[:-3]][0]
        if name.endswith("_lo"):
            return planes[name[:-3]][1]
        return planes[name]  # unpacked as uint32 already

    lanes4 = jnp.stack([u32_plane(name) for name in PACKED_LANE_PLANES])
    lanes4 = lanes4.reshape(NLANE, tiles, rows, 128).transpose(1, 0, 2, 3)
    windows4 = windows.reshape(tiles, rows, 128, cw).transpose(0, 3, 1, 2)
    # tile class from the v2 fast-chunk flags bits (packed side word 8):
    # 1 = every lane int-fast, 2 = every lane float-fast, 0 = general.
    # First chunks decode the stream head the fast bodies don't implement;
    # invalid/padding lanes are wildcard-fast — both exactly as the host
    # packer classifies.
    flags = planes["flags"]
    fast_i = jnp.where(valid, ((flags & 1) != 0) & (ci != 0), True)
    fast_f = jnp.where(valid, ((flags & 2) != 0) & (ci != 0), True)
    int_tiles = jnp.all(fast_i.reshape(tiles, tile_lanes), axis=1)
    flt_tiles = jnp.all(fast_f.reshape(tiles, tile_lanes), axis=1)
    tile_flags = jnp.where(int_tiles, 1, jnp.where(flt_tiles, 2, 0)).astype(jnp.int32)
    return windows4, lanes4, tile_flags


_assemble_resident_packed_jit = jax.jit(
    _assemble_resident_packed_traced,
    static_argnames=("c", "cw", "w", "spc", "rows"),
)


def assemble_resident_packed(plan, s_pad: int | None = None):
    """Eager entry: a ResidentChunkedPlan -> ((windows4, lanes4,
    tile_flags) on device, padded series count). The packed twin of
    assemble_resident_lanes — feeds chunked_scan_aggregate_packed, the
    same flagship kernel the streamed pipeline (parallel/stream.py)
    dispatches."""
    from ..ops.fused import ROWS_DEFAULT

    s = plan.page_rows.shape[0]
    s_pad = s if s_pad is None else max(s_pad, s)
    vecs = pad_chunked_plan(plan, s_pad)
    key = ("packed", s_pad, plan.num_chunks, plan.window_words)
    with RESIDENT_CHUNKED_PROF.dispatch(key) as d:
        packed = d.done(_assemble_resident_packed_jit(
            plan.words, plan.side, *vecs,
            c=plan.num_chunks, cw=plan.window_words, w=plan.page_words,
            spc=plan.side_page_chunks, rows=ROWS_DEFAULT,
        ))
    return packed, s_pad


def pad_chunked_plan(plan, s_pad: int):
    """Zero-pad a ResidentChunkedPlan's host vectors to ``s_pad`` series.
    Returns (page_rows, side_rows, n_chunks, total_bits, block_hi,
    block_lo) — the positional array args of the assembly bodies."""
    import numpy as _np

    s = plan.page_rows.shape[0]
    if s_pad == s:
        return (plan.page_rows, plan.side_rows, plan.n_chunks,
                plan.total_bits, plan.block_hi, plan.block_lo)
    pr = _np.zeros((s_pad, plan.page_rows.shape[1]), _np.int32)
    pr[:s] = plan.page_rows
    sr = _np.zeros((s_pad, plan.side_rows.shape[1]), _np.int32)
    sr[:s] = plan.side_rows
    nc = _np.zeros(s_pad, _np.int32)
    nc[:s] = plan.n_chunks
    tb = _np.zeros(s_pad, _np.int32)
    tb[:s] = plan.total_bits
    bh = _np.zeros(s_pad, _np.uint32)
    bh[:s] = plan.block_hi
    bl = _np.zeros(s_pad, _np.uint32)
    bl[:s] = plan.block_lo
    return pr, sr, nc, tb, bh, bl


def resident_chunked_local_fn(c: int, k: int, cw: int, w: int, spc: int,
                              with_psum: bool = False):
    """The assemble-from-residency + packed-decode body: device gathers
    over the pool + side planes build the PackedLanes view, fused with
    the flagship packed kernel. ONE definition shared by the
    single-device resident scan (resident/scan._packed_scan_fn) and the
    shard_map local of make_sharded_resident_chunked_scan — the two
    dispatch paths must never diverge on assembly semantics."""

    from ..ops.fused import ROWS_DEFAULT

    interpret = jax.default_backend() != "tpu"

    def local(pool_words, side_words, page_rows, side_rows, n_chunks,
              total_bits, block_hi, block_lo):
        windows4, lanes4, tile_flags = _assemble_resident_packed_traced(
            pool_words, side_words, page_rows, side_rows, n_chunks,
            total_bits, block_hi, block_lo, c=c, cw=cw, w=w, spc=spc,
            rows=ROWS_DEFAULT,
        )
        s_local = page_rows.shape[0]
        return chunked_scan_aggregate_packed(
            windows4, lanes4, tile_flags, n=s_local * c, s=s_local, c=c,
            k=k, with_psum=with_psum, interpret=interpret,
        )

    return local


def make_sharded_resident_chunked_scan(mesh, c: int, k: int, cw: int, w: int,
                                       spc: int):
    """Sharded decode-from-HBM CHUNKED scan: the page pool + side planes
    ride replicated (each device of a real mesh holds its placement's
    pages; on the forced CPU test mesh replication is free) while the
    per-series plan vectors shard over the mesh's series axis. Lane
    assembly AND decode run inside the shard_map, psum reduction
    unchanged."""

    local = resident_chunked_local_fn(c, k, cw, w, spc, with_psum=True)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=ScanAggregates(
            series_sum=P(SHARD_AXIS),
            series_count=P(SHARD_AXIS),
            series_min=P(SHARD_AXIS),
            series_max=P(SHARD_AXIS),
            series_last=P(SHARD_AXIS),
            total_sum=P(),
            total_count=P(),
            total_min=P(),
            total_max=P(),
            series_err=P(SHARD_AXIS),
        ),
        check_vma=False,
    )
    return jax.jit(fn)
