"""Host→device streaming pipeline: double-buffered upload + fused decode.

Reference mapping: SURVEY §2.7's "batched segment-upload RPC into device
HBM" / §7.5 fetch→pin→upload→kernel. At BASELINE config-5 scale (tens of
millions of series) the working set exceeds HBM, so scans stream: while the
device decodes batch N, batch N+1's packed arrays are already in flight
(`jax.device_put` is asynchronous), and batch N-P's results are drained to
bound in-flight memory at P batches.

Batches are the packed kernel layout (ops/fused.pack_lane_inputs) — the
same bytes filesets hold, so production reads go disk → packed host arrays
→ HBM without per-point host work.
"""

from __future__ import annotations

import functools
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Iterator

import jax
import numpy as np

from ..ops import fused
from .scan import chunked_scan_aggregate_packed


@dataclass
class StreamTotals:
    """Cross-batch aggregate of the per-batch ScanAggregates totals."""

    total_sum: float = 0.0
    total_count: int = 0
    total_min: float = float("inf")
    total_max: float = float("-inf")
    batches: int = 0

    def fold(self, agg) -> None:
        self.total_sum += float(agg.total_sum)
        self.total_count += int(agg.total_count)
        cnt = int(agg.total_count)
        if cnt:
            self.total_min = min(self.total_min, float(agg.total_min))
            self.total_max = max(self.total_max, float(agg.total_max))
        self.batches += 1


def packed_batches(batches: Iterable) -> Iterator[tuple]:
    """ChunkedBatch iterable → (windows4, lanes4, n, s, c, k) host tuples."""
    for batch in batches:
        packed = fused.pack_lane_inputs(batch)
        yield (
            packed.windows4,
            packed.lanes4,
            packed.n,
            batch.num_series,
            batch.num_chunks,
            batch.k,
        )


def stream_aggregate(
    host_batches: Iterable[tuple], prefetch: int = 2, drain_times: list | None = None
) -> StreamTotals:
    """Stream (windows4, lanes4, n, s, c, k) host batches through the packed
    kernel with ``prefetch`` batches in flight.

    Upload of batch N+1 overlaps compute of batch N (async dispatch); the
    oldest result is drained once the window exceeds ``prefetch``, bounding
    device memory to ~prefetch batches. ``drain_times`` (optional list)
    receives a perf_counter stamp per drained batch for steady-state timing.
    """
    import time as _time

    totals = StreamTotals()
    inflight: deque = deque()

    def drain_one():
        agg = inflight.popleft()
        jax.block_until_ready(agg)
        totals.fold(agg)
        if drain_times is not None:
            drain_times.append(_time.perf_counter())

    for w4, l4, n, s, c, k in host_batches:
        dev_w = jax.device_put(w4)
        dev_l = jax.device_put(l4)
        fn = _jitted(n, s, c, k)
        inflight.append(fn(dev_w, dev_l))
        if len(inflight) > prefetch:
            drain_one()
    while inflight:
        drain_one()
    return totals


@functools.lru_cache(maxsize=32)
def _jitted(n: int, s: int, c: int, k: int):
    # Mosaic kernels are TPU-only; other backends run the kernel body in
    # Pallas interpret mode (same code path, no Mosaic lowering)
    interpret = jax.default_backend() != "tpu"
    return jax.jit(
        functools.partial(
            chunked_scan_aggregate_packed, n=n, s=s, c=c, k=k, interpret=interpret
        )
    )


def fileset_packed_batches(readers: Iterable, batch_series: int = 65536):
    """FilesetReader iterable → packed host batches straight off the side
    tables (no CPU prescan): the production fetch→upload path."""
    for reader in readers:
        sids = reader.series_ids
        for i in range(0, len(sids), batch_series):
            chunk = reader.chunked_batch(sids[i : i + batch_series])
            yield from packed_batches([chunk])
