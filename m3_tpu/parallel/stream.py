"""Host→device streaming pipeline: double-buffered upload + fused decode.

Reference mapping: SURVEY §2.7's "batched segment-upload RPC into device
HBM" / §7.5 fetch→pin→upload→kernel. At BASELINE config-5 scale (tens of
millions of series) the working set exceeds HBM, so scans stream: while the
device decodes batch N, batch N+1's packed arrays are already in flight
(`jax.device_put` is asynchronous), and batch N-P's results are drained to
bound in-flight memory at P batches.

Batches are the packed kernel layout (ops/fused.pack_lane_inputs) — the
same bytes filesets hold, so production reads go disk → packed host arrays
→ HBM without per-point host work.
"""

from __future__ import annotations

import functools
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Iterator

import jax
import numpy as np

from ..ops import fused
from .scan import chunked_scan_aggregate_packed


@jax.jit
def _fold_totals(acc, tsum, tcnt, tmin, tmax):
    import jax.numpy as jnp

    from ..ops import u64

    a_sum, (c_hi, c_lo), a_min, a_max = acc
    has = tcnt > 0
    # count rides a (hi, lo) u32 pair: a plain i32 accumulator wraps past
    # 2^31 datapoints (~6 benchmark batches) and x64 is disabled
    c_hi, c_lo = u64.add((c_hi, c_lo), u64.from_u32(tcnt))
    return (
        a_sum + jnp.where(has, tsum, 0.0),
        (c_hi, c_lo),
        jnp.minimum(a_min, jnp.where(has, tmin, jnp.inf)),
        jnp.maximum(a_max, jnp.where(has, tmax, -jnp.inf)),
    )


@dataclass
class StreamTotals:
    """Cross-batch aggregate of the per-batch ScanAggregates totals.

    Folding stays ON DEVICE (a jitted scalar reduce per batch) — per-batch
    device→host scalar reads would serialize the pipeline on a sync each
    batch; the single transfer happens at finalize()."""

    _acc: tuple | None = None  # device accumulator (never downgraded)
    _final: tuple | None = None  # host snapshot cache for the properties
    batches: int = 0

    def fold(self, agg) -> None:
        import jax.numpy as jnp

        if self._acc is None:
            self._acc = (
                jnp.float32(0.0),
                (jnp.uint32(0), jnp.uint32(0)),
                jnp.float32(jnp.inf),
                jnp.float32(-jnp.inf),
            )
        self._acc = _fold_totals(
            self._acc, agg.total_sum, agg.total_count, agg.total_min, agg.total_max
        )
        self._final = None  # invalidate any snapshot taken mid-stream
        self.batches += 1

    def finalize(self) -> tuple:
        """One device→host transfer; safe to call mid-stream (the device
        accumulator is left untouched so further fold()s keep working)."""
        if self._final is None:
            if self._acc is None:
                self._final = (0.0, 0, float("inf"), float("-inf"))
            else:
                s, (c_hi, c_lo), lo, hi = jax.device_get(self._acc)
                self._final = (
                    float(s), (int(c_hi) << 32) | int(c_lo), float(lo), float(hi)
                )
        return self._final

    @property
    def total_sum(self) -> float:
        return self.finalize()[0]

    @property
    def total_count(self) -> int:
        return self.finalize()[1]

    @property
    def total_min(self) -> float:
        return self.finalize()[2]

    @property
    def total_max(self) -> float:
        return self.finalize()[3]


def packed_batches(batches: Iterable) -> Iterator[tuple]:
    """ChunkedBatch iterable → (windows4, lanes4, flags, n, s, c, k) host
    tuples."""
    for batch in batches:
        packed = fused.pack_lane_inputs(batch)
        yield (
            packed.windows4,
            packed.lanes4,
            packed.tile_flags,
            packed.n,
            batch.num_series,
            batch.num_chunks,
            batch.k,
            packed.order,
        )


def stream_aggregate(
    host_batches: Iterable[tuple], prefetch: int = 2, drain_times: list | None = None
) -> StreamTotals:
    """Stream (windows4, lanes4, tile_flags, n, s, c, k, lane_order) host
    batches (packed_batches output) through the packed kernel with
    ``prefetch`` batches in flight.

    Upload of batch N+1 overlaps compute of batch N (async dispatch); the
    oldest result is drained once the window exceeds ``prefetch``, bounding
    device memory to ~prefetch batches. ``drain_times`` (optional list)
    receives a perf_counter stamp per drained batch for steady-state timing.
    """
    import time as _time

    totals = StreamTotals()
    inflight: deque = deque()

    def drain_one():
        agg = inflight.popleft()
        totals.fold(agg)
        # HARD sync via a scalar device→host fetch: on tunneled transports
        # block_until_ready can return early for some shapes, which lets
        # the producer loop run arbitrarily far ahead and buffer every
        # pending upload in host RAM (observed: ~60GB for an unbounded
        # 80-batch stream). A 4-byte fetch is ordered after the batch's
        # compute, so it bounds in-flight batches for real.
        np.asarray(agg.total_count)
        if drain_times is not None:
            drain_times.append(_time.perf_counter())

    for w4, l4, flags, n, s, c, k, order in host_batches:
        dev_w = jax.device_put(w4)
        dev_l = jax.device_put(l4)
        dev_f = jax.device_put(flags)
        # stage the upload to completion BEFORE dispatching the kernel:
        # enqueueing a computation on still-in-flight transfers degrades the
        # transfer path catastrophically on tunneled devices (measured 0.2s
        # -> ~20s per batch), and the kernel (~ms) is far cheaper than the
        # upload anyway — cross-batch overlap still comes from the inflight
        # window below. The 1-element fetch is a real barrier (transfers
        # execute in order per device; see drain_one on why
        # block_until_ready alone is not)
        jax.block_until_ready((dev_w, dev_l, dev_f))
        np.asarray(dev_f.ravel()[:1])
        fn = _jitted(n, s, c, k, order)
        inflight.append(fn(dev_w, dev_l, dev_f))
        if len(inflight) > prefetch:
            drain_one()
    while inflight:
        drain_one()
    return totals


@functools.lru_cache(maxsize=32)
def _jitted(n: int, s: int, c: int, k: int, lane_order: str = "c"):
    # Mosaic kernels are TPU-only; other backends run the kernel body in
    # Pallas interpret mode (same code path, no Mosaic lowering)
    interpret = jax.default_backend() != "tpu"
    return jax.jit(
        functools.partial(
            chunked_scan_aggregate_packed, n=n, s=s, c=c, k=k,
            interpret=interpret, lane_order=lane_order,
        )
    )


def fileset_packed_batches(readers: Iterable, batch_series: int = 65536):
    """FilesetReader iterable → packed host batches straight off the side
    tables (no CPU prescan): the production fetch→upload path."""
    for reader in readers:
        sids = reader.series_ids
        for i in range(0, len(sids), batch_series):
            chunk = reader.chunked_batch(sids[i : i + batch_series])
            yield from packed_batches([chunk])
