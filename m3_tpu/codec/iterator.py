"""The reference's encoding iterator stack, host side.

Reference: /root/reference/src/dbnode/encoding/types.go:40-310 —
``ReaderIterator`` walks one encoded segment (codec/m3tsz.py here),
``MultiReaderIterator`` merges the segments of ONE replica in time order
(multi_reader_iterator.go), ``SeriesIterator`` merges replicas and dedupes
duplicate timestamps (series_iterator.go), and ``SeriesIterators`` batches
them. The TPU framework decodes the hot aggregate path on device
(ops/fused.py); this stack is the exact-semantics host path used by the
client session's replica merge, the storage read path, and anything that
needs annotations (which the device decoder does not surface).

Merge semantics:
- within one replica, callers pass segments oldest-first (flushed fileset
  blocks, then in-memory buffer blocks); on a duplicate timestamp the
  LATEST segment wins — matching the buffer-over-fileset precedence of
  dbShard.ReadEncoded (shard.go:1060).
- across replicas, the FIRST replica to produce a timestamp wins —
  series_iterator.go's first-wins dedupe (iterators.go:less).
"""

from __future__ import annotations

import heapq
from typing import Iterable, Iterator

from .m3tsz import Datapoint, ReaderIterator


class MultiReaderIterator:
    """Time-ordered merge of one replica's segments (multi_reader_iterator.go).

    ``segments`` are encoded m3tsz streams, oldest-first; empty segments are
    skipped. Exposes the same next()/current() surface as ReaderIterator.
    """

    def __init__(self, segments: Iterable[bytes], **reader_kwargs) -> None:
        self._heap: list[tuple[int, int, Datapoint, ReaderIterator]] = []
        self._current: Datapoint | None = None
        self.err: Exception | None = None
        for prio, seg in enumerate(segments):
            if not seg:
                continue
            it = ReaderIterator(seg, **reader_kwargs)
            self._push(prio, it)

    def _push(self, prio: int, it: ReaderIterator) -> None:
        if it.next():
            dp = it.current()
            # heap orders by (timestamp, -priority): among equal timestamps
            # the highest-priority (newest) segment surfaces first
            heapq.heappush(self._heap, (dp.timestamp, -prio, dp, it))
        elif it.err is not None and not isinstance(it.err, EOFError):
            # EOF is stream end; anything else is real corruption and must
            # surface, not silently truncate the merge (decode() parity)
            self.err = self.err or it.err

    def next(self) -> bool:
        if self.err is not None:
            raise self.err
        if not self._heap:
            self._current = None
            return False
        t, neg_prio, dp, it = heapq.heappop(self._heap)
        self._push(-neg_prio, it)
        # drop older-segment duplicates of the same timestamp
        while self._heap and self._heap[0][0] == t:
            _, np2, _, it2 = heapq.heappop(self._heap)
            self._push(-np2, it2)
        self._current = dp
        return True

    def current(self) -> Datapoint:
        assert self._current is not None
        return self._current

    def __iter__(self) -> Iterator[Datapoint]:
        while self.next():
            yield self.current()


class SeriesIterator:
    """Replica merge for one series (series_iterator.go).

    ``replicas`` are per-replica MultiReaderIterators (or anything with the
    next()/current() surface). Points outside [start, end) are filtered when
    bounds are given. First replica wins on duplicate timestamps.
    """

    def __init__(
        self,
        series_id: bytes,
        replicas: Iterable[MultiReaderIterator],
        start_nanos: int | None = None,
        end_nanos: int | None = None,
        tags: tuple | None = None,
    ) -> None:
        self.id = series_id
        self.tags = tags
        self.start = start_nanos
        self.end = end_nanos
        self.err: Exception | None = None
        self._heap: list[tuple[int, int, Datapoint, MultiReaderIterator]] = []
        self._current: Datapoint | None = None
        for prio, rep in enumerate(replicas):
            self._push(prio, rep)

    def _push(self, prio: int, rep: MultiReaderIterator) -> None:
        while rep.next():
            dp = rep.current()
            if self.start is not None and dp.timestamp < self.start:
                continue
            if self.end is not None and dp.timestamp >= self.end:
                return
            # equal timestamps: LOWEST replica index first -> first wins
            heapq.heappush(self._heap, (dp.timestamp, prio, dp, rep))
            return
        err = getattr(rep, "err", None)
        if err is not None and self.err is None:
            self.err = err

    def next(self) -> bool:
        if self.err is not None:
            raise self.err
        if not self._heap:
            self._current = None
            return False
        t, prio, dp, rep = heapq.heappop(self._heap)
        self._push(prio, rep)
        while self._heap and self._heap[0][0] == t:
            _, p2, _, rep2 = heapq.heappop(self._heap)
            self._push(p2, rep2)
        self._current = dp
        return True

    def current(self) -> Datapoint:
        assert self._current is not None
        return self._current

    def __iter__(self) -> Iterator[Datapoint]:
        while self.next():
            yield self.current()


class SeriesIterators:
    """Batch of SeriesIterators (encoding/types.go SeriesIterators)."""

    def __init__(self, iters: list[SeriesIterator]) -> None:
        self.iters = iters

    def __len__(self) -> int:
        return len(self.iters)

    def __iter__(self) -> Iterator[SeriesIterator]:
        return iter(self.iters)

    def __getitem__(self, i: int) -> SeriesIterator:
        return self.iters[i]
