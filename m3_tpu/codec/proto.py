"""Protobuf-value codec: compressed multi-field (message) time series.

Reference: /root/reference/src/dbnode/encoding/proto/ — encoder.go /
iterator.go compress protobuf-message values per timestamp with per-field
strategies: m3tsz timestamps, XOR for double fields, zigzag-varint deltas
for integer fields, an LRU dictionary + literals for bytes/string fields,
single bits for bools, and a per-record changed-field bitset so unchanged
fields cost one bit. This module is the same design over this framework's
bitstream primitives, with a self-describing schema header.

Wire layout:

    header := u8 version | varint n_fields
            | (u8 type | varint name_len | name)*
    record := m3tsz timestamp
            | changed bitset (1 bit per field)
            | changed field payloads in schema order
    stream := header | record* | m3tsz EOS tail
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass

from ..utils.xtime import Unit
from . import scheme
from .istream import IStream
from .m3tsz import MASK64, FloatXOR, TimestampEncoder, TimestampIterator
from .ostream import OStream

_VERSION = 1
_DICT_SIZE = 8  # LRU slots per bytes field (encoder.go byteFieldDictSize)
_DICT_IDX_BITS = 3


class FieldType(enum.IntEnum):
    DOUBLE = 1
    INT64 = 2
    BYTES = 3
    BOOL = 4


@dataclass(frozen=True)
class Field:
    name: str
    type: FieldType


Schema = tuple  # tuple[Field, ...]


def _zigzag(v: int) -> int:
    return ((v << 1) ^ (v >> 63)) & MASK64


def _unzigzag(u: int) -> int:
    v = (u >> 1) ^ -(u & 1)
    return v


def _write_varint_bits(os: OStream, value: int) -> None:
    """Unsigned LEB128 (Go PutUvarint; utils.varint.put_varint is the
    SIGNED/zigzag variant, so spell the unsigned form out here)."""
    if value < 0:
        raise ValueError("uvarint requires a non-negative value")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            os.write_bits(b | 0x80, 8)
        else:
            os.write_bits(b, 8)
            return


def _read_varint_bits(stream: IStream) -> int:
    out = 0
    shift = 0
    while True:
        b = stream.read_bits(8)
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out
        shift += 7


class _DoubleField:
    def __init__(self) -> None:
        self.xor = FloatXOR()
        self.first = True
        self.value = 0.0

    def write(self, os: OStream, v: float) -> None:
        bits = struct.unpack("<Q", struct.pack("<d", v))[0]
        if self.first:
            self.xor.write_full_float(os, bits)
            self.first = False
        else:
            self.xor.write_next_float(os, bits)
        self.value = v

    def read(self, stream: IStream) -> float:
        if self.first:
            self.xor.read_full_float(stream)
            self.first = False
        else:
            self.xor.read_next_float(stream)
        self.value = struct.unpack(
            "<d", struct.pack("<Q", self.xor.prev_float_bits)
        )[0]
        return self.value


class _IntField:
    def __init__(self) -> None:
        self.value = 0

    def write(self, os: OStream, v: int) -> None:
        _write_varint_bits(os, _zigzag(v - self.value))
        self.value = v

    def read(self, stream: IStream) -> int:
        # wrap into int64 (the encoder masks deltas to 64 bits, so the
        # accumulated value must wrap identically at the range boundary)
        raw = self.value + _unzigzag(_read_varint_bits(stream))
        self.value = ((raw + 2**63) % 2**64) - 2**63
        return self.value


class _BytesField:
    """LRU dictionary of recent values; refs cost 1+3 bits, literals are
    length-prefixed (encoder.go bytes field strategy)."""

    def __init__(self) -> None:
        self.lru: list[bytes] = []
        self.value = b""

    def _touch(self, v: bytes) -> None:
        if v in self.lru:
            self.lru.remove(v)
        self.lru.append(v)
        if len(self.lru) > _DICT_SIZE:
            self.lru.pop(0)

    def write(self, os: OStream, v: bytes) -> None:
        v = bytes(v)
        if v in self.lru:
            os.write_bits(0, 1)  # dict ref
            os.write_bits(self.lru.index(v), _DICT_IDX_BITS)
        else:
            os.write_bits(1, 1)  # literal
            _write_varint_bits(os, len(v))
            for b in v:
                os.write_bits(b, 8)
        self._touch(v)
        self.value = v

    def read(self, stream: IStream) -> bytes:
        if stream.read_bits(1) == 0:
            v = self.lru[stream.read_bits(_DICT_IDX_BITS)]
        else:
            n = _read_varint_bits(stream)
            v = bytes(stream.read_bits(8) for _ in range(n))
        self._touch(v)
        self.value = v
        return v


class _BoolField:
    def __init__(self) -> None:
        self.value = False

    def write(self, os: OStream, v: bool) -> None:
        os.write_bits(1 if v else 0, 1)
        self.value = bool(v)

    def read(self, stream: IStream) -> bool:
        self.value = stream.read_bits(1) == 1
        return self.value


_FIELD_STATES = {
    FieldType.DOUBLE: _DoubleField,
    FieldType.INT64: _IntField,
    FieldType.BYTES: _BytesField,
    FieldType.BOOL: _BoolField,
}

_DEFAULTS = {
    FieldType.DOUBLE: 0.0,
    FieldType.INT64: 0,
    FieldType.BYTES: b"",
    FieldType.BOOL: False,
}


_SCHEMA_ANN = b"\x00SCH"  # annotation payload magic for schema changes


def _serialize_schema(schema: Schema, seq: int) -> bytes:
    # varint counts/lengths, matching the stream header (a 300-field
    # schema or a >255-byte field name must not overflow a byte)
    from ..utils import varint as _vi

    out = [bytes([seq & 0xFF]), _vi.put_varint(len(schema))]
    for f in schema:
        name = f.name.encode()
        out.append(bytes([int(f.type)]))
        out.append(_vi.put_varint(len(name)))
        out.append(name)
    return _SCHEMA_ANN + b"".join(out)


def _deserialize_schema(payload: bytes) -> Schema:
    pos = len(_SCHEMA_ANN) + 1  # skip magic + seq

    def read_varint() -> int:
        nonlocal pos
        from ..utils import varint as _vi

        def rb() -> int:
            nonlocal pos
            b = payload[pos]
            pos += 1
            return b

        return _vi.read_varint(rb)

    n = read_varint()
    fields = []
    for _ in range(n):
        ftype = FieldType(payload[pos])
        pos += 1
        nlen = read_varint()
        fields.append(Field(payload[pos : pos + nlen].decode(), ftype))
        pos += nlen
    return tuple(fields)


def _migrate_states(old_schema, old_states, new_schema):
    """Schema evolution (proto/docs/encoding.md schema-change semantics):
    fields matched by (name, type) carry their compression state across
    the change; added / type-changed fields restart from defaults."""
    by_name = {
        (f.name, f.type): st for f, st in zip(old_schema, old_states)
    }
    return [
        by_name.get((f.name, f.type)) or _FIELD_STATES[f.type]()
        for f in new_schema
    ]


class ProtoEncoder:
    def __init__(self, start_nanos: int, schema: Schema, unit: Unit = Unit.SECOND) -> None:
        self.schema = tuple(schema)
        self.os = OStream()
        self.ts = TimestampEncoder(start_nanos, unit)
        self.unit = unit
        self._states = [_FIELD_STATES[f.type]() for f in self.schema]
        self._pending_schema: Schema | None = None
        self._schema_seq = 0
        self._write_header()

    def _write_header(self) -> None:
        self.os.write_bits(_VERSION, 8)
        _write_varint_bits(self.os, len(self.schema))
        for f in self.schema:
            self.os.write_bits(int(f.type), 8)
            name = f.name.encode()
            _write_varint_bits(self.os, len(name))
            for b in name:
                self.os.write_bits(b, 8)

    def set_schema(self, schema: Schema) -> None:
        """Mid-stream schema change (encoder.go control-bit schema change;
        here the new schema rides the annotation marker channel on the
        NEXT record, so EOS detection stays unambiguous). Matching fields
        keep their compression state."""
        self._pending_schema = tuple(schema)

    def encode(self, t_nanos: int, values: dict) -> None:
        ann = None
        if self._pending_schema is not None:
            self._schema_seq += 1
            ann = _serialize_schema(self._pending_schema, self._schema_seq)
            self._states = _migrate_states(
                self.schema, self._states, self._pending_schema
            )
            self.schema = self._pending_schema
            self._pending_schema = None
        self.ts.write_time(self.os, t_nanos, ann, self.unit)
        changed = []
        for f, st in zip(self.schema, self._states):
            v = values.get(f.name, st.value)
            changed.append(v != st.value or isinstance(st, _DoubleField) and st.first)
        for c in changed:
            self.os.write_bits(1 if c else 0, 1)
        for f, st, c in zip(self.schema, self._states, changed):
            if c:
                st.write(self.os, values.get(f.name, st.value))

    def stream(self) -> bytes:
        raw, pos = self.os.raw_bytes()
        if not raw:
            return b""
        return raw[:-1] + scheme.tail(raw[-1], pos)


@dataclass
class ProtoPoint:
    timestamp: int
    values: dict


class ProtoReaderIterator:
    def __init__(self, data: bytes, default_unit: Unit = Unit.SECOND) -> None:
        self.stream = IStream(data)
        self.ts = TimestampIterator(default_unit=default_unit)
        self.schema = self._read_header()
        self._states = [_FIELD_STATES[f.type]() for f in self.schema]
        self.current: ProtoPoint | None = None
        self.err: Exception | None = None  # corruption surfaces here
        self._seen_ann = None

    def _read_header(self) -> Schema:
        version = self.stream.read_bits(8)
        if version != _VERSION:
            raise ValueError(f"proto codec: unsupported version {version}")
        n = _read_varint_bits(self.stream)
        fields = []
        for _ in range(n):
            ftype = FieldType(self.stream.read_bits(8))
            name_len = _read_varint_bits(self.stream)
            name = bytes(
                self.stream.read_bits(8) for _ in range(name_len)
            ).decode()
            fields.append(Field(name, ftype))
        return tuple(fields)

    def next(self) -> bool:
        if self.err is not None:
            return False
        try:
            self.ts.read_timestamp(self.stream)
            if self.ts.done:
                return False
            ann = getattr(self.ts, "prev_annotation", None)
            if (
                ann is not None
                and ann is not self._seen_ann
                and ann.startswith(_SCHEMA_ANN)
            ):
                # mid-stream schema change delivered via the annotation
                # marker: remap field states by (name, type)
                new_schema = _deserialize_schema(ann)
                self._states = _migrate_states(
                    self.schema, self._states, new_schema
                )
                self.schema = new_schema
                self._seen_ann = ann
            changed = [self.stream.read_bits(1) == 1 for _ in self.schema]
            values = {}
            for f, st, c in zip(self.schema, self._states, changed):
                if c:
                    values[f.name] = st.read(self.stream)
                else:
                    values[f.name] = st.value
            self.current = ProtoPoint(self.ts.prev_time, values)
            return True
        except EOFError:
            return False
        except (ValueError, IndexError, OverflowError, KeyError) as exc:
            # corruption must stop iteration cleanly, never propagate
            # garbage points (corruption_prop_test.go contract)
            self.err = exc
            return False


def encode_proto_series(
    schema: Schema, points: list[tuple[int, dict]], unit: Unit = Unit.SECOND
) -> bytes:
    if not points:
        return b""
    enc = ProtoEncoder(points[0][0], schema, unit)
    for t, values in points:
        enc.encode(t, values)
    return enc.stream()


def decode_proto(data: bytes, default_unit: Unit = Unit.SECOND) -> list[ProtoPoint]:
    if not data:
        return []
    it = ProtoReaderIterator(data, default_unit)
    out = []
    while it.next():
        out.append(it.current)
    if it.err is not None:
        # the iterator contains corruption for streaming callers; the
        # whole-stream decode keeps raising (prior behavior)
        raise it.err
    return out
