"""Marker and delta-of-delta time encoding schemes.

Behavioral parity with /root/reference/src/dbnode/encoding/scheme.go:
- markers: 9-bit opcode 0x100 + 2-bit marker value (EOS=0, annotation=1,
  time-unit=2) embedded mid-stream; decoders peek 11 bits ahead of each
  delta-of-delta record to detect them (scheme.go:28-38).
- time buckets: zero bucket (1 bit '0'), escalating opcodes 0b10/0b110/0b1110
  with 7/9/12 value bits, then a default bucket 0b1111 with 32 value bits for
  second/millisecond streams and 64 for micro/nanosecond (scheme.go:42-52,
  143-165).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.xtime import Unit

# Marker scheme constants (scheme.go:28-38).
MARKER_OPCODE = 0x100
NUM_MARKER_OPCODE_BITS = 9
NUM_MARKER_VALUE_BITS = 2
NUM_MARKER_BITS = NUM_MARKER_OPCODE_BITS + NUM_MARKER_VALUE_BITS  # 11

END_OF_STREAM_MARKER = 0
ANNOTATION_MARKER = 1
TIME_UNIT_MARKER = 2


@dataclass(frozen=True)
class TimeBucket:
    opcode: int
    num_opcode_bits: int
    num_value_bits: int

    @property
    def min(self) -> int:
        return -(1 << (self.num_value_bits - 1))

    @property
    def max(self) -> int:
        return (1 << (self.num_value_bits - 1)) - 1


@dataclass(frozen=True)
class TimeEncodingScheme:
    zero_bucket: TimeBucket
    buckets: tuple[TimeBucket, ...]
    default_bucket: TimeBucket


def _new_scheme(bucket_value_bits: list[int], default_value_bits: int) -> TimeEncodingScheme:
    buckets = []
    num_opcode_bits = 1
    opcode = 0
    for i, vb in enumerate(bucket_value_bits):
        opcode = (1 << (i + 1)) | opcode
        buckets.append(TimeBucket(opcode, num_opcode_bits + 1, vb))
        num_opcode_bits += 1
    default_bucket = TimeBucket(opcode | 0x1, num_opcode_bits, default_value_bits)
    return TimeEncodingScheme(TimeBucket(0x0, 1, 0), tuple(buckets), default_bucket)


_BUCKET_BITS = [7, 9, 12]

TIME_ENCODING_SCHEMES: dict[Unit, TimeEncodingScheme] = {
    Unit.SECOND: _new_scheme(_BUCKET_BITS, 32),
    Unit.MILLISECOND: _new_scheme(_BUCKET_BITS, 32),
    Unit.MICROSECOND: _new_scheme(_BUCKET_BITS, 64),
    Unit.NANOSECOND: _new_scheme(_BUCKET_BITS, 64),
}


def scheme_for_unit(unit: Unit) -> TimeEncodingScheme | None:
    return TIME_ENCODING_SCHEMES.get(unit)


def write_special_marker(os, marker: int) -> None:
    """Write marker opcode + value (scheme.go WriteSpecialMarker)."""
    os.write_bits(MARKER_OPCODE, NUM_MARKER_OPCODE_BITS)
    os.write_bits(marker, NUM_MARKER_VALUE_BITS)


def tail(last_byte: int, pos: int) -> bytes:
    """Canonical stream tail: top ``pos`` bits of the last byte followed by the
    end-of-stream marker (scheme.go:243-258). The encoder's finalized stream is
    head (all full bytes but the last) + this tail.
    """
    from .ostream import OStream

    tmp = OStream()
    tmp.write_bits((last_byte & 0xFF) >> (8 - pos), pos)
    write_special_marker(tmp, END_OF_STREAM_MARKER)
    raw, _ = tmp.raw_bytes()
    return raw
