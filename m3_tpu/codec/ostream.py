"""MSB-first bit output stream.

Behavioral parity with the reference OStream
(/root/reference/src/dbnode/encoding/ostream.go): bits fill each byte from the
most-significant end; ``pos`` counts used bits (1..8) in the last byte.
"""

from __future__ import annotations


class OStream:
    __slots__ = ("buf", "pos")

    def __init__(self) -> None:
        self.buf = bytearray()
        self.pos = 0  # bits used in last byte; 0 when buffer empty, else 1..8

    def __len__(self) -> int:
        return len(self.buf)

    @property
    def bit_len(self) -> int:
        if not self.buf:
            return 0
        return (len(self.buf) - 1) * 8 + self.pos

    def _has_unused_bits(self) -> bool:
        return 0 < self.pos < 8

    def _grow(self, v: int, n: int) -> None:
        self.buf.append(v & 0xFF)
        self.pos = n

    def _fill_unused(self, v: int) -> None:
        self.buf[-1] |= (v & 0xFF) >> self.pos

    def write_bit(self, v: int) -> None:
        v = (v & 1) << 7
        if not self._has_unused_bits():
            self._grow(v, 1)
            return
        self._fill_unused(v)
        self.pos += 1

    def write_byte(self, v: int) -> None:
        v &= 0xFF
        if not self._has_unused_bits():
            self._grow(v, 8)
            return
        self._fill_unused(v)
        self._grow((v << (8 - self.pos)) & 0xFF, self.pos)

    def write_bytes(self, data: bytes) -> None:
        if not self._has_unused_bits():
            self.buf.extend(data)
            if data:
                self.pos = 8
            return
        for b in data:
            self.write_byte(b)

    def write_bits(self, v: int, num_bits: int) -> None:
        """Write the low ``num_bits`` of v, MSB first (ostream.go WriteBits)."""
        if num_bits <= 0:
            return
        if num_bits > 64:
            num_bits = 64
        v = (v << (64 - num_bits)) & ((1 << 64) - 1)
        while num_bits >= 8:
            self.write_byte(v >> 56)
            v = (v << 8) & ((1 << 64) - 1)
            num_bits -= 8
        while num_bits > 0:
            self.write_bit((v >> 63) & 1)
            v = (v << 1) & ((1 << 64) - 1)
            num_bits -= 1

    def raw_bytes(self) -> tuple[bytes, int]:
        return bytes(self.buf), self.pos
