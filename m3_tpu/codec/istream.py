"""MSB-first bit input stream over an in-memory byte buffer.

Behavioral parity with the reference IStream
(/root/reference/src/dbnode/encoding/istream.go): ReadBits/PeekBits/ReadByte
with unaligned reads. Raises EOFError past the end (the reference surfaces
io.EOF the same way; iterators treat it as stream end).
"""

from __future__ import annotations


class IStream:
    __slots__ = ("data", "byte_pos", "bit_pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.byte_pos = 0  # next byte index
        self.bit_pos = 0  # bits consumed in current byte (0..7)

    @property
    def remaining_bits(self) -> int:
        return (len(self.data) - self.byte_pos) * 8 - self.bit_pos

    def read_bit(self) -> int:
        return self.read_bits(1)

    def read_byte(self) -> int:
        return self.read_bits(8)

    def read_bits(self, num_bits: int) -> int:
        if num_bits > self.remaining_bits:
            raise EOFError("end of stream")
        res = 0
        n = num_bits
        data, bp, bit = self.data, self.byte_pos, self.bit_pos
        while n > 0:
            avail = 8 - bit
            take = avail if avail < n else n
            cur = data[bp]
            # take `take` bits starting at offset `bit` from MSB
            chunk = (cur >> (8 - bit - take)) & ((1 << take) - 1)
            res = (res << take) | chunk
            bit += take
            if bit == 8:
                bit = 0
                bp += 1
            n -= take
        self.byte_pos, self.bit_pos = bp, bit
        return res

    def peek_bits(self, num_bits: int) -> int:
        """Read without consuming; raises EOFError if not enough bits remain."""
        if num_bits > self.remaining_bits:
            raise EOFError("end of stream")
        save = (self.byte_pos, self.bit_pos)
        try:
            return self.read_bits(num_bits)
        finally:
            self.byte_pos, self.bit_pos = save

    def read(self, n: int) -> bytes:
        return bytes(self.read_byte() for _ in range(n))
