"""Native-decoder fast path for host reads.

Reference: /root/reference/src/dbnode/encoding/m3tsz/iterator.go:64 +
multi_reader_iterator.go — the Go read path decodes natively and merges
segments with newest-segment-wins dedupe. Here the batch C++ decoder
(native.decode_batch) produces (t, v, unit) arrays per segment and the
merge is one vectorized sort; streams carrying annotations drop to the
annotation-capable MultiReaderIterator so Datapoint.annotation survives
exactly. The pure-Python iterator remains the semantics reference
(hypothesis parity suites in tests/test_iterator.py)."""

from __future__ import annotations

import numpy as np

from ..utils.xtime import Unit
from .m3tsz import Datapoint


def merge_segment_arrays(triples):
    """Merge per-segment (times, values, units) arrays, newest-segment-wins
    per timestamp (MultiReaderIterator's heap dedupe, vectorized).
    ``triples`` are oldest-first."""
    live = [t for t in triples if len(t[0])]
    if not live:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.float64),
            np.zeros(0, np.uint8),
        )
    if len(live) == 1:
        return live[0]
    t_all = np.concatenate([t for t, _, _ in live])
    v_all = np.concatenate([v for _, v, _ in live])
    u_all = np.concatenate([u for _, _, u in live])
    order = np.argsort(t_all, kind="stable")  # equal t: concat order kept
    ts = t_all[order]
    keep = np.empty(len(ts), bool)
    keep[:-1] = ts[1:] != ts[:-1]
    keep[-1] = True  # last of each equal-t run = newest segment
    idx = order[keep]
    return t_all[idx], v_all[idx], u_all[idx]


def decode_stream_arrays(stream: bytes):
    """Decode ONE m3tsz stream → (times, values, units) arrays, or None
    when the stream carries annotations (the decoded-block cache stores
    plain arrays; annotated streams fall back to the Datapoint iterator
    so Datapoint.annotation survives). Native batch decoder when present,
    pure-Python decoder otherwise — either way the caller gets arrays."""
    from .. import native

    if not stream:
        return (
            np.zeros(0, np.int64),
            np.zeros(0, np.float64),
            np.zeros(0, np.uint8),
        )
    if native.available():
        triples, flags = native.decode_batch([stream], with_flags=True)
        if flags[0]:
            return None
        return triples[0]
    from .m3tsz import decode

    dps = decode(stream)
    if any(dp.annotation for dp in dps):
        return None
    return (
        np.asarray([dp.timestamp for dp in dps], np.int64),
        np.asarray([dp.value for dp in dps], np.float64),
        np.asarray([int(dp.unit) for dp in dps], np.uint8),
    )


def read_segments_arrays(segments, start=None, end=None):
    """Decode + merge segments into (times, values, units) arrays, or None
    when any segment carries annotations (caller falls back to the
    annotation-capable iterator) or there is nothing to decode natively."""
    from .. import native

    segs = [s for s in segments if s]
    if not segs or not native.available():
        return None
    triples, flags = native.decode_batch(segs, with_flags=True)
    if any(flags):
        return None
    t, v, u = merge_segment_arrays(triples)
    if start is not None:
        lo = int(np.searchsorted(t, start, side="left"))
        hi = int(np.searchsorted(t, end, side="left"))
        t, v, u = t[lo:hi], v[lo:hi], u[lo:hi]
    return t, v, u


def read_segments(segments, start=None, end=None):
    """list[Datapoint] via the native fast path; None → caller falls back."""
    arrs = read_segments_arrays(segments, start, end)
    if arrs is None:
        return None
    t, v, u = arrs
    return [
        Datapoint(int(tt), float(vv), Unit(int(uu)))
        for tt, vv, uu in zip(t, v, u)
    ]
