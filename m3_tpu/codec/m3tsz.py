"""M3TSZ streaming codec — bit-exact CPU reference implementation.

This is the ground-truth contract for the TPU decode kernels. Behavioral parity
with /root/reference/src/dbnode/encoding/m3tsz/:
- timestamps: delta-of-delta with per-unit bucketed variable-width encoding
  (timestamp_encoder.go:175-206), first timestamp as 64-bit unix nanos
  (timestamp_encoder.go:77-84), in-stream markers for end-of-stream /
  annotation / time-unit change (scheme.go:28-38, timestamp_iterator.go:147-201).
- values: Gorilla XOR floats (float_encoder_iterator.go:69-103) with optional
  int optimization — decimal scaling probe, significant-bit tracking with
  hysteresis, sign+diff records (encoder.go:111-249, m3tsz.go:78-118,
  int_sig_bits_tracker.go).
- stream finalization: head bytes + canonical tail carrying the EOS marker
  (encoder.go:383-446).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from ..utils import varint
from ..utils.bits import (
    MASK64,
    bits_to_float,
    float_to_bits,
    leading_and_trailing_zeros,
    num_sig,
    sign_extend,
)
from ..utils.xtime import Unit, from_normalized, initial_time_unit, to_normalized
from . import scheme
from .istream import IStream
from .ostream import OStream

# Value-stream opcodes (m3tsz.go:32-55).
OPCODE_ZERO_SIG = 0x0
OPCODE_NON_ZERO_SIG = 0x1
NUM_SIG_BITS = 6

OPCODE_ZERO_VALUE_XOR = 0x0
OPCODE_CONTAINED_VALUE_XOR = 0x2
OPCODE_UNCONTAINED_VALUE_XOR = 0x3
OPCODE_NO_UPDATE_SIG = 0x0
OPCODE_UPDATE_SIG = 0x1
OPCODE_UPDATE = 0x0
OPCODE_NO_UPDATE = 0x1
OPCODE_UPDATE_MULT = 0x1
OPCODE_NO_UPDATE_MULT = 0x0
OPCODE_POSITIVE = 0x0
OPCODE_NEGATIVE = 0x1
OPCODE_REPEAT = 0x1
OPCODE_NO_REPEAT = 0x0
OPCODE_FLOAT_MODE = 0x1
OPCODE_INT_MODE = 0x0

SIG_DIFF_THRESHOLD = 3
SIG_REPEAT_THRESHOLD = 5

MAX_MULT = 6
NUM_MULT_BITS = 3

MAX_INT = float(2**63)  # float64(math.MaxInt64) rounds up to 2^63
MIN_INT = float(-(2**63))
MAX_OPT_INT = 10.0**13

_MULTIPLIERS = [10.0**i for i in range(MAX_MULT + 1)]

DEFAULT_INT_OPTIMIZATION = True


def convert_to_int_float(v: float, cur_max_mult: int) -> tuple[float, int, bool]:
    """Probe decimal scaling of a float (m3tsz.go convertToIntFloat:78-118).

    Returns (value, multiplier, is_float). When is_float is False, ``value`` is
    an integral float equal to v * 10^multiplier (sign preserved).
    """
    if cur_max_mult == 0 and v < MAX_INT:
        # Quick check for values that are already ints.
        frac, i = math.modf(v)
        if frac == 0:
            return i, 0, False

    if cur_max_mult > MAX_MULT:
        raise ValueError("supplied multiplier is invalid")

    val = v * _MULTIPLIERS[cur_max_mult]
    sign = 1.0
    if v < 0:
        sign = -1.0
        val = val * -1.0

    mult = cur_max_mult
    while mult <= MAX_MULT and val < MAX_OPT_INT:
        frac, i = math.modf(val)
        if frac == 0:
            return sign * i, mult, False
        elif frac < 0.1:
            # Round down and check.
            if math.nextafter(val, 0.0) <= i:
                return sign * i, mult, False
        elif frac > 0.9:
            # Round up and check.
            nxt = i + 1
            if math.nextafter(val, nxt) >= nxt:
                return sign * nxt, mult, False
        val = val * 10.0
        mult += 1

    return v, 0, True


def convert_from_int_float(val: float, mult: int) -> float:
    if mult == 0:
        return val
    return val / _MULTIPLIERS[mult]


class FloatXOR:
    """XOR float codec state (float_encoder_iterator.go:36-166)."""

    __slots__ = ("prev_xor", "prev_float_bits")

    def __init__(self) -> None:
        self.prev_xor = 0
        self.prev_float_bits = 0

    # --- encode ---

    def write_full_float(self, os: OStream, val_bits: int) -> None:
        self.prev_float_bits = val_bits
        self.prev_xor = val_bits
        os.write_bits(val_bits, 64)

    def write_next_float(self, os: OStream, val_bits: int) -> None:
        xor = self.prev_float_bits ^ val_bits
        self._write_xor(os, xor)
        self.prev_xor = xor
        self.prev_float_bits = val_bits

    def _write_xor(self, os: OStream, cur_xor: int) -> None:
        if cur_xor == 0:
            os.write_bits(OPCODE_ZERO_VALUE_XOR, 1)
            return
        prev_leading, prev_trailing = leading_and_trailing_zeros(self.prev_xor)
        cur_leading, cur_trailing = leading_and_trailing_zeros(cur_xor)
        if cur_leading >= prev_leading and cur_trailing >= prev_trailing:
            os.write_bits(OPCODE_CONTAINED_VALUE_XOR, 2)
            os.write_bits(cur_xor >> prev_trailing, 64 - prev_leading - prev_trailing)
            return
        os.write_bits(OPCODE_UNCONTAINED_VALUE_XOR, 2)
        os.write_bits(cur_leading, 6)
        num_meaningful = 64 - cur_leading - cur_trailing
        os.write_bits(num_meaningful - 1, 6)
        os.write_bits(cur_xor >> cur_trailing, num_meaningful)

    # --- decode ---

    def read_full_float(self, stream: IStream) -> None:
        vb = stream.read_bits(64)
        self.prev_float_bits = vb
        self.prev_xor = vb

    def read_next_float(self, stream: IStream) -> None:
        cb = stream.read_bits(1)
        if cb == OPCODE_ZERO_VALUE_XOR:
            self.prev_xor = 0
            return
        cb = (cb << 1) | stream.read_bits(1)
        if cb == OPCODE_CONTAINED_VALUE_XOR:
            prev_leading, prev_trailing = leading_and_trailing_zeros(self.prev_xor)
            num_meaningful = 64 - prev_leading - prev_trailing
            meaningful = stream.read_bits(num_meaningful)
            self.prev_xor = (meaningful << prev_trailing) & MASK64
            self.prev_float_bits ^= self.prev_xor
            return
        packed = stream.read_bits(12)
        num_leading = (packed >> 6) & 0x3F
        num_meaningful = (packed & 0x3F) + 1
        meaningful = stream.read_bits(num_meaningful)
        num_trailing = 64 - num_leading - num_meaningful
        self.prev_xor = (meaningful << num_trailing) & MASK64
        self.prev_float_bits ^= self.prev_xor


class IntSigBitsTracker:
    """Significant-bit tracking with hysteresis (int_sig_bits_tracker.go)."""

    __slots__ = ("num_sig", "cur_highest_lower_sig", "num_lower_sig")

    def __init__(self) -> None:
        self.num_sig = 0
        self.cur_highest_lower_sig = 0
        self.num_lower_sig = 0

    def write_int_val_diff(self, os: OStream, val_bits: int, neg: bool) -> None:
        os.write_bit(OPCODE_NEGATIVE if neg else OPCODE_POSITIVE)
        os.write_bits(val_bits, self.num_sig)

    def write_int_sig(self, os: OStream, sig: int) -> None:
        if self.num_sig != sig:
            os.write_bit(OPCODE_UPDATE_SIG)
            if sig == 0:
                os.write_bit(OPCODE_ZERO_SIG)
            else:
                os.write_bit(OPCODE_NON_ZERO_SIG)
                os.write_bits(sig - 1, NUM_SIG_BITS)
        else:
            os.write_bit(OPCODE_NO_UPDATE_SIG)
        self.num_sig = sig

    def track_new_sig(self, sig: int) -> int:
        new_sig = self.num_sig
        if sig > self.num_sig:
            new_sig = sig
        elif self.num_sig - sig >= SIG_DIFF_THRESHOLD:
            if self.num_lower_sig == 0:
                self.cur_highest_lower_sig = sig
            elif sig > self.cur_highest_lower_sig:
                self.cur_highest_lower_sig = sig
            self.num_lower_sig += 1
            if self.num_lower_sig >= SIG_REPEAT_THRESHOLD:
                new_sig = self.cur_highest_lower_sig
                self.num_lower_sig = 0
        else:
            self.num_lower_sig = 0
        return new_sig


class TimestampEncoder:
    """Delta-of-delta timestamp encoder (timestamp_encoder.go)."""

    def __init__(self, start_nanos: int, unit: Unit = Unit.SECOND) -> None:
        self.prev_time = start_nanos
        self.prev_time_delta = 0
        self.prev_annotation: bytes | None = None
        self.time_unit = initial_time_unit(start_nanos, unit)
        self._time_unit_encoded_manually = False
        self._has_written_first = False

    def write_time(self, os: OStream, t_nanos: int, annotation: bytes | None, unit: Unit) -> None:
        if not self._has_written_first:
            self.write_first_time(os, t_nanos, annotation, unit)
            self._has_written_first = True
            return
        self.write_next_time(os, t_nanos, annotation, unit)

    def write_first_time(self, os: OStream, t_nanos: int, annotation: bytes | None, unit: Unit) -> None:
        # First time is always written in nanoseconds (timestamp_encoder.go:77-84).
        os.write_bits(self.prev_time & MASK64, 64)
        self.write_next_time(os, t_nanos, annotation, unit)

    def write_next_time(self, os: OStream, t_nanos: int, annotation: bytes | None, unit: Unit) -> None:
        self._write_annotation(os, annotation)
        tu_changed = self._maybe_write_time_unit_change(os, unit)

        time_delta = t_nanos - self.prev_time
        self.prev_time = t_nanos
        if tu_changed or self._time_unit_encoded_manually:
            # Normalized 64-bit nanos dod; reset delta (timestamp_encoder.go:94-102).
            dod = time_delta - self.prev_time_delta
            os.write_bits(dod & MASK64, 64)
            self.prev_time_delta = 0
            self._time_unit_encoded_manually = False
            return
        self._write_dod_unchanged(os, self.prev_time_delta, time_delta, unit)
        self.prev_time_delta = time_delta

    def write_time_unit(self, os: OStream, unit: Unit) -> None:
        os.write_byte(int(unit))
        self.time_unit = unit
        self._time_unit_encoded_manually = True

    def _maybe_write_time_unit_change(self, os: OStream, unit: Unit) -> bool:
        if not unit.is_valid() or unit == self.time_unit:
            return False
        scheme.write_special_marker(os, scheme.TIME_UNIT_MARKER)
        self.write_time_unit(os, unit)
        return True

    def _write_annotation(self, os: OStream, annotation: bytes | None) -> None:
        if not annotation or annotation == self.prev_annotation:
            return
        scheme.write_special_marker(os, scheme.ANNOTATION_MARKER)
        # Length-1 for varint savings (timestamp_encoder.go:158-163).
        os.write_bytes(varint.put_varint(len(annotation) - 1))
        os.write_bytes(annotation)
        self.prev_annotation = annotation

    def _write_dod_unchanged(self, os: OStream, prev_delta: int, cur_delta: int, unit: Unit) -> None:
        dod = to_normalized(cur_delta - prev_delta, unit)
        tes = scheme.scheme_for_unit(unit)
        if tes is None:
            raise ValueError(f"no time encoding scheme for unit {unit!r}")
        if dod == 0:
            zb = tes.zero_bucket
            os.write_bits(zb.opcode, zb.num_opcode_bits)
            return
        for bucket in tes.buckets:
            if bucket.min <= dod <= bucket.max:
                os.write_bits(bucket.opcode, bucket.num_opcode_bits)
                os.write_bits(dod & ((1 << bucket.num_value_bits) - 1), bucket.num_value_bits)
                return
        db = tes.default_bucket
        os.write_bits(db.opcode, db.num_opcode_bits)
        os.write_bits(dod & ((1 << db.num_value_bits) - 1), db.num_value_bits)


class Encoder:
    """M3TSZ encoder (encoder.go). Produces the finalized stream via stream()."""

    def __init__(
        self,
        start_nanos: int,
        int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
        default_unit: Unit = Unit.SECOND,
    ) -> None:
        # The initial stream unit comes from the options default (encoder.go:80,
        # options.go defaultDefaultTimeUnit); per-write units are signalled with
        # time-unit markers when they differ.
        self.os = OStream()
        self.ts_encoder = TimestampEncoder(start_nanos, default_unit)
        self.float_enc = FloatXOR()
        self.sig_tracker = IntSigBitsTracker()
        self.int_val = 0.0
        self.num_encoded = 0
        self.max_mult = 0
        self.int_optimized = int_optimized
        self.is_float = False

    def encode(
        self,
        t_nanos: int,
        value: float,
        unit: Unit = Unit.SECOND,
        annotation: bytes | None = None,
    ) -> None:
        self.ts_encoder.write_time(self.os, t_nanos, annotation, unit)
        if self.num_encoded == 0:
            self._write_first_value(value)
        else:
            self._write_next_value(value)
        self.num_encoded += 1

    def _write_first_value(self, v: float) -> None:
        if not self.int_optimized:
            self.float_enc.write_full_float(self.os, float_to_bits(v))
            return

        val, mult, is_float = convert_to_int_float(v, 0)
        if is_float:
            self.os.write_bit(OPCODE_FLOAT_MODE)
            self.float_enc.write_full_float(self.os, float_to_bits(v))
            self.is_float = True
            self.max_mult = mult
            return

        self.os.write_bit(OPCODE_INT_MODE)
        self.int_val = val
        neg_diff = True
        if val < 0:
            neg_diff = False
            val = -1 * val

        val_bits = int(val) & MASK64
        sig = num_sig(val_bits)
        self._write_int_sig_mult(sig, mult, False)
        self.sig_tracker.write_int_val_diff(self.os, val_bits, neg_diff)

    def _write_next_value(self, v: float) -> None:
        if not self.int_optimized:
            self.float_enc.write_next_float(self.os, float_to_bits(v))
            return

        val, mult, is_float = convert_to_int_float(v, self.max_mult)
        val_diff = 0.0
        if not is_float:
            val_diff = self.int_val - val

        if is_float or val_diff >= MAX_INT or val_diff <= MIN_INT:
            self._write_float_val(float_to_bits(val), mult)
            return
        self._write_int_val(val, mult, is_float, val_diff)

    def _write_float_val(self, val_bits: int, mult: int) -> None:
        if not self.is_float:
            # Converting from int to float mode (encoder.go:175-186).
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_NO_REPEAT)
            self.os.write_bit(OPCODE_FLOAT_MODE)
            self.float_enc.write_full_float(self.os, val_bits)
            self.is_float = True
            self.max_mult = mult
            return
        if val_bits == self.float_enc.prev_float_bits:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_REPEAT)
            return
        self.os.write_bit(OPCODE_NO_UPDATE)
        self.float_enc.write_next_float(self.os, val_bits)

    def _write_int_val(self, val: float, mult: int, is_float: bool, val_diff: float) -> None:
        if val_diff == 0 and is_float == self.is_float and mult == self.max_mult:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_REPEAT)
            return

        neg = False
        if val_diff < 0:
            neg = True
            val_diff = -1 * val_diff

        val_diff_bits = int(val_diff) & MASK64
        sig = num_sig(val_diff_bits)
        new_sig = self.sig_tracker.track_new_sig(sig)
        is_float_changed = is_float != self.is_float
        if mult > self.max_mult or self.sig_tracker.num_sig != new_sig or is_float_changed:
            self.os.write_bit(OPCODE_UPDATE)
            self.os.write_bit(OPCODE_NO_REPEAT)
            self.os.write_bit(OPCODE_INT_MODE)
            self._write_int_sig_mult(new_sig, mult, is_float_changed)
            self.sig_tracker.write_int_val_diff(self.os, val_diff_bits, neg)
            self.is_float = False
        else:
            self.os.write_bit(OPCODE_NO_UPDATE)
            self.sig_tracker.write_int_val_diff(self.os, val_diff_bits, neg)

        self.int_val = val

    def _write_int_sig_mult(self, sig: int, mult: int, float_changed: bool) -> None:
        self.sig_tracker.write_int_sig(self.os, sig)
        if mult > self.max_mult:
            self.os.write_bit(OPCODE_UPDATE_MULT)
            self.os.write_bits(mult, NUM_MULT_BITS)
            self.max_mult = mult
        elif self.sig_tracker.num_sig == sig and self.max_mult == mult and float_changed:
            # Only float mode changed: update mult anyway (encoder.go:241-245).
            self.os.write_bit(OPCODE_UPDATE_MULT)
            self.os.write_bits(self.max_mult, NUM_MULT_BITS)
        else:
            self.os.write_bit(OPCODE_NO_UPDATE_MULT)

    def stream(self) -> bytes:
        """Finalized stream: head bytes + canonical EOS tail (encoder.go:383-418)."""
        raw, pos = self.os.raw_bytes()
        if not raw:
            return b""
        return raw[:-1] + scheme.tail(raw[-1], pos)

    def __len__(self) -> int:
        raw, pos = self.os.raw_bytes()
        if not raw:
            return 0
        return len(raw) - 1 + len(scheme.tail(raw[-1], pos))


@dataclass
class Datapoint:
    timestamp: int  # unix nanos
    value: float
    unit: Unit = Unit.SECOND
    annotation: bytes | None = None


class TimestampIterator:
    """Delta-of-delta timestamp decoder (timestamp_iterator.go)."""

    def __init__(self, default_unit: Unit = Unit.SECOND, skip_markers: bool = False) -> None:
        self.prev_time = 0
        self.prev_time_delta = 0
        self.prev_annotation: bytes | None = None
        self.time_unit = Unit.NONE
        self.default_unit = default_unit
        self.time_unit_changed = False
        self.done = False
        self.skip_markers = skip_markers
        self.num_markers = 0  # markers consumed (EOS/annotation/time-unit)

    def read_timestamp(self, stream: IStream) -> bool:
        """Returns True when this was the first timestamp."""
        self.prev_annotation = None
        first = False
        if self.prev_time == 0:
            first = True
            self._read_first_timestamp(stream)
        else:
            self._read_next_timestamp(stream)
        if self.time_unit_changed:
            self.prev_time_delta = 0
            self.time_unit_changed = False
        return first

    def read_time_unit(self, stream: IStream) -> None:
        tu = stream.read_byte()
        try:
            unit = Unit(tu)
        except ValueError:
            unit = Unit.NONE
        if unit.is_valid() and unit != self.time_unit:
            self.time_unit_changed = True
        self.time_unit = unit

    def _read_first_timestamp(self, stream: IStream) -> None:
        nt = stream.read_bits(64)
        if self.time_unit == Unit.NONE:
            self.time_unit = initial_time_unit(nt, self.default_unit)
        self._read_next_timestamp(stream)
        self.prev_time = nt + self.prev_time_delta

    def _read_next_timestamp(self, stream: IStream) -> None:
        dod = self._read_marker_or_dod(stream)
        self.prev_time_delta += dod
        self.prev_time = self.prev_time + self.prev_time_delta

    def _try_read_marker(self, stream: IStream) -> tuple[int, bool]:
        try:
            opcode_and_value = stream.peek_bits(scheme.NUM_MARKER_BITS)
        except EOFError:
            return 0, False
        opcode = opcode_and_value >> scheme.NUM_MARKER_VALUE_BITS
        if opcode != scheme.MARKER_OPCODE:
            return 0, False
        marker = opcode_and_value & ((1 << scheme.NUM_MARKER_VALUE_BITS) - 1)
        if marker == scheme.END_OF_STREAM_MARKER:
            stream.read_bits(scheme.NUM_MARKER_BITS)
            self.done = True
            self.num_markers += 1
            return 0, True
        elif marker == scheme.ANNOTATION_MARKER:
            stream.read_bits(scheme.NUM_MARKER_BITS)
            self._read_annotation(stream)
            self.num_markers += 1
            return self._read_marker_or_dod(stream), True
        elif marker == scheme.TIME_UNIT_MARKER:
            stream.read_bits(scheme.NUM_MARKER_BITS)
            self.read_time_unit(stream)
            self.num_markers += 1
            return self._read_marker_or_dod(stream), True
        return 0, False

    def _read_marker_or_dod(self, stream: IStream) -> int:
        if not self.skip_markers:
            dod, success = self._try_read_marker(stream)
            if self.done:
                return 0
            if success:
                return dod
        tes = scheme.scheme_for_unit(self.time_unit)
        if tes is None:
            raise ValueError(f"no time encoding scheme for unit {self.time_unit!r}")
        return self._read_dod(stream, tes)

    def _read_dod(self, stream: IStream, tes: scheme.TimeEncodingScheme) -> int:
        if self.time_unit_changed:
            # 64-bit normalized nanos dod (timestamp_iterator.go:228-238).
            dod_bits = stream.read_bits(64)
            return sign_extend(dod_bits, 64)

        cb = stream.read_bits(1)
        if cb == tes.zero_bucket.opcode:
            return 0
        for bucket in tes.buckets:
            cb = (cb << 1) | stream.read_bits(1)
            if cb == bucket.opcode:
                dod_bits = stream.read_bits(bucket.num_value_bits)
                dod = sign_extend(dod_bits, bucket.num_value_bits)
                return from_normalized(dod, self.time_unit)
        dod_bits = stream.read_bits(tes.default_bucket.num_value_bits)
        dod = sign_extend(dod_bits, tes.default_bucket.num_value_bits)
        return from_normalized(dod, self.time_unit)

    def _read_annotation(self, stream: IStream) -> None:
        ant_len = varint.read_varint(stream.read_byte) + 1
        if ant_len <= 0:
            raise ValueError(f"unexpected annotation length {ant_len}")
        self.prev_annotation = stream.read(ant_len)


class ReaderIterator:
    """M3TSZ decoder with the reference's iterator API (iterator.go).

    Usage::

        it = ReaderIterator(data)
        while it.next():
            dp = it.current()
    """

    def __init__(
        self,
        data: bytes,
        int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
        default_unit: Unit = Unit.SECOND,
    ) -> None:
        self.stream = IStream(data)
        self.ts_iterator = TimestampIterator(default_unit)
        self.float_iter = FloatXOR()
        self.int_val = 0.0
        self.mult = 0
        self.sig = 0
        self.int_optimized = int_optimized
        self.is_float = False
        self.err: Exception | None = None
        self.closed = False

    # --- iteration ---

    def next(self) -> bool:
        if not self._has_next():
            return False
        try:
            first = self.ts_iterator.read_timestamp(self.stream)
            if self.ts_iterator.done:
                return False
            self._read_value(first)
        except (EOFError, ValueError) as e:  # parity: errors end iteration
            self.err = e
            return False
        return self._has_next()

    def current(self) -> Datapoint:
        if not self.int_optimized or self.is_float:
            value = bits_to_float(self.float_iter.prev_float_bits)
        else:
            value = convert_from_int_float(self.int_val, self.mult)
        return Datapoint(
            timestamp=self.ts_iterator.prev_time,
            value=value,
            unit=self.ts_iterator.time_unit,
            annotation=self.ts_iterator.prev_annotation,
        )

    def _has_next(self) -> bool:
        return self.err is None and not self.ts_iterator.done and not self.closed

    # --- value decode ---

    def _read_value(self, first: bool) -> None:
        if first:
            self._read_first_value()
        else:
            self._read_next_value()

    def _read_first_value(self) -> None:
        if not self.int_optimized:
            self.float_iter.read_full_float(self.stream)
            return
        if self.stream.read_bits(1) == OPCODE_FLOAT_MODE:
            self.float_iter.read_full_float(self.stream)
            self.is_float = True
            return
        self._read_int_sig_mult()
        self._read_int_val_diff()

    def _read_next_value(self) -> None:
        if not self.int_optimized:
            self.float_iter.read_next_float(self.stream)
            return
        if self.stream.read_bits(1) == OPCODE_UPDATE:
            if self.stream.read_bits(1) == OPCODE_REPEAT:
                return
            if self.stream.read_bits(1) == OPCODE_FLOAT_MODE:
                self.float_iter.read_full_float(self.stream)
                self.is_float = True
                return
            self._read_int_sig_mult()
            self._read_int_val_diff()
            self.is_float = False
            return
        if self.is_float:
            self.float_iter.read_next_float(self.stream)
        else:
            self._read_int_val_diff()

    def _read_int_sig_mult(self) -> None:
        if self.stream.read_bits(1) == OPCODE_UPDATE_SIG:
            if self.stream.read_bits(1) == OPCODE_ZERO_SIG:
                self.sig = 0
            else:
                self.sig = self.stream.read_bits(NUM_SIG_BITS) + 1
        if self.stream.read_bits(1) == OPCODE_UPDATE_MULT:
            self.mult = self.stream.read_bits(NUM_MULT_BITS)
            if self.mult > MAX_MULT:
                raise ValueError("supplied multiplier is invalid")

    def _read_int_val_diff(self) -> None:
        sign = -1.0
        if self.stream.read_bits(1) == OPCODE_NEGATIVE:
            sign = 1.0
        self.int_val += sign * self.stream.read_bits(self.sig)


def decode(
    data: bytes,
    int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
    default_unit: Unit = Unit.SECOND,
) -> list[Datapoint]:
    """Decode a full M3TSZ stream into datapoints."""
    it = ReaderIterator(data, int_optimized=int_optimized, default_unit=default_unit)
    out = []
    while it.next():
        out.append(it.current())
    # Parity with Go callers: io.EOF is treated as stream end, anything else
    # (e.g. an invalid multiplier) is a real decode error.
    if it.err is not None and not isinstance(it.err, EOFError):
        raise it.err
    return out


def encode_series(
    timestamps: list[int],
    values: list[float],
    start_nanos: int | None = None,
    int_optimized: bool = DEFAULT_INT_OPTIMIZATION,
    unit: Unit = Unit.SECOND,
) -> bytes:
    """Encode a series of (nanos, value) into a finalized M3TSZ stream."""
    if len(timestamps) != len(values):
        raise ValueError("timestamps and values must have the same length")
    if not timestamps:
        return b""
    if start_nanos is None:
        start_nanos = timestamps[0]
    enc = Encoder(start_nanos, int_optimized=int_optimized)
    for t, v in zip(timestamps, values):
        enc.encode(t, v, unit=unit)
    return enc.stream()
