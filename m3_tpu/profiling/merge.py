"""Fleet profile merge: the coordinator's whole-fleet flamegraph.

One ``/debug/pprof/fleet`` request pulls every peer's folded-stack
profile over the ``profile`` wire op and merges them by stack, tagging
each stack's counts per instance — so a single response renders a
flamegraph of the whole fleet with an instance split at every hot
frame. A dead peer is expected fleet weather: counted, reported in the
response, never fatal (the same contract as the selfmon peer pull).
"""

from __future__ import annotations

from ..utils.instrument import DEFAULT as METRICS

_M_PEER_ERRORS = METRICS.counter(
    "profile_fleet_peer_errors_total",
    "peer profile pulls that failed during a fleet profile merge",
)


def merge_profiles(profiles: list) -> dict:
    """``profiles``: [(instance_id, profile_dict)] (the StackSampler
    profile shape). Returns the merged folded table — stacks merged by
    identical frame sequence, each carrying its per-instance counts."""
    folded: dict[str, int] = {}
    by_instance: dict[str, dict] = {}
    for instance, prof in profiles:
        for stack, count in (prof or {}).get("folded", {}).items():
            folded[stack] = folded.get(stack, 0) + int(count)
            per = by_instance.setdefault(stack, {})
            per[instance] = per.get(instance, 0) + int(count)
    return {"folded": folded, "byInstance": by_instance}


def collect_fleet_profile(
    local_instance: str, local_profile: dict, peers: dict, seconds: float
) -> dict:
    """Pull + merge: the coordinator's own profile plus every peer's
    ``profile`` op result. ``peers``: {instance_id: node} where node
    exposes ``profile(seconds=...)`` (RemoteNode or any stub). The
    response is the ``/debug/pprof/fleet`` JSON shape."""
    profiles = [(local_instance, local_profile)]
    errors: dict[str, str] = {}
    for pid, node in sorted(peers.items()):
        try:
            profiles.append((pid, node.profile(seconds=seconds)))
        except Exception as exc:
            # a down peer must not cost the rest of the fleet's profile
            errors[pid] = f"{type(exc).__name__}: {exc}"
            _M_PEER_ERRORS.inc()
    merged = merge_profiles(profiles)
    return {
        "seconds": seconds,
        "instances": [inst for inst, _ in profiles],
        "errors": errors,
        "samples": sum(merged["folded"].values()),
        "folded": merged["folded"],
        "byInstance": merged["byInstance"],
    }
