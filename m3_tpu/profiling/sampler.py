"""Wall-clock stack sampler: the host tier of continuous profiling.

The production-proven always-on profiler shape (Ren et al., "Google-Wide
Profiling"; the reference exposes the same surface as x/debug pprof
endpoints on every service): a daemon thread snapshots every thread's
Python stack via ``sys._current_frames()`` at a low fixed rate and folds
the samples into a bounded table of semicolon-joined stacks — the
flamegraph "folded" format — with time-windowed retention, so
``profile(seconds=N)`` answers "where did the last N seconds go" on a
live process without restarting or attaching anything.

Design constraints, in order:

- **Low overhead.** One ``sys._current_frames()`` call per tick (a dict
  copy under the GIL), frame-walk and fold in plain Python, no
  allocation proportional to history (bounded per-bucket tables). The
  sampler meters its own cost (``m3tpu_profile_overhead_*``) and the
  PROFILE.md acceptance row holds it under 2% of the decode-aggregate
  bench at the default rate.
- **Deterministic scheduling.** Ticks ride a
  :class:`~m3_tpu.utils.schedule.FixedRateTicker` (absolute schedule +
  per-instance phase), so a fleet of samplers spreads over the interval
  and a stalled loop skips ticks instead of bursting. The clock is
  injectable: tests drive ``sample_once`` with a fake clock and fake
  frames and get bit-identical tables.
- **Bounded everything, loudly.** Stacks deeper than ``max_depth`` keep
  their LEAF-most frames (where the time is) behind a ``[truncated]``
  root marker, counted in ``m3tpu_profile_frames_truncated_total``. A
  bucket past ``max_stacks`` folds new stacks into the ``[overflow]``
  stack, counted in ``m3tpu_profile_stacks_truncated_total``. Collection
  failures are counted (``m3tpu_profile_errors_total``), never raised.
- **Profiles stay OUT of metric labels.** Frame/stack strings are
  unbounded-cardinality runtime data; they live in this table and its
  debug endpoints only — m3lint M3L005 deliberately has no ``frame`` or
  ``stack`` label key.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from ..utils.instrument import DEFAULT as METRICS

# the stack every bucket-capped sample folds into: visible in profiles as
# "this bucket saw more distinct stacks than the table holds"
OVERFLOW_STACK = "[overflow]"
# root marker of a depth-truncated stack (leaf-most frames kept)
TRUNCATED_FRAME = "[truncated]"


def default_hz() -> float:
    """M3_TPU_PROFILE_HZ (default 19): the fleet's always-on sampling
    rate. 19 Hz is deliberately prime-ish — it cannot phase-lock with
     1s/10s periodic loops (scrapes, rulers, flush ticks) and alias their
    work into every sample. 0 disables."""
    try:
        hz = float(os.environ.get("M3_TPU_PROFILE_HZ", "19"))
    except ValueError:
        return 19.0
    return max(hz, 0.0)


def frame_label(frame) -> str:
    """One frame -> ``path/to/file.py:function``; paths shortened to the
    last three components so labels are stable across checkouts."""
    code = frame.f_code
    fname = code.co_filename.replace("\\", "/")
    parts = fname.split("/")
    short = "/".join(parts[-3:]) if len(parts) > 3 else fname
    return f"{short}:{code.co_name}"


def fold_frames(frame, max_depth: int) -> tuple[str, int]:
    """Walk a leaf frame's ``f_back`` chain into a root-first folded
    stack string. Returns ``(stack, frames_truncated)`` — stacks deeper
    than ``max_depth`` keep the LEAF-most frames (that is where the time
    is being spent) behind a ``[truncated]`` root marker."""
    labels = []
    f = frame
    while f is not None:
        labels.append(frame_label(f))
        f = f.f_back
    labels.reverse()  # root first, flamegraph convention
    truncated = 0
    if len(labels) > max_depth:
        truncated = len(labels) - max_depth
        labels = [TRUNCATED_FRAME] + labels[-max_depth:]
    return ";".join(labels), truncated


def folded_text(folded: dict) -> str:
    """Folded table -> flamegraph.pl / speedscope input: one
    ``stack count`` line per stack, hottest first."""
    lines = [
        f"{stack} {int(count)}"
        for stack, count in sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))
    ]
    return "\n".join(lines) + ("\n" if lines else "")


class StackSampler:
    """Always-on wall-clock stack sampler for one process.

    ``sample_once(now=None, frames=None)`` is the testable seam — the
    loop just calls it on the ticker schedule. ``frames`` defaults to
    ``sys._current_frames()`` (minus the sampler's own thread);
    injecting a fake mapping + a fake ``clock`` makes tables fully
    deterministic for tests.

    Retention is bucketed: samples land in ``bucket_seconds``-wide
    windows keyed by ``int(now // bucket_seconds)``; buckets older than
    ``window_seconds`` drop on the next sample. ``profile(seconds=N)``
    merges the buckets covering the last N seconds.
    """

    def __init__(
        self,
        hz: float | None = None,
        window_seconds: float = 600.0,
        bucket_seconds: float = 10.0,
        max_stacks: int = 512,
        max_depth: int = 64,
        instance: str = "",
        clock=time.monotonic,
        memory=None,
        memory_interval: float = 5.0,
        registry=None,
    ) -> None:
        self.hz = default_hz() if hz is None else max(float(hz), 0.0)
        if bucket_seconds <= 0:
            raise ValueError("bucket_seconds must be positive")
        self.window_seconds = float(window_seconds)
        self.bucket_seconds = float(bucket_seconds)
        self.max_stacks = max(int(max_stacks), 1)
        self.max_depth = max(int(max_depth), 1)
        self.instance = instance
        self.clock = clock
        # optional device-memory accountant (profiling/device.py): a
        # zero-arg callable run every ``memory_interval`` seconds on the
        # sampler's schedule, so m3tpu_device_memory_bytes{kind} stays
        # fresh without a second daemon thread
        self.memory = memory
        self.memory_interval = float(memory_interval)
        self._last_memory = None
        # bucket index -> {folded stack: count}; insertion-ordered so
        # retention drops from the front
        self._buckets: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        reg = registry or METRICS
        self._m_samples = reg.counter(
            "profile_samples_total",
            "stack-sampler ticks completed (one sys._current_frames snapshot)",
        )
        self._m_frames_trunc = reg.counter(
            "profile_frames_truncated_total",
            "frames dropped from stacks deeper than the sampler's max_depth "
            "(leaf-most frames kept behind a [truncated] root marker)",
        )
        self._m_stacks_trunc = reg.counter(
            "profile_stacks_truncated_total",
            "samples folded into the [overflow] stack because a retention "
            "bucket hit its distinct-stack cap",
        )
        self._m_errors = reg.counter(
            "profile_errors_total",
            "stack-collection or device-memory-accounting failures inside "
            "the sampler loop (a persistently growing count means profiles "
            "are going dark)",
        )
        self._m_missed = reg.counter(
            "profile_ticks_missed_total",
            "scheduled sampling ticks skipped because the loop fell a full "
            "interval behind (the schedule skips forward, never bursts)",
        )
        self._m_overhead = reg.counter(
            "profile_overhead_seconds_total",
            "wall seconds the sampler itself spent collecting and folding "
            "stacks — the numerator of the overhead estimate",
        )
        self._g_overhead = reg.gauge(
            "profile_overhead_ratio",
            "sampler seconds per wall second since start (cumulative): the "
            "always-on profiler's own cost estimate, alertable via _m3tpu",
        )
        self._overhead_seconds = 0.0
        self._started_at: float | None = None

    # -- one tick (the testable unit) --

    def sample_once(self, now: float | None = None, frames=None) -> int:
        """Take one sample: fold every thread's stack into the current
        retention bucket. Returns the number of stacks recorded. Never
        raises — failures are counted in m3tpu_profile_errors_total."""
        t0 = time.perf_counter()
        if now is None:
            now = self.clock()
        try:
            if frames is None:
                frames = sys._current_frames()
            own = self._thread.ident if self._thread is not None else None
            folded: list[tuple[str, int]] = []
            for tid, frame in frames.items():
                if tid == own:
                    continue  # the sampler observing itself is pure noise
                folded.append(fold_frames(frame, self.max_depth))
        except Exception:
            self._m_errors.inc()
            return 0
        bucket_idx = int(now // self.bucket_seconds)
        recorded = 0
        with self._lock:
            bucket = self._buckets.get(bucket_idx)
            if bucket is None:
                bucket = self._buckets[bucket_idx] = {}
                self._evict_locked(now)
            for stack, frames_trunc in folded:
                if frames_trunc:
                    self._m_frames_trunc.inc(frames_trunc)
                if stack not in bucket and len(bucket) >= self.max_stacks:
                    self._m_stacks_trunc.inc()
                    stack = OVERFLOW_STACK
                bucket[stack] = bucket.get(stack, 0) + 1
                recorded += 1
        self._m_samples.inc()
        elapsed = time.perf_counter() - t0
        self._overhead_seconds += elapsed
        self._m_overhead.inc(elapsed)
        if self._started_at is not None:
            wall = max(now - self._started_at, elapsed, 1e-9)
            self._g_overhead.set(self._overhead_seconds / wall)
        return recorded

    def _evict_locked(self, now: float) -> None:
        keep_from = int((now - self.window_seconds) // self.bucket_seconds)
        for idx in [i for i in self._buckets if i < keep_from]:
            del self._buckets[idx]

    # -- the profile surface --

    def profile(self, seconds: float | None = None) -> dict:
        """Folded-stack profile of the last ``seconds`` (default: the
        whole retention window). The returned dict is the wire/JSON shape
        the ``profile`` op and ``/debug/pprof/profile`` serve."""
        if seconds is None:
            seconds = self.window_seconds
        seconds = min(max(float(seconds), self.bucket_seconds), self.window_seconds)
        now = self.clock()
        from_idx = int((now - seconds) // self.bucket_seconds)
        merged: dict[str, int] = {}
        with self._lock:
            for idx, bucket in self._buckets.items():
                if idx < from_idx:
                    continue
                for stack, count in bucket.items():
                    merged[stack] = merged.get(stack, 0) + count
        return {
            "enabled": True,
            "instance": self.instance,
            "hz": self.hz,
            "seconds": seconds,
            "samples": sum(merged.values()),
            "folded": merged,
        }

    # -- lifecycle --

    def start(self) -> "StackSampler":
        if self.hz <= 0:
            return self
        if self._thread is None:
            self._started_at = self.clock()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="m3tpu-profiler"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        from ..utils.schedule import FixedRateTicker

        ticker = FixedRateTicker(
            1.0 / self.hz,
            phase_key=f"profiler/{self.instance}",
            stop=self._stop,
        )
        next_memory = 0.0
        while True:
            stopped, missed = ticker.wait_next()
            if stopped:
                return
            if missed:
                self._m_missed.inc(missed)
            now = self.clock()
            self.sample_once(now=now)
            if self.memory is not None and now >= next_memory:
                next_memory = now + self.memory_interval
                try:
                    self._last_memory = self.memory()
                except Exception:
                    self._m_errors.inc()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
