"""Continuous profiling (the x/debug pprof role, always-on):

- **host tier** — :class:`StackSampler` (sampler.py): a wall-clock
  stack sampler folding ``sys._current_frames()`` snapshots into a
  bounded, time-windowed folded-stack table, served at
  ``/debug/pprof/profile`` and the ``profile`` wire op;
- **device tier** — ``utils.instrument.KernelProfiler`` dispatch
  timing + compiled HLO cost analysis (flops / bytes accessed per
  kernel), plus the live device-memory split
  (``m3tpu_device_memory_bytes{kind}``, device.py);
- **fleet tier** — ``/debug/pprof/fleet`` merges every peer's folded
  stacks by frame with per-instance tags (merge.py).

Each service process installs its sampler here (``install``) so the
wire op handlers and debug HTTP routes — which cannot thread a handle
through every dispatch table — find it, mirroring how
``instrument.DEFAULT`` is the process registry. Profiler health is
self-metered as ``m3tpu_profile_*`` and flows into ``_m3tpu`` via the
selfmon collector, so a ruler rule can alert on the profiler itself.
"""

from __future__ import annotations

from .device import collect_device_memory
from .merge import collect_fleet_profile, merge_profiles
from .sampler import StackSampler, default_hz, folded_text

__all__ = [
    "StackSampler",
    "collect_device_memory",
    "collect_fleet_profile",
    "default_hz",
    "folded_text",
    "install",
    "installed",
    "merge_profiles",
    "process_profile",
    "start_sampler",
]

# the process's installed sampler (the instrument.DEFAULT pattern): op
# handlers and debug routes read it; services install at startup
_SAMPLER: StackSampler | None = None


def install(sampler: StackSampler | None) -> None:
    global _SAMPLER
    _SAMPLER = sampler


def installed() -> StackSampler | None:
    return _SAMPLER


def process_profile(seconds: float | None = None) -> dict:
    """The installed sampler's profile — the one shape the ``profile``
    wire op and every pprof route serve. A process without a sampler
    (profiling disabled) answers with an explicit empty profile instead
    of erroring: the fleet merge must see 'nothing here', not a hole."""
    sampler = _SAMPLER
    if sampler is None:
        return {
            "enabled": False,
            "instance": "",
            "hz": 0.0,
            "seconds": 0.0,
            "samples": 0,
            "folded": {},
        }
    return sampler.profile(seconds=seconds)


def start_sampler(
    hz: float | None = None, instance: str = "", db=None, **kwargs
) -> StackSampler | None:
    """Service-startup helper: build, start, and install the process
    sampler with device-memory accounting attached (``db`` may be None —
    the accountant still tracks live jax buffers). Returns None when the
    resolved rate is 0 (profiling off)."""
    hz = default_hz() if hz is None else max(float(hz), 0.0)
    if hz <= 0:
        return None
    sampler = StackSampler(
        hz=hz,
        instance=instance,
        memory=lambda: collect_device_memory(db),
        **kwargs,
    )
    sampler.start()
    install(sampler)
    return sampler
